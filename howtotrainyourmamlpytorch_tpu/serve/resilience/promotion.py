"""Continuous train→serve promotion control plane.

PR 6 gave the fleet a SAFE promote verb (manifest verify → canary →
publish, ``serve/resilience/swap.py``); PR 10 gave the trainer an async
checkpoint publisher. This module closes the loop between them: a
supervisor that watches the trainer's checkpoint directory and drives
promotions across the serving fleet unattended — which makes it a
robustness problem first. Every automated promotion is an unattended
state change to live traffic, so the daemon is built around three
contracts:

* **candidate gating** — an epoch checkpoint becomes a candidate only
  once its ``.ready`` done-marker exists AND the marker's content digest
  matches the file (``utils/checkpoint.publish_done_marker`` writes the
  marker LAST, so a watcher can never pick up a torn publish); the
  candidate is then STAGED (a REAL copy into the daemon's retention dir
  — never a hardlink, so no staged artifact shares an inode with the
  trainer's files, and the trainer pruning old epochs cannot strand a
  rollback target), integrity-verified (``verify_checkpoint``), val-gated
  against the experiment's own recorded statistics before any replica is
  touched.
* **crash-safe idempotency** — every phase transition is journaled to an
  append-only fsync'd JSONL (``logs/promotions.jsonl``) BEFORE/AFTER the
  action it brackets. SIGKILL the daemon at any boundary, restart it,
  and replay resumes exactly once: a candidate journaled ``verified``
  but not ``promoted`` is checked against the fleet's served digest
  (``/healthz`` ``last_promoted_digest``/``checkpoint_digest``) — if the
  publish already landed the daemon records ``promoted`` with
  ``resumed`` set instead of double-promoting; digests with a terminal
  row are never re-driven (duplicate candidates dedupe by content
  digest).
* **automatic rollback** — after publish the daemon watches windowed
  error-rate / p99 / nonfinite counters scraped from the front door's
  ``/metrics`` and re-promotes the RETAINED last-known-good staged
  checkpoint if the new state regresses live traffic — the rollback
  PR 6's canary cannot provide, because a canary only runs BEFORE
  publish (``regress_after_promote`` in ``utils/faultinject.py`` is the
  deterministic proof of exactly that gap).

The daemon owns two threads — the watcher loop and the SLO sampler —
both joined by ``close()`` (graftlint ``thread-lifecycle``). The CLI
wrapper is ``tools/promotion_daemon.py``; the chaos proof is
``tools/chaos_train.py --schedule promote``.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import shutil
import sys
import threading
import time
import urllib.error
import urllib.request
from collections import deque

from ...telemetry import events as telemetry_events
from ...utils import faultinject
from ...utils.checkpoint import (
    CheckpointError,
    checkpoint_digest,
    read_done_marker,
    verify_checkpoint,
)
from ..errors import NoHealthyReplicaError, ReplicaDeadError, SwapRejectedError

#: Journal phase names (one JSONL row each). Terminal phases end a
#: digest's lifecycle; everything else is resumable after a crash.
PHASE_START = "start"
PHASE_VERIFIED = "verified"
PHASE_PROMOTED = "promoted"
PHASE_SLO_OK = "slo_ok"
PHASE_REJECTED = "rejected"
PHASE_ROLLBACK_START = "rollback_start"
PHASE_ROLLED_BACK = "rolled_back"
PHASE_DEDUPED = "deduped"
PHASE_RESUMED = "resumed"
#: Audit row for staging-dir GC: the named staged copy was (about to be)
#: removed under the retention policy. Journal-then-act, and replay
#: treats it as audit-only — retirement prunes bytes, it never changes a
#: candidate's lifecycle verdict.
PHASE_RETIRED = "retired"

TERMINAL_PHASES = (PHASE_REJECTED, PHASE_SLO_OK, PHASE_ROLLED_BACK)

#: ``daemon_kill_at_phase`` boundaries (utils/faultinject.py): SIGKILL
#: here, restart, and the journal replay must change no outcome.
KILL_PRE_VERIFY = 1  # ``start`` journaled, candidate not yet verified
KILL_PRE_PUBLISH = 2  # ``verified`` journaled, fleet not yet touched
KILL_POST_PUBLISH = 3  # fleet promoted, ``promoted`` row not yet written
KILL_PRE_RESOLVE = 4  # ``promoted`` journaled, SLO watch unresolved
KILL_MID_GC = 5  # ``retired`` row journaled, staged copy not yet removed


class PromotionTransportError(Exception):
    """The target fleet could not be reached / answered abnormally —
    transient by assumption; the daemon retries with backoff and leaves
    the candidate in-flight (journal-resumable), never rejected."""


@dataclasses.dataclass(frozen=True)
class PromotionConfig:
    """Control-plane knobs (CLI surface: ``tools/promotion_daemon.py``)."""

    #: The trainer's ``saved_models`` directory being watched.
    watch_dir: str
    #: Append-only crash-safe journal (``logs/promotions.jsonl``).
    journal_path: str
    #: Retention dir for staged candidate copies (rollback targets must
    #: survive the trainer pruning ``max_models_to_save``).
    staging_dir: str
    #: Directory-poll cadence of the watcher loop.
    poll_interval_s: float = 2.0
    #: Experiment statistic the val-gate reads (last recorded value;
    #: falls back to ``best_val_acc`` when the series is absent).
    val_stat_key: str = "val_accuracy_mean"
    #: A candidate without a finite recorded val stat is rejected (the
    #: epoch-0 checkpoint predates any validation epoch by contract).
    require_val_stat: bool = True
    #: When set, a candidate must beat the last-known-good's recorded
    #: stat by at least this much (may be negative to tolerate noise);
    #: ``None`` disables the comparison (stat presence still gates).
    val_min_delta: float | None = None
    #: Publish-drive retry budget for transient fleet errors.
    promote_retries: int = 3
    promote_backoff_s: float = 0.5
    #: Post-publish SLO watch: window length, sample cadence, and the
    #: regression thresholds over the window's /metrics deltas.
    slo_watch_s: float = 10.0
    slo_poll_s: float = 0.5
    p99_budget_ms: float = 30_000.0
    max_error_rate: float = 0.05
    max_new_nonfinite: int = 0
    #: Minimum answered requests in the window before error-rate/p99
    #: verdicts apply (a 1-request window must not decide a rollback).
    min_requests: int = 1
    #: Staging-dir retention beyond the always-kept last-known-good and
    #: in-flight copies: the N newest (mtime) other staged candidates
    #: survive each GC pass, the rest are removed with journaled
    #: ``retired`` rows. Candidates land roughly once per epoch, so the
    #: staging dir is bounded at ~(2 + N) checkpoint copies instead of
    #: growing with training length (disk-fill is a slow-motion outage).
    retain_staged: int = 2


# ---------------------------------------------------------------------------
# Journal
# ---------------------------------------------------------------------------


class PromotionJournal:
    """Append-only fsync'd JSONL journal — the daemon's crash-safe state.

    Each ``append`` is one fully-flushed line; replay (``load``) tolerates
    a torn final line (a SIGKILL mid-append loses at most the row being
    written, and the daemon's resume logic re-derives it from the fleet)."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)

    def append(self, phase: str, **fields) -> dict:
        row = {"t": time.time(), "phase": str(phase), **fields}
        line = json.dumps(row)
        with open(self.path, "a") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())
        return row

    @staticmethod
    def load(path: str) -> list[dict]:
        rows: list[dict] = []
        try:
            with open(path) as f:
                raw = f.read()
        except OSError:
            return rows
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue  # torn final line from a killed writer
            if isinstance(row, dict) and row.get("phase"):
                rows.append(row)
        return rows


def replay_journal(rows: list[dict]) -> dict:
    """Folds journal rows into the daemon's resume state: per-digest info
    (path/staged/epoch/val_stat), per-digest last phase, the terminal
    set, the last-known-good (newest ``slo_ok``), and the in-flight
    candidate (newest digest whose last phase is non-terminal)."""
    info: dict[str, dict] = {}
    last_phase: dict[str, str] = {}
    lkg: dict | None = None
    seen_pairs: set[tuple[str, str]] = set()
    order: list[str] = []
    for row in rows:
        digest = row.get("digest")
        if not digest:
            continue
        if row["phase"] == PHASE_RETIRED:
            # Staging-GC audit row: the candidate keeps whatever terminal
            # verdict it already journaled (folding it into last_phase
            # would resurrect a resolved digest as "in-flight" on
            # resume), and its ``staged`` field is a basename — folding
            # THAT into info would corrupt the entry's full staged path.
            continue
        entry = info.setdefault(digest, {"digest": digest})
        for key in ("path", "staged", "epoch", "val_stat"):
            if row.get(key) is not None:
                entry[key] = row[key]
        phase = row["phase"]
        if phase == PHASE_DEDUPED:
            seen_pairs.add((digest, str(row.get("path"))))
            continue
        if digest not in order:
            order.append(digest)
        if phase == PHASE_RESUMED:
            # An audit row, not a lifecycle state: folding it into
            # last_phase would make a crash AFTER a resume replay the
            # candidate from scratch (and double-drive a landed publish);
            # the row already records from_phase for the audit trail.
            continue
        last_phase[digest] = phase
        if phase == PHASE_SLO_OK:
            lkg = dict(entry)
    terminal = {d for d, p in last_phase.items() if p in TERMINAL_PHASES}
    inflight = None
    for digest in reversed(order):
        if digest not in terminal:
            inflight = dict(info[digest])
            inflight["last_phase"] = last_phase[digest]
            break
    return {
        "info": info,
        "last_phase": last_phase,
        "terminal": terminal,
        "lkg": lkg,
        "inflight": inflight,
        "seen_pairs": seen_pairs,
    }


# ---------------------------------------------------------------------------
# Fleet target (front door)
# ---------------------------------------------------------------------------


class HttpTarget:
    """Minimal front-door client for the daemon: POST /admin/promote,
    GET /healthz (503 bodies are health data, not errors), GET /metrics.
    Transport failures normalize to :class:`PromotionTransportError` so
    the retry loop has one class to catch. In-process targets (a
    ``ReplicaPool`` or ``ServingAPI``) are used directly — they already
    quack promote/healthz/metrics_text."""

    def __init__(self, base_url: str, timeout_s: float = 60.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def _fetch(self, path: str, payload: dict | None = None):
        data = None if payload is None else json.dumps(payload).encode()
        req = urllib.request.Request(
            self.base_url + path,
            data=data,
            headers={"Content-Type": "application/json"} if data else {},
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return resp.read()

    def promote(self, checkpoint_path: str) -> dict:
        try:
            return json.loads(
                self._fetch("/admin/promote", {"checkpoint": checkpoint_path})
            )
        except urllib.error.HTTPError as exc:
            body = {}
            try:
                body = json.load(exc)
            except Exception:  # noqa: BLE001 — body is best-effort detail
                pass
            if exc.code == 409:
                raise SwapRejectedError(
                    body.get("error", str(exc)),
                    reason=body.get("reason", "canary"),
                ) from None
            raise PromotionTransportError(
                f"promote answered {exc.code}: {body.get('error', exc)}"
            ) from None
        except (urllib.error.URLError, ConnectionError, OSError, TimeoutError) as exc:
            raise PromotionTransportError(f"promote failed: {exc}") from exc

    def healthz(self) -> dict:
        try:
            return json.loads(self._fetch("/healthz"))
        except urllib.error.HTTPError as exc:
            try:
                return json.load(exc)  # 503 carries the health body
            except Exception:  # noqa: BLE001
                raise PromotionTransportError(
                    f"healthz answered {exc.code}"
                ) from None
        except (urllib.error.URLError, ConnectionError, OSError, TimeoutError) as exc:
            raise PromotionTransportError(f"healthz failed: {exc}") from exc

    def metrics_text(self) -> str:
        try:
            return self._fetch("/metrics").decode()
        except (urllib.error.URLError, ConnectionError, OSError, TimeoutError) as exc:
            raise PromotionTransportError(f"metrics failed: {exc}") from exc


def parse_prometheus(text: str) -> dict[str, float]:
    """Exposition text -> ``{metric_name_with_labels: value}`` (comments
    and unparsable lines skipped)."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            out[name.strip()] = float(value)
        except ValueError:
            continue
    return out


#: Front-door metric suffixes the SLO watch reads, tried under the pool
#: prefix first (a pool front door renders only pool metrics), then the
#: single-engine prefix.
_SLO_PREFIXES = ("maml_serve_pool", "maml_serve")
_SLO_SUFFIXES = {
    "requests": "_requests_total",
    "errors": "_request_errors_total",
    "nonfinite": "_nonfinite_logits_total",
    "p99_ms": '_request_latency_ms{quantile="0.99"}',
}


def slo_counters(metrics: dict[str, float]) -> dict[str, float] | None:
    for prefix in _SLO_PREFIXES:
        if prefix + "_requests_total" in metrics:
            return {
                key: float(metrics.get(prefix + suffix, 0.0))
                for key, suffix in _SLO_SUFFIXES.items()
            }
    return None


# ---------------------------------------------------------------------------
# SLO watch
# ---------------------------------------------------------------------------


class SloWatch:
    """Continuous /metrics sampler with windowed post-publish verdicts.

    A background thread samples the front door's counters on a cadence
    into a bounded deque; after each publish the daemon anchors a
    baseline sample and asks for a verdict over the deltas since it.
    Scrape failures are skipped (a missed sample must not decide a
    rollback); the verdict needs at least ``min_requests`` answered in
    the window before error-rate/p99 apply — the nonfinite counter
    triggers on any delta beyond ``max_new_nonfinite``."""

    def __init__(self, target, config: PromotionConfig):
        self.target = target
        self.config = config
        self._samples: deque[tuple[float, dict]] = deque(maxlen=4096)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="promotion-slo-sampler", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            self.sample_now()
            self._stop.wait(self.config.slo_poll_s)

    def sample_now(self) -> dict | None:
        """One synchronous scrape; returns the counters (also appended to
        the window) or ``None`` on scrape failure."""
        try:
            counters = slo_counters(parse_prometheus(self.target.metrics_text()))
        except Exception:  # noqa: BLE001 — scrape failure is a skipped sample
            counters = None
        if counters is not None:
            self._samples.append((time.monotonic(), counters))
        return counters

    def verdict(self, baseline: dict | None) -> str | None:
        """Regression reason since ``baseline`` (a ``sample_now`` result),
        or ``None`` while the window looks healthy."""
        if baseline is None or not self._samples:
            return None
        _, now = self._samples[-1]
        d_requests = now["requests"] - baseline["requests"]
        d_errors = now["errors"] - baseline["errors"]
        d_nonfinite = now["nonfinite"] - baseline["nonfinite"]
        if d_nonfinite > self.config.max_new_nonfinite:
            return (
                f"nonfinite logits on live traffic: +{int(d_nonfinite)} "
                f"(max {self.config.max_new_nonfinite})"
            )
        if d_requests >= self.config.min_requests:
            error_rate = d_errors / d_requests
            if error_rate > self.config.max_error_rate:
                return (
                    f"error rate {error_rate:.3f} over {int(d_requests)} "
                    f"requests (max {self.config.max_error_rate})"
                )
            # The scrape exposes the fleet's ring-buffer p99, not a pure
            # post-publish window, so require BOTH over-budget AND growth
            # vs the post-publish baseline — a pre-publish latency spike
            # still in the ring must not condemn a healthy candidate.
            # (At low qps the ring moves slowly; the nonfinite and
            # error-rate deltas are the sharp rollback signals.)
            if (
                now["p99_ms"] > self.config.p99_budget_ms
                and now["p99_ms"] > 1.2 * baseline["p99_ms"]
            ):
                return (
                    f"p99 {now['p99_ms']:.0f} ms over budget "
                    f"{self.config.p99_budget_ms:.0f} ms (baseline "
                    f"{baseline['p99_ms']:.0f} ms)"
                )
        return None


# ---------------------------------------------------------------------------
# Daemon
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Candidate:
    epoch: int
    path: str
    digest: str


class PromotionDaemon:
    """The supervisor: scan → stage → verify/val-gate → promote (retry)
    → journal → SLO watch → resolve (``slo_ok`` or rollback). One watcher
    thread; see the module docstring for the three contracts."""

    def __init__(self, target, config: PromotionConfig):
        self.target = target
        self.config = config
        self.journal = PromotionJournal(config.journal_path)
        self.slo = SloWatch(target, config)
        os.makedirs(config.staging_dir, exist_ok=True)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        state = replay_journal(PromotionJournal.load(config.journal_path))
        self._info: dict[str, dict] = state["info"]
        self._terminal: set[str] = set(state["terminal"])
        self._seen_pairs: set[tuple[str, str]] = set(state["seen_pairs"])
        self._lkg: dict | None = state["lkg"]
        self._inflight: dict | None = state["inflight"]
        #: Count of publishes this daemon RESOLVED (slo_ok or rollback) —
        #: the ``--max_promotions`` exit condition.
        self.resolved_promotions = 0

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self.slo.start()
        self._thread = threading.Thread(
            target=self._run, name="promotion-watcher", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=30.0)
        self.slo.close()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.run_once()
            except Exception as exc:  # noqa: BLE001 — the loop must survive
                detail = f"{type(exc).__name__}: {exc}"[:300]
                telemetry_events.emit("promotion_error", error=detail)
                print(
                    f"promotion daemon: pass failed ({detail}); retrying "
                    f"in {self.config.poll_interval_s}s",
                    file=sys.stderr,
                )
            self._flush_telemetry()
            self._stop.wait(self.config.poll_interval_s)

    @staticmethod
    def _flush_telemetry() -> None:
        sink = telemetry_events.active()
        if sink is not None:
            sink.flush()

    # -- scan -----------------------------------------------------------

    def scan_candidates(self) -> list[Candidate]:
        """Fully-published, not-yet-terminal epoch candidates in epoch
        order. A checkpoint is only visible once its ``.ready`` marker
        exists AND the marker digest matches the file bytes (the torn-
        publish protocol); an already-terminal digest surfacing at a NEW
        path is journaled ``deduped`` once and skipped."""
        try:
            names = os.listdir(self.config.watch_dir)
        except OSError:
            return []
        epochs = []
        for name in names:
            suffix = name[len("train_model_"):]
            if name.startswith("train_model_") and suffix.isdigit():
                epochs.append(int(suffix))
        out: list[Candidate] = []
        for epoch in sorted(epochs):
            path = os.path.join(self.config.watch_dir, f"train_model_{epoch}")
            marker = read_done_marker(path)
            if marker is None:
                continue  # not fully published yet (or torn) — wait
            digest = str(marker["digest"])
            if digest in self._terminal or (
                self._inflight and self._inflight.get("digest") == digest
            ):
                pair = (digest, path)
                if digest in self._terminal and pair not in self._seen_pairs:
                    known = self._info.get(digest, {})
                    if known.get("path") != path:
                        self._seen_pairs.add(pair)
                        self.journal.append(
                            PHASE_DEDUPED, digest=digest, path=path
                        )
                continue
            if digest in self._info and self._info[digest].get("resolved"):
                continue
            out.append(Candidate(epoch=epoch, path=path, digest=digest))
        return out

    # -- one pass -------------------------------------------------------

    def run_once(self) -> None:
        """One watcher pass: resume any journaled in-flight candidate,
        then process new candidates in epoch order."""
        if self._inflight is not None:
            self._resume_inflight()
        for cand in self.scan_candidates():
            if self._stop.is_set():
                return
            self._process(cand)

    # -- candidate pipeline --------------------------------------------

    def _note_phase(self, phase: str, **fields) -> None:
        """Keeps the in-memory in-flight record aligned with the journal,
        so a transient failure retried in THE SAME process resumes from
        the right phase (cross-process restarts rebuild it by replay)."""
        if self._inflight is not None:
            self._inflight["last_phase"] = phase
            self._inflight.update(
                {k: v for k, v in fields.items() if v is not None}
            )

    def _staged_path(self, cand: Candidate) -> str:
        return os.path.join(
            self.config.staging_dir,
            f"{cand.digest[:16]}_{os.path.basename(cand.path)}",
        )

    def _stage(self, cand: Candidate) -> str:
        staged = self._staged_path(cand)
        if not os.path.exists(staged):
            _copy_atomic(cand.path, staged)
        return staged

    def _verify(self, cand: Candidate, staged: str):
        """Integrity + val-gate on the STAGED copy. Returns
        ``(val_stat, None)`` on acceptance, ``(None, (reason, detail))``
        on rejection."""
        faultinject.candidate_checkpoint_loading(staged)
        try:
            if checkpoint_digest(staged) != cand.digest:
                return None, (
                    "digest_mismatch",
                    "staged bytes disagree with the publish marker digest",
                )
            summary = verify_checkpoint(staged)
        except CheckpointError as exc:
            return None, ("corrupt", str(exc))
        val_stat = extract_val_stat(
            summary.get("experiment_state") or {}, self.config.val_stat_key
        )
        if val_stat is None and self.config.require_val_stat:
            return None, (
                "val_gate",
                f"no finite {self.config.val_stat_key!r} recorded in the "
                "candidate's experiment state",
            )
        if (
            self.config.val_min_delta is not None
            and val_stat is not None
            and self._lkg is not None
            and self._lkg.get("val_stat") is not None
            and val_stat < float(self._lkg["val_stat"]) + self.config.val_min_delta
        ):
            return None, (
                "val_gate",
                f"{self.config.val_stat_key}={val_stat:.4f} does not beat "
                f"last-known-good {float(self._lkg['val_stat']):.4f} "
                f"by {self.config.val_min_delta}",
            )
        return val_stat, None

    def _reject(self, digest: str, path: str, reason: str, detail: str) -> None:
        self._terminal.add(digest)
        self._inflight = None
        self.journal.append(
            PHASE_REJECTED, digest=digest, path=path,
            reason=reason, detail=detail[:300],
        )
        telemetry_events.emit(
            "promotion_rejected", digest=digest[:16], source=path,
            reason=reason, detail=detail[:300],
        )

    def _drive_promote(self, staged: str) -> int | None:
        """Drives ``target.promote`` with transient-error retry/backoff;
        returns the fleet's new state version. ``SwapRejectedError``
        propagates (terminal rejection); exhausted transient retries
        raise :class:`PromotionTransportError` (candidate stays
        in-flight and journal-resumable)."""
        last: Exception | None = None
        for attempt in range(max(int(self.config.promote_retries), 1)):
            if attempt:
                if self._stop.wait(
                    self.config.promote_backoff_s * (2 ** (attempt - 1))
                ):
                    break
            try:
                result = self.target.promote(staged)
                return (result or {}).get("state_version")
            except SwapRejectedError:
                raise
            except (
                PromotionTransportError, ReplicaDeadError,
                NoHealthyReplicaError, ConnectionError, TimeoutError, OSError,
            ) as exc:
                last = exc
        raise PromotionTransportError(
            f"fleet unreachable after {self.config.promote_retries} "
            f"attempt(s): {last}"
        )

    def _process(self, cand: Candidate) -> None:
        staged = self._stage(cand)
        info = {
            "digest": cand.digest, "path": cand.path,
            "staged": staged, "epoch": cand.epoch,
        }
        self._info[cand.digest] = dict(info)
        self._inflight = dict(info, last_phase=PHASE_START)
        self.journal.append(PHASE_START, **info)
        faultinject.daemon_phase(KILL_PRE_VERIFY)
        val_stat, rejection = self._verify(cand, staged)
        if rejection is not None:
            self._reject(cand.digest, cand.path, *rejection)
            return
        self._info[cand.digest]["val_stat"] = val_stat
        self.journal.append(
            PHASE_VERIFIED, digest=cand.digest, val_stat=val_stat
        )
        self._note_phase(PHASE_VERIFIED, val_stat=val_stat)
        faultinject.daemon_phase(KILL_PRE_PUBLISH)
        self._publish_and_resolve(cand.digest, staged, val_stat)

    def _publish_and_resolve(
        self, digest: str, staged: str, val_stat, resumed: bool = False
    ) -> None:
        try:
            version = self._drive_promote(staged)
        except SwapRejectedError as exc:
            self._reject(digest, staged, exc.reason, str(exc))
            return
        faultinject.daemon_phase(KILL_POST_PUBLISH)
        self.journal.append(
            PHASE_PROMOTED, digest=digest, state_version=version,
            resumed=resumed,
        )
        self._note_phase(PHASE_PROMOTED)
        telemetry_events.emit(
            "promotion_promoted", digest=digest[:16], source=staged,
            state_version=version, resumed=resumed,
        )
        faultinject.daemon_phase(KILL_PRE_RESOLVE)
        self._watch_and_resolve(digest, staged, val_stat)

    # -- SLO watch + rollback ------------------------------------------

    def _watch_and_resolve(self, digest: str, staged: str, val_stat) -> None:
        baseline = self.slo.sample_now()
        deadline = time.monotonic() + self.config.slo_watch_s
        reason: str | None = None
        while time.monotonic() < deadline and not self._stop.is_set():
            self._stop.wait(self.config.slo_poll_s)
            if baseline is None:
                # The post-publish baseline scrape failed (front door
                # momentarily saturated by the swap): keep trying — a
                # missing baseline must never vacuously bless the window.
                baseline = self.slo.sample_now()
                continue
            # Sample here too: the watch must not depend on the background
            # sampler being alive (run_once / --once drive it directly).
            self.slo.sample_now()
            reason = self.slo.verdict(baseline)
            if reason is not None:
                break
        if baseline is None:
            # The whole window passed unscrapeable: leave the candidate
            # journaled ``promoted`` (in-flight) — the next pass re-judges
            # a full window instead of recording ``slo_ok`` blind.
            return
        if reason is None:
            if self._stop.is_set():
                # Shutdown interrupted the watch: leave the candidate
                # journaled ``promoted`` (in-flight) — the next daemon
                # run resumes and judges a FULL window instead of
                # blessing a partial one.
                return
            self.slo.sample_now()
            reason = self.slo.verdict(baseline)
        if reason is None:
            self._terminal.add(digest)
            self._inflight = None
            self._info[digest]["resolved"] = True
            self.journal.append(PHASE_SLO_OK, digest=digest)
            self._lkg = {
                "digest": digest, "staged": staged, "val_stat": val_stat,
            }
            self.resolved_promotions += 1
            self._gc_staging()
            return
        telemetry_events.emit(
            "slo_regression", digest=digest[:16], reason=reason
        )
        rollback_to = self._lkg if (
            self._lkg and self._lkg.get("digest") != digest
        ) else None
        self.journal.append(
            PHASE_ROLLBACK_START, digest=digest, reason=reason,
            to=(rollback_to or {}).get("digest"),
        )
        self._note_phase(PHASE_ROLLBACK_START)
        self._finish_rollback(digest, rollback_to, reason)

    def _finish_rollback(self, digest: str, rollback_to, reason: str) -> None:
        """Drives the rollback promote and resolves the condemned digest.
        With no distinct last-known-good (a first-ever promotion
        regressed) there is nothing to roll to: the journal row records
        ``no_lkg`` and a LOUD ``slo_rollback_unavailable`` event fires —
        the fleet is still serving the condemned state and an operator
        must intervene; a phantom "rolled back" must never be claimed."""
        if rollback_to is not None:
            self._drive_promote(rollback_to["staged"])
        self._terminal.add(digest)
        self._inflight = None
        self._info.setdefault(digest, {})["resolved"] = True
        self.journal.append(
            PHASE_ROLLED_BACK, digest=digest,
            to=(rollback_to or {}).get("digest"),
            no_lkg=rollback_to is None,
        )
        if rollback_to is None:
            telemetry_events.emit(
                "slo_rollback_unavailable", digest=digest[:16], reason=reason
            )
            print(
                f"promotion daemon: digest {digest[:16]} regressed but NO "
                "last-known-good is retained — the fleet is still serving "
                "the condemned state; operator intervention required",
                file=sys.stderr,
            )
        else:
            telemetry_events.emit(
                "slo_rollback", digest=digest[:16],
                to=(rollback_to.get("digest") or "")[:16] or None,
                reason=reason,
            )
        self.resolved_promotions += 1
        self._gc_staging()

    def _gc_staging(self) -> None:
        """Bounded staging retention: the last-known-good and any
        in-flight copy are always kept (they are the rollback targets),
        plus the ``retain_staged`` newest other copies; everything older
        is removed, each removal journaled as a ``retired`` row FIRST.
        Journal-then-act makes the prune idempotent across a SIGKILL
        (``KILL_MID_GC``): a kill between row and unlink leaves a
        retired-but-present copy that the next pass simply re-retires,
        and replay treats ``retired`` as audit-only, so resume state
        never changes."""
        keep = set()
        if self._lkg:
            keep.add(os.path.basename(str(self._lkg.get("staged"))))
        if self._inflight:
            keep.add(os.path.basename(str(self._inflight.get("staged"))))
        try:
            names = os.listdir(self.config.staging_dir)
        except OSError:
            return
        # Audit linkage for the journal: staged basename -> digest.
        staged_digest = {
            os.path.basename(str(entry.get("staged"))): digest
            for digest, entry in self._info.items()
            if entry.get("staged")
        }
        aged: list[tuple[float, str]] = []
        for name in names:
            if name in keep:
                continue
            try:
                mtime = os.path.getmtime(
                    os.path.join(self.config.staging_dir, name)
                )
            except OSError:
                continue  # raced another remover — already gone
            aged.append((mtime, name))
        aged.sort(reverse=True)  # newest first; retain the head
        for _mtime, name in aged[max(0, self.config.retain_staged):]:
            self.journal.append(
                PHASE_RETIRED,
                digest=staged_digest.get(name),
                staged=name,
            )
            faultinject.daemon_phase(KILL_MID_GC)
            try:
                os.remove(os.path.join(self.config.staging_dir, name))
            except OSError:
                pass

    # -- crash resume ---------------------------------------------------

    def _fleet_digest(self) -> str | None:
        """The fleet's served promotion digest: ``None`` = UNREACHABLE
        (the caller must not decide anything on it), ``""`` = reachable
        but nothing promoted yet, else the digest string."""
        try:
            health = self.target.healthz()
        except Exception:  # noqa: BLE001 — fleet unreachable: decide later
            return None
        return (
            health.get("last_promoted_digest")
            or health.get("checkpoint_digest")
            or ""
        )

    def _resume_inflight(self) -> None:
        """Journal-replay resume: exactly-once semantics at every kill
        boundary. ``start`` → re-verify from the staged copy; ``verified``
        → ask the fleet whether the publish already landed (SIGKILL
        between publish and the ``promoted`` row) and either record it as
        resumed or drive it now; ``promoted``/``rollback_start`` → redo
        the unresolved SLO watch / rollback with a fresh window."""
        inflight = self._inflight
        if inflight is None:
            return
        digest = inflight["digest"]
        phase = inflight.get("last_phase", PHASE_START)
        staged = inflight.get("staged") or self._staged_path(
            Candidate(
                epoch=int(inflight.get("epoch", 0)),
                path=str(inflight.get("path")), digest=digest,
            )
        )
        if not os.path.exists(staged):
            source = str(inflight.get("path") or "")
            if source and os.path.exists(source):
                _copy_atomic(source, staged)
            else:
                self._reject(
                    digest, source, "staged_lost",
                    "daemon restarted with neither the staged copy nor the "
                    "source checkpoint on disk",
                )
                return
        self.journal.append(PHASE_RESUMED, digest=digest, from_phase=phase)
        telemetry_events.emit(
            "promotion_resumed", digest=digest[:16], from_phase=phase
        )
        val_stat = inflight.get("val_stat")
        if phase == PHASE_START:
            cand = Candidate(
                epoch=int(inflight.get("epoch", 0)),
                path=str(inflight.get("path")), digest=digest,
            )
            val_stat, rejection = self._verify(cand, staged)
            if rejection is not None:
                self._reject(digest, cand.path, *rejection)
                return
            self._info.setdefault(cand.digest, {})["val_stat"] = val_stat
            self.journal.append(
                PHASE_VERIFIED, digest=digest, val_stat=val_stat
            )
            self._publish_and_resolve(digest, staged, val_stat)
        elif phase == PHASE_VERIFIED:
            fleet = self._fleet_digest()
            if fleet is None:
                # Fleet unreachable right now: we cannot tell whether the
                # pre-crash publish landed — deciding blind risks a
                # double-drive. Leave the candidate in-flight; the next
                # pass asks again.
                return
            if fleet == digest:
                # Published before the crash: record, never double-drive.
                self.journal.append(
                    PHASE_PROMOTED, digest=digest, state_version=None,
                    resumed=True,
                )
                telemetry_events.emit(
                    "promotion_promoted", digest=digest[:16], source=staged,
                    state_version=None, resumed=True,
                )
                self._watch_and_resolve(digest, staged, val_stat)
            else:
                self._publish_and_resolve(
                    digest, staged, val_stat, resumed=True
                )
        elif phase == PHASE_PROMOTED:
            self._watch_and_resolve(digest, staged, val_stat)
        elif phase == PHASE_ROLLBACK_START:
            # The regression verdict is already journaled: never re-watch
            # (the one-shot regression may have passed — re-judging could
            # bless the digest the daemon already condemned); finish the
            # rollback drive instead.
            rollback_to = self._lkg if (
                self._lkg and self._lkg.get("digest") != digest
            ) else None
            self._finish_rollback(digest, rollback_to, "resumed")
        else:  # unknown phase (newer journal?) — leave it for the operator
            self._inflight = None


def _copy_atomic(src: str, dst: str) -> None:
    """Stage by REAL copy (tmp + rename), never hardlink: the staged
    artifact must share no inode with the trainer's file, so daemon-side
    corruption (``corrupt_candidate_at``) or retention can never reach
    back into the training run's own checkpoints."""
    tmp = dst + ".tmp"
    shutil.copyfile(src, tmp)
    os.replace(tmp, dst)


def extract_val_stat(experiment_state: dict, key: str) -> float | None:
    """The candidate's recorded validation statistic: last entry of the
    ``per_epoch_statistics`` series under ``key``, falling back to
    ``best_val_acc``; ``None`` when absent or non-finite."""
    stats = experiment_state.get("per_epoch_statistics") or {}
    values = stats.get(key) or []
    value = values[-1] if values else experiment_state.get("best_val_acc")
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    return value if math.isfinite(value) else None
