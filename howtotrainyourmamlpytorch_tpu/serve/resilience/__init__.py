"""Serving fault-tolerance layer: the request-path mirror of PR 3's
training pillars.

Training got checksummed checkpoints, preemption-safe shutdown, and a
divergence sentinel; the serving runtime gets the equivalent four:

* ``admission`` — bounded queues + deadline budgets: shed with 503 +
  ``Retry-After`` before the queue melts, cold-adapt traffic first
  (graceful degradation keeps the cache-hit classify tier alive).
* ``swap``      — safe hot-swap: checkpoint promotion verifies the
  integrity manifest, canaries every warmed bucket against the CANDIDATE
  state, checks logits finite, and only then publishes. A bad checkpoint
  never serves a single request.
* ``replica``   — the replica abstraction the pool supervises:
  ``LocalReplica`` (in-process, deterministic tier-1 fault tests under the
  compile guard), ``HttpReplica`` / ``SubprocessReplica`` (the production
  one-process-per-engine shape).
* ``serve/pool.py`` — N replicas behind one front door: health-checked,
  crash-restarted with exponential backoff and a crash-loop circuit
  breaker, with in-flight requests re-dispatched to healthy replicas
  (``serve_adapt``/``serve_classify`` are pure, so retry is idempotent).
* ``promotion`` — the continuous train→serve control plane: a journal-
  backed daemon that watches the trainer's checkpoint directory, stages
  + verifies + val-gates candidates, drives the canary-first pool
  promote with retry/backoff, and rolls back automatically when the
  post-publish SLO watch sees live regression (``tools/
  promotion_daemon.py`` is the CLI).

Every recovery path is proven by deterministic fault injection
(``utils/faultinject.py``: ``replica_kill_at_request``,
``wedge_replica_at_request``, ``corrupt_swap_at``, ``nan_next_logits``) in
tier-1, and measured by ``tools/serve_loadtest.py``.
"""

from .admission import AdmissionController
from .replica import (
    HttpReplica,
    LocalReplica,
    Replica,
    SubprocessReplica,
)
from .promotion import (
    PromotionConfig,
    PromotionDaemon,
    PromotionJournal,
    SloWatch,
)
from .swap import SwapResult, promote_checkpoint, promote_state

__all__ = [
    "AdmissionController",
    "Replica",
    "LocalReplica",
    "HttpReplica",
    "SubprocessReplica",
    "SwapResult",
    "promote_checkpoint",
    "promote_state",
    "PromotionConfig",
    "PromotionDaemon",
    "PromotionJournal",
    "SloWatch",
]
