"""Admission control: bounded queues, graceful degradation, early 503s.

An overloaded few-shot server fails in a specific, ugly way without this:
every queued episode holds its caller's thread (the API is synchronous),
queue time compounds into everyone's latency, and by the time requests
start timing out the queue holds seconds of work nobody is waiting for
anymore. The fix is the classic one — REFUSE work at the front door while
refusal is still cheap:

* **hard limit** (``max_queue_depth``): at or above this many queued
  episodes, every request is shed with ``OverloadedError`` (HTTP 503 +
  ``Retry-After``). The queue is bounded, so p99 under overload is the
  dispatch pipeline's, not the arrival process's.
* **degraded tier** (``degrade_queue_depth`` / ``max_queue_age_ms``): past
  the soft threshold — or when the oldest queued request has aged past the
  budget (a stalled pipeline, not a burst) — only CACHE-HIT traffic is
  admitted. A cold episode pays the full inner loop (~100x a cached
  classify on CPU); shedding cold-adapt first keeps the cheap tier alive
  at its SLO instead of letting one expensive request class starve both.

The controller is pure policy over two live signals (queue depth, oldest
queue age) — it owns no threads and takes no locks beyond the metric
counters, so `admit` adds nanoseconds to the request path.
"""

from __future__ import annotations

from ..engine import ServeConfig
from ..errors import OverloadedError
from ..metrics import ServeMetrics


class AdmissionController:
    """Shed-or-admit policy evaluated at the front door of every request."""

    def __init__(self, config: ServeConfig, metrics: ServeMetrics):
        self.config = config
        self.metrics = metrics

    # ------------------------------------------------------------------

    def degraded(self, queue_depth: int, oldest_age_s: float) -> bool:
        """True when the server should shed its expensive request class:
        queue depth past the soft threshold, or the oldest queued request
        older than the age budget."""
        cfg = self.config
        if 0 < cfg.degrade_queue_depth <= queue_depth:
            return True
        return oldest_age_s * 1e3 >= cfg.max_queue_age_ms > 0

    def admit(
        self, *, queue_depth: int, oldest_age_s: float, cache_hit: bool
    ) -> None:
        """Raises ``OverloadedError`` when the request must be shed; updates
        the ``degraded`` gauge and ``shed_total`` counter either way."""
        cfg = self.config
        degraded = self.degraded(queue_depth, oldest_age_s)
        self.metrics.degraded.set(1.0 if degraded else 0.0)
        if queue_depth >= cfg.max_queue_depth:
            self.metrics.shed_total.inc()
            raise OverloadedError(
                f"queue depth {queue_depth} at the {cfg.max_queue_depth} "
                "hard limit — request shed",
                retry_after_s=cfg.retry_after_s,
            )
        if degraded and not cache_hit:
            self.metrics.shed_total.inc()
            raise OverloadedError(
                "server degraded (queue depth "
                f"{queue_depth}, oldest wait {oldest_age_s * 1e3:.0f} ms) — "
                "cold-adapt request shed; cached support sets still served",
                retry_after_s=cfg.retry_after_s,
            )
