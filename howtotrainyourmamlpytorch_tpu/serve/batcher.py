"""Deadline-based request micro-batching.

The throughput lever of the serving runtime: the learners were TRAINED
with a vmapped meta-batch axis, so the device program is already shaped to
answer B episodes for barely more than the cost of one — the batcher's job
is to refill that axis from CONCURRENT traffic. Each incoming episode
joins the pending group for its shape bucket — under an episode-geometry
lattice (``serve/geometry.py``) that bucket is the COARSENED one, so
heterogeneous (way, shot, query) traffic co-batches into the small
declared bucket set instead of fragmenting into singleton groups; a group
flushes when it reaches ``max_batch`` episodes (the engine's fixed
meta-batch), when its oldest request has waited ``max_wait_ms``, or when
the tightest member DEADLINE would otherwise expire in the queue — the classic
latency-vs-throughput dial (0 ms degenerates to per-request dispatch,
large values trade tail latency for device efficiency).

One worker thread owns all dispatching; callers block on a
``concurrent.futures.Future`` so the public API stays synchronous while
arbitrarily many frontend threads (the HTTP handler pool) share one device
pipeline. Dispatch runs OUTSIDE the queue lock — enqueue latency never
includes device time. (That invariant is now mechanically enforced:
graftlint's ``blocking-under-lock`` flags a jitted dispatch reachable
under the Condition, and tier-1 runs this module's suites under the
``utils/locksan.py`` hold-time budget.)

Resilience contract (serve/errors.py): the worker thread is FENCED. An
exception anywhere in a group's dispatch — a poisoned episode deep in the
engine, a result-count mismatch, even a set_result race against a caller's
timeout-cancel — fails THAT group's futures with ``DispatchFailedError``
and keeps the worker alive; it must never strand every queued Future in
the process behind a dead thread. Episodes whose deadline has already
passed are dropped before dispatch (``DeadlineExceededError``): running
work nobody is waiting for would stretch every later request's queue time.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from concurrent.futures import Future, InvalidStateError

from .engine import EpisodeRequest, ServingEngine
from .errors import DeadlineExceededError, DispatchFailedError


class _Group:
    """Pending episodes of one bucket + the flush deadline (the earlier of
    oldest-arrival + max_wait and the tightest member request deadline)."""

    __slots__ = ("episodes", "futures", "deadline", "created")

    def __init__(self, deadline: float, created: float):
        self.episodes: list[EpisodeRequest] = []
        self.futures: list[Future] = []
        self.deadline = deadline
        self.created = created


def _fail(future: Future, exc: Exception) -> None:
    """Fails a future, tolerating the caller's concurrent timeout-cancel
    (``cancel`` can land between a ``cancelled()`` check and the set)."""
    try:
        future.set_exception(exc)
    except InvalidStateError:
        pass


def _resolve(future: Future, result) -> None:
    try:
        future.set_result(result)
    except InvalidStateError:
        pass


class MicroBatcher:
    """Collates concurrent same-bucket episodes into engine dispatches."""

    def __init__(self, engine: ServingEngine):
        self.engine = engine
        self.metrics = engine.metrics
        self.max_batch = engine.config.meta_batch_size
        self.max_wait_s = engine.config.max_wait_ms / 1e3
        self._lock = threading.Condition()
        # Insertion-ordered so ties flush oldest-group-first.
        self._groups: "OrderedDict[tuple, _Group]" = OrderedDict()
        self._closed = False
        self._last_dispatch_at = time.monotonic()
        # (computed_at, margin_s); stale-by-TTL entries are recomputed.
        self._margin_cache = (-self.MARGIN_TTL_S, 0.01)
        self._worker = threading.Thread(
            target=self._run, name="serve-batcher", daemon=True
        )
        self._worker.start()

    # ------------------------------------------------------------------
    # Producer side
    # ------------------------------------------------------------------

    def submit(self, episode: EpisodeRequest) -> Future:
        """Enqueues one prepared episode; the Future resolves to its
        ``(T, num_classes)`` logits (or raises the typed dispatch error).
        ``episode.deadline`` tightens the group's flush deadline so a
        short-budget request is never parked for the full batching
        window."""
        future: Future = Future()
        # Margin computed OUTSIDE the lock: it sorts latency windows, and
        # every concurrent submitter would otherwise serialize behind it.
        margin_s = (
            self._dispatch_margin_s() if episode.deadline is not None else 0.0
        )
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            now = time.monotonic()
            group = self._groups.get(episode.bucket)
            if group is None:
                group = _Group(now + self.max_wait_s, now)
                self._groups[episode.bucket] = group
            if episode.deadline is not None:
                # Flush a dispatch-time margin BEFORE the request deadline:
                # flushing exactly at expiry would guarantee the episode is
                # dropped by the pre-dispatch deadline check.
                flush_by = episode.deadline - margin_s
                group.deadline = min(group.deadline, max(now, flush_by))
            group.episodes.append(episode)
            group.futures.append(future)
            self._lock.notify()
        return future

    #: How long a computed dispatch margin stays fresh. Recomputing per
    #: request would sort two 2048-sample windows on every submit.
    MARGIN_TTL_S = 0.5

    def _dispatch_margin_s(self) -> float:
        """Estimated dispatch cost (observed adapt+classify medians, 10 ms
        floor before any history) — how far before a request's deadline its
        group must flush for the answer to still matter. Cached for
        ``MARGIN_TTL_S``; the tuple swap is atomic and a stale read is
        harmless (the margin is an estimate either way)."""
        now = time.monotonic()
        computed_at, value = self._margin_cache
        if now - computed_at >= self.MARGIN_TTL_S:
            margin_ms = (
                self.metrics.adapt_latency.percentile(50)
                + self.metrics.classify_latency.percentile(50)
            )
            value = max(0.01, margin_ms / 1e3)
            self._margin_cache = (now, value)
        return value

    def queue_depth(self) -> int:
        with self._lock:
            return sum(len(g.episodes) for g in self._groups.values())

    def oldest_pending_age_s(self) -> float:
        """Age of the oldest still-queued group (0.0 when idle) — the
        admission controller's stalled-pipeline signal."""
        with self._lock:
            if not self._groups:
                return 0.0
            oldest = min(g.created for g in self._groups.values())
        return max(0.0, time.monotonic() - oldest)

    def last_dispatch_age_s(self) -> float:
        """Seconds since the worker last completed a dispatch — ``/healthz``
        wedge telemetry (a large value under load means a stuck worker)."""
        return max(0.0, time.monotonic() - self._last_dispatch_at)

    def close(self, timeout: float = 5.0) -> None:
        """Stops the worker after draining pending groups."""
        with self._lock:
            self._closed = True
            self._lock.notify()
        self._worker.join(timeout)

    # ------------------------------------------------------------------
    # Worker side
    # ------------------------------------------------------------------

    def _take_ready(self) -> list[_Group]:
        """Pops every group that is full or past deadline (lock held)."""
        now = time.monotonic()
        ready = []
        for key in list(self._groups):
            group = self._groups[key]
            if (
                len(group.episodes) >= self.max_batch
                or now >= group.deadline
                or self._closed
            ):
                ready.append(self._groups.pop(key))
        return ready

    def _run(self) -> None:
        while True:
            with self._lock:
                while True:
                    ready = self._take_ready()
                    if ready or (self._closed and not self._groups):
                        break
                    if self._groups:
                        next_deadline = min(
                            g.deadline for g in self._groups.values()
                        )
                        self._lock.wait(
                            max(0.0, next_deadline - time.monotonic())
                        )
                    else:
                        self._lock.wait()
                drained = self._closed and not self._groups
            for group in ready:
                # The fence: NOTHING a group does may kill the worker —
                # a dead worker strands every queued Future forever.
                try:
                    self._dispatch(group)
                except Exception as exc:
                    failure = DispatchFailedError(
                        f"dispatch worker error: {type(exc).__name__}: {exc}"
                    )
                    failure.__cause__ = exc
                    for future in group.futures:
                        _fail(future, failure)
                self._last_dispatch_at = time.monotonic()
            if drained and not ready:
                return
            if drained and ready:
                # Dispatched the final drain batch; exit on next loop.
                with self._lock:
                    if not self._groups:
                        return

    def _split_expired(
        self, group: _Group
    ) -> tuple[list[EpisodeRequest], list[Future]]:
        """Fails the futures of already-expired episodes (nobody is waiting
        — the caller's ``Future.result`` timeout fired) and returns the
        still-live remainder."""
        now = time.monotonic()
        live_eps: list[EpisodeRequest] = []
        live_futures: list[Future] = []
        for episode, future in zip(group.episodes, group.futures):
            if episode.expired(now):
                if not future.cancelled():
                    # A cancelled future means the CALLER's wait already
                    # timed out and counted this deadline — don't double.
                    self.metrics.deadline_exceeded_total.inc()
                _fail(
                    future,
                    DeadlineExceededError(
                        "request deadline expired in the batcher queue "
                        "before dispatch"
                    ),
                )
            else:
                live_eps.append(episode)
                live_futures.append(future)
        return live_eps, live_futures

    def _dispatch(self, group: _Group) -> None:
        episodes, futures = self._split_expired(group)
        if not episodes:
            return
        try:
            results = self.engine.dispatch(episodes)
        except Exception as exc:  # surface to every caller, keep serving
            failure = DispatchFailedError(
                f"engine dispatch failed: {type(exc).__name__}: {exc}"
            )
            failure.__cause__ = exc
            for future in futures:
                _fail(future, failure)
            return
        if len(results) != len(episodes):
            for future in futures:
                _fail(
                    future,
                    DispatchFailedError(
                        f"engine returned {len(results)} results for "
                        f"{len(episodes)} episodes"
                    ),
                )
            return
        for future, logits in zip(futures, results):
            _resolve(future, logits)
