"""Deadline-based request micro-batching.

The throughput lever of the serving runtime: the learners were TRAINED
with a vmapped meta-batch axis, so the device program is already shaped to
answer B episodes for barely more than the cost of one — the batcher's job
is to refill that axis from CONCURRENT traffic. Each incoming episode
joins the pending group for its shape bucket; a group flushes when it
reaches ``max_batch`` episodes (the engine's fixed meta-batch) or when its
oldest request has waited ``max_wait_ms`` — the classic
latency-vs-throughput dial (0 ms degenerates to per-request dispatch,
large values trade tail latency for device efficiency).

One worker thread owns all dispatching; callers block on a
``concurrent.futures.Future`` so the public API stays synchronous while
arbitrarily many frontend threads (the HTTP handler pool) share one device
pipeline. Dispatch runs OUTSIDE the queue lock — enqueue latency never
includes device time.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from concurrent.futures import Future

from .engine import EpisodeRequest, ServingEngine


class _Group:
    """Pending episodes of one bucket + the oldest-request deadline."""

    __slots__ = ("episodes", "futures", "deadline")

    def __init__(self, deadline: float):
        self.episodes: list[EpisodeRequest] = []
        self.futures: list[Future] = []
        self.deadline = deadline


class MicroBatcher:
    """Collates concurrent same-bucket episodes into engine dispatches."""

    def __init__(self, engine: ServingEngine):
        self.engine = engine
        self.max_batch = engine.config.meta_batch_size
        self.max_wait_s = engine.config.max_wait_ms / 1e3
        self._lock = threading.Condition()
        # Insertion-ordered so ties flush oldest-group-first.
        self._groups: "OrderedDict[tuple, _Group]" = OrderedDict()
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name="serve-batcher", daemon=True
        )
        self._worker.start()

    # ------------------------------------------------------------------
    # Producer side
    # ------------------------------------------------------------------

    def submit(self, episode: EpisodeRequest) -> Future:
        """Enqueues one prepared episode; the Future resolves to its
        ``(T, num_classes)`` logits (or raises the dispatch error)."""
        future: Future = Future()
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            group = self._groups.get(episode.bucket)
            if group is None:
                group = _Group(time.monotonic() + self.max_wait_s)
                self._groups[episode.bucket] = group
            group.episodes.append(episode)
            group.futures.append(future)
            self._lock.notify()
        return future

    def queue_depth(self) -> int:
        with self._lock:
            return sum(len(g.episodes) for g in self._groups.values())

    def close(self, timeout: float = 5.0) -> None:
        """Stops the worker after draining pending groups."""
        with self._lock:
            self._closed = True
            self._lock.notify()
        self._worker.join(timeout)

    # ------------------------------------------------------------------
    # Worker side
    # ------------------------------------------------------------------

    def _take_ready(self) -> list[_Group]:
        """Pops every group that is full or past deadline (lock held)."""
        now = time.monotonic()
        ready = []
        for key in list(self._groups):
            group = self._groups[key]
            if (
                len(group.episodes) >= self.max_batch
                or now >= group.deadline
                or self._closed
            ):
                ready.append(self._groups.pop(key))
        return ready

    def _run(self) -> None:
        while True:
            with self._lock:
                while True:
                    ready = self._take_ready()
                    if ready or (self._closed and not self._groups):
                        break
                    if self._groups:
                        next_deadline = min(
                            g.deadline for g in self._groups.values()
                        )
                        self._lock.wait(
                            max(0.0, next_deadline - time.monotonic())
                        )
                    else:
                        self._lock.wait()
                drained = self._closed and not self._groups
            for group in ready:
                self._dispatch(group)
            if drained and not ready:
                return
            if drained and ready:
                # Dispatched the final drain batch; exit on next loop.
                with self._lock:
                    if not self._groups:
                        return

    def _dispatch(self, group: _Group) -> None:
        try:
            results = self.engine.dispatch(group.episodes)
        except Exception as exc:  # surface to every caller, keep serving
            for future in group.futures:
                if not future.cancelled():
                    future.set_exception(exc)
            return
        for future, logits in zip(group.futures, results):
            if not future.cancelled():
                future.set_result(logits)
