"""Serving observability: latency quantiles, counters, Prometheus text.

The metric primitives (``Counter`` and ``LatencyStat`` — the exact-window
quantile stat) live in the shared telemetry subsystem
(``telemetry/registry.py``) and are re-exported here, so the serving
runtime and the trainer run ONE implementation. The Prometheus text this
module renders is byte-identical to the pre-factoring surface
(``tests/test_serve_http.py`` scrapes it unchanged); the quantile/window
rationale (exact medians, not bucket midpoints) is documented with the
primitives.

Everything here is thread-safe: the HTTP frontend scrapes ``/metrics`` from
its own threads while batcher/engine threads record.
"""

from __future__ import annotations

import threading

from ..telemetry.registry import Counter, Gauge, LatencyStat

__all__ = ["Counter", "Gauge", "LatencyStat", "ServeMetrics"]


class ServeMetrics:
    """The serving runtime's metric registry (one per engine).

    ``render_prometheus`` emits the text exposition format `/metrics`
    serves; ``snapshot`` returns the same data as a dict for the in-process
    API and the bench harness.
    """

    PREFIX = "maml_serve"

    def __init__(self):
        self.adapt_latency = LatencyStat("adapt")
        self.classify_latency = LatencyStat("classify")
        self.request_latency = LatencyStat("request")
        self.requests_total = Counter("requests_total")
        self.request_errors = Counter("request_errors")
        self.episodes_served = Counter("episodes_served")
        self.cache_hits = Counter("cache_hits")
        self.cache_misses = Counter("cache_misses")
        self.batches_dispatched = Counter("batches_dispatched")
        self.padded_tasks = Counter("padded_tasks")
        # Resilience layer (serve/resilience, serve/pool): admission sheds,
        # queue-expired deadlines, and the hot-swap promotion verdicts.
        self.shed_total = Counter("shed_total")
        self.deadline_exceeded_total = Counter("deadline_exceeded_total")
        self.swaps_total = Counter("swaps_total")
        self.swap_rejected_total = Counter("swap_rejected_total")
        # Episodes whose host logits carried any non-finite value — the
        # live numeric-regression signal the promotion daemon's
        # post-publish SLO watch triggers rollback on (a canary can only
        # prove the candidate BEFORE publish; this counter watches it
        # under real traffic after).
        self.nonfinite_logits_total = Counter("nonfinite_logits_total")
        # Episode-geometry coarsening (serve/geometry.py): episodes padded
        # UP onto a lattice bucket vs episodes no bucket could contain
        # (rejected 400 at the front door). A climbing rejected count is a
        # client-fleet shape mismatch, NOT overload — keeping the two
        # distinguishable on a dashboard is the point of the split.
        self.geometry_coarsened_total = Counter("geometry_coarsened_total")
        self.geometry_rejected_total = Counter("geometry_rejected_total")
        self.degraded = Gauge("degraded")
        # bucket key -> {"dispatches": int, "episodes": int}; compile counts
        # live with the engine (it owns the jit boundary) and are merged
        # into snapshots by the caller.
        self._lock = threading.Lock()
        self._buckets: dict[tuple, dict] = {}

    def record_bucket_dispatch(self, key: tuple, episodes: int) -> None:
        with self._lock:
            row = self._buckets.setdefault(
                key, {"dispatches": 0, "episodes": 0}
            )
            row["dispatches"] += 1
            row["episodes"] += episodes

    def bucket_table(self) -> dict[tuple, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._buckets.items()}

    def cache_hit_rate(self) -> float:
        hits, misses = self.cache_hits.value, self.cache_misses.value
        total = hits + misses
        return hits / total if total else 0.0

    def snapshot(self, *, queue_depth: int = 0,
                 compile_table: dict | None = None,
                 program_table: list | None = None):
        """``compile_table``: ``{program_label: trace_count}`` from the
        engine (it owns the jit boundary and counts actual retraces).
        ``program_table``: the engine's per-bucket serve-program resource
        ledger rows (``telemetry/device.ProgramLedger.table()`` — FLOPs,
        bytes, arithmetic intensity, HBM footprint per compiled
        adapt/classify program)."""
        return {
            "requests_total": self.requests_total.value,
            "request_errors": self.request_errors.value,
            "episodes_served": self.episodes_served.value,
            "batches_dispatched": self.batches_dispatched.value,
            "padded_tasks": self.padded_tasks.value,
            "shed_total": self.shed_total.value,
            "deadline_exceeded_total": self.deadline_exceeded_total.value,
            "swaps_total": self.swaps_total.value,
            "swap_rejected_total": self.swap_rejected_total.value,
            "nonfinite_logits_total": self.nonfinite_logits_total.value,
            "geometry_coarsened_total": self.geometry_coarsened_total.value,
            "geometry_rejected_total": self.geometry_rejected_total.value,
            "degraded": bool(self.degraded.value),
            "queue_depth": queue_depth,
            "cache": {
                "hits": self.cache_hits.value,
                "misses": self.cache_misses.value,
                "hit_rate": self.cache_hit_rate(),
            },
            "latency_ms": {
                "adapt": self.adapt_latency.snapshot(),
                "classify": self.classify_latency.snapshot(),
                "request": self.request_latency.snapshot(),
            },
            "buckets": {
                "x".join(str(d) for d in key): dict(row)
                for key, row in self.bucket_table().items()
            },
            "compiles": dict(compile_table or {}),
            "programs": [dict(row) for row in (program_table or [])],
        }

    def render_prometheus(
        self, *, queue_depth: int = 0, compile_table: dict | None = None,
        program_table: list | None = None,
    ) -> str:
        p = self.PREFIX
        lines = [
            f"# TYPE {p}_requests_total counter",
            f"{p}_requests_total {self.requests_total.value}",
            f"# TYPE {p}_request_errors_total counter",
            f"{p}_request_errors_total {self.request_errors.value}",
            f"# TYPE {p}_episodes_served_total counter",
            f"{p}_episodes_served_total {self.episodes_served.value}",
            f"# TYPE {p}_batches_dispatched_total counter",
            f"{p}_batches_dispatched_total {self.batches_dispatched.value}",
            f"# TYPE {p}_padded_tasks_total counter",
            f"{p}_padded_tasks_total {self.padded_tasks.value}",
            f"# TYPE {p}_shed_total counter",
            f"{p}_shed_total {self.shed_total.value}",
            f"# TYPE {p}_deadline_exceeded_total counter",
            f"{p}_deadline_exceeded_total {self.deadline_exceeded_total.value}",
            f"# TYPE {p}_swaps_total counter",
            f"{p}_swaps_total {self.swaps_total.value}",
            f"# TYPE {p}_swap_rejected_total counter",
            f"{p}_swap_rejected_total {self.swap_rejected_total.value}",
            f"# TYPE {p}_nonfinite_logits_total counter",
            f"{p}_nonfinite_logits_total {self.nonfinite_logits_total.value}",
            f"# TYPE {p}_geometry_coarsened_total counter",
            f"{p}_geometry_coarsened_total "
            f"{self.geometry_coarsened_total.value}",
            f"# TYPE {p}_geometry_rejected_total counter",
            f"{p}_geometry_rejected_total "
            f"{self.geometry_rejected_total.value}",
            f"# TYPE {p}_degraded gauge",
            f"{p}_degraded {int(self.degraded.value)}",
            f"# TYPE {p}_queue_depth gauge",
            f"{p}_queue_depth {queue_depth}",
            f"# TYPE {p}_cache_hits_total counter",
            f"{p}_cache_hits_total {self.cache_hits.value}",
            f"# TYPE {p}_cache_misses_total counter",
            f"{p}_cache_misses_total {self.cache_misses.value}",
            f"# TYPE {p}_cache_hit_rate gauge",
            f"{p}_cache_hit_rate {self.cache_hit_rate():.6f}",
        ]
        for stage, stat in (
            ("adapt", self.adapt_latency),
            ("classify", self.classify_latency),
            ("request", self.request_latency),
        ):
            snap = stat.snapshot()
            lines += [
                f"# TYPE {p}_{stage}_latency_ms summary",
                f'{p}_{stage}_latency_ms{{quantile="0.5"}} '
                f"{snap['p50_ms']:.6f}",
                f'{p}_{stage}_latency_ms{{quantile="0.99"}} '
                f"{snap['p99_ms']:.6f}",
                f"{p}_{stage}_latency_ms_count {snap['count']}",
                f"{p}_{stage}_latency_ms_sum {snap['sum_ms']:.6f}",
            ]
        lines.append(f"# TYPE {p}_bucket_episodes_total counter")
        for key, row in sorted(self.bucket_table().items()):
            label = "x".join(str(d) for d in key)
            lines.append(
                f'{p}_bucket_episodes_total{{bucket="{label}"}} '
                f"{row['episodes']}"
            )
        lines.append(f"# TYPE {p}_program_compiles counter")
        for label, count in sorted((compile_table or {}).items()):
            lines.append(
                f'{p}_program_compiles{{program="{label}"}} {count}'
            )
        # Per-bucket serve-program resource ledger (telemetry/device.py):
        # compiler-metadata gauges per compiled program, so a dashboard
        # reads what each bucket's dispatch costs — not just how often it
        # runs. Fields the backend could not analyze are simply omitted.
        if program_table:
            for metric, field in (
                ("program_flops", "flops"),
                ("program_bytes_accessed", "bytes_accessed"),
                ("program_arithmetic_intensity", "arithmetic_intensity"),
                ("program_hbm_peak_bytes", "hbm_peak_bytes"),
                ("program_temp_bytes", "temp_bytes"),
            ):
                rows = [
                    row for row in program_table
                    if row.get(field) is not None
                ]
                if not rows:
                    continue
                lines.append(f"# TYPE {p}_{metric} gauge")
                for row in sorted(rows, key=lambda r: str(r.get("name"))):
                    label = row.get("name", "?")
                    bucket = row.get("bucket") or ""
                    lines.append(
                        f'{p}_{metric}{{program="{label}",'
                        f'bucket="{bucket}"}} {row[field]:g}'
                    )
        return "\n".join(lines) + "\n"
