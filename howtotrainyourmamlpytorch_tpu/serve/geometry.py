"""Episode-geometry coarsening: mixed (way, shot, query) traffic through a
fixed program set.

The engine's compile contract keys programs by the shape bucket ``(way,
shot, query)`` — which is exactly right for a fleet serving ONE episode
geometry, and exactly wrong for heterogeneous clients: every novel
``(way, shot, query)`` triple mints a new XLA program pair, and an
adversarial (or merely diverse) request mix compiles without bound.

``GeometryPolicy`` closes that hole declaratively. The operator declares a
small bucket LATTICE (a handful of ``(way, shot, query)`` triples); every
incoming episode is coarsened UP to the smallest lattice entry that
contains it by padding with structurally-zero slots:

* support grows from ``way * shot`` rows to ``W * S`` rows of zero images
  with label 0; a float32 ``support_mask`` (1.0 over the real prefix, 0.0
  over the padding) rides the wire next to the episode;
* queries grow from ``query`` rows to ``Q`` zero rows — padded query rows
  are sliced off the response before the client sees them;
* episodes no lattice entry can contain are REJECTED at the front door
  (``GeometryRejectedError``, a ``ValueError`` → HTTP 400 with the lattice
  in the message) — an unservable geometry must be an actionable client
  error, never an unbounded compile.

The numeric contract is BIT-exactness over the real slice: every learner's
masked serve path (``serve_adapt_masked``) folds the mask in as exact
zeros — masked cross-entropy in MAML/ANIL/GD inner loops, ``-inf`` on
padded attention slots in matching nets, zero-weight one-hot rows in
prototype means — so logits over the real classes of a padded dispatch
equal a dispatch at the episode's TRUE geometry bit-for-bit, for all five
families (``tests/test_geometry.py`` pins it). Padding is never lossy.
One fine print: for MAML/ANIL/GD/protonets the padded dispatch is also
bit-identical to the pre-geometry MASKLESS program; matching nets'
attention softmax fuses differently under XLA once the mask is a runtime
input, so masked-vs-maskless agree only to ~1 ulp (identical argmax) even
with an all-ones mask at identical shapes — the bit-exact anchor is the
masked program at the true geometry, which is what a lattice-less client
of a geometry deployment would get anyway.

That contract has one structural precondition, validated at policy
attachment: the backbone forward must be ROW-INDEPENDENT, i.e.
``norm_layer="layer_norm"``. Batch norm mixes statistics across the
support/query row axis, so a padded zero row would perturb every real
row's activations — coarsening under batch statistics is silently wrong,
so the policy refuses to attach rather than serve approximate logits.

Pure numpy + stdlib: the policy runs at the front door (request
preparation), owns no device state, and is importable without jax.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

__all__ = [
    "GeometryPolicy",
    "GeometryRejectedError",
    "PaddedEpisode",
]

#: The row-independent norm the bit-exactness contract requires (see
#: module docstring); ``models/backbone.py`` spells it the same way.
ROW_INDEPENDENT_NORM = "layer_norm"


class GeometryRejectedError(ValueError):
    """No lattice entry can contain the episode.

    A ``ValueError`` subclass so existing front doors already map it to
    HTTP 400 (client error) — crucially NOT an overload signal: retrying
    the identical episode can never succeed, and the message names the
    lattice so the client can re-shape instead of re-send."""


@dataclasses.dataclass(frozen=True)
class PaddedEpisode:
    """One episode coarsened onto a lattice entry: padded wire arrays, the
    mask, and both geometries (coarsened = the shape bucket it rides;
    real = the slice the client gets back)."""

    x_support: np.ndarray  # (W*S, C, H, W) float32, zero-padded tail
    y_support: np.ndarray  # (W*S,) int32, label 0 over the padding
    x_query: np.ndarray  # (Q, C, H, W) float32, zero-padded tail
    support_mask: np.ndarray  # (W*S,) float32, 1.0 real prefix / 0.0 pad
    way: int  # coarsened
    shot: int
    query: int
    real_way: int
    real_shot: int
    real_query: int

    @property
    def coarsened(self) -> bool:
        return (self.way, self.shot, self.query) != (
            self.real_way, self.real_shot, self.real_query
        )


def _slot_cost(entry: tuple[int, int, int]) -> int:
    """Total padded slots a bucket dispatches — the waste metric coarsening
    minimizes when several lattice entries contain an episode."""
    way, shot, query = entry
    return way * shot + query


class GeometryPolicy:
    """A declared ``(way, shot, query)`` bucket lattice + the coarsening
    map onto it. Immutable after construction; thread-safe by virtue of
    having no mutable state."""

    def __init__(self, lattice: Sequence[Sequence[int]]):
        entries = []
        for raw in lattice:
            entry = tuple(int(d) for d in raw)
            if len(entry) != 3 or min(entry) < 1:
                raise ValueError(
                    "geometry lattice entries must be (way, shot, query) "
                    f"triples of positive ints, got {raw!r}"
                )
            entries.append(entry)
        if not entries:
            raise ValueError("geometry lattice must declare at least one bucket")
        # Sorted by slot cost then lexicographically: ``coarsen`` scans in
        # order and takes the FIRST containing entry, so ties (equal waste)
        # resolve deterministically across processes — a fleet must agree
        # on the bucket an episode rides or digest-affine routing breaks.
        self.lattice: tuple[tuple[int, int, int], ...] = tuple(
            sorted(set(entries), key=lambda e: (_slot_cost(e), e))
        )

    def __repr__(self) -> str:
        return f"GeometryPolicy({list(self.lattice)!r})"

    def describe(self) -> str:
        return ", ".join("x".join(str(d) for d in e) for e in self.lattice)

    def validate_backbone(self, backbone_cfg) -> None:
        """Refuses attachment to a model whose forward is not
        row-independent (see module docstring) or whose head cannot
        express the lattice's widest way."""
        norm = getattr(backbone_cfg, "norm_layer", None)
        if norm != ROW_INDEPENDENT_NORM:
            raise ValueError(
                "episode-geometry coarsening requires a row-independent "
                f"backbone forward (norm_layer={ROW_INDEPENDENT_NORM!r}); "
                f"got norm_layer={norm!r}, whose batch statistics would let "
                "padded zero rows perturb real logits"
            )
        max_way = max(e[0] for e in self.lattice)
        num_classes = int(getattr(backbone_cfg, "num_classes", max_way))
        if max_way > num_classes:
            raise ValueError(
                f"geometry lattice declares way {max_way} but the served "
                f"head has only {num_classes} classes"
            )

    def coarsen(self, way: int, shot: int, query: int) -> tuple[int, int, int]:
        """The smallest (fewest padded slots) lattice entry containing
        ``(way, shot, query)``, or ``GeometryRejectedError``."""
        for entry in self.lattice:
            if entry[0] >= way and entry[1] >= shot and entry[2] >= query:
                return entry
        raise GeometryRejectedError(
            f"no geometry bucket can contain a {way}-way {shot}-shot "
            f"{query}-query episode; the declared lattice is "
            f"[{self.describe()}] — re-shape the episode to fit a bucket "
            "(this is a request-shape error, not overload: retrying the "
            "same episode cannot succeed)"
        )

    def pad_episode(
        self,
        x_support: np.ndarray,
        y_support: np.ndarray,
        x_query: np.ndarray,
        *,
        way: int,
        shot: int,
    ) -> PaddedEpisode:
        """Coarsens one validated, FLAT, float32 episode (the engine's
        ``prepare_episode`` shapes — support ``(way*shot, C, H, W)``,
        labels ``(way*shot,)``, queries ``(T, C, H, W)``) up to its lattice
        bucket. Real rows stay a contiguous prefix in their original
        order; padding is exact zeros (images), label 0 (a valid class —
        the mask, not the label, is what excludes the row), and mask 0."""
        real_query = int(x_query.shape[0])
        target_way, target_shot, target_query = self.coarsen(
            way, shot, real_query
        )
        n_real = int(x_support.shape[0])
        n_rows = target_way * target_shot
        xs = np.zeros((n_rows,) + x_support.shape[1:], np.float32)
        xs[:n_real] = x_support
        ys = np.zeros((n_rows,), np.int32)
        ys[:n_real] = y_support
        mask = np.zeros((n_rows,), np.float32)
        mask[:n_real] = 1.0
        xq = np.zeros((target_query,) + x_query.shape[1:], np.float32)
        xq[:real_query] = x_query
        return PaddedEpisode(
            x_support=xs,
            y_support=ys,
            x_query=xq,
            support_mask=mask,
            way=target_way,
            shot=target_shot,
            query=target_query,
            real_way=int(way),
            real_shot=int(shot),
            real_query=real_query,
        )
