"""Declarative knob space: every tunable registered as data.

Each :class:`Knob` records its CLI surface, legal candidate values, a
divisibility/compatibility guard (the ``parallel/sharding.guard_task_chunk``
refusal idiom: raise ``ValueError`` with the exact reason, never silently
clamp), and which bench keys the knob moves. Three consumers:

* ``tune/autotuner.py`` enumerates ``legal_candidates`` to build its
  probe set — an illegal value is unrepresentable, not a runtime crash
  three probes in;
* ``config_fingerprint`` hashes the RESOLVED knob set into the stable
  12-hex id stamped on heartbeat ``status.json``, telemetry ``step``
  events, and bench emissions, so every fleet event and bench row is
  attributable to the exact configuration that produced it;
* graftlint's resource-plane entry lints this module standalone — the
  space is code-reviewed data, not tribal knowledge.

The registry deliberately holds ONLY knobs with a measured bench key to
move (PERF_NOTES receipts): a knob nobody can judge is noise in the
search space.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Callable


@dataclasses.dataclass(frozen=True)
class TuneContext:
    """The machine/run facts guards check candidates against.

    ``dp``/``mp`` are the CURRENT mesh extents (the context a non-mesh
    knob must stay compatible with); ``n_devices`` bounds candidate mesh
    shapes; ``global_batch`` is the meta-batch size divisibility anchor.
    """

    n_devices: int = 1
    dp: int = 1
    mp: int = 1
    global_batch: int = 8


GuardFn = Callable[[Any, TuneContext], None]


@dataclasses.dataclass(frozen=True)
class Knob:
    """One tunable, as data.

    ``flag`` is the CLI spelling (train plane) or the config field path
    (serve plane) — the autotuner renders it verbatim into the winning
    one-liner. ``regime`` names the roofline regime the knob attacks
    (``dispatch``/``memory``/``compute``/``latency``): the autotuner
    ranks regime-matching knobs first after classifying the ledger's
    roofline position. ``moves`` are the bench keys an A/B on this knob
    is judged over.
    """

    name: str
    flag: str
    plane: str  # "train" | "serve"
    regime: str  # "dispatch" | "memory" | "compute" | "latency"
    default: Any
    candidates: tuple
    moves: tuple[str, ...]
    guard: GuardFn | None = None
    description: str = ""

    def check(self, value: Any, ctx: TuneContext) -> None:
        """Refuses an illegal ``value`` under ``ctx`` (ValueError with the
        reason), guard_task_chunk-style. Legal values pass silently."""
        if value != self.default and value not in self.candidates:
            raise ValueError(
                f"{self.flag} {value!r} is not a registered candidate for "
                f"knob {self.name!r} (legal: {list(self.candidates)})"
            )
        if self.guard is not None:
            self.guard(value, ctx)

    def legal_candidates(self, ctx: TuneContext) -> tuple:
        """The candidate values whose guards pass under ``ctx`` — the
        autotuner's probe set. The default is excluded (it is the A side
        of every A/B)."""
        out = []
        for value in self.candidates:
            if value == self.default:
                continue
            try:
                self.check(value, ctx)
            except ValueError:
                continue
            out.append(value)
        return tuple(out)


# ---------------------------------------------------------------------------
# Guards (the refusal idiom of parallel/sharding.guard_task_chunk: name the
# flag, the value, and the divisibility fact that rejects it)
# ---------------------------------------------------------------------------


def _guard_task_chunk(value: Any, ctx: TuneContext) -> None:
    chunk = int(value)
    if chunk <= 0:
        return
    if ctx.dp > 1 and chunk % ctx.dp != 0:
        raise ValueError(
            f"--task_chunk {chunk} must be a multiple of the mesh's dp "
            f"extent {ctx.dp} (each scan step shards its chunk of tasks "
            "over 'dp')"
        )
    if ctx.global_batch % chunk != 0:
        raise ValueError(
            f"--task_chunk {chunk} must divide the meta-batch size "
            f"{ctx.global_batch} (the scan form reshapes (B, ...) -> "
            "(B//chunk, chunk, ...))"
        )


def _guard_mesh_shape(value: Any, ctx: TuneContext) -> None:
    dp, mp = int(value[0]), int(value[1])
    if dp < 1 or mp < 1:
        raise ValueError(f"mesh shape dp{dp}xmp{mp}: extents must be >= 1")
    if dp * mp > ctx.n_devices:
        raise ValueError(
            f"mesh shape dp{dp}xmp{mp} needs {dp * mp} devices but only "
            f"{ctx.n_devices} are available"
        )
    if ctx.global_batch % dp != 0:
        raise ValueError(
            f"mesh shape dp{dp}xmp{mp}: the meta-batch size "
            f"{ctx.global_batch} must be a multiple of the dp extent {dp} "
            "(the task axis shards over 'dp')"
        )


def _guard_positive_int(flag: str) -> GuardFn:
    def guard(value: Any, ctx: TuneContext) -> None:  # noqa: ARG001
        if int(value) < 1:
            raise ValueError(f"{flag} must be >= 1, got {value}")

    return guard


def _guard_nonneg(flag: str) -> GuardFn:
    def guard(value: Any, ctx: TuneContext) -> None:  # noqa: ARG001
        if float(value) < 0:
            raise ValueError(f"{flag} must be >= 0, got {value}")

    return guard


# ---------------------------------------------------------------------------
# The registry
# ---------------------------------------------------------------------------

SPACE: dict[str, Knob] = {
    knob.name: knob
    for knob in (
        Knob(
            name="iters_per_dispatch",
            flag="--iters_per_dispatch",
            plane="train",
            regime="dispatch",
            default=1,
            candidates=(1, 5, 25),
            moves=(
                "maml++_omniglot_5w1s_meta_iters_per_s",
                "sustained_meta_iters_per_s",
            ),
            guard=_guard_positive_int("--iters_per_dispatch"),
            description=(
                "K meta-updates per device dispatch (lax.scan iteration "
                "batching) — amortizes the per-dispatch host overhead; "
                "the dominant lever when dispatch overhead bounds tiny "
                "programs (PERF_NOTES r03: 152 -> 6,993 meta-iters/s)."
            ),
        ),
        Knob(
            name="task_chunk",
            flag="--task_chunk",
            plane="train",
            regime="memory",
            default=0,
            candidates=(0, 2, 4, 8),
            moves=("hbm_peak_bytes", "imagenet_shape_meta_iters_per_s"),
            guard=_guard_task_chunk,
            description=(
                "Sequential task-axis scan chunking inside the step "
                "program: trades parallel task HBM footprint for scan "
                "steps — the HBM-spill lever for imagenet-shape batches."
            ),
        ),
        Knob(
            name="lane_pad_channels",
            flag="--lane_pad_channels",
            plane="train",
            regime="compute",
            default=False,
            candidates=(False, True),
            moves=("maml++_omniglot_5w1s_meta_iters_per_s", "mfu_pct"),
            description=(
                "Pad conv channel counts up to the VPU lane width so "
                "narrow backbones stop wasting lanes on structural "
                "zeros (PR 9 lever; judged on the aggregate key)."
            ),
        ),
        Knob(
            name="device_prefetch",
            flag="--device_prefetch",
            plane="train",
            regime="dispatch",
            default=-1,
            candidates=(-1, 0, 2, 4, 8),
            moves=("data_wait_frac", "sustained_meta_iters_per_s"),
            description=(
                "Device-prefetch stager depth (-1 auto, 0 off): hides "
                "host->device transfer behind compute; deeper queues "
                "buy overlap at HBM cost."
            ),
        ),
        Knob(
            name="mesh_shape",
            flag="--data_parallel_devices/--model_parallel_devices",
            plane="train",
            regime="compute",
            default=(1, 1),
            candidates=((1, 1), (2, 1), (4, 1), (8, 1), (2, 2), (4, 2)),
            moves=(
                "multichip_maml_scaling_efficiency",
                "comm_bytes_per_iter",
            ),
            guard=_guard_mesh_shape,
            description=(
                "dp x mp mesh shape: dp shards the task axis, mp the "
                "channel axes. Guarded by device count and meta-batch "
                "divisibility; judged on scaling efficiency vs comm."
            ),
        ),
        Knob(
            name="serve_max_batch",
            flag="serve.meta_batch_size",
            plane="serve",
            regime="latency",
            default=4,
            candidates=(1, 2, 4, 8, 16),
            moves=("serve_qps", "serve_p99_ms"),
            guard=_guard_positive_int("serve.meta_batch_size"),
            description=(
                "Serving micro-batch width per dispatch: wider batches "
                "buy QPS at tail-latency cost (one compile per width — "
                "the bucket set re-warms on change)."
            ),
        ),
        Knob(
            name="serve_max_wait_ms",
            flag="serve.max_wait_ms",
            plane="serve",
            regime="latency",
            default=2.0,
            candidates=(0.0, 0.5, 2.0, 5.0, 10.0),
            moves=("serve_p99_ms", "serve_qps"),
            guard=_guard_nonneg("serve.max_wait_ms"),
            description=(
                "Batcher deadline: how long an under-full micro-batch "
                "may wait for co-riders before dispatching anyway."
            ),
        ),
        Knob(
            name="serve_queue_margin",
            flag="serve.degrade_queue_depth/serve.max_queue_depth",
            plane="serve",
            regime="latency",
            default=(16, 64),
            candidates=((8, 32), (16, 64), (32, 128)),
            moves=("serve_error_rate", "serve_p99_ms"),
            description=(
                "Queue-depth margin pair (degrade threshold, hard "
                "cap): where the engine starts shedding accuracy and "
                "where it starts refusing — the overload-vs-tail "
                "dispatch margin."
            ),
        ),
    )
}


def resolve(
    overrides: dict[str, Any] | None = None,
    ctx: TuneContext | None = None,
) -> dict[str, Any]:
    """The full resolved knob set: defaults overlaid with ``overrides``
    (knob-name keyed), every value guard-checked under ``ctx``. Unknown
    override names refuse loudly — a typo must not silently tune
    nothing."""
    ctx = ctx or TuneContext()
    overrides = dict(overrides or {})
    unknown = sorted(set(overrides) - set(SPACE))
    if unknown:
        raise ValueError(
            f"unknown knob(s) {unknown}; registered: {sorted(SPACE)}"
        )
    resolved: dict[str, Any] = {}
    for name, knob in SPACE.items():
        value = overrides.get(name, knob.default)
        knob.check(value, ctx)
        resolved[name] = value
    return resolved


def config_fingerprint(resolved: dict[str, Any]) -> str:
    """Stable 12-hex id of a resolved knob set: sha256 over the
    canonical (sorted-key, no-whitespace) JSON rendering. Tuples and
    lists hash identically (JSON has only arrays) — the fingerprint is
    a value hash, not a Python-type hash."""
    canon = json.dumps(
        {k: resolved[k] for k in sorted(resolved)},
        sort_keys=True,
        separators=(",", ":"),
        default=str,
    )
    return hashlib.sha256(canon.encode("utf-8")).hexdigest()[:12]


#: argparse attribute -> knob-name mapping for the train plane (the serve
#: knobs live on ServeConfig, not the train parser).
_ARG_ATTRS = {
    "iters_per_dispatch": "iters_per_dispatch",
    "task_chunk": "task_chunk",
    "lane_pad_channels": "lane_pad_channels",
    "device_prefetch": "device_prefetch",
}


def fingerprint_from_args(args: Any) -> str:
    """``config_fingerprint`` of a parsed train-CLI namespace (or any
    object carrying the knob attributes). Missing attributes fall back
    to the knob default — an older config JSON without a knob hashes as
    if the knob were at its default, which is what it runs as. Guards
    are NOT re-checked here: the fingerprint attributes the config that
    actually ran, including one an operator forced past the space."""
    resolved = {name: knob.default for name, knob in SPACE.items()}
    for attr, name in _ARG_ATTRS.items():
        if hasattr(args, attr):
            value = getattr(args, attr)
            # Coerce to the default's type so a pre-normalized namespace
            # (string bools, numeric strings) hashes identically to the
            # processed one.
            if isinstance(SPACE[name].default, bool):
                value = str(value).lower() == "true" if isinstance(value, str) else bool(value)
            elif isinstance(SPACE[name].default, int):
                value = int(value)
            resolved[name] = value
    dp = int(getattr(args, "data_parallel_devices", 1) or 1)
    mp = int(getattr(args, "model_parallel_devices", 1) or 1)
    resolved["mesh_shape"] = (dp, mp)
    return config_fingerprint(resolved)
