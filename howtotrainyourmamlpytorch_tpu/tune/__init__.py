"""Self-driving resource plane (ISSUE 20).

``tune/space.py`` declares every performance knob as DATA — legal
values, divisibility guards, and which bench key each knob moves — so
the search space is introspectable and lint-checkable instead of
scattered across argparse. ``tune/autotuner.py`` closes the loop: it
reads the ProgramLedger's roofline position and the BENCH_* trajectory,
ranks candidate single-knob moves, drives short A/B probes under
bench's contention-sentinel protocol, and hands the verdict to
``tools/bench_judge.py`` mechanically.
"""

from .space import (  # noqa: F401
    Knob,
    TuneContext,
    SPACE,
    config_fingerprint,
    fingerprint_from_args,
    resolve,
)
