"""Ledger-guided autotuner: roofline position -> ranked A/B probes ->
mechanical keep/revert via bench_judge.

Zero human choices, end to end: the knob space is declared data
(``tune/space.py``), the roofline regime is read from the ProgramLedger
(arithmetic intensity vs the ``PEAK_FLOPS_BY_KIND`` peak over an HBM
ridge), every probe runs under bench's contention-sentinel protocol
(flagged probes retried then DISCARDED — a poisoned number is never
judged), and the verdict is handed to ``tools/bench_judge.judge``
mechanically: the winning lever's gate is appended to
``tools/bench_gates.json`` with provenance ``source: autotune:<run_id>``
only when the judge says ``keep``. A human never picks a number, and a
future regression of the kept lever still turns tier-1 red through the
ordinary judge path.

The probe is deliberately tiny (2-stage 4-filter first-order MAML on
28x28 synthetic episodes): the tuned knobs move DISPATCH and LAYOUT
costs, which the tiny program exposes undiluted, and a probe must be
cheap enough to run on a quiet host between real work. Measured values
land in ``AUTOTUNE_<run_id>_r0*.json`` wrappers (the BENCH_* trajectory
layout), so the receipts replay through the same judge.

Measurement and sentinel functions are injectable (``measure_fn``/
``sentinel_fn``) so the decision machinery is testable without a JAX
probe; CLI: ``tools/autotune.py``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import statistics
import time

from .space import SPACE, TuneContext, config_fingerprint, resolve

#: The probe's judged bench key and the baseline key its gate references.
PROBE_KEY = "autotune_probe_meta_iters_per_s"
BASELINE_KEY = "autotune_baseline_meta_iters_per_s"

#: HBM bandwidth (bytes/s) per device kind for the roofline ridge —
#: conservative public figures, same keying as
#: ``telemetry/device.PEAK_FLOPS_BY_KIND``. The ridge (peak FLOPs / BW)
#: splits memory-bound from compute-bound programs; a kind missing here
#: falls back to the dispatch regime, which is also the honest CPU
#: answer (no cost analysis, dispatch overhead dominates tiny programs).
HBM_BW_BY_KIND = {
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,
    "TPU v4": 1228e9,
    "TPU v6 lite": 1640e9,
}


@dataclasses.dataclass(frozen=True)
class ProbeSpec:
    """The tiny A/B workload (identical for baseline and candidates —
    only the knob under test differs)."""

    batch_size: int = 8
    num_classes: int = 5
    shots: int = 1
    num_stages: int = 2
    num_filters: int = 4
    image_size: int = 28
    inner_steps: int = 2
    #: Meta-iterations one timing window aims for (rounded to whole
    #: dispatches of the candidate's K).
    window_iters: int = 50
    windows: int = 3
    #: Sentinel retries before a contended probe is discarded.
    contention_retries: int = 2


def classify_regime(
    arithmetic_intensity: float | None,
    device_kind: str,
    peak_flops: float | None,
) -> tuple[str, str]:
    """Roofline position -> knob regime (``dispatch``/``memory``/
    ``compute``) + a human reason. Intensity below the ridge means the
    program is HBM-bound; above it, FLOPs-bound; unknown (no cost
    analysis — CPU backends) means per-dispatch overhead is the only
    measurable lever."""
    bw = HBM_BW_BY_KIND.get(device_kind)
    if arithmetic_intensity is None or not peak_flops or not bw:
        return "dispatch", (
            f"no roofline position for {device_kind!r} (no cost analysis "
            "or no bandwidth table entry): dispatch overhead is the "
            "measurable lever"
        )
    ridge = peak_flops / bw
    if arithmetic_intensity < ridge:
        return "memory", (
            f"intensity {arithmetic_intensity:.1f} FLOP/B below the "
            f"{device_kind} ridge {ridge:.1f}: HBM-bound"
        )
    return "compute", (
        f"intensity {arithmetic_intensity:.1f} FLOP/B above the "
        f"{device_kind} ridge {ridge:.1f}: FLOPs-bound"
    )


def rank_candidates(
    regime: str, ctx: TuneContext, max_candidates: int = 6
) -> list[tuple[str, object]]:
    """Single-knob candidates ``(knob_name, value)``, regime-matching
    knobs first (stable within a knob: declared candidate order), capped
    at ``max_candidates``. Only probe-appliable train knobs are ranked —
    a knob the probe cannot apply would judge noise."""
    ranked: list[tuple[str, object]] = []
    knobs = sorted(
        (k for k in SPACE.values()
         if k.plane == "train" and k.name in PROBE_APPLIERS),
        key=lambda k: (k.regime != regime, k.name),
    )
    for knob in knobs:
        for value in knob.legal_candidates(ctx):
            ranked.append((knob.name, value))
    return ranked[:max_candidates]


# ---------------------------------------------------------------------------
# The default probe (JAX) — injectable for tests
# ---------------------------------------------------------------------------


def _probe_batch(spec: ProbeSpec, rng):
    import numpy as np

    n = spec.num_classes * spec.shots
    img = (1, spec.image_size, spec.image_size)
    xs = rng.rand(spec.batch_size, n, *img).astype(np.float32)
    xt = rng.rand(spec.batch_size, n, *img).astype(np.float32)
    ys = np.tile(
        np.repeat(np.arange(spec.num_classes, dtype=np.int32), spec.shots),
        (spec.batch_size, 1),
    )
    return xs, xt, ys, ys.copy()


def _probe_config(overrides: dict, spec: ProbeSpec):
    from ..models import BackboneConfig, MAMLConfig

    backbone = BackboneConfig(
        num_stages=spec.num_stages,
        num_filters=spec.num_filters,
        per_step_bn_statistics=True,
        num_steps=spec.inner_steps,
        num_classes=spec.num_classes,
        image_channels=1,
        image_height=spec.image_size,
        image_width=spec.image_size,
        lane_pad_channels=bool(overrides.get("lane_pad_channels", False)),
    )
    return MAMLConfig(
        backbone=backbone,
        number_of_training_steps_per_iter=spec.inner_steps,
        number_of_evaluation_steps_per_iter=spec.inner_steps,
        task_learning_rate=0.1,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        second_order=False,
        use_multi_step_loss_optimization=False,
        task_chunk=int(overrides.get("task_chunk", 0)),
    )


#: Knob name -> how the default probe applies it. Membership IS the
#: "probeable on this host" predicate ``rank_candidates`` filters on;
#: the values document the seam each knob rides.
PROBE_APPLIERS = {
    "iters_per_dispatch": "K batches per run_train_iters dispatch",
    "task_chunk": "MAMLConfig.task_chunk",
    "lane_pad_channels": "BackboneConfig.lane_pad_channels",
}


def default_measure(overrides: dict, spec: ProbeSpec) -> float:
    """Builds the tiny learner with ``overrides`` applied and returns the
    median-window meta-iters/s (same windowed-median shape as bench's
    ``_windowed_rates`` — robust to a transient dip, no max-selection
    bias)."""
    import jax
    import numpy as np

    from ..models import MAMLFewShotLearner

    cfg = _probe_config(overrides, spec)
    k = int(overrides.get("iters_per_dispatch", 1))
    learner = MAMLFewShotLearner(cfg)
    state = learner.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    batches = [_probe_batch(spec, rng) for _ in range(k)]
    state, _ = learner.run_train_iters(state, batches, epoch=0)  # compile
    jax.block_until_ready(state.theta)
    per_window = max(1, -(-spec.window_iters // k))
    rates = []
    for _ in range(spec.windows):
        t0 = time.perf_counter()
        for _ in range(per_window):
            state, _ = learner.run_train_iters(state, batches, epoch=0)
        jax.block_until_ready(state.theta)
        rates.append(per_window * k / (time.perf_counter() - t0))
    return statistics.median(rates)


#: Run-local sentinel floor, established once per process by
#: ``default_sentinel`` (min of a few startup readings). An A/B probe
#: needs WITHIN-RUN consistency — both sides measured under equal load —
#: not the cross-run comparability the persistent BENCH quiet norms
#: provide, and those norms are recorded for other hosts. The env
#: override (``BENCH_QUIET_SENTINEL_MS``) still wins when set, and the
#: live-trainer /proc scan — the direct signal — is always honored.
_run_floor_ms: float | None = None


def default_sentinel() -> dict:
    """One contention reading via bench's sentinel protocol (lazy import:
    ``bench`` lives at the repo root, on ``sys.path`` for every tools/
    CLI), judged against the run-local floor (see ``_run_floor_ms``).
    Returns ``{"contended": bool, ...signals}``; an import failure
    reports honestly unknown (``contended: False, sentinel_ms: None``) —
    the CLI records the gap rather than inventing a quiet reading."""
    global _run_floor_ms
    try:
        import bench
    except ImportError:
        return {"contended": False, "sentinel_ms": None,
                "reason": "bench module unavailable"}

    env = os.environ.get("BENCH_QUIET_SENTINEL_MS")
    if _run_floor_ms is None:
        if env:
            try:
                _run_floor_ms = float(env)
            except ValueError:
                _run_floor_ms = None
        if _run_floor_ms is None:
            _run_floor_ms = min(
                bench._sentinel_ms(repeats=10) for _ in range(3)
            )
    ms = bench._sentinel_ms(repeats=10)
    trainers = bench._live_trainer_pids()
    contended = bool(trainers) or (
        ms > bench.SENTINEL_CONTENTION_FACTOR * _run_floor_ms
    )
    return {
        "contended": contended,
        "sentinel_ms": ms,
        "floor_ms": _run_floor_ms,
        "live_trainers": trainers,
    }


# ---------------------------------------------------------------------------
# The run
# ---------------------------------------------------------------------------


def _measure_clean(
    overrides: dict, spec: ProbeSpec, measure_fn, sentinel_fn
) -> tuple[float | None, list[dict]]:
    """One sentinel-bracketed measurement, retried while flagged. Returns
    ``(value, sentinel_log)`` — value ``None`` when every attempt was
    contended (the probe is DISCARDED, never judged)."""
    log: list[dict] = []
    for _attempt in range(spec.contention_retries + 1):
        before = sentinel_fn()
        value = measure_fn(overrides, spec)
        after = sentinel_fn()
        flagged = bool(before["contended"] or after["contended"])
        log.append({"before": before, "after": after, "flagged": flagged})
        if not flagged:
            return value, log
    return None, log


def autotune_run(
    *,
    run_id: str,
    ctx: TuneContext | None = None,
    spec: ProbeSpec | None = None,
    min_gain: float = 0.05,
    max_candidates: int = 6,
    device_kind: str | None = None,
    peak_flops: float | None = None,
    arithmetic_intensity: float | None = None,
    measure_fn=default_measure,
    sentinel_fn=default_sentinel,
    judge_fn=None,
) -> dict:
    """The full loop: classify -> rank -> probe (sentinel-clean) ->
    judge -> verdict document.

    The caller (``tools/autotune.py``) owns filesystem side effects
    (emission wrappers, the gates-file append); this function returns the
    verdict document only, so tests can drive it hermetically with
    injected ``measure_fn``/``sentinel_fn``. ``judge_fn`` defaults to
    ``tools.bench_judge.judge`` (lazy import)."""
    ctx = ctx or TuneContext()
    spec = spec or ProbeSpec()
    if judge_fn is None:
        from tools.bench_judge import judge as judge_fn  # noqa: PLC0415

    regime, regime_reason = classify_regime(
        arithmetic_intensity, device_kind or "cpu", peak_flops
    )
    candidates = rank_candidates(regime, ctx, max_candidates)

    baseline, baseline_log = _measure_clean({}, spec, measure_fn, sentinel_fn)
    result = {
        "run_id": run_id,
        "regime": regime,
        "regime_reason": regime_reason,
        "ranked_candidates": [
            {"knob": name, "value": value} for name, value in candidates
        ],
        "baseline": baseline,
        "baseline_sentinel": baseline_log[-1] if baseline_log else None,
        "probes": [],
        "winner": None,
        "emissions": None,
    }
    if baseline is None:
        result["error"] = (
            "baseline probe contended on every attempt — nothing judged"
        )
        return result

    best = None  # (value, knob_name, knob_value, fingerprint)
    for name, value in candidates:
        overrides = {name: value}
        measured, _log = _measure_clean(
            overrides, spec, measure_fn, sentinel_fn
        )
        probe_row = {
            "knob": name,
            "value": value,
            "measured": measured,
            "discarded": measured is None,
        }
        result["probes"].append(probe_row)
        if measured is None:
            continue
        if best is None or measured > best[0]:
            fp = config_fingerprint(resolve(overrides, ctx))
            best = (measured, name, value, fp)

    if best is None:
        result["error"] = "every candidate probe contended — nothing judged"
        return result

    measured, knob_name, knob_value, fingerprint = best
    knob = SPACE[knob_name]
    lever = f"{knob.flag}={knob_value}"
    gate_expr = f"this > {1.0 + min_gain:g} * {BASELINE_KEY}"
    gates_doc = {
        "schema": 1,
        "gates": {
            PROBE_KEY: {
                "direction": "higher",
                "gate": gate_expr,
                "lever": lever,
                "source": f"autotune:{run_id}",
            },
        },
        "ungated_ok": [
            BASELINE_KEY, "contended", "config_fingerprint",
            "autotune_knob", "autotune_value",
        ],
    }
    baseline_fp = config_fingerprint(resolve({}, ctx))
    runs = [
        {
            "name": f"AUTOTUNE_{run_id}_r01.json",
            "n": 1,
            "parsed": {
                PROBE_KEY: baseline,
                BASELINE_KEY: baseline,
                "contended": False,
                "config_fingerprint": baseline_fp,
            },
            "contended": False,
        },
        {
            "name": f"AUTOTUNE_{run_id}_r02.json",
            "n": 2,
            "parsed": {
                PROBE_KEY: measured,
                BASELINE_KEY: baseline,
                "autotune_knob": knob_name,
                "autotune_value": knob_value,
                "contended": False,
                "config_fingerprint": fingerprint,
            },
            "contended": False,
        },
    ]
    judged = judge_fn(gates_doc, runs)
    verdict = judged["verdicts"][PROBE_KEY]["verdict"]
    result["emissions"] = [dict(run) for run in runs]
    result["judge"] = {
        "verdict": verdict,
        "reason": judged["verdicts"][PROBE_KEY]["reason"],
        "gate": gate_expr,
    }
    result["winner"] = (
        {
            "knob": knob_name,
            "value": knob_value,
            "lever": lever,
            "measured": measured,
            "baseline": baseline,
            "gain": measured / baseline - 1.0,
            "config_fingerprint": fingerprint,
            "gate_entry": {
                **gates_doc["gates"][PROBE_KEY],
                "note": (
                    f"autotuned on {device_kind or 'cpu'}: {lever} "
                    f"{baseline:.2f} -> {measured:.2f} meta-iters/s "
                    f"({(measured / baseline - 1.0) * 100:.0f}% gain, "
                    f"sentinel-clean)"
                ),
            },
        }
        if verdict == "keep"
        else None
    )
    return result


def append_gate(
    gates_path: str, key: str, entry: dict, ungated_extra=()
) -> None:
    """Appends/replaces one gate in ``tools/bench_gates.json`` (atomic
    tmp+rename — a killed autotuner never leaves a torn gates file) and
    records any referenced helper keys in ``ungated_ok``."""
    with open(gates_path) as f:
        doc = json.load(f)
    doc["gates"][key] = entry
    ungated = list(doc.get("ungated_ok", []))
    for name in ungated_extra:
        if name not in ungated:
            ungated.append(name)
    doc["ungated_ok"] = ungated
    tmp = gates_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    os.replace(tmp, gates_path)
