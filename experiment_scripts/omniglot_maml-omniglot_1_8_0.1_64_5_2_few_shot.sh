#!/bin/sh
# Runner template: $execution_script$ / $experiment_config$ are filled by
# generate_scripts.py. Arg 1 optionally selects a device ordinal.
export DEVICE_ID=$1
echo $DEVICE_ID
cd ..
export DATASET_DIR="datasets/"
python train_maml_system.py --name_of_args_json_file experiment_config/omniglot_maml-omniglot_1_8_0.1_64_5_2.json --gpu_to_use $DEVICE_ID --transfer_dtype uint8 --iters_per_dispatch 25 --use_pallas_fused_norm True
