"""Runner-script generator: one shell script per experiment config.

Capability parity with the reference's
``script_generation_tools/generate_scripts.py`` (``:31-45``): for every JSON
in ``experiment_config/``, fill ``local_run_template_script.sh``'s last line
with the entry script + config name and write
``experiment_scripts/<config>_few_shot.sh``.

Documented divergence: the reference points every script at
``train_maml_system.py`` (``generate_scripts.py:6``), including the
gradient-descent and matching-nets configs, contradicting their own
``model`` tags; here the entry point follows the config's model.
"""

from __future__ import annotations

import json
import os

SCRIPT_DIR = os.path.dirname(__file__)
LOCAL_SCRIPT_DIR = os.path.join(SCRIPT_DIR, "..", "experiment_scripts")
EXPERIMENT_JSON_DIR = os.path.join(SCRIPT_DIR, "..", "experiment_config")
MODEL_TO_SCRIPT = {
    "gradient_descent": "train_gradient_descent_system.py",
    "matching_nets": "train_matching_nets_system.py",
}
DEFAULT_SCRIPT = "train_maml_system.py"
PREFIX = "few_shot"


def main() -> None:
    os.makedirs(LOCAL_SCRIPT_DIR, exist_ok=True)
    with open(os.path.join(SCRIPT_DIR, "local_run_template_script.sh")) as f:
        template = f.readlines()

    for file in sorted(os.listdir(EXPERIMENT_JSON_DIR)):
        if not file.endswith(".json"):
            continue
        with open(os.path.join(EXPERIMENT_JSON_DIR, file)) as f:
            cfg = json.load(f)
        model = cfg.get("model", "maml")
        lines = list(template)
        lines[-1] = (
            lines[-1]
            .replace("$execution_script$", MODEL_TO_SCRIPT.get(model, DEFAULT_SCRIPT))
            .replace("$experiment_config$", file)
        )
        # Second-order MAML at 20-way diverges under the TPU's default
        # bf16-multiply matmul precision (PERF_NOTES.md); pin true f32.
        second_order = (
            str(cfg.get("second_order", "")).lower() in ("true", "1")
            or int(cfg.get("first_order_to_second_order_epoch", -1)) >= 0
        )
        if int(cfg.get("num_classes_per_set", 0)) >= 20 and second_order:
            lines[-1] = lines[-1].rstrip("\n") + " --matmul_precision highest\n"
        # Omniglot pixels are exactly 0/1, so the uint8 wire format is
        # BIT-EXACT (tests/test_wire_codec.py) while moving 4x fewer bytes
        # through the device tunnel — 2.2x measured scan-dispatch throughput
        # and 4x less tunnel-client leak (PERF_NOTES.md).
        if "omniglot" in cfg.get("dataset_name", "").lower():
            lines[-1] = lines[-1].rstrip("\n") + " --transfer_dtype uint8\n"
            # K=25 scan dispatch halves the flagship epoch wall-clock
            # (7.7 s vs 15.5 s) with golden-run accuracy evidence (two full
            # runs: 0.99267 / 0.99567 test vs the reference's 0.99433 —
            # GOLDEN_RUNS.md). MAML entry only: the baselines' builders
            # fall back to K=1 (no run_train_iters), so pinning there
            # would only mislead.
            if MODEL_TO_SCRIPT.get(model, DEFAULT_SCRIPT) == DEFAULT_SCRIPT:
                lines[-1] = (
                    lines[-1].rstrip("\n") + " --iters_per_dispatch 25\n"
                )
        # The Pallas fused bn+leaky_relu kernel wins 1.28x on the MAML++
        # EVAL path (the only path the maml learner gates it onto; the
        # second-order train step keeps the lax norm) but measurably LOSES
        # on the GD (0.93x) and matching-nets (0.77x) training paths —
        # tools/pallas_bench.py, PERF_NOTES.md. Enable it only for the MAML
        # entry point.
        if MODEL_TO_SCRIPT.get(model, DEFAULT_SCRIPT) == DEFAULT_SCRIPT:
            lines[-1] = (
                lines[-1].rstrip("\n") + " --use_pallas_fused_norm True\n"
            )
        out = os.path.join(
            LOCAL_SCRIPT_DIR, "{}_{}.sh".format(file.replace(".json", ""), PREFIX)
        )
        with open(out, "w") as f:
            f.write("".join(lines))
        os.chmod(out, 0o755)
    print("scripts written to", os.path.abspath(LOCAL_SCRIPT_DIR))


if __name__ == "__main__":
    main()
