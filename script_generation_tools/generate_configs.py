"""Experiment config generator: cartesian hyperparameter sweep over $var$
templates.

Capability parity with the reference's
``script_generation_tools/generate_configs.py`` (``:29-136``), with
reference-identical output filenames:
``<template>-<dataset>_<shots>_<batch>_<innerlr>_<filters>_<ways>_<seed>.json``
(the sweep-tag field order is the reference's ``hyper_config`` namedtuple
order). The ``omniglot_gradient-descent`` / ``omniglot_matching-nets``
templates reproduce the reference's two hand-added baseline configs: they
are emitted only for the (1-shot, 5-way, seed 1) point and carry the
model-tagged experiment names of the bundled runs (``omniglot_gd_*``,
``omniglot_matching_nets_*``).

The checked-in ``experiment_config/`` files are not a clean generator output:
the reference hand-edited a handful after generating (model tags on the six
bundled-run configs, renamed experiment names on the two seed-1 flagship
runs, a stray ``task_learning_rate`` and an absolute ``dataset_path``).
``REFERENCE_HAND_EDITS`` reproduces those edits per file so regeneration is
content-identical with the reference's 38 configs.
"""

from __future__ import annotations

import json
import os

SEED_LIST = [0, 1, 2]

# Per-dataset sweep ranges (the paper's experiment grid), field order as the
# reference's hyper_config namedtuple (generate_configs.py:29-36).
HYPER = {
    "omniglot": dict(
        num_samples_per_class_range=[1, 5],
        batch_size_range=[8],
        init_inner_loop_learning_rate_range=[0.1],
        num_filters=[64],
        num_classes_range=[20, 5],
        target_samples_per_class=1,
    ),
    "mini-imagenet": dict(
        num_samples_per_class_range=[1, 5],
        batch_size_range=[2],
        init_inner_loop_learning_rate_range=[0.01],
        num_filters=[48],
        num_classes_range=[5],
        target_samples_per_class=15,
    ),
}

# The reference's two baseline configs exist only at this sweep point
# (experiment_config/omniglot_{gradient-descent,matching-nets}-*.json).
BASELINE_TEMPLATES = {
    "omniglot_gradient-descent": "gd",
    "omniglot_matching-nets": "matching_nets",
}
BASELINE_POINT = dict(shots=1, ways=5, seed=1)

# Post-generation edits present in the reference's checked-in configs but not
# producible by its template sweep (see module docstring).
REFERENCE_HAND_EDITS = {
    "omniglot_maml++-omniglot_1_8_0.1_64_5_1.json": {
        "experiment_name": "omniglot_maml++_1_8_0.1_64_5_1",
        "model": "maml++",
    },
    "omniglot_maml++-omniglot_1_8_0.1_64_20_1.json": {"model": "maml++"},
    "omniglot_maml-omniglot_1_8_0.1_64_5_1.json": {
        "experiment_name": "omniglot_maml_1_8_0.1_64_5_1",
        "model": "maml",
    },
    "omniglot_maml-omniglot_1_8_0.1_64_20_1.json": {"model": "maml"},
    "omniglot_maml-omniglot_1_8_0.1_64_5_0.json": {"task_learning_rate": 0.1},
    "mini-imagenet_maml-mini-imagenet_1_2_0.01_48_5_0.json": {
        "dataset_path": "/datasets/mini-imagenet",
    },
}

TEMPLATE_DIR = os.path.join(os.path.dirname(__file__), "..",
                            "experiment_template_config")
TARGET_DIR = os.path.join(os.path.dirname(__file__), "..", "experiment_config")


def sweep(dataset_name: str):
    h = HYPER[dataset_name]
    for shots in h["num_samples_per_class_range"]:
        for batch in h["batch_size_range"]:
            for inner_lr in h["init_inner_loop_learning_rate_range"]:
                for filters in h["num_filters"]:
                    for ways in h["num_classes_range"]:
                        yield dict(
                            dataset_name=dataset_name,
                            num_classes=ways,
                            samples_per_class=shots,
                            target_samples_per_class=h["target_samples_per_class"],
                            batch_size=batch,
                            train_update_steps=5,
                            val_update_steps=5,
                            init_inner_loop_learning_rate=inner_lr,
                            load_into_memory=True,
                            learnable_bn_gamma=True,
                            learnable_bn_beta=True,
                            conv_padding=True,
                            num_filters=filters,
                        )


def fill_template(text: str, values: dict) -> str:
    for key, item in values.items():
        text = text.replace(f"${key}$", str(item).lower())
    return text


def main() -> None:
    os.makedirs(TARGET_DIR, exist_ok=True)
    count = 0
    for template_file in sorted(os.listdir(TEMPLATE_DIR)):
        if not template_file.endswith(".json"):
            continue
        template_name = template_file.replace(".json", "")
        dataset_name = (
            "omniglot" if "omniglot" in template_file else "mini-imagenet"
        )
        with open(os.path.join(TEMPLATE_DIR, template_file)) as f:
            template = f.read()
        for seed in SEED_LIST:
            for values in sweep(dataset_name):
                values = dict(values)
                values["train_seed"] = seed
                values["val_seed"] = 0
                # Reference sweep-tag field order (hyper_config order).
                sweep_tag = "_".join(
                    str(values[k])
                    for k in (
                        "samples_per_class", "batch_size",
                        "init_inner_loop_learning_rate", "num_filters",
                        "num_classes",
                    )
                )
                run_name = f"{dataset_name}_{sweep_tag}_{seed}"
                values["experiment_name"] = run_name
                if template_name in BASELINE_TEMPLATES:
                    if not (
                        values["samples_per_class"] == BASELINE_POINT["shots"]
                        and values["num_classes"] == BASELINE_POINT["ways"]
                        and seed == BASELINE_POINT["seed"]
                    ):
                        continue
                    tag = BASELINE_TEMPLATES[template_name]
                    values[f"experiment_name_{tag}"] = (
                        f"{dataset_name}_{tag}_{sweep_tag}_{seed}"
                    )
                out_name = f"{template_name}-{run_name}.json"
                text = fill_template(template, values)
                if out_name in REFERENCE_HAND_EDITS:
                    config = json.loads(text)
                    config.update(REFERENCE_HAND_EDITS[out_name])
                    text = json.dumps(config, indent=2) + "\n"
                with open(os.path.join(TARGET_DIR, out_name), "w") as f:
                    f.write(text)
                count += 1
    print(f"{count} configs written to", os.path.abspath(TARGET_DIR))


if __name__ == "__main__":
    main()
