"""Experiment config generator: cartesian hyperparameter sweep over $var$
templates.

Capability parity with the reference's
``script_generation_tools/generate_configs.py`` (``:29-136``): for every
(seed x dataset x shots x ways x batch x inner-lr x filters) combination,
fill the matching ``experiment_template_config/*.json`` template by
``$var$`` substitution and write it to ``experiment_config/``, named
``<template>-<dataset>_<shots>_<batch>_<innerlr>_<filters>_<ways...>_<seed>
.json``.
"""

from __future__ import annotations

import os

SEED_LIST = [0, 1, 2]

# Per-dataset sweep ranges (the paper's experiment grid).
HYPER = {
    "omniglot": dict(
        num_samples_per_class_range=[1, 5],
        num_classes_range=[20, 5],
        batch_size_range=[8],
        init_inner_loop_learning_rate_range=[0.1],
        num_filters=[64],
        target_samples_per_class=1,
    ),
    "mini-imagenet": dict(
        num_samples_per_class_range=[1, 5],
        num_classes_range=[5],
        batch_size_range=[2],
        init_inner_loop_learning_rate_range=[0.01],
        num_filters=[48],
        target_samples_per_class=15,
    ),
}

TEMPLATE_DIR = os.path.join(os.path.dirname(__file__), "..",
                            "experiment_template_config")
TARGET_DIR = os.path.join(os.path.dirname(__file__), "..", "experiment_config")


def sweep(dataset_name: str):
    h = HYPER[dataset_name]
    for shots in h["num_samples_per_class_range"]:
        for ways in h["num_classes_range"]:
            for batch in h["batch_size_range"]:
                for inner_lr in h["init_inner_loop_learning_rate_range"]:
                    for filters in h["num_filters"]:
                        yield dict(
                            dataset_name=dataset_name,
                            num_classes=ways,
                            samples_per_class=shots,
                            target_samples_per_class=h["target_samples_per_class"],
                            batch_size=batch,
                            train_update_steps=5,
                            val_update_steps=5,
                            init_inner_loop_learning_rate=inner_lr,
                            load_into_memory=True,
                            learnable_bn_gamma=True,
                            learnable_bn_beta=True,
                            conv_padding=True,
                            num_filters=filters,
                        )


def fill_template(text: str, values: dict) -> str:
    for key, item in values.items():
        text = text.replace(f"${key}$", str(item).lower())
    return text


def main() -> None:
    os.makedirs(TARGET_DIR, exist_ok=True)
    for template_file in sorted(os.listdir(TEMPLATE_DIR)):
        if not template_file.endswith(".json"):
            continue
        dataset_name = (
            "omniglot" if "omniglot" in template_file else "mini-imagenet"
        )
        with open(os.path.join(TEMPLATE_DIR, template_file)) as f:
            template = f.read()
        for seed in SEED_LIST:
            for values in sweep(dataset_name):
                values = dict(values)
                values["train_seed"] = seed
                values["val_seed"] = 0
                sweep_tag = "_".join(
                    str(values[k])
                    for k in (
                        "num_classes", "samples_per_class", "batch_size",
                        "init_inner_loop_learning_rate", "num_filters",
                        "train_update_steps",
                    )
                )
                values["experiment_name"] = (
                    f"{dataset_name}_{sweep_tag}_{seed}"
                )
                out_name = "{}-{}.json".format(
                    template_file.replace(".json", ""),
                    values["experiment_name"],
                )
                with open(os.path.join(TARGET_DIR, out_name), "w") as f:
                    f.write(fill_template(template, values))
    print("configs written to", os.path.abspath(TARGET_DIR))


if __name__ == "__main__":
    main()
