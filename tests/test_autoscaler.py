"""Journal-backed fleet autoscaler (ISSUE 20): pure-policy decisions,
journal replay, and SIGKILL-at-every-boundary exactly-once resume.

Everything here is deterministic and in-process: the daemon is driven
against a stub fleet (healthz/metrics_text/resize) so decide semantics,
confirm streaks, cooldown, journal replay and kill-boundary resume are
provable without subprocess nondeterminism; daemon SIGKILLs are
simulated by aborting the pipeline at the exact
``faultinject.autoscaler_phase`` boundaries and rebuilding the daemon
over the same journal — the artifact state a real SIGKILL leaves. The
real-process topology (daemon CLI killed with SIGKILL under live
traffic) is proven by the chaos harness
(``tools/chaos_train.py --schedule autoscale``)."""

import pytest

from howtotrainyourmamlpytorch_tpu.serve.resilience import (
    autoscaler as asc,
)
from howtotrainyourmamlpytorch_tpu.serve.resilience.autoscaler import (
    AutoscalerConfig,
    AutoscalerDaemon,
    AutoscalerPolicy,
    Observation,
    decide,
    observe,
    replay_scale_journal,
)
from howtotrainyourmamlpytorch_tpu.serve.resilience.promotion import (
    PromotionJournal,
)


class StubScaleTarget:
    """A fleet front door as the autoscaler sees one: health, metrics,
    and an idempotent ``resize``. ``resize_calls`` records every issued
    target (re-issues are EXPECTED on resume — the exactly-once claim is
    about journal lifecycle and final size, not call counts)."""

    def __init__(self, size=1, queue=0.0, p99=10.0, degraded=False):
        self.size = size
        self.queue = queue
        self.p99 = p99
        self.degraded = degraded
        self.resize_calls: list[int] = []

    def healthz(self):
        return {
            "pool_size": self.size,
            "healthy_replicas": self.size,
            "degraded": self.degraded,
            "ready": self.size > 0,
        }

    def metrics_text(self):
        return "\n".join([
            f"maml_serve_pool_degraded {1.0 if self.degraded else 0.0}",
            'maml_serve_pool_request_latency_ms{quantile="0.99"} '
            f"{self.p99}",
            f"maml_serve_queue_depth {self.queue}",
        ])

    def resize(self, n):
        self.resize_calls.append(int(n))
        self.size = int(n)
        return {"pool_size": self.size}


def make_daemon(tmp_path, target, **policy_kw):
    defaults = dict(
        max_replicas=4, cooldown_s=0.0, confirm_samples=1,
        settle_timeout_s=2.0,
    )
    defaults.update(policy_kw)
    return AutoscalerDaemon(
        target,
        AutoscalerConfig(
            journal_path=str(tmp_path / "autoscale.jsonl"),
            poll_interval_s=0.01,
        ),
        AutoscalerPolicy(**defaults),
    )


def obs(**kw):
    defaults = dict(
        pool_size=2, healthy_replicas=2, degraded=False,
        queue_depth=0.0, p99_ms=100.0,
    )
    defaults.update(kw)
    return Observation(**defaults)


POLICY = AutoscalerPolicy(max_replicas=8)


# ---------------------------------------------------------------------------
# decide(): pure policy
# ---------------------------------------------------------------------------


def test_decide_scale_up_on_queue_per_replica():
    verdict = decide(obs(queue_depth=10.0), POLICY)  # 5.0/replica > 4.0
    assert verdict is not None
    target, reason = verdict
    assert target == 4  # step_up 2
    assert reason.startswith("scale_up")
    assert "queue/replica" in reason


def test_decide_scale_up_on_p99():
    target, reason = decide(obs(p99_ms=900.0), POLICY)
    assert target == 4
    assert "p99" in reason


def test_decide_memory_veto_blocks_scale_up():
    assert decide(obs(p99_ms=900.0, memory_frac=0.95), POLICY) is None
    # Below the veto line the same observation scales up.
    assert decide(obs(p99_ms=900.0, memory_frac=0.5), POLICY) is not None


def test_decide_hysteresis_holds_between_thresholds():
    # p99 between down (50) and up (250): neither direction moves.
    assert decide(obs(p99_ms=100.0), POLICY) is None


def test_decide_scale_down_when_idle():
    target, reason = decide(obs(pool_size=4, healthy_replicas=4,
                                p99_ms=10.0), POLICY)
    assert target == 3  # step_down 1
    assert reason.startswith("scale_down")


def test_decide_scale_down_blocked_while_degraded():
    assert decide(
        obs(pool_size=4, healthy_replicas=3, p99_ms=10.0, degraded=True),
        POLICY,
    ) is None


def test_decide_clamped_at_bounds():
    assert decide(obs(pool_size=8, healthy_replicas=8, p99_ms=900.0),
                  POLICY) is None  # already at max
    assert decide(obs(pool_size=1, healthy_replicas=1, p99_ms=10.0),
                  POLICY) is None  # already at min


# ---------------------------------------------------------------------------
# observe(): metrics fusion
# ---------------------------------------------------------------------------


def test_observe_fuses_health_and_metrics():
    target = StubScaleTarget(size=3, queue=6.0, p99=123.0)
    o = observe(target)
    assert o.pool_size == 3
    assert o.healthy_replicas == 3
    assert o.queue_depth == 6.0
    assert o.p99_ms == 123.0
    assert o.degraded is False
    assert o.memory_frac is None  # no heartbeat: never vetoes


def test_observe_missing_queue_reads_zero():
    """Pool front doors may not render the engine queue gauge; absent
    must read 0 (errs toward scale-down, the safe direction)."""

    class NoQueue(StubScaleTarget):
        def metrics_text(self):
            return ('maml_serve_pool_request_latency_ms{quantile="0.99"} '
                    f"{self.p99}")

    assert observe(NoQueue(size=2, p99=50.0)).queue_depth == 0.0


def test_observe_falls_back_to_engine_latency_prefix():
    class EngineOnly(StubScaleTarget):
        def metrics_text(self):
            return ('maml_serve_request_latency_ms{quantile="0.99"} '
                    f"{self.p99}")

    assert observe(EngineOnly(p99=77.0)).p99_ms == 77.0


# ---------------------------------------------------------------------------
# replay_scale_journal()
# ---------------------------------------------------------------------------


def test_replay_ignores_resumed_rows_for_phase():
    """A ``resumed`` audit row must not become a decision's last phase:
    a second crash right after a resume would otherwise look resolved."""
    rows = [
        {"t": 1.0, "phase": "decided", "decision_id": "scale-0001",
         "from_size": 1, "to_size": 3, "reason": "scale_up: test"},
        {"t": 2.0, "phase": "resumed", "decision_id": "scale-0001",
         "from_phase": "decided"},
    ]
    state = replay_scale_journal(rows)
    assert state["inflight"]["last_phase"] == "decided"
    assert state["inflight"]["to_size"] == 3


def test_replay_terminal_settled_and_newest_inflight():
    rows = [
        {"t": 1.0, "phase": "decided", "decision_id": "scale-0001",
         "from_size": 1, "to_size": 3, "reason": "r"},
        {"t": 2.0, "phase": "settled", "decision_id": "scale-0001",
         "to_size": 3, "healthy": True},
        {"t": 3.0, "phase": "decided", "decision_id": "scale-0002",
         "from_size": 3, "to_size": 2, "reason": "r"},
        {"t": 4.0, "phase": "applied", "decision_id": "scale-0002",
         "to_size": 2},
    ]
    state = replay_scale_journal(rows)
    assert state["terminal"] == {"scale-0001"}
    assert state["inflight"]["decision_id"] == "scale-0002"
    assert state["inflight"]["last_phase"] == "applied"


def test_replay_aborted_is_terminal():
    rows = [
        {"t": 1.0, "phase": "decided", "decision_id": "scale-0001",
         "from_size": 1, "to_size": 3, "reason": "r"},
        {"t": 2.0, "phase": "aborted", "decision_id": "scale-0001",
         "to_size": 3, "error": "boom"},
    ]
    state = replay_scale_journal(rows)
    assert state["terminal"] == {"scale-0001"}
    assert state["inflight"] is None


# ---------------------------------------------------------------------------
# run_once(): confirm streaks, cooldown, journal lifecycle
# ---------------------------------------------------------------------------


def test_run_once_journals_then_acts_then_settles(tmp_path):
    target = StubScaleTarget(size=1, p99=900.0)
    daemon = make_daemon(tmp_path, target)
    row = daemon.run_once()
    assert row["phase"] == "settled"
    assert row["healthy"] is True
    assert target.size == 3
    phases = [r["phase"]
              for r in PromotionJournal.load(daemon.journal.path)]
    assert phases == ["decided", "applied", "settled"]
    decided = PromotionJournal.load(daemon.journal.path)[0]
    assert decided["from_size"] == 1
    assert decided["to_size"] == 3
    assert decided["reason"].startswith("scale_up")


def test_confirm_streak_rides_out_one_sample_blips(tmp_path):
    target = StubScaleTarget(size=1, p99=900.0)
    daemon = make_daemon(tmp_path, target, confirm_samples=2)
    assert daemon.run_once() is None  # one sample: unconfirmed
    target.p99 = 100.0  # blip over: streak resets
    assert daemon.run_once() is None
    target.p99 = 900.0
    assert daemon.run_once() is None  # fresh streak, sample 1
    assert daemon.run_once()["phase"] == "settled"  # sample 2: confirmed
    assert target.size == 3


def test_cooldown_separates_decisions(tmp_path):
    target = StubScaleTarget(size=1, p99=900.0)
    daemon = make_daemon(tmp_path, target, cooldown_s=60.0)
    assert daemon.run_once()["phase"] == "settled"
    assert target.size == 3
    # Still breaching, but inside the cooldown window: hold.
    assert daemon.run_once() is None
    assert target.size == 3


# ---------------------------------------------------------------------------
# Crash-safe idempotency: journal replay at every kill boundary
# (mirrors tests/test_promotion.py's promotion-daemon contract)
# ---------------------------------------------------------------------------


class _Killed(BaseException):
    """In-process stand-in for SIGKILL: aborts the pipeline mid-phase;
    the daemon object is then discarded and a fresh one replays the
    journal — the exact artifact state a real SIGKILL leaves (the real
    signal path is proven by the autoscale chaos run's daemon
    subprocess)."""


def _kill_at_phase(monkeypatch, phase):
    def hook(p):
        if p == phase:
            raise _Killed(f"phase {p}")

    monkeypatch.setattr(asc.faultinject, "autoscaler_phase", hook)


def _disarm(monkeypatch):
    monkeypatch.setattr(
        asc.faultinject, "autoscaler_phase", lambda p: None
    )


@pytest.mark.parametrize(
    "kill_phase,resizes_before",
    [
        (asc.KILL_PRE_APPLY, 0),   # decided journaled, fleet untouched
        (asc.KILL_POST_APPLY, 1),  # fleet resized, applied row unwritten
        (asc.KILL_PRE_SETTLE, 1),  # applied journaled, settle unconfirmed
    ],
)
def test_journal_replay_after_kill_at_phase_boundary(
    tmp_path, monkeypatch, kill_phase, resizes_before
):
    """SIGKILL at each phase boundary, restart, resume exactly-once:
    the fleet lands at the journaled TARGET size (resize is idempotent
    on it, so re-issuing is safe on either side of the kill) and the
    decision settles exactly once."""
    target = StubScaleTarget(size=1, p99=900.0)
    daemon = make_daemon(tmp_path, target)
    _kill_at_phase(monkeypatch, kill_phase)
    with pytest.raises(_Killed):
        daemon.run_once()
    assert len(target.resize_calls) == resizes_before
    assert target.size == (1 if resizes_before == 0 else 3)

    _disarm(monkeypatch)
    daemon2 = make_daemon(tmp_path, target)
    row = daemon2.run_once()  # journal replay drives the resume
    assert row["phase"] == "settled"
    assert row["resumed"] is True
    assert target.size == 3
    rows = PromotionJournal.load(daemon2.journal.path)
    settled = [r for r in rows if r["phase"] == "settled"
               and r["decision_id"] == "scale-0001"]
    assert len(settled) == 1, "exactly one settle, ever"
    assert any(r["phase"] == "resumed" for r in rows)
    # Every re-issued resize asked for the SAME journaled target: no
    # delta was replayed, so no double-spawned replica is possible.
    assert set(target.resize_calls) == {3}

    # Idempotent forever after: a held fleet changes nothing (p99 parked
    # between the thresholds).
    target.p99 = 100.0
    assert daemon2.run_once() is None
    assert target.size == 3


def test_double_crash_after_resume_still_single_settle(
    tmp_path, monkeypatch
):
    """Kill pre-apply, resume, kill again post-apply (after the
    ``resumed`` row), restart: the decision still settles exactly once
    and the fleet holds the one journaled target."""
    target = StubScaleTarget(size=1, p99=900.0)
    daemon = make_daemon(tmp_path, target)
    _kill_at_phase(monkeypatch, asc.KILL_PRE_APPLY)
    with pytest.raises(_Killed):
        daemon.run_once()
    assert target.size == 1

    # Second incarnation dies mid-resume, after re-issuing the resize
    # but before the ``applied`` row lands.
    _kill_at_phase(monkeypatch, asc.KILL_POST_APPLY)
    daemon2 = make_daemon(tmp_path, target)
    with pytest.raises(_Killed):
        daemon2.run_once()
    assert target.size == 3

    _disarm(monkeypatch)
    daemon3 = make_daemon(tmp_path, target)
    row = daemon3.run_once()
    assert row["phase"] == "settled"
    rows = PromotionJournal.load(daemon3.journal.path)
    assert sum(1 for r in rows if r["phase"] == "settled") == 1
    assert sum(1 for r in rows if r["phase"] == "resumed") == 2
    assert set(target.resize_calls) == {3}


def test_resume_skips_duplicate_applied_row(tmp_path, monkeypatch):
    """Killed between ``applied`` and ``settled``: the resume re-issues
    the idempotent resize but does NOT journal a second ``applied`` row
    — the journal stays a truthful single-drive record."""
    target = StubScaleTarget(size=1, p99=900.0)
    daemon = make_daemon(tmp_path, target)
    _kill_at_phase(monkeypatch, asc.KILL_PRE_SETTLE)
    with pytest.raises(_Killed):
        daemon.run_once()

    _disarm(monkeypatch)
    daemon2 = make_daemon(tmp_path, target)
    assert daemon2.run_once()["phase"] == "settled"
    rows = PromotionJournal.load(daemon2.journal.path)
    assert sum(1 for r in rows if r["phase"] == "applied") == 1


def test_fresh_decisions_never_collide_with_journaled_ids(tmp_path):
    """Decision ids continue past the journaled history after a
    restart — a resumed daemon must not reuse ``scale-0001``."""
    target = StubScaleTarget(size=1, p99=900.0)
    daemon = make_daemon(tmp_path, target)
    assert daemon.run_once()["phase"] == "settled"

    target.p99 = 10.0  # now idle: the next decision scales down
    daemon2 = make_daemon(tmp_path, target)
    row = daemon2.run_once()
    assert row["phase"] == "settled"
    assert row["decision_id"] == "scale-0002"
    assert target.size == 2


def test_transport_failure_aborts_and_is_terminal(tmp_path):
    """A fleet that refuses the resize journals ``aborted`` (terminal):
    the next observation re-decides instead of wedging on the corpse."""

    class RefusingTarget(StubScaleTarget):
        def resize(self, n):
            raise asc.PromotionTransportError("fleet unreachable")

    target = RefusingTarget(size=1, p99=900.0)
    daemon = make_daemon(tmp_path, target)
    row = daemon.run_once()
    assert row["phase"] == "aborted"
    state = replay_scale_journal(
        PromotionJournal.load(daemon.journal.path)
    )
    assert state["inflight"] is None  # terminal: nothing to resume
