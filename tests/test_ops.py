"""Op-level parity tests against torch.nn.functional (CPU torch is the
ground truth for the reference's numerical semantics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from howtotrainyourmamlpytorch_tpu.ops import (
    accuracy,
    avg_pool2d,
    conv2d,
    cross_entropy,
    linear,
    max_pool2d,
)
from howtotrainyourmamlpytorch_tpu.ops.norm import (
    batch_norm,
    init_batch_norm_state,
    layer_norm,
)


def test_conv2d_matches_torch(rng):
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    w = rng.randn(5, 3, 3, 3).astype(np.float32)
    b = rng.randn(5).astype(np.float32)
    ours = conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), stride=1, padding=1)
    theirs = F.conv2d(torch.from_numpy(x), torch.from_numpy(w), torch.from_numpy(b),
                      stride=1, padding=1).numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-4)


def test_conv2d_stride2_no_padding(rng):
    x = rng.randn(2, 1, 28, 28).astype(np.float32)
    w = rng.randn(4, 1, 3, 3).astype(np.float32)
    ours = conv2d(jnp.asarray(x), jnp.asarray(w), None, stride=2, padding=0)
    theirs = F.conv2d(torch.from_numpy(x), torch.from_numpy(w), stride=2).numpy()
    assert ours.shape == theirs.shape
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-4)


def test_conv2d_nhwc_layout_matches_nchw(rng):
    """The NHWC layout-experiment switch (ops/conv.set_conv_layout) must be
    numerically equivalent — same NCHW external contract, different internal
    lowering (VERDICT r3 next #2)."""
    from howtotrainyourmamlpytorch_tpu.ops import conv as conv_ops

    x = rng.randn(2, 3, 9, 9).astype(np.float32)
    w = rng.randn(5, 3, 3, 3).astype(np.float32)
    b = rng.randn(5).astype(np.float32)
    ref = conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
                 stride=2, padding=1)
    conv_ops.set_conv_layout("NHWC")
    try:
        alt = conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
                     stride=2, padding=1)
    finally:
        conv_ops.set_conv_layout("NCHW")
    assert alt.shape == ref.shape
    np.testing.assert_allclose(np.asarray(alt), np.asarray(ref), atol=1e-4)
    with pytest.raises(ValueError):
        conv_ops.set_conv_layout("NCWH")


def test_linear_matches_torch(rng):
    x = rng.randn(4, 16).astype(np.float32)
    w = rng.randn(5, 16).astype(np.float32)
    b = rng.randn(5).astype(np.float32)
    ours = linear(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    theirs = F.linear(torch.from_numpy(x), torch.from_numpy(w), torch.from_numpy(b)).numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-5)


def test_batch_norm_matches_torch_training_mode(rng):
    """The reference always runs F.batch_norm(training=True)
    (meta_neural_network_architectures.py:246-247)."""
    x = rng.randn(6, 4, 5, 5).astype(np.float32)
    gamma = rng.rand(4).astype(np.float32) + 0.5
    beta = rng.randn(4).astype(np.float32)
    state = init_batch_norm_state(4)
    out, new_state = batch_norm(
        jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta), state, 0
    )
    rm = torch.zeros(4)
    rv = torch.ones(4)
    theirs = F.batch_norm(
        torch.from_numpy(x), rm, rv, torch.from_numpy(gamma), torch.from_numpy(beta),
        training=True, momentum=0.1, eps=1e-5,
    ).numpy()
    np.testing.assert_allclose(np.asarray(out), theirs, atol=1e-4)
    # Running stats updated with torch semantics (unbiased var).
    np.testing.assert_allclose(np.asarray(new_state.running_mean), rm.numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_state.running_var), rv.numpy(), atol=1e-4)


def test_batch_norm_per_step_rows(rng):
    """Per-step gamma/beta/statistics are indexed by the inner step
    (meta_neural_network_architectures.py:226-234); only the indexed row of
    the running stats is written."""
    x = rng.randn(6, 4, 5, 5).astype(np.float32)
    gamma = np.stack([np.full(4, 1.0), np.full(4, 2.0)]).astype(np.float32)
    beta = np.zeros((2, 4), np.float32)
    state = init_batch_norm_state(4, num_steps=2)
    out0, st0 = batch_norm(jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta), state, 0)
    out1, st1 = batch_norm(jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta), state, 1)
    np.testing.assert_allclose(np.asarray(out1), 2.0 * np.asarray(out0), atol=1e-4)
    # step 0 writes row 0 only; row 1 untouched
    assert not np.allclose(np.asarray(st0.running_mean[0]), 0.0)
    np.testing.assert_allclose(np.asarray(st0.running_mean[1]), 0.0)
    np.testing.assert_allclose(np.asarray(st1.running_mean[0]), 0.0)
    # out-of-range step clamps to last row
    out_clamped, _ = batch_norm(
        jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta), state, 7
    )
    np.testing.assert_allclose(np.asarray(out_clamped), np.asarray(out1), atol=1e-6)


def test_layer_norm_matches_torch(rng):
    x = rng.randn(3, 4, 5, 5).astype(np.float32)
    w = np.ones((4, 5, 5), np.float32)
    b = rng.randn(4, 5, 5).astype(np.float32)
    ours = layer_norm(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    theirs = F.layer_norm(
        torch.from_numpy(x), (4, 5, 5), torch.from_numpy(w), torch.from_numpy(b)
    ).numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-4)


def test_max_pool_matches_torch(rng):
    x = rng.randn(2, 3, 7, 7).astype(np.float32)  # odd size: floor mode
    ours = max_pool2d(jnp.asarray(x), 2, 2)
    theirs = F.max_pool2d(torch.from_numpy(x), 2, 2).numpy()
    assert ours.shape == theirs.shape == (2, 3, 3, 3)
    np.testing.assert_allclose(np.asarray(ours), theirs)


def test_avg_pool_matches_torch(rng):
    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    ours = avg_pool2d(jnp.asarray(x), 6)
    theirs = F.avg_pool2d(torch.from_numpy(x), 6).numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-5)


def test_cross_entropy_matches_torch(rng):
    logits = rng.randn(10, 5).astype(np.float32)
    labels = rng.randint(0, 5, 10)
    ours = cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    theirs = F.cross_entropy(torch.from_numpy(logits), torch.from_numpy(labels)).item()
    np.testing.assert_allclose(float(ours), theirs, atol=1e-5)


def test_accuracy():
    logits = jnp.asarray([[1.0, 0.0], [0.0, 1.0], [1.0, 0.0]])
    labels = jnp.asarray([0, 1, 1])
    assert float(accuracy(logits, labels)) == pytest.approx(2 / 3)
