"""Data pipeline tests: index build, split, deterministic episode sampling,
augmentation, loader batching and resume seed fast-forward (SURVEY §4 test
strategy — fixed-seed episode-sampler golden behavior)."""

import threading
import time
import json
import os

import numpy as np
import pytest
from PIL import Image

from howtotrainyourmamlpytorch_tpu.data import (
    FewShotLearningDataset,
    MetaLearningSystemDataLoader,
    rotate_image,
)
from howtotrainyourmamlpytorch_tpu.utils.parser_utils import Bunch


def make_dataset_dir(root, n_alphabets=4, n_chars=5, n_imgs=4, size=28):
    rng = np.random.RandomState(0)
    for a in range(n_alphabets):
        for c in range(n_chars):
            d = root / f"Alphabet{a}" / f"character{c:02d}"
            d.mkdir(parents=True, exist_ok=True)
            proto = rng.randint(0, 2, (size, size)) * 255
            for i in range(n_imgs):
                img = proto.copy()
                flip = rng.rand(size, size) < 0.05
                img[flip] = 255 - img[flip]
                Image.fromarray(img.astype(np.uint8), mode="L").convert("1").save(
                    str(d / f"{i}.png")
                )


def make_args(tmp_path, **overrides):
    defaults = dict(
        dataset_name="omniglot_mini",
        dataset_path=str(tmp_path / "omniglot_mini"),
        image_height=28,
        image_width=28,
        image_channels=1,
        reset_stored_filepaths=False,
        reverse_channels=False,
        labels_as_int=False,
        train_val_test_split=[0.5, 0.25, 0.25],
        indexes_of_folders_indicating_class=[-3, -2],
        num_target_samples=1,
        num_samples_per_class=1,
        num_classes_per_set=5,
        train_seed=1,
        val_seed=0,
        sets_are_pre_split=False,
        load_into_memory=False,
        num_of_gpus=1,
        batch_size=4,
        samples_per_iter=1,
        num_dataprovider_workers=2,
    )
    defaults.update(overrides)
    return Bunch(defaults)


@pytest.fixture
def dataset_env(tmp_path, monkeypatch):
    make_dataset_dir(tmp_path / "omniglot_mini")
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    return tmp_path


def test_index_cache_and_split(dataset_env):
    args = make_args(dataset_env)
    ds = FewShotLearningDataset(args)
    # Index JSONs cached with the reference's filenames (data.py:244-248).
    assert (dataset_env / "omniglot_mini.json").exists()
    assert (dataset_env / "map_to_label_name_omniglot_mini.json").exists()
    assert (dataset_env / "label_name_to_map_omniglot_mini.json").exists()
    # 20 classes ratio-split 10/5/5.
    assert len(ds.datasets["train"]) == 10
    assert len(ds.datasets["val"]) == 5
    assert len(ds.datasets["test"]) == 5
    # Rebuilding from the cache gives the identical split (seeded shuffle).
    ds2 = FewShotLearningDataset(args)
    assert list(ds2.datasets["train"]) == list(ds.datasets["train"])


def test_episode_determinism_and_shapes(dataset_env):
    args = make_args(dataset_env)
    ds = FewShotLearningDataset(args)
    xs, xt, ys, yt, seed = ds.get_set("train", seed=123, augment_images=True)
    assert xs.shape == (5, 1, 1, 28, 28)
    assert xt.shape == (5, 1, 1, 28, 28)
    assert ys.shape == (5, 1) and yt.shape == (5, 1)
    # Each episode relabels classes 0..N-1.
    assert sorted(ys[:, 0].tolist()) == [0, 1, 2, 3, 4]
    # Same seed -> bitwise identical episode; different seed -> different.
    xs2, *_ = ds.get_set("train", seed=123, augment_images=True)
    np.testing.assert_array_equal(xs, xs2)
    xs3, *_ = ds.get_set("train", seed=124, augment_images=True)
    assert not np.array_equal(xs, xs3)


def test_val_and_test_seeds_fixed(dataset_env):
    """Val/test use the derived val seed; test == val (data.py:136-142)."""
    args = make_args(dataset_env)
    ds = FewShotLearningDataset(args)
    assert ds.init_seed["test"] == ds.init_seed["val"]
    assert ds.init_seed["train"] != ds.init_seed["val"]


def test_rotation_augment_applied_only_in_train(dataset_env):
    args = make_args(dataset_env)
    ds = FewShotLearningDataset(args)
    # Find a seed whose first episode class draws k != 0.
    for seed in range(50):
        rng = np.random.RandomState(seed)
        classes = rng.choice(
            list(ds.dataset_size_dict["train"].keys()), size=5, replace=False
        )
        rng.shuffle(classes)
        if rng.randint(0, 4, 5)[0] != 0:
            break
    plain, *_ = ds.get_set("train", seed=seed, augment_images=False)
    rotated, *_ = ds.get_set("train", seed=seed, augment_images=True)
    assert not np.array_equal(plain, rotated)


def test_rotate_image_quarter_turns():
    im = np.arange(12, dtype=np.float32).reshape(3, 4, 1)
    r1 = rotate_image(im, 1)
    assert r1.shape == (4, 3, 1)
    np.testing.assert_array_equal(rotate_image(im, 4), im)


def test_loader_batching_and_resume(dataset_env):
    args = make_args(dataset_env)
    loader = MetaLearningSystemDataLoader(args, current_iter=0)
    batches = list(loader.get_train_batches(total_batches=3, augment_images=False))
    assert len(batches) == 3
    xs, xt, ys, yt, seeds = batches[0]
    assert xs.shape == (4, 5, 1, 1, 28, 28)
    assert seeds.shape == (4,)

    # A loader resumed at iteration 2 reproduces batch index 2 exactly
    # (data.py:583-588 seed fast-forward).
    resumed = MetaLearningSystemDataLoader(args, current_iter=2)
    resumed_batches = list(
        resumed.get_train_batches(total_batches=1, augment_images=False)
    )
    np.testing.assert_array_equal(batches[2][0], resumed_batches[0][0])
    np.testing.assert_array_equal(batches[2][4], resumed_batches[0][4])


def test_loader_mixes_replay_manifest_into_train_stream(dataset_env, tmp_path):
    """Hard-episode feedback edge (ISSUE 13): with a replay manifest
    configured, every Nth TRAIN episode slot draws a mined seed (cycled,
    deterministic — the yielded batch's seed column proves it), the other
    slots are untouched, and val batches never replay."""
    import json as json_module

    manifest = tmp_path / "replay_manifest.json"
    manifest.write_text(json_module.dumps({
        "schema": 1, "source": "test",
        "episodes": [{"seed": 777, "margin": 0.01},
                     {"seed": 888, "margin": 0.02},
                     {"seed": 999, "margin": 0.03}],
    }))
    plain_args = make_args(dataset_env)
    plain = MetaLearningSystemDataLoader(plain_args, current_iter=0)
    plain_batches = list(
        plain.get_train_batches(total_batches=2, augment_images=False)
    )

    args = make_args(
        dataset_env, replay_manifest=str(manifest), replay_every=4
    )
    loader = MetaLearningSystemDataLoader(args, current_iter=0)
    assert loader.replay_seeds == (777, 888, 999)
    batches = list(
        loader.get_train_batches(total_batches=2, augment_images=False)
    )
    seeds = np.concatenate([b[4] for b in batches])
    plain_seeds = np.concatenate([b[4] for b in plain_batches])
    # Slots 3 and 7 (1-based every-4th) replay mined seeds, cycled.
    assert seeds[3] == 777 and seeds[7] == 888
    untouched = [i for i in range(len(seeds)) if (i + 1) % 4]
    np.testing.assert_array_equal(seeds[untouched], plain_seeds[untouched])
    # The replayed episode is the mined seed's episode, bit-exact.
    ds = FewShotLearningDataset(make_args(dataset_env))
    xs_777, *_ = ds.get_set("train", seed=777, augment_images=False)
    np.testing.assert_array_equal(batches[0][0][3], xs_777)
    # Val stream: no replay, identical to the plain loader's.
    val = list(loader.get_val_batches(total_batches=1))
    plain_val = list(plain.get_val_batches(total_batches=1))
    np.testing.assert_array_equal(val[0][4], plain_val[0][4])
    # Resume alignment: slots are keyed to the GLOBAL episode index, so a
    # loader resumed mid-run reproduces the uninterrupted run's replay
    # stream exactly — the pinned resume bit-exactness contract holds
    # with a manifest active.
    uninterrupted = MetaLearningSystemDataLoader(args, current_iter=0)
    full = list(
        uninterrupted.get_train_batches(total_batches=3, augment_images=False)
    )
    resumed = MetaLearningSystemDataLoader(args, current_iter=2)
    resumed_batches = list(
        resumed.get_train_batches(total_batches=1, augment_images=False)
    )
    # Global slot 11 rides cycle pointer 2 (seed 999) in BOTH runs — a
    # within-call pointer would restart at 777 on resume.
    assert resumed_batches[0][4][3] == 999 and full[2][4][3] == 999
    np.testing.assert_array_equal(full[2][4], resumed_batches[0][4])
    np.testing.assert_array_equal(full[2][0], resumed_batches[0][0])


def test_loader_rejects_bad_replay_manifest(dataset_env, tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text('{"schema": 99, "episodes": [{"seed": 1}]}')
    with pytest.raises(ValueError, match="newer"):
        MetaLearningSystemDataLoader(
            make_args(dataset_env, replay_manifest=str(bad)),
            current_iter=0,
        )
    empty = tmp_path / "empty.json"
    empty.write_text('{"schema": 1, "episodes": []}')
    with pytest.raises(ValueError, match="no episodes"):
        MetaLearningSystemDataLoader(
            make_args(dataset_env, replay_manifest=str(empty)),
            current_iter=0,
        )


def test_loader_val_batches_repeatable(dataset_env):
    args = make_args(dataset_env)
    loader = MetaLearningSystemDataLoader(args, current_iter=0)
    a = list(loader.get_val_batches(total_batches=2))
    b = list(loader.get_val_batches(total_batches=2))
    np.testing.assert_array_equal(a[0][0], b[0][0])
    np.testing.assert_array_equal(a[1][0], b[1][0])


def test_ram_preload_matches_disk(dataset_env):
    args = make_args(dataset_env)
    disk = FewShotLearningDataset(args)
    ram = FewShotLearningDataset(make_args(dataset_env, load_into_memory=True))
    e_disk = disk.get_set("val", seed=7, augment_images=False)
    e_ram = ram.get_set("val", seed=7, augment_images=False)
    np.testing.assert_allclose(e_disk[0], e_ram[0])
    np.testing.assert_array_equal(e_disk[2], e_ram[2])

def test_interleaved_val_epoch_does_not_poison_train_stream(dataset_env):
    """Regression: the experiment loop holds ONE long-lived train generator
    and runs val epochs inside it (experiment_builder.py:402-449, mirroring
    the reference's loop at experiment_builder.py:308-343). The thread-pool
    synthesis shares a single dataset object, so a val epoch's
    ``switch_set("val")``/``set_augmentation(False)`` must NOT leak into
    train batches produced afterwards — every post-val train batch must
    still be an augmented train-split episode with the train seed stream."""
    args = make_args(dataset_env)
    loader = MetaLearningSystemDataLoader(args, current_iter=0)

    train_gen = loader.get_train_batches(total_batches=8, augment_images=True)
    got = [next(train_gen)]
    # Interleave a full val epoch (evaluation never augments).
    val_batches = list(loader.get_val_batches(total_batches=2,
                                              augment_images=False))
    assert len(val_batches) == 2
    got.extend(train_gen)  # drain the remaining 7 train batches
    assert len(got) == 8

    # Expected stream, synthesized directly with explicit train arguments.
    ds = FewShotLearningDataset(args)
    for b, (xs, xt, ys, yt, seeds) in enumerate(got):
        for i in range(args.batch_size):
            idx = b * loader.global_batch + i
            seed = ds.init_seed["train"] + idx
            assert seeds[i] == seed
            exp_xs, _exp_xt, exp_ys, _e, _s = ds.get_set(
                "train", seed=seed, augment_images=True
            )
            np.testing.assert_array_equal(xs[i], exp_xs)
            np.testing.assert_array_equal(ys[i], exp_ys)


def test_loader_sentinel_survives_full_prefetch_queue(dataset_env):
    """The end-of-epoch sentinel must be delivered even when the consumer
    lags and the bounded prefetch queue is full when the producer finishes
    (a put_nowait here once dropped it and stranded the consumer forever)."""
    args = make_args(dataset_env)
    loader = MetaLearningSystemDataLoader(args, current_iter=0)
    gen = loader.get_train_batches(total_batches=4, augment_images=False)
    first = next(gen)
    assert first[0].shape[0] == 4
    time.sleep(0.5)  # let the producer finish all batches + fill the queue
    rest = list(gen)  # must terminate, not hang
    assert len(rest) == 3


def test_loader_propagates_synthesis_errors(dataset_env):
    """A mid-epoch synthesis failure re-raises in the consumer instead of
    silently truncating the epoch."""
    args = make_args(dataset_env)
    loader = MetaLearningSystemDataLoader(args, current_iter=0)

    def boom(*a, **k):
        raise ValueError("corrupt image")

    loader.dataset.get_set = boom
    with pytest.raises(ValueError, match="corrupt image"):
        list(loader.get_train_batches(total_batches=2, augment_images=False))


def test_defer_augment_ships_raw_pixels_plus_rotation_payload(dataset_env):
    """--device_augment episodes: raw (unrotated) pixels + the per-class
    quarter-turn payload, with the episode RNG stream (class/sample/k
    selection) bit-identical to the host-augmented mode. Applying the host
    rotation to the raw pixels with the shipped ks reproduces the
    host-augmented episode exactly — the transform moved, nothing else."""
    args = make_args(dataset_env)
    args_dev = make_args(dataset_env, device_augment=True)
    ds_host = FewShotLearningDataset(args)
    ds_dev = FewShotLearningDataset(args_dev)
    assert ds_dev.defer_augment and not ds_host.defer_augment

    for seed in (123, 321):
        host = ds_host.get_set("train", seed=seed, augment_images=True)
        raw = ds_dev.get_set("train", seed=seed, augment_images=True)
        assert len(host) == 5 and len(raw) == 6
        xs_raw, xt_raw, ys, yt, _seed, ks = raw
        assert ks.shape == (args.num_classes_per_set,)
        np.testing.assert_array_equal(ys, host[2])
        # Raw pixels == the unaugmented episode (same selection stream).
        plain = ds_host.get_set("train", seed=seed, augment_images=False)
        np.testing.assert_array_equal(xs_raw, plain[0])
        # Host-rotating the raw pixels with the shipped ks == host episode.
        for raw_x, host_x in ((xs_raw, host[0]), (xt_raw, host[1])):
            rotated = np.stack([
                np.stack([
                    np.transpose(
                        rotate_image(np.transpose(im, (1, 2, 0)), int(k)),
                        (2, 0, 1),
                    )
                    for im in cls
                ])
                for cls, k in zip(raw_x, ks)
            ])
            np.testing.assert_array_equal(rotated, host_x)
    # Eval episodes apply no augmentation -> plain 5-tuple, no payload.
    assert len(ds_dev.get_set("val", seed=7, augment_images=False)) == 5


def test_loader_collates_defer_augment_payload(dataset_env):
    args = make_args(dataset_env, device_augment=True)
    loader = MetaLearningSystemDataLoader(args, current_iter=0)
    batches = list(loader.get_train_batches(total_batches=2,
                                            augment_images=True))
    for batch in batches:
        assert len(batch) == 6
        assert batch[5].shape == (args.batch_size, args.num_classes_per_set)
        assert batch[5].dtype == np.int32
    # Val batches stay 5-element (no augmentation, no payload).
    val = list(loader.get_val_batches(total_batches=1, augment_images=False))
    assert len(val[0]) == 5


def test_builder_rollback_shuts_down_stager_and_releases_buffers(dataset_env):
    """Satellite (ISSUE 7): abandoning a mid-epoch iteration through the
    builder's ROLLBACK path must close the device-prefetch stager — thread
    stopped, staged device buffers deleted — before the replay builds its
    replacement. An abandoned stager would otherwise pin up to ``depth``
    dispatch groups of device memory for the rest of the run."""
    import pytest

    from howtotrainyourmamlpytorch_tpu.experiment_builder import (
        ExperimentBuilder,
    )
    from howtotrainyourmamlpytorch_tpu.utils import faultinject
    from test_faultinject import _builder, _exp_args

    tmp = dataset_env
    stagers = []
    original = ExperimentBuilder._make_stager

    def spying(self, batches):
        stager = original(self, batches)
        stagers.append(stager)
        return stager

    ExperimentBuilder._make_stager = spying
    # Float wire: NaN poisoning rides the real data path (uint8 clips it).
    faultinject.activate(faultinject.FaultPlan(nan_at_iter=1))
    try:
        builder = _builder(
            _exp_args(tmp, "exp_stager_rollback", on_nonfinite="rollback",
                      total_epochs_before_pause=1)
        )
        with pytest.raises(SystemExit):
            builder.run_experiment()
    finally:
        ExperimentBuilder._make_stager = original
        fault_events = list(faultinject.events)
        faultinject.reset()

    # The poisoned first pass was abandoned by the rollback; its stager
    # (and the replay's, finished normally) must both be fully closed.
    assert len(stagers) >= 2, "rollback did not re-enter the train loop"
    assert fault_events and fault_events[0] == "nan:1"
    for stager in stagers:
        assert stager.closed
        assert not stager._thread.is_alive()
        assert stager._buffer == []
    assert not any(
        t.name == "device-prefetch-stager" and t.is_alive()
        for t in threading.enumerate()
    )


def test_process_backend_matches_thread_backend(dataset_env):
    """The forked-worker synthesis backend (reference DataLoader-worker
    model) yields bit-identical batches to the thread backend."""
    args = make_args(dataset_env)
    t = MetaLearningSystemDataLoader(args, current_iter=0)
    thread_batches = list(t.get_train_batches(total_batches=3,
                                              augment_images=True))
    args_p = make_args(dataset_env)
    args_p.dataprovider_backend = "process"
    args_p.num_dataprovider_workers = 2
    p = MetaLearningSystemDataLoader(args_p, current_iter=0)
    try:
        proc_batches = list(p.get_train_batches(total_batches=3,
                                                augment_images=True))
        assert len(proc_batches) == len(thread_batches) == 3
        for tb, pb in zip(thread_batches, proc_batches):
            for a, b in zip(tb, pb):
                np.testing.assert_array_equal(a, b)
    finally:
        p._pool.shutdown(wait=True)
