"""Pallas fused bn+leaky_relu kernel vs the pure-lax reference
(interpret mode on CPU; the same kernels compile for TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.ops.norm import (
    BatchNormState,
    batch_norm,
    init_batch_norm_state,
)
from howtotrainyourmamlpytorch_tpu.ops.pallas_fused_norm import (
    fused_bn_leaky_relu,
)


def _reference(x, gamma, beta, eps=1e-5, slope=0.01):
    state = init_batch_norm_state(x.shape[1])
    out, _ = batch_norm(x, gamma, beta, state, 0, eps=eps)
    return jax.nn.leaky_relu(out, negative_slope=slope)


@pytest.mark.parametrize("shape", [(10, 64, 14, 14), (3, 5, 4, 4)])
def test_forward_matches_reference(shape, rng):
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    gamma = jnp.asarray(rng.rand(shape[1]) + 0.5, jnp.float32)
    beta = jnp.asarray(rng.randn(shape[1]), jnp.float32)
    y, mean, var = fused_bn_leaky_relu(x, gamma, beta, 1e-5, 0.01, True)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(_reference(x, gamma, beta)), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(mean), np.asarray(jnp.mean(x, axis=(0, 2, 3))), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(var), np.asarray(jnp.var(x, axis=(0, 2, 3))), rtol=1e-4, atol=1e-5
    )


def test_gradients_match_reference(rng):
    shape = (4, 5, 6, 6)
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    gamma = jnp.asarray(rng.rand(shape[1]) + 0.5, jnp.float32)
    beta = jnp.asarray(rng.randn(shape[1]), jnp.float32)
    t = jnp.asarray(rng.randn(*shape), jnp.float32)

    def loss_fused(x, gamma, beta):
        y, _, _ = fused_bn_leaky_relu(x, gamma, beta, 1e-5, 0.01, True)
        return jnp.sum(y * t)

    def loss_ref(x, gamma, beta):
        return jnp.sum(_reference(x, gamma, beta) * t)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, gamma, beta)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b, name in zip(gf, gr, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4, err_msg=name
        )


def test_bf16_input_fp32_stats(rng):
    x = jnp.asarray(rng.randn(6, 8, 5, 5), jnp.bfloat16)
    gamma = jnp.ones((8,), jnp.float32)
    beta = jnp.zeros((8,), jnp.float32)
    y, mean, var = fused_bn_leaky_relu(x, gamma, beta, 1e-5, 0.01, True)
    assert y.dtype == jnp.bfloat16
    assert mean.dtype == jnp.float32 and var.dtype == jnp.float32
    ref = _reference(x.astype(jnp.float32), gamma, beta)
    np.testing.assert_allclose(np.asarray(y, np.float32), np.asarray(ref),
                               rtol=0.05, atol=0.05)


def _make_maml(fused, second_order=False):
    from howtotrainyourmamlpytorch_tpu.models import (
        BackboneConfig,
        MAMLConfig,
        MAMLFewShotLearner,
    )

    cfg = MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2, num_filters=4, per_step_bn_statistics=True,
            num_steps=2, num_classes=5, image_height=8, image_width=8,
            use_pallas_fused_norm=fused,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        second_order=second_order,
    )
    learner = MAMLFewShotLearner(cfg)
    return learner, learner.init_state(jax.random.PRNGKey(5))


def _episode_batch(rng):
    xs = rng.rand(2, 5, 1, 1, 8, 8).astype(np.float32)
    ys = np.tile(np.arange(5)[None, :, None], (2, 1, 1))
    return (xs, xs.copy(), ys, ys.copy())


def test_fused_maml_eval_matches_lax(rng):
    """MAML evaluation — the path that enables the fused kernel (one level
    of reverse AD: the inner value_and_grad) — matches the lax path."""
    batch = _episode_batch(rng)
    la, sa = _make_maml(False)
    lb, sb = _make_maml(True)
    _, ma, logits_a = la.run_validation_iter(sa, batch)
    _, mb, logits_b = lb.run_validation_iter(sb, batch)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b),
                               rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("second_order", [False, True])
def test_fused_config_trains_like_lax(rng, second_order):
    """With use_pallas_fused_norm=True, MAML train steps auto-select the lax
    path (the outer meta-gradient cannot differentiate the custom_vjp a
    second time), so training must both run and match the lax config
    exactly."""
    batch = _episode_batch(rng)
    la, sa = _make_maml(False, second_order)
    lb, sb = _make_maml(True, second_order)
    for _ in range(2):
        sa, ma = la.run_train_iter(sa, batch, epoch=20)
        sb, mb = lb.run_train_iter(sb, batch, epoch=20)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]),
                               rtol=1e-6, atol=0)
    for a, b in zip(jax.tree.leaves(sa.theta), jax.tree.leaves(sb.theta)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
