"""Unit tests for the graftlint rule set: one positive (rule fires) and one
negative (rule stays quiet) case per rule, plus the suppression contract.

Violating code lives in source *strings* handed to ``lint_source`` — the
test file itself must stay clean, since tier-1 lints ``tests/`` too
(``test_graftlint_clean.py``).
"""

import textwrap

from tools.graftlint import RULES, lint_source, lint_sources


def rules_of(src: str, path: str = "mod.py") -> set:
    return {v.rule for v in lint_source(textwrap.dedent(src), path)}


def violations_of(src: str, path: str = "mod.py"):
    return lint_source(textwrap.dedent(src), path)


def test_rule_registry_has_at_least_eight_rules():
    assert len(RULES) >= 8


# ---------------------------------------------------------------------------
# prng-reuse
# ---------------------------------------------------------------------------


def test_prng_reuse_positive_double_consume():
    src = """
    import jax

    def sample(key):
        a = jax.random.normal(key, (3,))
        b = jax.random.uniform(key, (3,))
        return a + b
    """
    assert "prng-reuse" in rules_of(src)


def test_prng_reuse_positive_consume_in_loop():
    src = """
    import jax

    def sample(key, n):
        out = []
        for _ in range(n):
            out.append(jax.random.normal(key, (3,)))
        return out
    """
    assert "prng-reuse" in rules_of(src)


def test_prng_reuse_negative_split_between_uses():
    src = """
    import jax

    def sample(key):
        key, sub = jax.random.split(key)
        a = jax.random.normal(sub, (3,))
        key, sub = jax.random.split(key)
        b = jax.random.uniform(sub, (3,))
        return a + b

    def loop(key, n):
        out = []
        for k in jax.random.split(key, n):
            out.append(jax.random.normal(k, (3,)))
        return out
    """
    assert "prng-reuse" not in rules_of(src)


# ---------------------------------------------------------------------------
# host-numpy-in-trace
# ---------------------------------------------------------------------------


def test_host_numpy_positive_np_on_traced_value():
    src = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def step(x):
        y = jnp.mean(x)
        return np.asarray(y) * 2
    """
    assert "host-numpy-in-trace" in rules_of(src)


def test_host_numpy_negative_np_on_host_constants():
    src = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    def importance(n):
        return np.ones(n, np.float32) / n

    @jax.jit
    def step(x):
        return jnp.mean(x)
    """
    assert "host-numpy-in-trace" not in rules_of(src)


def test_host_numpy_negative_treemap_callback_is_not_traced():
    # jax.tree.map callbacks run host-side eagerly outside a trace —
    # np inside them is idiomatic (e.g. asserting pytrees in tests).
    src = """
    import jax
    import numpy as np

    def check(before, after):
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b),
                     before, after)
    """
    assert "host-numpy-in-trace" not in rules_of(src)


# ---------------------------------------------------------------------------
# tracer-branch
# ---------------------------------------------------------------------------


def test_tracer_branch_positive_if_on_device_value():
    src = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        loss = jnp.mean(x)
        if loss > 0:
            return loss
        return -loss
    """
    assert "tracer-branch" in rules_of(src)


def test_tracer_branch_negative_static_config_branch():
    src = """
    import jax
    import jax.numpy as jnp

    def make(second_order):
        @jax.jit
        def step(x):
            if second_order:
                return jnp.mean(x)
            if x.ndim == 2:
                return jnp.sum(x)
            return jnp.sum(jax.lax.stop_gradient(x))
        return step
    """
    assert "tracer-branch" not in rules_of(src)


# ---------------------------------------------------------------------------
# jit-static-config
# ---------------------------------------------------------------------------


def test_jit_static_config_positive_config_arg_not_static():
    src = """
    import jax
    import jax.numpy as jnp

    def step(x, cfg):
        return jnp.mean(x) * cfg["scale"]

    compiled = jax.jit(step)
    """
    assert "jit-static-config" in rules_of(src)


def test_jit_static_config_negative_with_static_argnames():
    src = """
    import functools
    import jax
    import jax.numpy as jnp

    def step(x, mode):
        return jnp.mean(x)

    compiled = jax.jit(step, static_argnames=("mode",))

    @functools.partial(jax.jit, static_argnames=("cfg",))
    def step2(x, cfg):
        return jnp.mean(x)

    bound = jax.jit(functools.partial(step, mode="fast"))
    """
    assert "jit-static-config" not in rules_of(src)


# ---------------------------------------------------------------------------
# missing-donate
# ---------------------------------------------------------------------------


def test_missing_donate_positive_train_step_without_donation():
    src = """
    import jax
    import jax.numpy as jnp

    def _train_step(state, batch):
        return state, jnp.mean(batch)

    train_step = jax.jit(_train_step)
    """
    assert "missing-donate" in rules_of(src)


def test_missing_donate_negative_donated_or_eval():
    src = """
    import jax
    import jax.numpy as jnp

    def _train_step(state, batch):
        return state, jnp.mean(batch)

    def _evaluation_step(state, batch):
        return jnp.mean(batch)

    train_step = jax.jit(_train_step, donate_argnums=(0,))
    eval_step = jax.jit(_evaluation_step)
    """
    assert "missing-donate" not in rules_of(src)


# ---------------------------------------------------------------------------
# dead-flag
# ---------------------------------------------------------------------------

_PARSER_SRC = """
import argparse

def get_parser():
    parser = argparse.ArgumentParser()
    add = parser.add_argument
    add("--batch_size", type=int, default=32)
    add("--ancient_knob", type=int, default=3)
    return parser
"""

_CONSUMER_SRC = """
def build(args):
    return args.batch_size * 2
"""

#: The dead-flag rule requires reads from several distinct modules before
#: it trusts the scan as complete (partial-scan guard) — give it a
#: plausible consumer spread.
_CONSUMER_MODULES = {
    f"pkg/consumer_{i}.py": _CONSUMER_SRC for i in range(4)
}


def test_dead_flag_positive_unread_flag():
    violations = lint_sources(
        {"pkg/utils/parser_utils.py": _PARSER_SRC, **_CONSUMER_MODULES}
    )
    dead = [v for v in violations if v.rule == "dead-flag"]
    assert len(dead) == 1
    assert "ancient_knob" in dead[0].message


def test_dead_flag_negative_flag_read_via_getattr_string():
    consumer = _CONSUMER_SRC + """
def build2(args):
    return getattr(args, "ancient_knob", 3)
"""
    violations = lint_sources(
        {
            "pkg/utils/parser_utils.py": _PARSER_SRC,
            "pkg/consumer.py": consumer,
            **_CONSUMER_MODULES,
        }
    )
    assert not [v for v in violations if v.rule == "dead-flag"]


def test_dead_flag_only_fires_on_parser_utils_module():
    # The same add() calls in a random module are not a flag surface.
    assert "dead-flag" not in rules_of(_PARSER_SRC, path="pkg/other.py")


def test_dead_flag_stays_quiet_on_partial_scans():
    # "dead" is relative to the scanned set: linting parser_utils.py alone
    # (or a changed-files subset missing the consumer spread) must not
    # flood every live flag as dead — the rule requires reads from several
    # distinct modules before trusting the scan.
    assert "dead-flag" not in rules_of(_PARSER_SRC, path="pkg/utils/parser_utils.py")
    violations = lint_sources(
        {
            "pkg/utils/parser_utils.py": _PARSER_SRC,
            "pkg/consumer.py": _CONSUMER_SRC,
        }
    )
    assert not [v for v in violations if v.rule == "dead-flag"]


# ---------------------------------------------------------------------------
# device-op-in-data-path
# ---------------------------------------------------------------------------


def test_device_op_positive_jnp_in_loader():
    src = """
    import jax.numpy as jnp

    def collate(episodes):
        return jnp.stack(episodes)
    """
    assert "device-op-in-data-path" in rules_of(src, path="pkg/data/loader.py")


def test_device_op_negative_numpy_loader_and_non_data_module():
    numpy_loader = """
    import numpy as np

    def collate(episodes):
        return np.stack(episodes)
    """
    assert "device-op-in-data-path" not in rules_of(
        numpy_loader, path="pkg/data/loader.py"
    )
    jax_model = """
    import jax.numpy as jnp

    def forward(x):
        return jnp.mean(x)
    """
    assert "device-op-in-data-path" not in rules_of(
        jax_model, path="pkg/models/net.py"
    )


def test_device_op_allowlists_the_prefetch_stager_only():
    """The device-prefetch stager is the ONE sanctioned jax import in the
    data path (its job is the async device_put) — allowlisted in the rule
    itself, not via an inline suppression. Any other data/ module using
    jax still flags."""
    stager_src = """
    import jax

    def stage(batch):
        return jax.device_put(batch)
    """
    assert "device-op-in-data-path" not in rules_of(
        stager_src, path="pkg/data/device_prefetch.py"
    )
    # The same source anywhere else in data/ is still a violation —
    # including a BRAND-NEW data/ module (directory-scoped, not a file
    # list: coverage does not wait for someone to extend an enum).
    assert "device-op-in-data-path" in rules_of(
        stager_src, path="pkg/data/dataset.py"
    )
    assert "device-op-in-data-path" in rules_of(
        stager_src, path="pkg/data/fast_synth.py"
    )
    assert "device-op-in-data-path" in rules_of(
        stager_src, path="pkg/data/brand_new_module.py"
    )
    assert "device-op-in-data-path" in rules_of(
        stager_src, path="data/loader.py"  # bare relative path
    )


# ---------------------------------------------------------------------------
# traced-mutation
# ---------------------------------------------------------------------------


def test_traced_mutation_positive_capture_append_and_self_write():
    src = """
    import jax
    import jax.numpy as jnp

    class Learner:
        def __init__(self):
            self.history = []
            self.step = jax.jit(self._step)

        def _step(self, x):
            y = jnp.mean(x)
            self.history.append(y)
            self.last = y
            return y
    """
    found = rules_of(src)
    assert "traced-mutation" in found


def test_traced_mutation_negative_local_accumulation():
    src = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        metrics = {}
        metrics["loss"] = jnp.mean(x)
        parts = []
        parts.append(metrics["loss"])
        return metrics, parts
    """
    assert "traced-mutation" not in rules_of(src)


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

_VIOLATING_LINE = (
    "import jax\n"
    "\n"
    "def sample(key):\n"
    "    a = jax.random.normal(key, (3,))\n"
    "    b = jax.random.uniform(key, (3,)){}\n"
    "    return a + b\n"
)


def test_suppression_with_reason_silences_the_rule():
    src = _VIOLATING_LINE.format(
        "  # graftlint: disable=prng-reuse -- intentional: same-draw test"
    )
    assert rules_of(src) == set()


def test_suppression_without_reason_is_a_violation():
    src = _VIOLATING_LINE.format("  # graftlint: disable=prng-reuse")
    found = rules_of(src)
    assert "bad-suppression" in found


def test_suppression_of_unknown_rule_is_a_violation():
    src = _VIOLATING_LINE.format(
        "  # graftlint: disable=no-such-rule -- reason here"
    )
    found = rules_of(src)
    assert "bad-suppression" in found
    assert "prng-reuse" in found  # the real finding is NOT silenced


def test_standalone_suppression_covers_next_line():
    src = (
        "import jax\n"
        "\n"
        "def sample(key):\n"
        "    a = jax.random.normal(key, (3,))\n"
        "    # graftlint: disable=prng-reuse -- exercising identical draws\n"
        "    b = jax.random.uniform(key, (3,))\n"
        "    return a + b\n"
    )
    assert rules_of(src) == set()


def test_unused_suppression_is_a_violation():
    # A well-formed disable that silences nothing is stale and must be
    # reported, so suppressions get cleaned up when the excused code goes.
    src = (
        "import jax\n"
        "\n"
        "def sample(key):\n"
        "    # graftlint: disable=prng-reuse -- no longer needed here\n"
        "    return jax.random.normal(key, (3,))\n"
    )
    found = violations_of(src)
    assert [v.rule for v in found] == ["bad-suppression"]
    assert "unused suppression" in found[0].message


def test_parse_error_is_reported_not_raised():
    found = violations_of("def broken(:\n    pass\n")
    assert [v.rule for v in found] == ["parse-error"]


# ---------------------------------------------------------------------------
# ISSUE 8: rule coverage over the sharding-helper shapes (parallel/sharding)
# ---------------------------------------------------------------------------


def test_host_numpy_on_spec_helpers_outside_trace_is_clean():
    """The declarative sharding helpers interrogate leaves with host numpy
    (np.ndim/np.shape in rank-dependent specs and the divisibility guard)
    OUTSIDE any traced function — that is their design and must stay
    lint-clean."""
    src = """
    import numpy as np
    from jax.sharding import PartitionSpec as P

    def last_axis(axis_name):
        def spec(leaf):
            return P(*([None] * (np.ndim(leaf) - 1) + [axis_name]))
        return spec

    def guard_divisible(mesh, spec, leaf):
        shape = np.shape(leaf)
        out = []
        for i, axis in enumerate(spec):
            if axis is not None and shape[i] % mesh.shape[axis] != 0:
                axis = None
            out.append(axis)
        return P(*out)
    """
    assert "host-numpy-in-trace" not in rules_of(
        src, path="pkg/parallel/sharding.py"
    )


def test_host_numpy_gather_inside_traced_function_flags():
    """A gather helper (np.asarray on device values) belongs OUTSIDE the
    trace — the same call inside a jitted step would bake the gathered
    constant. The rule must catch a gather-shaped call migrating into a
    traced function."""
    src = """
    import jax
    import numpy as np

    @jax.jit
    def step(state):
        gathered = np.asarray(state)
        return gathered * 2
    """
    assert "host-numpy-in-trace" in rules_of(
        src, path="pkg/parallel/sharding.py"
    )


def test_device_op_mesh_aware_staging_does_not_widen_the_data_path_ban():
    """Mesh-aware staging (ISSUE 8) hands the stager a Sharding as DATA —
    it must not license new jax/jax.sharding imports across data/. A new
    data/ module reaching for jax.sharding directly still flags; the
    sharding helpers themselves live in parallel/, outside the ban."""
    sharded_loader = """
    from jax.sharding import NamedSharding, PartitionSpec

    def collate(mesh, episodes):
        return NamedSharding(mesh, PartitionSpec("dp"))
    """
    assert "device-op-in-data-path" in rules_of(
        sharded_loader, path="pkg/data/sharded_loader.py"
    )
    assert "device-op-in-data-path" not in rules_of(
        sharded_loader, path="pkg/parallel/sharding.py"
    )
    # The allowlisted stager stays clean with the sharding-aware put form.
    sharding_aware_put = """
    import jax

    def stage(batch, sharding):
        return jax.device_put(batch, sharding)
    """
    assert "device-op-in-data-path" not in rules_of(
        sharding_aware_put, path="pkg/data/device_prefetch.py"
    )


# ---------------------------------------------------------------------------
# thread-lifecycle
# ---------------------------------------------------------------------------


def test_thread_lifecycle_positive_class_spawns_without_join():
    src = """
    import threading

    class LeakyWorker:
        def __init__(self):
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

        def _run(self):
            pass

        def close(self):
            self._closed = True  # never joins the thread
    """
    assert "thread-lifecycle" in rules_of(src)


def test_thread_lifecycle_positive_module_level_retained_thread():
    src = """
    from threading import Thread

    def start_background(fn):
        worker = Thread(target=fn, daemon=True)
        worker.start()
        return worker
    """
    assert "thread-lifecycle" in rules_of(src)


def test_thread_lifecycle_negative_owner_joins_on_close():
    src = """
    import threading

    class Supervised:
        def __init__(self):
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

        def _run(self):
            pass

        def close(self):
            self._thread.join(timeout=5.0)
    """
    assert "thread-lifecycle" not in rules_of(src)


def test_thread_lifecycle_negative_string_and_path_joins_dont_count():
    src = """
    import os
    import threading

    class StillLeaky:
        def __init__(self):
            self._thread = threading.Thread(target=self._run)
            self._thread.start()

        def _run(self):
            pass

        def describe(self):
            return ", ".join(["a", "b"]) + os.path.join("x", "y")
    """
    # String/path joins are not thread joins: the rule still fires.
    assert "thread-lifecycle" in rules_of(src)


def test_thread_lifecycle_negative_fire_and_forget_out_of_scope():
    src = """
    import threading

    def notify(fn):
        threading.Thread(target=fn, daemon=True).start()
    """
    # No retained handle -> nothing a shutdown path could join.
    assert "thread-lifecycle" not in rules_of(src)


# ---------------------------------------------------------------------------
# device-probe-before-distributed-init
# ---------------------------------------------------------------------------


def test_device_probe_before_init_positive_module_level():
    src = """
    import jax
    from howtotrainyourmamlpytorch_tpu.parallel import initialize_distributed

    devices = jax.devices()
    initialize_distributed()
    """
    assert "device-probe-before-distributed-init" in rules_of(src)


def test_device_probe_before_init_positive_probe_without_any_init_call():
    src = """
    import jax
    from howtotrainyourmamlpytorch_tpu.parallel import initialize_distributed

    n = len(jax.local_devices())
    """
    assert "device-probe-before-distributed-init" in rules_of(src)


def test_device_probe_before_init_positive_inside_main():
    src = """
    import jax
    from howtotrainyourmamlpytorch_tpu.parallel import (
        initialize_distributed_from_argv,
    )

    def main():
        kind = jax.devices()[0].device_kind
        initialize_distributed_from_argv()
        return kind
    """
    assert "device-probe-before-distributed-init" in rules_of(src)


def test_device_probe_after_init_negative():
    src = """
    import jax
    from howtotrainyourmamlpytorch_tpu.parallel import initialize_distributed

    initialize_distributed()
    devices = jax.devices()
    """
    assert "device-probe-before-distributed-init" not in rules_of(src)


def test_device_probe_negative_module_without_bringup_import():
    # A module with no multi-host ambition may probe devices freely — the
    # ordering contract binds only files that import the bring-up helper.
    src = """
    import jax

    devices = jax.devices()
    """
    assert "device-probe-before-distributed-init" not in rules_of(src)


# ---------------------------------------------------------------------------
# durable-write
# ---------------------------------------------------------------------------


def test_durable_write_positive_truncating_open_in_tier_module():
    # Inside serve/tier/ every truncating open is a violation — only the
    # atomic helper itself may touch the bytes directly.
    src = """
    def save(path, data):
        with open(path, "wb") as f:
            f.write(data)
    """
    path = "howtotrainyourmamlpytorch_tpu/serve/tier/spill.py"
    assert "durable-write" in rules_of(src, path)


def test_durable_write_positive_journal_path_anywhere():
    src = """
    def rewrite(journal_path, rows):
        with open(journal_path, "w") as f:
            f.write(rows)
    """
    assert "durable-write" in rules_of(src)


def test_durable_write_negative_append_read_and_atomic_helper():
    # Journal appends, reads, and the sanctioned atomic writer all pass;
    # so does a write-mode open on a path with no durable marker.
    src = """
    def append(journal_path, row):
        with open(journal_path, "a") as f:
            f.write(row)

    def load(spill_path):
        with open(spill_path, "rb") as f:
            return f.read()

    def dump_log(log_path, text):
        with open(log_path, "w") as f:
            f.write(text)
    """
    assert "durable-write" not in rules_of(src)
    atomic = """
    import os
    import tempfile

    def atomic_write_bytes(path, data):
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        os.write(fd, data)
        os.fsync(fd)
        os.close(fd)
        os.replace(tmp, path)
    """
    path = "howtotrainyourmamlpytorch_tpu/serve/tier/atomic.py"
    assert "durable-write" not in rules_of(atomic, path)
