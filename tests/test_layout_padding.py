"""Lane-padded compute layout (ISSUE 9 lever 1, ``ops/layout.py``).

The padding equivalence argument (zero conv filters -> zero channels ->
per-channel BN emits beta=0 -> leaky_relu/max_pool preserve 0 -> the next
conv's zero weight columns ignore them -> the head slices them off) must
hold EXACTLY, not approximately, or the flag silently trains a different
model. Pinned here:

* padded vs unpadded logits BIT-EXACT across all three learners (eval);
* second-order train parity: identical loss, real-slice parameters within
  the documented reassociation tolerance, padding lanes FROZEN at their
  init values over multiple meta-updates (their gradients are exactly 0);
* compile-exactly-once under the PR 2 guard with the padded layout active;
* a padded run on the 8-device CPU dp mesh (first-order — the GSPMD conv
  CHECK-crash is second-order-specific, ``spmd_fo_compile_guard``);
* checkpoint round-trip padded -> unpadded -> padded: archives NEVER
  contain padding, so padded and unpadded writers/readers interoperate
  bit-exactly (``CheckpointableLearner`` strips on save, re-pads on load);
* the inference prefix load re-pads the same way (serving cold start).
"""

import dataclasses
import os

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    GradientDescentLearner,
    MAMLConfig,
    MAMLFewShotLearner,
    MatchingNetsLearner,
)
from howtotrainyourmamlpytorch_tpu.ops.layout import (
    lane_padded_width,
    pad_tree,
    strip_tree,
    trees_same_shapes,
    zero_pad_to,
)
from howtotrainyourmamlpytorch_tpu.parallel import make_mesh

LEARNERS = [MAMLFewShotLearner, GradientDescentLearner, MatchingNetsLearner]


def make_cfg(lane_pad=False, **kw):
    backbone_kw = dict(
        num_stages=2,
        num_filters=6,  # deliberately lane-hostile: pads to 8
        per_step_bn_statistics=True,
        num_steps=2,
        num_classes=5,
        image_height=8,
        image_width=8,
        lane_pad_channels=lane_pad,
    )
    backbone_kw.update(kw.pop("backbone_kw", {}))
    kw.setdefault("second_order", True)
    return MAMLConfig(
        backbone=BackboneConfig(**backbone_kw),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        use_multi_step_loss_optimization=False,
        **kw,
    )


def make_batch(rng, tasks=4, size=8):
    xs = rng.randn(tasks, 5, 1, 1, size, size).astype(np.float32)
    ys = np.tile(np.arange(5)[None, :, None], (tasks, 1, 1)).astype(np.int32)
    return xs, xs.copy(), ys, ys.copy()


def real_slice(padded_leaf, real_leaf):
    return np.asarray(padded_leaf)[
        tuple(slice(0, s) for s in np.shape(real_leaf))
    ]


def padding_mask(padded_leaf, real_leaf):
    mask = np.ones(np.shape(padded_leaf), bool)
    mask[tuple(slice(0, s) for s in np.shape(real_leaf))] = False
    return mask


# ---------------------------------------------------------------------------
# ops/layout.py units
# ---------------------------------------------------------------------------


def test_lane_padded_width_values():
    # The north-star case and its neighbors: sublane powers below one full
    # lane, lane multiples at or above it.
    assert lane_padded_width(48) == 64
    assert lane_padded_width(64) == 64
    assert lane_padded_width(3) == 8
    assert lane_padded_width(9) == 16
    assert lane_padded_width(128) == 128
    assert lane_padded_width(129) == 256
    assert lane_padded_width(160) == 256  # MetaOptNet ResNet-12 stage 2
    assert lane_padded_width(320) == 384
    with pytest.raises(ValueError):
        lane_padded_width(0)


def test_zero_pad_to_identity_and_shape_errors():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    same = zero_pad_to(jax.numpy.asarray(x), (2, 3))
    np.testing.assert_array_equal(np.asarray(same), x)
    padded = np.asarray(zero_pad_to(jax.numpy.asarray(x), (4, 8)))
    np.testing.assert_array_equal(padded[:2, :3], x)
    assert np.all(padded[2:] == 0) and np.all(padded[:, 3:] == 0)
    with pytest.raises(ValueError):
        zero_pad_to(jax.numpy.asarray(x), (1, 3))
    with pytest.raises(ValueError):
        zero_pad_to(jax.numpy.asarray(x), (2, 3, 1))


def test_strip_pad_tree_round_trip():
    rng = np.random.RandomState(0)
    unpadded = {"w": rng.randn(6, 3).astype(np.float32), "b": np.zeros(6, np.float32)}
    template = {"w": np.zeros((8, 8), np.float32), "b": np.ones(8, np.float32)}
    padded = pad_tree(unpadded, template)
    # Padding lanes carry the template's canonical values.
    assert np.all(padded["b"][6:] == 1.0)
    stripped = strip_tree(padded, unpadded)
    for k in unpadded:
        np.testing.assert_array_equal(stripped[k], unpadded[k])
    assert not trees_same_shapes(unpadded, template)
    assert trees_same_shapes(padded, template)


# ---------------------------------------------------------------------------
# Parity across all three learners
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cls", LEARNERS)
def test_padded_eval_logits_bit_exact(cls, rng):
    batch = make_batch(rng)
    a = cls(make_cfg(lane_pad=False))
    p = cls(make_cfg(lane_pad=True))
    _, la, logits_a = a.run_validation_iter(
        a.init_state(jax.random.PRNGKey(1)), batch
    )
    _, lp, logits_p = p.run_validation_iter(
        p.init_state(jax.random.PRNGKey(1)), batch
    )
    np.testing.assert_array_equal(np.asarray(logits_a), np.asarray(logits_p))
    assert float(la["loss"]) == float(lp["loss"])


@pytest.mark.parametrize("cls", LEARNERS)
def test_padded_train_parity_and_padding_frozen(cls, rng):
    """Three meta-updates (second order for MAML): losses identical, the
    real parameter slice within reassociation tolerance of the unpadded
    program, and every padding lane still EXACTLY its init value — the
    zero-gradient proof that padding can never leak into training."""
    batches = [make_batch(rng) for _ in range(3)]
    a = cls(make_cfg(lane_pad=False))
    p = cls(make_cfg(lane_pad=True))
    sa = a.init_state(jax.random.PRNGKey(2))
    sp = p.init_state(jax.random.PRNGKey(2))
    init_theta = jax.tree.map(np.asarray, sp.theta)
    for batch in batches:
        sa, la = a.run_train_iter(sa, batch, epoch=0)
        sp, lp = p.run_train_iter(sp, batch, epoch=0)
        assert float(la["loss"]) == float(lp["loss"])
    flat_p = jax.tree_util.tree_flatten_with_path(sp.theta)[0]
    flat_a = jax.tree_util.tree_flatten_with_path(sa.theta)[0]
    flat_i = jax.tree_util.tree_flatten_with_path(init_theta)[0]
    for (key, leaf_p), (_, leaf_a), (_, leaf_i) in zip(flat_p, flat_a, flat_i):
        leaf_p = np.asarray(leaf_p)
        np.testing.assert_allclose(
            real_slice(leaf_p, leaf_a), np.asarray(leaf_a),
            rtol=2e-5, atol=1e-6, err_msg=str(key),
        )
        mask = padding_mask(leaf_p, leaf_a)
        np.testing.assert_array_equal(
            leaf_p[mask], np.asarray(leaf_i)[mask], err_msg=str(key)
        )


def test_padded_second_order_meta_grads_match(rng):
    """The meta-gradient itself (not just its Adam image): padded vs
    unpadded second-order grads on the real slice within the documented
    tolerance, exactly zero on every padding lane."""
    import optax

    cfg_a, cfg_p = make_cfg(lane_pad=False), make_cfg(lane_pad=True)
    a, p = MAMLFewShotLearner(cfg_a), MAMLFewShotLearner(cfg_p)
    sa = a.init_state(jax.random.PRNGKey(3))
    sp = p.init_state(jax.random.PRNGKey(3))
    batch = a._prepare_batch(make_batch(rng))
    importance = a._train_importance(0)

    def meta_grads(learner, state):
        outer = {"theta": state.theta, "lslr": state.lslr}
        return jax.grad(
            lambda o: learner._meta_loss(
                o, state.bn_state, batch, importance, 2, True,
                None, True,
            )[0]
        )(outer)

    ga, gp = meta_grads(a, sa), meta_grads(p, sp)
    assert float(optax.global_norm(ga)) > 0  # non-degenerate comparison
    for (key, leaf_p), (_, leaf_a) in zip(
        jax.tree_util.tree_flatten_with_path(gp["theta"])[0],
        jax.tree_util.tree_flatten_with_path(ga["theta"])[0],
    ):
        leaf_p = np.asarray(leaf_p)
        np.testing.assert_allclose(
            real_slice(leaf_p, leaf_a), np.asarray(leaf_a),
            rtol=2e-5, atol=1e-6, err_msg=str(key),
        )
        assert np.all(leaf_p[padding_mask(leaf_p, leaf_a)] == 0.0), key


def test_padded_resnet12_eval_bit_exact(rng):
    cfg_kw = dict(
        backbone_kw=dict(
            architecture="resnet12",
            resnet_widths=(4, 5, 6, 7),  # pads to (8, 8, 8, 8)
            per_step_bn_statistics=False,
            max_pooling=True,
            # 16x16 survives the four 2x2 pools (16 -> 8 -> 4 -> 2 -> 1);
            # 8x8 would pool a 1x1 map to empty and NaN the global mean.
            image_height=16,
            image_width=16,
        ),
        second_order=False,
    )
    batch = make_batch(rng, size=16)
    a = MAMLFewShotLearner(make_cfg(lane_pad=False, **cfg_kw))
    p = MAMLFewShotLearner(make_cfg(lane_pad=True, **cfg_kw))
    _, la, logits_a = a.run_validation_iter(
        a.init_state(jax.random.PRNGKey(4)), batch
    )
    _, lp, logits_p = p.run_validation_iter(
        p.init_state(jax.random.PRNGKey(4)), batch
    )
    np.testing.assert_array_equal(np.asarray(logits_a), np.asarray(logits_p))
    assert float(la["loss"]) == float(lp["loss"])


def test_lane_pad_requires_conv_norm_batch_norm():
    with pytest.raises(ValueError, match="lane_pad_channels"):
        MAMLFewShotLearner(
            make_cfg(
                lane_pad=True,
                backbone_kw=dict(norm_layer="layer_norm"),
            )
        ).init_state(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="lane_pad_channels"):
        MAMLFewShotLearner(
            make_cfg(lane_pad=True, backbone_kw=dict(block_order="norm_conv"))
        ).init_state(jax.random.PRNGKey(0))


def test_lane_friendly_width_is_a_no_op(rng):
    """At an already-lane-friendly width (8) padding changes no shapes, so
    the padded learner IS the unpadded program (and checkpoints skip the
    strip/pad path entirely)."""
    a = MAMLFewShotLearner(
        make_cfg(lane_pad=False, backbone_kw=dict(num_filters=8))
    )
    p = MAMLFewShotLearner(
        make_cfg(lane_pad=True, backbone_kw=dict(num_filters=8))
    )
    sa = a.init_state(jax.random.PRNGKey(5))
    sp = p.init_state(jax.random.PRNGKey(5))
    for la, lp in zip(jax.tree.leaves(sa.theta), jax.tree.leaves(sp.theta)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lp))
    assert p._lane_pad_templates("init_state") is None


# ---------------------------------------------------------------------------
# Compile-once + dp mesh
# ---------------------------------------------------------------------------


def test_padded_train_step_compiles_once(compile_guard, rng):
    learner = MAMLFewShotLearner(make_cfg(lane_pad=True))
    state = learner.init_state(jax.random.PRNGKey(6))
    with compile_guard() as guard:
        for _ in range(3):
            state, _ = learner.run_train_iter(state, make_batch(rng), epoch=0)
        jax.block_until_ready(state.theta)
    guard.assert_compiles("_train_step", exactly=1)
    guard.assert_unique_signatures("_train_step")


def test_padded_run_on_dp_mesh_matches_unpadded(spmd_fo_compile_guard, rng):
    """First-order padded training on the 8-device CPU dp mesh: same
    losses as the unpadded mesh program, padding stays frozen — the layout
    lever composes with the PR 8 mesh scale-out."""
    mesh = make_mesh(jax.devices()[:8], data_parallel=8, model_parallel=1)
    kw = dict(second_order=False)
    a = MAMLFewShotLearner(make_cfg(lane_pad=False, **kw), mesh=mesh)
    p = MAMLFewShotLearner(make_cfg(lane_pad=True, **kw), mesh=mesh)
    sa = a.shard_state(a.init_state(jax.random.PRNGKey(7)))
    sp = p.shard_state(p.init_state(jax.random.PRNGKey(7)))
    for _ in range(2):
        batch = make_batch(rng, tasks=8)
        sa, la = a.run_train_iter(sa, batch, epoch=0)
        sp, lp = p.run_train_iter(sp, batch, epoch=0)
        assert float(la["loss"]) == float(lp["loss"])
    for leaf_p, leaf_a in zip(
        jax.tree.leaves(p.gather_state(sp).theta),
        jax.tree.leaves(a.gather_state(sa).theta),
    ):
        np.testing.assert_allclose(
            real_slice(leaf_p, leaf_a), np.asarray(leaf_a),
            rtol=2e-5, atol=1e-6,
        )


# ---------------------------------------------------------------------------
# Checkpoint layout portability
# ---------------------------------------------------------------------------

EXP = {"current_iter": 9, "best_val_acc": 0.25}


def test_checkpoint_round_trip_padded_unpadded_padded(tmp_path, rng):
    """padded writer -> unpadded reader -> padded reader: the archive is
    layout-free, every reader sees the same real-channel values, and the
    re-padded state's padding lanes carry the canonical init values."""
    writer = MAMLFewShotLearner(make_cfg(lane_pad=True))
    state = writer.init_state(jax.random.PRNGKey(8))
    state, _ = writer.run_train_iter(state, make_batch(rng), epoch=0)
    path = os.path.join(tmp_path, "train_model_3")
    writer.save_model(path, state, dict(EXP))

    unpadded = MAMLFewShotLearner(make_cfg(lane_pad=False))
    s_unpadded, exp = unpadded.load_model(str(tmp_path), "train_model", 3)
    assert exp == EXP
    for leaf_u, leaf_w in zip(
        jax.tree.leaves(s_unpadded.theta), jax.tree.leaves(state.theta)
    ):
        np.testing.assert_array_equal(
            np.asarray(leaf_u), real_slice(leaf_w, leaf_u)
        )

    # Second leg: the unpadded reader re-saves, a padded reader restores.
    path2 = os.path.join(tmp_path, "train_model_4")
    unpadded.save_model(path2, s_unpadded, dict(EXP))
    padded = MAMLFewShotLearner(make_cfg(lane_pad=True))
    s_padded, _ = padded.load_model(str(tmp_path), "train_model", 4)
    init_padded = padded.init_state(jax.random.PRNGKey(0))
    for leaf_p, leaf_w, leaf_i in zip(
        jax.tree.leaves(s_padded.theta),
        jax.tree.leaves(state.theta),
        jax.tree.leaves(init_padded.theta),
    ):
        leaf_p = np.asarray(leaf_p)
        np.testing.assert_array_equal(leaf_p.shape, np.shape(leaf_w))
        sl = real_slice(leaf_p, real_slice(leaf_w, leaf_p))  # no-op slice
        np.testing.assert_array_equal(sl, np.asarray(leaf_w))
        # Padding lanes: canonical template values (zero weights, unit
        # gammas), NOT whatever the writer's padded run carried.
        mask = padding_mask(leaf_p, real_slice(leaf_w, leaf_p))
        if mask.any():
            np.testing.assert_array_equal(
                leaf_p[mask], np.asarray(leaf_i)[mask]
            )

    # And the round-tripped padded state keeps producing identical logits.
    batch = make_batch(rng)
    _, _, logits_w = writer.run_validation_iter(state, batch)
    _, _, logits_p = padded.run_validation_iter(s_padded, batch)
    np.testing.assert_array_equal(np.asarray(logits_w), np.asarray(logits_p))


def test_padded_archive_equals_unpadded_archive(tmp_path):
    """Same init key, padded and unpadded writers: the serialized archives
    hold identical leaves (manifest CRCs computed over the STRIPPED state),
    so layout is invisible to the PR 3 integrity layer."""
    a = MAMLFewShotLearner(make_cfg(lane_pad=False))
    p = MAMLFewShotLearner(make_cfg(lane_pad=True))
    pa = os.path.join(tmp_path, "train_model_1")
    pp = os.path.join(tmp_path, "train_model_2")
    a.save_model(pa, a.init_state(jax.random.PRNGKey(9)), dict(EXP))
    p.save_model(pp, p.init_state(jax.random.PRNGKey(9)), dict(EXP))
    za, zp = np.load(pa), np.load(pp)  # save_checkpoint adds no extension
    try:
        assert set(za.files) == set(zp.files)
        for name in za.files:
            np.testing.assert_array_equal(za[name], zp[name], err_msg=name)
    finally:
        za.close()
        zp.close()


def test_inference_prefix_load_re_pads(tmp_path, rng):
    """Serving cold start: an unpadded archive restores into a padded
    learner's inference template with the real slice intact and padding at
    canonical init values."""
    writer = MAMLFewShotLearner(make_cfg(lane_pad=False))
    state = writer.init_state(jax.random.PRNGKey(10))
    state, _ = writer.run_train_iter(state, make_batch(rng), epoch=0)
    path = os.path.join(tmp_path, "train_model_5")
    writer.save_model(path, state, dict(EXP))

    padded = MAMLFewShotLearner(make_cfg(lane_pad=True))
    istate, exp = padded.load_inference_state(path)
    assert exp == EXP
    init_istate = padded.init_inference_state(jax.random.PRNGKey(0))
    for leaf_p, leaf_w, leaf_i in zip(
        jax.tree.leaves(istate.theta),
        jax.tree.leaves(state.theta),
        jax.tree.leaves(init_istate.theta),
    ):
        leaf_p = np.asarray(leaf_p)
        np.testing.assert_array_equal(real_slice(leaf_p, leaf_w), leaf_w)
        mask = padding_mask(leaf_p, leaf_w)
        if mask.any():
            np.testing.assert_array_equal(
                leaf_p[mask], np.asarray(leaf_i)[mask]
            )
