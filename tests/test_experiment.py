"""Experiment runtime tests: config parsing, storage, checkpoint roundtrip,
and an end-to-end ExperimentBuilder run with pause/resume and ensemble test
(SURVEY §4 — the reference has no tests; this is the from-scratch strategy)."""

import json
import os
import sys

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.experiment_builder import ExperimentBuilder
from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.data import MetaLearningSystemDataLoader
from howtotrainyourmamlpytorch_tpu.utils import storage
from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
    load_checkpoint,
    save_checkpoint,
)
from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
    args_to_maml_config,
    get_args,
)

from test_data import make_args, make_dataset_dir


# ---------------------------------------------------------------------------
# Config system (C19)
# ---------------------------------------------------------------------------


def test_get_args_json_override(tmp_path, monkeypatch):
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    cfg = {
        "batch_size": 8,
        "second_order": True,
        "continue_from_epoch": 7,  # must be IGNORED (parser_utils.py:103)
        "gpu_to_use": 3,  # must be IGNORED
        "per_step_bn_statistics": "true",  # string -> bool coercion
        "dataset_path": "omniglot_dataset",
    }
    cfg_file = tmp_path / "cfg.json"
    cfg_file.write_text(json.dumps(cfg))
    args, device = get_args(["--name_of_args_json_file", str(cfg_file)])
    assert args.batch_size == 8
    assert args.second_order is True
    assert args.per_step_bn_statistics is True
    assert args.continue_from_epoch == "latest"  # CLI default survives
    assert args.gpu_to_use is None
    assert args.dataset_path == os.path.join(str(tmp_path), "omniglot_dataset")


def test_parity_bug_flag_parses_and_coerces(tmp_path, monkeypatch):
    """`--parity_bug` is a real CLI flag (GOLDEN_RUNS.md documents it as the
    reproduction knob for the reference matching-nets reporting bug) and goes
    through the string->bool coercion like every other reference-style flag."""
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    args, _ = get_args(["--parity_bug", "True"])
    assert args.parity_bug is True
    args, _ = get_args([])
    assert args.parity_bug is False


def test_args_to_maml_config(tmp_path, monkeypatch):
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    cfg = {
        "dataset_name": "mini_imagenet_full_size",
        "image_height": 84, "image_width": 84, "image_channels": 3,
        "cnn_num_filters": 48, "num_stages": 4,
        "number_of_training_steps_per_iter": 5,
        "number_of_evaluation_steps_per_iter": 5,
        "per_step_bn_statistics": True,
        "init_inner_loop_learning_rate": 0.01,
        "num_classes_per_set": 5,
        "max_pooling": True, "conv_padding": True,
        "second_order": True,
    }
    cfg_file = tmp_path / "cfg.json"
    cfg_file.write_text(json.dumps(cfg))
    args, _ = get_args(["--name_of_args_json_file", str(cfg_file)])
    mc = args_to_maml_config(args)
    assert mc.backbone.num_filters == 48
    assert mc.backbone.image_height == 84
    assert mc.backbone.per_step_bn_statistics
    # init_inner_loop_learning_rate honored when task_learning_rate is default
    assert mc.task_learning_rate == 0.01
    # ImageNet grad clamp (few_shot_learning_system.py:332-335)
    assert mc.clip_grad_value == 10.0


# ---------------------------------------------------------------------------
# Storage (C18)
# ---------------------------------------------------------------------------


def test_storage_csv_roundtrip(tmp_path):
    exp = str(tmp_path)
    storage.save_statistics(exp, ["a", "b"], create=True)
    storage.save_statistics(exp, [1, 2])
    storage.save_statistics(exp, [3, 4])
    loaded = storage.load_statistics(exp)
    assert loaded["a"] == ["1", "3"]
    assert loaded["b"] == ["2", "4"]


def test_build_experiment_folder(tmp_path):
    saved, logs, samples = storage.build_experiment_folder(str(tmp_path / "exp"))
    for p in (saved, logs, samples):
        assert os.path.isdir(p)
    assert saved.endswith("saved_models")


# ---------------------------------------------------------------------------
# Checkpoint (SURVEY §5 checkpoint/resume)
# ---------------------------------------------------------------------------


def _tiny_cfg():
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2, num_filters=4, per_step_bn_statistics=True,
            num_steps=2, num_classes=5,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        total_epochs=2, total_iter_per_epoch=2,
    )


def test_checkpoint_roundtrip(tmp_path):
    learner = MAMLFewShotLearner(_tiny_cfg())
    state = learner.init_state(jax.random.PRNGKey(3))
    exp_state = {"current_iter": 7, "best_val_acc": 0.5,
                 "per_epoch_statistics": {"val_accuracy_mean": [0.5]}}
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, state, exp_state)
    template = learner.init_state(jax.random.PRNGKey(0))
    restored, exp_restored = load_checkpoint(path, template)
    assert exp_restored["current_iter"] == 7
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_shape_mismatch_raises(tmp_path):
    learner = MAMLFewShotLearner(_tiny_cfg())
    state = learner.init_state(jax.random.PRNGKey(3))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, state, {})
    other = MAMLFewShotLearner(
        MAMLConfig(
            backbone=BackboneConfig(num_stages=2, num_filters=8, num_classes=5),
            number_of_training_steps_per_iter=2,
            number_of_evaluation_steps_per_iter=2,
        )
    )
    with pytest.raises(ValueError, match="shape"):
        load_checkpoint(path, other.init_state(jax.random.PRNGKey(0)))


# ---------------------------------------------------------------------------
# End-to-end ExperimentBuilder (CPU, tiny)
# ---------------------------------------------------------------------------


def _experiment_args(tmp_path):
    return make_args(
        tmp_path,
        experiment_name=str(tmp_path / "exp"),
        seed=104,
        continue_from_epoch="latest",
        max_models_to_save=5,
        total_epochs=3,
        total_iter_per_epoch=2,
        total_epochs_before_pause=100,
        num_evaluation_tasks=8,
        evaluate_on_test_set_only=False,
        batch_size=2,
        model="maml++",
        # learner config keys
        num_stages=2, cnn_num_filters=4, conv_padding=True, max_pooling=True,
        norm_layer="batch_norm", per_step_bn_statistics=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        num_classes_per_set=5, second_order=False,
        first_order_to_second_order_epoch=-1,
        use_multi_step_loss_optimization=True, multi_step_loss_num_epochs=2,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        enable_inner_loop_optimizable_bn_params=False,
        learnable_bn_gamma=True, learnable_bn_beta=True,
        meta_learning_rate=0.001, min_learning_rate=1e-5,
        task_learning_rate=0.1, init_inner_loop_learning_rate=0.1,
    )


def test_experiment_builder_end_to_end(tmp_path, monkeypatch):
    make_dataset_dir(tmp_path / "omniglot_mini")
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    args = _experiment_args(tmp_path)
    model = MAMLFewShotLearner(args_to_maml_config(args))
    builder = ExperimentBuilder(
        args=args, data=MetaLearningSystemDataLoader, model=model, device=None
    )
    test_losses = builder.run_experiment()
    assert 0.0 <= test_losses["test_accuracy_mean"] <= 1.0

    logs = os.path.join(str(tmp_path / "exp"), "logs")
    saved = os.path.join(str(tmp_path / "exp"), "saved_models")
    stats = storage.load_statistics(logs)
    assert len(stats["epoch"]) == 3
    assert "train_accuracy_mean" in stats and "val_accuracy_mean" in stats
    assert os.path.exists(os.path.join(saved, "train_model_3"))
    assert os.path.exists(os.path.join(saved, "train_model_latest"))
    assert os.path.exists(os.path.join(logs, "test_summary.csv"))
    assert os.path.exists(os.path.join(logs, "summary_statistics.json"))

    # Resume-stats regression pin (reference ordering bug, ISSUE 3
    # satellite): the epoch-N checkpoint must contain epoch N's own stats
    # row, otherwise a resume silently shifts the top-5 ensemble's
    # val-stats-index -> checkpoint mapping.
    for e in (1, 2, 3):
        with np.load(os.path.join(saved, f"train_model_{e}")) as archive:
            ckpt_state = json.loads(
                bytes(archive["__experiment_state__"]).decode()
            )
        assert len(ckpt_state["per_epoch_statistics"]["val_accuracy_mean"]) == e


def test_experiment_builder_resume(tmp_path, monkeypatch):
    make_dataset_dir(tmp_path / "omniglot_mini")
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    args = _experiment_args(tmp_path)

    # Phase 1: pause after 1 epoch (sys.exit, experiment_builder.py:365-368).
    args.total_epochs_before_pause = 1
    model = MAMLFewShotLearner(args_to_maml_config(args))
    builder = ExperimentBuilder(
        args=args, data=MetaLearningSystemDataLoader, model=model, device=None
    )
    with pytest.raises(SystemExit):
        builder.run_experiment()
    assert builder.state["current_iter"] == 2

    # Phase 2: resume from latest and finish.
    args2 = _experiment_args(tmp_path)
    model2 = MAMLFewShotLearner(args_to_maml_config(args2))
    builder2 = ExperimentBuilder(
        args=args2, data=MetaLearningSystemDataLoader, model=model2, device=None
    )
    assert builder2.state["current_iter"] == 2
    assert builder2.epoch == 1
    builder2.run_experiment()
    stats = storage.load_statistics(os.path.join(str(tmp_path / "exp"), "logs"))
    assert len(stats["epoch"]) == 3  # 1 from phase one + 2 after resume
