"""Runtime lock-order sanitizer (``utils/locksan.py``).

Unit contracts — instrumented-lock API parity, cycle detection, hold-time
accounting (a ``Condition.wait`` releases the lock, so waits never count
as holds), reentrant RLock handling — plus the real-scenario proof: the
2-replica pool serving through a replica kill-mid-stream runs entirely
under the sanitizer with a clean acquisition-order graph and hot-path
holds inside budget. (The serve/chaos suites additionally run under the
sanitizer wholesale via the autouse conftest fixture.)
"""

import queue
import threading
import time

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.utils import faultinject
from howtotrainyourmamlpytorch_tpu.utils.locksan import LockSanitizer


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.deactivate()
    yield
    faultinject.deactivate()


# ---------------------------------------------------------------------------
# Units
# ---------------------------------------------------------------------------


def test_instrumented_lock_api_parity():
    with LockSanitizer():
        lock = threading.Lock()
        assert lock.acquire()
        assert lock.locked()
        assert not lock.acquire(blocking=False)
        lock.release()
        assert not lock.locked()
        with lock:
            assert lock.locked()
        # concurrent.futures imports lazily and touches _at_fork_reinit
        # at module load — the delegating surface must carry it.
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=1) as pool:
            assert pool.submit(lambda: 7).result(timeout=10) == 7
    assert threading.Lock is not lock.__class__


def test_deactivate_restores_native_factories():
    native = threading.Lock
    with LockSanitizer():
        assert threading.Lock is not native
    assert threading.Lock is native
    assert threading.RLock().__class__.__name__ == "RLock"


def test_cycle_detected_without_an_actual_deadlock():
    """The sanitizer's whole point: both halves of an AB/BA inversion
    record their edge even when the threads never overlap — no schedule
    luck needed to see the deadlock."""
    with LockSanitizer() as san:
        a = threading.Lock()
        b = threading.Lock()

        def forward():
            with a:
                with b:
                    pass

        def backward():
            with b:
                with a:
                    pass

        for target in (forward, backward):
            t = threading.Thread(target=target)
            t.start()
            t.join()
    assert len(san.cycles()) == 1
    with pytest.raises(AssertionError, match="cyclic lock-acquisition"):
        san.assert_clean()


def test_same_site_peer_instances_are_not_a_cycle():
    """Two instances created by the same line (two replicas' pool locks)
    locked in sequence is peer ordering, not an inversion."""
    with LockSanitizer() as san:

        def make():
            return threading.Lock()

        x, y = make(), make()
        with x:
            with y:
                pass
        with y:
            with x:
                pass
    assert san.cycles() == []


def test_condition_wait_not_counted_as_hold():
    with LockSanitizer() as san:
        cond = threading.Condition()
        woke = []

        def waiter():
            with cond:
                cond.wait(timeout=10.0)
                woke.append(True)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.25)
        with cond:
            cond.notify()
        t.join(timeout=10)
    assert woke == [True]
    # The waiter parked ~0.25s, but wait() released the lock: no site may
    # show a hold anywhere near the park time.
    assert all(hold < 0.2 for hold in san.max_hold_s.values()), (
        san.max_hold_s
    )


def test_hold_budget_verdict_fires():
    with LockSanitizer() as san:
        lock = threading.Lock()
        with lock:
            time.sleep(0.06)
    over = san.over_budget(0.05)
    assert len(over) == 1
    with pytest.raises(AssertionError, match="hold time over"):
        san.assert_clean(hold_budget_s=0.05)
    # Budget scoped to a non-matching path filter stays quiet.
    san.assert_clean(hold_budget_s=0.05, match="no/such/path")


def test_rlock_reentrancy_single_hold_no_self_edges():
    with LockSanitizer() as san:
        r = threading.RLock()
        with r:
            with r:
                with r:
                    pass
    assert san.edges == {}
    assert sum(san.acquisitions.values()) == 1


def test_queue_locks_are_attributed_to_the_queue_owner():
    with LockSanitizer() as san:
        q = queue.Queue()
        q.put(1)
        assert q.get(timeout=5) == 1
    assert any("test_locksan.py" in site for site in san.acquisitions)


def test_locks_created_before_activation_stay_native():
    pre = threading.Lock()
    with LockSanitizer() as san:
        with pre:
            pass
    assert san.acquisitions == {}


def test_nested_sanitizers_restore_the_outer_one():
    """An inner sanitizer (the `locksan` fixture used inside an
    autouse-sanitized suite) must hand the factories back to the OUTER
    sanitizer on exit — not hard-reset them to native, which would leave
    the suite-level cycle check instrumenting nothing and passing
    vacuously."""
    native = threading.Lock
    with LockSanitizer() as outer:
        with LockSanitizer() as inner:
            inner_lock = threading.Lock()
            with inner_lock:
                pass
        # Inner exited: the OUTER factories must be live again.
        assert threading.Lock is not native
        outer_lock = threading.Lock()
        with outer_lock:
            pass
    assert threading.Lock is native
    assert inner.acquisitions and outer.acquisitions


def test_cross_thread_lock_release_does_not_fabricate_edges():
    """A plain Lock may legally be released by another thread (one-shot
    signal idiom). The acquirer's stale held entry must be pruned at its
    next acquire instead of minting bogus ordering edges."""
    with LockSanitizer() as san:
        signal_lock = threading.Lock()
        other = threading.Lock()
        signal_lock.acquire()
        releaser = threading.Thread(target=signal_lock.release)
        releaser.start()
        releaser.join()
        # signal_lock's entry on THIS thread is stale now; the next
        # acquire must not record an edge signal_lock -> other. (Edges
        # recorded BEFORE the release — e.g. Thread()'s internal locks
        # created while signal_lock was genuinely held — are real.)
        with other:
            pass
    assert (signal_lock.site, other.site) not in san.edges, san.edges
    assert san.cycles() == []


# ---------------------------------------------------------------------------
# The real scenario: 2-replica pool, kill mid-stream, sanitized
# ---------------------------------------------------------------------------


def tiny_cfg():
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=4,
            image_height=8,
            image_width=8,
            num_classes=5,
            per_step_bn_statistics=True,
            num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
    )


def test_pool_kill_mid_stream_under_locksan():
    """The PR 6 crash-mid-stream scenario re-run with every serve-plane
    lock instrumented: a replica dies under live traffic, the pool
    re-dispatches and restarts it, and the OBSERVED acquisition-order
    graph of the whole episode — pool supervisor, batcher worker, engine
    counters, cache, metrics, telemetry — is acyclic with every serve
    hot-path hold inside budget."""
    from howtotrainyourmamlpytorch_tpu.serve import (
        PoolConfig,
        ReplicaPool,
        ServeConfig,
        ServingAPI,
    )
    from howtotrainyourmamlpytorch_tpu.serve.resilience import LocalReplica

    rng = np.random.RandomState(0)
    with LockSanitizer() as san:
        learner = MAMLFewShotLearner(tiny_cfg())

        def factory(index: int) -> LocalReplica:
            api = ServingAPI(
                learner,
                learner.init_state(jax.random.key(0)),
                ServeConfig(meta_batch_size=2, max_wait_ms=0.0),
            )
            api.engine.warmup([(5, 1, 3)])
            return LocalReplica(api, replica_id=f"locksan-{index}")

        pool = ReplicaPool(
            factory,
            PoolConfig(
                n_replicas=2,
                health_interval_s=0.02,
                restart_backoff_s=0.05,
                min_uptime_s=0.0,
            ),
        )
        try:
            assert pool.wait_ready(timeout=120.0)
            faultinject.activate(
                faultinject.FaultPlan(replica_kill_at_request=5)
            )
            answered = []

            def client(n):
                for _ in range(n):
                    xs = rng.rand(5, 1, 8, 8).astype(np.float32)
                    ys = np.arange(5, dtype=np.int32)
                    xq = rng.rand(3, 1, 8, 8).astype(np.float32)
                    answered.append(
                        pool.classify(xs, ys, xq, timeout=60.0)
                    )

            threads = [
                threading.Thread(target=client, args=(4,)) for _ in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert len(answered) == 12  # zero failed requests
            assert pool.metrics.replica_deaths_total.value >= 1
        finally:
            faultinject.deactivate()
            pool.close()
    # Enough concurrency ran that an empty graph would mean the
    # sanitizer saw nothing — assert real coverage, then the verdicts.
    assert sum(san.acquisitions.values()) > 100
    assert any("serve" in site for site in san.acquisitions)
    san.assert_clean(hold_budget_s=2.0, match="serve")
