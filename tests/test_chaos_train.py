"""Chaos-harness tier-1 gate (tools/chaos_train.py — ISSUE 10).

PR 6's loadtest-SLO idea applied to training: a DETERMINISTIC seeded fault
schedule driven through the real ``train_maml_system.py`` CLI, asserting
the job finishes with zero human intervention, every fault class maps to
its documented recovery, and recovery is a measured number (MTTR per fault
class) — not a hope. Plus the real-dispatcher end-to-end: a wedged
dispatch detected by the watchdog inside its deadline, exiting with the
distinct requeue-degraded code and a thread-stack diagnostic, resumed by
``train_maml_system_dispatch.py`` on a smaller virtual mesh from the last
valid checkpoint.

These are full-CLI subprocess runs on a synthesized tiny dataset (~30-60s
each on CPU); everything cheaper about the same machinery lives in
``test_watchdog.py`` / ``test_dispatch_supervise.py`` /
``test_faultinject.py``.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tools.chaos_train import (
    FAULT_CLASSES,
    HANG_EXIT_CODE,
    _partition_phases,
    _plan_phase,
    make_tiny_dataset,
    run_chaos,
    tiny_config,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def workdir(tmp_path):
    make_tiny_dataset(str(tmp_path / "omniglot_mini"), seed=0)
    return str(tmp_path)


# ---------------------------------------------------------------------------
# Harness planning units (no subprocesses)
# ---------------------------------------------------------------------------


def test_partition_defers_evidence_riders_past_kill_and_hang():
    """nan/enospc recovery evidence lives in buffered telemetry and
    end-of-epoch state; SIGKILL and the watchdog's os._exit flush nothing,
    so those riders are deferred to the next surviving phase (a SIGTERM
    phase drains + flushes and may carry them)."""
    assert _partition_phases(["nan", "enospc", "producer", "sigterm"]) == [
        ["nan", "enospc", "producer", "sigterm"], []
    ]
    phases = _partition_phases(["enospc", "kill", "nan", "hang", "sigterm"])
    assert phases == [["kill"], ["hang"], ["enospc", "nan", "sigterm"], []]
    # A trailing deferred rider lands in the final clean phase.
    assert _partition_phases(["nan", "kill"]) == [["kill"], ["nan"]]


def test_plan_phase_lands_stoppers_on_epoch_boundaries():
    plan = _plan_phase(["nan", "sigterm"], 0, epoch_len=2, total_iters=6)
    assert plan == {"nan_at_iter": 0, "sigterm_at_iter": 2}
    # Mid-epoch resume: the stopper still lands on the NEXT boundary.
    plan = _plan_phase(["kill"], 3, epoch_len=2, total_iters=6)
    assert plan == {"sigkill_at_iter": 4}
    # A hang at the final boundary would wedge a dispatch that does not
    # exist; the plan caps it at the last real dispatch.
    plan = _plan_phase(["hang"], 4, epoch_len=2, total_iters=6)
    assert plan == {"hang_at_iter": 5}
    with pytest.raises(ValueError, match="unknown fault"):
        _plan_phase(["cosmic_ray"], 0, epoch_len=2, total_iters=6)


# ---------------------------------------------------------------------------
# The chaos gates (real CLI subprocesses)
# ---------------------------------------------------------------------------


def test_chaos_schedule_of_six_fault_classes_recovers_unattended(workdir):
    """The acceptance gate: >= 5 distinct fault classes (here all six —
    NaN batch, ENOSPC, producer fault, SIGTERM, mesh-worker SIGKILL,
    wedged-dispatch hang) through the real CLI on a 2-device virtual
    mesh, with zero manual intervention, every class recovering as
    documented, and a finite final model. The hang degrades the mesh
    (dp2 -> dp1), so this schedule asserts finite-and-progressing, not
    bit-exactness (the smaller dp extent changes the reduction order —
    the restore itself is pinned bit-exact by test_mesh_checkpoint)."""
    schedule = ["nan", "enospc", "producer", "sigterm", "kill", "hang"]
    assert set(schedule) == set(FAULT_CLASSES)
    verdict = run_chaos(workdir, schedule, devices=2, verbose=False)
    assert verdict["completed"], verdict
    for fault in schedule:
        assert verdict["faults"][fault]["recovered"], verdict["faults"]
    # Documented exit codes: preemption 75, SIGKILL signal-death, hang 76.
    assert verdict["faults"]["sigterm"]["rc"] == 75
    assert verdict["faults"]["hang"]["rc"] == HANG_EXIT_CODE
    assert verdict["faults"]["hang"]["degraded_to_devices"] == 1
    assert verdict["mesh_degraded"] is True
    assert verdict["final_finite"] is True
    # MTTR is a number per stopping fault class, not a hope.
    assert set(verdict["mttr_s"]) == {"sigterm", "kill", "hang"}
    assert all(0 < v < 300 for v in verdict["mttr_s"].values())
    assert verdict["train_recovery_s"] is not None
    assert verdict["ok"], verdict


def test_promote_chaos_continuous_train_serve_loop(workdir):
    """ISSUE 13 acceptance, end to end with zero intervention: a REAL
    trainer run publishes checkpoints while a 2-replica pool serves
    continuous loadtest traffic and the promotion-daemon CLI (its own
    process) drives the loop. Through one run: the trainer SIGKILLed
    mid-publish (torn window — the marker protocol keeps the watcher
    blind) and resumed; the daemon's first staged candidate corrupted
    (``corrupt_candidate_at``) and rejected pre-publish; the daemon
    itself SIGKILLed after its first promoted row and restarted with no
    outcome change (journal replay — no double-promote, no skipped
    candidate); >= 3 clean automatic promotions; one forced
    post-promotion regression (``regress_after_promote`` -> NaN logits
    on live traffic) rolled back automatically to the prior digest; p99
    verdict PASS with ZERO failed requests through every swap; and the
    run's own telemetry mined into a non-empty replay manifest."""
    from tools.chaos_train import run_promote_chaos

    verdict = run_promote_chaos(workdir, verbose=False)
    assert verdict["trainer_completed"], verdict
    assert verdict.get("trainer_killed_mid_publish"), verdict
    assert verdict.get("daemon_killed_mid_run"), verdict
    assert verdict["promotions"] >= 3, verdict
    assert verdict["corrupt_rejected"] >= 1, verdict
    assert verdict["rollback_seen"] and verdict["rollback_to_lkg"], verdict
    assert verdict["double_promoted"] == [], verdict
    assert verdict["loadtest_offered"] > 0
    assert verdict["loadtest_failed"] == 0, verdict
    assert verdict["loadtest_slo_pass"], verdict
    assert verdict["mined_episodes"] > 0, verdict
    assert verdict["ok"], verdict


def test_chaos_exact_path_schedule_is_bitexact_vs_unfaulted_twin(workdir):
    """Preemption + worker-kill + ENOSPC recoveries REPLAY the same
    trajectory: final params bit-exact vs an unfaulted twin run (the
    async-write x emergency-write fence and the seed fast-forward are
    exactly what this proves end-to-end)."""
    verdict = run_chaos(
        workdir, ["enospc", "sigterm", "kill"], devices=1,
        baseline=True, verbose=False,
    )
    assert verdict["completed"], verdict
    assert verdict["bitexact_vs_baseline"] is True
    assert verdict["mesh_degraded"] is False
    assert verdict["ok"], verdict


def test_dispatcher_resumes_watchdog_hang_on_smaller_mesh_e2e(
    workdir, tmp_path
):
    """The real supervision loop end-to-end: a deterministically wedged
    dispatch inside the real CLI is detected by the watchdog WITHIN its
    deadline, the process exits with the distinct requeue-degraded code
    carrying a full thread-stack diagnostic, and the dispatcher resumes
    the SAME experiment on the next-smaller virtual mesh from the last
    valid checkpoint to completion — zero human intervention."""
    cfg_path = tiny_config(workdir, "chaos_disp", devices=2)
    cfg_dir = tmp_path / "experiment_config"
    cfg_dir.mkdir(exist_ok=True)
    os.replace(cfg_path, str(cfg_dir / "chaos_disp.json"))
    exp_dir = os.path.join(workdir, "chaos_disp")

    env = dict(os.environ)
    env["DATASET_DIR"] = workdir
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # Wedge the dispatch of iteration 4 — after epoch 1's checkpoint.
    env["MAML_FAULTS"] = "hang_at_iter=3"
    env["MAML_DISPATCH_ENTRY"] = os.path.join(REPO, "train_maml_system.py")

    proc = subprocess.run(
        [sys.executable, "-u",
         os.path.join(REPO, "train_maml_system_dispatch.py"), "chaos_disp"],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=420, check=False,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)

    # The watchdog left its diagnostic: a full thread-stack dump naming
    # the wedged iteration, and a 'hang' telemetry event with the
    # distinct exit code, fired within the configured deadline.
    stacks = open(os.path.join(exp_dir, "logs", "hang_stacks.txt")).read()
    assert "iteration 4" in stacks
    assert "Thread" in stacks or "thread" in stacks
    events = [
        json.loads(line)
        for line in open(os.path.join(exp_dir, "logs", "telemetry.jsonl"))
        if line.strip()
    ]
    hangs = [e for e in events if e["type"] == "hang"]
    assert len(hangs) == 1
    assert hangs[0]["exit_code"] == HANG_EXIT_CODE
    assert hangs[0]["iter"] == 4
    assert hangs[0]["elapsed_s"] >= hangs[0]["deadline_s"]
    assert hangs[0]["elapsed_s"] < 10 * hangs[0]["deadline_s"]

    # The dispatcher's audit trail: degraded dp2 -> dp1 on the hang code.
    audit = open(
        os.path.join(exp_dir, "logs", "interruptions.csv")
    ).read()
    assert "hang-degrade:dp2->dp1" in audit
    assert "--- chaos_disp: hang (rc 76)" in proc.stdout

    # The degraded resume picked up from the last VALID checkpoint (epoch
    # 1, iter 2 — the wedged iteration never published) and ran to the
    # test eval with finite params.
    assert os.path.exists(os.path.join(exp_dir, "logs", "test_summary.csv"))
    latest = os.path.join(exp_dir, "saved_models", "train_model_latest")
    with np.load(latest) as archive:
        state = json.loads(bytes(archive["__experiment_state__"]).decode())
        leaves = {
            k: archive[k] for k in archive.files if k.startswith("leaf_")
        }
    assert state["current_iter"] == 6
    for key, leaf in leaves.items():
        assert np.isfinite(np.asarray(leaf, np.float64)).all(), key
    loads = [e for e in events if e["type"] == "checkpoint_load"]
    assert any(e.get("path") == "train_model_latest" for e in loads) or loads


def test_killhost_two_process_fleet_survives_losing_a_host(
    workdir, multihost_cpu_guard
):
    """The pod-scale acceptance gate (ISSUE 11): a 2-process CPU fleet
    driven through the real dispatcher CLI survives SIGKILL of one worker
    mid-epoch with zero intervention — the supervisor observes the host
    loss, coordinates shutdown of the survivor, writes a host-attributed
    audit row stamped with the observed death time, auto-resumes DEGRADED
    on the surviving process from the last published checkpoint
    (mesh-portable; rank 0 was the single writer), completes training +
    test eval, and the recovery is a measured number."""
    from tools.chaos_train import run_killhost_chaos

    verdict = run_killhost_chaos(workdir, verbose=False)
    assert verdict["completed"], verdict
    assert verdict["dispatcher_rc"] == 0, verdict
    assert verdict["host_loss_audit_rows"], verdict
    assert verdict["degraded_to_one_process"], verdict
    assert verdict["multihost_recovery_s"] is not None
    assert 0 < verdict["multihost_recovery_s"] < 300
    assert verdict["final_finite"] is True
    assert verdict["ok"], verdict
