"""End-to-end fault-injection tests for the fault-tolerant runtime
(ISSUE 3): every recovery pillar is proven against the REAL
``ExperimentBuilder`` loop with deterministic injected failures —

(a) resume with a truncated ``latest`` quarantines the corrupt files and
    falls back to the newest valid epoch checkpoint;
(b) SIGTERM mid-epoch writes an emergency checkpoint + requeue exit code,
    and the resumed run matches the uninterrupted run bit-exactly in
    params and task-seed sequence;
(c) an injected NaN meta-loss halts with a typed error / is skipped
    on-device / triggers checkpoint rollback, per ``--on_nonfinite``;
(d) write-retry budget semantics live in ``test_checkpoint_integrity.py``.

All tests are tiny CPU runs (2 epochs x 2 iters, 4-filter net); learners
are cached per config so the XLA programs compile once for the module."""

import json
import os

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.data import MetaLearningSystemDataLoader
from howtotrainyourmamlpytorch_tpu.experiment_builder import (
    REQUEUE_EXIT_CODE,
    ExperimentBuilder,
    NonFiniteLossError,
)
from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner
from howtotrainyourmamlpytorch_tpu.models.common import (
    discard_nonfinite_update,
    nonfinite_flag,
)
from howtotrainyourmamlpytorch_tpu.utils import faultinject, storage
from howtotrainyourmamlpytorch_tpu.utils.parser_utils import args_to_maml_config

from test_data import make_args, make_dataset_dir


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.deactivate()
    yield
    faultinject.reset()


@pytest.fixture
def dataset_env(tmp_path, monkeypatch):
    make_dataset_dir(tmp_path / "omniglot_mini")
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    return tmp_path


def _exp_args(tmp_path, name="exp", **overrides):
    base = dict(
        experiment_name=str(tmp_path / name),
        seed=104, continue_from_epoch="latest", max_models_to_save=5,
        total_epochs=2, total_iter_per_epoch=2, total_epochs_before_pause=100,
        num_evaluation_tasks=4, evaluate_on_test_set_only=False, batch_size=2,
        model="maml++",
        num_stages=2, cnn_num_filters=4, conv_padding=True, max_pooling=True,
        norm_layer="batch_norm", per_step_bn_statistics=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        num_classes_per_set=5, second_order=False,
        first_order_to_second_order_epoch=-1,
        use_multi_step_loss_optimization=True, multi_step_loss_num_epochs=2,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        enable_inner_loop_optimizable_bn_params=False,
        learnable_bn_gamma=True, learnable_bn_beta=True,
        meta_learning_rate=0.001, min_learning_rate=1e-5,
        task_learning_rate=0.1, init_inner_loop_learning_rate=0.1,
    )
    base.update(overrides)
    return make_args(tmp_path, **base)


#: Config -> learner cache: the compiled XLA step programs are reused by
#: every builder in this module (one compile per distinct MAMLConfig).
_LEARNERS: dict = {}


def _learner(args) -> MAMLFewShotLearner:
    cfg = args_to_maml_config(args)
    if cfg not in _LEARNERS:
        _LEARNERS[cfg] = MAMLFewShotLearner(cfg)
    return _LEARNERS[cfg]


def _builder(args, data=MetaLearningSystemDataLoader) -> ExperimentBuilder:
    return ExperimentBuilder(args=args, data=data, model=_learner(args),
                             device=None)


def _ckpt(path):
    """Raw (leaf arrays, experiment state) straight from the npz."""
    with np.load(path) as archive:
        leaves = {k: archive[k] for k in archive.files if k.startswith("leaf_")}
        state = json.loads(bytes(archive["__experiment_state__"]).decode())
    return leaves, state


class RecordingLoader(MetaLearningSystemDataLoader):
    """Records the per-batch episode-seed arrays the train loop consumes —
    the task-seed sequence of the run."""

    records: list = []

    def get_train_batches(self, **kwargs):
        for batch in super().get_train_batches(**kwargs):
            type(self).records.append(np.asarray(batch[4]).copy())
            yield batch


# ---------------------------------------------------------------------------
# Harness unit behavior
# ---------------------------------------------------------------------------


def test_fault_plan_from_env(monkeypatch):
    faultinject.reset()
    monkeypatch.setenv(faultinject.ENV_VAR, "nan_at_iter=5; fail_next_writes=2")
    plan = faultinject.current_plan()
    assert plan.nan_at_iter == 5
    assert plan.fail_next_writes == 2
    faultinject.reset()
    monkeypatch.setenv(faultinject.ENV_VAR, "explode_reactor=1")
    with pytest.raises(ValueError, match="unknown fault"):
        faultinject.current_plan()
    faultinject.reset()
    monkeypatch.delenv(faultinject.ENV_VAR)
    assert faultinject.current_plan() is None


def test_poison_batch_is_targeted_and_one_shot():
    xs = np.zeros((2, 3), np.float32)
    sample = (xs, xs.copy(), np.zeros(2, np.int32), np.zeros(2, np.int32), 7)
    faultinject.activate(faultinject.FaultPlan(nan_at_iter=3))
    same = faultinject.poison_batch(sample, 2)
    assert same is sample  # wrong iteration: untouched
    poisoned = faultinject.poison_batch(sample, 3)
    assert np.isnan(poisoned[1]).all()
    assert not np.isnan(poisoned[0]).any()  # support images untouched
    assert faultinject.events == ["nan:3"]
    assert faultinject.poison_batch(sample, 3) is sample  # consumed


def test_sentinel_device_helpers():
    assert float(nonfinite_flag(np.float32(1.0), np.ones(3))) == 0.0
    assert float(nonfinite_flag(np.float32(np.nan))) == 1.0
    assert float(nonfinite_flag(np.array([1.0, np.inf]))) == 1.0
    new = {"w": np.ones(2, np.float32), "i": np.int32(5)}
    old = {"w": np.zeros(2, np.float32), "i": np.int32(4)}
    kept = discard_nonfinite_update(nonfinite_flag(np.float32(np.nan)), new, old)
    np.testing.assert_array_equal(np.asarray(kept["w"]), old["w"])
    taken = discard_nonfinite_update(nonfinite_flag(np.float32(2.0)), new, old)
    np.testing.assert_array_equal(np.asarray(taken["w"]), new["w"])


# ---------------------------------------------------------------------------
# Pillar (b): preemption-safe shutdown + bit-exact resume
# ---------------------------------------------------------------------------


def test_sigterm_emergency_checkpoint_and_bitexact_resume(dataset_env):
    tmp = dataset_env
    latest_a = str(tmp / "exp_a" / "saved_models" / "train_model_latest")
    latest_b = str(tmp / "exp_b" / "saved_models" / "train_model_latest")

    # Run A: uninterrupted 2 epochs (pause exits cleanly at the end).
    RecordingLoader.records = seeds_a = []
    builder_a = _builder(
        _exp_args(tmp, "exp_a", total_epochs_before_pause=2),
        data=RecordingLoader,
    )
    with pytest.raises(SystemExit) as exit_a:
        builder_a.run_experiment()
    assert exit_a.value.code is None  # clean pause, not the requeue code
    leaves_a, state_a = _ckpt(latest_a)
    assert state_a["current_iter"] == 4

    # Run B: SIGTERM delivered right after iteration 3 (mid-epoch 2).
    RecordingLoader.records = seeds_b = []
    faultinject.activate(faultinject.FaultPlan(sigterm_at_iter=3))
    builder_b = _builder(_exp_args(tmp, "exp_b"), data=RecordingLoader)
    with pytest.raises(SystemExit) as exit_b:
        builder_b.run_experiment()
    assert exit_b.value.code == REQUEUE_EXIT_CODE
    assert faultinject.events == ["sigterm:3"]
    _, state_mid = _ckpt(latest_b)
    assert state_mid["current_iter"] == 3  # at most one dispatch "lost"
    interruptions = storage.load_statistics(
        str(tmp / "exp_b" / "logs"), filename="interruptions.csv"
    )
    assert interruptions["current_iter"] == ["3"]
    faultinject.deactivate()

    # Run B2: requeue (the resume command the exit code asks for).
    RecordingLoader.records = seeds_b2 = []
    builder_b2 = _builder(
        _exp_args(tmp, "exp_b", total_epochs_before_pause=1),
        data=RecordingLoader,
    )
    assert builder_b2.state["current_iter"] == 3
    with pytest.raises(SystemExit):
        builder_b2.run_experiment()

    # Interrupted-then-resumed == uninterrupted: bit-exact params AND the
    # identical CONSUMED task-seed sequence (B consumed windows 0-2, B2
    # window 3). The device-prefetch stager legitimately PULLS ahead of
    # consumption, so the loader-yield records are a prefix-superset of the
    # consumed windows: B's consumed prefix is its first 3 windows, B2's
    # its first 1 — anything beyond was staged, abandoned at shutdown, and
    # (proven by the bit-exact params above) never trained on.
    leaves_b, state_b = _ckpt(latest_b)
    assert state_b["current_iter"] == 4
    assert set(leaves_b) == set(leaves_a)
    for key in leaves_a:
        np.testing.assert_array_equal(leaves_a[key], leaves_b[key])
    consumed_b = np.concatenate(seeds_b)[: 3 * builder_b.args.batch_size]
    consumed_b2 = np.concatenate(seeds_b2)[: 1 * builder_b2.args.batch_size]
    np.testing.assert_array_equal(
        np.concatenate(seeds_a)[: 4 * builder_a.args.batch_size],
        np.concatenate([consumed_b, consumed_b2]),
    )


def test_shutdown_flag_honored_in_stateless_eval_phase(dataset_env):
    """A SIGTERM during the test-ensemble phase (where state holds a
    RELOADED old checkpoint) must exit promptly with the requeue code and
    must NOT write an emergency checkpoint over ``latest``."""
    import signal as _signal

    tmp = dataset_env
    builder = _builder(_exp_args(tmp))
    builder._shutdown_signum = int(_signal.SIGTERM)
    with pytest.raises(SystemExit) as exits:
        builder._maybe_emergency_exit(write_checkpoint=False)
    assert exits.value.code == REQUEUE_EXIT_CODE
    assert os.listdir(str(tmp / "exp" / "saved_models")) == []
    interruptions = storage.load_statistics(
        str(tmp / "exp" / "logs"), filename="interruptions.csv"
    )
    assert interruptions["signal"] == [str(int(_signal.SIGTERM))]


def test_legacy_csv_header_alignment_on_resume(dataset_env):
    """Resuming an experiment whose summary CSV predates this build (no
    trips/step-time columns) must append rows aligned to the FILE's header
    instead of silently shifting every column after the mismatch."""
    tmp = dataset_env
    with pytest.raises(SystemExit):
        _builder(_exp_args(tmp, total_epochs_before_pause=1)).run_experiment()
    logs = str(tmp / "exp" / "logs")
    csv_path = os.path.join(logs, "summary_statistics.csv")
    with open(csv_path) as f:
        rows = [line.rstrip("\n").split(",") for line in f]
    dropped = ("train_nonfinite_trips", "train_step_time_p50",
               "train_step_time_p95")
    keep = [i for i, col in enumerate(rows[0]) if col not in dropped]
    assert len(keep) < len(rows[0])  # the simulated legacy header is smaller
    with open(csv_path, "w") as f:
        for row in rows:
            f.write(",".join(row[i] for i in keep) + "\n")
    legacy_header = [rows[0][i] for i in keep]

    with pytest.raises(SystemExit):
        _builder(_exp_args(tmp, total_epochs_before_pause=1)).run_experiment()
    stats = storage.load_statistics(logs)
    assert list(stats.keys()) == legacy_header
    assert stats["epoch"] == ["1", "2"]
    assert [len(v) for v in stats.values()] == [2] * len(legacy_header)


# ---------------------------------------------------------------------------
# Pillar (a): corrupt-latest fallback on resume
# ---------------------------------------------------------------------------


def test_corrupt_latest_falls_back_to_newest_valid_epoch(dataset_env):
    tmp = dataset_env
    saved = str(tmp / "exp" / "saved_models")

    # Phase 1: one clean epoch -> valid train_model_1 (+ latest alias).
    with pytest.raises(SystemExit):
        _builder(_exp_args(tmp, total_epochs_before_pause=1)).run_experiment()

    # Phase 2: second epoch, but its checkpoint write is truncated at byte
    # 100 (bit-rot / torn write that the atomic rename cannot guard).
    faultinject.activate(faultinject.FaultPlan(truncate_checkpoint_at=100))
    with pytest.raises(SystemExit):
        _builder(_exp_args(tmp, total_epochs_before_pause=1)).run_experiment()
    assert faultinject.events == ["truncate:train_model_2@100"]
    faultinject.deactivate()

    # Phase 3: resume degrades gracefully — latest (and its hardlinked
    # epoch-2 file) are quarantined, epoch 1 loads, the run completes.
    builder = _builder(_exp_args(tmp))
    assert builder.state["current_iter"] == 2  # resumed from epoch 1
    assert os.path.exists(os.path.join(saved, "train_model_latest.corrupt"))
    assert os.path.exists(os.path.join(saved, "train_model_2.corrupt"))
    assert not os.path.exists(os.path.join(saved, "train_model_latest"))
    test_losses = builder.run_experiment()
    assert 0.0 <= test_losses["test_accuracy_mean"] <= 1.0
    # Epoch 2 was re-trained and re-checkpointed validly this time.
    _, state = _ckpt(os.path.join(saved, "train_model_2"))
    assert state["current_iter"] == 4


def test_explicit_epoch_resume_propagates_typed_corruption(dataset_env):
    """``--continue_from_epoch <int>`` on a corrupt file must raise the
    typed error (the user named that exact checkpoint: no silent
    fallback), not an opaque zipfile error."""
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        CheckpointCorruptError,
    )

    tmp = dataset_env
    with pytest.raises(SystemExit):
        _builder(_exp_args(tmp, total_epochs_before_pause=1)).run_experiment()
    path = str(tmp / "exp" / "saved_models" / "train_model_1")
    with open(path, "r+b") as f:
        f.truncate(64)
    with pytest.raises(CheckpointCorruptError):
        _builder(_exp_args(tmp, continue_from_epoch=1))


# ---------------------------------------------------------------------------
# Pillar (c): divergence sentinel policies
# ---------------------------------------------------------------------------


def test_sentinel_halt_raises_before_any_checkpoint(dataset_env):
    tmp = dataset_env
    faultinject.activate(faultinject.FaultPlan(nan_at_iter=1))
    builder = _builder(_exp_args(tmp))  # --on_nonfinite defaults to halt
    with pytest.raises(NonFiniteLossError, match="halt"):
        builder.run_experiment()
    assert faultinject.events == ["nan:1"]
    # The poisoned epoch reached NO checkpoint and NO stats row.
    assert os.listdir(str(tmp / "exp" / "saved_models")) == []
    assert not os.path.exists(
        str(tmp / "exp" / "logs" / "summary_statistics.csv")
    )


def test_sentinel_skip_discards_update_and_counts_trip(dataset_env):
    tmp = dataset_env
    faultinject.activate(faultinject.FaultPlan(nan_at_iter=1))
    builder = _builder(_exp_args(tmp, on_nonfinite="skip"))
    test_losses = builder.run_experiment()
    assert 0.0 <= test_losses["test_accuracy_mean"] <= 1.0
    leaves, state = _ckpt(
        str(tmp / "exp" / "saved_models" / "train_model_latest")
    )
    for key, leaf in leaves.items():
        assert np.isfinite(np.asarray(leaf, np.float64)).all(), key
    assert state["nonfinite_trips_total"] == 1.0
    # Trips are counted in the metrics dicts -> per-epoch stats + CSV.
    assert state["per_epoch_statistics"]["train_nonfinite_trips"] == [1.0, 0.0]
    stats = storage.load_statistics(str(tmp / "exp" / "logs"))
    assert stats["train_nonfinite_trips"] == ["1.0", "0.0"]
    # The masked epoch summary stays finite despite the NaN loss sample.
    assert np.isfinite(float(stats["train_loss_mean"][0]))


def test_sigterm_during_poisoned_epoch_never_checkpoints_nan(dataset_env):
    """Sentinel x preemption interaction: a SIGTERM landing between a NaN
    dispatch and its detection point must not persist the poisoned state
    over the newest valid checkpoint. Under halt the shutdown path raises
    the typed error instead of exiting with the requeue code."""
    tmp = dataset_env
    faultinject.activate(
        faultinject.FaultPlan(nan_at_iter=2, sigterm_at_iter=3)
    )
    builder = _builder(_exp_args(tmp))  # halt policy (default)
    with pytest.raises(NonFiniteLossError, match="poisoned"):
        builder.run_experiment()
    # latest is still epoch 1's valid checkpoint, not the NaN state.
    leaves, state = _ckpt(
        str(tmp / "exp" / "saved_models" / "train_model_latest")
    )
    assert state["current_iter"] == 2
    for key, leaf in leaves.items():
        assert np.isfinite(np.asarray(leaf, np.float64)).all(), key


def test_sentinel_rollback_reloads_and_fastforwards_data(dataset_env):
    tmp = dataset_env
    # Poison the first iteration of epoch 2: epoch 1's checkpoint exists,
    # the poisoned update then propagates NaN through iteration 3, and the
    # boundary sentinel rolls back to epoch 1 with a shifted seed window.
    faultinject.activate(faultinject.FaultPlan(nan_at_iter=2))
    builder = _builder(_exp_args(tmp, on_nonfinite="rollback"))
    test_losses = builder.run_experiment()
    assert 0.0 <= test_losses["test_accuracy_mean"] <= 1.0
    assert faultinject.events == ["nan:2"]
    leaves, state = _ckpt(
        str(tmp / "exp" / "saved_models" / "train_model_2")
    )
    for key, leaf in leaves.items():
        assert np.isfinite(np.asarray(leaf, np.float64)).all(), key
    assert state["current_iter"] == 4
    assert state["nonfinite_rollbacks"] == 1
    assert state["nonfinite_trips_total"] == 2.0  # iters 2 and 3 tripped
    # Exactly one stats row per epoch: the poisoned epoch never reached the
    # CSV, only its clean replay did.
    stats = storage.load_statistics(str(tmp / "exp" / "logs"))
    assert len(stats["epoch"]) == 2


# ---------------------------------------------------------------------------
# ISSUE 10: async checkpointing x preemption — the exit-path fence
# ---------------------------------------------------------------------------


def test_sigterm_with_async_epoch_write_in_flight_fences_then_bitexact(
    dataset_env, monkeypatch
):
    """SIGTERM arriving while the async checkpoint writer is mid-flight:
    the emergency ``latest`` write must WAIT for (fence) the in-flight
    epoch write — no torn archive, no stale alias clobbering the newer
    emergency state — and kill-and-resume stays bit-exact.

    The in-flight window is forced deterministically: the background half
    of every checkpoint write is slowed by ~1s, so epoch 1's async write
    (submitted at the iter-2 boundary) is still in flight when the
    injected SIGTERM lands after iter 3."""
    import time as _time

    import howtotrainyourmamlpytorch_tpu.utils.checkpoint as ckpt

    tmp = dataset_env
    real_write = ckpt.write_snapshot

    def slow_write(path, snapshot, **kw):
        _time.sleep(1.0)
        return real_write(path, snapshot, **kw)

    monkeypatch.setattr(ckpt, "write_snapshot", slow_write)

    # Run A: uninterrupted twin (same slow writer; params unaffected).
    with pytest.raises(SystemExit) as exit_a:
        _builder(
            _exp_args(tmp, "exp_a", total_epochs_before_pause=2)
        ).run_experiment()
    assert exit_a.value.code is None
    leaves_a, state_a = _ckpt(
        str(tmp / "exp_a" / "saved_models" / "train_model_latest")
    )
    assert state_a["current_iter"] == 4

    # Run B: SIGTERM after iter 3, epoch-1 async write still in flight.
    faultinject.activate(faultinject.FaultPlan(sigterm_at_iter=3))
    builder_b = _builder(_exp_args(tmp, "exp_b"))
    with pytest.raises(SystemExit) as exit_b:
        builder_b.run_experiment()
    assert exit_b.value.code == REQUEUE_EXIT_CODE
    faultinject.deactivate()

    saved_b = str(tmp / "exp_b" / "saved_models")
    # The fenced ordering held: the epoch-1 archive fully published (valid
    # manifest, iter 2), and ``latest`` is the NEWER emergency state (iter
    # 3) — not the async alias of epoch 1, and not a torn write.
    _, state_epoch1 = _ckpt(os.path.join(saved_b, "train_model_1"))
    assert state_epoch1["current_iter"] == 2
    _, state_latest = _ckpt(os.path.join(saved_b, "train_model_latest"))
    assert state_latest["current_iter"] == 3
    assert not os.path.exists(
        os.path.join(saved_b, "train_model_latest.tmp")
    )

    # Kill-and-resume is bit-exact vs the uninterrupted twin.
    builder_b2 = _builder(
        _exp_args(tmp, "exp_b", total_epochs_before_pause=1)
    )
    assert builder_b2.state["current_iter"] == 3
    with pytest.raises(SystemExit):
        builder_b2.run_experiment()
    leaves_b, state_b = _ckpt(os.path.join(saved_b, "train_model_latest"))
    assert state_b["current_iter"] == 4
    assert set(leaves_b) == set(leaves_a)
    for key in leaves_a:
        np.testing.assert_array_equal(leaves_a[key], leaves_b[key])


def test_checkpoint_interval_cadence_bounds_rpo(dataset_env):
    """``--checkpoint_interval_s``: a time-based mid-epoch write of the
    full resume-compatible state to ``train_model_latest`` — a crash/kill
    then loses at most the cadence, not the whole epoch. With a ~0
    interval, every non-boundary dispatch writes one (iters 1 and 3 of
    the 2x2 run); the write goes through the async writer and is
    resume-loadable."""
    tmp = dataset_env
    builder = _builder(
        _exp_args(tmp, total_epochs_before_pause=2,
                  checkpoint_interval_s=1e-4)
    )
    with pytest.raises(SystemExit) as exits:
        builder.run_experiment()
    assert exits.value.code is None  # clean pause
    events = [
        json.loads(line)
        for line in open(str(tmp / "exp" / "logs" / "telemetry.jsonl"))
        if line.strip()
    ]
    intervals = [e for e in events if e["type"] == "checkpoint_interval"]
    assert [e["iter"] for e in intervals] == [1, 3]
    # The final latest is the epoch-2 alias (published after iter 4).
    _, state = _ckpt(
        str(tmp / "exp" / "saved_models" / "train_model_latest")
    )
    assert state["current_iter"] == 4

    # The interval write itself is the emergency-write form: resume-
    # compatible, through the async writer.
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        AsyncCheckpointWriter,
    )

    builder2 = _builder(_exp_args(tmp, name="exp2"))
    builder2._ckpt_writer = AsyncCheckpointWriter()
    try:
        builder2._interval_checkpoint()
        builder2._ckpt_writer.drain()
    finally:
        builder2._ckpt_writer.close()
        builder2._ckpt_writer = None
    _, state2 = _ckpt(
        str(tmp / "exp2" / "saved_models" / "train_model_latest")
    )
    assert state2["current_iter"] == 0
    resumed = _builder(_exp_args(tmp, name="exp2"))
    assert resumed.state["current_iter"] == 0
