"""Two-process ``jax.distributed`` bring-up (VERDICT r3 next #7).

Real multi-host hardware is unavailable here, but the multi-host wiring in
``parallel/distributed.py`` is still testable: two forked CPU processes —
one coordinator, one worker — each with 2 virtual devices, must come up as
ONE global runtime of 2 processes x 2 local devices = 4 global devices.
This is the first executed evidence that ``initialize_distributed`` passes
the right arguments through to ``jax.distributed.initialize`` and that the
opt-in env-var path composes with the platform forcing.

The reference has no multi-node backend at all (no ``torch.distributed``
anywhere — SURVEY §2); this subsystem is a TPU-framework extension.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# argv[1] = coordinator address, argv[2] = process id, argv[3] = mode
# (args | env). Asserts the global runtime spans both processes.
WORKER_SRC = textwrap.dedent(
    """
    import os, sys

    # Platform retarget WITHOUT a device probe: jax.distributed.initialize
    # must run before anything initializes the XLA backend.
    from howtotrainyourmamlpytorch_tpu.utils.platform import force_virtual_cpu_env

    force_virtual_cpu_env(2)

    from howtotrainyourmamlpytorch_tpu.parallel import initialize_distributed

    addr, pid, mode = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    if mode == "env":
        os.environ["JAX_COORDINATOR_ADDRESS"] = addr
        os.environ["JAX_NUM_PROCESSES"] = "2"
        initialize_distributed(process_id=pid)
    else:
        initialize_distributed(
            coordinator_address=addr, num_processes=2, process_id=pid
        )

    import jax

    assert jax.process_count() == 2, jax.process_count()
    assert jax.local_device_count() == 2, jax.local_device_count()
    assert jax.device_count() == 4, jax.device_count()
    print("DISTRIBUTED_OK", pid, jax.device_count())
    """
)


def _free_port() -> int:
    """A free localhost port — or a skip if this sandbox has no sockets.

    The bind here doubles as the capability probe: once it succeeds,
    loopback networking provably works, so a later bring-up hang is a REAL
    failure (deadlocked initialize), not an environment artifact."""
    try:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]
    except OSError as e:
        pytest.skip(f"loopback sockets unavailable in this sandbox: {e}")


def _clean_env():
    env = dict(os.environ)
    # The workers must opt in via their own explicit signal only.
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.pop("JAX_NUM_PROCESSES", None)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count (2, not 8)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.parametrize("mode", ["args", "env"])
def test_two_process_cpu_bringup(tmp_path, mode):
    addr = f"127.0.0.1:{_free_port()}"
    script = tmp_path / "distributed_worker.py"
    script.write_text(WORKER_SRC)
    env = _clean_env()
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), addr, str(pid), mode],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            cwd=REPO,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out)
    except subprocess.TimeoutExpired:
        partial = []
        for p in procs:
            p.kill()
            out, _ = p.communicate()
            partial.append(out)
        # Loopback provably works (_free_port bound a socket), so a hang IS
        # the failure class this test exists to catch — a deadlocked
        # bring-up must not report as a green skip.
        pytest.fail(
            "distributed bring-up deadlocked (120 s):\n"
            + "\n---\n".join(partial)
        )
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    for pid, out in enumerate(outs):
        assert f"DISTRIBUTED_OK {pid} 4" in out, out
