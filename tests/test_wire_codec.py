"""uint8 wire-format tests (models/common.WireCodec, --transfer_dtype uint8).

The codec must be BIT-EXACT: decoded device images identical to what the
float32 wire carries, so golden runs and parity tests hold regardless of the
wire format. Also covers the deferred-normalization host pipeline the codec
requires for RGB datasets (axon-tunnel leak mitigation, PERF_NOTES.md).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models.common import (
    WireCodec,
    decode_images,
    encode_images,
    prepare_batch,
    wire_codec_for,
)
from howtotrainyourmamlpytorch_tpu.data.augment import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    augment_image,
)
from howtotrainyourmamlpytorch_tpu.models import (
    MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
    args_to_maml_config,
)

from test_data import make_args, make_dataset_dir


# ---------------------------------------------------------------------------
# Codec selection
# ---------------------------------------------------------------------------


def _args(tmp_path, **kw):
    return make_args(tmp_path, **kw)


def test_codec_selection(tmp_path):
    a = _args(tmp_path, transfer_dtype="uint8", dataset_name="omniglot_dataset")
    assert wire_codec_for(a) == WireCodec(1.0, None, None)

    a = _args(tmp_path, transfer_dtype="uint8",
              dataset_name="mini_imagenet_full_size")
    codec = wire_codec_for(a)
    assert codec.scale == 255.0
    np.testing.assert_allclose(codec.mean, IMAGENET_MEAN)

    a = _args(tmp_path, transfer_dtype="uint8", dataset_name="cifar100",
              classification_mean=[0.5, 0.5, 0.5],
              classification_std=[0.25, 0.25, 0.25])
    assert wire_codec_for(a).std == (0.25, 0.25, 0.25)

    # float32 wire or unknown dataset -> no codec
    assert wire_codec_for(_args(tmp_path, dataset_name="omniglot_dataset")) is None
    assert wire_codec_for(
        _args(tmp_path, transfer_dtype="uint8", dataset_name="quickdraw")
    ) is None


# ---------------------------------------------------------------------------
# Bit-exact roundtrip
# ---------------------------------------------------------------------------


def test_roundtrip_binary_images_exact():
    rng = np.random.RandomState(0)
    x = rng.randint(0, 2, (4, 1, 28, 28)).astype(np.float32)  # omniglot 0/1
    codec = WireCodec(1.0, None, None)
    wire = encode_images(x, codec)
    assert wire.dtype == np.uint8
    decoded = np.asarray(decode_images(jnp.asarray(wire), codec, jnp.float32))
    np.testing.assert_array_equal(decoded, x)


def test_roundtrip_rgb255_with_device_norm_exact():
    """k/255 pixels + deferred normalization == host float32 normalization,
    bitwise (same f32 op order: /255 then (x-mean)/std)."""
    rng = np.random.RandomState(1)
    k = rng.randint(0, 256, (3, 3, 8, 8)).astype(np.float32)
    host = k / 255.0  # what the deferred host pipeline ships
    mean = IMAGENET_MEAN.reshape(-1, 1, 1)
    std = IMAGENET_STD.reshape(-1, 1, 1)
    host_normalized = (host - mean) / std  # float32-wire reference values

    codec = WireCodec(
        255.0, tuple(IMAGENET_MEAN.tolist()), tuple(IMAGENET_STD.tolist())
    )
    wire = encode_images(host, codec)
    np.testing.assert_array_equal(wire, k.astype(np.uint8))  # exact k recovery
    decoded = np.asarray(decode_images(jnp.asarray(wire), codec, jnp.float32))
    np.testing.assert_array_equal(decoded, host_normalized.astype(np.float32))


def test_prepare_batch_uint8_wire():
    rng = np.random.RandomState(2)
    xs = rng.randint(0, 2, (2, 5, 1, 1, 4, 4)).astype(np.float32)
    xt = rng.randint(0, 2, (2, 5, 2, 1, 4, 4)).astype(np.float32)
    ys = np.tile(np.arange(5)[None, :, None], (2, 1, 1))
    yt = np.tile(np.arange(5)[None, :, None], (2, 1, 2))
    codec = WireCodec(1.0, None, None)
    pxs, pxt, pys, pyt = prepare_batch((xs, xt, ys, yt), codec)
    assert pxs.dtype == np.uint8 and pxt.dtype == np.uint8
    assert pxs.shape == (2, 5, 1, 4, 4) and pxt.shape == (2, 10, 1, 4, 4)
    # Same flattening as the float32 wire
    fxs, fxt, fys, fyt = prepare_batch((xs, xt, ys, yt))
    np.testing.assert_array_equal(pxs.astype(np.float32), fxs)
    np.testing.assert_array_equal(pys, fys)


# ---------------------------------------------------------------------------
# Deferred normalization host pipeline
# ---------------------------------------------------------------------------


def test_augment_defer_normalization_imagenet(tmp_path):
    args = _args(tmp_path, dataset_name="mini_imagenet_full_size")
    rng = np.random.RandomState(3)
    im = rng.randint(0, 256, (8, 8, 3)).astype(np.float32) / 255.0
    full = augment_image(im.copy(), k=0, channels=3, augment_bool=True,
                         args=args, dataset_name="mini_imagenet_full_size",
                         rng=rng)
    deferred = augment_image(im.copy(), k=0, channels=3, augment_bool=True,
                             args=args,
                             dataset_name="mini_imagenet_full_size",
                             rng=rng, defer_normalization=True)
    # deferred output is raw k/255 pixels; device normalization reproduces
    # the host-normalized values exactly
    mean = IMAGENET_MEAN.reshape(-1, 1, 1)
    std = IMAGENET_STD.reshape(-1, 1, 1)
    np.testing.assert_array_equal((deferred - mean) / std, full)


def test_augment_defer_normalization_cifar_rng_parity(tmp_path):
    """Dropping the normalize step must not shift the crop/flip RNG draws."""
    args = _args(tmp_path, dataset_name="cifar100",
                 classification_mean=[0.5, 0.5, 0.5],
                 classification_std=[0.25, 0.25, 0.25])
    im = np.random.RandomState(4).randint(0, 256, (32, 32, 3)).astype(
        np.float32
    ) / 255.0
    rng_a, rng_b = np.random.RandomState(7), np.random.RandomState(7)
    full = augment_image(im.copy(), k=0, channels=3, augment_bool=True,
                         args=args, dataset_name="cifar100", rng=rng_a)
    deferred = augment_image(im.copy(), k=0, channels=3, augment_bool=True,
                             args=args, dataset_name="cifar100", rng=rng_b,
                             defer_normalization=True)
    np.testing.assert_array_equal(
        (deferred - np.float32(0.5)) / np.float32(0.25), full
    )


# ---------------------------------------------------------------------------
# End-to-end: uint8 wire training == float32 wire training, bitwise
# ---------------------------------------------------------------------------


@pytest.fixture
def omniglot_env(tmp_path, monkeypatch):
    make_dataset_dir(tmp_path / "omniglot_mini")
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    return tmp_path


def _learner_args(tmp_path, **kw):
    return make_args(
        tmp_path,
        num_stages=2, cnn_num_filters=4, conv_padding=True, max_pooling=True,
        norm_layer="batch_norm", per_step_bn_statistics=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        num_classes_per_set=5, second_order=False,
        first_order_to_second_order_epoch=-1,
        use_multi_step_loss_optimization=True, multi_step_loss_num_epochs=3,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        enable_inner_loop_optimizable_bn_params=False,
        learnable_bn_gamma=True, learnable_bn_beta=True,
        meta_learning_rate=0.001, min_learning_rate=1e-5,
        task_learning_rate=0.1, init_inner_loop_learning_rate=0.1,
        total_epochs=2, total_iter_per_epoch=2,
        **kw,
    )


def test_uint8_wire_training_bitwise_identical(omniglot_env):
    rng = np.random.RandomState(5)
    xs = rng.randint(0, 2, (2, 5, 1, 1, 12, 12)).astype(np.float32)
    xt = rng.randint(0, 2, (2, 5, 1, 1, 12, 12)).astype(np.float32)
    ys = np.tile(np.arange(5)[None, :, None], (2, 1, 1)).astype(np.int32)
    yt = ys.copy()
    batch = (xs, xt, ys, yt)

    args_f32 = _learner_args(omniglot_env, image_height=12, image_width=12)
    args_u8 = _learner_args(omniglot_env, image_height=12, image_width=12,
                            transfer_dtype="uint8")
    lf = MAMLFewShotLearner(args_to_maml_config(args_f32))
    lu = MAMLFewShotLearner(args_to_maml_config(args_u8))
    assert lu.cfg.wire_codec == WireCodec(1.0, None, None)

    sf = lf.init_state(jax.random.PRNGKey(9))
    su = lu.init_state(jax.random.PRNGKey(9))
    for it in range(3):
        sf, mf = lf.run_train_iter(sf, batch, epoch=0)
        su, mu = lu.run_train_iter(su, batch, epoch=0)
        assert float(mf["loss"]) == float(mu["loss"]), f"iter {it}"
    for a, b in zip(jax.tree.leaves(sf), jax.tree.leaves(su)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # eval path decodes too
    _, ef, pf = lf.run_validation_iter(sf, batch)
    _, eu, pu = lu.run_validation_iter(su, batch)
    assert float(ef["loss"]) == float(eu["loss"])
    np.testing.assert_array_equal(np.asarray(pf), np.asarray(pu))


def test_on_device_rotation_training_bitwise_identical(omniglot_env):
    """--device_augment omniglot: training on raw-pixel episodes with the
    in-step rot90-by-gather is BIT-EXACT vs training on host-rotated
    episodes, over multiple iterations AND through the eval path — the
    on-device extension of the uint8-wire bit-exactness contract (a
    rotation is pure data movement; rotating 0/1 pixels is exact in any
    dtype)."""
    from howtotrainyourmamlpytorch_tpu.data import FewShotLearningDataset

    args_host = _learner_args(omniglot_env, transfer_dtype="uint8")
    args_dev = _learner_args(omniglot_env, transfer_dtype="uint8",
                             device_augment=True)
    ds_host = FewShotLearningDataset(args_host)
    ds_dev = FewShotLearningDataset(args_dev)
    lh = MAMLFewShotLearner(args_to_maml_config(args_host))
    ld = MAMLFewShotLearner(args_to_maml_config(args_dev))
    assert ld.cfg.device_augment is not None
    assert lh.cfg.device_augment is None

    def batch_from(ds, seeds):
        episodes = [ds.get_set("train", seed=s, augment_images=True)
                    for s in seeds]
        cols = list(zip(*episodes))
        return tuple(np.stack(c) for c in cols[:4]) + tuple(
            np.asarray(c) for c in cols[5:]
        )

    sh = lh.init_state(jax.random.PRNGKey(21))
    sd = ld.init_state(jax.random.PRNGKey(21))
    for it in range(3):
        seeds = [1000 + 10 * it, 2000 + 10 * it]
        bh, bd = batch_from(ds_host, seeds), batch_from(ds_dev, seeds)
        assert len(bh) == 4 and len(bd) == 5  # raw pixels + ks payload
        sh, mh = lh.run_train_iter(sh, bh, epoch=0)
        sd, md = ld.run_train_iter(sd, bd, epoch=0)
        assert float(mh["loss"]) == float(md["loss"]), f"iter {it}"
    for a, b in zip(jax.tree.leaves(sh), jax.tree.leaves(sd)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Eval applies no augmentation on either side: identical programs.
    eval_batch = batch_from(ds_host, [31, 32])
    _, eh, ph = lh.run_validation_iter(sh, eval_batch)
    _, ed, pd = ld.run_validation_iter(sd, eval_batch)
    assert float(eh["loss"]) == float(ed["loss"])
    np.testing.assert_array_equal(np.asarray(ph), np.asarray(pd))

    # The baselines share the decode+augment path (models/common.
    # decode_train_batch): same bit-exactness contract for both.
    from howtotrainyourmamlpytorch_tpu.models import (
        GradientDescentLearner,
        MatchingNetsLearner,
    )

    for cls in (GradientDescentLearner, MatchingNetsLearner):
        bh, bd = batch_from(ds_host, [51, 52]), batch_from(ds_dev, [51, 52])
        blh = cls(args_to_maml_config(args_host))
        bld = cls(args_to_maml_config(args_dev))
        sbh = blh.init_state(jax.random.PRNGKey(23))
        sbd = bld.init_state(jax.random.PRNGKey(23))
        sbh, mbh = blh.run_train_iter(sbh, bh, epoch=0)
        sbd, mbd = bld.run_train_iter(sbd, bd, epoch=0)
        assert float(mbh["loss"]) == float(mbd["loss"]), cls.__name__
        for a, b in zip(jax.tree.leaves(sbh), jax.tree.leaves(sbd)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cifar_crop_flip_fixed_key_parity():
    """The on-device cifar crop/flip is pinned by fixed-key parity: for a
    given episode key the device transform's draws, reproduced on the
    host and applied with the HOST pipeline's own crop/flip (pad-4 +
    slice + mirror, data/augment._random_crop semantics), give identical
    pixels. Draw laws match torchvision RandomCrop(32, 4) +
    RandomHorizontalFlip: offsets uniform over [0, 2*pad], flips p=0.5."""
    from howtotrainyourmamlpytorch_tpu.models.common import crop_flip_by_key

    rng = np.random.RandomState(11)
    pad, h, w = 4, 32, 32
    x = rng.randint(0, 256, (6, 3, h, w)).astype(np.float32) / 255.0
    for seed, stream in ((77, 0), (77, 1), (1234, 0)):
        device = np.asarray(
            crop_flip_by_key(jnp.asarray(x), jnp.uint32(seed), pad, stream)
        )
        # Reproduce the draws exactly as the device transform makes them.
        key = jax.random.fold_in(jax.random.PRNGKey(seed), stream)
        k_off, k_flip = jax.random.split(key)
        offs = np.asarray(
            jax.random.randint(k_off, (x.shape[0], 2), 0, 2 * pad + 1)
        )
        flips = np.asarray(
            jax.random.bernoulli(k_flip, 0.5, (x.shape[0],))
        )
        assert offs.min() >= 0 and offs.max() <= 2 * pad
        # Apply them with the host pipeline's own padded-crop + mirror.
        host = []
        for img, (top, left), flip in zip(x, offs, flips):
            padded = np.pad(img, ((0, 0), (pad, pad), (pad, pad)))
            crop = padded[:, top:top + h, left:left + w]
            host.append(crop[..., ::-1] if flip else crop)
        np.testing.assert_array_equal(device, np.stack(host))
    # Different streams (support vs target) draw independently.
    a = np.asarray(crop_flip_by_key(jnp.asarray(x), jnp.uint32(5), pad, 0))
    b = np.asarray(crop_flip_by_key(jnp.asarray(x), jnp.uint32(5), pad, 1))
    assert not np.array_equal(a, b)


def test_cifar_device_augment_requires_uint8_wire(tmp_path):
    """crop_flip without the deferred-normalization codec would pad
    NORMALIZED pixels with zeros (diverging from the reference's
    pad-before-normalize order) — refused at config build."""
    from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
        device_augment_for,
    )

    good = _args(tmp_path, dataset_name="cifar100", transfer_dtype="uint8",
                 device_augment=True,
                 classification_mean=[0.5, 0.5, 0.5],
                 classification_std=[0.25, 0.25, 0.25])
    assert device_augment_for(good).kind == "crop_flip"
    bad = _args(tmp_path, dataset_name="cifar100", device_augment=True,
                classification_mean=[0.5, 0.5, 0.5],
                classification_std=[0.25, 0.25, 0.25])
    with pytest.raises(ValueError, match="transfer_dtype uint8"):
        device_augment_for(bad)


def test_uint8_wire_gd_and_matching_nets_bitwise_identical(omniglot_env):
    """The baselines decode the wire too (review finding: with a deferred-
    normalization codec their steps would otherwise train on raw pixels)."""
    from howtotrainyourmamlpytorch_tpu.models import (
        GradientDescentLearner,
        MatchingNetsLearner,
    )

    rng = np.random.RandomState(6)
    xs = rng.randint(0, 2, (2, 5, 1, 1, 12, 12)).astype(np.float32)
    xt = rng.randint(0, 2, (2, 5, 1, 1, 12, 12)).astype(np.float32)
    ys = np.tile(np.arange(5)[None, :, None], (2, 1, 1)).astype(np.int32)
    batch = (xs, xt, ys, ys.copy())

    args_f32 = _learner_args(omniglot_env, image_height=12, image_width=12)
    args_u8 = _learner_args(omniglot_env, image_height=12, image_width=12,
                            transfer_dtype="uint8")
    for cls in (GradientDescentLearner, MatchingNetsLearner):
        lf = cls(args_to_maml_config(args_f32))
        lu = cls(args_to_maml_config(args_u8))
        sf = lf.init_state(jax.random.PRNGKey(13))
        su = lu.init_state(jax.random.PRNGKey(13))
        sf, mf = lf.run_train_iter(sf, batch, epoch=0)
        su, mu = lu.run_train_iter(su, batch, epoch=0)
        assert float(mf["loss"]) == float(mu["loss"]), cls.__name__
        _, ef, _ = lf.run_validation_iter(sf, batch)
        _, eu, _ = lu.run_validation_iter(su, batch)
        assert float(ef["loss"]) == float(eu["loss"]), cls.__name__
