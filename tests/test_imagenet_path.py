"""Mini-ImageNet code-path smoke tests (VERDICT r2 missing #1 / next #7).

The mini-ImageNet images are absent from this environment (only the index
JSONs exist), so these tests exercise the full imagenet pipeline on a
SYNTHETIC pre-split RGB dataset tree: ``sets_are_pre_split`` top-folder
split (``/root/reference/data.py:169-211``), RGB ``/255`` image load
(``:374-395``), ImageNet mean/std normalization, the ±10 outer-grad clamp
(``few_shot_learning_system.py:332-335``), and the uint8 wire codec's
deferred on-device normalization — end-to-end through ExperimentBuilder.
The day the real dataset is mounted, the shipped configs run this exact
path at full shape.
"""

import os

import numpy as np
import pytest
from PIL import Image

from howtotrainyourmamlpytorch_tpu.data import MetaLearningSystemDataLoader
from howtotrainyourmamlpytorch_tpu.data.dataset import FewShotLearningDataset
from howtotrainyourmamlpytorch_tpu.experiment_builder import ExperimentBuilder
from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner
from howtotrainyourmamlpytorch_tpu.utils import storage
from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
    args_to_maml_config,
)

from test_data import make_args


def make_presplit_rgb_dir(root, n_classes=6, n_imgs=4, size=21):
    """``root/{train,val,test}/<class>/<i>.png`` RGB tree (the reference's
    mini_imagenet_full_size layout, README.md:34-40 there)."""
    rng = np.random.RandomState(7)
    for set_name in ("train", "val", "test"):
        for c in range(n_classes):
            d = root / set_name / f"n{set_name}{c:04d}"
            d.mkdir(parents=True, exist_ok=True)
            proto = rng.randint(0, 256, (size, size, 3))
            for i in range(n_imgs):
                img = np.clip(
                    proto + rng.randint(-30, 31, proto.shape), 0, 255
                ).astype(np.uint8)
                Image.fromarray(img, mode="RGB").save(str(d / f"{i}.png"))


def _imagenet_args(tmp_path, **kw):
    """The mini-imagenet config surface (mini-imagenet_maml++-mini-imagenet_
    5_2_0.01_48_5_0.json) at test scale: RGB 84x84-style strided path,
    batch 2, pre-split sets, clamp via the imagenet dataset name."""
    defaults = dict(
        dataset_name="mini_imagenet_full_size",
        dataset_path=str(tmp_path / "mini_imagenet_full_size"),
        image_height=21, image_width=21, image_channels=3,
        sets_are_pre_split=True,
        indexes_of_folders_indicating_class=[-3, -2],
        load_into_memory=True,
        num_target_samples=1, num_samples_per_class=1, num_classes_per_set=5,
        batch_size=2,
        num_stages=2, cnn_num_filters=8, conv_padding=True,
        max_pooling=False,  # strided convs + global avg-pool (imagenet arch)
        norm_layer="batch_norm", per_step_bn_statistics=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        second_order=False, first_order_to_second_order_epoch=-1,
        use_multi_step_loss_optimization=True, multi_step_loss_num_epochs=2,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        enable_inner_loop_optimizable_bn_params=False,
        learnable_bn_gamma=True, learnable_bn_beta=True,
        meta_learning_rate=0.001, min_learning_rate=1e-5,
        task_learning_rate=None, init_inner_loop_learning_rate=0.01,
        total_epochs=2, total_iter_per_epoch=2,
        total_epochs_before_pause=100, num_evaluation_tasks=4,
        evaluate_on_test_set_only=False, seed=104,
        continue_from_epoch="from_scratch", max_models_to_save=5,
    )
    defaults.update(kw)
    return make_args(tmp_path, **defaults)


@pytest.fixture
def imagenet_env(tmp_path, monkeypatch):
    make_presplit_rgb_dir(tmp_path / "mini_imagenet_full_size")
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    return tmp_path


def test_presplit_rgb_dataset_surface(imagenet_env):
    args = _imagenet_args(imagenet_env)
    ds = FewShotLearningDataset(args=args)
    # Top-folder split, 6 classes each, images loaded as HWC float32 k/255.
    for set_name in ("train", "val", "test"):
        assert len(ds.datasets[set_name]) == 6, set_name
    xs, xt, ys, yt, _seed = ds.get_set("train", seed=3, augment_images=True)
    assert xs.shape == (5, 1, 3, 21, 21)
    # host pipeline normalized with the ImageNet constants
    from howtotrainyourmamlpytorch_tpu.data.augment import (
        IMAGENET_MEAN,
        IMAGENET_STD,
    )

    raw = xs * IMAGENET_STD.reshape(-1, 1, 1) + IMAGENET_MEAN.reshape(-1, 1, 1)
    k = raw * 255.0
    np.testing.assert_allclose(k, np.rint(k), atol=1e-3)  # k/255 pixels
    assert raw.min() >= -1e-5 and raw.max() <= 1.0 + 1e-5


def test_imagenet_clamp_selected(imagenet_env):
    cfg = args_to_maml_config(_imagenet_args(imagenet_env))
    assert cfg.clip_grad_value == 10.0  # few_shot_learning_system.py:332-335
    assert cfg.task_learning_rate == 0.01
    assert not cfg.backbone.max_pooling


def _run_experiment(tmp_path, exp_name, **kw):
    args = _imagenet_args(
        tmp_path, experiment_name=str(tmp_path / exp_name), **kw
    )
    model = MAMLFewShotLearner(args_to_maml_config(args))
    builder = ExperimentBuilder(
        args=args, data=MetaLearningSystemDataLoader, model=model, device=None
    )
    test_losses = builder.run_experiment()
    return args, test_losses


def test_end_to_end_imagenet_path(imagenet_env):
    """Full ExperimentBuilder run on the synthetic pre-split RGB tree —
    train epochs, val epochs, checkpoints, top-5 ensemble test."""
    args, test_losses = _run_experiment(imagenet_env, "im_exp")
    assert 0.0 <= test_losses["test_accuracy_mean"] <= 1.0
    logs = os.path.join(str(imagenet_env / "im_exp"), "logs")
    stats = storage.load_statistics(logs)
    assert len(stats["epoch"]) == 2
    assert os.path.exists(os.path.join(logs, "test_summary.csv"))


def test_end_to_end_imagenet_uint8_wire_identical(imagenet_env):
    """uint8 wire (deferred on-device normalization) must reproduce the
    float32 wire's training trajectory through the REAL loader.

    Pixels recover exactly (k/255), but XLA reassociates the on-device
    ``(x - mean) / std`` (division-by-constant becomes multiply-by-
    reciprocal inside the fused train step), so losses match to ~1 ulp
    rather than bitwise — unlike omniglot's cast-only codec, which IS
    bitwise (tests/test_wire_codec.py)."""
    _, f32 = _run_experiment(imagenet_env, "im_f32")
    _, u8 = _run_experiment(imagenet_env, "im_u8", transfer_dtype="uint8")
    # Accuracy is discrete, but a near-boundary logit could flip one of the
    # eval predictions under the ~1-ulp loss difference — tolerate a single
    # flipped prediction out of the eval set rather than exact ==.
    n_eval_preds = 4 * 5 * 1  # num_evaluation_tasks * way * targets (fixture)
    assert abs(f32["test_accuracy_mean"] - u8["test_accuracy_mean"]) <= (
        1.0 / n_eval_preds + 1e-9
    )
    a = storage.load_statistics(os.path.join(str(imagenet_env / "im_f32"), "logs"))
    b = storage.load_statistics(os.path.join(str(imagenet_env / "im_u8"), "logs"))
    np.testing.assert_allclose(
        [float(v) for v in a["train_loss_mean"]],
        [float(v) for v in b["train_loss_mean"]],
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        [float(v) for v in a["val_accuracy_mean"]],
        [float(v) for v in b["val_accuracy_mean"]],
        atol=1e-12,
    )
