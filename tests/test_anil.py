"""ANIL (Raghu et al.) on the full shared learner contract.

ANIL is MAML with the inner loop restricted to the classifier head via the
``adapt_mask`` partition seam (models/anil.py); everything else — LSLR,
MSL, serve split, checkpoint prefix, divergence sentinel, mesh rules — is
inherited. These tests pin the three things the restriction must mean:

* the ADAPTED set is exactly the head (LSLR table and serve artifact hold
  ``linear/weight`` + ``linear/bias`` and nothing else);
* the body is frozen THROUGH ADAPTATION but still meta-trained (conv
  leaves move under ``run_train_iter``, never inside ``serve_adapt``);
* every shared-contract surface (serve parity incl. trained state and the
  uint8 wire, dp-mesh training, mesh-portable checkpoints, the nonfinite
  sentinel, serve compile-once) holds for the subclass unchanged.
"""

import os

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding

from howtotrainyourmamlpytorch_tpu.models import (
    ANILLearner,
    BackboneConfig,
    MAMLConfig,
)
from howtotrainyourmamlpytorch_tpu.models.common import WireCodec
from howtotrainyourmamlpytorch_tpu.parallel import make_mesh
from howtotrainyourmamlpytorch_tpu.serve import ServeConfig, ServingAPI
from howtotrainyourmamlpytorch_tpu.utils.trees import partition
from test_serve_parity import (
    eval_batch,
    golden_fixture_episode,
    serve_and_reference,
    tiny_cfg,
)

HEAD_LEAVES = 2  # linear/weight + linear/bias


def small_cfg(**kw):
    """8x8 config for the non-parity tests (parity rides test_serve_parity's
    14x14 ``tiny_cfg`` because the golden fixtures are recorded at 14x14)."""
    kw.setdefault("second_order", False)
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=4,
            num_classes=5,
            image_height=8,
            image_width=8,
            num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        use_multi_step_loss_optimization=False,
        **kw,
    )


def small_batch(rng, tasks=2, hw=8):
    xs = rng.randn(tasks, 5, 1, 1, hw, hw).astype(np.float32)
    xt = rng.randn(tasks, 5, 1, 1, hw, hw).astype(np.float32)
    ys = np.tile(np.arange(5)[None, :, None], (tasks, 1, 1)).astype(np.int32)
    return xs, xt, ys, ys.copy()


def head_paths(tree):
    """Top-level path groups of the tree's non-None leaves."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return {jax.tree_util.keystr(path[:1]) for path, _ in flat}


# ---------------------------------------------------------------------------
# The partition IS the specialization
# ---------------------------------------------------------------------------


def test_adapt_partition_is_exactly_the_head():
    learner = ANILLearner(small_cfg())
    state = learner.init_state(jax.random.key(0))
    adapt, _frozen = partition(state.theta, learner.adapt_mask(state.theta))
    assert len(jax.tree.leaves(adapt)) == HEAD_LEAVES
    assert head_paths(adapt) == {"['linear']"}
    # LSLR is sized FROM the partition: head rows only, nothing for the body.
    assert len(jax.tree.leaves(state.lslr)) == HEAD_LEAVES
    assert head_paths(state.lslr) == {"['linear']"}


def test_serve_artifact_is_head_only_and_tiny():
    """``serve_adapt`` returns only the adapted partition — for ANIL a
    kilobyte-scale head, not MAML's full fast-weight tree."""
    learner = ANILLearner(small_cfg())
    istate = learner.init_inference_state(jax.random.key(1))
    rng = np.random.RandomState(1)
    xs = rng.rand(5, 1, 8, 8).astype(np.float32)
    ys = np.arange(5, dtype=np.int32)
    artifact = learner.serve_adapt(istate, xs, ys)
    leaves = jax.tree.leaves(artifact)
    assert len(leaves) == HEAD_LEAVES
    assert head_paths(artifact) == {"['linear']"}
    assert sum(np.asarray(l).nbytes for l in leaves) < 16 * 1024


def test_body_frozen_through_adaptation_but_meta_trained(rng):
    """Adaptation must not touch conv leaves (they are not even IN the
    adapted tree); the outer loop must still train them."""
    learner = ANILLearner(small_cfg())
    state = learner.init_state(jax.random.key(2))
    # Host copies up front: the train step donates its input state buffers.
    before = [
        (path, np.array(leaf))
        for path, leaf in jax.tree_util.tree_flatten_with_path(state.theta)[0]
    ]
    new_state, losses = learner.run_train_iter(
        state, small_batch(rng), epoch=0
    )
    assert float(losses["nonfinite"]) == 0.0
    after = dict(jax.tree_util.tree_flatten_with_path(new_state.theta)[0])
    body_moved = 0
    for path, leaf in before:
        if jax.tree_util.keystr(path[:1]) == "['linear']":
            continue
        if not np.array_equal(leaf, np.asarray(after[path])):
            body_moved += 1
    assert body_moved > 0, "outer loop must meta-train the frozen body"


def test_second_order_is_legal_and_differs_from_first_order(rng):
    """The outer gradient differentiates THROUGH the head-only inner loop:
    a second-order step must run and produce different head weights than
    the first-order approximation from the same init and batch."""
    batch = small_batch(rng)
    heads = {}
    for so in (False, True):
        learner = ANILLearner(small_cfg(second_order=so))
        state = learner.init_state(jax.random.key(3))
        state, losses = learner.run_train_iter(state, batch, epoch=0)
        assert float(losses["nonfinite"]) == 0.0
        adapt, _ = partition(state.theta, learner.adapt_mask(state.theta))
        heads[so] = [np.asarray(l) for l in jax.tree.leaves(adapt)]
    assert any(
        not np.array_equal(a, b) for a, b in zip(heads[False], heads[True])
    )


# ---------------------------------------------------------------------------
# Serve parity (bit-exact vs the eval graph)
# ---------------------------------------------------------------------------


def test_anil_served_fixture_episode_bit_exact():
    learner = ANILLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(4))
    xs, ys, xq, yq = golden_fixture_episode()
    served, cached, ref = serve_and_reference(learner, state, xs, ys, xq, yq)
    np.testing.assert_array_equal(served, ref)
    np.testing.assert_array_equal(cached, ref)


def test_anil_trained_state_bit_exact(rng):
    learner = ANILLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(5))
    state, _ = learner.run_train_iter(
        state, small_batch(rng, tasks=2, hw=14), epoch=0
    )
    xs, ys, xq, yq = golden_fixture_episode()
    served, cached, ref = serve_and_reference(learner, state, xs, ys, xq, yq)
    np.testing.assert_array_equal(served, ref)
    np.testing.assert_array_equal(cached, ref)


def test_anil_uint8_wire_codec_bit_exact():
    learner = ANILLearner(tiny_cfg(wire_codec=WireCodec(1.0, None, None)))
    state = learner.init_state(jax.random.key(6))
    xs, ys, xq, yq = golden_fixture_episode(binary=True)
    served, cached, ref = serve_and_reference(learner, state, xs, ys, xq, yq)
    np.testing.assert_array_equal(served, ref)
    np.testing.assert_array_equal(cached, ref)


# ---------------------------------------------------------------------------
# dp mesh + mesh-portable checkpoints
# ---------------------------------------------------------------------------


def dp_mesh(n):
    return make_mesh(jax.devices()[:n], data_parallel=n, model_parallel=1)


def test_anil_dp_mesh_train_runs(spmd_fo_compile_guard, rng):
    learner = ANILLearner(small_cfg(), mesh=dp_mesh(4))
    state = learner.shard_state(learner.init_state(jax.random.key(7)))
    for _ in range(2):
        state, losses = learner.run_train_iter(
            state, small_batch(rng, tasks=4), epoch=0
        )
    assert float(losses["nonfinite"]) == 0.0
    assert np.isfinite(float(losses["loss"]))
    for leaf in jax.tree.leaves(state.theta):
        assert isinstance(leaf.sharding, NamedSharding)
        assert leaf.sharding.mesh.shape == learner.mesh.shape


def test_anil_mesh_checkpoint_roundtrip(tmp_path):
    """Save under a 2-device dp mesh, resume single-device: bit-exact, and
    the restored LSLR tree keeps its head-only structure."""
    writer = ANILLearner(small_cfg(), mesh=dp_mesh(2))
    state = writer.shard_state(writer.init_state(jax.random.key(8)))
    exp = {"current_iter": 9}
    writer.save_model(os.path.join(tmp_path, "train_model_9"), state, exp)

    reader = ANILLearner(small_cfg())
    restored, restored_exp = reader.load_model(str(tmp_path), "train_model", 9)
    assert restored_exp == exp
    saved = [np.asarray(x) for x in jax.tree.leaves(writer.gather_state(state))]
    back = [np.asarray(x) for x in jax.tree.leaves(restored)]
    for a, b in zip(saved, back):
        np.testing.assert_array_equal(a, b)
    assert len(jax.tree.leaves(restored.lslr)) == HEAD_LEAVES


# ---------------------------------------------------------------------------
# Sentinel + compile discipline
# ---------------------------------------------------------------------------


def test_anil_nonfinite_sentinel_trips(rng):
    learner = ANILLearner(small_cfg(skip_nonfinite_updates=True))
    state = learner.init_state(jax.random.key(9))
    clean = small_batch(rng)
    state, losses = learner.run_train_iter(state, clean, epoch=0)
    assert float(losses["nonfinite"]) == 0.0
    theta_before = [np.asarray(l) for l in jax.tree.leaves(state.theta)]
    poisoned = (np.full_like(clean[0], np.inf),) + clean[1:]
    state, losses = learner.run_train_iter(state, poisoned, epoch=0)
    assert float(losses["nonfinite"]) == 1.0
    # skip_nonfinite_updates: the poisoned step must not move theta.
    for a, b in zip(theta_before, jax.tree.leaves(state.theta)):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_anil_serve_compiles_once(compile_guard):
    """Distinct support sets at one geometry reuse the one adapt/classify
    program pair — no per-episode recompiles."""
    learner = ANILLearner(small_cfg())
    state = learner.init_state(jax.random.key(10))
    api = ServingAPI(
        learner, state, ServeConfig(meta_batch_size=2, max_wait_ms=0.0)
    )
    rng = np.random.RandomState(11)

    def episode():
        xs = rng.rand(5, 1, 8, 8).astype(np.float32)
        ys = np.arange(5, dtype=np.int32)
        xq = rng.rand(3, 1, 8, 8).astype(np.float32)
        return xs, ys, xq

    try:
        api.classify(*episode())  # warm: compiles the pair once
        with compile_guard() as guard:
            for _ in range(3):
                out = api.classify(*episode())
                assert out["logits"].shape == (3, 5)
        assert guard.count("serve_adapt_anil") == 0
        assert guard.count("serve_classify_anil") == 0
        assert len(guard.events) == 0
    finally:
        api.close()
