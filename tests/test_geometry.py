"""Episode-geometry coarsening (serve/geometry.py): mixed (way, shot,
query) traffic through a fixed program set.

Three layers of contract, pinned in order:

* the POLICY: deterministic lattice ordering (slot cost, then
  lexicographic — a fleet must agree on the bucket an episode rides),
  coarsen-to-first-containing, actionable rejection, and structurally-zero
  padding with a correct mask;
* the NUMERICS: for every learner family, logits over the REAL classes of
  a coarsened dispatch are bit-exact with a dispatch at the episode's true
  geometry. For MAML/ANIL/GD/protonets that anchor extends to the
  pre-geometry MASKLESS engine bit-for-bit; matching nets' attention
  softmax fuses differently once the mask is a runtime input (~1 ulp,
  identical argmax — see the geometry.py docstring fine print), so its
  bit-exact anchor is the masked program at the true geometry;
* the COMPILE ECONOMY: a mixed stream of >= 6 distinct geometries compiles
  at most the declared bucket set (one masked adapt per bucket; classify
  shared across buckets with equal query count), and the second pass over
  the same mix compiles nothing.

Plus the observability/front-door seams: the ``coarsened`` response flag,
``geometry_coarsened_total`` / ``geometry_rejected_total`` counters, the
HTTP 400 (NOT 503: no Retry-After, no shed flag) rejection path, and the
/metrics scrape.
"""

import json
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.data import synthesize_episode
from howtotrainyourmamlpytorch_tpu.models import (
    ANILLearner,
    BackboneConfig,
    GradientDescentLearner,
    MAMLConfig,
    MAMLFewShotLearner,
    MatchingNetsLearner,
    ProtoNetsLearner,
)
from howtotrainyourmamlpytorch_tpu.serve import (
    ServeConfig,
    ServingAPI,
    make_http_server,
)
from howtotrainyourmamlpytorch_tpu.serve.geometry import (
    GeometryPolicy,
    GeometryRejectedError,
)

FAMILIES = {
    "maml": MAMLFewShotLearner,
    "anil": ANILLearner,
    "gradient_descent": GradientDescentLearner,
    "matching_nets": MatchingNetsLearner,
    "protonets": ProtoNetsLearner,
}

#: Exactly-bit-exact against the pre-geometry maskless engine too (the
#: matching-nets exception is the module-docstring fine print).
MASKLESS_EXACT = {"maml", "anil", "gradient_descent", "protonets"}

LATTICE = ((3, 1, 4), (5, 2, 8))

#: Six distinct geometries, all containable by LATTICE: two exact fits,
#: four that must coarsen.
MIX = ((2, 1, 3), (3, 1, 4), (2, 2, 5), (4, 1, 6), (5, 1, 8), (5, 2, 8))

IMAGE = (1, 8, 8)


def geo_cfg(**kw):
    """layer_norm backbone — the row-independence precondition the policy
    validates at attachment."""
    kw.setdefault("second_order", False)
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=4,
            num_classes=5,
            image_height=8,
            image_width=8,
            num_steps=2,
            norm_layer="layer_norm",
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        use_multi_step_loss_optimization=False,
        **kw,
    )


def serve_cfg(lattice=LATTICE, **kw):
    kw.setdefault("meta_batch_size", 2)
    kw.setdefault("max_wait_ms", 0.0)
    return ServeConfig(geometry_lattice=lattice, **kw)


# ---------------------------------------------------------------------------
# Policy: lattice order, coarsening map, rejection, padding
# ---------------------------------------------------------------------------


def test_lattice_sorted_by_slot_cost_then_lexicographic_and_deduped():
    policy = GeometryPolicy([(5, 2, 8), (3, 1, 4), (3, 1, 4), (2, 2, 2)])
    # slot costs: (2,2,2)->6, (3,1,4)->7, (5,2,8)->18
    assert policy.lattice == ((2, 2, 2), (3, 1, 4), (5, 2, 8))
    assert policy.describe() == "2x2x2, 3x1x4, 5x2x8"


def test_equal_cost_ties_resolve_lexicographically():
    # Both cost 6; a fleet must coarsen (2,1,2) identically everywhere.
    policy = GeometryPolicy([(3, 1, 3), (2, 2, 2)])
    assert policy.lattice == ((2, 2, 2), (3, 1, 3))
    assert policy.coarsen(2, 1, 2) == (2, 2, 2)
    assert policy.coarsen(3, 1, 1) == (3, 1, 3)


def test_coarsen_table():
    policy = GeometryPolicy(LATTICE)
    cases = {
        (2, 1, 3): (3, 1, 4),
        (3, 1, 4): (3, 1, 4),  # exact fit
        (2, 2, 5): (5, 2, 8),  # shot forces the big bucket
        (4, 1, 6): (5, 2, 8),  # query forces it
        (5, 1, 8): (5, 2, 8),
        (5, 2, 8): (5, 2, 8),  # exact fit
    }
    for geometry, bucket in cases.items():
        assert policy.coarsen(*geometry) == bucket


def test_rejection_is_actionable_and_not_overload():
    policy = GeometryPolicy(LATTICE)
    with pytest.raises(GeometryRejectedError) as exc_info:
        policy.coarsen(5, 3, 2)  # shot 3 fits no bucket
    msg = str(exc_info.value)
    assert policy.describe() in msg, "message must name the lattice"
    assert "not overload" in msg
    assert isinstance(exc_info.value, ValueError)  # the existing 400 map


def test_bad_lattice_entries_refused():
    with pytest.raises(ValueError):
        GeometryPolicy([])
    with pytest.raises(ValueError):
        GeometryPolicy([(5, 0, 2)])
    with pytest.raises(ValueError):
        GeometryPolicy([(5, 2)])


def test_pad_episode_structure():
    policy = GeometryPolicy(LATTICE)
    xs, ys, xq = synthesize_episode(2, 1, 3, image_shape=IMAGE, seed=5)
    padded = policy.pad_episode(xs, ys, xq, way=2, shot=1)
    assert (padded.way, padded.shot, padded.query) == (3, 1, 4)
    assert (padded.real_way, padded.real_shot, padded.real_query) == (2, 1, 3)
    assert padded.coarsened
    # Real rows are a contiguous, untouched prefix; padding is exact zeros
    # with label 0 and mask 0.
    np.testing.assert_array_equal(padded.x_support[:2], xs)
    np.testing.assert_array_equal(padded.y_support[:2], ys)
    np.testing.assert_array_equal(padded.x_query[:3], xq)
    np.testing.assert_array_equal(
        padded.x_support[2:], np.zeros((1,) + IMAGE, np.float32)
    )
    np.testing.assert_array_equal(
        padded.x_query[3:], np.zeros((1,) + IMAGE, np.float32)
    )
    np.testing.assert_array_equal(padded.y_support[2:], [0])
    np.testing.assert_array_equal(padded.support_mask, [1.0, 1.0, 0.0])
    assert padded.support_mask.dtype == np.float32

    exact = policy.pad_episode(
        *synthesize_episode(5, 2, 8, image_shape=IMAGE, seed=6), way=5, shot=2
    )
    assert not exact.coarsened
    np.testing.assert_array_equal(exact.support_mask, np.ones(10, np.float32))


def backbone(**kw):
    return BackboneConfig(
        num_stages=2,
        num_filters=4,
        num_classes=5,
        image_height=8,
        image_width=8,
        num_steps=2,
        **kw,
    )


def test_validate_backbone_refuses_batch_norm_and_narrow_heads():
    policy = GeometryPolicy(LATTICE)
    with pytest.raises(ValueError, match="row-independent"):
        policy.validate_backbone(backbone())  # batch_norm default
    narrow = GeometryPolicy(((7, 1, 4),))
    with pytest.raises(ValueError, match="only 5 classes"):
        narrow.validate_backbone(backbone(norm_layer="layer_norm"))


def test_engine_refuses_batch_norm_backbone():
    bad = MAMLConfig(
        backbone=backbone(),  # batch_norm default
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        use_multi_step_loss_optimization=False,
        second_order=False,
    )
    learner = MAMLFewShotLearner(bad)
    state = learner.init_state(jax.random.key(0))
    with pytest.raises(ValueError, match="row-independent"):
        ServingAPI(learner, state, serve_cfg())


def test_engine_refuses_lattice_wider_than_head():
    learner = MAMLFewShotLearner(geo_cfg())
    state = learner.init_state(jax.random.key(0))
    with pytest.raises(ValueError, match="only 5 classes"):
        ServingAPI(learner, state, serve_cfg(lattice=((7, 1, 4),)))


# ---------------------------------------------------------------------------
# Numerics: the real-class slice of a coarsened dispatch is bit-exact
# ---------------------------------------------------------------------------


def classify_once(api, episode):
    xs, ys, xq = episode
    return api.classify(xs, ys, xq)


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_coarsened_logits_bit_exact_real_slice(family):
    learner = FAMILIES[family](geo_cfg())
    state = learner.init_state(jax.random.key(1))
    episode = synthesize_episode(2, 1, 3, image_shape=IMAGE, seed=7)

    api_geo = ServingAPI(learner, state, serve_cfg())
    api_fit = ServingAPI(learner, state, serve_cfg(lattice=((2, 1, 3),)))
    api_plain = ServingAPI(
        learner, state, ServeConfig(meta_batch_size=2, max_wait_ms=0.0)
    )
    try:
        coarse = classify_once(api_geo, episode)
        fit = classify_once(api_fit, episode)
        plain = classify_once(api_plain, episode)
    finally:
        api_geo.close()
        api_fit.close()
        api_plain.close()

    assert coarse["coarsened"] and coarse["bucket"] == "3x1x4"
    assert not fit["coarsened"] and fit["bucket"] == "2x1x3"
    assert not plain["coarsened"] and plain["bucket"] == "2x1x3"

    logits = np.asarray(coarse["logits"])
    # Padded query rows dropped; padded class columns can never win.
    assert logits.shape == (3, 5)
    assert np.isneginf(logits[:, 2:]).all()
    assert np.isfinite(logits[:, :2]).all()

    # Coarsened == masked dispatch at the TRUE geometry, bit-for-bit, for
    # every family: padding is never lossy.
    np.testing.assert_array_equal(
        logits[:, :2], np.asarray(fit["logits"])[:, :2]
    )
    plain_logits = np.asarray(plain["logits"])
    if family in MASKLESS_EXACT:
        np.testing.assert_array_equal(logits[:, :2], plain_logits[:, :2])
    else:  # matching nets: ~1 ulp vs the maskless fusion, same argmax
        np.testing.assert_allclose(
            logits[:, :2], plain_logits[:, :2], rtol=1e-5
        )
        np.testing.assert_array_equal(
            np.argmax(logits[:, :2], axis=-1),
            np.argmax(plain_logits[:, :2], axis=-1),
        )


# ---------------------------------------------------------------------------
# Compile economy: the mix rides the lattice's program set
# ---------------------------------------------------------------------------


def test_mixed_stream_compiles_at_most_the_lattice(compile_guard):
    assert len(set(MIX)) >= 6
    learner = MAMLFewShotLearner(geo_cfg())
    state = learner.init_state(jax.random.key(2))
    api = ServingAPI(learner, state, serve_cfg())
    try:
        with compile_guard() as guard:
            api.engine.warmup()  # a geometry engine warms its whole lattice
            for i, geometry in enumerate(MIX):
                episode = synthesize_episode(
                    *geometry, image_shape=IMAGE, seed=100 + i
                )
                out = classify_once(api, episode)
                assert np.asarray(out["logits"]).shape == (geometry[2], 5)
        # One masked adapt program per bucket; LATTICE's buckets have
        # distinct query counts so classify is also one per bucket.
        guard.assert_compiles("serve_adapt_maml", exactly=len(LATTICE))
        guard.assert_compiles("serve_classify_maml", exactly=len(LATTICE))
        assert len(guard.events) == 2 * len(LATTICE)

        # Steady state: a second pass over the same mix compiles NOTHING.
        with compile_guard() as steady:
            for i, geometry in enumerate(MIX):
                episode = synthesize_episode(
                    *geometry, image_shape=IMAGE, seed=200 + i
                )
                classify_once(api, episode)
        assert len(steady.events) == 0
        # The engine's own trace table agrees: 2 adapt + 2 classify shapes.
        assert len(api.engine.compile_table()) == 2 * len(LATTICE)
    finally:
        api.close()


def test_shared_classify_program_across_equal_query_buckets(compile_guard):
    """Buckets that differ only in support geometry share ONE classify
    program — the query-side shape is the whole classify signature."""
    lattice = ((2, 1, 6), (5, 2, 6))
    learner = MAMLFewShotLearner(geo_cfg())
    state = learner.init_state(jax.random.key(3))
    api = ServingAPI(learner, state, serve_cfg(lattice=lattice))
    try:
        with compile_guard() as guard:
            api.engine.warmup()
        guard.assert_compiles("serve_adapt_maml", exactly=2)
        guard.assert_compiles("serve_classify_maml", exactly=1)
    finally:
        api.close()


# ---------------------------------------------------------------------------
# Observability + front door
# ---------------------------------------------------------------------------


def test_geometry_counters_and_rejection():
    learner = MAMLFewShotLearner(geo_cfg())
    state = learner.init_state(jax.random.key(4))
    api = ServingAPI(learner, state, serve_cfg())
    try:
        classify_once(api, synthesize_episode(3, 1, 4, image_shape=IMAGE))
        snap = api.metrics.snapshot()
        assert snap["geometry_coarsened_total"] == 0  # exact fit
        classify_once(
            api, synthesize_episode(2, 1, 3, image_shape=IMAGE, seed=1)
        )
        with pytest.raises(GeometryRejectedError):
            classify_once(
                api, synthesize_episode(5, 3, 2, image_shape=IMAGE, seed=2)
            )
        snap = api.metrics.snapshot()
        assert snap["geometry_coarsened_total"] == 1
        assert snap["geometry_rejected_total"] == 1
    finally:
        api.close()


@pytest.fixture
def served_geo():
    learner = MAMLFewShotLearner(geo_cfg())
    state = learner.init_state(jax.random.key(5))
    api = ServingAPI(learner, state, serve_cfg(max_wait_ms=1.0))
    api.engine.warmup()
    server = make_http_server(api, port=0)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        yield f"http://127.0.0.1:{port}", api
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=10)
        api.close()


def post_episode(base, payload):
    req = urllib.request.Request(
        f"{base}/v1/episode",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.load(resp)


def episode_payload(way, shot, query, seed=0):
    xs, ys, xq = synthesize_episode(
        way, shot, query, image_shape=IMAGE, seed=seed
    )
    return {
        "support": xs.tolist(),
        "support_labels": ys.tolist(),
        "query": xq.tolist(),
    }


def test_http_geometry_rejection_is_400_not_overload(served_geo):
    base, _api = served_geo
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        post_episode(base, episode_payload(5, 3, 2))
    err = exc_info.value
    assert err.code == 400
    body = json.load(err)
    assert body["geometry_rejected"] is True
    assert "3x1x4" in body["error"] and "not overload" in body["error"]
    # Deliberately NOT shaped like overload: no shed flag, no Retry-After.
    assert "shed" not in body
    assert err.headers.get("Retry-After") is None


def test_http_coarsened_roundtrip_and_metrics_scrape(served_geo):
    base, _api = served_geo
    status, body = post_episode(base, episode_payload(2, 1, 3, seed=3))
    assert status == 200
    assert body["coarsened"] is True
    assert body["bucket"] == "3x1x4"
    assert np.asarray(body["logits"]).shape == (3, 5)
    assert max(body["predictions"]) < 2  # -inf pad columns never win

    with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
        text = resp.read().decode()
    assert "maml_serve_geometry_coarsened_total 1" in text
    assert "maml_serve_geometry_rejected_total 0" in text
