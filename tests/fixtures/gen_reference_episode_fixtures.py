"""Generate golden fixtures by EXECUTING the reference sampler.

Runs the actual ``FewShotLearningDatasetParallel.get_set`` /
``load_dataset`` code from the read-only reference checkout
(``/root/reference/data.py:478-524,169-211``) against a synthetic class
tree, recording every RNG-driven decision — selected classes, shuffled
order, per-class rotation ``k``, per-class sample indices, episode label
matrices, and the ratio-split class partition — into
``reference_episodes.json``. ``tests/test_golden_episodes.py`` then asserts
the repo's sampler reproduces the recordings bit for bit.

Requires the reference checkout (it is imported, never copied); the fixture
JSON is committed so CI does not need it. torchvision is absent from the
environment, so it is stubbed before import — the stubbed pieces
(transforms) are never exercised: image loading and augmentation are
monkeypatched to pure recorders, which leaves exactly the RNG call order
under test.

Usage: python tests/fixtures/gen_reference_episode_fixtures.py [ref_path]
"""

import json
import os
import sys
import types

REF = sys.argv[1] if len(sys.argv) > 1 else "/root/reference"
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "reference_episodes.json")

# --- import the reference data module with unused deps stubbed -----------
tv = types.ModuleType("torchvision")
tv.transforms = types.ModuleType("torchvision.transforms")
tv.transforms.Compose = lambda *a, **k: None
tv.transforms.ToTensor = lambda *a, **k: None
tv.transforms.Normalize = lambda *a, **k: None
tv.transforms.RandomCrop = lambda *a, **k: None
tv.transforms.RandomHorizontalFlip = lambda *a, **k: None
sys.modules["torchvision"] = tv
sys.modules["torchvision.transforms"] = tv.transforms

# data.py does `from utils.parser_utils import get_args` at module level,
# which parses argv; give it an importable stub instead.
utils_pkg = types.ModuleType("utils")
parser_stub = types.ModuleType("utils.parser_utils")
parser_stub.get_args = lambda *a, **k: None
utils_pkg.parser_utils = parser_stub
sys.modules["utils"] = utils_pkg
sys.modules["utils.parser_utils"] = parser_stub

sys.path.insert(0, REF)
import importlib

ref_data = importlib.import_module("data")

import numpy as np  # noqa: E402
import torch  # noqa: E402

Cls = ref_data.FewShotLearningDatasetParallel


def make_stub(n_classes, samples_per_class, num_classes_per_set,
              num_samples_per_class, num_target_samples):
    """A bare instance with only the attributes get_set touches."""
    self = Cls.__new__(Cls)
    self.num_classes_per_set = num_classes_per_set
    self.num_samples_per_class = num_samples_per_class
    self.num_target_samples = num_target_samples
    self.image_channel = 1
    self.dataset_name = "omniglot_dataset"
    self.args = types.SimpleNamespace()
    keys = [f"c{i:03d}" for i in range(n_classes)]
    self.datasets = {
        "train": {k: [f"{k}/s{j:02d}" for j in range(samples_per_class)]
                  for k in keys}
    }
    self.dataset_size_dict = {
        "train": {k: samples_per_class for k in keys}
    }
    return self


def record_episode(stub, seed):
    """Run the REFERENCE get_set, recording loads and augmentation ks."""
    loads = []
    ks = []

    def fake_load_batch(batch_image_paths):
        loads.append(batch_image_paths[0])
        return torch.zeros(1, 1, 1, 1)

    def fake_augment_image(image, k, channels, augment_bool, dataset_name,
                           args):
        ks.append(int(k))
        return image[0]

    stub.load_batch = fake_load_batch
    orig = ref_data.augment_image
    ref_data.augment_image = fake_augment_image
    try:
        _xs, _xt, ys, yt, out_seed = Cls.get_set(
            stub, "train", seed=seed, augment_images=False
        )
    finally:
        ref_data.augment_image = orig

    n = stub.num_classes_per_set
    per_class = stub.num_samples_per_class + stub.num_target_samples
    classes_in_order = []
    samples = []
    for ci in range(n):
        chunk = loads[ci * per_class:(ci + 1) * per_class]
        cls_names = {p.split("/")[0] for p in chunk}
        assert len(cls_names) == 1
        classes_in_order.append(chunk[0].split("/")[0])
        samples.append([int(p.split("/s")[1]) for p in chunk])
    class_ks = ks[::per_class]
    assert ks == [k for k in class_ks for _ in range(per_class)]
    return {
        "seed": seed,
        "selected_classes": classes_in_order,
        "rotation_k": class_ks,
        "sample_indices": samples,
        "support_labels": np.asarray(ys).astype(int).tolist(),
        "target_labels": np.asarray(yt).astype(int).tolist(),
        "returned_seed": int(out_seed),
    }


def record_split(n_classes, val_seed_arg, split):
    """Run the REFERENCE load_dataset ratio-split branch on synthetic keys,
    plus the derived-seed math of __init__ (data.py:132-142)."""
    self = Cls.__new__(Cls)
    val_seed = np.random.RandomState(seed=val_seed_arg).randint(1, 999999)
    self.seed = {"val": int(val_seed)}
    self.args = types.SimpleNamespace(
        sets_are_pre_split=False, load_into_memory=False
    )
    self.train_val_test_split = split
    keys = [f"c{i:03d}" for i in range(n_classes)]
    self.load_datapaths = lambda: (
        {k: [f"{k}/s00"] for k in keys}, {k: k for k in keys}, None
    )
    splits = Cls.load_dataset(self)
    return {
        "n_classes": n_classes,
        "val_seed_arg": val_seed_arg,
        "derived_val_seed": int(val_seed),
        "split": list(split),
        "train_classes": list(splits["train"].keys()),
        "val_classes": list(splits["val"].keys()),
        "test_classes": list(splits["test"].keys()),
    }


def main():
    fixture = {"configs": [], "splits": [], "derived_seeds": []}
    configs = [
        dict(n_classes=30, samples_per_class=20, num_classes_per_set=5,
             num_samples_per_class=1, num_target_samples=1),
        dict(n_classes=30, samples_per_class=20, num_classes_per_set=20,
             num_samples_per_class=1, num_target_samples=1),
        dict(n_classes=30, samples_per_class=20, num_classes_per_set=5,
             num_samples_per_class=5, num_target_samples=2),
    ]
    seeds = [0, 1, 7, 104, 12345, 999999]
    for cfg in configs:
        stub = make_stub(**cfg)
        episodes = [record_episode(stub, s) for s in seeds]
        fixture["configs"].append({"config": cfg, "episodes": episodes})

    fixture["splits"] = [
        record_split(50, 0, [0.7, 0.15, 0.15]),
        record_split(50, 104, [0.8, 0.1, 0.1]),
        record_split(1623, 0, [0.70918861, 0.03080872, 0.26000266]),
    ]
    for arg in (0, 104, 12345):
        fixture["derived_seeds"].append({
            "arg": arg,
            "derived": int(np.random.RandomState(seed=arg).randint(1, 999999)),
        })

    with open(OUT, "w") as f:
        json.dump(fixture, f, indent=1)
    n_eps = sum(len(c["episodes"]) for c in fixture["configs"])
    print(f"wrote {OUT}: {n_eps} episodes, {len(fixture['splits'])} splits")


if __name__ == "__main__":
    main()
