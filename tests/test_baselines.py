"""Tests for the two non-meta baselines (VERDICT r1 item 4).

``GradientDescentLearner`` — reference ``gradient_descent.py:98-124``: real
Adam fine-tuning of shared weights per task; evaluation also mutates by
design. ``MatchingNetsLearner`` — reference ``matching_nets.py:128,338-379``:
cosine attention over support embeddings, including the ``parity_bug``
switch reproducing the reference's support-label loss target. Each learner
also gets an end-to-end ExperimentBuilder smoke run (incl. the top-N
checkpoint-ensemble test path).
"""

import os

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.data import MetaLearningSystemDataLoader
from howtotrainyourmamlpytorch_tpu.experiment_builder import ExperimentBuilder
from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    GradientDescentLearner,
    MAMLConfig,
    MatchingNetsLearner,
)
from howtotrainyourmamlpytorch_tpu.utils import storage
from howtotrainyourmamlpytorch_tpu.utils.parser_utils import args_to_maml_config

from test_data import make_args, make_dataset_dir
from test_experiment import _experiment_args


def _cfg(**kw):
    defaults = dict(
        backbone=BackboneConfig(
            num_stages=2, num_filters=4, per_step_bn_statistics=False,
            num_steps=2, num_classes=5, image_height=8, image_width=8,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        meta_learning_rate=0.01,
    )
    defaults.update(kw)
    return MAMLConfig(**defaults)


def _separable_batch(rng, b=2, n=5, k=1, t=1, hw=8):
    """Tasks where class identity is linearly recoverable (fixed class
    prototypes + small noise) so a few Adam steps visibly reduce loss."""
    protos = rng.randn(n, 1, hw, hw).astype(np.float32)

    def episode(m):
        return np.stack(
            [protos + 0.1 * rng.randn(n, 1, hw, hw).astype(np.float32)
             for _ in range(m)], axis=1
        )  # (N, m, 1, hw, hw)

    xs = np.stack([episode(k) for _ in range(b)])
    xt = np.stack([episode(t) for _ in range(b)])
    ys = np.tile(np.arange(n)[None, :, None], (b, 1, k))
    yt = np.tile(np.arange(n)[None, :, None], (b, 1, t))
    return xs, xt, ys, yt


# ---------------------------------------------------------------------------
# Gradient-descent baseline
# ---------------------------------------------------------------------------


def test_gd_loss_decreases(rng):
    learner = GradientDescentLearner(_cfg())
    state = learner.init_state(jax.random.PRNGKey(0))
    batch = _separable_batch(rng)
    first = None
    for _ in range(12):
        state, losses = learner.run_train_iter(state, batch, epoch=0)
        if first is None:
            first = float(losses["loss"])
    last = float(losses["loss"])
    assert np.isfinite(last)
    assert last < 0.5 * first, (first, last)


def test_gd_eval_mutates_state_by_design(rng):
    """The reference fine-tunes during eval too (gradient_descent.py:108,124);
    run_validation_iter must return an evolved state."""
    learner = GradientDescentLearner(_cfg())
    state = learner.init_state(jax.random.PRNGKey(0))
    # Snapshot before: the eval step donates its input state (the old
    # buffers are consumed — eval mutates by design).
    theta_before = [np.asarray(l) for l in jax.tree.leaves(state.theta)]
    iter_before = int(state.iteration)
    batch = _separable_batch(rng)
    new_state, losses, preds = learner.run_validation_iter(state, batch)
    assert np.isfinite(float(losses["loss"]))
    # (B, N*T, classes) per-task preds for the ensemble path.
    assert preds.shape == (2, 5, 5)
    changed = any(
        not np.array_equal(a, np.asarray(b))
        for a, b in zip(theta_before, jax.tree.leaves(new_state.theta))
    )
    assert changed
    assert int(new_state.iteration) == iter_before + 1


def test_gd_metrics_are_last_tasks(rng):
    """Reference returns the LAST task's loss/acc (gradient_descent.py:122)."""
    learner = GradientDescentLearner(_cfg())
    state = learner.init_state(jax.random.PRNGKey(0))
    xs, xt, ys, yt = _separable_batch(rng, b=3)
    # Make the last task's target labels deliberately wrong -> high loss.
    yt_bad = yt.copy()
    yt_bad[-1] = (yt[-1] + 1) % 5
    _, losses_good, _ = learner.run_validation_iter(state, (xs, xt, ys, yt))
    state2 = learner.init_state(jax.random.PRNGKey(0))
    _, losses_bad, _ = learner.run_validation_iter(state2, (xs, xt, ys, yt_bad))
    assert float(losses_bad["loss"]) > float(losses_good["loss"])


# ---------------------------------------------------------------------------
# Matching-nets baseline
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("parity_bug", [False, True])
def test_matching_nets_trains(rng, parity_bug):
    learner = MatchingNetsLearner(_cfg(), parity_bug=parity_bug)
    state = learner.init_state(jax.random.PRNGKey(0))
    batch = _separable_batch(rng)
    for _ in range(12):
        state, losses = learner.run_train_iter(state, batch, epoch=0)
    assert np.isfinite(float(losses["loss"]))
    if not parity_bug:
        # The corrected formulation learns the separable toy task.
        assert float(losses["accuracy"]) > 0.8


def test_matching_nets_eval_pure(rng):
    """Eval discards running stats and weight updates: state unchanged,
    repeated eval identical."""
    learner = MatchingNetsLearner(_cfg())
    state = learner.init_state(jax.random.PRNGKey(0))
    batch = _separable_batch(rng)
    state1, losses1, preds1 = learner.run_validation_iter(state, batch)
    state2, losses2, preds2 = learner.run_validation_iter(state, batch)
    assert state1 is state
    np.testing.assert_array_equal(np.asarray(preds1), np.asarray(preds2))
    assert float(losses1["loss"]) == float(losses2["loss"])
    assert preds1.shape == (2, 5, 5)


def test_matching_nets_parity_bug_changes_loss(rng):
    """The two loss formulations genuinely differ on the same weights."""
    a = MatchingNetsLearner(_cfg(), parity_bug=False)
    b = MatchingNetsLearner(_cfg(), parity_bug=True)
    state_a = a.init_state(jax.random.PRNGKey(0))
    state_b = b.init_state(jax.random.PRNGKey(0))
    batch = _separable_batch(rng)
    _, la, _ = a.run_validation_iter(state_a, batch)
    _, lb, _ = b.run_validation_iter(state_b, batch)
    assert float(la["loss"]) != float(lb["loss"])


# ---------------------------------------------------------------------------
# ExperimentBuilder smoke runs (CPU, tiny) — one per baseline
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("learner_cls,model_tag", [
    (GradientDescentLearner, "gradient-descent"),
    (MatchingNetsLearner, "matching-nets"),
])
def test_experiment_builder_baseline_end_to_end(
    tmp_path, monkeypatch, learner_cls, model_tag
):
    make_dataset_dir(tmp_path / "omniglot_mini")
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    args = _experiment_args(tmp_path)
    args.model = model_tag
    model = learner_cls(args_to_maml_config(args))
    builder = ExperimentBuilder(
        args=args, data=MetaLearningSystemDataLoader, model=model, device=None
    )
    test_losses = builder.run_experiment()
    assert 0.0 <= test_losses["test_accuracy_mean"] <= 1.0

    logs = os.path.join(str(tmp_path / "exp"), "logs")
    stats = storage.load_statistics(logs)
    assert len(stats["epoch"]) == 3
    assert os.path.exists(os.path.join(logs, "test_summary.csv"))
