"""Serving runtime mechanics: the zero-recompile contract, the
adapted-params cache, and the micro-batcher.

The recompile test is the serving twin of ``tests/test_sanitizers.py``: a
mixed-shape request stream (5w1s, 5w5s, 3w1s, varying query counts) must
compile each serve program exactly once per SHAPE CLASS under the PR 2
``compile_guard`` — request count must never mint compiles.
"""

import threading
import time

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
    MatchingNetsLearner,
)
from howtotrainyourmamlpytorch_tpu.serve import (
    AdaptedParamsCache,
    MicroBatcher,
    ServeConfig,
    ServingAPI,
    ServingEngine,
    support_digest,
)


def tiny_cfg(**kw):
    defaults = dict(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=4,
            image_height=8,
            image_width=8,
            num_classes=5,
            per_step_bn_statistics=True,
            num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
    )
    defaults.update(kw)
    return MAMLConfig(**defaults)


def make_engine(**serve_kw):
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    return ServingEngine(learner, state, ServeConfig(**serve_kw))


def episode(rng, way=5, shot=1, query=3):
    img = (1, 8, 8)
    xs = rng.rand(way * shot, *img).astype(np.float32)
    ys = np.repeat(np.arange(way), shot).astype(np.int32)
    xq = rng.rand(query, *img).astype(np.float32)
    return xs, ys, xq


# ---------------------------------------------------------------------------
# Zero per-request recompiles (compile_guard-pinned)
# ---------------------------------------------------------------------------


def test_mixed_shape_stream_compiles_once_per_bucket(rng, compile_guard):
    """5w1s / 5w5s / 3w1s with varying query counts, three passes over the
    stream: adapt compiles once per distinct support shape, classify once
    per distinct query shape, and NOTHING recompiles on repeat traffic."""
    engine = make_engine(meta_batch_size=2, max_wait_ms=0.0)
    stream = [
        (5, 1, 3),
        (5, 5, 3),
        (3, 1, 2),
        (5, 1, 15),
        (5, 1, 3),  # repeat bucket, fresh data
    ]
    with compile_guard() as guard:
        for _ in range(3):  # repeat passes: request count must not compile
            for way, shot, query in stream:
                ep = engine.prepare_episode(*episode(rng, way, shot, query))
                engine.dispatch([ep])
    # Distinct adapt signatures: support counts {5, 25, 3}; distinct
    # classify signatures: query counts {3, 2, 15}.
    guard.assert_compiles("serve_adapt_maml", exactly=3)
    guard.assert_compiles("serve_classify_maml", exactly=3)
    guard.assert_unique_signatures("serve_adapt_maml")
    guard.assert_unique_signatures("serve_classify_maml")
    # The engine's own compile table (exported at /metrics) agrees.
    table = engine.compile_table()
    assert sum(v for k, v in table.items() if k.startswith("adapt:")) == 3
    assert sum(v for k, v in table.items() if k.startswith("classify:")) == 3
    assert all(v == 1 for v in table.values()), table


def test_traffic_level_does_not_mint_signatures(rng, compile_guard):
    """1, 2, and 3 concurrent episodes of one bucket all ride the same
    padded (meta_batch,) program — concurrency is not a shape."""
    engine = make_engine(meta_batch_size=3, max_wait_ms=0.0)
    eps = [
        engine.prepare_episode(*episode(rng)) for _ in range(6)
    ]
    with compile_guard() as guard:
        engine.dispatch(eps[:1])
        engine.dispatch(eps[1:3])
        engine.dispatch(eps[3:6])
    guard.assert_compiles("serve_adapt_maml", exactly=1)
    guard.assert_compiles("serve_classify_maml", exactly=1)


def test_warmup_precompiles_declared_buckets(rng, compile_guard):
    engine = make_engine(meta_batch_size=2, max_wait_ms=0.0)
    with compile_guard() as guard:
        engine.warmup([(5, 1, 3), (5, 5, 3)])
        before = guard.count("serve_adapt_maml")
        assert len(engine.cache) == 0, "warmup must not occupy cache capacity"
        ep = engine.prepare_episode(*episode(rng, 5, 5, 3))
        engine.dispatch([ep])
    assert before == 2
    guard.assert_compiles("serve_adapt_maml", exactly=2)  # no new compile


# ---------------------------------------------------------------------------
# Adapted-params cache
# ---------------------------------------------------------------------------


def test_cache_lru_eviction_and_digest():
    cache = AdaptedParamsCache(capacity=2)
    rng = np.random.RandomState(0)
    keys = []
    for seed in range(3):
        xs, ys, _ = episode(np.random.RandomState(seed))
        keys.append(support_digest(xs, ys, learner="maml", state_version=0))
    assert len(set(keys)) == 3
    cache.put(keys[0], "a")
    cache.put(keys[1], "b")
    assert cache.get(keys[0]) == "a"  # refreshes recency
    cache.put(keys[2], "c")  # evicts keys[1] (LRU)
    assert keys[1] not in cache
    assert cache.get(keys[0]) == "a" and cache.get(keys[2]) == "c"
    assert cache.evictions == 1
    # digest covers dtype: same bytes, different dtype must not collide
    xs, ys, _ = episode(rng)
    d32 = support_digest(xs, ys, learner="maml", state_version=0)
    d8 = support_digest(
        xs.astype(np.uint8), ys, learner="maml", state_version=0
    )
    assert d32 != d8


def test_cache_hit_skips_adapt_program(rng):
    engine = make_engine(meta_batch_size=2, max_wait_ms=0.0)
    xs, ys, xq = episode(rng)
    ep1 = engine.prepare_episode(xs, ys, xq)
    engine.dispatch([ep1])
    adapt_count = engine.metrics.adapt_latency.snapshot()["count"]
    # Same support, different queries: adapt must not run again.
    ep2 = engine.prepare_episode(xs, ys, rng.rand(3, 1, 8, 8).astype(np.float32))
    engine.dispatch([ep2])
    assert engine.metrics.adapt_latency.snapshot()["count"] == adapt_count
    assert engine.metrics.cache_hits.value == 1
    assert engine.metrics.cache_misses.value == 1


def test_state_swap_invalidates_cache(rng):
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    engine = ServingEngine(
        learner, state, ServeConfig(meta_batch_size=2, max_wait_ms=0.0)
    )
    xs, ys, xq = episode(rng)
    first = engine.dispatch([engine.prepare_episode(xs, ys, xq)])[0]
    assert len(engine.cache) == 1
    state2 = learner.init_state(jax.random.key(1))
    version = engine.update_state(state2)
    assert version == 1
    assert len(engine.cache) == 0
    second = engine.dispatch([engine.prepare_episode(xs, ys, xq)])[0]
    assert engine.metrics.cache_hits.value == 0
    assert not np.array_equal(first, second), "new weights must answer"


def test_mn_cache_artifact_is_embeddings_not_params(rng):
    """Matching nets cache support embeddings (KBs), not parameter trees."""
    learner = MatchingNetsLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    engine = ServingEngine(
        learner, state, ServeConfig(meta_batch_size=2, max_wait_ms=0.0)
    )
    ep = engine.prepare_episode(*episode(rng))
    engine.dispatch([ep])
    artifact = engine.cache.get(ep.digest)
    assert set(artifact) == {"support_emb", "support_labels"}
    assert artifact["support_emb"].shape == (5, 5)  # (S, num_classes)


# ---------------------------------------------------------------------------
# Micro-batcher
# ---------------------------------------------------------------------------


def test_batcher_collates_full_group_into_one_dispatch(rng):
    engine = make_engine(meta_batch_size=3, max_wait_ms=5000.0)
    batcher = MicroBatcher(engine)
    try:
        eps = [engine.prepare_episode(*episode(rng)) for _ in range(3)]
        futures = [batcher.submit(ep) for ep in eps]
        logits = [f.result(timeout=30) for f in futures]
    finally:
        batcher.close()
    # Full group (== max_batch) flushed as ONE meta-batch dispatch well
    # before the 5 s deadline.
    assert engine.metrics.batches_dispatched.value == 1
    assert engine.metrics.padded_tasks.value == 0
    assert all(l.shape == (3, 5) for l in logits)


def test_batcher_deadline_flushes_partial_group(rng):
    engine = make_engine(meta_batch_size=4, max_wait_ms=10.0)
    batcher = MicroBatcher(engine)
    try:
        t0 = time.perf_counter()
        future = batcher.submit(engine.prepare_episode(*episode(rng)))
        logits = future.result(timeout=30)
        waited_ms = (time.perf_counter() - t0) * 1e3
    finally:
        batcher.close()
    assert logits.shape == (3, 5)
    assert waited_ms >= 10.0, "partial group must wait out the deadline"
    assert engine.metrics.padded_tasks.value == 3  # 1 real + 3 pad tasks


def test_batcher_groups_by_bucket(rng):
    """Mixed-bucket concurrent traffic dispatches per bucket, never mixed."""
    engine = make_engine(meta_batch_size=2, max_wait_ms=20.0)
    batcher = MicroBatcher(engine)
    try:
        futs = []
        for way, shot, query in [(5, 1, 3), (3, 1, 2), (5, 1, 3), (3, 1, 2)]:
            ep = engine.prepare_episode(*episode(rng, way, shot, query))
            futs.append((query, batcher.submit(ep)))
        for query, fut in futs:
            assert fut.result(timeout=30).shape == (query, 5)
    finally:
        batcher.close()
    assert engine.metrics.batches_dispatched.value == 2
    table = engine.metrics.bucket_table()
    assert table[(5, 1, 3)]["episodes"] == 2
    assert table[(3, 1, 2)]["episodes"] == 2


def test_batcher_propagates_dispatch_errors_typed(rng, monkeypatch):
    """Engine failures surface as DispatchFailedError (original exception
    as __cause__) — callers branch on type, not message."""
    from howtotrainyourmamlpytorch_tpu.serve import DispatchFailedError

    engine = make_engine(meta_batch_size=2, max_wait_ms=0.0)
    batcher = MicroBatcher(engine)

    def boom(eps):
        raise RuntimeError("device fell over")

    monkeypatch.setattr(engine, "dispatch", boom)
    try:
        future = batcher.submit(engine.prepare_episode(*episode(rng)))
        with pytest.raises(DispatchFailedError, match="device fell over") as err:
            future.result(timeout=30)
        assert isinstance(err.value.__cause__, RuntimeError)
    finally:
        batcher.close()


def test_batcher_worker_survives_poisoned_episode(rng):
    """The fence (ISSUE 6 satellite): an exception escaping the dispatch
    path fails the poisoned group's futures with a typed error and keeps
    the worker alive — it must never strand every queued Future forever."""
    from howtotrainyourmamlpytorch_tpu.serve import DispatchFailedError

    engine = make_engine(meta_batch_size=2, max_wait_ms=0.0)
    batcher = MicroBatcher(engine)
    try:
        # A poisoned episode: hand-built (bypassing prepare_episode's
        # validation) with a support/label length mismatch that detonates
        # deep inside the engine at stack/pad time.
        good = engine.prepare_episode(*episode(rng))
        import dataclasses as dc

        poisoned = dc.replace(
            good, y_support=good.y_support[:-1], digest="poisoned"
        )
        bad_future = batcher.submit(poisoned)
        with pytest.raises(DispatchFailedError):
            bad_future.result(timeout=30)
        assert batcher._worker.is_alive(), "worker thread must survive"
        # The worker keeps serving: a fresh well-formed request succeeds.
        ok_future = batcher.submit(engine.prepare_episode(*episode(rng)))
        assert ok_future.result(timeout=30).shape == (3, 5)
    finally:
        batcher.close()


def test_batcher_worker_survives_result_count_mismatch(rng, monkeypatch):
    from howtotrainyourmamlpytorch_tpu.serve import DispatchFailedError

    engine = make_engine(meta_batch_size=2, max_wait_ms=0.0)
    batcher = MicroBatcher(engine)
    real_dispatch = engine.dispatch
    monkeypatch.setattr(engine, "dispatch", lambda eps: [])
    try:
        future = batcher.submit(engine.prepare_episode(*episode(rng)))
        with pytest.raises(DispatchFailedError, match="0 results"):
            future.result(timeout=30)
        monkeypatch.setattr(engine, "dispatch", real_dispatch)
        ok = batcher.submit(engine.prepare_episode(*episode(rng)))
        assert ok.result(timeout=30).shape == (3, 5)
    finally:
        batcher.close()


def test_expired_deadline_dropped_before_dispatch(rng):
    """A request whose deadline passed while queued is failed with
    DeadlineExceededError and NOT dispatched — the device never runs work
    nobody is waiting for."""
    from howtotrainyourmamlpytorch_tpu.serve import DeadlineExceededError

    engine = make_engine(meta_batch_size=4, max_wait_ms=30.0)
    batcher = MicroBatcher(engine)
    try:
        ep = engine.prepare_episode(*episode(rng))
        ep.deadline = time.monotonic()  # already expired on arrival
        future = batcher.submit(ep)
        with pytest.raises(DeadlineExceededError):
            future.result(timeout=30)
        assert engine.metrics.batches_dispatched.value == 0
        assert engine.metrics.deadline_exceeded_total.value == 1
        # DeadlineExceededError IS a TimeoutError (pre-resilience contract).
        assert issubclass(DeadlineExceededError, TimeoutError)
    finally:
        batcher.close()


def test_tight_deadline_flushes_group_early(rng):
    """A short-budget request must not be parked for the full batching
    window: its deadline tightens the group flush."""
    engine = make_engine(meta_batch_size=4, max_wait_ms=60_000.0)
    batcher = MicroBatcher(engine)
    try:
        ep = engine.prepare_episode(*episode(rng))
        ep.deadline = time.monotonic() + 0.1
        t0 = time.perf_counter()
        future = batcher.submit(ep)
        logits = future.result(timeout=30)
        elapsed = time.perf_counter() - t0
    finally:
        batcher.close()
    assert logits.shape == (3, 5)
    assert elapsed < 30.0, "must flush at the deadline, not the 60 s window"


def test_batcher_close_drains_and_rejects(rng):
    engine = make_engine(meta_batch_size=4, max_wait_ms=60_000.0)
    batcher = MicroBatcher(engine)
    future = batcher.submit(engine.prepare_episode(*episode(rng)))
    batcher.close()  # must flush the pending partial group, not strand it
    assert future.result(timeout=5).shape == (3, 5)
    with pytest.raises(RuntimeError, match="closed"):
        batcher.submit(engine.prepare_episode(*episode(rng)))


def test_concurrent_submitters_all_answered(rng):
    api = ServingAPI(
        MAMLFewShotLearner(tiny_cfg()),
        MAMLFewShotLearner(tiny_cfg()).init_state(jax.random.key(0)),
        ServeConfig(meta_batch_size=4, max_wait_ms=2.0),
    )
    results: dict[int, np.ndarray] = {}
    errors: list[Exception] = []

    def client(i):
        r = np.random.RandomState(i)
        try:
            results[i] = api.classify(*episode(r))["logits"]
        except Exception as exc:  # pragma: no cover - failure detail
            errors.append(exc)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(12)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    finally:
        api.close()
    assert not errors
    assert len(results) == 12
    assert all(v.shape == (3, 5) for v in results.values())


# ---------------------------------------------------------------------------
# Hot-swap concurrency (ISSUE 6 satellite)
# ---------------------------------------------------------------------------


def test_hot_swap_hammer_never_mixes_state_versions(rng):
    """A writer thread hammers ``update_state`` while 8 reader threads
    classify the SAME episode (cache off): every dispatch must return
    logits bit-exact with ONE of the two pure states — any mixture (e.g.
    adapt under v0, classify under v1) would produce a third value. This
    pins the atomic published-state snapshot in the engine."""
    learner = MAMLFewShotLearner(tiny_cfg())
    s0 = learner.init_state(jax.random.key(0))
    s1 = learner.init_state(jax.random.key(1))
    engine = ServingEngine(
        learner,
        s0,
        ServeConfig(meta_batch_size=2, max_wait_ms=0.0, cache_capacity=0),
    )
    xs, ys, xq = episode(rng)
    ref0 = engine.dispatch([engine.prepare_episode(xs, ys, xq)])[0]
    engine.update_state(s1)
    ref1 = engine.dispatch([engine.prepare_episode(xs, ys, xq)])[0]
    assert not np.array_equal(ref0, ref1)
    engine.update_state(s0)

    stop = threading.Event()
    swap_count = [0]

    def writer():
        while not stop.is_set():
            engine.update_state(s1 if swap_count[0] % 2 == 0 else s0)
            swap_count[0] += 1
            time.sleep(0.0005)

    outputs: list[np.ndarray] = []
    out_lock = threading.Lock()
    errors: list[Exception] = []

    def reader():
        try:
            for _ in range(12):
                out = engine.dispatch(
                    [engine.prepare_episode(xs, ys, xq)]
                )[0]
                with out_lock:
                    outputs.append(out)
        except Exception as exc:  # pragma: no cover - failure detail
            errors.append(exc)

    writer_thread = threading.Thread(target=writer, daemon=True)
    readers = [
        threading.Thread(target=reader, daemon=True) for _ in range(8)
    ]
    writer_thread.start()
    for t in readers:
        t.start()
    for t in readers:
        t.join(timeout=120)
    stop.set()
    writer_thread.join(timeout=10)
    assert not errors
    assert len(outputs) == 96
    assert swap_count[0] > 0, "writer must actually have swapped"
    matched0 = sum(1 for o in outputs if np.array_equal(o, ref0))
    matched1 = sum(1 for o in outputs if np.array_equal(o, ref1))
    assert matched0 + matched1 == len(outputs), (
        "a dispatch mixed state versions: "
        f"{len(outputs) - matched0 - matched1} outputs match neither state"
    )


# ---------------------------------------------------------------------------
# Request validation
# ---------------------------------------------------------------------------


def test_malformed_episodes_rejected_at_the_front_door(rng):
    engine = make_engine(meta_batch_size=2)
    xs, ys, xq = episode(rng)
    with pytest.raises(ValueError, match="support labels"):
        engine.prepare_episode(xs, ys[:-1], xq)
    with pytest.raises(ValueError, match="expects"):
        engine.prepare_episode(
            rng.rand(5, 1, 9, 9).astype(np.float32), ys, xq
        )
    with pytest.raises(ValueError, match=r"\[0, 5\)"):
        engine.prepare_episode(xs, ys + 3, xq)
    with pytest.raises(ValueError, match="no query"):
        engine.prepare_episode(xs, ys, xq[:0])
    with pytest.raises(ValueError, match="mixed buckets"):
        engine.dispatch(
            [
                engine.prepare_episode(*episode(rng, 5, 1, 3)),
                engine.prepare_episode(*episode(rng, 5, 1, 2)),
            ]
        )


# ---------------------------------------------------------------------------
# Review-hardening pins
# ---------------------------------------------------------------------------


def test_ragged_and_gapped_support_sets_rejected(rng):
    """Bucket identity (way, shot) must be a well-defined SHAPE class: a
    ragged support set (uneven per-class counts) or a label gap would let
    two different support SIZES share a bucket and crash the whole
    co-batched dispatch group at np.stack — reject at the front door."""
    engine = make_engine(meta_batch_size=2)
    img = (1, 8, 8)
    # ragged: class 0 twice, class 1 once
    with pytest.raises(ValueError, match="class-uniform"):
        engine.prepare_episode(
            rng.rand(3, *img).astype(np.float32),
            np.asarray([0, 0, 1], np.int32),
            rng.rand(2, *img).astype(np.float32),
        )
    # label gap: way inferred as 3 but class 1 absent
    with pytest.raises(ValueError, match="class-uniform"):
        engine.prepare_episode(
            rng.rand(2, *img).astype(np.float32),
            np.asarray([0, 2], np.int32),
            rng.rand(2, *img).astype(np.float32),
        )
    # empty support: would adapt on a mean-of-empty (NaN) loss
    with pytest.raises(ValueError, match="no support"):
        engine.prepare_episode(
            rng.rand(0, *img).astype(np.float32),
            np.asarray([], np.int32),
            rng.rand(2, *img).astype(np.float32),
        )


def test_classify_timeout_raises_builtin_timeouterror(rng, monkeypatch):
    """Future.result raises concurrent.futures.TimeoutError, which on
    Python < 3.11 is NOT the builtin — the API must translate so embedders
    (and the HTTP 503 branch) can catch ``TimeoutError``."""
    from concurrent.futures import Future

    learner = MAMLFewShotLearner(tiny_cfg())
    api = ServingAPI(
        learner,
        learner.init_state(jax.random.key(0)),
        ServeConfig(meta_batch_size=2, max_wait_ms=0.0),
    )
    try:
        monkeypatch.setattr(
            api.batcher, "submit", lambda ep: Future()  # never resolves
        )
        with pytest.raises(TimeoutError, match="deadline"):
            api.classify(*episode(rng), timeout=0.05)
        assert api.metrics.request_errors.value == 1
        assert api.metrics.requests_total.value == 1  # offered, not hidden
    finally:
        api.close()


def test_failed_requests_still_counted(rng):
    learner = MAMLFewShotLearner(tiny_cfg())
    api = ServingAPI(
        learner,
        learner.init_state(jax.random.key(0)),
        ServeConfig(meta_batch_size=2, max_wait_ms=0.0),
    )
    try:
        xs, ys, xq = episode(rng)
        with pytest.raises(ValueError):
            api.classify(xs, ys[:-1], xq)
        assert api.metrics.requests_total.value == 1
        assert api.metrics.request_errors.value == 1
        assert "request_errors_total 1" in api.metrics_text()
    finally:
        api.close()


def test_gd_serving_uses_the_injected_learning_rate(rng, tmp_path):
    """The GD fine-tune lr is serve STATE, not config: (a) serving a live
    GDState uses its injected (epoch-schedule) lr bit-exactly; (b) a
    serving cold start recomputes that lr from the checkpoint's recorded
    training progress instead of resetting to the epoch-0 rate."""
    import jax.numpy as jnp

    from howtotrainyourmamlpytorch_tpu.models import GradientDescentLearner
    from howtotrainyourmamlpytorch_tpu.models.common import set_injected_lr
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import save_checkpoint

    cfg = tiny_cfg(total_epochs=10, total_iter_per_epoch=4)
    learner = GradientDescentLearner(cfg)
    state = learner.init_state(jax.random.key(0))
    # Simulate epoch-7 training: inject the decayed lr like run_train_iter.
    epoch = 7
    state = state._replace(
        opt_state=set_injected_lr(state.opt_state, learner._epoch_lr(epoch))
    )
    xs, ys, xq = episode(rng)
    istate = learner.inference_state(state)
    np.testing.assert_allclose(
        float(istate.fine_tune_lr), learner._epoch_lr(epoch), rtol=1e-6
    )

    engine = ServingEngine(
        learner, state, ServeConfig(meta_batch_size=2, max_wait_ms=0.0)
    )
    served = engine.dispatch([engine.prepare_episode(xs, ys, xq)])[0]
    # Reference LAST (the GD eval step donates state buffers).
    _, _, ref = learner.run_validation_iter(
        state,
        (xs.reshape(1, 5, 1, 1, 8, 8), xq.reshape(1, 3, 1, 1, 8, 8),
         ys.reshape(1, 5, 1), np.zeros((1, 3, 1), np.int32)),
    )
    np.testing.assert_array_equal(served, np.asarray(ref)[0])

    # Cold start: current_iter 30 at 4 iters/epoch -> epoch 7 schedule lr.
    fresh = GradientDescentLearner(cfg)
    full = fresh.init_state(jax.random.key(0))
    path = str(tmp_path / "gd_ckpt")
    save_checkpoint(path, full, {"current_iter": 30})
    loaded, exp = fresh.load_inference_state(path)
    assert exp["current_iter"] == 30
    np.testing.assert_allclose(
        float(loaded.fine_tune_lr), fresh._epoch_lr(7), rtol=1e-6
    )
    assert isinstance(loaded.fine_tune_lr, jnp.ndarray)
