"""Direct unit tests for ``utils/storage.py`` (previously covered only
incidentally through ``test_experiment.py``): CSV create/append/load
round-trips, ragged-row behavior, and the atomic-JSON crash contract."""

import json
import os

import pytest

from howtotrainyourmamlpytorch_tpu.utils import storage
from howtotrainyourmamlpytorch_tpu.utils.parser_utils import Bunch


# ---------------------------------------------------------------------------
# CSV statistics
# ---------------------------------------------------------------------------


def test_csv_create_overwrites_and_append_extends(tmp_path):
    exp = str(tmp_path)
    storage.save_statistics(exp, ["a", "b", "c"], create=True)
    storage.save_statistics(exp, [1, 2, 3])
    # create=True truncates: a restart that re-creates starts a fresh file.
    storage.save_statistics(exp, ["a", "b", "c"], create=True)
    storage.save_statistics(exp, [4.5, "x", -1])
    loaded = storage.load_statistics(exp)
    assert loaded == {"a": ["4.5"], "b": ["x"], "c": ["-1"]}


def test_csv_roundtrip_multiple_rows_preserves_order(tmp_path):
    exp = str(tmp_path)
    storage.save_statistics(exp, ["epoch", "loss"], create=True)
    for e in range(5):
        storage.save_statistics(exp, [e, e * 0.5])
    loaded = storage.load_statistics(exp)
    assert loaded["epoch"] == [str(e) for e in range(5)]
    assert loaded["loss"] == [str(e * 0.5) for e in range(5)]


def test_csv_custom_filename_isolated(tmp_path):
    exp = str(tmp_path)
    storage.save_statistics(exp, ["x"], create=True)
    storage.save_statistics(exp, ["y"], create=True, filename="other.csv")
    storage.save_statistics(exp, [1])
    storage.save_statistics(exp, [2], filename="other.csv")
    assert storage.load_statistics(exp) == {"x": ["1"]}
    assert storage.load_statistics(exp, filename="other.csv") == {"y": ["2"]}


def test_csv_ragged_rows_load_without_crashing(tmp_path):
    """Contract pin: a short row contributes only the columns it has, and
    surplus values in a long row are dropped (zip semantics) — loading must
    never raise on a file a crashed run left ragged."""
    exp = str(tmp_path)
    storage.save_statistics(exp, ["a", "b", "c"], create=True)
    storage.save_statistics(exp, [1, 2])         # short row
    storage.save_statistics(exp, [3, 4, 5, 6])   # long row
    loaded = storage.load_statistics(exp)
    assert loaded["a"] == ["1", "3"]
    assert loaded["b"] == ["2", "4"]
    assert loaded["c"] == ["5"]  # short row contributed nothing to c


# ---------------------------------------------------------------------------
# Atomic JSON
# ---------------------------------------------------------------------------


def test_save_to_json_roundtrip_and_no_tmp_left(tmp_path):
    path = str(tmp_path / "log.json")
    storage.save_to_json(path, {"k": [1, 2], "s": "v"})
    assert storage.load_from_json(path) == {"k": [1, 2], "s": "v"}
    assert not os.path.exists(path + ".tmp")


def test_save_to_json_crash_mid_dump_keeps_old_file(tmp_path, monkeypatch):
    """The satellite fix: a crash mid-dump must not destroy the existing
    file (the old truncate-then-write lost ``summary_statistics.json`` /
    ``experiment_log.json`` permanently)."""
    path = str(tmp_path / "log.json")
    storage.save_to_json(path, {"epoch": 1})

    def boom(*args, **kwargs):
        raise RuntimeError("simulated crash mid-dump")

    monkeypatch.setattr(storage.json, "dump", boom)
    with pytest.raises(RuntimeError, match="mid-dump"):
        storage.save_to_json(path, {"epoch": 2})
    monkeypatch.undo()
    assert storage.load_from_json(path) == {"epoch": 1}


def test_experiment_log_create_and_update(tmp_path):
    logs = str(tmp_path)
    args = Bunch({"seed": 1, "dataset_name": "omniglot"})
    storage.create_json_experiment_log(logs, args)
    storage.update_json_experiment_log_epoch_stats(
        {"train_loss_mean": 0.5}, logs
    )
    storage.update_json_experiment_log_epoch_stats(
        {"train_loss_mean": 0.25}, logs
    )
    summary = storage.load_from_json(os.path.join(logs, "experiment_log.json"))
    assert summary["seed"] == 1
    assert summary["epoch_stats"]["train_loss_mean"] == [0.5, 0.25]
    assert summary["experiment_status"][0][1] == "initialization"
    # Raw JSON on disk is valid (atomic write published a complete file).
    with open(os.path.join(logs, "experiment_log.json")) as f:
        json.load(f)


def test_build_experiment_folder_idempotent(tmp_path):
    first = storage.build_experiment_folder(str(tmp_path / "exp"))
    second = storage.build_experiment_folder(str(tmp_path / "exp"))
    assert first == second
    for p in first:
        assert os.path.isdir(p)
