"""Unified telemetry subsystem (ISSUE 5): shared registry, structured event
log, on-demand profiling, and the hot-path contract.

The load-bearing guarantees pinned here:

* telemetry-ON runs of the REAL K=1 and K=25 train paths compile each step
  program exactly once (``compile_guard``) and add ZERO per-iteration host
  syncs (``jax.device_get`` counted during the loop);
* the serving ``/metrics`` primitives ARE the shared registry classes
  (one implementation, byte-identical scrape surface);
* events buffer host-side and only flush at boundaries; the JSONL schema
  round-trips through ``tools/telemetry_report.py``;
* sentinel trips, checkpoint saves/loads, preemption/requeue all
  self-report through the global sink (driven end-to-end with the
  ``utils/faultinject.py`` hooks against the real ``ExperimentBuilder``);
* a SIGTERM landing inside a profiler capture window still flushes the
  trace on the requeue exit path (the ISSUE 5 fix).
"""

import json
import math
import os
import signal as signal_module
import subprocess
import sys

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.telemetry import (
    EventLog,
    MetricsRegistry,
    ProfilerController,
    TrainTelemetry,
    read_events,
)
from howtotrainyourmamlpytorch_tpu.telemetry import events as telemetry_events
from howtotrainyourmamlpytorch_tpu.utils import faultinject, storage

from test_data import make_dataset_dir
from test_sanitizers import tiny_batch, tiny_cfg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    """No fault plan and no global event sink may leak between tests."""
    faultinject.deactivate()
    previous = telemetry_events.install(None)
    yield
    telemetry_events.install(previous)
    faultinject.reset()


@pytest.fixture
def dataset_env(tmp_path, monkeypatch):
    make_dataset_dir(tmp_path / "omniglot_mini")
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    return tmp_path


@pytest.fixture
def fake_profiler(monkeypatch):
    """Records jax.profiler start/stop calls instead of tracing."""
    calls: list[tuple] = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda path: calls.append(("start", path))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop",))
    )
    return calls


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def test_registry_get_or_create_and_snapshot():
    reg = MetricsRegistry()
    reg.counter("compiles").inc(3)
    assert reg.counter("compiles") is reg.counter("compiles")
    reg.gauge("queue_depth").set(7)
    win = reg.window("step_ms")
    for v in (1.0, 2.0, 3.0, 4.0):
        win.observe(v)
    snap = reg.snapshot()
    assert snap["counters"]["compiles"] == 3
    assert snap["gauges"]["queue_depth"] == 7.0
    assert snap["windows"]["step_ms"]["count"] == 4
    # Nearest-rank percentiles (LatencyStat semantics, shared with serve).
    assert snap["windows"]["step_ms"]["p50_ms"] == 3.0
    assert snap["windows"]["step_ms"]["p95_ms"] == 4.0


def test_serve_metrics_reexports_shared_registry_classes():
    """The dedupe pin: serve/metrics.py runs the SAME implementation the
    trainer uses — not a drifted copy (the Prometheus scrape surface is
    covered unchanged by test_serve_http.py)."""
    from howtotrainyourmamlpytorch_tpu.serve import metrics as serve_metrics
    from howtotrainyourmamlpytorch_tpu.telemetry import registry

    assert serve_metrics.Counter is registry.Counter
    assert serve_metrics.LatencyStat is registry.LatencyStat


# ---------------------------------------------------------------------------
# Event log
# ---------------------------------------------------------------------------


def test_event_log_buffers_until_flush(tmp_path):
    log = EventLog(str(tmp_path / "telemetry.jsonl"))
    log.emit("step", iter=1, step_s=0.5)
    log.emit("step", iter=2, step_s=0.25)
    assert not os.path.exists(log.path)  # emit is buffer-only: no I/O
    assert log.pending() == 2
    assert log.flush() == 3  # schema header + 2 events
    assert log.pending() == 0
    log.emit("step", iter=3, step_s=0.125)
    log.flush()
    events = read_events(log.path)
    assert [e["type"] for e in events] == ["schema", "step", "step", "step"]
    assert events[0]["version"] == 1
    assert [e.get("iter") for e in events[1:]] == [1, 2, 3]


def test_event_log_serializes_nonfinite_as_null(tmp_path):
    log = EventLog(str(tmp_path / "telemetry.jsonl"))
    log.emit("epoch_summary", loss=float("nan"), acc=np.float32(0.5),
             inf=float("inf"),
             nested={"deep": float("nan"), "vals": [1.0, float("inf")]})
    log.flush()
    raw = open(log.path).read()
    assert "NaN" not in raw and "Infinity" not in raw  # strict JSON
    event = read_events(log.path)[-1]
    assert event["loss"] is None and event["inf"] is None
    assert event["acc"] == 0.5
    # Recursive scrub: a NaN deep inside a snapshot payload degrades to
    # null instead of raising at flush time and killing the run.
    assert event["nested"]["deep"] is None
    assert event["nested"]["vals"] == [1.0, None]


def test_flush_io_failure_degrades_without_raising(tmp_path, capsys):
    """Telemetry is an observability extra: a disk-full/NFS blip at a flush
    boundary must drop the batch with a warning, never crash the run (or
    turn a preemption-requeue exit into a crash)."""
    log = EventLog(str(tmp_path / "missing_dir" / "telemetry.jsonl"))
    log.emit("step", iter=1)
    assert log.flush() == 0  # open() fails: degraded, not raised
    log.emit("step", iter=2)
    assert log.flush() == 0
    warnings = capsys.readouterr().err
    assert warnings.count("telemetry flush") == 1  # warn once, not per flush
    os.makedirs(tmp_path / "missing_dir")
    log.emit("step", iter=3)
    assert log.flush() == 2  # recovered: schema header + the new event
    events = read_events(log.path)
    assert [e["type"] for e in events] == ["schema", "step"]


def test_flush_drops_unserializable_records_without_raising(tmp_path, capsys):
    """A non-JSON payload (ndarray, set) slipping past _jsonable must drop
    only the offending record at flush time — never raise through a
    boundary or the requeue exit."""
    log = EventLog(str(tmp_path / "telemetry.jsonl"))
    log.emit("good", iter=1)
    log.emit("bad", blob=np.zeros(3))  # ndim>0: passes _jsonable untouched
    log.emit("good", iter=2)
    assert log.flush() == 3  # schema + the two good records
    assert "non-JSON payloads" in capsys.readouterr().err
    events = read_events(log.path)
    assert [e["type"] for e in events] == ["schema", "good", "good"]


def test_read_events_refuses_newer_schema(tmp_path):
    path = tmp_path / "telemetry.jsonl"
    path.write_text(json.dumps({"t": 0.0, "type": "schema", "version": 99}) + "\n")
    with pytest.raises(ValueError, match="schema 99"):
        read_events(str(path))


def test_global_sink_install_restore_and_noop(tmp_path):
    telemetry_events.emit("orphan", x=1)  # no sink: must be a silent no-op
    log = EventLog(str(tmp_path / "telemetry.jsonl"))
    previous = telemetry_events.install(log)
    telemetry_events.emit("hello", x=2)
    assert telemetry_events.install(previous) is log  # restore returns ours
    telemetry_events.emit("orphan", x=3)  # dropped again
    log.flush()
    events = [e for e in read_events(log.path) if e["type"] != "schema"]
    assert [e["type"] for e in events] == ["hello"]


# ---------------------------------------------------------------------------
# Hot-path contract: compile-once + zero per-iteration host syncs
# ---------------------------------------------------------------------------


def test_telemetry_on_k1_train_step_compiles_once_no_host_syncs(
    compile_guard, rng, tmp_path, monkeypatch
):
    """The acceptance criterion: full telemetry (event log, compile bridge,
    per-dispatch recording) on the REAL K=1 train path — exactly one
    compile of ``_train_step`` and zero ``jax.device_get`` calls outside
    the declared forced-read boundaries."""
    from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner

    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    batch = tiny_batch(rng)
    telemetry = TrainTelemetry(str(tmp_path), enabled=True)

    device_gets = {"n": 0}
    real_device_get = jax.device_get

    def counting_device_get(x):
        device_gets["n"] += 1
        return real_device_get(x)

    with telemetry.activate():
        with compile_guard() as guard:
            # Warm-up dispatch (the compile), then the counted steady state.
            state, _ = learner.run_train_iter(state, batch, epoch=0)
            telemetry.record_dispatch(1, n_iters=1, data_wait_s=0.0)
            monkeypatch.setattr(jax, "device_get", counting_device_get)
            for i in range(2, 6):
                state, _ = learner.run_train_iter(state, batch, epoch=0)
                telemetry.record_dispatch(i, n_iters=1, data_wait_s=0.0)
            # The forced-read boundary work — flush + HEARTBEAT write +
            # anomaly bookkeeping — inside the counted window too: the
            # introspection plane must add zero device reads of its own.
            telemetry.boundary(5, 0.0, reason="log")
            monkeypatch.setattr(jax, "device_get", real_device_get)
            jax.block_until_ready(state.theta)
        guard.assert_compiles("_train_step", exactly=1)
        guard.assert_unique_signatures("_train_step")
    assert device_gets["n"] == 0  # telemetry recording forced NO reads
    events = read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
    steps = [e for e in events if e["type"] == "step"]
    assert len(steps) == 4  # first dispatch only drops the anchor
    compiles = [e for e in events if e["type"] == "compile"]
    assert sum("_train_step" in e["name"] for e in compiles) == 1
    # The registry's production gauge: run progress, updated per dispatch.
    assert telemetry.registry.snapshot()["gauges"]["current_iter"] == 5.0


def test_telemetry_on_k25_multi_path_compiles_once(compile_guard, rng, tmp_path):
    from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner

    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    batches = [tiny_batch(rng) for _ in range(25)]
    telemetry = TrainTelemetry(str(tmp_path), enabled=True)
    with telemetry.activate():
        with compile_guard() as guard:
            for d in range(3):
                state, _ = learner.run_train_iters(state, batches, epoch=0)
                telemetry.record_dispatch(
                    (d + 1) * 25, n_iters=25, data_wait_s=0.0
                )
            jax.block_until_ready(state.theta)
        guard.assert_compiles("multi", exactly=1)
        guard.assert_unique_signatures("multi")
    steps = [
        e
        for e in read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
        if e["type"] == "step"
    ]
    assert [e["k"] for e in steps] == [25, 25]


# ---------------------------------------------------------------------------
# On-demand profiling
# ---------------------------------------------------------------------------


def test_profiler_start_flag_one_shot(fake_profiler, tmp_path):
    """The legacy --profile_trace_path semantics: one bounded capture at the
    start of the run, then never again."""
    ctl = ProfilerController(
        trace_path=str(tmp_path / "trace"), num_iters=3,
        trigger_path=str(tmp_path / "trigger"),
    )
    for _ in range(10):
        ctl.tick(1)
    assert fake_profiler == [("start", str(tmp_path / "trace")), ("stop",)]
    assert not ctl.active


def test_profiler_file_trigger_bounded_and_rearmable(fake_profiler, tmp_path):
    trigger = tmp_path / "trigger"
    ctl = ProfilerController(
        num_iters=2, trigger_path=str(trigger),
        default_trace_dir=str(tmp_path / "traces"),
    )
    ctl.tick(1)
    assert fake_profiler == []  # nothing armed, nothing requested
    trigger.touch()
    ctl.poll_trigger()
    assert not trigger.exists()  # consumed: one capture per touch
    ctl.tick(1)
    assert ctl.active
    ctl.tick(1)  # window of 2 complete
    assert not ctl.active
    trigger.touch()  # re-armable: a second touch captures again
    ctl.poll_trigger()
    ctl.tick(2)
    starts = [c for c in fake_profiler if c[0] == "start"]
    assert len(starts) == 2
    assert starts[0][1] != starts[1][1]  # each capture in its own directory
    assert fake_profiler.count(("stop",)) == 2


def test_profiler_signal_request_and_sigusr1_install(fake_profiler, tmp_path):
    telemetry = TrainTelemetry(str(tmp_path), enabled=True,
                               profile_num_iters=1)
    before = signal_module.getsignal(signal_module.SIGUSR1)
    with telemetry.activate():
        assert signal_module.getsignal(signal_module.SIGUSR1) is not before
        os.kill(os.getpid(), signal_module.SIGUSR1)
        telemetry.record_dispatch(1, n_iters=1)  # anchor
        telemetry.record_dispatch(2, n_iters=1)  # starts + completes capture
    assert signal_module.getsignal(signal_module.SIGUSR1) is before
    assert [c[0] for c in fake_profiler] == ["start", "stop"]
    types = [
        e["type"]
        for e in read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
    ]
    assert "profile_start" in types and "profile_stop" in types


# ---------------------------------------------------------------------------
# End-to-end through the real ExperimentBuilder (faultinject-driven)
# ---------------------------------------------------------------------------


def _run_skip_experiment(tmp):
    from test_faultinject import _builder, _exp_args

    faultinject.activate(faultinject.FaultPlan(nan_at_iter=1))
    builder = _builder(_exp_args(tmp, on_nonfinite="skip"))
    test_losses = builder.run_experiment()
    assert 0.0 <= test_losses["test_accuracy_mean"] <= 1.0
    return str(tmp / "exp" / "logs")


def test_e2e_event_stream_sentinel_and_checkpoints(dataset_env):
    """The whole run self-reports: step breakdown, compile events, sentinel
    trip (via the faultinject NaN hook), checkpoint save/alias/load,
    run_start/run_end — and the summary CSV carries the new data-wait
    columns next to the step-time ones."""
    logs = _run_skip_experiment(dataset_env)
    events = read_events(os.path.join(logs, "telemetry.jsonl"))
    types = [e["type"] for e in events]
    # "compile" is deliberately absent from this list: the module-level
    # learner cache (test_faultinject._LEARNERS) may have compiled this
    # config in an earlier test, making a zero-compile run the CORRECT
    # steady state; compile-event emission is pinned by the K=1
    # compile_guard test above.
    for expected in (
        "run_start", "step", "host_sync", "epoch_summary",
        "nonfinite_trip", "checkpoint_save", "checkpoint_alias",
        "checkpoint_load", "run_end",
    ):
        assert expected in types, f"missing {expected} in {sorted(set(types))}"
    # The sentinel trip rode the epoch-boundary forced read (skip policy).
    trip = next(e for e in events if e["type"] == "nonfinite_trip")
    assert trip["policy"] == "skip" and trip["trips"] == 1.0
    # Step events carry the full breakdown; the consumer-blocking wait +
    # device share sum to the step. With the device-prefetch stager active
    # (the default) the blocking wait is the STAGE wait — the synthesis
    # data_wait overlaps device compute and is reported off to the side.
    step = next(e for e in events if e["type"] == "step")
    assert step["step_s"] >= step["device_s"] >= 0.0
    assert step["data_wait_s"] >= 0.0 and step["stage_wait_s"] >= 0.0
    blocking = (
        step["stage_wait_s"] if step["staged"]
        else step["data_wait_s"] + step["stage_wait_s"]
    )
    assert math.isclose(
        step["device_s"], max(step["step_s"] - blocking, 0.0),
        rel_tol=1e-9,
    )
    # Checkpoint events carry durations + sizes from utils/checkpoint.py.
    save = next(e for e in events if e["type"] == "checkpoint_save")
    assert save["bytes"] > 0 and save["duration_s"] > 0
    # Satellite fix: the epoch CSV now separates data wait from step time
    # (and, since the device-prefetch stager, the stage wait as well).
    stats = storage.load_statistics(logs)
    for column in ("train_step_time_p50", "train_step_time_p95",
                   "train_data_wait_p50", "train_data_wait_p95",
                   "train_stage_wait_p50", "train_stage_wait_p95"):
        assert column in stats, column
    # ISSUE 12 quiet-on-golden receipts, from the SAME healthy run: the
    # live detector reported no anomaly, replaying the recorded step
    # samples through a fresh detector stays quiet too, and the heartbeat
    # landed with last-known progress + the builder extras.
    assert not [e for e in events if e["type"] == "anomaly"]
    from howtotrainyourmamlpytorch_tpu.telemetry import (
        RollingAnomalyDetector,
        read_heartbeat,
    )

    steps = [e for e in events if e["type"] == "step"]
    det = RollingAnomalyDetector()
    assert all(
        det.observe("step_time", float(e["step_s"]) / max(int(e["k"]), 1))
        is None
        for e in steps
    )
    doc = read_heartbeat(os.path.join(logs, "status.json"))
    assert doc is not None
    assert doc["current_iter"] > 0
    assert doc["trace_id"]
    assert "last_checkpoint_age_s" in doc
    assert "watchdog" in doc  # builder extra: armed/deadline/fired snapshot


def test_report_cli_schema_roundtrip(dataset_env):
    """The JSONL a real run writes parses through the report tool's summary
    (in-process AND via the CLI ``--json``), with consistent counts."""
    logs = _run_skip_experiment(dataset_env)
    sys.path.insert(0, REPO)
    from tools.telemetry_report import resolve_jsonl, summarize

    events = read_events(resolve_jsonl(str(dataset_env / "exp")))
    summary = summarize(events)
    n_step_events = sum(1 for e in events if e["type"] == "step")
    assert summary["iters"] >= n_step_events  # K>=1 expansion
    assert summary["breakdown"]["step"]["count"] == summary["iters"]
    assert summary["breakdown"]["data_wait"]["count"] == summary["iters"]
    # Steady state may legitimately show ZERO compiles (the module-level
    # learner cache reuses the compiled programs across tests); the
    # compile-event pin lives in the K=1 compile_guard test above.
    assert isinstance(summary["compiles"], list)
    assert summary["event_counts"]["step"] == n_step_events

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "tools/telemetry_report.py",
         os.path.join(logs, "telemetry.jsonl"), "--json"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    cli_summary = json.loads(proc.stdout)
    assert cli_summary["schema"] == summary["schema"]
    assert cli_summary["iters"] == summary["iters"]
    assert cli_summary["event_counts"] == summary["event_counts"]
    # Human rendering smoke: the table mode must not crash on the same run.
    proc_text = subprocess.run(
        [sys.executable, "tools/telemetry_report.py", str(dataset_env / "exp")],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120,
    )
    assert proc_text.returncode == 0, proc_text.stderr
    assert "step-time breakdown" in proc_text.stdout
    assert "compile timeline" in proc_text.stdout


def test_sigterm_inside_profile_window_flushes_trace(
    dataset_env, fake_profiler
):
    """ISSUE 5 satellite fix: a preemption landing inside the
    --profile_num_iters capture window must stop (flush) the trace on the
    requeue exit path, and the preemption/requeue events must reach the
    JSONL."""
    from howtotrainyourmamlpytorch_tpu.experiment_builder import (
        REQUEUE_EXIT_CODE,
    )

    from test_faultinject import _builder, _exp_args

    tmp = dataset_env
    faultinject.activate(faultinject.FaultPlan(sigterm_at_iter=1))
    builder = _builder(
        _exp_args(
            tmp,
            profile_trace_path=str(tmp / "trace"),
            profile_num_iters=100,  # window far larger than the run
        )
    )
    with pytest.raises(SystemExit) as exits:
        builder.run_experiment()
    assert exits.value.code == REQUEUE_EXIT_CODE
    assert [c[0] for c in fake_profiler] == ["start", "stop"]
    assert not builder.telemetry.profiler.active
    events = read_events(str(tmp / "exp" / "logs" / "telemetry.jsonl"))
    types = [e["type"] for e in events]
    assert "profile_start" in types
    assert "profile_stop" in types
    assert "preemption" in types
    assert "requeue_exit" in types
    requeue = next(e for e in events if e["type"] == "requeue_exit")
    assert requeue["code"] == REQUEUE_EXIT_CODE


def test_telemetry_flag_off_writes_no_jsonl(dataset_env):
    """--telemetry False: no event log, but step-time CSV stats survive."""
    from test_faultinject import _builder, _exp_args

    tmp = dataset_env
    builder = _builder(_exp_args(tmp, telemetry=False,
                                 total_epochs_before_pause=1))
    with pytest.raises(SystemExit):
        builder.run_experiment()
    assert not os.path.exists(
        str(tmp / "exp" / "logs" / "telemetry.jsonl")
    )
    stats = storage.load_statistics(str(tmp / "exp" / "logs"))
    assert "train_step_time_p50" in stats
    assert "train_data_wait_p50" in stats


# ---------------------------------------------------------------------------
# Mesh attribution (ISSUE 8): topology on step events + epoch CSV + report
# ---------------------------------------------------------------------------


def test_step_events_and_epoch_stats_carry_mesh_topology(tmp_path):
    """Multichip runs stamp every step event with ``n_devices``/
    ``mesh_shape`` and the epoch summary with NUMERIC ``n_devices``/
    ``mesh_dp``/``mesh_mp`` columns (``pack_and_save_metrics`` float()s
    every epoch key — a shape STRING would crash the CSV writer), so a
    throughput regression is attributable to a topology change from the
    telemetry alone."""
    telemetry = TrainTelemetry(
        str(tmp_path), enabled=True, n_devices=8, mesh_dp=8, mesh_mp=1
    )
    telemetry.record_dispatch(1, n_iters=1)
    telemetry.record_dispatch(2, n_iters=1)
    stats = telemetry.epoch_stats("train", epoch=0)
    assert stats["n_devices"] == 8
    assert stats["mesh_dp"] == 8
    assert stats["mesh_mp"] == 1
    for key in ("n_devices", "mesh_dp", "mesh_mp"):
        float(stats[key])  # the CSV packer's contract
    telemetry.flush()
    events = read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
    step = next(e for e in events if e["type"] == "step")
    assert step["n_devices"] == 8
    assert step["mesh_shape"] == "dp8xmp1"


def test_single_device_topology_defaults_keep_rows_comparable(tmp_path):
    """Single-chip runs carry the same columns (1 / "single"), so multichip
    and single-chip epochs stay comparable CSV rows under the stable-schema
    contract — including the <2-dispatch NaN path."""
    telemetry = TrainTelemetry(str(tmp_path), enabled=True)
    stats = telemetry.epoch_stats("train", epoch=0)  # zero dispatches
    assert stats["n_devices"] == 1
    assert stats["mesh_dp"] == 1
    assert stats["mesh_mp"] == 1
    assert telemetry.mesh_shape == "single"


def test_report_surfaces_mesh_topology():
    """tools/telemetry_report reads the topology off the step events
    themselves (pre-mesh logs default to 1 device / "single")."""
    from tools.telemetry_report import render_text, summarize

    def step(i, **kw):
        return {
            "type": "step", "t": float(i), "iter": i, "k": 1,
            "step_s": 0.1, "data_wait_s": 0.0, "stage_wait_s": 0.0,
            "device_s": 0.1, **kw,
        }

    summary = summarize(
        [step(1, n_devices=8, mesh_shape="dp8xmp1"),
         step(2, n_devices=8, mesh_shape="dp8xmp1")]
    )
    assert summary["n_devices"] == 8
    assert summary["mesh_shape"] == "dp8xmp1"
    assert "8 device(s)" in render_text(summary)
    assert "dp8xmp1" in render_text(summary)

    legacy = summarize([step(1), step(2)])  # pre-mesh event log
    assert legacy["n_devices"] == 1
    assert legacy["mesh_shape"] == "single"


# ---------------------------------------------------------------------------
# Fleet observability plane (ISSUE 12): trace/dispatch ids, streaming
# reader, heartbeat, anomaly detection, fleet report
# ---------------------------------------------------------------------------


def test_trace_id_stamped_on_every_event_from_every_thread(tmp_path):
    """The run-scoped trace_id rides the process-global event context, so
    EVERY emitter — the telemetry recorder itself, deep layers publishing
    through the global sink (checkpoint writer, stager, watchdog) —
    stamps the same id without threading it through signatures."""
    telemetry = TrainTelemetry(str(tmp_path), enabled=True,
                               trace_id="tracetest01",
                               process_index=1, process_count=2)
    with telemetry.activate():
        telemetry.record_dispatch(1, n_iters=1)
        telemetry.record_dispatch(2, n_iters=1)
        telemetry_events.emit("data_fault", iter=2)  # a deep-layer emitter
        telemetry.event("preemption", signal=15, iter=2)
    events = read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
    assert events and all(
        e.get("trace_id") == "tracetest01"
        for e in events if e["type"] != "schema"
    ), sorted({(e["type"], e.get("trace_id")) for e in events})
    # Host identity rides the context too: a deep emitter that knows
    # neither (the stager) still attributes to the rank that saw it —
    # a fleet merge must not default its lane to rank 0.
    fault = next(e for e in events if e["type"] == "data_fault")
    assert fault["process_index"] == 1 and fault["process_count"] == 2
    # Context is restored after activate: later emitters don't inherit it.
    assert telemetry_events.get_context().get("trace_id") != "tracetest01"


def test_trace_id_inherited_from_dispatcher_env(tmp_path, monkeypatch):
    """Every rank of a fleet phase inherits the dispatcher-exported trace
    id, so N ranks' streams merge into one correlated timeline."""
    monkeypatch.setenv(telemetry_events.TRACE_ID_ENV, "fleettrace99")
    t0 = TrainTelemetry(str(tmp_path), enabled=True, process_index=0)
    t1 = TrainTelemetry(str(tmp_path), enabled=True, process_index=1)
    assert t0.trace_id == t1.trace_id == "fleettrace99"
    monkeypatch.delenv(telemetry_events.TRACE_ID_ENV)
    t2 = TrainTelemetry(str(tmp_path), enabled=True)
    assert t2.trace_id and t2.trace_id != "fleettrace99"  # fresh per run


def test_step_events_carry_dispatch_id(tmp_path):
    """dispatch_id == the iteration the dispatch ended at — identical on
    every rank of a lockstep fleet, the cross-rank join key."""
    telemetry = TrainTelemetry(str(tmp_path), enabled=True)
    with telemetry.activate():
        for d in range(1, 4):
            telemetry.record_dispatch(d * 25, n_iters=25)
    steps = [
        e for e in read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
        if e["type"] == "step"
    ]
    assert [e["dispatch_id"] for e in steps] == [50, 75]
    assert [e["dispatch_id"] for e in steps] == [e["iter"] for e in steps]


def test_event_reader_streams_from_offset_and_since(tmp_path):
    from howtotrainyourmamlpytorch_tpu.telemetry import EventReader

    path = str(tmp_path / "telemetry.jsonl")
    log = EventLog(path, clock=lambda: 100.0)
    log.emit("a", iter=1)
    log.flush()
    reader = EventReader(path)
    first = reader.read()
    assert [e["type"] for e in first] == ["schema", "a"]
    assert reader.read() == []  # nothing new past the offset
    log.emit("b", iter=2)
    log.flush()
    assert [e["type"] for e in reader.read()] == ["b"]  # resumes mid-file
    # since-filter: schema lines always pass (the version refusal must not
    # depend on the window), stale events drop.
    events = EventReader(path).read(since=101.0)
    assert [e["type"] for e in events] == ["schema"]
    assert read_events(path, since=0.0) == read_events(path)


def test_event_reader_tolerates_torn_lines_and_incomplete_tail(
    tmp_path, capsys
):
    """The PR 11 torn-line contract regression-pinned through the NEW
    streaming path: a malformed mid-file line is skipped with a warning;
    an incomplete FINAL line (writer mid-append) is NOT consumed and
    parses on the next read once the writer finishes it."""
    from howtotrainyourmamlpytorch_tpu.telemetry import EventReader

    path = tmp_path / "telemetry.jsonl"
    path.write_text(
        json.dumps({"t": 1.0, "type": "a"}) + "\n"
        + '{"t": 2.0, "type": "to'  # torn by a concurrent writer
        + 'rn"}garbage\n'
        + json.dumps({"t": 3.0, "type": "b"}) + "\n"
        + '{"t": 4.0, "type": "tail'  # incomplete: no newline yet
    )
    reader = EventReader(str(path))
    events = reader.read()
    assert [e["type"] for e in events] == ["a", "b"]
    assert reader.torn_lines == 1
    assert "unparseable line" in capsys.readouterr().err
    # The writer finishes the tail line: the SAME reader picks it up.
    with open(path, "a") as f:
        f.write('_event"}\n')
    assert [e["type"] for e in reader.read()] == ["tail_event"]


def test_read_events_includes_complete_unterminated_final_line(tmp_path):
    """One-shot post-mortem semantics: a run SIGKILLed after its last
    event's closing brace but before the newline still surfaces that
    event through read_events (it may be the preemption/hang record that
    explains the death) — while the incremental reader leaves the
    unterminated line unconsumed for the writer to finish."""
    from howtotrainyourmamlpytorch_tpu.telemetry import EventReader

    path = tmp_path / "telemetry.jsonl"
    path.write_text(
        json.dumps({"t": 1.0, "type": "a"}) + "\n"
        + json.dumps({"t": 2.0, "type": "hang"})  # no trailing newline
    )
    assert [e["type"] for e in read_events(str(path))] == ["a", "hang"]
    # Tail-follow mode: the unterminated line stays pending (no warning,
    # no torn count), and the offset never advances past it.
    reader = EventReader(str(path))
    assert [e["type"] for e in reader.read()] == ["a"]
    assert reader.torn_lines == 0
    with open(path, "a") as f:
        f.write("\n")
    assert [e["type"] for e in reader.read()] == ["hang"]


def test_fleet_replayed_dispatch_ids_pair_by_occurrence(tmp_path):
    """Elastic lifecycle correctness: after a degrade/resume, replayed
    iterations reuse dispatch_ids under the SAME trace. The i-th
    occurrence on each rank pairs with the peers' i-th occurrence — a
    replay must not be skew-compared against a dead phase's sample."""
    from tools.telemetry_report import fleet_summarize

    path = tmp_path / "fleet.jsonl"
    lines = []
    # Phase 1: both ranks run dispatch 1 (tied) and dispatch 2, where
    # rank 1 stalls 10s (the hang) and rank 0 is fine.
    lines.append(json.dumps(_fleet_step(0, 1, 0.1, t=1.0)))
    lines.append(json.dumps(_fleet_step(1, 1, 0.1, t=1.0)))
    lines.append(json.dumps(_fleet_step(0, 2, 0.1, t=2.0)))
    lines.append(json.dumps(_fleet_step(1, 2, 10.0, t=2.0)))
    # Phase 2 (post-resume replay of dispatch 2): both ranks healthy.
    lines.append(json.dumps(_fleet_step(0, 2, 0.2, t=50.0)))
    lines.append(json.dumps(_fleet_step(1, 2, 0.2, t=50.0)))
    path.write_text("\n".join(lines) + "\n")
    summary = fleet_summarize([str(path)])
    # Three paired dispatches: 1, 2(phase 1), 2(replay). The hang shows
    # as ONE 9.9s skew; the replay pairs against the replay (zero skew) —
    # a single-slot-per-rank merge would instead compare rank 0's replay
    # (0.2) against rank 1's dead-phase 10.0 and fabricate a 9.8s skew.
    assert summary["dispatch_skew"]["dispatches"] == 3
    assert summary["dispatch_skew"]["max_ms"] == pytest.approx(9900.0)
    assert summary["worst_dispatches"][0]["dispatch_id"] == 2
    assert summary["worst_dispatches"][1]["skew_ms"] <= 100.0
    assert summary["timeline_truncated"] is False


def test_heartbeat_roundtrip_and_atomicity(tmp_path, monkeypatch):
    from howtotrainyourmamlpytorch_tpu.telemetry import (
        HeartbeatWriter,
        heartbeat_path,
        read_heartbeat,
    )

    path = heartbeat_path(str(tmp_path))
    assert path.endswith("status.json")
    assert heartbeat_path(str(tmp_path), process_index=1).endswith(
        "status.r1.json"
    )
    writer = HeartbeatWriter(path)
    assert writer.write({"current_iter": 50, "epoch": 1})
    doc = read_heartbeat(path)
    assert doc["current_iter"] == 50 and doc["epoch"] == 1
    assert doc["schema"] == 1 and doc["t"] > 0

    # Atomicity: a crash mid-write (the SIGTERM/SIGKILL window) leaves the
    # PREVIOUS heartbeat intact — the tmp+rename contract means a reader
    # can never observe a torn document.
    real_replace = os.replace

    def dying_replace(src, dst):
        raise OSError("killed mid-publish")

    monkeypatch.setattr(os, "replace", dying_replace)
    assert not writer.write({"current_iter": 999})
    monkeypatch.setattr(os, "replace", real_replace)
    survivor = read_heartbeat(path)
    assert survivor["current_iter"] == 50  # old beat survived, untorn
    assert not os.path.exists(writer._tmp)  # failed tmp cleaned up
    # Recovery: the next beat publishes normally.
    assert writer.write({"current_iter": 75})
    assert read_heartbeat(path)["current_iter"] == 75
    # Tolerant reader: absent and torn files read as None, never raise.
    assert read_heartbeat(str(tmp_path / "missing.json")) is None
    (tmp_path / "torn.json").write_text('{"current_iter": 5')
    assert read_heartbeat(str(tmp_path / "torn.json")) is None


def test_heartbeat_written_at_boundaries_with_window_stats(tmp_path):
    from howtotrainyourmamlpytorch_tpu.telemetry import read_heartbeat

    telemetry = TrainTelemetry(str(tmp_path), enabled=True, n_devices=2,
                               mesh_dp=2, trace_id="hbtrace")
    telemetry.heartbeat_extra = lambda: {"epoch": 3,
                                         "last_checkpoint_age_s": 1.5}
    status = os.path.join(str(tmp_path), "status.json")
    with telemetry.activate():
        for i in range(1, 6):
            telemetry.record_dispatch(i, n_iters=1, data_wait_s=0.0)
        assert not os.path.exists(status)  # no beat off-boundary
        telemetry.boundary(5, 0.001, reason="log")
        doc = read_heartbeat(status)
    assert doc["current_iter"] == 5
    assert doc["epoch"] == 3
    assert doc["trace_id"] == "hbtrace"
    assert doc["n_devices"] == 2 and doc["mesh_dp"] == 2
    assert doc["last_checkpoint_age_s"] == 1.5
    assert doc["meta_iters_per_s"] > 0
    assert doc["anomalies"] == 0
    # A broken extra hook degrades to the base payload, never raises.
    telemetry.heartbeat_extra = lambda: 1 / 0
    telemetry.write_heartbeat(7)
    assert read_heartbeat(status)["current_iter"] == 7


def test_heartbeat_disabled_with_telemetry_flag(tmp_path):
    telemetry = TrainTelemetry(str(tmp_path), enabled=False)
    telemetry.boundary(5, 0.0, reason="log")
    assert not os.path.exists(os.path.join(str(tmp_path), "status.json"))


def test_anomaly_detector_fires_on_seeded_slow_dispatch_quiet_on_noise():
    from howtotrainyourmamlpytorch_tpu.telemetry import (
        RollingAnomalyDetector,
    )

    det = RollingAnomalyDetector(warmup=16, factor=3.0, min_delta_s=0.05)
    # Healthy-but-noisy stream (deterministic lognormal-ish jitter around
    # 100 ms): must stay quiet for hundreds of samples.
    rng = np.random.RandomState(0)
    for value in 0.1 * np.exp(0.15 * rng.randn(400)):
        assert det.observe("step_time", float(value)) is None
    # One seeded slow dispatch (a straggler/hang precursor): fires, with
    # the window p95 attached for attribution.
    fired = det.observe("step_time", 1.5)
    assert fired is not None
    assert fired["kind"] == "step_time"
    assert fired["value_s"] == 1.5
    assert fired["window_p95_s"] < 0.2
    # The outlier did NOT join the window: an identical successor fires
    # too (one hang cannot mask the next).
    assert det.observe("step_time", 1.5) is not None
    # Quiet again on healthy samples afterwards.
    assert det.observe("step_time", 0.1) is None


def test_anomaly_detector_warmup_and_report_cap():
    from howtotrainyourmamlpytorch_tpu.telemetry import (
        RollingAnomalyDetector,
    )

    det = RollingAnomalyDetector(warmup=16, max_reports=2)
    # Cold start: even absurd samples can't fire before warmup — the
    # compile-bearing first dispatches must not read as anomalies.
    for _ in range(15):
        assert det.observe("step_time", 50.0) is None
    det2 = RollingAnomalyDetector(warmup=4, max_reports=2)
    for _ in range(8):
        det2.observe("step_time", 0.01)
    assert det2.observe("step_time", 5.0) is not None
    assert det2.observe("step_time", 5.0) is not None
    assert det2.observe("step_time", 5.0) is None  # capped, still counted
    assert det2.reports == 3


def test_anomaly_event_emitted_from_real_recording_path(
    tmp_path, monkeypatch
):
    """A seeded slow dispatch through the REAL record_dispatch path (a
    scripted perf_counter) lands a typed ``anomaly`` event in the JSONL,
    identity-stamped and dispatch-correlated."""
    from howtotrainyourmamlpytorch_tpu.telemetry import runtime as tr

    clock = {"now": 0.0, "dt": 0.01}
    monkeypatch.setattr(
        tr.time, "perf_counter",
        lambda: clock.__setitem__("now", clock["now"] + clock["dt"])
        or clock["now"],
    )
    telemetry = TrainTelemetry(str(tmp_path), enabled=True,
                               process_index=1, process_count=2)
    telemetry.anomaly = tr.RollingAnomalyDetector(warmup=8)
    with telemetry.activate():
        for i in range(1, 30):
            telemetry.record_dispatch(i, n_iters=1)
        clock["dt"] = 2.0  # one seeded straggler dispatch
        telemetry.record_dispatch(30, n_iters=1)
        clock["dt"] = 0.01
        telemetry.record_dispatch(31, n_iters=1)
    events = read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
    anomalies = [e for e in events if e["type"] == "anomaly"]
    assert len(anomalies) == 1, [e["type"] for e in events]
    anomaly = anomalies[0]
    assert anomaly["kind"] == "step_time"
    assert anomaly["iter"] == 30 and anomaly["dispatch_id"] == 30
    assert anomaly["value_s"] == pytest.approx(2.0)
    assert anomaly["process_index"] == 1  # identity-stamped like any event
    assert telemetry.registry.snapshot()["counters"]["anomalies"] == 1


def _fleet_step(rank, i, step_s, t, trace="tr1", **kw):
    return {
        "type": "step", "t": t, "iter": i, "dispatch_id": i, "k": 1,
        "step_s": step_s, "data_wait_s": 0.0, "stage_wait_s": 0.0,
        "device_s": step_s, "process_index": rank, "process_count": 2,
        "trace_id": trace, **kw,
    }


def test_fleet_summarize_merges_lanes_and_attributes_slowest_rank(tmp_path):
    """The fleet report's data model over two ranks' JSONL files: ordered
    merged timeline, per-rank lanes, per-dispatch slowest-rank attribution
    on dispatch_id, cross-rank skew stats, trace consistency."""
    from tools.telemetry_report import fleet_summarize, render_fleet_text

    files = []
    for rank, slow in ((0, 0.10), (1, 0.13)):
        path = tmp_path / f"rank{rank}.jsonl"
        lines = [json.dumps({"t": 0.0, "type": "schema", "version": 1})]
        for i in (1, 2, 3):
            lines.append(json.dumps(
                _fleet_step(rank, i, slow if i == 2 else 0.1, t=float(i))
            ))
        lines.append(json.dumps({
            "t": 10.0 + rank, "type": "run_end", "process_index": rank,
            "process_count": 2, "trace_id": "tr1",
        }))
        path.write_text("\n".join(lines) + "\n")
        files.append(str(path))
    summary = fleet_summarize(files)
    assert summary["ranks"] == [0, 1]
    assert summary["trace_consistent"] and summary["trace_ids"] == ["tr1"]
    assert summary["lanes"][0]["step"]["count"] == 3
    assert summary["lanes"][1]["step"]["count"] == 3
    # Dispatch 2: rank 1 slowest by 30 ms; dispatches 1/3 tie at 0 skew.
    assert summary["dispatch_skew"]["dispatches"] == 3
    assert summary["dispatch_skew"]["max_ms"] == pytest.approx(30.0)
    assert summary["slowest_rank_dispatches"]["1"] >= 1
    worst = summary["worst_dispatches"][0]
    assert worst["dispatch_id"] == 2 and worst["slowest_rank"] == 1
    # Timeline is merged in time order with rank lanes.
    assert [e["rank"] for e in summary["timeline"]] == [0, 1]
    text = render_fleet_text(summary)
    assert "slowest-rank attribution" in text
    assert "rank 1" in text
    # A divergent trace id is surfaced as an inconsistency, not hidden.
    extra = tmp_path / "foreign.jsonl"
    extra.write_text(
        json.dumps(_fleet_step(0, 9, 0.1, t=99.0, trace="OTHER")) + "\n"
    )
    mixed = fleet_summarize(files + [str(extra)])
    assert not mixed["trace_consistent"]
    assert "INCONSISTENT" in render_fleet_text(mixed)


def test_fleet_report_cli_over_real_two_rank_streams(tmp_path):
    """Two REAL TrainTelemetry recorders (same dispatcher-style trace id,
    distinct ranks, one shared logs file layout per rank) merge through
    the real CLI with consistent trace/dispatch ids."""
    for rank in (0, 1):
        rank_dir = tmp_path / f"rank{rank}"
        os.makedirs(rank_dir)
        telemetry = TrainTelemetry(
            str(rank_dir), enabled=True, process_index=rank,
            process_count=2, trace_id="clifleettrace",
        )
        with telemetry.activate():
            for i in range(1, 5):
                telemetry.record_dispatch(i, n_iters=1)
            telemetry.boundary(4, 0.001, reason="log")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "tools/telemetry_report.py", "--fleet",
         str(tmp_path / "rank0"), str(tmp_path / "rank1"), "--json"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout)
    assert summary["ranks"] == [0, 1]
    assert summary["trace_ids"] == ["clifleettrace"]
    assert summary["trace_consistent"]
    assert summary["dispatch_skew"]["dispatches"] == 3  # iters 2..4 shared
    # Human rendering over the same pair (in-process — the CLI table path
    # is exercised by the unit test above; no second interpreter spawn).
    from tools.telemetry_report import fleet_summarize, render_fleet_text

    text = render_fleet_text(
        fleet_summarize([str(tmp_path / "rank0"), str(tmp_path / "rank1")])
    )
    assert "per-rank step lanes" in text
    assert "cross-rank dispatch skew" in text


def test_serve_dispatch_events_carry_n_devices(tmp_path):
    """The serving engine stamps ``n_devices`` on serve_dispatch events
    with the span its programs actually run on — 1 today, even on a
    multi-device host (this test runs under the 8-device conftest mesh, so
    it would catch ``len(jax.local_devices())`` misattribution); a future
    sharded-serving engine raises it with its mesh size."""
    from test_serve_runtime import episode, make_engine

    log = EventLog(os.path.join(str(tmp_path), "telemetry.jsonl"))
    prev = telemetry_events.install(log)
    try:
        engine = make_engine(meta_batch_size=2, max_wait_ms=0.0)
        ep = engine.prepare_episode(*episode(np.random.RandomState(0)))
        engine.dispatch([ep])
        log.flush()
    finally:
        telemetry_events.install(prev)
    events = read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
    dispatch = next(e for e in events if e["type"] == "serve_dispatch")
    assert dispatch["n_devices"] == 1
    assert len(jax.local_devices()) > 1  # host count would misattribute
    # Fleet correlation: the engine numbers its dispatches and joins the
    # surrounding run's trace (env-inherited or self-started).
    assert dispatch["dispatch_id"] >= 1
    assert dispatch.get("trace_id")


# ---------------------------------------------------------------------------
# Device-resource observability plane (ISSUE 15): ProgramLedger, MFU,
# watermarks, memory-growth anomaly, OOM forensics
# ---------------------------------------------------------------------------


def test_ledger_ingest_on_k1_path_compile_once_zero_syncs(
    compile_guard, rng, tmp_path, monkeypatch
):
    """The ledger's hot-path contract: resolving a pending compile into a
    cost/memory row via the learner's AOT hook is a CACHE HIT inside the
    counted window — still exactly one ``_train_step`` compile and zero
    ``jax.device_get`` calls, with the ``program_profile`` event and the
    heartbeat's windowed ``mfu_pct`` riding the existing boundaries."""
    from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner

    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    batch = tiny_batch(rng)
    telemetry = TrainTelemetry(str(tmp_path), enabled=True)

    device_gets = {"n": 0}
    real_device_get = jax.device_get

    def counting_device_get(x):
        device_gets["n"] += 1
        return real_device_get(x)

    with telemetry.activate():
        with compile_guard() as guard:
            state, _ = learner.run_train_iter(state, batch, epoch=0)
            telemetry.record_dispatch(1, n_iters=1)
            monkeypatch.setattr(jax, "device_get", counting_device_get)
            # Ledger ingest INSIDE the device_get-counted window: the AOT
            # lower().compile() must be pure host work on the cache.
            entry = telemetry.ingest_train_program(
                learner, state, batch, 0, single=True
            )
            for i in range(2, 6):
                state, _ = learner.run_train_iter(state, batch, epoch=0)
                telemetry.record_dispatch(i, n_iters=1)
                # Steady state: nothing pending, ingest is a None-check.
                assert telemetry.ingest_train_program(
                    learner, state, batch, 0, single=True
                ) is None
            telemetry.boundary(5, 0.0, reason="log")
            monkeypatch.setattr(jax, "device_get", real_device_get)
            jax.block_until_ready(state.theta)
        guard.assert_compiles("_train_step", exactly=1)
        guard.assert_unique_signatures("_train_step")
    assert device_gets["n"] == 0
    assert entry is not None and entry.role == "train" and entry.k == 1
    assert entry.flops and entry.flops > 0
    assert entry.dispatch_flops == entry.flops  # K=1
    assert entry.hbm_peak_bytes is not None and entry.hbm_peak_bytes > 0
    events = read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
    profile = next(e for e in events if e["type"] == "program_profile")
    assert profile["name"] == "_train_step"
    assert profile["k"] == 1 and profile["flops"] == entry.flops
    assert profile["peak_flops"] > 0
    hb = json.load(open(os.path.join(str(tmp_path), "status.json")))
    assert hb["mfu_pct"] > 0  # windowed rate x ledger flops / peak
    assert hb["hbm_peak_bytes"] == entry.hbm_peak_bytes


def test_ledger_k25_dispatch_flops_are_k_times_k1_body(
    compile_guard, rng, tmp_path
):
    """THE regression test for the 25x-MFU-understatement class: the K=25
    scan program's ledger accounting is exactly K x the K=1 body — the
    declared dispatch multiplier is encoded in code (models/common.
    dispatch_multiplier via maml.ledger_train_program), not re-derived by
    each consumer. Also pins the K-scan compile-once contract with the
    ledger active on the real K=25 path."""
    from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner
    from howtotrainyourmamlpytorch_tpu.telemetry.device import (
        ProgramLedger,
        record_train_program,
    )

    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    batches = [tiny_batch(rng) for _ in range(25)]
    telemetry = TrainTelemetry(str(tmp_path), enabled=True)
    with telemetry.activate():
        with compile_guard() as guard:
            for d in range(2):
                state, _ = learner.run_train_iters(state, batches, epoch=0)
                telemetry.record_dispatch((d + 1) * 25, n_iters=25)
                entry = telemetry.ingest_train_program(
                    learner, state, batches, 0, single=False
                )
            jax.block_until_ready(state.theta)
        guard.assert_compiles("multi", exactly=1)
    assert entry is None or entry.k == 25  # second pass: nothing pending
    ledger = telemetry.ledger
    e25 = ledger.train_entry()
    assert e25.k == 25 and e25.flops > 0
    assert e25.dispatch_flops == 25 * e25.flops
    # The K=1 body through the SAME accounting path (a separate program —
    # compiled outside the guard; XLA counts both scan bodies once, so
    # the per-iteration costs agree to reassociation-level noise and the
    # dispatch costs differ by exactly the declared multiplier).
    probe = ProgramLedger(emit_events=False)
    e1 = record_train_program(probe, learner, state, batches[:1], 0)
    assert e1.k == 1
    assert e25.flops == pytest.approx(e1.flops, rel=1e-3)
    assert e25.dispatch_flops == pytest.approx(
        25 * e1.dispatch_flops, rel=1e-3
    )


def test_ledger_graceful_when_backend_omits_analyses():
    """Backend degradation: ``memory_analysis`` raising and
    ``cost_analysis`` omitting keys both degrade to None fields — never
    an exception on a recording path."""
    from howtotrainyourmamlpytorch_tpu.telemetry.device import ProgramLedger

    class NoMemoryCompiled:
        def cost_analysis(self):
            return [{"flops": 123.0, "bytes accessed": 41.0}]

        def memory_analysis(self):
            raise NotImplementedError("unsupported backend")

    class BareCompiled:
        def cost_analysis(self):
            raise RuntimeError("no cost model")

        def memory_analysis(self):
            return None

    ledger = ProgramLedger(peak_flops=1e12, emit_events=False)
    entry = ledger.record_compiled("step", NoMemoryCompiled(), k=4)
    assert entry.flops == 123.0 and entry.dispatch_flops == 492.0
    assert entry.arithmetic_intensity == pytest.approx(3.0)
    assert entry.hbm_peak_bytes is None and entry.temp_bytes is None
    bare = ledger.record_compiled("other", BareCompiled())
    assert bare.flops is None and bare.dispatch_flops is None
    assert bare.hbm_peak_bytes is None
    assert ledger.mfu_pct(10.0) is None  # no train entry -> no MFU claim
    rows = ledger.table()
    assert {row["name"] for row in rows} == {"step", "other"}


def test_memory_stats_absent_on_cpu_degrades_to_none():
    """CPU backends expose no ``memory_stats``: the sampler returns None
    (not an empty crash), the heartbeat simply omits the memory field and
    the growth detector is never fed."""
    from howtotrainyourmamlpytorch_tpu.telemetry import device as dev

    assert jax.default_backend() == "cpu"
    assert dev.sample_memory_stats() is None


def test_memory_growth_detector_fires_on_monotonic_rise_only():
    from howtotrainyourmamlpytorch_tpu.telemetry import MemoryGrowthDetector

    gib = 1 << 30
    det = MemoryGrowthDetector(consecutive=4, min_delta_bytes=256 << 20,
                               min_frac=0.01)
    # Noisy steady state: rises keep breaking -> never fires.
    for value in (10, 11, 10, 11, 10, 11, 10, 11, 10, 11):
        assert det.observe(value * gib) is None
    # Monotonic climb: fires once the run + delta floors clear.
    fired = None
    for step in range(1, 10):
        fired = fired or det.observe((11 + step) * gib)
    assert fired is not None and fired["kind"] == "memory_growth"
    assert fired["rise_bytes"] >= 256 << 20
    # Re-armed: the very next sample cannot fire again without a new climb.
    assert det.observe((22 * gib) - 1) is None


def test_heartbeat_carries_watermarks_and_memory_growth_anomaly(
    tmp_path, monkeypatch
):
    """On backends WITH memory_stats (faked here — CPU has none), the
    heartbeat carries per-device watermarks, a ``memory`` event lands in
    the JSONL per boundary, and a monotonic rise across boundaries emits
    the typed ``memory_growth`` anomaly event."""
    from howtotrainyourmamlpytorch_tpu.telemetry import MemoryGrowthDetector
    from howtotrainyourmamlpytorch_tpu.telemetry import device as dev

    telemetry = TrainTelemetry(str(tmp_path), enabled=True)
    telemetry.memory_growth = MemoryGrowthDetector(
        consecutive=3, min_delta_bytes=1 << 20, min_frac=0.0
    )
    sample = {"n": 0}

    def fake_stats():
        sample["n"] += 1
        return [{
            "device": 0, "kind": "FakeTPU",
            "bytes_in_use": sample["n"] * (64 << 20),
            "peak_bytes_in_use": sample["n"] * (96 << 20),
        }]

    monkeypatch.setattr(dev, "sample_memory_stats", fake_stats)
    with telemetry.activate():
        for i in range(1, 7):
            telemetry.record_dispatch(i, n_iters=1)
            telemetry.boundary(i, 0.0, reason="log")
    events = read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
    memories = [e for e in events if e["type"] == "memory"]
    assert memories and memories[-1]["bytes_in_use_total"] == 6 * (64 << 20)
    growth = [
        e for e in events
        if e["type"] == "anomaly" and e.get("kind") == "memory_growth"
    ]
    assert growth, [e for e in events if e["type"] == "anomaly"]
    assert growth[0]["rise_bytes"] > 0
    hb = json.load(open(os.path.join(str(tmp_path), "status.json")))
    assert hb["memory"][0]["bytes_in_use"] == 6 * (64 << 20)


def test_oom_at_iter_writes_forensics_and_exits_registered_code(
    dataset_env,
):
    """The OOM-forensics acceptance, chaos-style through the real
    ExperimentBuilder: an injected RESOURCE_EXHAUSTED at a dispatch
    boundary exits through the REGISTERED code with a complete
    ``logs/oom_report.json`` (top programs by temp bytes, watermarks slot,
    config levers), an ``oom`` telemetry event, and an audit row."""
    from howtotrainyourmamlpytorch_tpu.telemetry.device import OOM_EXIT_CODE

    from test_faultinject import _builder, _exp_args

    tmp = dataset_env
    faultinject.activate(faultinject.FaultPlan(oom_at_iter=1))
    builder = _builder(_exp_args(tmp))
    with pytest.raises(SystemExit) as exits:
        builder.run_experiment()
    assert exits.value.code == OOM_EXIT_CODE == 77
    assert any(e.startswith("oom:") for e in faultinject.events)
    report_path = tmp / "exp" / "logs" / "oom_report.json"
    assert report_path.exists()
    report = json.load(open(report_path))
    assert report["exit_code"] == OOM_EXIT_CODE
    assert "RESOURCE_EXHAUSTED" in report["error"]
    assert "top_programs_by_temp_bytes" in report
    assert "memory_watermarks" in report  # None on CPU, key present
    levers = report["config_levers"]
    assert levers["batch_size"] is not None
    assert "task_chunk" in levers and "iters_per_dispatch" in levers
    events = read_events(str(tmp / "exp" / "logs" / "telemetry.jsonl"))
    oom = next(e for e in events if e["type"] == "oom")
    assert oom["code"] == OOM_EXIT_CODE
    assert oom["report"] == "oom_report.json"
    with open(tmp / "exp" / "logs" / "interruptions.csv") as f:
        assert ",oom," in f.read().replace("\r", "")


def test_serve_engine_ledger_rows_reach_metrics(tmp_path, compile_guard):
    """The serve side of the plane: warmup ingests one ledger row per
    compiled program (labels matching the compile table), /metrics gains
    the per-bucket program gauges, and a traffic dispatch on the warmed
    bucket mints NO new program signatures with the ledger active."""
    from test_serve_runtime import episode, make_engine

    with compile_guard() as guard:
        engine = make_engine(meta_batch_size=2, max_wait_ms=0.0)
        engine.warmup([(5, 1, 3)])
        rows = engine.ledger.table()
        assert {row["role"] for row in rows} == {
            "serve_adapt", "serve_classify",
        }
        assert all(row["bucket"] == "5x1x3" for row in rows)
        labels = {row["name"] for row in rows}
        assert labels == set(engine.compile_table())
        before = set(guard.signatures("serve_"))
        ep = engine.prepare_episode(*episode(np.random.RandomState(0)))
        engine.dispatch([ep])
        assert set(guard.signatures("serve_")) == before  # no new sigs
    text = engine.metrics.render_prometheus(
        program_table=engine.ledger.table()
    )
    assert "maml_serve_program_flops" in text
    assert 'bucket="5x1x3"' in text
    snap = engine.metrics.snapshot(program_table=engine.ledger.table())
    assert len(snap["programs"]) == len(rows)


def test_report_device_section_renders_and_tolerates_empty_ledger(
    tmp_path,
):
    """Report degradation contract: a JSONL with program_profile + memory
    events renders the device section (programs table, MFU, watermarks);
    a pre-ledger JSONL (no device events) summarizes with ``device: None``
    and renders without crashing."""
    sys.path.insert(0, REPO)
    from tools.telemetry_report import render_text, summarize

    log = EventLog(str(tmp_path / "telemetry.jsonl"))
    prev = telemetry_events.install(log)
    try:
        telemetry_events.emit(
            "program_profile", name="multi", role="train", k=25,
            flops=2.0e6, dispatch_flops=5.0e7, bytes_accessed=1.0e6,
            arithmetic_intensity=2.0, hbm_peak_bytes=123456,
            temp_bytes=1000, bucket=None, device_kind="cpu",
            peak_flops=1.974e14,
        )
        telemetry_events.emit(
            "memory", iter=5,
            devices=[{"device": 0, "bytes_in_use": 7, "peak_bytes_in_use": 9}],
            bytes_in_use_total=7, peak_bytes_in_use_max=9,
        )
        telemetry_events.emit("step", iter=1, dispatch_id=1, k=1,
                              step_s=0.5, data_wait_s=0.0,
                              stage_wait_s=0.0, staged=False, device_s=0.5)
    finally:
        telemetry_events.install(prev)
    log.flush()
    summary = summarize(read_events(log.path))
    device = summary["device"]
    assert device is not None
    assert device["programs"][0]["name"] == "multi"
    assert device["programs"][0]["k"] == 25
    # MFU from the JSONL alone: rate (2 iters/s) x flops / stamped peak.
    assert device["mfu_pct"] == pytest.approx(
        100.0 * 2.0 * 2.0e6 / 1.974e14, rel=1e-5
    )
    assert device["memory"]["bytes_in_use_total"] == 7
    text = render_text(summary)
    assert "device-resource ledger" in text
    assert "windowed MFU" in text and "memory watermarks" in text
    # program_profile/memory stay OUT of the generic event log section.
    assert summary["event_counts"]["program_profile"] == 1
    assert not [e for e in summary["events"]
                if e["type"] in ("program_profile", "memory")]

    # Empty-ledger rendering: a log with no device events at all.
    bare = EventLog(str(tmp_path / "bare.jsonl"))
    bare.emit("step", iter=1, dispatch_id=1, k=1, step_s=0.5,
              data_wait_s=0.0, stage_wait_s=0.0, staged=False,
              device_s=0.5)
    bare.flush()
    bare_summary = summarize(read_events(bare.path))
    assert bare_summary["device"] is None
    assert "device-resource ledger" not in render_text(bare_summary)


def test_anomaly_detector_short_history_never_fires_or_crashes():
    """Histories shorter than the warmup (and windows shorter than the
    p95's nominal 128 samples) are the cold-start norm — every detector
    entry point must stay quiet AND well-defined on them, not just after
    hundreds of samples."""
    from howtotrainyourmamlpytorch_tpu.telemetry import (
        RollingAnomalyDetector,
    )

    det = RollingAnomalyDetector(warmup=16)
    # Empty history: stats are None (the heartbeat omits windowed
    # figures), not a zero-division or an empty-max crash.
    assert det.window_stats("step_time") is None
    assert det.window_stats("never_fed") is None
    # One sample: stats well-defined, p95 IS that sample.
    assert det.observe("step_time", 0.1) is None
    stats = det.window_stats("step_time")
    assert stats == {
        "count": 1, "sum_s": 0.1, "mean_s": 0.1, "p95_s": 0.1,
    }
    # Exactly warmup-1 samples in the window: still disarmed — the 16th
    # overall sample (window holds 15) cannot fire however absurd.
    for _ in range(14):
        det.observe("step_time", 0.1)
    assert det.observe("step_time", 1e6) is None  # window len 15 < 16
    # That monster JOINED the window (pre-warmup samples are never
    # classified, so nothing is withheld) — now armed, and the p95 over
    # the short window includes it, so detection self-calibrates to the
    # poisoned cold start rather than firing on the next big sample.
    stats = det.window_stats("step_time")
    assert stats["count"] == 16
    assert stats["p95_s"] == 1e6
    assert det.observe("step_time", 2e6) is None  # 2e6 < 3 * p95
    assert det.reports == 0


def test_anomaly_p95_short_window_index_edges():
    """The p95 order-statistic index stays in range on 1- and 2-sample
    windows (min(int(.95*n), n-1)) and picks the max on both."""
    from howtotrainyourmamlpytorch_tpu.telemetry import (
        RollingAnomalyDetector,
    )

    det = RollingAnomalyDetector(warmup=2)
    det.observe("data_wait", 0.3)
    assert det.window_stats("data_wait")["p95_s"] == 0.3
    det.observe("data_wait", 0.1)
    # Two samples: index min(int(1.9), 1) = 1 → the larger one.
    assert det.window_stats("data_wait")["p95_s"] == 0.3
    # Armed at exactly warmup=2: a clear outlier fires against the
    # 2-sample p95 — short histories arm as soon as contracted, no more.
    fired = det.observe("data_wait", 1.1)
    assert fired is not None and fired["window"] == 2


def test_memory_growth_short_history_below_consecutive_never_fires():
    """A rise shorter than the consecutive-windows contract never fires,
    however large; a fresh detector tolerates any first sample."""
    from howtotrainyourmamlpytorch_tpu.telemetry.anomaly import (
        MemoryGrowthDetector,
    )

    det = MemoryGrowthDetector(consecutive=3, min_delta_bytes=1 << 20)
    # First-ever sample (no baseline): quiet.
    assert det.observe(10 << 30) is None
    # Two rising samples (one short of the contract): quiet despite a
    # multi-GB climb.
    assert det.observe(12 << 30) is None
    assert det.observe(14 << 30) is None
    # A dip resets the run — the NEXT two rises are again one short.
    assert det.observe(11 << 30) is None
    assert det.observe(13 << 30) is None
    assert det.observe(15 << 30) is None
    assert det.reports == 0
    # The third consecutive rise completes the contract and fires.
    fired = det.observe(17 << 30)
    assert fired is not None and fired["windows"] == 3
