"""Unified telemetry subsystem (ISSUE 5): shared registry, structured event
log, on-demand profiling, and the hot-path contract.

The load-bearing guarantees pinned here:

* telemetry-ON runs of the REAL K=1 and K=25 train paths compile each step
  program exactly once (``compile_guard``) and add ZERO per-iteration host
  syncs (``jax.device_get`` counted during the loop);
* the serving ``/metrics`` primitives ARE the shared registry classes
  (one implementation, byte-identical scrape surface);
* events buffer host-side and only flush at boundaries; the JSONL schema
  round-trips through ``tools/telemetry_report.py``;
* sentinel trips, checkpoint saves/loads, preemption/requeue all
  self-report through the global sink (driven end-to-end with the
  ``utils/faultinject.py`` hooks against the real ``ExperimentBuilder``);
* a SIGTERM landing inside a profiler capture window still flushes the
  trace on the requeue exit path (the ISSUE 5 fix).
"""

import json
import math
import os
import signal as signal_module
import subprocess
import sys

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.telemetry import (
    EventLog,
    MetricsRegistry,
    ProfilerController,
    TrainTelemetry,
    read_events,
)
from howtotrainyourmamlpytorch_tpu.telemetry import events as telemetry_events
from howtotrainyourmamlpytorch_tpu.utils import faultinject, storage

from test_data import make_dataset_dir
from test_sanitizers import tiny_batch, tiny_cfg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    """No fault plan and no global event sink may leak between tests."""
    faultinject.deactivate()
    previous = telemetry_events.install(None)
    yield
    telemetry_events.install(previous)
    faultinject.reset()


@pytest.fixture
def dataset_env(tmp_path, monkeypatch):
    make_dataset_dir(tmp_path / "omniglot_mini")
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    return tmp_path


@pytest.fixture
def fake_profiler(monkeypatch):
    """Records jax.profiler start/stop calls instead of tracing."""
    calls: list[tuple] = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda path: calls.append(("start", path))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop",))
    )
    return calls


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def test_registry_get_or_create_and_snapshot():
    reg = MetricsRegistry()
    reg.counter("compiles").inc(3)
    assert reg.counter("compiles") is reg.counter("compiles")
    reg.gauge("queue_depth").set(7)
    win = reg.window("step_ms")
    for v in (1.0, 2.0, 3.0, 4.0):
        win.observe(v)
    snap = reg.snapshot()
    assert snap["counters"]["compiles"] == 3
    assert snap["gauges"]["queue_depth"] == 7.0
    assert snap["windows"]["step_ms"]["count"] == 4
    # Nearest-rank percentiles (LatencyStat semantics, shared with serve).
    assert snap["windows"]["step_ms"]["p50_ms"] == 3.0
    assert snap["windows"]["step_ms"]["p95_ms"] == 4.0


def test_serve_metrics_reexports_shared_registry_classes():
    """The dedupe pin: serve/metrics.py runs the SAME implementation the
    trainer uses — not a drifted copy (the Prometheus scrape surface is
    covered unchanged by test_serve_http.py)."""
    from howtotrainyourmamlpytorch_tpu.serve import metrics as serve_metrics
    from howtotrainyourmamlpytorch_tpu.telemetry import registry

    assert serve_metrics.Counter is registry.Counter
    assert serve_metrics.LatencyStat is registry.LatencyStat


# ---------------------------------------------------------------------------
# Event log
# ---------------------------------------------------------------------------


def test_event_log_buffers_until_flush(tmp_path):
    log = EventLog(str(tmp_path / "telemetry.jsonl"))
    log.emit("step", iter=1, step_s=0.5)
    log.emit("step", iter=2, step_s=0.25)
    assert not os.path.exists(log.path)  # emit is buffer-only: no I/O
    assert log.pending() == 2
    assert log.flush() == 3  # schema header + 2 events
    assert log.pending() == 0
    log.emit("step", iter=3, step_s=0.125)
    log.flush()
    events = read_events(log.path)
    assert [e["type"] for e in events] == ["schema", "step", "step", "step"]
    assert events[0]["version"] == 1
    assert [e.get("iter") for e in events[1:]] == [1, 2, 3]


def test_event_log_serializes_nonfinite_as_null(tmp_path):
    log = EventLog(str(tmp_path / "telemetry.jsonl"))
    log.emit("epoch_summary", loss=float("nan"), acc=np.float32(0.5),
             inf=float("inf"),
             nested={"deep": float("nan"), "vals": [1.0, float("inf")]})
    log.flush()
    raw = open(log.path).read()
    assert "NaN" not in raw and "Infinity" not in raw  # strict JSON
    event = read_events(log.path)[-1]
    assert event["loss"] is None and event["inf"] is None
    assert event["acc"] == 0.5
    # Recursive scrub: a NaN deep inside a snapshot payload degrades to
    # null instead of raising at flush time and killing the run.
    assert event["nested"]["deep"] is None
    assert event["nested"]["vals"] == [1.0, None]


def test_flush_io_failure_degrades_without_raising(tmp_path, capsys):
    """Telemetry is an observability extra: a disk-full/NFS blip at a flush
    boundary must drop the batch with a warning, never crash the run (or
    turn a preemption-requeue exit into a crash)."""
    log = EventLog(str(tmp_path / "missing_dir" / "telemetry.jsonl"))
    log.emit("step", iter=1)
    assert log.flush() == 0  # open() fails: degraded, not raised
    log.emit("step", iter=2)
    assert log.flush() == 0
    warnings = capsys.readouterr().err
    assert warnings.count("telemetry flush") == 1  # warn once, not per flush
    os.makedirs(tmp_path / "missing_dir")
    log.emit("step", iter=3)
    assert log.flush() == 2  # recovered: schema header + the new event
    events = read_events(log.path)
    assert [e["type"] for e in events] == ["schema", "step"]


def test_flush_drops_unserializable_records_without_raising(tmp_path, capsys):
    """A non-JSON payload (ndarray, set) slipping past _jsonable must drop
    only the offending record at flush time — never raise through a
    boundary or the requeue exit."""
    log = EventLog(str(tmp_path / "telemetry.jsonl"))
    log.emit("good", iter=1)
    log.emit("bad", blob=np.zeros(3))  # ndim>0: passes _jsonable untouched
    log.emit("good", iter=2)
    assert log.flush() == 3  # schema + the two good records
    assert "non-JSON payloads" in capsys.readouterr().err
    events = read_events(log.path)
    assert [e["type"] for e in events] == ["schema", "good", "good"]


def test_read_events_refuses_newer_schema(tmp_path):
    path = tmp_path / "telemetry.jsonl"
    path.write_text(json.dumps({"t": 0.0, "type": "schema", "version": 99}) + "\n")
    with pytest.raises(ValueError, match="schema 99"):
        read_events(str(path))


def test_global_sink_install_restore_and_noop(tmp_path):
    telemetry_events.emit("orphan", x=1)  # no sink: must be a silent no-op
    log = EventLog(str(tmp_path / "telemetry.jsonl"))
    previous = telemetry_events.install(log)
    telemetry_events.emit("hello", x=2)
    assert telemetry_events.install(previous) is log  # restore returns ours
    telemetry_events.emit("orphan", x=3)  # dropped again
    log.flush()
    events = [e for e in read_events(log.path) if e["type"] != "schema"]
    assert [e["type"] for e in events] == ["hello"]


# ---------------------------------------------------------------------------
# Hot-path contract: compile-once + zero per-iteration host syncs
# ---------------------------------------------------------------------------


def test_telemetry_on_k1_train_step_compiles_once_no_host_syncs(
    compile_guard, rng, tmp_path, monkeypatch
):
    """The acceptance criterion: full telemetry (event log, compile bridge,
    per-dispatch recording) on the REAL K=1 train path — exactly one
    compile of ``_train_step`` and zero ``jax.device_get`` calls outside
    the declared forced-read boundaries."""
    from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner

    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    batch = tiny_batch(rng)
    telemetry = TrainTelemetry(str(tmp_path), enabled=True)

    device_gets = {"n": 0}
    real_device_get = jax.device_get

    def counting_device_get(x):
        device_gets["n"] += 1
        return real_device_get(x)

    with telemetry.activate():
        with compile_guard() as guard:
            # Warm-up dispatch (the compile), then the counted steady state.
            state, _ = learner.run_train_iter(state, batch, epoch=0)
            telemetry.record_dispatch(1, n_iters=1, data_wait_s=0.0)
            monkeypatch.setattr(jax, "device_get", counting_device_get)
            for i in range(2, 6):
                state, _ = learner.run_train_iter(state, batch, epoch=0)
                telemetry.record_dispatch(i, n_iters=1, data_wait_s=0.0)
            monkeypatch.setattr(jax, "device_get", real_device_get)
            jax.block_until_ready(state.theta)
        guard.assert_compiles("_train_step", exactly=1)
        guard.assert_unique_signatures("_train_step")
    assert device_gets["n"] == 0  # telemetry recording forced NO reads
    events = read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
    steps = [e for e in events if e["type"] == "step"]
    assert len(steps) == 4  # first dispatch only drops the anchor
    compiles = [e for e in events if e["type"] == "compile"]
    assert sum("_train_step" in e["name"] for e in compiles) == 1
    # The registry's production gauge: run progress, updated per dispatch.
    assert telemetry.registry.snapshot()["gauges"]["current_iter"] == 5.0


def test_telemetry_on_k25_multi_path_compiles_once(compile_guard, rng, tmp_path):
    from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner

    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    batches = [tiny_batch(rng) for _ in range(25)]
    telemetry = TrainTelemetry(str(tmp_path), enabled=True)
    with telemetry.activate():
        with compile_guard() as guard:
            for d in range(3):
                state, _ = learner.run_train_iters(state, batches, epoch=0)
                telemetry.record_dispatch(
                    (d + 1) * 25, n_iters=25, data_wait_s=0.0
                )
            jax.block_until_ready(state.theta)
        guard.assert_compiles("multi", exactly=1)
        guard.assert_unique_signatures("multi")
    steps = [
        e
        for e in read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
        if e["type"] == "step"
    ]
    assert [e["k"] for e in steps] == [25, 25]


# ---------------------------------------------------------------------------
# On-demand profiling
# ---------------------------------------------------------------------------


def test_profiler_start_flag_one_shot(fake_profiler, tmp_path):
    """The legacy --profile_trace_path semantics: one bounded capture at the
    start of the run, then never again."""
    ctl = ProfilerController(
        trace_path=str(tmp_path / "trace"), num_iters=3,
        trigger_path=str(tmp_path / "trigger"),
    )
    for _ in range(10):
        ctl.tick(1)
    assert fake_profiler == [("start", str(tmp_path / "trace")), ("stop",)]
    assert not ctl.active


def test_profiler_file_trigger_bounded_and_rearmable(fake_profiler, tmp_path):
    trigger = tmp_path / "trigger"
    ctl = ProfilerController(
        num_iters=2, trigger_path=str(trigger),
        default_trace_dir=str(tmp_path / "traces"),
    )
    ctl.tick(1)
    assert fake_profiler == []  # nothing armed, nothing requested
    trigger.touch()
    ctl.poll_trigger()
    assert not trigger.exists()  # consumed: one capture per touch
    ctl.tick(1)
    assert ctl.active
    ctl.tick(1)  # window of 2 complete
    assert not ctl.active
    trigger.touch()  # re-armable: a second touch captures again
    ctl.poll_trigger()
    ctl.tick(2)
    starts = [c for c in fake_profiler if c[0] == "start"]
    assert len(starts) == 2
    assert starts[0][1] != starts[1][1]  # each capture in its own directory
    assert fake_profiler.count(("stop",)) == 2


def test_profiler_signal_request_and_sigusr1_install(fake_profiler, tmp_path):
    telemetry = TrainTelemetry(str(tmp_path), enabled=True,
                               profile_num_iters=1)
    before = signal_module.getsignal(signal_module.SIGUSR1)
    with telemetry.activate():
        assert signal_module.getsignal(signal_module.SIGUSR1) is not before
        os.kill(os.getpid(), signal_module.SIGUSR1)
        telemetry.record_dispatch(1, n_iters=1)  # anchor
        telemetry.record_dispatch(2, n_iters=1)  # starts + completes capture
    assert signal_module.getsignal(signal_module.SIGUSR1) is before
    assert [c[0] for c in fake_profiler] == ["start", "stop"]
    types = [
        e["type"]
        for e in read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
    ]
    assert "profile_start" in types and "profile_stop" in types


# ---------------------------------------------------------------------------
# End-to-end through the real ExperimentBuilder (faultinject-driven)
# ---------------------------------------------------------------------------


def _run_skip_experiment(tmp):
    from test_faultinject import _builder, _exp_args

    faultinject.activate(faultinject.FaultPlan(nan_at_iter=1))
    builder = _builder(_exp_args(tmp, on_nonfinite="skip"))
    test_losses = builder.run_experiment()
    assert 0.0 <= test_losses["test_accuracy_mean"] <= 1.0
    return str(tmp / "exp" / "logs")


def test_e2e_event_stream_sentinel_and_checkpoints(dataset_env):
    """The whole run self-reports: step breakdown, compile events, sentinel
    trip (via the faultinject NaN hook), checkpoint save/alias/load,
    run_start/run_end — and the summary CSV carries the new data-wait
    columns next to the step-time ones."""
    logs = _run_skip_experiment(dataset_env)
    events = read_events(os.path.join(logs, "telemetry.jsonl"))
    types = [e["type"] for e in events]
    # "compile" is deliberately absent from this list: the module-level
    # learner cache (test_faultinject._LEARNERS) may have compiled this
    # config in an earlier test, making a zero-compile run the CORRECT
    # steady state; compile-event emission is pinned by the K=1
    # compile_guard test above.
    for expected in (
        "run_start", "step", "host_sync", "epoch_summary",
        "nonfinite_trip", "checkpoint_save", "checkpoint_alias",
        "checkpoint_load", "run_end",
    ):
        assert expected in types, f"missing {expected} in {sorted(set(types))}"
    # The sentinel trip rode the epoch-boundary forced read (skip policy).
    trip = next(e for e in events if e["type"] == "nonfinite_trip")
    assert trip["policy"] == "skip" and trip["trips"] == 1.0
    # Step events carry the full breakdown; the consumer-blocking wait +
    # device share sum to the step. With the device-prefetch stager active
    # (the default) the blocking wait is the STAGE wait — the synthesis
    # data_wait overlaps device compute and is reported off to the side.
    step = next(e for e in events if e["type"] == "step")
    assert step["step_s"] >= step["device_s"] >= 0.0
    assert step["data_wait_s"] >= 0.0 and step["stage_wait_s"] >= 0.0
    blocking = (
        step["stage_wait_s"] if step["staged"]
        else step["data_wait_s"] + step["stage_wait_s"]
    )
    assert math.isclose(
        step["device_s"], max(step["step_s"] - blocking, 0.0),
        rel_tol=1e-9,
    )
    # Checkpoint events carry durations + sizes from utils/checkpoint.py.
    save = next(e for e in events if e["type"] == "checkpoint_save")
    assert save["bytes"] > 0 and save["duration_s"] > 0
    # Satellite fix: the epoch CSV now separates data wait from step time
    # (and, since the device-prefetch stager, the stage wait as well).
    stats = storage.load_statistics(logs)
    for column in ("train_step_time_p50", "train_step_time_p95",
                   "train_data_wait_p50", "train_data_wait_p95",
                   "train_stage_wait_p50", "train_stage_wait_p95"):
        assert column in stats, column


def test_report_cli_schema_roundtrip(dataset_env):
    """The JSONL a real run writes parses through the report tool's summary
    (in-process AND via the CLI ``--json``), with consistent counts."""
    logs = _run_skip_experiment(dataset_env)
    sys.path.insert(0, REPO)
    from tools.telemetry_report import resolve_jsonl, summarize

    events = read_events(resolve_jsonl(str(dataset_env / "exp")))
    summary = summarize(events)
    n_step_events = sum(1 for e in events if e["type"] == "step")
    assert summary["iters"] >= n_step_events  # K>=1 expansion
    assert summary["breakdown"]["step"]["count"] == summary["iters"]
    assert summary["breakdown"]["data_wait"]["count"] == summary["iters"]
    # Steady state may legitimately show ZERO compiles (the module-level
    # learner cache reuses the compiled programs across tests); the
    # compile-event pin lives in the K=1 compile_guard test above.
    assert isinstance(summary["compiles"], list)
    assert summary["event_counts"]["step"] == n_step_events

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "tools/telemetry_report.py",
         os.path.join(logs, "telemetry.jsonl"), "--json"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    cli_summary = json.loads(proc.stdout)
    assert cli_summary["schema"] == summary["schema"]
    assert cli_summary["iters"] == summary["iters"]
    assert cli_summary["event_counts"] == summary["event_counts"]
    # Human rendering smoke: the table mode must not crash on the same run.
    proc_text = subprocess.run(
        [sys.executable, "tools/telemetry_report.py", str(dataset_env / "exp")],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120,
    )
    assert proc_text.returncode == 0, proc_text.stderr
    assert "step-time breakdown" in proc_text.stdout
    assert "compile timeline" in proc_text.stdout


def test_sigterm_inside_profile_window_flushes_trace(
    dataset_env, fake_profiler
):
    """ISSUE 5 satellite fix: a preemption landing inside the
    --profile_num_iters capture window must stop (flush) the trace on the
    requeue exit path, and the preemption/requeue events must reach the
    JSONL."""
    from howtotrainyourmamlpytorch_tpu.experiment_builder import (
        REQUEUE_EXIT_CODE,
    )

    from test_faultinject import _builder, _exp_args

    tmp = dataset_env
    faultinject.activate(faultinject.FaultPlan(sigterm_at_iter=1))
    builder = _builder(
        _exp_args(
            tmp,
            profile_trace_path=str(tmp / "trace"),
            profile_num_iters=100,  # window far larger than the run
        )
    )
    with pytest.raises(SystemExit) as exits:
        builder.run_experiment()
    assert exits.value.code == REQUEUE_EXIT_CODE
    assert [c[0] for c in fake_profiler] == ["start", "stop"]
    assert not builder.telemetry.profiler.active
    events = read_events(str(tmp / "exp" / "logs" / "telemetry.jsonl"))
    types = [e["type"] for e in events]
    assert "profile_start" in types
    assert "profile_stop" in types
    assert "preemption" in types
    assert "requeue_exit" in types
    requeue = next(e for e in events if e["type"] == "requeue_exit")
    assert requeue["code"] == REQUEUE_EXIT_CODE


def test_telemetry_flag_off_writes_no_jsonl(dataset_env):
    """--telemetry False: no event log, but step-time CSV stats survive."""
    from test_faultinject import _builder, _exp_args

    tmp = dataset_env
    builder = _builder(_exp_args(tmp, telemetry=False,
                                 total_epochs_before_pause=1))
    with pytest.raises(SystemExit):
        builder.run_experiment()
    assert not os.path.exists(
        str(tmp / "exp" / "logs" / "telemetry.jsonl")
    )
    stats = storage.load_statistics(str(tmp / "exp" / "logs"))
    assert "train_step_time_p50" in stats
    assert "train_data_wait_p50" in stats


# ---------------------------------------------------------------------------
# Mesh attribution (ISSUE 8): topology on step events + epoch CSV + report
# ---------------------------------------------------------------------------


def test_step_events_and_epoch_stats_carry_mesh_topology(tmp_path):
    """Multichip runs stamp every step event with ``n_devices``/
    ``mesh_shape`` and the epoch summary with NUMERIC ``n_devices``/
    ``mesh_dp``/``mesh_mp`` columns (``pack_and_save_metrics`` float()s
    every epoch key — a shape STRING would crash the CSV writer), so a
    throughput regression is attributable to a topology change from the
    telemetry alone."""
    telemetry = TrainTelemetry(
        str(tmp_path), enabled=True, n_devices=8, mesh_dp=8, mesh_mp=1
    )
    telemetry.record_dispatch(1, n_iters=1)
    telemetry.record_dispatch(2, n_iters=1)
    stats = telemetry.epoch_stats("train", epoch=0)
    assert stats["n_devices"] == 8
    assert stats["mesh_dp"] == 8
    assert stats["mesh_mp"] == 1
    for key in ("n_devices", "mesh_dp", "mesh_mp"):
        float(stats[key])  # the CSV packer's contract
    telemetry.flush()
    events = read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
    step = next(e for e in events if e["type"] == "step")
    assert step["n_devices"] == 8
    assert step["mesh_shape"] == "dp8xmp1"


def test_single_device_topology_defaults_keep_rows_comparable(tmp_path):
    """Single-chip runs carry the same columns (1 / "single"), so multichip
    and single-chip epochs stay comparable CSV rows under the stable-schema
    contract — including the <2-dispatch NaN path."""
    telemetry = TrainTelemetry(str(tmp_path), enabled=True)
    stats = telemetry.epoch_stats("train", epoch=0)  # zero dispatches
    assert stats["n_devices"] == 1
    assert stats["mesh_dp"] == 1
    assert stats["mesh_mp"] == 1
    assert telemetry.mesh_shape == "single"


def test_report_surfaces_mesh_topology():
    """tools/telemetry_report reads the topology off the step events
    themselves (pre-mesh logs default to 1 device / "single")."""
    from tools.telemetry_report import render_text, summarize

    def step(i, **kw):
        return {
            "type": "step", "t": float(i), "iter": i, "k": 1,
            "step_s": 0.1, "data_wait_s": 0.0, "stage_wait_s": 0.0,
            "device_s": 0.1, **kw,
        }

    summary = summarize(
        [step(1, n_devices=8, mesh_shape="dp8xmp1"),
         step(2, n_devices=8, mesh_shape="dp8xmp1")]
    )
    assert summary["n_devices"] == 8
    assert summary["mesh_shape"] == "dp8xmp1"
    assert "8 device(s)" in render_text(summary)
    assert "dp8xmp1" in render_text(summary)

    legacy = summarize([step(1), step(2)])  # pre-mesh event log
    assert legacy["n_devices"] == 1
    assert legacy["mesh_shape"] == "single"


def test_serve_dispatch_events_carry_n_devices(tmp_path):
    """The serving engine stamps ``n_devices`` on serve_dispatch events
    with the span its programs actually run on — 1 today, even on a
    multi-device host (this test runs under the 8-device conftest mesh, so
    it would catch ``len(jax.local_devices())`` misattribution); a future
    sharded-serving engine raises it with its mesh size."""
    from test_serve_runtime import episode, make_engine

    log = EventLog(os.path.join(str(tmp_path), "telemetry.jsonl"))
    prev = telemetry_events.install(log)
    try:
        engine = make_engine(meta_batch_size=2, max_wait_ms=0.0)
        ep = engine.prepare_episode(*episode(np.random.RandomState(0)))
        engine.dispatch([ep])
        log.flush()
    finally:
        telemetry_events.install(prev)
    events = read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
    dispatch = next(e for e in events if e["type"] == "serve_dispatch")
    assert dispatch["n_devices"] == 1
    assert len(jax.local_devices()) > 1  # host count would misattribute
