"""Second-order-capable fused Pallas normalization stack vs the pure-lax
reference (interpret mode on CPU; the same kernels compile for TPU).

Covers the three new pieces of ``ops/pallas_fused_norm.py``:

* ``fused_bn_leaky_relu_ho`` — the ``custom_jvp`` op that is legal inside
  reverse-over-reverse programs (the MAML/MAML++ train step): forward,
  first-order AND second-order gradient parity against lax;
* the row-blocked two-phase kernel path (large activations that exceed the
  VMEM budget — e.g. the mini-ImageNet 84x84 stages), forced here by
  shrinking the budget;
* ``fused_bn_leaky_relu_pool`` — the norm -> leaky_relu -> 2x2 max-pool
  epilogue, same parity bar;

plus the train-path gating (``BackboneConfig.fused_norm_train`` /
``fused_norm_pool``) through the real second-order MAML train program.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.ops import max_pool2d
from howtotrainyourmamlpytorch_tpu.ops import pallas_fused_norm as pfn
from howtotrainyourmamlpytorch_tpu.ops.norm import (
    batch_norm,
    init_batch_norm_state,
)

EPS, SLOPE = 1e-5, 0.01


def _reference(x, gamma, beta):
    state = init_batch_norm_state(x.shape[1])
    out, _ = batch_norm(x, gamma, beta, state, 0, eps=EPS)
    return jax.nn.leaky_relu(out, negative_slope=SLOPE)


def _reference_pool(x, gamma, beta):
    return max_pool2d(_reference(x, gamma, beta), 2, 2)


def _ho(x, gamma, beta):
    return pfn.fused_bn_leaky_relu_ho(x, gamma, beta, EPS, SLOPE, True)


def _pool(x, gamma, beta):
    return pfn.fused_bn_leaky_relu_pool(x, gamma, beta, EPS, SLOPE, True)


def _inputs(rng, shape):
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    gamma = jnp.asarray(rng.rand(shape[1]) + 0.5, jnp.float32)
    beta = jnp.asarray(rng.randn(shape[1]), jnp.float32)
    return x, gamma, beta


@pytest.fixture
def small_blocks(monkeypatch):
    """Force the row-blocked two-phase kernel path at CPU-test shapes."""
    monkeypatch.setattr(pfn, "_MAX_RESIDENT_BYTES", 24 * 128 * 4)


# ---------------------------------------------------------------------------
# fused_bn_leaky_relu_ho
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape", [(10, 64, 14, 14), (3, 5, 4, 4)])
def test_ho_forward_matches_reference(shape, rng):
    x, gamma, beta = _inputs(rng, shape)
    y, mean, var = _ho(x, gamma, beta)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(_reference(x, gamma, beta)),
        rtol=1e-5, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(mean), np.asarray(jnp.mean(x, axis=(0, 2, 3))),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(var), np.asarray(jnp.var(x, axis=(0, 2, 3))),
        rtol=1e-4, atol=1e-5,
    )


def test_ho_first_order_gradients_match(rng):
    shape = (4, 5, 6, 6)
    x, gamma, beta = _inputs(rng, shape)
    t = jnp.asarray(rng.randn(*shape), jnp.float32)

    gf = jax.grad(
        lambda *a: jnp.sum(_ho(*a)[0] * t), argnums=(0, 1, 2)
    )(x, gamma, beta)
    gr = jax.grad(
        lambda *a: jnp.sum(_reference(*a) * t), argnums=(0, 1, 2)
    )(x, gamma, beta)
    for a, b, name in zip(gf, gr, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4, err_msg=name
        )


def _rev_over_rev(f, x, gamma, beta):
    """The MAML-shaped composition: outer grad over a function that itself
    takes an inner grad (reverse-over-reverse) — exactly what the
    one-level ``custom_vjp`` op cannot linearize."""

    def outer(x):
        def inner_loss(g):
            return jnp.sum(f(x, g, beta)[0] ** 2)

        g1 = gamma - 0.1 * jax.grad(inner_loss)(gamma)
        return jnp.sum(f(x, g1, beta)[0])

    return jax.grad(outer)(x)


def test_ho_second_order_matches_reference(rng):
    x, gamma, beta = _inputs(rng, (4, 5, 6, 6))
    ref = lambda x, g, b: (_reference(x, g, b),)  # noqa: E731
    got = _rev_over_rev(_ho, x, gamma, beta)
    want = _rev_over_rev(ref, x, gamma, beta)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
    )


def test_vjp_op_still_fails_rev_over_rev(rng):
    """Documents WHY the ho op exists: the one-level custom_vjp kernel pair
    cannot be linearized a second time. If jax ever learns to do this the
    gating in models/maml.py can be simplified — this test will say so."""
    x, gamma, beta = _inputs(rng, (3, 4, 4, 4))
    vjp_op = lambda x, g, b: pfn.fused_bn_leaky_relu(  # noqa: E731
        x, g, b, EPS, SLOPE, True
    )
    with pytest.raises(Exception):
        _rev_over_rev(vjp_op, x, gamma, beta)


def test_ho_bf16_input_fp32_stats(rng):
    x = jnp.asarray(rng.randn(6, 8, 5, 5), jnp.bfloat16)
    gamma = jnp.ones((8,), jnp.float32)
    beta = jnp.zeros((8,), jnp.float32)
    y, mean, var = _ho(x, gamma, beta)
    assert y.dtype == jnp.bfloat16
    assert mean.dtype == jnp.float32 and var.dtype == jnp.float32
    ref = _reference(x.astype(jnp.float32), gamma, beta)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(ref), rtol=0.05, atol=0.05
    )


# ---------------------------------------------------------------------------
# Row-blocked two-phase kernels
# ---------------------------------------------------------------------------


def test_blocked_forward_matches_reference(rng, small_blocks):
    x, gamma, beta = _inputs(rng, (6, 37, 10, 12))
    y, mean, var = pfn.fused_bn_leaky_relu(x, gamma, beta, EPS, SLOPE, True)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(_reference(x, gamma, beta)),
        rtol=1e-5, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(mean), np.asarray(jnp.mean(x, axis=(0, 2, 3))),
        rtol=1e-5, atol=1e-6,
    )


def test_blocked_backward_matches_reference(rng, small_blocks):
    shape = (6, 37, 10, 12)
    x, gamma, beta = _inputs(rng, shape)
    t = jnp.asarray(rng.randn(*shape), jnp.float32)
    fused = lambda *a: pfn.fused_bn_leaky_relu(  # noqa: E731
        *a, EPS, SLOPE, True
    )
    gf = jax.grad(
        lambda *a: jnp.sum(fused(*a)[0] * t), argnums=(0, 1, 2)
    )(x, gamma, beta)
    gr = jax.grad(
        lambda *a: jnp.sum(_reference(*a) * t), argnums=(0, 1, 2)
    )(x, gamma, beta)
    for a, b, name in zip(gf, gr, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4, err_msg=name
        )


def test_blocked_kernels_under_vmap(rng, small_blocks):
    """The north-star shapes hit the blocked (gridded) kernels UNDER the
    task vmap of the meta-batch — pallas batching must compose with the
    grid for all three ops (fwd + grad)."""
    x = jnp.asarray(rng.randn(3, 4, 5, 8, 8), jnp.float32)  # (B, N, C, H, W)
    gamma = jnp.asarray(rng.rand(5) + 0.5, jnp.float32)
    beta = jnp.asarray(rng.randn(5), jnp.float32)
    ref = jax.vmap(lambda xi: _reference(xi, gamma, beta))(x)
    for op in (pfn.fused_bn_leaky_relu, pfn.fused_bn_leaky_relu_ho):
        f = lambda xi: op(xi, gamma, beta, EPS, SLOPE, True)[0]  # noqa: B023,E731
        np.testing.assert_allclose(
            np.asarray(jax.vmap(f)(x)), np.asarray(ref), rtol=1e-5, atol=1e-5
        )
        g = jax.grad(lambda xx: jnp.sum(jax.vmap(f)(xx) ** 2))(x)
        gr = jax.grad(
            lambda xx: jnp.sum(
                jax.vmap(lambda xi: _reference(xi, gamma, beta))(xx) ** 2
            )
        )(x)
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(gr), rtol=1e-4, atol=1e-4
        )
    fp = lambda xi: _pool(xi, gamma, beta)[0]  # noqa: E731
    refp = jax.vmap(lambda xi: _reference_pool(xi, gamma, beta))(x)
    np.testing.assert_allclose(
        np.asarray(jax.vmap(fp)(x)), np.asarray(refp), rtol=1e-5, atol=1e-5
    )


def test_blocked_ho_second_order(rng, small_blocks):
    x, gamma, beta = _inputs(rng, (4, 5, 8, 8))
    ref = lambda x, g, b: (_reference(x, g, b),)  # noqa: E731
    got = _rev_over_rev(_ho, x, gamma, beta)
    want = _rev_over_rev(ref, x, gamma, beta)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
    )


# ---------------------------------------------------------------------------
# fused_bn_leaky_relu_pool
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("blocked", [False, True])
def test_pool_forward_matches_reference(rng, blocked, monkeypatch):
    if blocked:
        monkeypatch.setattr(pfn, "_MAX_RESIDENT_BYTES", 24 * 128 * 4)
    x, gamma, beta = _inputs(rng, (4, 5, 8, 6))
    y, mean, var = _pool(x, gamma, beta)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(_reference_pool(x, gamma, beta)),
        rtol=1e-5, atol=1e-5,
    )
    # Statistics cover the FULL pre-pool activation.
    np.testing.assert_allclose(
        np.asarray(mean), np.asarray(jnp.mean(x, axis=(0, 2, 3))),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(var), np.asarray(jnp.var(x, axis=(0, 2, 3))),
        rtol=1e-4, atol=1e-5,
    )


def test_pool_gradients_match_reference(rng):
    shape = (4, 5, 8, 6)
    x, gamma, beta = _inputs(rng, shape)
    t = jnp.asarray(rng.randn(shape[0], shape[1], 4, 3), jnp.float32)
    gf = jax.grad(
        lambda *a: jnp.sum(_pool(*a)[0] * t), argnums=(0, 1, 2)
    )(x, gamma, beta)
    gr = jax.grad(
        lambda *a: jnp.sum(_reference_pool(*a) * t), argnums=(0, 1, 2)
    )(x, gamma, beta)
    for a, b, name in zip(gf, gr, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4, err_msg=name
        )


def test_pool_second_order_matches_reference(rng):
    x, gamma, beta = _inputs(rng, (3, 4, 6, 6))
    ref = lambda x, g, b: (_reference_pool(x, g, b),)  # noqa: E731
    got = _rev_over_rev(_pool, x, gamma, beta)
    want = _rev_over_rev(ref, x, gamma, beta)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
    )


def test_pool_rejects_odd_spatial(rng):
    x, gamma, beta = _inputs(rng, (2, 4, 7, 6))
    with pytest.raises(ValueError, match="even"):
        _pool(x, gamma, beta)


# ---------------------------------------------------------------------------
# Train-path gating through the real MAML program
# ---------------------------------------------------------------------------


def _make_maml(fused_train=False, fused_pool=False, max_pooling=False):
    from howtotrainyourmamlpytorch_tpu.models import (
        BackboneConfig,
        MAMLConfig,
        MAMLFewShotLearner,
    )

    cfg = MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2, num_filters=4, per_step_bn_statistics=True,
            num_steps=2, num_classes=5, image_height=8, image_width=8,
            max_pooling=max_pooling,
            fused_norm_train=fused_train, fused_norm_pool=fused_pool,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        second_order=True,
    )
    learner = MAMLFewShotLearner(cfg)
    return learner, learner.init_state(jax.random.PRNGKey(5))


def _episode_batch(rng):
    xs = rng.rand(2, 5, 1, 8, 8).astype(np.float32)
    ys = np.tile(np.arange(5)[None, :], (2, 1))
    return (xs, xs.copy(), ys, ys.copy())


def _meta_value_and_grad(learner, state, batch, second_order=True):
    outer = {"theta": state.theta, "lslr": state.lslr}
    batch = tuple(jnp.asarray(b) for b in batch)
    importance = jnp.full((2,), 0.5, jnp.float32)
    return jax.value_and_grad(learner._meta_loss, has_aux=True)(
        outer, state.bn_state, batch, importance, 2, second_order
    )


@pytest.mark.parametrize("max_pooling", [False, True])
@pytest.mark.parametrize("fused_pool", [False, True])
def test_fused_train_second_order_meta_grad_matches_lax(
    rng, max_pooling, fused_pool
):
    """The acceptance bar: lax-vs-Pallas SECOND-order meta-gradient parity
    through the full train program (vmap over tasks, scan over inner steps,
    remat, inner value_and_grad) with the fused train path enabled."""
    batch = _episode_batch(rng)
    la, sa = _make_maml(False, False, max_pooling)
    lb, sb = _make_maml(True, fused_pool, max_pooling)
    (loss_a, _), grads_a = _meta_value_and_grad(la, sa, batch)
    (loss_b, _), grads_b = _meta_value_and_grad(lb, sb, batch)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(grads_a), jax.tree.leaves(grads_b)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5
        )


def test_fused_train_first_order_runs_and_matches(rng):
    """First-order MAML still differentiates the inner value_and_grad via
    the carry (reverse-over-reverse in structure) — the ho op must hold
    there too."""
    batch = _episode_batch(rng)
    la, sa = _make_maml(False)
    lb, sb = _make_maml(True)
    (loss_a, _), grads_a = _meta_value_and_grad(la, sa, batch, second_order=False)
    (loss_b, _), grads_b = _meta_value_and_grad(lb, sb, batch, second_order=False)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(grads_a), jax.tree.leaves(grads_b)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5
        )


def test_fused_train_full_train_iter_runs(rng):
    """End-to-end run_train_iter with the fused train path (jit + donate +
    optimizer): losses stay tolerance-equal to lax on the first update
    (after Adam steps the ulp-level kernel/lax noise is sign-amplified, so
    exact trajectory equality is not the contract — gradient parity above
    is)."""
    batch = _episode_batch(rng)
    la, sa = _make_maml(False, False, True)
    lb, sb = _make_maml(True, True, True)
    sa, ma = la.run_train_iter(sa, batch, epoch=20)
    sb, mb = lb.run_train_iter(sb, batch, epoch=20)
    np.testing.assert_allclose(
        float(ma["loss"]), float(mb["loss"]), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        float(ma["accuracy"]), float(mb["accuracy"]), rtol=0, atol=1e-6
    )


def test_eval_knob_stays_independent(rng):
    """fused_norm_train alone must not change the eval path program choice
    (eval is gated by use_pallas_fused_norm; VERDICT-measured 1.28x there
    vs unmeasured jvp) — eval results match lax exactly in program terms."""
    batch = _episode_batch(rng)
    la, sa = _make_maml(False)
    lb, sb = _make_maml(True)
    _, ma, logits_a = la.run_validation_iter(sa, batch)
    _, mb, logits_b = lb.run_validation_iter(sb, batch)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]), rtol=0, atol=0)
    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b))


def test_resnet_fused_train_runs(rng):
    """The shared fused_norm_act also serves ResNet-12; the jvp variant must
    run under the second-order train step there."""
    from howtotrainyourmamlpytorch_tpu.models import (
        BackboneConfig,
        MAMLConfig,
        MAMLFewShotLearner,
    )

    cfg = MAMLConfig(
        backbone=BackboneConfig(
            architecture="resnet12", num_stages=4, num_filters=4,
            per_step_bn_statistics=True, num_steps=2, num_classes=5,
            image_height=16, image_width=16, fused_norm_train=True,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        second_order=True,
    )
    learner = MAMLFewShotLearner(cfg)
    state = learner.init_state(jax.random.PRNGKey(0))
    xs = rng.rand(2, 5, 1, 16, 16).astype(np.float32)
    ys = np.tile(np.arange(5)[None, :], (2, 1))
    state, losses = learner.run_train_iter(
        state, (xs, xs.copy(), ys, ys.copy()), epoch=20
    )
    assert np.isfinite(float(losses["loss"]))


# ---------------------------------------------------------------------------
# Config surface + log cadence satellites
# ---------------------------------------------------------------------------


def test_fused_train_flags_parse_and_wire(tmp_path, monkeypatch):
    from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
        args_to_maml_config,
        get_args,
    )

    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    args, _ = get_args(
        ["--fused_norm_train", "True", "--fused_norm_pool", "True"]
    )
    assert args.fused_norm_train is True
    assert args.fused_norm_pool is True
    cfg = args_to_maml_config(args)
    assert cfg.backbone.fused_norm_train is True
    assert cfg.backbone.fused_norm_pool is True

    args, _ = get_args([])
    assert args.fused_norm_train is False
    assert args.fused_norm_pool is False
    cfg = args_to_maml_config(args)
    assert cfg.backbone.fused_norm_train is False
    assert cfg.backbone.fused_norm_pool is False


def test_resolve_fused_variant():
    from howtotrainyourmamlpytorch_tpu.models.backbone import (
        BackboneConfig,
        resolve_fused_variant,
    )

    cfg = BackboneConfig()
    assert resolve_fused_variant(cfg, None) == "off"
    assert resolve_fused_variant(cfg, True) == "vjp"
    assert resolve_fused_variant(cfg, False) == "off"
    assert resolve_fused_variant(cfg, "jvp") == "jvp"
    cfg_eval = dataclasses.replace(cfg, use_pallas_fused_norm=True)
    assert resolve_fused_variant(cfg_eval, None) == "vjp"
    cfg_train = dataclasses.replace(cfg, fused_norm_train=True)
    assert resolve_fused_variant(cfg_train, None) == "jvp"
    with pytest.raises(ValueError):
        resolve_fused_variant(cfg, "sideways")


@pytest.mark.parametrize("chunk", [5, 25, 50, 125])
def test_multi_dispatch_log_cadence_matches_k1(chunk):
    """VERDICT r3 weak #5: the K>1 dispatch path logged at half the K=1
    cadence (`% 100` vs `% 50`). The shared predicate now yields the same
    number of log lines per 500-iter epoch regardless of K (one extra is
    tolerated when K doesn't divide the cadence boundary exactly)."""
    from howtotrainyourmamlpytorch_tpu.experiment_builder import (
        TRAIN_LOG_EVERY,
        _multi_log_due,
    )

    total = 500
    k1_prints = sum(
        1 for i in range(1, total + 1) if i % TRAIN_LOG_EVERY == 0 or i == 1
    )
    multi_prints = sum(
        1
        for i in range(chunk, total + 1, chunk)
        if _multi_log_due(i, chunk)
    )
    # A dispatch can log at most once, so huge K caps at one line per
    # dispatch; otherwise cadence must match K=1 (±1 for boundary phase).
    expected = min(k1_prints, total // chunk)
    assert abs(multi_prints - expected) <= 1, (chunk, multi_prints, expected)
