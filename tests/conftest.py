"""Test configuration: force an 8-device virtual CPU mesh.

Multi-device sharding tests run on CPU via
``--xla_force_host_platform_device_count`` (SURVEY §4's test strategy).
``jax`` may already be imported at interpreter startup (axon tunnel), so the
platform switch goes through ``utils.platform.force_virtual_cpu`` (env vars +
``jax.config``) — this works as long as no backend has been initialized yet.
"""

from howtotrainyourmamlpytorch_tpu.utils.platform import force_virtual_cpu

force_virtual_cpu(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)
