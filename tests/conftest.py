"""Test configuration: force an 8-device virtual CPU mesh.

Multi-device sharding tests run on CPU via
``--xla_force_host_platform_device_count`` (SURVEY §4's test strategy).
``jax`` may already be imported at interpreter startup (axon tunnel), so the
platform is switched through ``jax.config`` rather than env vars — this works
as long as no backend has been initialized yet.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)
