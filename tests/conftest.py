"""Test configuration: force an 8-device virtual CPU mesh.

Multi-device sharding tests run on CPU via
``--xla_force_host_platform_device_count`` (SURVEY §4's test strategy).
``jax`` may already be imported at interpreter startup (axon tunnel), so the
platform switch goes through ``utils.platform.force_virtual_cpu`` (env vars +
``jax.config``) — this works as long as no backend has been initialized yet.
"""

from howtotrainyourmamlpytorch_tpu.utils.platform import force_virtual_cpu

force_virtual_cpu(8)

import os  # noqa: E402
import subprocess  # noqa: E402
import sys  # noqa: E402
import textwrap  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def rng():
    return np.random.RandomState(0)


# ---------------------------------------------------------------------------
# Recompile guard (trace-time sanitizer, utils/sanitize.py)
# ---------------------------------------------------------------------------
#
# Usage:   with compile_guard() as guard: <run iterations>
#          guard.assert_compiles("_train_step", exactly=1)
# The guard listens to jax.log_compiles() and indexes XLA compiles by jitted
# function name and by signature (shapes/dtypes, incl. the K scan axis), so
# tests can pin "this step compiles once per (shape, dtype, K) class" — the
# regression guard behind every bench key in PERF_NOTES.md.


@pytest.fixture
def compile_guard():
    from howtotrainyourmamlpytorch_tpu.utils.sanitize import compile_guard

    return compile_guard


# ---------------------------------------------------------------------------
# GSPMD partitioner guard
# ---------------------------------------------------------------------------
#
# Some jaxlib builds CHECK-crash in XLA's CPU GSPMD partitioner when
# compiling dp/mp-sharded conv programs (convolution_handler.cc:831 "Check
# failed: ShapeUtil::Compatible(shard_shape, sharded_conv->shape())"). The
# crash is an F-level abort: it kills the whole pytest process and silently
# truncates the suite at whichever file hits it first (which is exactly how
# every test alphabetically after test_multi_iter went unexercised for
# several rounds). Tests that compile sharded conv programs therefore take
# the ``spmd_compile_guard`` fixture: ONE subprocess probe per session
# determines whether this backend's partitioner survives, and if not those
# tests skip with the reason instead of aborting mid-suite. On healthy
# backends (the TPU bench chip, fixed jaxlibs) the probe passes and every
# sharded test runs normally.

_SPMD_PROBE_TEMPLATE = """
import numpy as np, jax
from howtotrainyourmamlpytorch_tpu.utils.platform import force_virtual_cpu
force_virtual_cpu(2)
from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig, MAMLConfig, MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.parallel import make_mesh

# Minimal reproducer of the crashing program class: a dp-sharded MAML
# train step over a per-step-BN conv net (K=1 AND the K-scan dispatch).
cfg = MAMLConfig(
    backbone=BackboneConfig(
        num_stages=2, num_filters=4, per_step_bn_statistics=True,
        num_steps=2, num_classes=5, image_height=8, image_width=8,
    ),
    number_of_training_steps_per_iter=2,
    number_of_evaluation_steps_per_iter=2,
    second_order={second_order},
)
mesh = make_mesh(jax.devices()[:2], data_parallel=2, model_parallel=1)
learner = MAMLFewShotLearner(cfg, mesh=mesh)
state = learner.shard_state(learner.init_state(jax.random.PRNGKey(0)))
rng = np.random.RandomState(0)
xs = rng.rand(2, 5, 1, 1, 8, 8).astype(np.float32)
ys = np.tile(np.arange(5)[None, :, None], (2, 1, 1))
state, _ = learner.run_train_iter(
    state, (xs, xs.copy(), ys, ys.copy()), epoch=0
)
batch = (xs, xs.copy(), ys, ys.copy())
state, _ = learner.run_train_iters(state, [batch, batch], epoch=0)
jax.block_until_ready(state.theta)
if {second_order}:
    # The guarded second-order test class ALSO compiles raw GSPMD
    # sharded-conv programs (plain jit + value_and_grad over a
    # dp-sharded batch, and the arg-driven mp layouts) — the learner's
    # own dp step reduces inside a shard_map-manual region since
    # ISSUE 17 and no longer routes convs through the partitioner's
    # convolution handler, so the probe must exercise the raw class
    # explicitly or it would green-light tests that still abort.
    import jax.numpy as jnp
    from howtotrainyourmamlpytorch_tpu.parallel.sharding import (
        batch_sharding_spec,
    )

    def raw_meta_loss(outer, bn, sharded_batch, imp):
        loss, _ = learner._meta_loss(
            outer, bn, sharded_batch, imp, 2, True, None, True
        )
        return loss

    prepared = learner._prepare_batch(batch)
    sharded = tuple(
        jax.device_put(jnp.asarray(p), batch_sharding_spec(mesh))
        for p in prepared
    )
    outer = dict(theta=state.theta, lslr=state.lslr)
    imp = jnp.asarray(learner._train_importance(100))
    loss, _ = jax.jit(jax.value_and_grad(raw_meta_loss))(
        outer, state.bn_state, sharded, imp
    )
    jax.block_until_ready(loss)
print("SPMD_PROBE_OK")
"""


def _spmd_probe(tmp_path_factory, second_order: bool, what: str):
    script = tmp_path_factory.mktemp("spmd_probe") / "probe.py"
    script.write_text(
        textwrap.dedent(
            _SPMD_PROBE_TEMPLATE.format(second_order=second_order)
        )
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the probe forces its own device count
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        proc = subprocess.run(
            [sys.executable, str(script)],
            capture_output=True, text=True, env=env, cwd=REPO, timeout=600,
        )
        ok = "SPMD_PROBE_OK" in proc.stdout
        detail = f"probe rc={proc.returncode}"
    except (OSError, subprocess.TimeoutExpired) as exc:
        ok = False
        detail = f"probe did not run: {exc}"
    if not ok:
        pytest.skip(
            f"XLA's CPU GSPMD partitioner aborts compiling {what} sharded "
            f"conv programs in this jaxlib ({detail}; known "
            "convolution_handler.cc:831 CHECK) — sharded-compile tests are "
            "guarded so the abort cannot truncate the suite"
        )


@pytest.fixture(scope="session")
def spmd_compile_guard(tmp_path_factory):
    _spmd_probe(tmp_path_factory, second_order=True, what="second-order")


@pytest.fixture(scope="session")
def spmd_fo_compile_guard(tmp_path_factory):
    """First-order variant of ``spmd_compile_guard``: the observed
    CHECK-crash class is SECOND-ORDER-specific on some jaxlibs (this
    container's included), so first-order dp-sharded tests get their own
    probe — they run (and keep real mesh coverage) where the second-order
    tests must skip, and still skip on backends broken for both."""
    _spmd_probe(tmp_path_factory, second_order=False, what="first-order")


# ---------------------------------------------------------------------------
# Multi-host CPU guard (ISSUE 11)
# ---------------------------------------------------------------------------
#
# The two-process multi-host tests need a CPU backend that can COMPUTE
# across processes (gloo collectives — "Multiprocess computations aren't
# implemented on the CPU backend" on jaxlibs without it) plus a working
# first-order dp-sharded conv compile. One session-scoped two-process probe
# decides; unsupported backends skip with the reason instead of hanging or
# aborting mid-suite.

_MULTIHOST_PROBE_SRC = """
import sys
from howtotrainyourmamlpytorch_tpu.utils.platform import force_virtual_cpu_env

force_virtual_cpu_env(1)

from howtotrainyourmamlpytorch_tpu.parallel import initialize_distributed

addr, pid = sys.argv[1], int(sys.argv[2])
initialize_distributed(
    coordinator_address=addr, num_processes=2, process_id=pid,
    distributed_init_timeout_s=90,
)

import jax
import numpy as np

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig, MAMLConfig, MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.models.common import (
    StagedBatch, prepare_batch,
)
from howtotrainyourmamlpytorch_tpu.parallel import make_mesh

cfg = MAMLConfig(
    backbone=BackboneConfig(
        num_stages=2, num_filters=4, per_step_bn_statistics=True,
        num_steps=2, num_classes=5, image_height=8, image_width=8,
    ),
    number_of_training_steps_per_iter=2,
    number_of_evaluation_steps_per_iter=2,
    second_order=False,
)
mesh = make_mesh(jax.devices(), data_parallel=2, model_parallel=1)
learner = MAMLFewShotLearner(cfg, mesh=mesh)
state = learner.shard_state(learner.init_state(jax.random.PRNGKey(0)))
rng = np.random.RandomState(0)
xs = rng.rand(2, 5, 1, 1, 8, 8).astype(np.float32)
ys = np.tile(np.arange(5)[None, :, None], (2, 1, 1))
sh = learner.staged_batch_sharding(1)
local = prepare_batch(
    tuple(a[pid:pid + 1] for a in (xs, xs.copy(), ys, ys.copy()))
)
batch = StagedBatch(
    arrays=tuple(
        jax.make_array_from_process_local_data(sh, a) for a in local
    ),
    n_iters=1, first_iter=0,
)
state, losses = learner.run_train_iter(state, batch, epoch=0)
print("loss", float(jax.device_get(losses["loss"])))
print("MULTIHOST_PROBE_OK", pid)
"""


@pytest.fixture(scope="session")
def multihost_cpu_guard(tmp_path_factory):
    import socket

    try:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
    except OSError as exc:
        pytest.skip(f"loopback sockets unavailable in this sandbox: {exc}")
    script = tmp_path_factory.mktemp("multihost_probe") / "probe.py"
    script.write_text(_MULTIHOST_PROBE_SRC)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # each rank forces its own 1-device platform
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.pop("JAX_NUM_PROCESSES", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    addr = f"127.0.0.1:{port}"
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), addr, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, cwd=REPO, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    ok = True
    detail = ""
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
        ok = all(p.returncode == 0 for p in procs) and all(
            f"MULTIHOST_PROBE_OK {pid}" in out
            for pid, out in enumerate(outs)
        )
        if not ok:
            detail = f"rcs {[p.returncode for p in procs]}"
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
            p.communicate()
        ok, detail = False, "probe timed out"
    if not ok:
        tail = "\n".join(out[-500:] for out in outs)
        pytest.skip(
            "two-process CPU multi-host computation unsupported on this "
            f"backend ({detail}) — multi-host tests are probe-guarded so "
            f"an unsupported jaxlib cannot hang the suite:\n{tail}"
        )


# ---------------------------------------------------------------------------
# Lock-order sanitizer (runtime twin of graftlint v2, utils/locksan.py)
# ---------------------------------------------------------------------------
#
# Usage:   with locksan() as san: <run concurrent code>
#          san.assert_clean(hold_budget_s=0.5, match="serve")
# The sanitizer instruments every threading.Lock/RLock CREATED inside the
# with-block (Condition and queue.Queue build on those factories) and
# records the acquisition-order graph + per-site hold times; a cycle in
# the graph is a potential deadlock that really happened in this
# process's lock nesting — no lucky schedule required.
#
# Tier-1 additionally runs the serve/chaos suites UNDER the sanitizer
# (the autouse fixture below): every in-process pool/batcher/engine test
# doubles as a deadlock + hold-budget proof. Overhead on the serve hot
# path is measured < 2% (PERF_NOTES.md "Lock sanitizer overhead").

#: Test modules whose every test runs under the sanitizer. These are the
#: suites exercising the real concurrent serving/chaos machinery
#: in-process — exactly where an inversion would bite production.
_LOCKSAN_SUITES = {
    "test_serve_runtime",
    "test_serve_resilience",
    "test_serve_http",
    "test_chaos_train",
    "test_promotion",
}

#: Hold budget for serve-plane locks while sanitized: the serving hot
#: path's critical sections are dict/list operations (the batcher
#: dispatches OUTSIDE its lock; engine compiles outside too), so even a
#: heavily-loaded CI host stays orders of magnitude under this.
_LOCKSAN_SERVE_HOLD_BUDGET_S = 2.0


@pytest.fixture
def locksan():
    from howtotrainyourmamlpytorch_tpu.utils.locksan import LockSanitizer

    return LockSanitizer


@pytest.fixture(autouse=True)
def _locksan_on_serve_suites(request):
    module = os.path.splitext(os.path.basename(str(request.node.fspath)))[0]
    if module not in _LOCKSAN_SUITES:
        yield None
        return
    from howtotrainyourmamlpytorch_tpu.utils.locksan import LockSanitizer

    with LockSanitizer() as san:
        yield san
    san.assert_clean(
        hold_budget_s=_LOCKSAN_SERVE_HOLD_BUDGET_S,
        match=os.path.join("howtotrainyourmamlpytorch_tpu", "serve"),
    )
