"""MAML/MAML++ system tests: inner-loop semantics, gradient order,
finite-difference checks, trainer contract (few_shot_learning_system.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models.backbone import BackboneConfig
from howtotrainyourmamlpytorch_tpu.models.maml import (
    MAMLConfig,
    MAMLFewShotLearner,
    final_step_importance,
)


def tiny_cfg(**kw):
    defaults = dict(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=8,
            image_height=14,
            image_width=14,
            num_classes=3,
            per_step_bn_statistics=True,
            num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        total_iter_per_epoch=4,
        total_epochs=3,
        remat_inner_steps=True,
    )
    defaults.update(kw)
    return MAMLConfig(**defaults)


def tiny_batch(rng, b=2, n=3, k=2, t=2, c=1, h=14, w=14):
    xs = rng.randn(b, n, k, c, h, w).astype(np.float32)
    xt = rng.randn(b, n, t, c, h, w).astype(np.float32)
    ys = np.tile(np.arange(n)[None, :, None], (b, 1, k)).astype(np.float32)
    yt = np.tile(np.arange(n)[None, :, None], (b, 1, t)).astype(np.float32)
    return xs, xt, ys, yt


def test_train_iter_runs_and_decreases_loss(rng):
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    batch = tiny_batch(rng)
    losses = []
    for i in range(8):
        state, metrics = learner.run_train_iter(state, batch, epoch=0)
        losses.append(metrics["loss"])
    assert losses[-1] < losses[0], losses
    assert 0.0 <= metrics["accuracy"] <= 1.0
    assert "loss_importance_vector_0" in metrics
    # LR is pinned to the PASSED epoch (scheduler.step(epoch) semantics)
    assert metrics["learning_rate"] == pytest.approx(0.001)


def test_validation_iter_is_pure(rng):
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    batch = tiny_batch(rng)
    flat_before = jax.tree.leaves(state)
    state2, losses, preds = learner.run_validation_iter(state, batch)
    for a, b in zip(flat_before, jax.tree.leaves(state2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert preds.shape == (2, 6, 3)  # (tasks, N*T, classes)


def test_first_vs_second_order_gradients_differ(rng):
    """create_graph=use_second_order (few_shot_learning_system.py:138-139):
    the orders must produce different outer gradients."""
    cfg = tiny_cfg()
    learner = MAMLFewShotLearner(cfg)
    state = learner.init_state(jax.random.key(1))
    batch_np = tiny_batch(rng)
    batch = learner._prepare_batch(batch_np)
    importance = final_step_importance(2)

    def outer_grads(second_order):
        outer = {"theta": state.theta, "lslr": state.lslr}
        g, _ = jax.grad(learner._meta_loss, has_aux=True)(
            outer, state.bn_state, batch, jnp.asarray(importance), 2, second_order
        )
        return g

    g_fo = outer_grads(False)
    g_so = outer_grads(True)
    w_fo = np.asarray(g_fo["theta"]["conv0"]["conv"]["weight"])
    w_so = np.asarray(g_so["theta"]["conv0"]["conv"]["weight"])
    assert not np.allclose(w_fo, w_so, atol=1e-6)


def test_second_order_gradient_finite_difference(rng):
    """The outer gradient of the adapted target loss w.r.t. a parameter must
    match a central finite difference through the full inner loop."""
    cfg = tiny_cfg()
    learner = MAMLFewShotLearner(cfg)
    state = learner.init_state(jax.random.key(2))
    batch = learner._prepare_batch(tiny_batch(rng, b=1))
    importance = jnp.asarray(final_step_importance(2))

    def loss_for(theta):
        outer = {"theta": theta, "lslr": state.lslr}
        loss, _ = learner._meta_loss(
            outer, state.bn_state, batch, importance, 2, True
        )
        return loss

    g = jax.grad(loss_for)(state.theta)
    # probe one scalar: linear bias[0]
    eps = 1e-3

    def perturb(delta):
        theta = jax.tree.map(lambda x: x, state.theta)
        theta["linear"]["bias"] = theta["linear"]["bias"].at[0].add(delta)
        return float(loss_for(theta))

    fd = (perturb(eps) - perturb(-eps)) / (2 * eps)
    analytic = float(g["linear"]["bias"][0])
    assert analytic == pytest.approx(fd, rel=0.05, abs=1e-4)


def test_lslr_gets_outer_updates_only_when_learnable(rng):
    batch = tiny_batch(rng)
    for learnable in [True, False]:
        learner = MAMLFewShotLearner(
            tiny_cfg(learnable_per_layer_per_step_inner_loop_learning_rate=learnable)
        )
        state = learner.init_state(jax.random.key(0))
        lslr_before = np.asarray(state.lslr["linear"]["weight"])
        state, _ = learner.run_train_iter(state, batch, epoch=0)
        lslr_after = np.asarray(state.lslr["linear"]["weight"])
        changed = not np.allclose(lslr_before, lslr_after)
        assert changed == learnable


def test_bn_gamma_frozen_when_not_learnable(rng):
    batch = tiny_batch(rng)
    learner = MAMLFewShotLearner(tiny_cfg(learnable_bn_gamma=False))
    state = learner.init_state(jax.random.key(0))
    gamma_before = np.asarray(state.theta["conv0"]["norm"]["gamma"])
    beta_before = np.asarray(state.theta["conv0"]["norm"]["beta"])
    state, _ = learner.run_train_iter(state, batch, epoch=0)
    np.testing.assert_array_equal(
        gamma_before, np.asarray(state.theta["conv0"]["norm"]["gamma"])
    )
    assert not np.allclose(beta_before, np.asarray(state.theta["conv0"]["norm"]["beta"]))


def test_derivative_order_annealing(rng):
    """second_order and epoch > first_order_to_second_order_epoch
    (few_shot_learning_system.py:304-305)."""
    learner = MAMLFewShotLearner(tiny_cfg(first_order_to_second_order_epoch=1))
    assert not learner._use_second_order(0)
    assert not learner._use_second_order(1)
    assert learner._use_second_order(2)


def test_bn_state_updates_during_training(rng):
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    rm_before = np.asarray(state.bn_state["conv0"].running_mean)
    state, _ = learner.run_train_iter(state, tiny_batch(rng), epoch=0)
    rm_after = np.asarray(state.bn_state["conv0"].running_mean)
    assert not np.allclose(rm_before, rm_after)
    # only rows 0..num_steps-1 written (per-step indexing)
    assert rm_after.shape == (2, 8)


def test_cosine_lr_schedule_by_epoch():
    """torch CosineAnnealingLR closed form, driven by the passed epoch
    (few_shot_learning_system.py:70-71,346)."""
    cfg = tiny_cfg(meta_learning_rate=0.001, min_learning_rate=1e-5,
                   total_epochs=10, total_iter_per_epoch=100)
    learner = MAMLFewShotLearner(cfg)
    assert learner._epoch_lr(0) == pytest.approx(0.001)
    assert learner._epoch_lr(5) == pytest.approx((0.001 + 1e-5) / 2, rel=1e-3)
    assert learner._epoch_lr(10) == pytest.approx(1e-5, rel=1e-3)


def test_config_validates_bn_rows_vs_inner_steps():
    """Mismatched per-step BN rows vs inner steps must be rejected, not
    silently clamped."""
    with pytest.raises(ValueError, match="num_steps"):
        tiny_cfg(number_of_training_steps_per_iter=7)
