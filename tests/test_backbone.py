"""Backbone structure/shape tests vs the reference architecture
(meta_neural_network_architectures.py:542-684)."""

import jax
import jax.numpy as jnp
import numpy as np

from howtotrainyourmamlpytorch_tpu.models.backbone import BackboneConfig, VGGBackbone


def make(cfg=None):
    cfg = cfg or BackboneConfig()
    return VGGBackbone(cfg)


def test_omniglot_shapes_max_pooling():
    """28x28 Omniglot, 4 stages, max pooling: spatial 28->14->7->3->1,
    feature dim 64 (matches reference dummy-trace build)."""
    cfg = BackboneConfig(per_step_bn_statistics=True, num_steps=5)
    bb = make(cfg)
    assert cfg.stage_spatial_shapes() == [(14, 14), (7, 7), (3, 3), (1, 1)]
    assert cfg.feature_dim == 64
    params, bn_state = bb.init(jax.random.key(0))
    x = jnp.zeros((10, 1, 28, 28))
    logits, new_bn = bb.apply(params, bn_state, x, 0)
    assert logits.shape == (10, 5)
    assert params["conv0"]["norm"]["gamma"].shape == (5, 64)
    assert bn_state["conv0"].running_mean.shape == (5, 64)


def test_imagenet_shapes_strided():
    """84x84 Mini-ImageNet, 48 filters, strided convs + global avg pool
    (reference :565-570,605-606)."""
    cfg = BackboneConfig(
        num_filters=48, max_pooling=False, image_channels=3,
        image_height=84, image_width=84, per_step_bn_statistics=True,
    )
    bb = make(cfg)
    assert cfg.feature_dim == 48
    params, bn_state = bb.init(jax.random.key(0))
    x = jnp.zeros((4, 3, 84, 84))
    logits, _ = bb.apply(params, bn_state, x, 0)
    assert logits.shape == (4, 5)


def test_param_count_matches_reference_formula():
    """4 conv stages (3x3, 64f) + per-step BN gamma/beta + linear head."""
    cfg = BackboneConfig(per_step_bn_statistics=True, num_steps=5)
    params, _ = make(cfg).init(jax.random.key(0))
    count = sum(x.size for x in jax.tree.leaves(params))
    conv = (64 * 1 * 9 + 64) + 3 * (64 * 64 * 9 + 64)
    bn = 4 * 2 * 5 * 64
    lin = 5 * 64 + 5
    assert count == conv + bn + lin


def test_inner_loop_mask_excludes_norm_params():
    """Inner loop adapts conv/linear only unless
    enable_inner_loop_optimizable_bn_params (few_shot_learning_system.py:105-120)."""
    cfg = BackboneConfig(per_step_bn_statistics=True)
    bb = make(cfg)
    params, _ = bb.init(jax.random.key(0))
    mask = bb.inner_loop_mask(params)
    assert mask["conv0"]["conv"]["weight"] is True
    assert mask["conv0"]["norm"]["gamma"] is False
    assert mask["linear"]["weight"] is True

    cfg2 = BackboneConfig(
        per_step_bn_statistics=True, enable_inner_loop_optimizable_bn_params=True
    )
    bb2 = make(cfg2)
    params2, _ = bb2.init(jax.random.key(0))
    # gamma/beta revert to (F,) so they can be inner-adapted (ref :194-198)
    assert params2["conv0"]["norm"]["gamma"].shape == (64,)
    assert bb2.inner_loop_mask(params2)["conv0"]["norm"]["gamma"] is True


def test_layer_norm_variant():
    cfg = BackboneConfig(norm_layer="layer_norm")
    bb = make(cfg)
    params, bn_state = bb.init(jax.random.key(0))
    assert bn_state == {}
    assert params["conv0"]["norm"]["weight"].shape == (64, 28, 28)
    x = jnp.zeros((2, 1, 28, 28))
    logits, _ = bb.apply(params, bn_state, x, 0)
    assert logits.shape == (2, 5)


def test_xavier_init_statistics():
    cfg = BackboneConfig()
    params, _ = make(cfg).init(jax.random.key(42))
    w = np.asarray(params["conv1"]["conv"]["weight"])
    fan = 64 * 9 + 64 * 9
    limit = np.sqrt(6.0 / fan)
    assert np.abs(w).max() <= limit + 1e-6
    assert np.asarray(params["conv0"]["conv"]["bias"]).sum() == 0.0


def test_norm_conv_block_order():
    """C7 (MetaNormLayerConvReLU, meta_neural_network_architectures.py:
    436-539): norm of the stage INPUT -> conv -> LeakyReLU. Norm features
    and per-step BN state follow the input channels per stage."""
    cfg = BackboneConfig(
        block_order="norm_conv", per_step_bn_statistics=True, num_steps=3,
        num_filters=8, num_stages=2, image_height=8, image_width=8,
    )
    bb = make(cfg)
    params, bn_state = bb.init(jax.random.key(0))
    # Stage 0 normalizes the 1-channel image; stage 1 the 8-filter output.
    assert params["conv0"]["norm"]["gamma"].shape == (3, 1)
    assert params["conv1"]["norm"]["gamma"].shape == (3, 8)
    assert bn_state["conv0"].running_mean.shape == (3, 1)
    assert bn_state["conv1"].running_mean.shape == (3, 8)
    x = jnp.ones((4, 1, 8, 8))
    logits, new_bn = bb.apply(params, bn_state, x, 0)
    assert logits.shape == (4, 5)
    assert np.all(np.isfinite(np.asarray(logits)))
    # The orderings genuinely differ on the same input.
    ref_params, ref_bn = make(
        BackboneConfig(num_filters=8, num_stages=2, image_height=8,
                       image_width=8)
    ).init(jax.random.key(0))
    ref_logits, _ = make(
        BackboneConfig(num_filters=8, num_stages=2, image_height=8,
                       image_width=8)
    ).apply(ref_params, ref_bn, x, 0)
    assert not np.allclose(np.asarray(logits), np.asarray(ref_logits))


def test_norm_conv_layer_norm_shapes():
    cfg = BackboneConfig(
        block_order="norm_conv", norm_layer="layer_norm",
        num_filters=8, num_stages=2, image_height=8, image_width=8,
    )
    bb = make(cfg)
    params, bn_state = bb.init(jax.random.key(0))
    # LN normalizes the stage input (C, H, W): image for stage 0, the
    # pooled stage-0 output for stage 1.
    assert params["conv0"]["norm"]["weight"].shape == (1, 8, 8)
    assert params["conv1"]["norm"]["weight"].shape == (8, 4, 4)
    logits, _ = bb.apply(params, bn_state, jnp.ones((2, 1, 8, 8)), 0)
    assert logits.shape == (2, 5)


def test_norm_conv_maml_trains():
    """The C7 ordering runs through a full MAML++ train iter."""
    from howtotrainyourmamlpytorch_tpu.models import MAMLConfig, MAMLFewShotLearner

    cfg = MAMLConfig(
        backbone=BackboneConfig(
            block_order="norm_conv", per_step_bn_statistics=True, num_steps=2,
            num_filters=4, num_stages=2, image_height=8, image_width=8,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
    )
    learner = MAMLFewShotLearner(cfg)
    state = learner.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    xs = rng.rand(2, 5, 1, 1, 8, 8).astype(np.float32)
    ys = np.tile(np.arange(5)[None, :, None], (2, 1, 1))
    state, losses = learner.run_train_iter(state, (xs, xs.copy(), ys, ys.copy()), epoch=0)
    assert np.isfinite(float(losses["loss"]))


def test_invalid_block_order_raises():
    import pytest

    with pytest.raises(ValueError, match="block_order"):
        make(BackboneConfig(block_order="bogus")).init(jax.random.key(0))


def test_strided_avgpool_second_order_train_iter():
    """The avg-pool (max_pooling=False) backbone must survive the MAML
    outer gradient at BOTH derivative orders — reduce_window-add failed to
    linearize under reverse-over-reverse AD (ops/pool.py avg_pool2d)."""
    import jax
    import numpy as np

    from howtotrainyourmamlpytorch_tpu.models import (
        BackboneConfig, MAMLConfig, MAMLFewShotLearner,
    )

    cfg = MAMLConfig(
        backbone=BackboneConfig(
            num_stages=4, num_filters=4, per_step_bn_statistics=True,
            num_steps=2, num_classes=5, image_channels=3,
            image_height=20, image_width=20, max_pooling=False,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        second_order=True, use_multi_step_loss_optimization=True,
        multi_step_loss_num_epochs=10,
    )
    learner = MAMLFewShotLearner(cfg)
    state = learner.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    xs = rng.randn(2, 5, 1, 3, 20, 20).astype("f")
    ys = np.tile(np.arange(5)[None, :, None], (2, 1, 1))
    state, losses = learner.run_train_iter(state, (xs, xs.copy(), ys, ys.copy()), 0)
    assert np.isfinite(float(losses["loss"]))
