"""Fast episode assembly (native C kernel / vectorized fallback) parity:
the batched gather+rot90+CHW path must be bit-identical to the reference-
order per-image loop it replaces (data.py:478-524 semantics)."""

import os

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.data import FewShotLearningDataset
from howtotrainyourmamlpytorch_tpu.data.fast_synth import (
    _gather_rot_chw_numpy,
    gather_rot_chw,
    native_available,
)

from test_data import make_args, make_dataset_dir


@pytest.fixture
def ram_env(tmp_path, monkeypatch):
    make_dataset_dir(tmp_path / "omniglot_mini")
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    return tmp_path


def test_kernel_matches_numpy_rot90_all_k():
    rng = np.random.RandomState(0)
    for H, W, C in [(28, 28, 1), (16, 16, 3), (8, 12, 1)]:
        src = np.ascontiguousarray(rng.randn(7, H, W, C).astype(np.float32))
        idx = np.array([3, 0, 6, 3], np.int64)
        ks = range(4) if H == W else [0, 2]
        for k in ks:
            expect = _gather_rot_chw_numpy(src, idx, k)
            got = gather_rot_chw(src, idx, k)
            np.testing.assert_array_equal(got, expect)
            assert got.shape == (4, C, H, W)


def test_native_kernel_in_use():
    # The target environment ships a C toolchain; this must not silently
    # degrade to the NumPy fallback. Set ALLOW_NO_NATIVE=1 to opt out on
    # compiler-less hosts.
    if os.environ.get("ALLOW_NO_NATIVE"):
        pytest.skip("native kernel explicitly waived")
    assert native_available()


def test_fast_episode_bit_identical_to_slow_path(ram_env):
    args = make_args(ram_env, load_into_memory=True)
    ds = FewShotLearningDataset(args)
    assert ds._fast_assembly_ok(True) and ds._fast_assembly_ok(False)

    slow = FewShotLearningDataset(make_args(ram_env, load_into_memory=True))
    slow._fast_assembly_ok = lambda augment_images: False

    for seed in [0, 7, 123, 2**31 - 5]:
        for augment in (True, False):
            fast_ep = ds.get_set("train", seed=seed, augment_images=augment)
            slow_ep = slow.get_set("train", seed=seed, augment_images=augment)
            for f, s in zip(fast_ep, slow_ep):
                np.testing.assert_array_equal(np.asarray(f), np.asarray(s))


def test_disk_backed_dataset_uses_slow_path(ram_env):
    ds = FewShotLearningDataset(make_args(ram_env, load_into_memory=False))
    assert not ds._fast_assembly_ok(True)
    # and still produces the same episodes as the RAM fast path
    ram = FewShotLearningDataset(make_args(ram_env, load_into_memory=True))
    a = ds.get_set("val", seed=11, augment_images=False)
    b = ram.get_set("val", seed=11, augment_images=False)
    np.testing.assert_allclose(np.asarray(a[0]), np.asarray(b[0]))
    np.testing.assert_array_equal(np.asarray(a[2]), np.asarray(b[2]))
