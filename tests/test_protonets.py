"""Prototypical networks (Snell et al. 2017) on the shared contract.

ProtoNets is the metric-learning end of the learner zoo: no inner loop at
all — ``serve_adapt`` is an embed + per-class mean, and the cacheable
artifact is a ``(num_classes, feat)`` prototype table. These tests pin the
prototype math against numpy references, then run the learner through
every shared-contract surface: serve parity bit-exact vs
``run_validation_iter`` (init state, trained state, uint8 wire), training
actually learns a separable batch, dp-mesh training, mesh-portable
checkpoints, the nonfinite sentinel, and serve compile-once.
"""

import os

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    ProtoNetsLearner,
    ProtoNetsState,
)
from howtotrainyourmamlpytorch_tpu.models.common import WireCodec
from howtotrainyourmamlpytorch_tpu.models.protonets import (
    class_prototypes,
    squared_distance_logits,
)
from howtotrainyourmamlpytorch_tpu.parallel import make_mesh
from howtotrainyourmamlpytorch_tpu.serve import ServeConfig, ServingAPI
from test_serve_parity import (
    golden_fixture_episode,
    serve_and_reference,
    tiny_cfg,
)


def small_cfg(**kw):
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=4,
            num_classes=5,
            image_height=8,
            image_width=8,
            num_steps=2,
        ),
        meta_learning_rate=0.01,
        **kw,
    )


def small_batch(rng, tasks=2, hw=8):
    xs = rng.randn(tasks, 5, 1, 1, hw, hw).astype(np.float32)
    xt = rng.randn(tasks, 5, 1, 1, hw, hw).astype(np.float32)
    ys = np.tile(np.arange(5)[None, :, None], (tasks, 1, 1)).astype(np.int32)
    return xs, xt, ys, ys.copy()


def separable_batch(rng, tasks=2, hw=8):
    """Each class is a distinct constant image + small noise — linearly
    separable, so the loss must fall under training."""
    base = np.linspace(-1.0, 1.0, 5, dtype=np.float32)

    def draw(shot):
        x = np.zeros((tasks, 5, shot, 1, hw, hw), np.float32)
        for c in range(5):
            x[:, c] = base[c] + 0.05 * rng.randn(tasks, shot, 1, hw, hw)
        return x

    ys = np.tile(np.arange(5)[None, :, None], (tasks, 1, 2)).astype(np.int32)
    return draw(2), draw(2), ys, ys.copy()


# ---------------------------------------------------------------------------
# Prototype math vs numpy
# ---------------------------------------------------------------------------


def test_class_prototypes_are_per_class_means(rng):
    emb = rng.randn(10, 7).astype(np.float32)
    ys = np.repeat(np.arange(5), 2).astype(np.int32)
    got = np.asarray(class_prototypes(emb, ys, 5))
    want = np.stack([emb[ys == c].mean(axis=0) for c in range(5)])
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_class_prototypes_mask_excludes_rows_exactly(rng):
    """A masked-out row contributes an EXACT zero: prototypes over the real
    rows are bit-identical whether the padded rows exist or not."""
    emb_real = rng.randn(6, 7).astype(np.float32)
    ys_real = np.repeat(np.arange(3), 2).astype(np.int32)
    unpadded = np.asarray(class_prototypes(emb_real, ys_real, 5))

    emb_pad = np.concatenate([emb_real, rng.randn(4, 7).astype(np.float32)])
    ys_pad = np.concatenate([ys_real, np.zeros(4, np.int32)])
    mask = np.concatenate([np.ones(6), np.zeros(4)]).astype(np.float32)
    padded = np.asarray(class_prototypes(emb_pad, ys_pad, 5, mask))
    np.testing.assert_array_equal(padded, unpadded)


def test_class_prototypes_absent_class_is_zero_not_nan(rng):
    emb = rng.randn(4, 3).astype(np.float32)
    ys = np.array([0, 0, 1, 1], np.int32)  # classes 2..4 absent
    got = np.asarray(class_prototypes(emb, ys, 5))
    assert np.isfinite(got).all()
    np.testing.assert_array_equal(got[2:], np.zeros((3, 3), np.float32))


def test_squared_distance_logits_vs_numpy(rng):
    q = rng.randn(4, 6).astype(np.float32)
    p = rng.randn(5, 6).astype(np.float32)
    got = np.asarray(squared_distance_logits(q, p))
    want = -((q[:, None, :] - p[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    assert got.shape == (4, 5)


# ---------------------------------------------------------------------------
# Serve parity (bit-exact vs the eval graph) + the tiny artifact
# ---------------------------------------------------------------------------


def test_protonets_served_fixture_episode_bit_exact():
    learner = ProtoNetsLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    xs, ys, xq, yq = golden_fixture_episode()
    served, cached, ref = serve_and_reference(learner, state, xs, ys, xq, yq)
    np.testing.assert_array_equal(served, ref)
    np.testing.assert_array_equal(cached, ref)


def test_protonets_trained_state_bit_exact(rng):
    learner = ProtoNetsLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(1))
    state, losses = learner.run_train_iter(
        state, small_batch(rng, tasks=2, hw=14), epoch=0
    )
    assert float(losses["nonfinite"]) == 0.0
    xs, ys, xq, yq = golden_fixture_episode()
    served, cached, ref = serve_and_reference(learner, state, xs, ys, xq, yq)
    np.testing.assert_array_equal(served, ref)
    np.testing.assert_array_equal(cached, ref)


def test_protonets_uint8_wire_codec_bit_exact():
    learner = ProtoNetsLearner(tiny_cfg(wire_codec=WireCodec(1.0, None, None)))
    state = learner.init_state(jax.random.key(2))
    xs, ys, xq, yq = golden_fixture_episode(binary=True)
    served, cached, ref = serve_and_reference(learner, state, xs, ys, xq, yq)
    np.testing.assert_array_equal(served, ref)
    np.testing.assert_array_equal(cached, ref)


def test_serve_artifact_is_a_prototype_table(rng):
    """The whole cacheable artifact is one (num_classes, feat) table —
    the metric tier's cost story in one assert."""
    learner = ProtoNetsLearner(small_cfg())
    istate = learner.init_inference_state(jax.random.key(3))
    xs = rng.rand(5, 1, 8, 8).astype(np.float32)
    ys = np.arange(5, dtype=np.int32)
    artifact = learner.serve_adapt(istate, xs, ys)
    assert set(artifact) == {"prototypes"}
    protos = np.asarray(artifact["prototypes"])
    assert protos.shape[0] == 5
    assert protos.nbytes < 8 * 1024


# ---------------------------------------------------------------------------
# Training learns; mesh; checkpoints; sentinel; compile discipline
# ---------------------------------------------------------------------------


def test_protonets_training_reduces_loss(rng):
    learner = ProtoNetsLearner(small_cfg())
    state = learner.init_state(jax.random.key(4))
    batch = separable_batch(rng)
    state, first = learner.run_train_iter(state, batch, epoch=0)
    first_loss = float(first["loss"])
    for _ in range(20):
        state, losses = learner.run_train_iter(state, batch, epoch=0)
    assert float(losses["nonfinite"]) == 0.0
    assert float(losses["loss"]) < first_loss
    assert float(losses["accuracy"]) > 0.9


def dp_mesh(n):
    return make_mesh(jax.devices()[:n], data_parallel=n, model_parallel=1)


def test_protonets_dp_mesh_train_runs(spmd_fo_compile_guard, rng):
    learner = ProtoNetsLearner(small_cfg(), mesh=dp_mesh(4))
    state = learner.shard_state(learner.init_state(jax.random.key(5)))
    for _ in range(2):
        state, losses = learner.run_train_iter(
            state, small_batch(rng, tasks=4), epoch=0
        )
    assert float(losses["nonfinite"]) == 0.0
    assert np.isfinite(float(losses["loss"]))
    for leaf in jax.tree.leaves(state.theta):
        assert isinstance(leaf.sharding, NamedSharding)
        assert leaf.sharding.mesh.shape == learner.mesh.shape


def test_protonets_mesh_checkpoint_roundtrip(tmp_path):
    """The reverse direction of test_anil's: save single-device, resume
    onto a 2-device dp mesh — bit-exact, restored leaves on the mesh."""
    writer = ProtoNetsLearner(small_cfg())
    state = writer.init_state(jax.random.key(6))
    exp = {"current_iter": 3}
    writer.save_model(os.path.join(tmp_path, "train_model_3"), state, exp)

    reader = ProtoNetsLearner(small_cfg(), mesh=dp_mesh(2))
    restored, restored_exp = reader.load_model(str(tmp_path), "train_model", 3)
    assert restored_exp == exp
    assert isinstance(restored, ProtoNetsState)
    saved = [np.asarray(x) for x in jax.tree.leaves(writer.gather_state(state))]
    back = [
        np.asarray(x) for x in jax.tree.leaves(reader.gather_state(restored))
    ]
    for a, b in zip(saved, back):
        np.testing.assert_array_equal(a, b)
    for leaf in jax.tree.leaves(restored):
        assert isinstance(leaf.sharding, NamedSharding)
        assert leaf.sharding.mesh.shape == reader.mesh.shape


def test_protonets_nonfinite_sentinel_trips(rng):
    learner = ProtoNetsLearner(small_cfg(skip_nonfinite_updates=True))
    state = learner.init_state(jax.random.key(7))
    clean = small_batch(rng)
    state, losses = learner.run_train_iter(state, clean, epoch=0)
    assert float(losses["nonfinite"]) == 0.0
    theta_before = [np.asarray(l) for l in jax.tree.leaves(state.theta)]
    poisoned = (np.full_like(clean[0], np.inf),) + clean[1:]
    state, losses = learner.run_train_iter(state, poisoned, epoch=0)
    assert float(losses["nonfinite"]) == 1.0
    for a, b in zip(theta_before, jax.tree.leaves(state.theta)):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_protonets_serve_compiles_once(compile_guard):
    learner = ProtoNetsLearner(small_cfg())
    state = learner.init_state(jax.random.key(8))
    api = ServingAPI(
        learner, state, ServeConfig(meta_batch_size=2, max_wait_ms=0.0)
    )
    rng = np.random.RandomState(9)

    def episode():
        xs = rng.rand(5, 1, 8, 8).astype(np.float32)
        ys = np.arange(5, dtype=np.int32)
        xq = rng.rand(3, 1, 8, 8).astype(np.float32)
        return xs, ys, xq

    try:
        api.classify(*episode())  # warm
        with compile_guard() as guard:
            for _ in range(3):
                out = api.classify(*episode())
                assert out["logits"].shape == (3, 5)
        assert guard.count("serve_adapt_protonets") == 0
        assert guard.count("serve_classify_protonets") == 0
        assert len(guard.events) == 0
    finally:
        api.close()
