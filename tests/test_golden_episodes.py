"""Bit-for-bit parity of the episode sampler with the REFERENCE
implementation, against recorded golden fixtures.

``fixtures/reference_episodes.json`` was produced by executing the
reference's actual ``FewShotLearningDatasetParallel.get_set`` /
``load_dataset`` (``data.py:478-524,169-211``) on a synthetic class tree
(see ``fixtures/gen_reference_episode_fixtures.py``). These tests replay
the repo's sampler on the same tree and assert every RNG-driven decision —
class selection + shuffle order, per-class rotation k, per-class sample
indices, episode label matrices, ratio-split partition, derived split
seeds — matches the recordings exactly.
"""

import json
import os

import numpy as np
import pytest
from PIL import Image

import howtotrainyourmamlpytorch_tpu.data.dataset as dataset_mod
from howtotrainyourmamlpytorch_tpu.data import FewShotLearningDataset
from howtotrainyourmamlpytorch_tpu.utils.parser_utils import Bunch

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "reference_episodes.json")

with open(FIXTURE) as f:
    GOLDEN = json.load(f)


def _repo_stub(cfg):
    """Bare sampler instance over the generator's synthetic class tree."""
    ds = FewShotLearningDataset.__new__(FewShotLearningDataset)
    ds.num_classes_per_set = cfg["num_classes_per_set"]
    ds.num_samples_per_class = cfg["num_samples_per_class"]
    ds.num_target_samples = cfg["num_target_samples"]
    ds.image_channel = 1
    ds.dataset_name = "omniglot_dataset"
    ds.args = Bunch({})
    ds.data_loaded_in_memory = False
    keys = [f"c{i:03d}" for i in range(cfg["n_classes"])]
    ds.datasets = {
        "train": {
            k: [f"{k}/s{j:02d}" for j in range(cfg["samples_per_class"])]
            for k in keys
        }
    }
    ds.dataset_size_dict = {
        "train": {k: cfg["samples_per_class"] for k in keys}
    }
    return ds


@pytest.mark.parametrize("cfg_idx", range(len(GOLDEN["configs"])))
def test_get_set_matches_reference_recording(cfg_idx, monkeypatch):
    entry = GOLDEN["configs"][cfg_idx]
    cfg = entry["config"]
    ds = _repo_stub(cfg)
    per_class = cfg["num_samples_per_class"] + cfg["num_target_samples"]

    for episode in entry["episodes"]:
        loads, ks = [], []
        monkeypatch.setattr(
            ds, "load_image",
            lambda raw: (loads.append(raw), np.zeros((1, 1, 1), np.float32))[1],
        )
        monkeypatch.setattr(
            dataset_mod, "augment_image",
            lambda image, k, **kw: (ks.append(int(k)), image)[1],
        )
        _xs, _xt, ys, yt, out_seed = ds.get_set(
            "train", seed=episode["seed"], augment_images=False
        )

        classes_in_order = [
            loads[ci * per_class].split("/")[0]
            for ci in range(cfg["num_classes_per_set"])
        ]
        samples = [
            [int(p.split("/s")[1]) for p in
             loads[ci * per_class:(ci + 1) * per_class]]
            for ci in range(cfg["num_classes_per_set"])
        ]
        assert classes_in_order == episode["selected_classes"]
        assert samples == episode["sample_indices"]
        assert ks[::per_class] == episode["rotation_k"]
        assert ys.astype(int).tolist() == episode["support_labels"]
        assert yt.astype(int).tolist() == episode["target_labels"]
        assert int(out_seed) == episode["returned_seed"]


@pytest.mark.parametrize("split_idx", range(len(GOLDEN["splits"])))
def test_ratio_split_matches_reference_recording(split_idx):
    rec = GOLDEN["splits"][split_idx]
    ds = FewShotLearningDataset.__new__(FewShotLearningDataset)
    ds.args = Bunch({"sets_are_pre_split": False, "load_into_memory": False})
    ds.seed = {"val": rec["derived_val_seed"]}
    ds.train_val_test_split = rec["split"]
    keys = [f"c{i:03d}" for i in range(rec["n_classes"])]
    ds.load_datapaths = lambda: (
        {k: ["x"] for k in keys}, {k: k for k in keys}, None
    )
    splits = ds.load_dataset()
    assert list(splits["train"]) == rec["train_classes"]
    assert list(splits["val"]) == rec["val_classes"]
    assert list(splits["test"]) == rec["test_classes"]


def test_derived_split_seeds_match_reference(tmp_path, monkeypatch):
    """Full __init__ derives the same split seeds the reference does
    (data.py:132-142), including test == val."""
    root = tmp_path / "omniglot_mini"
    rng = np.random.RandomState(0)
    for a in range(2):
        for c in range(4):
            d = root / f"Alphabet{a}" / f"char{c}"
            d.mkdir(parents=True)
            img = (rng.randint(0, 2, (28, 28)) * 255).astype(np.uint8)
            Image.fromarray(img, mode="L").save(str(d / "0.png"))
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))

    derived = {d["arg"]: d["derived"] for d in GOLDEN["derived_seeds"]}
    args = Bunch(dict(
        dataset_name="omniglot_mini",
        dataset_path=str(root),
        image_height=28, image_width=28, image_channels=1,
        reset_stored_filepaths=False, reverse_channels=False,
        labels_as_int=False, train_val_test_split=[0.5, 0.25, 0.25],
        indexes_of_folders_indicating_class=[-3, -2],
        num_target_samples=1, num_samples_per_class=1, num_classes_per_set=2,
        train_seed=104, val_seed=0, sets_are_pre_split=False,
        load_into_memory=False,
    ))
    ds = FewShotLearningDataset(args)
    assert ds.init_seed["train"] == derived[104]
    assert ds.init_seed["val"] == derived[0]
    assert ds.init_seed["test"] == derived[0]
