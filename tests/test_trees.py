"""Coverage for ``utils/trees.py`` — the partition/merge pytree helpers the
inner loop, checkpointing, and sharding all lean on (previously untested)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.utils.trees import merge, partition


def tree():
    return {
        "conv0": {"weight": jnp.ones((2, 2)), "bias": jnp.zeros((2,))},
        "norm": {"gamma": jnp.full((2,), 2.0), "beta": jnp.full((2,), 3.0)},
    }


def mask_conv_only():
    return {
        "conv0": {"weight": True, "bias": True},
        "norm": {"gamma": False, "beta": False},
    }


def test_partition_splits_by_mask():
    selected, rest = partition(tree(), mask_conv_only())
    assert selected["norm"]["gamma"] is None
    assert selected["norm"]["beta"] is None
    assert rest["conv0"]["weight"] is None
    np.testing.assert_array_equal(selected["conv0"]["weight"], np.ones((2, 2)))
    np.testing.assert_array_equal(rest["norm"]["beta"], np.full((2,), 3.0))


def test_partition_halves_are_valid_pytrees():
    # None subtrees are empty to JAX: each half carries exactly its own
    # leaves, and together they carry all of them.
    selected, rest = partition(tree(), mask_conv_only())
    assert len(jax.tree.leaves(selected)) == 2
    assert len(jax.tree.leaves(rest)) == 2
    assert len(jax.tree.leaves(tree())) == 4


def test_merge_restores_partitioned_tree():
    original = tree()
    selected, rest = partition(original, mask_conv_only())
    merged = merge(selected, rest)
    assert jax.tree.structure(merged) == jax.tree.structure(original)
    jax.tree.map(np.testing.assert_array_equal, merged, original)


def test_merge_order_independent_for_complementary_trees():
    selected, rest = partition(tree(), mask_conv_only())
    jax.tree.map(
        np.testing.assert_array_equal, merge(selected, rest), merge(rest, selected)
    )


def test_merge_first_non_none_wins():
    a = {"x": jnp.ones(2), "y": None}
    b = {"x": jnp.zeros(2), "y": jnp.full((2,), 7.0)}
    merged = merge(a, b)
    np.testing.assert_array_equal(merged["x"], np.ones(2))  # a wins on overlap
    np.testing.assert_array_equal(merged["y"], np.full((2,), 7.0))


def test_merge_three_way():
    t = tree()
    mask_a = mask_conv_only()
    a, bc = partition(t, mask_a)
    mask_b = {
        "conv0": {"weight": False, "bias": False},
        "norm": {"gamma": True, "beta": False},
    }
    b, c = partition(bc, mask_b)
    merged = merge(a, b, c)
    jax.tree.map(np.testing.assert_array_equal, merged, t)


def test_merge_all_none_position_stays_none():
    a = {"x": None}
    b = {"x": None}
    assert merge(a, b)["x"] is None


def test_partition_mask_structure_mismatch_raises():
    with pytest.raises(ValueError):
        partition(tree(), {"conv0": {"weight": True}})


def test_partition_merge_under_jit_and_grad():
    # The helpers run inside the traced inner loop — they must be
    # transparent to jit and differentiation.
    t = {"a": jnp.arange(3.0), "b": jnp.arange(3.0) + 1.0}
    mask = {"a": True, "b": False}

    @jax.jit
    def loss(params):
        adapt, frozen = partition(params, mask)
        adapt = jax.tree.map(lambda x: x * 2.0, adapt)
        full = merge(adapt, frozen)
        return sum(jnp.sum(v) for v in jax.tree.leaves(full))

    grads = jax.grad(loss)(t)
    np.testing.assert_array_equal(grads["a"], np.full(3, 2.0))
    np.testing.assert_array_equal(grads["b"], np.ones(3))
