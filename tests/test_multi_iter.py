"""Iteration batching (run_train_iters): K scanned meta-updates must be
numerically equivalent to K individual run_train_iter calls on the same
batch stream. (Not bitwise: the scanned program compiles differently, and
Adam's rsqrt amplifies ulp-level reduction-order differences.)"""

import jax
import numpy as np

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)


def _cfg():
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2, num_filters=4, per_step_bn_statistics=True,
            num_steps=2, num_classes=5, image_height=8, image_width=8,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        total_epochs=4, total_iter_per_epoch=2,
        multi_step_loss_num_epochs=2,
    )


def _batches(k, rng):
    out = []
    for _ in range(k):
        xs = rng.rand(3, 5, 1, 1, 8, 8).astype(np.float32)
        ys = np.tile(np.arange(5)[None, :, None], (3, 1, 1))
        out.append((xs, xs.copy(), ys, ys.copy()))
    return out


def test_multi_matches_sequential():
    cfg = _cfg()
    rng = np.random.RandomState(0)
    batches = _batches(3, rng)

    for epoch in (0, 3):  # MSL regime and final-only regime
        learner_a = MAMLFewShotLearner(cfg)
        state_a = learner_a.init_state(jax.random.PRNGKey(7))
        for b in batches:
            state_a, losses_a = learner_a.run_train_iter(state_a, b, epoch=epoch)

        learner_b = MAMLFewShotLearner(cfg)
        state_b = learner_b.init_state(jax.random.PRNGKey(7))
        state_b, losses_b = learner_b.run_train_iters(state_b, batches, epoch=epoch)

        for leaf_a, leaf_b in zip(
            jax.tree.leaves(state_a.theta), jax.tree.leaves(state_b.theta)
        ):
            np.testing.assert_allclose(
                np.asarray(leaf_a), np.asarray(leaf_b), rtol=2e-2, atol=1e-3
            )
        # Per-iteration metrics: run_train_iters returns (K,) arrays whose
        # last entry matches the final sequential iteration's scalar.
        assert np.asarray(losses_b["loss"]).shape == (len(batches),)
        assert np.asarray(losses_b["accuracy"]).shape == (len(batches),)
        np.testing.assert_allclose(
            float(losses_a["loss"]),
            float(np.asarray(losses_b["loss"])[-1]),
            rtol=5e-2, atol=1e-3,
        )


def test_multi_iter_sharded_mesh(spmd_compile_guard):
    """run_train_iters under a dp mesh: batches shard over 'dp', result
    matches the unsharded multi-step run. Guarded: some jaxlib builds
    CHECK-crash XLA's CPU GSPMD partitioner on sharded conv programs
    (tests/conftest.py spmd_compile_guard), which would abort the whole
    pytest process here and truncate the suite."""
    from howtotrainyourmamlpytorch_tpu.parallel import make_mesh

    cfg = _cfg()
    rng = np.random.RandomState(1)
    batches = _batches(2, rng)
    mesh = make_mesh(jax.devices()[:4], data_parallel=4, model_parallel=1)
    # batch of 3 tasks doesn't divide 4 -> use 4-task batches
    batches = [
        tuple(np.concatenate([a, a[:1]], axis=0) for a in b) for b in batches
    ]

    plain = MAMLFewShotLearner(cfg)
    s0 = plain.init_state(jax.random.PRNGKey(2))
    s0, _ = plain.run_train_iters(s0, batches, epoch=3)

    sharded = MAMLFewShotLearner(cfg, mesh=mesh)
    s1 = sharded.init_state(jax.random.PRNGKey(2))
    s1, _ = sharded.run_train_iters(s1, batches, epoch=3)

    for a, b in zip(jax.tree.leaves(s0.theta), jax.tree.leaves(s1.theta)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-2, atol=1e-3
        )


def test_k_dispatch_summary_sample_fidelity(tmp_path, monkeypatch):
    """Epoch CSV mean/std must be computed from one sample per meta-update
    at any --iters_per_dispatch (VERDICT r2 weak #6): a K=4 run over the
    same deterministic stream produces the same per-epoch summary
    statistics as K=1 (tolerance-equal; the scanned program compiles
    differently)."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_data import make_args, make_dataset_dir

    from howtotrainyourmamlpytorch_tpu.experiment_builder import (
        ExperimentBuilder,
    )
    from howtotrainyourmamlpytorch_tpu.data import MetaLearningSystemDataLoader
    from howtotrainyourmamlpytorch_tpu.utils import storage
    from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
        args_to_maml_config,
    )

    make_dataset_dir(tmp_path / "omniglot_mini")
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))

    def run(exp, k):
        args = make_args(
            tmp_path,
            experiment_name=str(tmp_path / exp),
            seed=104, continue_from_epoch="from_scratch",
            max_models_to_save=5,
            total_epochs=2, total_iter_per_epoch=10,
            total_epochs_before_pause=100, num_evaluation_tasks=4,
            evaluate_on_test_set_only=False, batch_size=2,
            iters_per_dispatch=k,
            num_stages=2, cnn_num_filters=4, conv_padding=True,
            max_pooling=True, norm_layer="batch_norm",
            per_step_bn_statistics=True,
            number_of_training_steps_per_iter=2,
            number_of_evaluation_steps_per_iter=2,
            num_classes_per_set=5, second_order=False,
            first_order_to_second_order_epoch=-1,
            use_multi_step_loss_optimization=True,
            multi_step_loss_num_epochs=2,
            learnable_per_layer_per_step_inner_loop_learning_rate=True,
            enable_inner_loop_optimizable_bn_params=False,
            learnable_bn_gamma=True, learnable_bn_beta=True,
            meta_learning_rate=0.001, min_learning_rate=1e-5,
            task_learning_rate=0.1, init_inner_loop_learning_rate=0.1,
        )
        from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner

        model = MAMLFewShotLearner(args_to_maml_config(args))
        ExperimentBuilder(
            args=args, data=MetaLearningSystemDataLoader, model=model,
            device=None,
        ).run_experiment()
        return storage.load_statistics(
            os.path.join(str(tmp_path / exp), "logs")
        )

    s1 = run("exp_k1", 1)
    # K=4 does not divide 10 -> exercises the short epoch-boundary chunk too
    s4 = run("exp_k4", 4)
    for key in ("train_loss_mean", "train_loss_std", "train_accuracy_mean",
                "train_accuracy_std"):
        a = np.asarray([float(v) for v in s1[key]])
        b = np.asarray([float(v) for v in s4[key]])
        np.testing.assert_allclose(a, b, rtol=5e-2, atol=2e-3, err_msg=key)
