"""Iteration batching (run_train_iters): K scanned meta-updates must be
numerically equivalent to K individual run_train_iter calls on the same
batch stream. (Not bitwise: the scanned program compiles differently, and
Adam's rsqrt amplifies ulp-level reduction-order differences.)"""

import jax
import numpy as np

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)


def _cfg():
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2, num_filters=4, per_step_bn_statistics=True,
            num_steps=2, num_classes=5, image_height=8, image_width=8,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        total_epochs=4, total_iter_per_epoch=2,
        multi_step_loss_num_epochs=2,
    )


def _batches(k, rng):
    out = []
    for _ in range(k):
        xs = rng.rand(3, 5, 1, 1, 8, 8).astype(np.float32)
        ys = np.tile(np.arange(5)[None, :, None], (3, 1, 1))
        out.append((xs, xs.copy(), ys, ys.copy()))
    return out


def test_multi_matches_sequential():
    cfg = _cfg()
    rng = np.random.RandomState(0)
    batches = _batches(3, rng)

    for epoch in (0, 3):  # MSL regime and final-only regime
        learner_a = MAMLFewShotLearner(cfg)
        state_a = learner_a.init_state(jax.random.PRNGKey(7))
        for b in batches:
            state_a, losses_a = learner_a.run_train_iter(state_a, b, epoch=epoch)

        learner_b = MAMLFewShotLearner(cfg)
        state_b = learner_b.init_state(jax.random.PRNGKey(7))
        state_b, losses_b = learner_b.run_train_iters(state_b, batches, epoch=epoch)

        for leaf_a, leaf_b in zip(
            jax.tree.leaves(state_a.theta), jax.tree.leaves(state_b.theta)
        ):
            np.testing.assert_allclose(
                np.asarray(leaf_a), np.asarray(leaf_b), rtol=2e-2, atol=1e-3
            )
        # Last-iteration metrics agree.
        np.testing.assert_allclose(
            float(losses_a["loss"]), float(losses_b["loss"]), rtol=5e-2, atol=1e-3
        )


def test_multi_iter_sharded_mesh():
    """run_train_iters under a dp mesh: batches shard over 'dp', result
    matches the unsharded multi-step run."""
    from howtotrainyourmamlpytorch_tpu.parallel import make_mesh

    cfg = _cfg()
    rng = np.random.RandomState(1)
    batches = _batches(2, rng)
    mesh = make_mesh(jax.devices()[:4], data_parallel=4, model_parallel=1)
    # batch of 3 tasks doesn't divide 4 -> use 4-task batches
    batches = [
        tuple(np.concatenate([a, a[:1]], axis=0) for a in b) for b in batches
    ]

    plain = MAMLFewShotLearner(cfg)
    s0 = plain.init_state(jax.random.PRNGKey(2))
    s0, _ = plain.run_train_iters(s0, batches, epoch=3)

    sharded = MAMLFewShotLearner(cfg, mesh=mesh)
    s1 = sharded.init_state(jax.random.PRNGKey(2))
    s1, _ = sharded.run_train_iters(s1, batches, epoch=3)

    for a, b in zip(jax.tree.leaves(s0.theta), jax.tree.leaves(s1.theta)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-2, atol=1e-3
        )
