"""Inner-loop optimizer and MSL schedule tests
(inner_loop_optimizers.py, few_shot_learning_system.py:83-103)."""

import jax
import jax.numpy as jnp
import numpy as np

from howtotrainyourmamlpytorch_tpu.inner_loop import init_lslr, lslr_update, sgd_update
from howtotrainyourmamlpytorch_tpu.models.maml import (
    final_step_importance,
    per_step_loss_importance,
)
from howtotrainyourmamlpytorch_tpu.utils.trees import merge, partition


def test_sgd_update():
    p = {"w": jnp.ones(3)}
    g = {"w": jnp.full(3, 2.0)}
    out = sgd_update(p, g, 0.1)
    np.testing.assert_allclose(np.asarray(out["w"]), 0.8)


def test_lslr_allocates_num_steps_plus_one():
    """Parity with inner_loop_optimizers.py:90 (num_steps+1 rates)."""
    adapt = {"a": jnp.zeros((2, 2)), "b": jnp.zeros(3)}
    lslr = init_lslr(adapt, num_steps=5, init_learning_rate=0.1)
    assert lslr["a"].shape == (6,)
    np.testing.assert_allclose(np.asarray(lslr["b"]), 0.1)


def test_lslr_update_indexes_per_step():
    adapt = {"a": jnp.ones(2)}
    lslr = {"a": jnp.asarray([0.1, 0.5, 0.0])}
    g = {"a": jnp.ones(2)}
    out0 = lslr_update(adapt, g, lslr, 0)
    out1 = lslr_update(adapt, g, lslr, 1)
    np.testing.assert_allclose(np.asarray(out0["a"]), 0.9)
    np.testing.assert_allclose(np.asarray(out1["a"]), 0.5)


def test_lslr_gradient_flows_to_learning_rate():
    """LSLR rates receive outer gradients even first-order (the update
    w - lr*g is differentiable in lr)."""
    adapt = {"a": jnp.ones(())}
    lslr = {"a": jnp.asarray([0.1, 0.1])}

    def loss(lslr_):
        g = {"a": jnp.asarray(2.0)}
        new = lslr_update(adapt, g, lslr_, 0)
        return new["a"] ** 2

    grad = jax.grad(loss)(lslr)
    assert float(grad["a"][0]) != 0.0
    assert float(grad["a"][1]) == 0.0


def test_partition_merge_roundtrip():
    tree = {"x": {"w": jnp.ones(2), "norm": jnp.zeros(2)}}
    mask = {"x": {"w": True, "norm": False}}
    sel, rest = partition(tree, mask)
    assert rest["x"]["w"] is None and sel["x"]["norm"] is None
    merged = merge(sel, rest)
    np.testing.assert_allclose(np.asarray(merged["x"]["w"]), 1.0)
    np.testing.assert_allclose(np.asarray(merged["x"]["norm"]), 0.0)


def test_msl_importance_matches_reference_math():
    """Exact replication of get_per_step_loss_importance_vector
    (few_shot_learning_system.py:83-103)."""
    for epoch in [0, 3, 9, 15, 50]:
        n, msl_epochs = 5, 10
        ours = per_step_loss_importance(epoch, n, msl_epochs)
        # reference math, independently recomputed
        w = np.ones(n) * (1.0 / n)
        decay = 1.0 / n / msl_epochs
        min_nf = 0.03 / n
        for i in range(n - 1):
            w[i] = max(w[i] - epoch * decay, min_nf)
        w[-1] = min(w[-1] + epoch * (n - 1) * decay, 1.0 - (n - 1) * min_nf)
        np.testing.assert_allclose(ours, w, atol=1e-7)
        np.testing.assert_allclose(ours.sum(), 1.0, atol=1e-5)


def test_msl_importance_converges_to_final_step():
    v = per_step_loss_importance(9, 5, 10)
    assert v[-1] > 0.9
    one_hot = final_step_importance(5)
    np.testing.assert_allclose(one_hot, [0, 0, 0, 0, 1.0])
