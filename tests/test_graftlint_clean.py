"""Tier-1 gate: the whole tree lints clean, forever.

Runs the real CLI (``python -m tools.graftlint``) over the same surface a
CI step would, so no separate CI config is needed — a new violation
anywhere in ``howtotrainyourmamlpytorch_tpu/``, ``tests/`` or ``tools/``
fails the suite. Also pins the CLI contract itself (non-zero exit,
``--format=github`` annotations incl. the v2 concurrency rules,
``--list-rules``) and keeps the README rule table in sync with the live
registry.

The per-plane standalone pins that used to be eight near-identical test
functions (one of which shadowed another by sharing its name — exactly
the duplication this table removes) are ONE parametrized in-process test
over :data:`PLANES`: same coverage (explicit target lists that survive a
LINT_TARGETS reshuffle, discovery assertions so an empty scan can't
vacuously pass, zero-suppression scans where a plane must be clean on
its own merits), a fraction of the walltime (no per-plane subprocess).
"""

import json
import os
import re
import subprocess
import sys
import tempfile
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# The package target covers every subpackage; entry files at the repo
# root (train_*.py, bench.py) ride the planes below AND the explicit
# list here so the tree-wide CLI gate scans them too.
LINT_TARGETS = [
    "howtotrainyourmamlpytorch_tpu", "tests", "tools",
    "train_maml_system.py", "train_gradient_descent_system.py",
    "train_matching_nets_system.py", "train_anil_system.py",
    "train_protonets_system.py", "train_maml_system_dispatch.py",
    "bench.py",
]

PKG = "howtotrainyourmamlpytorch_tpu"

#: plane -> {targets, expect (basenames the scan must discover),
#: zero_suppressions}. One entry per subsystem a past PR pinned; the
#: parametrized test below is the single implementation.
PLANES = {
    "serve": {
        "targets": [
            f"{PKG}/serve", "tools/serve_maml.py", "tools/serve_bench.py",
        ],
        "expect": {"engine.py", "batcher.py", "cache.py", "api.py",
                   "metrics.py"},
        "zero_suppressions": False,  # ISSUE 4 predates the zero-sup pins
    },
    "telemetry": {
        "targets": [f"{PKG}/telemetry", "tools/telemetry_report.py"],
        "expect": {"registry.py", "events.py", "profiling.py", "runtime.py",
                   "heartbeat.py", "anomaly.py", "device.py",
                   "telemetry_report.py"},
        "zero_suppressions": True,
    },
    "device-plane": {
        # ISSUE 15: the per-program FLOPs/HBM ledger + its tool consumers
        # (profile_step's accounting block, the report's device section,
        # bench's mfu_pct/hbm_peak_bytes derivation) stay clean standalone
        # with zero suppressions.
        "targets": [
            f"{PKG}/telemetry/device.py", "tools/profile_step.py",
            "tools/telemetry_report.py", "bench.py",
        ],
        "expect": {"device.py", "profile_step.py", "telemetry_report.py",
                   "bench.py"},
        "zero_suppressions": True,
    },
    "serve-resilience": {
        "targets": [
            f"{PKG}/serve/resilience", f"{PKG}/serve/pool.py",
            f"{PKG}/serve/errors.py", "tools/serve_loadtest.py",
        ],
        "expect": {"admission.py", "swap.py", "replica.py", "pool.py",
                   "errors.py", "serve_loadtest.py"},
        "zero_suppressions": True,
    },
    "device-prefetch": {
        # Its ``jax.device_put`` is the ONE sanctioned exception to
        # device-op-in-data-path, granted via the rule's own allowlist —
        # an inline suppression would weaken the data-path ban.
        "targets": [f"{PKG}/data/device_prefetch.py"],
        "expect": {"device_prefetch.py"},
        "zero_suppressions": True,
    },
    "parallel": {
        "targets": [f"{PKG}/parallel"],
        "expect": {"mesh.py", "sharding.py", "distributed.py",
                   "multihost.py"},
        "zero_suppressions": True,
    },
    "layout": {
        "targets": [f"{PKG}/ops/layout.py"],
        "expect": {"layout.py"},
        "zero_suppressions": True,
    },
    "train-resilience": {
        # ISSUE 10: watchdog monitor, async checkpoint writer, prefetch
        # stager and dispatcher all pass thread-lifecycle (spawn + an
        # owner-reachable join).
        "targets": [
            f"{PKG}/utils/watchdog.py", f"{PKG}/utils/checkpoint.py",
            f"{PKG}/data/device_prefetch.py", "tools/chaos_train.py",
            "train_maml_system_dispatch.py",
        ],
        "expect": {"watchdog.py", "checkpoint.py", "device_prefetch.py",
                   "chaos_train.py", "train_maml_system_dispatch.py"},
        "zero_suppressions": True,
    },
    "multihost": {
        # Entry files live at the repo root (outside the default package
        # targets); this plane is what keeps them scanned forever —
        # including device-probe-before-distributed-init ordering.
        "targets": [
            f"{PKG}/parallel", "train_maml_system.py",
            "train_gradient_descent_system.py",
            "train_matching_nets_system.py", "train_anil_system.py",
            "train_protonets_system.py", "train_maml_system_dispatch.py",
            "tools/serve_maml.py", "tools/chaos_train.py", "bench.py",
        ],
        "expect": {"distributed.py", "mesh.py", "multihost.py",
                   "train_maml_system.py", "train_maml_system_dispatch.py"},
        "zero_suppressions": True,
    },
    "observability": {
        "targets": [
            "tools/bench_judge.py", "tools/telemetry_report.py",
            f"{PKG}/telemetry", f"{PKG}/utils/watchdog.py",
            "train_maml_system_dispatch.py", "bench.py",
        ],
        "expect": {"bench_judge.py", "telemetry_report.py", "heartbeat.py",
                   "anomaly.py", "device.py", "events.py", "runtime.py",
                   "watchdog.py"},
        "zero_suppressions": True,
    },
    "control-plane": {
        # ISSUE 13: the promotion daemon's watcher/SLO threads carry
        # owner-reachable joins (thread-lifecycle coverage is live here).
        "targets": [
            f"{PKG}/serve/resilience/promotion.py",
            "tools/promotion_daemon.py", "tools/episode_miner.py",
            "tools/chaos_train.py",
        ],
        "expect": {"promotion.py", "promotion_daemon.py",
                   "episode_miner.py", "chaos_train.py"},
        "zero_suppressions": True,
    },
    "concurrency": {
        # ISSUE 14: the analyzer itself and its runtime twin lint clean
        # under the full rule set (incl. the five rules they implement).
        "targets": ["tools/graftlint", f"{PKG}/utils/locksan.py"],
        "expect": {"concurrency.py", "rules.py", "engine.py", "core.py",
                   "tracing.py", "locksan.py"},
        "zero_suppressions": True,
    },
    "serve-tier": {
        # ISSUE 18: the durable serving tier (atomic writer, artifact
        # spill, AOT executable cache, routing ring) lints clean with
        # zero suppressions — including its own durable-write rule.
        "targets": [f"{PKG}/serve/tier"],
        "expect": {"__init__.py", "atomic.py", "spill.py", "execcache.py",
                   "ring.py"},
        "zero_suppressions": True,
    },
    "learner-zoo": {
        # ISSUE 19: the two new learner families (head-only ANIL, metric
        # protonets) plus their entry points lint clean standalone with
        # zero suppressions — the shared-contract peers earn no carve-outs.
        "targets": [
            f"{PKG}/models/anil.py", f"{PKG}/models/protonets.py",
            "train_anil_system.py", "train_protonets_system.py",
        ],
        "expect": {"anil.py", "protonets.py", "train_anil_system.py",
                   "train_protonets_system.py"},
        "zero_suppressions": True,
    },
    "geometry": {
        # ISSUE 19: the episode-geometry subsystem (coarsening policy +
        # its synthetic traffic generator) is pure host-side numpy and
        # must stay that way — zero suppressions.
        "targets": [
            f"{PKG}/serve/geometry.py", f"{PKG}/data/synth_geometry.py",
        ],
        "expect": {"geometry.py", "synth_geometry.py"},
        "zero_suppressions": True,
    },
    "resource-plane": {
        # ISSUE 20: the self-driving resource plane — declarative knob
        # space + ledger-guided autotuner, journal-backed autoscaler
        # daemon, and their CLIs — lints clean standalone with zero
        # suppressions (incl. durable-write on the journal/gates paths
        # and thread-lifecycle/signal-handler rules on the daemon).
        "targets": [
            f"{PKG}/tune", f"{PKG}/serve/resilience/autoscaler.py",
            "tools/autotune.py", "tools/autoscaler_daemon.py",
            "tools/serve_loadtest.py",
        ],
        "expect": {"__init__.py", "space.py", "autotuner.py",
                   "autoscaler.py", "autotune.py", "autoscaler_daemon.py",
                   "serve_loadtest.py"},
        "zero_suppressions": True,
    },
    "program-plane": {
        # ISSUE 17: the IR-level program analyzer and the fused-collective
        # machinery its budget rule enforces lint clean under the full
        # AST rule set themselves.
        "targets": [
            "tools/graftlint/programs.py",
            f"{PKG}/parallel/collectives.py",
        ],
        "expect": {"programs.py", "collectives.py"},
        "zero_suppressions": True,
    },
}


def run_cli(*argv: str, cwd: str = REPO) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *argv],
        capture_output=True,
        text=True,
        cwd=cwd,
        env=env,
        timeout=300,
    )


def test_tree_lints_clean():
    proc = run_cli(*LINT_TARGETS)
    assert proc.returncode == 0, (
        "graftlint found violations in the tree:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert "graftlint: clean" in proc.stderr


def test_in_process_api_agrees_with_cli():
    from tools.graftlint import lint_paths

    violations = lint_paths([os.path.join(REPO, t) for t in LINT_TARGETS])
    assert violations == [], [v.format_text() for v in violations]


@pytest.mark.parametrize("plane", sorted(PLANES))
def test_plane_lints_clean_standalone(plane):
    """Each subsystem stays lint-clean as its OWN target: explicit lists
    survive any LINT_TARGETS reshuffle, the discovery assertion keeps an
    empty scan from vacuously passing, and zero-suppression planes must
    be clean on their own merits."""
    from tools.graftlint import lint_paths
    from tools.graftlint.engine import _collect_files

    spec = PLANES[plane]
    targets = [os.path.join(REPO, t) for t in spec["targets"]]
    for target in targets:
        assert os.path.exists(target), target
    scanned = _collect_files(targets)
    names = {os.path.basename(p) for p in scanned}
    assert spec["expect"] <= names, (plane, names)
    violations = lint_paths(targets)
    assert violations == [], [v.format_text() for v in violations]
    if spec["zero_suppressions"]:
        # The REAL suppression parser, not a substring grep: the linter's
        # own sources mention the directive in docstrings/templates
        # without carrying one.
        from tools.graftlint.core import _parse_suppressions

        for path in scanned:
            with open(path) as f:
                assert _parse_suppressions(f.read()) == [], path


def test_observability_gate_data_parses():
    """The judge's gate DATA rides next to it: it must parse and carry
    the schema the judge reads (a malformed gates file would otherwise
    only surface on the next judge run)."""
    with open(os.path.join(REPO, "tools", "bench_gates.json")) as f:
        gates_doc = json.load(f)
    assert gates_doc["schema"] == 1 and gates_doc["gates"]


def test_cli_exits_nonzero_and_annotates_on_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "\n"
        "def sample(key):\n"
        "    a = jax.random.normal(key, (3,))\n"
        "    b = jax.random.uniform(key, (3,))\n"
        "    return a + b\n"
    )
    proc = run_cli(str(bad))
    assert proc.returncode == 1
    assert "prng-reuse" in proc.stdout

    proc_gh = run_cli(str(bad), "--format=github")
    assert proc_gh.returncode == 1
    line = proc_gh.stdout.strip().splitlines()[0]
    assert line.startswith("::error file=")
    assert "title=graftlint prng-reuse" in line


#: Seeded violations proving each rule fires through the REAL CLI, with
#: ``--format=github`` annotations verified for the v2 concurrency rules
#: (the CI surface the new rules ship on).
_SEEDED_CLI_CASES = {
    "thread-lifecycle": """
        import threading

        class Leaky:
            def __init__(self):
                self._t = threading.Thread(target=print)
                self._t.start()
        """,
    "device-probe-before-distributed-init": """
        import jax
        from howtotrainyourmamlpytorch_tpu.parallel import (
            initialize_distributed,
        )

        print(jax.devices())
        initialize_distributed()
        """,
    "lock-order-inversion": """
        import threading

        class Pair:
            def __init__(self):
                self._la = threading.Lock()
                self._lb = threading.Lock()

            def forward(self):
                with self._la:
                    with self._lb:
                        pass

            def backward(self):
                with self._lb:
                    with self._la:
                        pass
        """,
    "blocking-under-lock": """
        import threading
        import time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                with self._lock:
                    time.sleep(1.0)
        """,
    "signal-handler-unsafe": """
        import signal
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                signal.signal(signal.SIGTERM, self._onterm)

            def _onterm(self, signum, frame):
                with self._lock:
                    self.flag = True
        """,
    "chief-only-write": """
        import os

        class T:
            def __init__(self, args):
                self.process_index = int(args.process_index)
                self._is_chief = self.process_index == 0

            def publish(self, src, dst):
                os.replace(src, dst)
        """,
    "exit-code-contract": """
        import sys

        sys.exit(42)
        """,
    "durable-write": """
        def rewrite(journal_path, rows):
            with open(journal_path, "w") as f:
                f.write(rows)
        """,
}


@pytest.mark.parametrize("rule", sorted(_SEEDED_CLI_CASES))
def test_rule_registered_and_fires_through_cli(rule):
    from tools.graftlint import RULES

    assert rule in RULES
    with tempfile.TemporaryDirectory() as tmp:
        bad = os.path.join(tmp, "seeded.py")
        with open(bad, "w") as f:
            f.write(textwrap.dedent(_SEEDED_CLI_CASES[rule]))
        proc = run_cli(bad)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert rule in proc.stdout
        proc_gh = run_cli(bad, "--format=github")
        assert proc_gh.returncode == 1
        assert f"title=graftlint {rule}" in proc_gh.stdout


def test_cli_list_rules_names_the_full_set():
    proc = run_cli("--list-rules")
    assert proc.returncode == 0
    listed = {
        line.split(":", 1)[0] for line in proc.stdout.splitlines() if ":" in line
    }
    assert {
        "prng-reuse",
        "host-numpy-in-trace",
        "tracer-branch",
        "jit-static-config",
        "missing-donate",
        "dead-flag",
        "device-op-in-data-path",
        "traced-mutation",
        "thread-lifecycle",
        "device-probe-before-distributed-init",
        "durable-write",
        "lock-order-inversion",
        "blocking-under-lock",
        "signal-handler-unsafe",
        "chief-only-write",
        "exit-code-contract",
        "collective-budget",
        "dtype-leak",
        "donation-violation",
        "host-callback-in-step",
        "spec-coverage",
    } <= listed
    assert len(listed) >= 20


def test_readme_rule_table_in_sync_with_registry():
    """The README "Static analysis & sanitizers" rule table is generated
    from ``--list-rules`` — every registered rule id must appear in the
    README, and the README must not name rules that no longer exist, so
    the docs and the live registry can never drift."""
    from tools.graftlint import RULES

    with open(os.path.join(REPO, "README.md")) as f:
        readme = f.read()
    start = readme.index("## Static analysis & sanitizers")
    end = readme.find("\n## ", start + 1)
    section = readme[start:] if end == -1 else readme[start:end]
    for rule_id in RULES:
        assert f"`{rule_id}`" in section, (
            f"README rule table is missing {rule_id!r} — regenerate it "
            "from `python -m tools.graftlint --list-rules`"
        )
    # Reverse direction: every first-column id in the rule table must
    # still be a registered rule — a renamed/removed rule may not leave
    # a stale row behind.
    table_ids = re.findall(r"^\| `([a-z][a-z0-9-]*)` \|", section, re.M)
    assert table_ids, "README rule table rows not found"
    for table_id in table_ids:
        assert table_id in RULES, (
            f"README rule table names {table_id!r}, which is not a "
            "registered rule — regenerate the table from --list-rules"
        )


def test_cli_select_filters_rules(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "\n"
        "def sample(key):\n"
        "    a = jax.random.normal(key, (3,))\n"
        "    b = jax.random.uniform(key, (3,))\n"
        "    return a + b\n"
    )
    proc = run_cli(str(bad), "--select", "missing-donate")
    assert proc.returncode == 0  # the only finding is prng-reuse, filtered out
    proc_unknown = run_cli(str(bad), "--select", "bogus-rule")
    assert proc_unknown.returncode == 2
