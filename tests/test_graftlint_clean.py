"""Tier-1 gate: the whole tree lints clean, forever.

Runs the real CLI (``python -m tools.graftlint``) over the same surface a CI
step would, so no separate CI config is needed — a new violation anywhere in
``howtotrainyourmamlpytorch_tpu/``, ``tests/`` or ``tools/`` fails the
suite. Also pins the CLI contract itself: non-zero exit on violations,
``--format=github`` annotations, ``--list-rules``.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# The package target covers every subpackage (incl. the serving runtime,
# howtotrainyourmamlpytorch_tpu/serve/ — pinned explicitly below so a
# future target-list refactor can't silently drop the new subsystem).
LINT_TARGETS = ["howtotrainyourmamlpytorch_tpu", "tests", "tools"]


def run_cli(*argv: str, cwd: str = REPO) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *argv],
        capture_output=True,
        text=True,
        cwd=cwd,
        env=env,
        timeout=300,
    )


def test_tree_lints_clean():
    proc = run_cli(*LINT_TARGETS)
    assert proc.returncode == 0, (
        "graftlint found violations in the tree:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert "graftlint: clean" in proc.stderr


def test_in_process_api_agrees_with_cli():
    from tools.graftlint import lint_paths

    violations = lint_paths([os.path.join(REPO, t) for t in LINT_TARGETS])
    assert violations == [], [v.format_text() for v in violations]


def test_serve_subsystem_lints_clean_standalone():
    """The serving runtime (ISSUE 4) stays lint-clean as its own target:
    the whole-package gate above covers it transitively, but this pin makes
    the coverage explicit and survives any future LINT_TARGETS reshuffle.
    Also asserts the linter actually DISCOVERED the serve modules (an empty
    scan would vacuously pass)."""
    serve_dir = os.path.join(REPO, "howtotrainyourmamlpytorch_tpu", "serve")
    assert os.path.isdir(serve_dir)
    proc = run_cli(serve_dir, "tools/serve_maml.py", "tools/serve_bench.py")
    assert proc.returncode == 0, (
        "graftlint found violations in the serving runtime:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert "graftlint: clean" in proc.stderr

    from tools.graftlint import lint_paths
    from tools.graftlint.engine import _collect_files

    scanned = {os.path.basename(p) for p in _collect_files([serve_dir])}
    assert {
        "engine.py", "batcher.py", "cache.py", "api.py", "metrics.py",
    } <= scanned
    assert lint_paths([serve_dir]) == []


def test_telemetry_subsystem_lints_clean_standalone():
    """The telemetry subsystem (ISSUE 5) stays lint-clean as its own target
    with ZERO suppressions: the whole-package gate covers it transitively,
    but this pin survives any future LINT_TARGETS reshuffle. Also asserts
    the linter actually DISCOVERED the telemetry modules (an empty scan
    would vacuously pass) and that no inline suppressions crept in."""
    telemetry_dir = os.path.join(
        REPO, "howtotrainyourmamlpytorch_tpu", "telemetry"
    )
    report_tool = os.path.join(REPO, "tools", "telemetry_report.py")
    assert os.path.isdir(telemetry_dir)
    proc = run_cli(telemetry_dir, report_tool)
    assert proc.returncode == 0, (
        "graftlint found violations in the telemetry subsystem:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert "graftlint: clean" in proc.stderr

    from tools.graftlint import lint_paths
    from tools.graftlint.engine import _collect_files

    scanned = _collect_files([telemetry_dir, report_tool])
    names = {os.path.basename(p) for p in scanned}
    assert {
        "registry.py", "events.py", "profiling.py", "runtime.py",
        "heartbeat.py", "anomaly.py", "telemetry_report.py",
    } <= names
    assert lint_paths([telemetry_dir, report_tool]) == []
    # Zero suppressions: the subsystem must be clean on its own merits.
    for path in scanned:
        with open(path) as f:
            assert "graftlint: disable" not in f.read(), path


def test_control_plane_lints_clean_standalone():
    """The continuous train→serve control plane (ISSUE 13) stays
    lint-clean as its own target with ZERO suppressions: the promotion
    daemon module + CLI, the episode miner, and the chaos harness that
    drives the promote schedule. ``thread-lifecycle`` coverage is live
    here — the daemon's watcher and SLO-sampler threads both carry
    owner-reachable joins. Also asserts the linter actually DISCOVERED
    the modules (an empty scan would vacuously pass)."""
    targets = [
        os.path.join(REPO, "howtotrainyourmamlpytorch_tpu", "serve",
                     "resilience", "promotion.py"),
        os.path.join(REPO, "tools", "promotion_daemon.py"),
        os.path.join(REPO, "tools", "episode_miner.py"),
        os.path.join(REPO, "tools", "chaos_train.py"),
    ]
    for target in targets:
        assert os.path.exists(target), target
    proc = run_cli(*targets)
    assert proc.returncode == 0, (
        "graftlint found violations in the promotion control plane:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert "graftlint: clean" in proc.stderr

    from tools.graftlint import lint_paths
    from tools.graftlint.engine import _collect_files

    scanned = _collect_files(targets)
    names = {os.path.basename(p) for p in scanned}
    assert {
        "promotion.py", "promotion_daemon.py", "episode_miner.py",
        "chaos_train.py",
    } <= names
    assert lint_paths(targets) == []
    for path in scanned:
        with open(path) as f:
            assert "graftlint: disable" not in f.read(), path


def test_observability_plane_lints_clean_standalone():
    """The fleet observability plane (ISSUE 12) stays lint-clean as its
    own target with ZERO suppressions: the bench judge + gate data, the
    fleet report tool, the heartbeat/anomaly modules, and the
    trace-stamping emitters. Also asserts the linter actually DISCOVERED
    the modules (an empty scan would vacuously pass)."""
    targets = [
        os.path.join(REPO, "tools", "bench_judge.py"),
        os.path.join(REPO, "tools", "telemetry_report.py"),
        os.path.join(REPO, "howtotrainyourmamlpytorch_tpu", "telemetry"),
        os.path.join(REPO, "howtotrainyourmamlpytorch_tpu", "utils",
                     "watchdog.py"),
        os.path.join(REPO, "train_maml_system_dispatch.py"),
        os.path.join(REPO, "bench.py"),
    ]
    for target in targets:
        assert os.path.exists(target), target
    # The gate DATA rides next to the judge: it must parse and carry the
    # schema the judge reads (a malformed gates file would otherwise only
    # surface on the next judge run).
    import json as json_module

    with open(os.path.join(REPO, "tools", "bench_gates.json")) as f:
        gates_doc = json_module.load(f)
    assert gates_doc["schema"] == 1 and gates_doc["gates"]
    proc = run_cli(*targets)
    assert proc.returncode == 0, (
        "graftlint found violations in the observability plane:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert "graftlint: clean" in proc.stderr

    from tools.graftlint import lint_paths
    from tools.graftlint.engine import _collect_files

    scanned = _collect_files(targets)
    names = {os.path.basename(p) for p in scanned}
    assert {
        "bench_judge.py", "telemetry_report.py", "heartbeat.py",
        "anomaly.py", "events.py", "runtime.py", "watchdog.py",
    } <= names
    assert lint_paths(targets) == []
    for path in scanned:
        with open(path) as f:
            assert "graftlint: disable" not in f.read(), path


def test_resilience_layer_lints_clean_standalone():
    """The serving resilience layer (ISSUE 6) stays lint-clean as its own
    target with ZERO suppressions: ``serve/pool.py``, the
    ``serve/resilience`` package, and ``tools/serve_loadtest.py``. The
    whole-package gate covers them transitively; this pin survives any
    future LINT_TARGETS reshuffle, asserts the linter actually DISCOVERED
    the modules (an empty scan would vacuously pass), and refuses inline
    suppressions."""
    serve_dir = os.path.join(REPO, "howtotrainyourmamlpytorch_tpu", "serve")
    resilience_dir = os.path.join(serve_dir, "resilience")
    pool_py = os.path.join(serve_dir, "pool.py")
    errors_py = os.path.join(serve_dir, "errors.py")
    loadtest_py = os.path.join(REPO, "tools", "serve_loadtest.py")
    assert os.path.isdir(resilience_dir)
    proc = run_cli(
        resilience_dir, pool_py, errors_py, "tools/serve_loadtest.py"
    )
    assert proc.returncode == 0, (
        "graftlint found violations in the resilience layer:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert "graftlint: clean" in proc.stderr

    from tools.graftlint import lint_paths
    from tools.graftlint.engine import _collect_files

    targets = [resilience_dir, pool_py, errors_py, loadtest_py]
    scanned = _collect_files(targets)
    names = {os.path.basename(p) for p in scanned}
    assert {
        "admission.py", "swap.py", "replica.py", "pool.py", "errors.py",
        "serve_loadtest.py",
    } <= names
    assert lint_paths(targets) == []
    # Zero suppressions: the layer must be clean on its own merits.
    for path in scanned:
        with open(path) as f:
            assert "graftlint: disable" not in f.read(), path


def test_device_prefetch_lints_clean_standalone():
    """The device-prefetch stager (ISSUE 7) stays lint-clean as its own
    target with ZERO suppressions. Its ``jax.device_put`` is the one
    sanctioned exception to ``device-op-in-data-path``, granted via the
    rule's own allowlist — an inline suppression would weaken the
    data-path ban for every future edit of the file."""
    stager_py = os.path.join(
        REPO, "howtotrainyourmamlpytorch_tpu", "data", "device_prefetch.py"
    )
    assert os.path.isfile(stager_py)
    proc = run_cli(stager_py)
    assert proc.returncode == 0, (
        "graftlint found violations in the device-prefetch stager:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert "graftlint: clean" in proc.stderr

    from tools.graftlint import lint_paths

    assert lint_paths([stager_py]) == []
    with open(stager_py) as f:
        assert "graftlint: disable" not in f.read()


def test_layout_module_lints_clean_standalone():
    """The lane-padded compute layout (ISSUE 9, ``ops/layout.py``) stays
    lint-clean as its own target with ZERO suppressions: its strip/pad
    helpers host-numpy-interrogate leaves by design, all of it legal
    OUTSIDE traces (checkpoint save/restore boundaries only)."""
    layout_py = os.path.join(
        REPO, "howtotrainyourmamlpytorch_tpu", "ops", "layout.py"
    )
    assert os.path.isfile(layout_py)
    proc = run_cli(layout_py)
    assert proc.returncode == 0, (
        "graftlint found violations in the layout module:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert "graftlint: clean" in proc.stderr

    from tools.graftlint import lint_paths

    assert lint_paths([layout_py]) == []
    with open(layout_py) as f:
        assert "graftlint: disable" not in f.read()


def test_cli_exits_nonzero_and_annotates_on_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "\n"
        "def sample(key):\n"
        "    a = jax.random.normal(key, (3,))\n"
        "    b = jax.random.uniform(key, (3,))\n"
        "    return a + b\n"
    )
    proc = run_cli(str(bad))
    assert proc.returncode == 1
    assert "prng-reuse" in proc.stdout

    proc_gh = run_cli(str(bad), "--format=github")
    assert proc_gh.returncode == 1
    line = proc_gh.stdout.strip().splitlines()[0]
    assert line.startswith("::error file=")
    assert "title=graftlint prng-reuse" in line


def test_cli_list_rules_names_the_full_set():
    proc = run_cli("--list-rules")
    assert proc.returncode == 0
    listed = {
        line.split(":", 1)[0] for line in proc.stdout.splitlines() if ":" in line
    }
    assert {
        "prng-reuse",
        "host-numpy-in-trace",
        "tracer-branch",
        "jit-static-config",
        "missing-donate",
        "dead-flag",
        "device-op-in-data-path",
        "traced-mutation",
    } <= listed
    assert len(listed) >= 8


def test_cli_select_filters_rules(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "\n"
        "def sample(key):\n"
        "    a = jax.random.normal(key, (3,))\n"
        "    b = jax.random.uniform(key, (3,))\n"
        "    return a + b\n"
    )
    proc = run_cli(str(bad), "--select", "missing-donate")
    assert proc.returncode == 0  # the only finding is prng-reuse, filtered out
    proc_unknown = run_cli(str(bad), "--select", "bogus-rule")
    assert proc_unknown.returncode == 2


def test_parallel_package_lints_clean_standalone():
    """The multi-chip sharding layer (ISSUE 8) stays lint-clean as its own
    target with ZERO suppressions: the declarative rule tables + shard/
    gather helpers in ``parallel/`` host-numpy-interrogate leaves and issue
    ``jax.device_put`` by design — all of it legal OUTSIDE traces and
    OUTSIDE the data path, none of it excused by an inline suppression.
    Also asserts the linter actually DISCOVERED the sharding modules (an
    empty scan would vacuously pass)."""
    parallel_dir = os.path.join(
        REPO, "howtotrainyourmamlpytorch_tpu", "parallel"
    )
    assert os.path.isdir(parallel_dir)
    proc = run_cli(parallel_dir)
    assert proc.returncode == 0, (
        "graftlint found violations in the sharding layer:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert "graftlint: clean" in proc.stderr

    from tools.graftlint import lint_paths
    from tools.graftlint.engine import _collect_files

    scanned = _collect_files([parallel_dir])
    names = {os.path.basename(p) for p in scanned}
    assert {"mesh.py", "sharding.py", "distributed.py"} <= names
    assert lint_paths([parallel_dir]) == []
    # Zero suppressions: the layer must be clean on its own merits.
    for path in scanned:
        with open(path) as f:
            assert "graftlint: disable" not in f.read(), path


def test_resilience_layer_lints_clean_standalone():
    """The training-side resilience layer (ISSUE 10) stays lint-clean as
    its own target with ZERO suppressions — and in particular passes the
    ``thread-lifecycle`` rule it motivated: the watchdog monitor, the
    async checkpoint writer and the prefetch stager all spawn threads AND
    register a join path reachable from their owner's shutdown. Also
    asserts the linter actually DISCOVERED the modules (an empty scan
    would vacuously pass)."""
    targets = [
        os.path.join(REPO, "howtotrainyourmamlpytorch_tpu", "utils",
                     "watchdog.py"),
        os.path.join(REPO, "howtotrainyourmamlpytorch_tpu", "utils",
                     "checkpoint.py"),
        os.path.join(REPO, "howtotrainyourmamlpytorch_tpu", "data",
                     "device_prefetch.py"),
        os.path.join(REPO, "tools", "chaos_train.py"),
        os.path.join(REPO, "train_maml_system_dispatch.py"),
    ]
    for target in targets:
        assert os.path.exists(target), target
    proc = run_cli(*targets)
    assert proc.returncode == 0, (
        "graftlint found violations in the resilience layer:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert "graftlint: clean" in proc.stderr

    from tools.graftlint import lint_paths

    assert lint_paths(targets) == []
    for path in targets:
        with open(path) as f:
            assert "graftlint: disable" not in f.read(), path


def test_thread_lifecycle_rule_is_registered_and_fires():
    """The seeded-violation proof that the tree-wide gate actually guards
    thread lifecycles: a retained un-joined Thread in a scratch file is a
    ``thread-lifecycle`` violation through the REAL CLI."""
    import tempfile
    import textwrap

    from tools.graftlint import RULES

    assert "thread-lifecycle" in RULES  # id -> rule registry
    with tempfile.TemporaryDirectory() as tmp:
        bad = os.path.join(tmp, "leaky.py")
        with open(bad, "w") as f:
            f.write(textwrap.dedent(
                """
                import threading

                class Leaky:
                    def __init__(self):
                        self._t = threading.Thread(target=print)
                        self._t.start()
                """
            ))
        proc = run_cli(bad)
        assert proc.returncode == 1
        assert "thread-lifecycle" in proc.stdout


def test_multihost_layer_lints_clean_standalone():
    """The pod-scale multi-host layer (ISSUE 11) stays lint-clean as its
    own target with ZERO suppressions — and in particular the four entry
    points plus the dispatcher/bench/chaos tools pass the
    ``device-probe-before-distributed-init`` ordering rule they
    motivated. Entry files live at the repo root (outside the default
    package targets), so this pin is what keeps them scanned forever."""
    targets = [
        os.path.join(REPO, "howtotrainyourmamlpytorch_tpu", "parallel"),
        os.path.join(REPO, "train_maml_system.py"),
        os.path.join(REPO, "train_gradient_descent_system.py"),
        os.path.join(REPO, "train_matching_nets_system.py"),
        os.path.join(REPO, "train_maml_system_dispatch.py"),
        os.path.join(REPO, "tools", "serve_maml.py"),
        os.path.join(REPO, "tools", "chaos_train.py"),
        os.path.join(REPO, "bench.py"),
    ]
    for target in targets:
        assert os.path.exists(target), target
    proc = run_cli(*targets)
    assert proc.returncode == 0, (
        "graftlint found violations in the multi-host layer:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    assert "graftlint: clean" in proc.stderr

    from tools.graftlint import lint_paths
    from tools.graftlint.engine import _collect_files

    scanned = {os.path.basename(p) for p in _collect_files(targets)}
    assert {
        "distributed.py", "mesh.py", "multihost.py",
        "train_maml_system.py", "train_maml_system_dispatch.py",
    } <= scanned
    assert lint_paths(targets) == []
    for path in _collect_files(targets):
        with open(path) as f:
            assert "graftlint: disable" not in f.read(), path


def test_device_probe_rule_is_registered_and_fires():
    """Seeded-violation proof through the real CLI: a device probe before
    ``initialize_distributed`` in a scratch entry file is a
    ``device-probe-before-distributed-init`` violation."""
    import tempfile
    import textwrap

    from tools.graftlint import RULES

    assert "device-probe-before-distributed-init" in RULES
    with tempfile.TemporaryDirectory() as tmp:
        bad = os.path.join(tmp, "bad_entry.py")
        with open(bad, "w") as f:
            f.write(textwrap.dedent(
                """
                import jax
                from howtotrainyourmamlpytorch_tpu.parallel import (
                    initialize_distributed,
                )

                print(jax.devices())
                initialize_distributed()
                """
            ))
        proc = run_cli(bad)
        assert proc.returncode == 1
        assert "device-probe-before-distributed-init" in proc.stdout
