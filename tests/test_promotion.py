"""Continuous train→serve control loop (ISSUE 13): promotion daemon
idempotency, torn-publish visibility, SLO auto-rollback, and the
hard-episode feedback edge.

Everything here is deterministic and in-process: the daemon is driven
against a stub fleet (promote/healthz/metrics_text) so journal replay,
dedupe, val-gating, retry and rollback are provable without subprocess
nondeterminism; daemon SIGKILLs are simulated by aborting the pipeline at
the exact ``faultinject.daemon_phase`` boundaries and rebuilding the
daemon over the same journal — the artifact state a real SIGKILL leaves.
The real-process topology (trainer CLI + front door + daemon CLI killed
with SIGKILL) is proven by the chaos harness
(``tests/test_chaos_train.py::test_promote_chaos_*``)."""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.serve import ServeConfig, ServingAPI
from howtotrainyourmamlpytorch_tpu.serve.engine import confidence_stats
from howtotrainyourmamlpytorch_tpu.serve.pool import PoolConfig, ReplicaPool
from howtotrainyourmamlpytorch_tpu.serve.resilience import LocalReplica
from howtotrainyourmamlpytorch_tpu.serve.resilience import (
    promotion as promo,
)
from howtotrainyourmamlpytorch_tpu.serve.resilience.promotion import (
    PromotionConfig,
    PromotionDaemon,
    PromotionJournal,
    replay_journal,
)
from howtotrainyourmamlpytorch_tpu.telemetry import EventLog
from howtotrainyourmamlpytorch_tpu.telemetry import events as telemetry_events
from howtotrainyourmamlpytorch_tpu.telemetry.events import read_events
from howtotrainyourmamlpytorch_tpu.utils import faultinject
from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
    AsyncCheckpointWriter,
    checkpoint_digest,
    publish_alias,
    publish_done_marker,
    read_done_marker,
    save_checkpoint,
    snapshot_for_save,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.deactivate()
    yield
    faultinject.deactivate()


# ---------------------------------------------------------------------------
# Fixture checkpoints + stub fleet
# ---------------------------------------------------------------------------


def state_tree(seed: int) -> dict:
    rng = np.random.RandomState(seed)
    return {
        "w": rng.rand(4, 3).astype(np.float32),
        "b": rng.rand(3).astype(np.float32),
    }


def write_candidate(
    watch_dir, epoch, seed=None, val_acc=0.5, with_stats=True, marker=True
):
    """One published epoch checkpoint (+ optional done-marker)."""
    os.makedirs(watch_dir, exist_ok=True)
    exp_state = {"current_iter": epoch * 2}
    if with_stats:
        exp_state["per_epoch_statistics"] = {
            "val_accuracy_mean": [val_acc - 0.01, val_acc][: epoch + 1]
            or [val_acc]
        }
        exp_state["best_val_acc"] = val_acc
    path = os.path.join(watch_dir, f"train_model_{epoch}")
    save_checkpoint(path, state_tree(seed if seed is not None else epoch),
                    exp_state)
    if marker:
        publish_done_marker(path)
    return path


class StubTarget:
    """A fleet front door reduced to what the daemon consumes."""

    def __init__(self):
        self.promoted: list[str] = []
        self.digest: str | None = None
        self.fail_promotes = 0
        self.nonfinite_after_promotes: set[int] = set()
        self._nonfinite_delay: int | None = None
        self.counters = {"requests": 100.0, "errors": 0.0, "nonfinite": 0.0,
                         "p99": 5.0}

    def promote(self, path):
        if self.fail_promotes > 0:
            self.fail_promotes -= 1
            raise ConnectionError("fleet transiently unreachable")
        self.promoted.append(path)
        self.digest = checkpoint_digest(path)
        if len(self.promoted) in self.nonfinite_after_promotes:
            # Live regression shape: the counter moves on traffic AFTER
            # the publish (and after the daemon's baseline scrape).
            self._nonfinite_delay = 1
        return {"state_version": len(self.promoted)}

    def healthz(self):
        return {"ready": True, "last_promoted_digest": self.digest}

    def metrics_text(self):
        c = self.counters
        if self._nonfinite_delay is not None:
            if self._nonfinite_delay <= 0:
                c["nonfinite"] += 3
                self._nonfinite_delay = None
            else:
                self._nonfinite_delay -= 1
        c["requests"] += 1  # live traffic keeps flowing
        return (
            f"maml_serve_pool_requests_total {c['requests']}\n"
            f"maml_serve_pool_request_errors_total {c['errors']}\n"
            f"maml_serve_pool_nonfinite_logits_total {c['nonfinite']}\n"
            'maml_serve_pool_request_latency_ms{quantile="0.99"} '
            f"{c['p99']}\n"
        )


def make_daemon(tmp_path, target, **overrides) -> PromotionDaemon:
    defaults = dict(
        watch_dir=str(tmp_path / "saved_models"),
        journal_path=str(tmp_path / "logs" / "promotions.jsonl"),
        staging_dir=str(tmp_path / "promotion_staging"),
        poll_interval_s=0.05,
        slo_watch_s=0.15,
        slo_poll_s=0.03,
        promote_retries=3,
        promote_backoff_s=0.01,
    )
    defaults.update(overrides)
    return PromotionDaemon(target, PromotionConfig(**defaults))


def phases_for(journal_path, digest):
    return [
        row["phase"]
        for row in PromotionJournal.load(journal_path)
        if row.get("digest") == digest
    ]


# ---------------------------------------------------------------------------
# Torn-publish visibility (satellite: done-marker protocol)
# ---------------------------------------------------------------------------


def test_done_marker_digest_matches_file(tmp_path):
    path = write_candidate(tmp_path / "saved_models", epoch=0)
    marker = read_done_marker(path)
    assert marker is not None
    assert marker["digest"] == checkpoint_digest(path)
    assert marker["bytes"] == os.path.getsize(path)


def test_watcher_blind_until_marker_lands(tmp_path):
    """An epoch archive without its ``.ready`` marker is invisible to the
    candidate scan — the torn-publish window can never hand the daemon a
    half-published checkpoint."""
    watch = tmp_path / "saved_models"
    write_candidate(watch, epoch=0, marker=False)
    daemon = make_daemon(tmp_path, StubTarget())
    assert daemon.scan_candidates() == []
    publish_done_marker(os.path.join(str(watch), "train_model_0"))
    assert [c.epoch for c in daemon.scan_candidates()] == [0]


def test_marker_write_retries_transient_enospc(tmp_path):
    """fail-next-K-writes regression (satellite): a transient write
    failure during the marker publish is retried — the marker lands whole
    and its digest still matches the archive."""
    watch = tmp_path / "saved_models"
    path = write_candidate(watch, epoch=0, marker=False)
    faultinject.activate(faultinject.FaultPlan(fail_next_writes=2))
    publish_done_marker(path)
    assert any(e.startswith("write-fail:") for e in faultinject.events)
    marker = read_done_marker(path)
    assert marker is not None and marker["digest"] == checkpoint_digest(path)


def test_marker_failure_past_budget_leaves_no_candidate(tmp_path):
    """When every marker write attempt fails (budget exhausted), the
    publish raises AND the watcher still sees nothing — fail closed."""
    watch = tmp_path / "saved_models"
    path = write_candidate(watch, epoch=0, marker=False)
    faultinject.activate(faultinject.FaultPlan(fail_next_writes=10))
    with pytest.raises(OSError):
        publish_done_marker(path)
    faultinject.deactivate()
    daemon = make_daemon(tmp_path, StubTarget())
    assert daemon.scan_candidates() == []


def test_async_writer_publishes_marker_last(tmp_path):
    """The async checkpoint writer's job order is archive → alias →
    marker: when the marker exists the archive and alias are complete."""
    tree = state_tree(0)
    exp = {"per_epoch_statistics": {"val_accuracy_mean": [0.5]}}
    epoch_path = str(tmp_path / "train_model_0")
    latest = str(tmp_path / "train_model_latest")
    writer = AsyncCheckpointWriter()
    try:
        writer.submit(
            epoch_path, snapshot_for_save(tree, exp), alias_dst=latest,
            publish_marker=True,
        )
        writer.drain()
    finally:
        writer.close()
    marker = read_done_marker(epoch_path)
    assert marker is not None
    assert os.path.exists(latest)
    assert marker["digest"] == checkpoint_digest(epoch_path)


def test_kill_trainer_mid_publish_window_is_marker_shaped():
    """The ``kill_trainer_mid_publish`` fault fires inside
    ``publish_done_marker`` BEFORE the marker write — the archive is on
    disk, the marker is not (hook-level pin; the SIGKILL itself is proven
    by the chaos run)."""
    plan = faultinject.activate(
        faultinject.FaultPlan(kill_trainer_mid_publish=1)
    )
    fired = {}

    def fake_kill(pid, sig):
        fired["sig"] = sig

    real_kill = os.kill
    os.kill = fake_kill
    try:
        faultinject.trainer_publish_marker("/tmp/x")
    finally:
        os.kill = real_kill
    assert fired and plan.kill_trainer_mid_publish == 0
    assert faultinject.events == ["kill-mid-publish:x"]


# ---------------------------------------------------------------------------
# Daemon pipeline: promote, dedupe, gates
# ---------------------------------------------------------------------------


def test_daemon_promotes_candidates_in_epoch_order(tmp_path):
    watch = tmp_path / "saved_models"
    write_candidate(watch, epoch=1, val_acc=0.6)
    write_candidate(watch, epoch=0, val_acc=0.5)
    target = StubTarget()
    daemon = make_daemon(tmp_path, target)
    daemon.run_once()
    assert len(target.promoted) == 2
    # Epoch order: the staged copy of epoch 0 was driven first.
    assert "train_model_0" in target.promoted[0]
    assert "train_model_1" in target.promoted[1]
    journal = PromotionJournal.load(daemon.config.journal_path)
    by_phase = [r["phase"] for r in journal]
    assert by_phase.count("promoted") == 2
    assert by_phase.count("slo_ok") == 2
    # LKG is the newest clean publish; staged copies are retained there.
    assert daemon._lkg is not None
    assert os.path.exists(daemon._lkg["staged"])
    assert daemon.resolved_promotions == 2


def test_duplicate_digest_dedupes_without_repromote(tmp_path):
    watch = tmp_path / "saved_models"
    path0 = write_candidate(watch, epoch=0, val_acc=0.5)
    target = StubTarget()
    daemon = make_daemon(tmp_path, target)
    daemon.run_once()
    assert len(target.promoted) == 1
    # The same bytes resurface as a new epoch file (publish replay):
    # deduped by content digest, journaled once, never re-promoted.
    dup = os.path.join(str(watch), "train_model_7")
    publish_alias(path0, dup)
    publish_done_marker(dup)
    daemon.run_once()
    daemon.run_once()
    assert len(target.promoted) == 1
    rows = PromotionJournal.load(daemon.config.journal_path)
    dedupes = [r for r in rows if r["phase"] == "deduped"]
    assert len(dedupes) == 1 and "train_model_7" in dedupes[0]["path"]


def test_val_gate_rejects_statless_and_regressing_candidates(tmp_path):
    watch = tmp_path / "saved_models"
    write_candidate(watch, epoch=0, with_stats=False)  # no val stat yet
    write_candidate(watch, epoch=1, val_acc=0.7)
    write_candidate(watch, epoch=2, val_acc=0.4)  # worse than LKG
    target = StubTarget()
    daemon = make_daemon(tmp_path, target, val_min_delta=0.0)
    daemon.run_once()
    assert len(target.promoted) == 1  # only epoch 1
    rows = PromotionJournal.load(daemon.config.journal_path)
    rejected = {
        r["digest"]: r for r in rows if r["phase"] == "rejected"
    }
    reasons = sorted(r["reason"] for r in rejected.values())
    assert reasons == ["val_gate", "val_gate"]
    assert daemon.resolved_promotions == 1


def test_corrupt_candidate_rejected_pre_publish_trainer_file_intact(
    tmp_path,
):
    """``corrupt_candidate_at`` truncates the daemon's STAGED copy: the
    candidate is rejected before any replica is touched, journaled and
    emitted as a typed telemetry event, and the trainer's own epoch file
    is untouched."""
    watch = tmp_path / "saved_models"
    path = write_candidate(watch, epoch=0, val_acc=0.5)
    original_digest = checkpoint_digest(path)
    sink = EventLog(str(tmp_path / "telemetry.jsonl"))
    previous = telemetry_events.install(sink)
    faultinject.activate(faultinject.FaultPlan(corrupt_candidate_at=64))
    try:
        target = StubTarget()
        daemon = make_daemon(tmp_path, target)
        daemon.run_once()
    finally:
        telemetry_events.install(previous)
    assert target.promoted == []
    assert any(
        e.startswith("corrupt-candidate:") for e in faultinject.events
    )
    rows = PromotionJournal.load(daemon.config.journal_path)
    rejected = [r for r in rows if r["phase"] == "rejected"]
    assert len(rejected) == 1
    assert rejected[0]["reason"] in ("digest_mismatch", "corrupt")
    # Trainer's file untouched; only the staged copy was corrupted.
    assert checkpoint_digest(path) == original_digest
    sink.flush()
    events = read_events(str(tmp_path / "telemetry.jsonl"))
    assert any(e["type"] == "promotion_rejected" for e in events)


def test_transient_fleet_failure_retries_then_promotes(tmp_path):
    watch = tmp_path / "saved_models"
    write_candidate(watch, epoch=0)
    target = StubTarget()
    target.fail_promotes = 2  # two transient failures, then healthy
    daemon = make_daemon(tmp_path, target)
    daemon.run_once()
    assert len(target.promoted) == 1
    assert phases_for(daemon.config.journal_path,
                      target.digest)[-1] == "slo_ok"


# ---------------------------------------------------------------------------
# Crash-safe idempotency: journal replay at every kill boundary
# ---------------------------------------------------------------------------


class _Killed(BaseException):
    """In-process stand-in for SIGKILL: aborts the pipeline mid-phase;
    the daemon object is then discarded and a fresh one replays the
    journal — the exact artifact state a real SIGKILL leaves (the real
    signal path is proven by the chaos run's daemon subprocess)."""


def _kill_at_phase(monkeypatch, phase):
    def hook(p):
        if p == phase:
            raise _Killed(f"phase {p}")

    monkeypatch.setattr(promo.faultinject, "daemon_phase", hook)


@pytest.mark.parametrize(
    "kill_phase,promotes_before,expect_resume_without_promote",
    [
        (promo.KILL_PRE_VERIFY, 0, False),    # journaled, not verified
        (promo.KILL_PRE_PUBLISH, 0, False),   # verified, fleet untouched
        (promo.KILL_POST_PUBLISH, 1, True),   # published, row missing
        (promo.KILL_PRE_RESOLVE, 1, True),    # promoted row, unresolved
    ],
)
def test_journal_replay_after_kill_at_phase_boundary(
    tmp_path, monkeypatch, kill_phase, promotes_before,
    expect_resume_without_promote,
):
    """SIGKILL at each phase boundary, restart, resume idempotently:
    exactly ONE fleet publish total — never a double-promote, never a
    skipped candidate."""
    watch = tmp_path / "saved_models"
    write_candidate(watch, epoch=0)
    target = StubTarget()
    daemon = make_daemon(tmp_path, target)
    _kill_at_phase(monkeypatch, kill_phase)
    with pytest.raises(_Killed):
        daemon.run_once()
    assert len(target.promoted) == promotes_before
    monkeypatch.setattr(promo.faultinject, "daemon_phase", lambda p: None)

    # Restart: a fresh daemon over the same journal + the same fleet.
    daemon2 = make_daemon(tmp_path, target)
    daemon2.run_once()
    assert len(target.promoted) == 1, "exactly one publish, ever"
    digest = checkpoint_digest(target.promoted[0])
    phases = phases_for(daemon2.config.journal_path, digest)
    assert phases[-1] == "slo_ok"
    assert phases.count("promoted") >= 1
    if expect_resume_without_promote:
        promoted_rows = [
            r for r in PromotionJournal.load(daemon2.config.journal_path)
            if r["phase"] == "promoted"
        ]
        # The restart recorded the already-landed publish as resumed
        # instead of double-driving it.
        assert any(r.get("resumed") for r in promoted_rows) or (
            kill_phase == promo.KILL_PRE_RESOLVE
        )
    # Idempotent forever after: more passes change nothing.
    daemon2.run_once()
    assert len(target.promoted) == 1
    assert daemon2.resolved_promotions == 1


def test_replay_ignores_resumed_rows_for_phase(tmp_path):
    """A ``resumed`` audit row must not become a digest's last phase: a
    second crash right after a resume would otherwise replay the
    candidate from scratch and double-drive a landed publish."""
    rows = [
        {"t": 1.0, "phase": "start", "digest": "d1", "path": "p",
         "staged": "s", "epoch": 0},
        {"t": 2.0, "phase": "verified", "digest": "d1", "val_stat": 0.5},
        {"t": 3.0, "phase": "resumed", "digest": "d1",
         "from_phase": "verified"},
    ]
    state = replay_journal(rows)
    assert state["inflight"]["last_phase"] == "verified"


def test_double_crash_after_resume_still_single_promote(tmp_path, monkeypatch):
    """Kill post-publish, resume, kill again mid-resume (after the
    ``resumed`` row), restart: still exactly ONE fleet publish."""
    watch = tmp_path / "saved_models"
    write_candidate(watch, epoch=0)
    target = StubTarget()
    daemon = make_daemon(tmp_path, target)
    _kill_at_phase(monkeypatch, promo.KILL_POST_PUBLISH)
    with pytest.raises(_Killed):
        daemon.run_once()
    assert len(target.promoted) == 1
    # Second incarnation dies right after journaling its ``resumed`` row
    # (before any further phase row lands).
    daemon2 = make_daemon(tmp_path, target)
    real_append = daemon2.journal.append

    def append_then_die(phase, **fields):
        row = real_append(phase, **fields)
        if phase == promo.PHASE_RESUMED:
            raise _Killed("mid-resume")
        return row

    monkeypatch.setattr(promo.faultinject, "daemon_phase", lambda p: None)
    monkeypatch.setattr(daemon2.journal, "append", append_then_die)
    with pytest.raises(_Killed):
        daemon2.run_once()
    # Third incarnation must resume from ``verified`` (fleet digest
    # matches) — never reprocess from scratch.
    daemon3 = make_daemon(tmp_path, target)
    daemon3.run_once()
    assert len(target.promoted) == 1, "double-promote after double crash"
    digest = checkpoint_digest(target.promoted[0])
    assert phases_for(daemon3.config.journal_path, digest)[-1] == "slo_ok"


def test_unscrapeable_slo_window_leaves_candidate_unresolved(tmp_path):
    """If /metrics is unscrapeable for the whole post-publish window, the
    daemon must NOT bless the candidate ``slo_ok`` blind — it stays
    journaled ``promoted`` and a later pass (metrics back) resolves it."""
    watch = tmp_path / "saved_models"
    write_candidate(watch, epoch=0)
    target = StubTarget()
    real_metrics = target.metrics_text
    target.metrics_text = lambda: (_ for _ in ()).throw(
        ConnectionError("front door saturated")
    )
    daemon = make_daemon(tmp_path, target)
    daemon.run_once()
    assert len(target.promoted) == 1
    digest = checkpoint_digest(target.promoted[0])
    assert phases_for(daemon.config.journal_path, digest)[-1] == "promoted"
    assert daemon.resolved_promotions == 0
    # Metrics recover: the next pass re-judges a full window and resolves.
    target.metrics_text = real_metrics
    daemon.run_once()
    assert phases_for(daemon.config.journal_path, digest)[-1] == "slo_ok"
    assert len(target.promoted) == 1


def test_resume_waits_when_fleet_unreachable(tmp_path, monkeypatch):
    """Resume at the ``verified`` boundary with the fleet UNREACHABLE
    must not decide: deciding blind risks double-driving a publish that
    already landed. The candidate stays in-flight until /healthz answers."""
    watch = tmp_path / "saved_models"
    write_candidate(watch, epoch=0)
    target = StubTarget()
    daemon = make_daemon(tmp_path, target)
    _kill_at_phase(monkeypatch, promo.KILL_POST_PUBLISH)
    with pytest.raises(_Killed):
        daemon.run_once()
    assert len(target.promoted) == 1
    monkeypatch.setattr(promo.faultinject, "daemon_phase", lambda p: None)

    daemon2 = make_daemon(tmp_path, target)
    real_healthz = target.healthz
    target.healthz = lambda: (_ for _ in ()).throw(ConnectionError("down"))
    daemon2.run_once()
    # Unreachable: neither a second publish nor a promoted row.
    assert len(target.promoted) == 1
    digest = checkpoint_digest(target.promoted[0])
    assert "promoted" not in phases_for(
        daemon2.config.journal_path, digest
    )[2:]  # only the pre-crash publish... no resumed promoted row yet
    # Fleet back: the same daemon resolves without double-driving.
    target.healthz = real_healthz
    daemon2.run_once()
    assert len(target.promoted) == 1
    assert phases_for(daemon2.config.journal_path, digest)[-1] == "slo_ok"


def test_regression_without_lkg_is_loud_not_phantom(tmp_path):
    """A first-ever promotion that regresses has nothing to roll back to:
    the journal row records ``no_lkg`` and a distinct
    ``slo_rollback_unavailable`` event fires — never a phantom
    "rolled back" claim."""
    watch = tmp_path / "saved_models"
    write_candidate(watch, epoch=0)
    target = StubTarget()
    target.nonfinite_after_promotes = {1}  # the very first publish regresses
    sink = EventLog(str(tmp_path / "telemetry.jsonl"))
    previous = telemetry_events.install(sink)
    try:
        daemon = make_daemon(tmp_path, target)
        daemon.run_once()
    finally:
        telemetry_events.install(previous)
    assert len(target.promoted) == 1  # no rollback promote was driven
    rows = PromotionJournal.load(daemon.config.journal_path)
    rolled = [r for r in rows if r["phase"] == "rolled_back"]
    assert rolled and rolled[0]["no_lkg"] is True and rolled[0]["to"] is None
    sink.flush()
    kinds = {e["type"] for e in read_events(str(tmp_path / "telemetry.jsonl"))}
    assert "slo_rollback_unavailable" in kinds
    assert "slo_rollback" not in kinds


def test_replay_tolerates_torn_final_line(tmp_path):
    journal = tmp_path / "promotions.jsonl"
    journal.write_text(
        json.dumps({"t": 1.0, "phase": "start", "digest": "d1",
                    "path": "p", "staged": "s", "epoch": 0}) + "\n"
        + '{"t": 2.0, "phase": "promo'  # torn mid-append by SIGKILL
    )
    state = replay_journal(PromotionJournal.load(str(journal)))
    assert state["inflight"]["digest"] == "d1"
    assert state["inflight"]["last_phase"] == "start"


# ---------------------------------------------------------------------------
# Staging-dir GC: bounded retention, journaled retired rows, kill-safe
# ---------------------------------------------------------------------------


def staged_names(daemon):
    return sorted(os.listdir(daemon.config.staging_dir))


def test_staging_gc_bounds_dir_to_lkg_plus_retained(tmp_path):
    """Five promoted epochs with ``retain_staged=1``: the staging dir ends
    at lkg + 1 newest other copy, every pruned copy left a journaled
    ``retired`` row naming its digest, and replay stays idempotent."""
    watch = tmp_path / "saved_models"
    for epoch in range(5):
        write_candidate(watch, epoch=epoch, val_acc=0.5 + 0.05 * epoch)
    target = StubTarget()
    daemon = make_daemon(tmp_path, target, retain_staged=1)
    daemon.run_once()
    assert len(target.promoted) == 5
    names = staged_names(daemon)
    assert os.path.basename(daemon._lkg["staged"]) in names
    assert len(names) <= 2, names
    retired = [
        r for r in PromotionJournal.load(daemon.config.journal_path)
        if r["phase"] == promo.PHASE_RETIRED
    ]
    assert len(retired) >= 3
    assert all(r.get("staged") and r.get("digest") for r in retired), retired
    # Retired rows are audit-only on replay: a fresh daemon resumes with
    # nothing in flight and re-promotes nothing.
    daemon2 = make_daemon(tmp_path, target, retain_staged=1)
    daemon2.run_once()
    assert len(target.promoted) == 5


def test_staging_gc_survives_mid_prune_sigkill(tmp_path, monkeypatch):
    """SIGKILL between the ``retired`` row and the unlink: the orphaned
    copy is still on disk at restart, the next pass re-retires it
    (journal-then-act is idempotent), and no candidate is ever skipped
    or double-promoted."""
    watch = tmp_path / "saved_models"
    for epoch in range(4):
        write_candidate(watch, epoch=epoch, val_acc=0.5 + 0.05 * epoch)
    target = StubTarget()
    daemon = make_daemon(tmp_path, target, retain_staged=0)
    _kill_at_phase(monkeypatch, promo.KILL_MID_GC)
    with pytest.raises(_Killed):
        daemon.run_once()
    retired = [
        r for r in PromotionJournal.load(daemon.config.journal_path)
        if r["phase"] == promo.PHASE_RETIRED
    ]
    assert len(retired) == 1
    orphan = retired[0]["staged"]
    assert orphan in staged_names(daemon), (
        "journal-then-act: the row must land BEFORE the unlink"
    )
    monkeypatch.setattr(promo.faultinject, "daemon_phase", lambda p: None)
    daemon2 = make_daemon(tmp_path, target, retain_staged=0)
    daemon2.run_once()
    assert len(target.promoted) == 4, "a mid-GC kill may not skip candidates"
    names = staged_names(daemon2)
    assert names == [os.path.basename(daemon2._lkg["staged"])], names
    rows = PromotionJournal.load(daemon2.config.journal_path)
    assert [
        r["staged"] for r in rows if r["phase"] == promo.PHASE_RETIRED
    ].count(orphan) >= 2, "the orphan must be re-retired on the next pass"
    digest = checkpoint_digest(target.promoted[-1])
    assert phases_for(daemon2.config.journal_path, digest)[-1] == "slo_ok"


def test_replay_retired_rows_are_audit_only():
    """A ``retired`` row must neither resurrect a resolved digest as
    in-flight nor corrupt its recorded staged path (the row's ``staged``
    is a basename)."""
    rows = [
        {"t": 1.0, "phase": "start", "digest": "d1", "path": "p",
         "staged": "/stage/s1", "epoch": 0},
        {"t": 2.0, "phase": "verified", "digest": "d1", "val_stat": 0.5},
        {"t": 3.0, "phase": "promoted", "digest": "d1", "state_version": 1},
        {"t": 4.0, "phase": "slo_ok", "digest": "d1"},
        {"t": 5.0, "phase": "retired", "digest": "d1", "staged": "s1"},
        {"t": 6.0, "phase": "retired", "digest": None, "staged": "zz"},
    ]
    state = replay_journal(rows)
    assert state["inflight"] is None
    assert "d1" in state["terminal"]
    assert state["info"]["d1"]["staged"] == "/stage/s1"
    assert state["lkg"]["digest"] == "d1"


# ---------------------------------------------------------------------------
# Post-promotion SLO watch + automatic rollback
# ---------------------------------------------------------------------------


def test_slo_regression_rolls_back_to_retained_lkg(tmp_path):
    """A promotion whose state regresses live traffic (nonfinite counter
    moves inside the watch window) is rolled back automatically to the
    RETAINED last-known-good staged copy — even though the trainer's own
    copy of that epoch could already be pruned."""
    watch = tmp_path / "saved_models"
    good = write_candidate(watch, epoch=0, val_acc=0.5)
    target = StubTarget()
    daemon = make_daemon(tmp_path, target)
    daemon.run_once()
    assert len(target.promoted) == 1
    lkg_staged = daemon._lkg["staged"]
    good_digest = checkpoint_digest(good)

    # The trainer prunes the source epoch; the daemon's retention copy
    # is what rollback will drive.
    os.remove(good)
    os.remove(good + ".ready")

    write_candidate(watch, epoch=1, val_acc=0.9, seed=11)
    target.nonfinite_after_promotes = {2}  # regress right after publish
    sink = EventLog(str(tmp_path / "telemetry.jsonl"))
    previous = telemetry_events.install(sink)
    try:
        daemon.run_once()
    finally:
        telemetry_events.install(previous)
    # Publish #2 was the bad candidate, publish #3 the rollback.
    assert len(target.promoted) == 3
    assert target.promoted[2] == lkg_staged
    assert target.digest == good_digest
    rows = PromotionJournal.load(daemon.config.journal_path)
    bad_digest = [
        r["digest"] for r in rows if r["phase"] == "rollback_start"
    ][0]
    assert phases_for(daemon.config.journal_path, bad_digest)[-1] == (
        "rolled_back"
    )
    rolled = [r for r in rows if r["phase"] == "rolled_back"][0]
    assert rolled["to"] == good_digest
    # LKG unchanged: the regressing digest never becomes a rollback
    # target, and the typed telemetry trail names the reason.
    assert daemon._lkg["digest"] == good_digest
    sink.flush()
    events = read_events(str(tmp_path / "telemetry.jsonl"))
    kinds = {e["type"] for e in events}
    assert {"slo_regression", "slo_rollback"} <= kinds


def test_regress_after_promote_fault_arms_nan_logits():
    plan = faultinject.activate(
        faultinject.FaultPlan(regress_after_promote=4)
    )
    faultinject.promotion_applied()
    assert plan.nan_next_logits == 4
    assert plan.regress_after_promote == 0
    faultinject.promotion_applied()  # one-shot
    assert plan.nan_next_logits == 4


def test_slo_watch_thresholds():
    cfg = PromotionConfig(
        watch_dir=".", journal_path="j", staging_dir=".",
        max_error_rate=0.1, max_new_nonfinite=0, min_requests=10,
        p99_budget_ms=100.0,
    )
    target = StubTarget()
    watch = promo.SloWatch(target, cfg)
    base = watch.sample_now()
    assert watch.verdict(base) is None
    target.counters["nonfinite"] += 1
    watch.sample_now()
    assert "nonfinite" in watch.verdict(base)
    # Error-rate needs min_requests answered first.
    target.counters["nonfinite"] -= 1
    target.counters["errors"] += 3
    watch.sample_now()
    assert watch.verdict(base) is None  # only a handful of requests yet
    target.counters["requests"] += 20
    watch.sample_now()
    assert "error rate" in watch.verdict(base)


# ---------------------------------------------------------------------------
# Serving confidence telemetry + nonfinite counters (satellite)
# ---------------------------------------------------------------------------


def tiny_api(**kw):
    cfg = MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2, num_filters=4, image_height=8, image_width=8,
            num_classes=5, per_step_bn_statistics=True, num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
    )
    learner = MAMLFewShotLearner(cfg)
    defaults = dict(meta_batch_size=2, max_wait_ms=0.0)
    defaults.update(kw)
    return ServingAPI(
        learner, learner.init_state(jax.random.key(0)),
        ServeConfig(**defaults),
    )


def episode(rng, way=5, shot=1, query=3):
    img = (1, 8, 8)
    xs = rng.rand(way * shot, *img).astype(np.float32)
    ys = np.repeat(np.arange(way), shot).astype(np.int32)
    xq = rng.rand(query, *img).astype(np.float32)
    return xs, ys, xq


def test_confidence_stats_shape_and_degradation():
    logits = np.array([[10.0, 0.0, 0.0], [0.0, 5.0, 4.9]])
    margin, entropy = confidence_stats(logits)
    assert 0.0 < margin < 1.0 and entropy > 0.0
    sure = confidence_stats(np.array([[100.0, 0.0, 0.0]]))
    unsure = confidence_stats(np.array([[0.1, 0.0, 0.0]]))
    assert sure[0] > unsure[0] and sure[1] < unsure[1]
    nan_margin, _ = confidence_stats(np.full((2, 3), np.nan))
    assert not np.isfinite(nan_margin)


def test_serve_dispatch_stamps_margin_entropy_tags(rng, tmp_path):
    api = tiny_api()
    sink = EventLog(str(tmp_path / "telemetry.jsonl"))
    previous = telemetry_events.install(sink)
    try:
        api.classify(*episode(rng), tag="seed:41")
        api.classify(*episode(rng))
    finally:
        telemetry_events.install(previous)
        api.close()
    sink.flush()
    dispatches = [
        e for e in read_events(str(tmp_path / "telemetry.jsonl"))
        if e["type"] == "serve_dispatch"
    ]
    assert dispatches
    tags = [t for e in dispatches for t in e["tags"]]
    assert "seed:41" in tags
    for e in dispatches:
        assert len(e["margins"]) == e["episodes"]
        assert len(e["entropies"]) == e["episodes"]
        assert all(
            m is None or 0.0 <= m <= 1.0 for m in e["margins"]
        )
        assert e["nonfinite"] == 0


def test_confidence_stamping_is_host_side(rng, compile_guard):
    """Margin/entropy stamping adds zero program signatures and zero
    device syncs: pure numpy over the already-fetched host logits."""
    api = tiny_api()
    try:
        api.classify(*episode(rng))  # warm the program pair
        with compile_guard() as guard:
            for i in range(3):
                api.classify(*episode(rng, query=3), tag=f"seed:{i}")
        guard.assert_compiles("serve_adapt_maml", exactly=0)
        guard.assert_compiles("serve_classify_maml", exactly=0)
    finally:
        api.close()


def test_nonfinite_logits_counted_engine_and_pool(rng):
    """NaN logits on live traffic move the nonfinite counters at BOTH
    surfaces the SLO watch can scrape: the engine's own /metrics and the
    pool front door's."""
    def factory(index):
        api = tiny_api()
        api.engine.warmup([(5, 1, 3)])
        return LocalReplica(api, replica_id=f"local-{index}")

    pool = ReplicaPool(
        factory,
        PoolConfig(n_replicas=1, health_interval_s=0.02,
                   restart_backoff_s=0.05, min_uptime_s=0.0),
    )
    try:
        assert pool.wait_ready(timeout=120.0)
        pool.classify(*episode(rng))
        assert pool.metrics.nonfinite_logits_total.value == 0
        faultinject.activate(faultinject.FaultPlan(nan_next_logits=1))
        pool.classify(*episode(rng))
        assert pool.metrics.nonfinite_logits_total.value == 1
        assert "maml_serve_pool_nonfinite_logits_total 1" in (
            pool.metrics_text()
        )
    finally:
        pool.close()


def test_single_api_metrics_expose_nonfinite_and_digest(rng, tmp_path):
    api = tiny_api()
    try:
        faultinject.activate(faultinject.FaultPlan(nan_next_logits=1))
        api.classify(*episode(rng))
        assert api.metrics.nonfinite_logits_total.value >= 1
        assert "maml_serve_nonfinite_logits_total" in api.metrics_text()
        assert api.healthz()["checkpoint_digest"] is None  # boot state
    finally:
        api.close()


# ---------------------------------------------------------------------------
# Hard-episode feedback edge: miner -> replay manifest -> loader mix-in
# ---------------------------------------------------------------------------


def test_miner_selects_low_margin_tagged_episodes(tmp_path):
    from tools.episode_miner import (
        mine_events,
        select_hard_episodes,
        write_manifest,
    )

    events = [
        {"type": "serve_dispatch", "tags": ["seed:5", "seed:6"],
         "margins": [0.05, 0.9], "entropies": [1.5, 0.1]},
        {"type": "serve_dispatch", "tags": ["seed:5", None],
         "margins": [0.2, 0.01], "entropies": [1.0, 2.0]},
        {"type": "serve_dispatch", "tags": ["untagged"],
         "margins": [0.0], "entropies": [2.0]},
        {"type": "serve_dispatch", "tags": ["seed:7"],
         "margins": [None], "entropies": [None]},  # NaN logits episode
        {"type": "step"},
    ]
    stats = mine_events(events)
    assert set(stats) == {5, 6, 7}
    assert stats[5]["count"] == 2 and stats[5]["margin"] == 0.05
    assert stats[7]["margin"] == 0.0  # non-finite = maximally hard
    hard = select_hard_episodes(stats, max_margin=0.5, top=10)
    assert [row["seed"] for row in hard] == [7, 5]  # hardest first

    out = str(tmp_path / "replay_manifest.json")
    write_manifest(out, hard, source="test")
    from howtotrainyourmamlpytorch_tpu.data.loader import (
        load_replay_manifest,
    )

    assert load_replay_manifest(out) == (7, 5)


def test_replay_seed_mixes_deterministically():
    from howtotrainyourmamlpytorch_tpu.data.loader import replay_seed

    seeds = (101, 202)
    stream = [replay_seed(1000, i, seeds, 4) for i in range(12)]
    # Every 4th slot draws a mined seed, cycled; the rest are untouched.
    assert stream[3] == 101 and stream[7] == 202 and stream[11] == 101
    untouched = [s for i, s in enumerate(stream) if (i + 1) % 4]
    assert untouched == [1000 + i for i in range(12) if (i + 1) % 4]
    # Off = identity.
    assert [replay_seed(1000, i, (), 0) for i in range(4)] == [
        1000, 1001, 1002, 1003
    ]


def test_miner_cli_refuses_empty_manifest(tmp_path):
    """Nothing mined -> no manifest written, non-zero exit — a scripted
    mine-then-train pipeline must branch instead of handing the loader an
    empty manifest it refuses."""
    import subprocess
    import sys

    telemetry = tmp_path / "telemetry.jsonl"
    telemetry.write_text(json.dumps({
        "t": 1.0, "type": "serve_dispatch", "tags": ["seed:9"],
        "margins": [0.9], "entropies": [0.1],
    }) + "\n")
    out = tmp_path / "manifest.json"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "episode_miner.py"),
         "--telemetry", str(telemetry), "--out", str(out),
         "--max-margin", "0.1", "--json"],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert proc.returncode == 3, proc.stdout + proc.stderr
    assert not out.exists()
    assert json.loads(proc.stdout)["mined"] == 0


def test_miner_cli_round_trip(tmp_path):
    import subprocess
    import sys

    telemetry = tmp_path / "telemetry.jsonl"
    with open(telemetry, "w") as f:
        f.write(json.dumps({
            "t": 1.0, "type": "serve_dispatch", "tags": ["seed:9"],
            "margins": [0.1], "entropies": [1.0],
        }) + "\n")
    out = tmp_path / "manifest.json"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "episode_miner.py"),
         "--telemetry", str(telemetry), "--out", str(out), "--json"],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout)
    assert summary["mined"] == 1
    manifest = json.loads(out.read_text())
    assert manifest["episodes"][0]["seed"] == 9


# ---------------------------------------------------------------------------
# Daemon threads shut down clean (thread-lifecycle contract, live)
# ---------------------------------------------------------------------------


def test_daemon_threads_start_and_join(tmp_path):
    watch = tmp_path / "saved_models"
    write_candidate(watch, epoch=0)
    target = StubTarget()
    daemon = make_daemon(tmp_path, target)
    daemon.start()
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline and not target.promoted:
        time.sleep(0.02)
    daemon.close()
    assert target.promoted, "watcher thread never drove the promotion"
    assert not daemon._thread.is_alive()
    assert not daemon.slo._thread.is_alive()
    leftovers = [
        t for t in threading.enumerate()
        if t.name in ("promotion-watcher", "promotion-slo-sampler")
        and t.is_alive()
    ]
    assert leftovers == []
