"""Numerical parity vs the reference torch implementation (CPU).

The strongest correctness evidence we can produce: the reference's own code
(/root/reference, imported read-only and run on CPU torch) and this
framework are given IDENTICAL weights and IDENTICAL episode batches, and
must produce the same losses and the same evolved parameters through full
train iterations — second order, MSL, LSLR, per-step BN, Adam + cosine
schedule included (few_shot_learning_system.py:170-369).

Tolerances are loose enough for f32 reduction-order noise and nothing else:
per-iteration loss agreement ~1e-5 over the first iterations, before
chaotic second-order drift dominates.
"""

from __future__ import annotations

import os
import sys

import numpy as np
import pytest

REFERENCE = "/root/reference"

torch = pytest.importorskip("torch")

if not os.path.isdir(REFERENCE):
    pytest.skip("reference checkout not present", allow_module_level=True)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from parity_check import (  # noqa: E402
    build_ours,
    build_reference,
    copy_torch_params_into_state,
    make_episode_batch,
    our_theta,
    torch_theta,
)


def _run_pair(ways: int, iters: int, second_order: bool):
    torch.manual_seed(104)
    ref = build_reference(ways, 3, 8, 1e-3, 10, second_order)
    learner, state = build_ours(ways, 3, 8, 1e-3, 10, second_order)
    state = copy_torch_params_into_state(ref, state)

    b, n, k, t = 2, ways, 1, 1
    rng = np.random.RandomState(7)
    protos = rng.randn(n, 1, 28, 28).astype("f")
    results = []
    for _ in range(iters):
        batch = make_episode_batch(rng, protos, b, n, k, t)
        tb = tuple(torch.tensor(a) for a in batch)
        ref_losses, _ = ref.run_train_iter(data_batch=tb, epoch=0)
        state, our_losses = learner.run_train_iter(state, batch, 0)
        rt, ot = torch_theta(ref), our_theta(state)
        dtheta = max(np.max(np.abs(rt[key] - ot[key])) for key in rt)
        results.append((
            float(ref_losses["loss"].detach()), float(our_losses["loss"]),
            float(ref_losses["accuracy"]), float(our_losses["accuracy"]),
            dtheta,
        ))
    return results


@pytest.mark.parametrize("ways", [5, 20])
def test_second_order_train_iters_match_reference(ways):
    for it, (rl, ol, ra, oa, dtheta) in enumerate(_run_pair(ways, 3, True)):
        assert abs(rl - ol) < 1e-4, (it, rl, ol)
        assert abs(ra - oa) < 1e-6, (it, ra, oa)
        assert dtheta < 1e-4, (it, dtheta)


def test_first_order_train_iters_match_reference():
    for it, (rl, ol, ra, oa, dtheta) in enumerate(_run_pair(5, 3, False)):
        assert abs(rl - ol) < 1e-4, (it, rl, ol)
        assert abs(ra - oa) < 1e-6, (it, ra, oa)
        assert dtheta < 1e-4, (it, dtheta)


def test_validation_iter_matches_reference():
    """Eval episodes (reference run_validation_iter,
    few_shot_learning_system.py:371-397): same weights + batch -> same loss,
    accuracy, and per-task target logits; our state must be unchanged (the
    functional form of the reference's BN backup/restore)."""
    torch.manual_seed(104)
    ref = build_reference(5, 3, 8, 1e-3, 10, True)
    learner, state = build_ours(5, 3, 8, 1e-3, 10, True)
    state = copy_torch_params_into_state(ref, state)

    b, n, k, t = 2, 5, 1, 1
    rng = np.random.RandomState(11)
    protos = rng.randn(n, 1, 28, 28).astype("f")
    batch = make_episode_batch(rng, protos, b, n, k, t)

    tb = tuple(torch.tensor(a) for a in batch)
    # Materialize host copies BEFORE the call: run_validation_iter returns
    # its input state object, so comparing state to new_state afterwards
    # would be vacuous.
    theta_before = {k: v.copy() for k, v in our_theta(state).items()}
    ref_losses, ref_preds = ref.run_validation_iter(data_batch=tb)
    new_state, our_losses, our_preds = learner.run_validation_iter(state, batch)

    assert abs(float(ref_losses["loss"]) - float(our_losses["loss"])) < 1e-4
    assert abs(float(ref_losses["accuracy"])
               - float(our_losses["accuracy"])) < 1e-6
    np.testing.assert_allclose(
        np.asarray(our_preds), np.stack(ref_preds), atol=1e-4
    )
    # purity: eval must not move our train state
    for key, before in theta_before.items():
        np.testing.assert_array_equal(before, our_theta(new_state)[key])


def test_matching_nets_train_iter_matches_reference():
    """Our MatchingNetsLearner with parity_bug=True is the reference's
    matching-nets step (matching_nets.py:98-145, including its support-set
    loss-target quirk at :128 and the per-task Adam update) — proving the
    golden-run accuracy gap (0.952 vs the reference's bundled 0.612) comes
    from that reference bug, not from solving a different problem."""
    import jax
    from parity_check import build_reference_matching_nets, copy_torch_backbone
    from howtotrainyourmamlpytorch_tpu.models import (
        BackboneConfig, MAMLConfig, MatchingNetsLearner,
    )

    torch.manual_seed(104)
    ref = build_reference_matching_nets(5, 8)
    cfg = MAMLConfig(
        backbone=BackboneConfig(
            num_stages=4, num_filters=8, per_step_bn_statistics=False,
            num_steps=1, num_classes=5, image_channels=1, max_pooling=True,
        ),
        number_of_training_steps_per_iter=1,
        number_of_evaluation_steps_per_iter=1,
        second_order=False, meta_learning_rate=1e-3, min_learning_rate=1e-5,
        total_epochs=100,
    )
    learner = MatchingNetsLearner(cfg, parity_bug=True)
    state = learner.init_state(jax.random.PRNGKey(0))
    sd = {k: np.array(v.detach().cpu().numpy(), copy=True)
          for k, v in ref.classifier.state_dict().items()}
    theta, bn = copy_torch_backbone(sd, state.theta)
    state = state._replace(theta=theta, bn_state=bn)

    b, n, k, t = 2, 5, 1, 1
    rng = np.random.RandomState(3)
    protos = rng.randn(n, 1, 28, 28).astype("f")
    for it in range(3):
        batch = make_episode_batch(rng, protos, b, n, k, t)
        tb = tuple(torch.tensor(a) for a in batch)
        ref_losses, _ = ref.run_train_iter(data_batch=tb, epoch=0)
        state, our_losses = learner.run_train_iter(state, batch, 0)
        assert abs(float(ref_losses["loss"].detach())
                   - float(our_losses["loss"])) < 1e-4, it
        assert abs(float(ref_losses["accuracy"])
                   - float(our_losses["accuracy"])) < 1e-6, it


def test_gradient_descent_train_iter_matches_reference():
    """Our GradientDescentLearner is the reference's baseline step
    (gradient_descent.py:85-129: per-step Adam updates on the support loss,
    an extra update on the final target loss, last-task metrics)."""
    import jax
    from parity_check import (
        build_reference_gradient_descent,
        copy_torch_backbone,
    )
    from howtotrainyourmamlpytorch_tpu.models import (
        BackboneConfig, MAMLConfig, GradientDescentLearner,
    )

    torch.manual_seed(104)
    ref = build_reference_gradient_descent(5, 2, 8)
    cfg = MAMLConfig(
        backbone=BackboneConfig(
            num_stages=4, num_filters=8, per_step_bn_statistics=False,
            num_steps=2, num_classes=5, image_channels=1, max_pooling=True,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        second_order=False, meta_learning_rate=1e-3, min_learning_rate=1e-5,
        total_epochs=100,
    )
    learner = GradientDescentLearner(cfg)
    state = learner.init_state(jax.random.PRNGKey(0))
    sd = {k: np.array(v.detach().cpu().numpy(), copy=True)
          for k, v in ref.classifier.state_dict().items()}
    theta, bn = copy_torch_backbone(sd, state.theta)
    state = state._replace(theta=theta, bn_state=bn)

    b, n, k, t = 2, 5, 1, 1
    rng = np.random.RandomState(5)
    protos = rng.randn(n, 1, 28, 28).astype("f")
    for it in range(3):
        batch = make_episode_batch(rng, protos, b, n, k, t)
        tb = tuple(torch.tensor(a) for a in batch)
        ref_losses, _ = ref.run_train_iter(data_batch=tb, epoch=0)
        state, our_losses = learner.run_train_iter(state, batch, 0)
        assert abs(float(ref_losses["loss"].detach())
                   - float(our_losses["loss"])) < 1e-4, it
        assert abs(float(ref_losses["accuracy"])
                   - float(our_losses["accuracy"])) < 1e-6, it
        sd2 = ref.classifier.state_dict()
        w_ref = sd2["layer_dict.conv0.conv.weight"].detach().numpy()
        w_our = np.asarray(state.theta["conv0"]["conv"]["weight"])
        assert np.max(np.abs(w_ref - w_our)) < 1e-4, it


def test_strided_imagenet_architecture_matches_reference():
    """The mini-imagenet backbone variant (84x84x3, 48->8 filters here,
    max_pooling=False: stride-2 convs + global avg pool,
    meta_neural_network_architectures.py:565-570,601-606) through full
    first-order train iterations."""
    import jax
    from parity_check import (
        _reference_args, copy_torch_params_into_state,
    )
    from few_shot_learning_system import MAMLFewShotClassifier
    from howtotrainyourmamlpytorch_tpu.models import (
        BackboneConfig, MAMLConfig, MAMLFewShotLearner,
    )

    torch.manual_seed(104)
    args = _reference_args(
        5, 2, 8, 1e-3, 10, False,
        image_height=20, image_width=20, image_channels=3,
        max_pooling=False,
    )
    ref = MAMLFewShotClassifier(
        im_shape=(2, 3, 20, 20), device=torch.device("cpu"), args=args
    )
    cfg = MAMLConfig(
        backbone=BackboneConfig(
            num_stages=4, num_filters=8, per_step_bn_statistics=True,
            num_steps=2, num_classes=5, image_channels=3,
            image_height=20, image_width=20, max_pooling=False,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        task_learning_rate=0.1,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        second_order=False, use_multi_step_loss_optimization=True,
        multi_step_loss_num_epochs=10,
        meta_learning_rate=1e-3, min_learning_rate=1e-5, total_epochs=100,
    )
    learner = MAMLFewShotLearner(cfg)
    state = learner.init_state(jax.random.PRNGKey(0))
    state = copy_torch_params_into_state(ref, state)

    b, n, k, t = 2, 5, 1, 1
    rng = np.random.RandomState(13)
    protos = rng.randn(n, 3, 20, 20).astype("f")
    for it in range(2):
        batch = make_episode_batch(rng, protos, b, n, k, t)
        tb = tuple(torch.tensor(a) for a in batch)
        ref_losses, _ = ref.run_train_iter(data_batch=tb, epoch=0)
        state, our_losses = learner.run_train_iter(state, batch, 0)
        assert abs(float(ref_losses["loss"].detach())
                   - float(our_losses["loss"])) < 1e-4, it
        assert abs(float(ref_losses["accuracy"])
                   - float(our_losses["accuracy"])) < 1e-6, it
