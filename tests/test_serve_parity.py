"""Served predictions are BIT-EXACT with the eval harness.

The acceptance contract of the serving runtime (ISSUE 4): for a golden
fixture episode (labels from the recorded reference-sampler fixtures in
``tests/fixtures/``, images seeded from the episode's recorded seed), the
logits answered by the full serving path — request preparation, shape
bucketing, TASK-AXIS PADDING to the engine's fixed meta-batch, the split
adapt/classify program pair, the adapted-params cache — are bitwise equal
to ``run_validation_iter``'s for all three learner families.

Ordering note: the GD eval step donates its input state buffers, so every
test runs the serving path FIRST and the reference eval last.
"""

import json
import os

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    GradientDescentLearner,
    MAMLConfig,
    MAMLFewShotLearner,
    MatchingNetsLearner,
)
from howtotrainyourmamlpytorch_tpu.models.common import WireCodec
from howtotrainyourmamlpytorch_tpu.serve import ServeConfig, ServingAPI
from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
    load_for_inference,
    save_checkpoint,
)

FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "reference_episodes.json"
)

LEARNER_CLASSES = {
    "maml": MAMLFewShotLearner,
    "gradient_descent": GradientDescentLearner,
    "matching_nets": MatchingNetsLearner,
}


def tiny_cfg(**kw):
    defaults = dict(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=8,
            image_height=14,
            image_width=14,
            num_classes=5,
            per_step_bn_statistics=True,
            num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
    )
    defaults.update(kw)
    return MAMLConfig(**defaults)


def golden_fixture_episode(query: int = 3, binary: bool = False):
    """The first recorded reference-sampler episode (5-way 1-shot), images
    deterministically seeded from its recorded episode seed. Query rows are
    drawn per class so the episode exercises every head index."""
    with open(FIXTURE) as f:
        golden = json.load(f)
    entry = golden["configs"][0]
    episode = entry["episodes"][0]
    way = entry["config"]["num_classes_per_set"]
    shot = entry["config"]["num_samples_per_class"]
    rng = np.random.RandomState(episode["seed"])
    shape = (1, 14, 14)

    def draw(n):
        if binary:  # omniglot-like exact-0/1 pixels (uint8 wire codec path)
            return (rng.rand(n, *shape) > 0.5).astype(np.float32)
        return rng.rand(n, *shape).astype(np.float32)

    ys = np.asarray(episode["support_labels"], np.int32).reshape(way, shot)
    xs = draw(way * shot).reshape(way, shot, *shape)
    yq = np.tile(np.arange(way, dtype=np.int32)[:, None], (1, query))
    xq = draw(way * query).reshape(way, query, *shape)
    return xs, ys, xq, yq


def eval_batch(xs, ys, xq, yq):
    """(B=1, N, K, ...) episode batch for ``run_validation_iter``."""
    return (xs[None], xq[None], ys[None], yq[None])


def serve_and_reference(learner, state, xs, ys, xq, yq, meta_batch=3):
    """Runs the episode through the FULL serving path (bucketing + padding:
    one episode into a meta_batch-of-3 program), then the eval harness.
    Returns ``(served_first, served_cache_hit, reference)`` logits."""
    api = ServingAPI(
        learner,
        state,
        ServeConfig(meta_batch_size=meta_batch, max_wait_ms=0.0),
    )
    try:
        first = api.classify(xs, ys, xq)
        again = api.classify(xs, ys, xq)
        assert not first["cache_hit"]
        assert again["cache_hit"], "repeat support set must hit the cache"
    finally:
        api.close()
    # Reference LAST: the GD eval step donates the state buffers.
    _, _, ref = learner.run_validation_iter(state, eval_batch(xs, ys, xq, yq))
    return first["logits"], again["logits"], np.asarray(ref)[0]


@pytest.mark.parametrize("family", sorted(LEARNER_CLASSES))
def test_served_fixture_episode_bit_exact(family):
    learner = LEARNER_CLASSES[family](tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    xs, ys, xq, yq = golden_fixture_episode()
    served, cached, ref = serve_and_reference(learner, state, xs, ys, xq, yq)
    np.testing.assert_array_equal(served, ref)
    np.testing.assert_array_equal(cached, ref)


def test_maml_trained_state_bit_exact(rng):
    """Parity must survive a real (non-init) state: one train iter first."""
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(1))
    xs, ys, xq, yq = golden_fixture_episode()
    train_batch = (
        rng.randn(2, 5, 2, 1, 14, 14).astype(np.float32),
        rng.randn(2, 5, 2, 1, 14, 14).astype(np.float32),
        np.tile(np.arange(5)[None, :, None], (2, 1, 2)).astype(np.int32),
        np.tile(np.arange(5)[None, :, None], (2, 1, 2)).astype(np.int32),
    )
    state, _ = learner.run_train_iter(state, train_batch, epoch=0)
    served, cached, ref = serve_and_reference(learner, state, xs, ys, xq, yq)
    np.testing.assert_array_equal(served, ref)
    np.testing.assert_array_equal(cached, ref)


def test_maml_extra_eval_step_config_bit_exact():
    """eval_steps == train_steps + 1 takes the eval harness's NON-final-only
    program (prediction at the train-step index) — serving must adapt to
    min(train, eval) steps, not the raw eval count."""
    learner = MAMLFewShotLearner(
        tiny_cfg(number_of_evaluation_steps_per_iter=3)
    )
    state = learner.init_state(jax.random.key(2))
    xs, ys, xq, yq = golden_fixture_episode()
    served, cached, ref = serve_and_reference(learner, state, xs, ys, xq, yq)
    np.testing.assert_array_equal(served, ref)
    np.testing.assert_array_equal(cached, ref)


def test_maml_uint8_wire_codec_bit_exact():
    """The uint8 wire path (omniglot scale-1 codec, exact-0/1 pixels) must
    stay bit-exact through serve-side encode + in-graph decode."""
    learner = MAMLFewShotLearner(tiny_cfg(wire_codec=WireCodec(1.0, None, None)))
    state = learner.init_state(jax.random.key(3))
    xs, ys, xq, yq = golden_fixture_episode(binary=True)
    served, cached, ref = serve_and_reference(learner, state, xs, ys, xq, yq)
    np.testing.assert_array_equal(served, ref)
    np.testing.assert_array_equal(cached, ref)


def test_matching_nets_parity_bug_mode_bit_exact():
    """The bug-for-bug reference reproduction serves through the same split
    (shape coincidence N*K == N*T == num_classes required by that mode)."""
    learner = MatchingNetsLearner(tiny_cfg(), parity_bug=True)
    state = learner.init_state(jax.random.key(4))
    xs, ys, xq, yq = golden_fixture_episode(query=1)
    served, cached, ref = serve_and_reference(learner, state, xs, ys, xq, yq)
    np.testing.assert_array_equal(served, ref)
    np.testing.assert_array_equal(cached, ref)


@pytest.mark.parametrize("family", sorted(LEARNER_CLASSES))
def test_load_for_inference_serves_bit_exact(family, tmp_path):
    """Cold start from a manifest-verified checkpoint: params+BN-only load
    (no optimizer state) answers bitwise identically to serving the live
    train state."""
    learner = LEARNER_CLASSES[family](tiny_cfg())
    state = learner.init_state(jax.random.key(5))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, state, {"current_iter": 7})

    template = learner.init_inference_state(jax.random.key(99))
    loaded, exp = load_for_inference(path, template)
    assert exp["current_iter"] == 7

    xs, ys, xq, yq = golden_fixture_episode()
    api = ServingAPI(
        learner, loaded, ServeConfig(meta_batch_size=2, max_wait_ms=0.0)
    )
    try:
        served = api.classify(xs, ys, xq)["logits"]
    finally:
        api.close()
    _, _, ref = learner.run_validation_iter(state, eval_batch(xs, ys, xq, yq))
    np.testing.assert_array_equal(served, np.asarray(ref)[0])
