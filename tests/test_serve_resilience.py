"""Serving resilience layer (ISSUE 6): deterministic fault-injection
proofs for every recovery path on the request side.

The serving twin of ``tests/test_faultinject.py``: replica crash
mid-stream recovers with ZERO failed requests (bounded re-dispatch,
compile-guard-pinned to mint no new program signatures on healthy
replicas), a wedged replica is detected by the supervisor and replaced
within the health-check budget, corrupt/NaN checkpoint swaps are rejected
with the old state still serving bit-exact, and overload sheds with
typed 503s instead of unbounded queue growth.

Everything runs in-process on tiny shapes (CPU, tier-1, no slow marker);
one end-to-end test boots real worker SUBPROCESSES through the same pool
to prove the production topology.
"""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.serve import (
    NoHealthyReplicaError,
    OverloadedError,
    PoolConfig,
    ReplicaPool,
    ServeConfig,
    ServingAPI,
    SwapRejectedError,
)
from howtotrainyourmamlpytorch_tpu.serve.resilience import (
    AdmissionController,
    LocalReplica,
)
from howtotrainyourmamlpytorch_tpu.telemetry import EventLog
from howtotrainyourmamlpytorch_tpu.telemetry import events as telemetry_events
from howtotrainyourmamlpytorch_tpu.telemetry.events import read_events
from howtotrainyourmamlpytorch_tpu.utils import faultinject
from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
    CheckpointCorruptError,
    save_checkpoint,
    verify_checkpoint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_cfg():
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=4,
            image_height=8,
            image_width=8,
            num_classes=5,
            per_step_bn_statistics=True,
            num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
    )


# One learner for the module: engines jit their own program pairs anyway,
# but the backbone init / config plumbing is shared.
LEARNER = MAMLFewShotLearner(tiny_cfg())


def make_api(**serve_kw):
    defaults = dict(meta_batch_size=2, max_wait_ms=0.0)
    defaults.update(serve_kw)
    return ServingAPI(
        LEARNER, LEARNER.init_state(jax.random.key(0)),
        ServeConfig(**defaults),
    )


def episode(rng, way=5, shot=1, query=3):
    img = (1, 8, 8)
    xs = rng.rand(way * shot, *img).astype(np.float32)
    ys = np.repeat(np.arange(way), shot).astype(np.int32)
    xq = rng.rand(query, *img).astype(np.float32)
    return xs, ys, xq


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.deactivate()
    yield
    faultinject.deactivate()


def local_pool(n=2, warm_bucket=(5, 1, 3), **pool_kw):
    """A LocalReplica pool over fresh tiny APIs, warmed before serving."""
    def factory(index: int) -> LocalReplica:
        api = make_api()
        api.engine.warmup([warm_bucket])
        return LocalReplica(api, replica_id=f"local-{index}")

    defaults = dict(
        n_replicas=n,
        health_interval_s=0.02,
        health_timeout_s=1.0,
        unhealthy_after=2,
        restart_backoff_s=0.05,
        restart_backoff_max_s=1.0,
        min_uptime_s=0.0,
    )
    defaults.update(pool_kw)
    pool = ReplicaPool(factory, PoolConfig(**defaults))
    assert pool.wait_ready(timeout=120.0), "pool never became healthy"
    return pool


# ---------------------------------------------------------------------------
# Fault-injection plumbing (the four new serve faults)
# ---------------------------------------------------------------------------


def test_serve_faults_parse_from_env(monkeypatch):
    monkeypatch.setenv(
        faultinject.ENV_VAR,
        "replica_kill_at_request=3,wedge_replica_at_request=7;"
        "corrupt_swap_at=128,nan_next_logits=2",
    )
    faultinject.reset()
    plan = faultinject.current_plan()
    assert plan.replica_kill_at_request == 3
    assert plan.wedge_replica_at_request == 7
    assert plan.corrupt_swap_at == 128
    assert plan.nan_next_logits == 2
    faultinject.reset()


def test_serve_request_fault_counts_and_consumes():
    faultinject.activate(faultinject.FaultPlan(replica_kill_at_request=2))
    assert faultinject.serve_request_fault() is None  # request 1
    assert faultinject.serve_request_fault() == "kill"  # request 2: fires
    assert faultinject.serve_request_fault() is None  # consumed, one-shot
    assert faultinject.events == ["replica-kill:2"]


def test_poison_logits_is_counted_and_bounded():
    faultinject.activate(faultinject.FaultPlan(nan_next_logits=1))
    poisoned = faultinject.poison_logits(np.ones((2, 3), np.float32))
    assert np.isnan(poisoned).all()
    clean = faultinject.poison_logits(np.ones((2, 3), np.float32))
    assert np.isfinite(clean).all(), "one-shot budget must be consumed"


# ---------------------------------------------------------------------------
# Admission control + graceful degradation
# ---------------------------------------------------------------------------


def test_admission_hard_limit_sheds_everything():
    api = make_api(max_queue_depth=4, degrade_queue_depth=0)
    ctrl = api.admission
    ctrl.admit(queue_depth=3, oldest_age_s=0.0, cache_hit=False)  # admitted
    with pytest.raises(OverloadedError, match="hard limit"):
        ctrl.admit(queue_depth=4, oldest_age_s=0.0, cache_hit=True)
    assert api.metrics.shed_total.value == 1
    api.close()


def test_admission_degraded_sheds_cold_keeps_cache_hits():
    """Graceful degradation: past the soft threshold, cold-adapt traffic is
    shed while cache-hit classify traffic keeps flowing."""
    api = make_api(max_queue_depth=64, degrade_queue_depth=2)
    ctrl = api.admission
    with pytest.raises(OverloadedError, match="cold-adapt"):
        ctrl.admit(queue_depth=2, oldest_age_s=0.0, cache_hit=False)
    ctrl.admit(queue_depth=2, oldest_age_s=0.0, cache_hit=True)  # served
    assert api.metrics.degraded.value == 1.0
    ctrl.admit(queue_depth=0, oldest_age_s=0.0, cache_hit=False)
    assert api.metrics.degraded.value == 0.0, "degradation must clear"
    api.close()


def test_admission_queue_age_degrades_even_at_low_depth():
    api = make_api(max_queue_age_ms=100.0, degrade_queue_depth=64)
    with pytest.raises(OverloadedError):
        api.admission.admit(
            queue_depth=1, oldest_age_s=0.2, cache_hit=False
        )
    api.close()


def test_overload_sheds_instead_of_unbounded_queue(rng):
    """End-to-end: with the queue parked (huge batching window), requests
    past the hard limit get typed 503s and the queue stays BOUNDED."""
    api = make_api(
        meta_batch_size=8,
        max_wait_ms=60_000.0,
        max_queue_depth=3,
        degrade_queue_depth=0,
    )
    api.engine.warmup([(5, 1, 3)])
    workers = []
    try:
        for _ in range(3):  # park 3 requests in the queue
            t = threading.Thread(
                target=lambda: api.classify(*episode(rng), timeout=30),
                daemon=True,
            )
            t.start()
            workers.append(t)
        deadline = time.monotonic() + 5
        while api.batcher.queue_depth() < 3:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        for _ in range(5):
            with pytest.raises(OverloadedError) as err:
                api.classify(*episode(rng))
            assert err.value.retry_after_s > 0
        assert api.batcher.queue_depth() <= 3, "queue must stay bounded"
        assert api.metrics.shed_total.value == 5
        assert api.healthz()["status"] in ("ok", "degraded")
    finally:
        api.close()
        for t in workers:
            t.join(timeout=30)


# ---------------------------------------------------------------------------
# Safe hot-swap
# ---------------------------------------------------------------------------


def swap_checkpoint(tmp_path, name="swap_ckpt", key=7, poison_nan=False):
    state = LEARNER.init_state(jax.random.key(key))
    if poison_nan:
        state = state._replace(
            theta=jax.tree.map(
                lambda a: np.full_like(np.asarray(a), np.nan), state.theta
            )
        )
    path = str(tmp_path / name)
    save_checkpoint(path, state, {"current_iter": 0})
    return path


def test_promote_accepts_good_checkpoint_no_new_signatures(
    rng, tmp_path, compile_guard
):
    """A valid promotion canaries every warmed bucket against the candidate
    and publishes — WITHOUT minting any new program signature (canaries
    ride the compiled pair; a swap must never cause a compile storm)."""
    api = make_api()
    api.engine.warmup([(5, 1, 3), (5, 5, 3)])
    before = api.classify(*episode(rng))
    ckpt = swap_checkpoint(tmp_path)
    with compile_guard() as guard:
        result = api.promote(ckpt)
    assert guard.count("serve_adapt_maml") == 0
    assert guard.count("serve_classify_maml") == 0
    assert result["state_version"] == 1
    assert result["buckets_canaried"] == 2
    after = api.classify(*episode(rng))
    assert after["state_version"] == 1
    assert before["state_version"] == 0
    assert api.metrics.swaps_total.value == 1
    api.close()


def test_corrupt_swap_rejected_old_state_serves_bit_exact(rng, tmp_path):
    """The ``corrupt_swap_at`` fault truncates the checkpoint right before
    the promotion loads it: the manifest verify refuses it, and the old
    state keeps serving bit-exact."""
    api = make_api()
    api.engine.warmup([(5, 1, 3)])
    xs, ys, xq = episode(rng)
    before = np.asarray(api.classify(xs, ys, xq)["logits"])
    ckpt = swap_checkpoint(tmp_path)
    faultinject.activate(faultinject.FaultPlan(corrupt_swap_at=256))
    with pytest.raises(SwapRejectedError) as err:
        api.promote(ckpt)
    assert err.value.reason == "corrupt_checkpoint"
    assert isinstance(err.value.__cause__, CheckpointCorruptError)
    assert any(e.startswith("corrupt-swap:") for e in faultinject.events)
    after = api.classify(xs, ys, xq)
    assert after["state_version"] == 0
    np.testing.assert_array_equal(np.asarray(after["logits"]), before)
    assert api.metrics.swap_rejected_total.value == 1
    api.close()


def test_nan_checkpoint_rejected_by_canary(rng, tmp_path):
    """A numerically-broken (all-NaN params) checkpoint passes the
    manifest (its bytes are intact!) but the canary episode catches the
    non-finite logits before publish."""
    api = make_api()
    api.engine.warmup([(5, 1, 3)])
    xs, ys, xq = episode(rng)
    before = np.asarray(api.classify(xs, ys, xq)["logits"])
    ckpt = swap_checkpoint(tmp_path, poison_nan=True)
    with pytest.raises(SwapRejectedError) as err:
        api.promote(ckpt)
    assert err.value.reason == "nonfinite_logits"
    after = api.classify(xs, ys, xq)
    assert after["state_version"] == 0
    np.testing.assert_array_equal(np.asarray(after["logits"]), before)
    api.close()


def test_nan_logits_fault_rejects_swap_and_emits_event(rng, tmp_path):
    """The ``nan_next_logits`` fault proves the finite-logits gate without
    crafting a broken checkpoint, and the rejection emits a structured
    ``swap_rejected`` telemetry event."""
    api = make_api()
    api.engine.warmup([(5, 1, 3)])
    log = EventLog(str(tmp_path / "telemetry.jsonl"))
    previous = telemetry_events.install(log)
    try:
        faultinject.activate(faultinject.FaultPlan(nan_next_logits=1))
        with pytest.raises(SwapRejectedError):
            api.promote(swap_checkpoint(tmp_path))
        log.flush()
    finally:
        telemetry_events.install(previous)
    rejected = [
        e for e in read_events(log.path) if e["type"] == "swap_rejected"
    ]
    assert len(rejected) == 1
    assert rejected[0]["reason"] == "nonfinite_logits"
    assert rejected[0]["state_version"] == 0
    api.close()


def test_verify_checkpoint_front_door(tmp_path):
    ckpt = swap_checkpoint(tmp_path)
    summary = verify_checkpoint(ckpt)
    assert summary["has_manifest"] is True
    assert summary["leaves"] > 0
    with open(ckpt, "r+b") as f:
        f.truncate(200)
    with pytest.raises(CheckpointCorruptError):
        verify_checkpoint(ckpt)


# ---------------------------------------------------------------------------
# Replica pool: crash recovery, wedge detection, circuit breaker
# ---------------------------------------------------------------------------


def test_replica_crash_mid_stream_zero_failed_requests(rng, compile_guard):
    """THE tentpole proof: a replica dies serving request K; the pool
    re-dispatches onto the healthy replica and every request in the stream
    is answered — zero failures, and the recovery window mints ZERO new
    program signatures on the healthy replica (both replicas were warmed;
    re-dispatch rides existing programs)."""
    pool = local_pool(n=2, restart_backoff_s=600.0)  # no restart mid-test
    try:
        faultinject.activate(
            faultinject.FaultPlan(replica_kill_at_request=3)
        )
        with compile_guard() as guard:
            for i in range(8):
                out = pool.classify(*episode(rng))
                assert np.asarray(out["logits"]).shape == (3, 5)
        assert guard.count("serve_adapt_maml") == 0
        assert guard.count("serve_classify_maml") == 0
        assert "replica-kill:3" in faultinject.events
        assert pool.metrics.retry_total.value == 1
        assert pool.metrics.replica_deaths_total.value == 1
        assert pool.metrics.request_errors.value == 0
        health = pool.healthz()
        assert health["healthy_replicas"] == 1
        assert health["degraded"] is True and health["ready"] is True
    finally:
        pool.close()


def test_supervisor_restarts_crashed_replica(rng):
    pool = local_pool(n=2, restart_backoff_s=0.02)
    try:
        faultinject.activate(
            faultinject.FaultPlan(replica_kill_at_request=1)
        )
        pool.classify(*episode(rng))  # kills one replica; re-dispatched
        deadline = time.monotonic() + 60
        while pool.healthz()["healthy_replicas"] < 2:
            assert time.monotonic() < deadline, "replica never restarted"
            time.sleep(0.02)
        assert pool.metrics.replica_restarts_total.value == 1
        pool.classify(*episode(rng))  # the reborn fleet serves
    finally:
        pool.close()


def test_wedged_replica_detected_and_replaced_within_budget(rng):
    """A replica that stops answering health checks (but holds its slot)
    is detected by the supervisor within ``unhealthy_after *
    health_interval + health_timeout`` and replaced."""
    pool = local_pool(n=2, restart_backoff_s=0.02, health_interval_s=0.02)
    try:
        faultinject.activate(
            faultinject.FaultPlan(wedge_replica_at_request=1)
        )
        out = pool.classify(*episode(rng))  # arms the wedge; still answers
        assert np.asarray(out["logits"]).shape == (3, 5)
        assert "replica-wedge:1" in faultinject.events
        t0 = time.monotonic()
        deadline = t0 + 60
        saw_death = False
        while time.monotonic() < deadline:
            if pool.metrics.replica_deaths_total.value >= 1:
                saw_death = True
                break
            time.sleep(0.01)
        assert saw_death, "supervisor never detected the wedged replica"
        while pool.healthz()["healthy_replicas"] < 2:
            assert time.monotonic() < deadline, "replacement never came up"
            time.sleep(0.02)
        assert pool.metrics.replica_restarts_total.value >= 1
        # Traffic flowed around the wedge the whole time.
        pool.classify(*episode(rng))
        assert pool.metrics.request_errors.value == 0
    finally:
        pool.close()


def test_crash_loop_trips_circuit_breaker(rng):
    """A slot whose replica keeps dying young is parked (circuit open)
    instead of restart-looping; the pool keeps serving on the healthy
    slot and reports itself degraded."""
    calls = {"bad": 0}

    def factory(index: int):
        if index == 1:
            calls["bad"] += 1
            raise RuntimeError("this replica never comes up")
        api = make_api()
        api.engine.warmup([(5, 1, 3)])
        return LocalReplica(api, replica_id=f"local-{index}")

    pool = ReplicaPool(
        factory,
        PoolConfig(
            n_replicas=2,
            health_interval_s=0.02,
            restart_backoff_s=0.01,
            restart_backoff_max_s=0.05,
            min_uptime_s=0.0,
            circuit_breaker_after=3,
        ),
    )
    try:
        assert pool.wait_ready(timeout=60, healthy=1)
        deadline = time.monotonic() + 30
        while pool.metrics.circuit_open_total.value < 1:
            assert time.monotonic() < deadline, "breaker never tripped"
            time.sleep(0.02)
        assert calls["bad"] == 3, "breaker must stop further restarts"
        time.sleep(0.2)
        assert calls["bad"] == 3
        health = pool.healthz()
        assert health["degraded"] is True and health["ready"] is True
        states = {r["index"]: r["state"] for r in health["replicas"]}
        assert states[1] == "circuit_open"
        out = pool.classify(*episode(np.random.RandomState(0)))
        assert np.asarray(out["logits"]).shape == (3, 5)
    finally:
        pool.close()


def test_no_healthy_replica_is_typed_503(rng):
    def factory(index: int):
        raise RuntimeError("fleet is down")

    pool = ReplicaPool(
        factory,
        PoolConfig(
            n_replicas=1,
            health_interval_s=0.02,
            restart_backoff_s=0.01,
            circuit_breaker_after=1,
        ),
    )
    try:
        with pytest.raises(NoHealthyReplicaError) as err:
            pool.classify(*episode(rng))
        assert isinstance(err.value, OverloadedError)  # maps to 503
        assert pool.metrics.shed_total.value == 1
        assert pool.healthz()["ready"] is False
    finally:
        pool.close()


def test_pool_promote_rejects_corrupt_checkpoint_at_front_door(
    rng, tmp_path
):
    """A corrupt checkpoint is refused ONCE by the front-door manifest
    verify — no replica spends a load or canary on it, and every replica
    keeps serving the old version."""
    pool = local_pool(n=2, restart_backoff_s=600.0)
    try:
        ckpt = swap_checkpoint(tmp_path)
        with open(ckpt, "r+b") as f:
            f.truncate(300)
        with pytest.raises(SwapRejectedError) as err:
            pool.promote(ckpt)
        assert err.value.reason == "corrupt_checkpoint"
        out = pool.classify(*episode(rng))
        assert out["state_version"] == 0
    finally:
        pool.close()


def test_pool_promote_rolls_good_checkpoint_to_all_replicas(rng, tmp_path):
    pool = local_pool(n=2, restart_backoff_s=600.0)
    try:
        result = pool.promote(swap_checkpoint(tmp_path))
        assert result["promoted_replicas"] == 2
        for _ in range(2):  # round-robin touches both replicas
            assert pool.classify(*episode(rng))["state_version"] == 1
    finally:
        pool.close()


# ---------------------------------------------------------------------------
# Loadtest smoke (tier-1: tiny request count, in-process)
# ---------------------------------------------------------------------------


def test_loadtest_smoke_in_process(rng):
    from tools.serve_loadtest import run_loadtest, synth_episodes

    api = make_api(
        meta_batch_size=4, max_wait_ms=2.0,
        max_queue_depth=128, degrade_queue_depth=0,
    )
    api.engine.warmup([(5, 1, 3)])
    try:
        result = run_loadtest(
            api,
            synth_episodes(4, way=5, shot=1, query=3, image_shape=(1, 8, 8)),
            rate_qps=20.0,
            duration_s=0.8,
            p99_budget_ms=30_000.0,
            error_slo=0.01,
            seed=0,
        )
    finally:
        api.close()
    assert result["offered"] > 0
    assert result["completed_ok"] == result["offered"]
    assert result["serve_error_rate"] == 0.0
    assert result["slo_pass"] is True
    assert result["serve_slo_p99_ms"] == 30_000.0
    assert result["serve_loadtest_p99_ms"] > 0
    assert result["serve_recovery_s"] == 0.0
    # The SLO verdict keys serve_bench.py re-exports are all present.
    for key in (
        "serve_loadtest_qps", "serve_error_rate", "serve_recovery_s",
        "serve_slo_p99_ms", "slo_pass", "shed", "deadline_exceeded",
    ):
        assert key in result
    json.dumps(result)  # --json output must be serializable as-is


def test_loadtest_counts_sheds_and_fails_verdict(rng):
    """An overloaded target cannot produce a passing verdict: sheds count
    into the error rate."""
    from tools.serve_loadtest import run_loadtest, synth_episodes

    api = make_api(
        meta_batch_size=8,
        max_wait_ms=60_000.0,  # park everything: all but the queue cap shed
        max_queue_depth=1,
        degrade_queue_depth=0,
    )
    api.engine.warmup([(5, 1, 3)])
    try:
        result = run_loadtest(
            api,
            synth_episodes(4, way=5, shot=1, query=3, image_shape=(1, 8, 8)),
            rate_qps=30.0,
            duration_s=0.7,
            p99_budget_ms=30_000.0,
            error_slo=0.01,
            timeout_s=1.0,
            seed=1,
        )
    finally:
        api.close()
    assert result["shed"] + result["deadline_exceeded"] > 0
    assert result["serve_error_rate"] > 0.01
    assert result["slo_pass"] is False


# ---------------------------------------------------------------------------
# Production topology: subprocess replicas end-to-end
# ---------------------------------------------------------------------------


def test_subprocess_pool_end_to_end(rng, tmp_path):
    """The real thing, once: two ``tools/serve_maml.py`` worker PROCESSES
    under pool supervision. Replica 0 is armed (via env) to hard-exit on
    its first episode; the stream still answers every request, and the
    supervisor respawns the dead worker."""
    from howtotrainyourmamlpytorch_tpu.serve.resilience.replica import (
        SubprocessReplica,
        serve_maml_argv,
    )

    cfg_json = {
        "num_stages": 2,
        "cnn_num_filters": 4,
        "num_classes_per_set": 5,
        "image_height": 8,
        "image_width": 8,
        "image_channels": 1,
        "per_step_bn_statistics": True,
        "number_of_training_steps_per_iter": 2,
        "number_of_evaluation_steps_per_iter": 2,
    }
    config_path = str(tmp_path / "serve_cfg.json")
    with open(config_path, "w") as f:
        json.dump(cfg_json, f)

    armed = {"fault": True}  # only the FIRST replica-0 spawn gets the fault

    def factory(index: int) -> SubprocessReplica:
        port_file = os.path.join(
            str(tmp_path), f"replica_{index}_{time.monotonic_ns()}.port"
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env.pop(faultinject.ENV_VAR, None)
        if index == 0 and armed.pop("fault", False):
            env[faultinject.ENV_VAR] = "replica_kill_at_request=1"
        argv = serve_maml_argv(
            config_path,
            port_file=port_file,
            warmup="5x1x3",
            max_batch=2,
            max_wait_ms=1.0,
            repo_root=REPO,
        )
        return SubprocessReplica(
            argv,
            replica_id=f"worker-{index}",
            env=env,
            port_file=port_file,
            startup_timeout_s=180.0,
        )

    pool = ReplicaPool(
        factory,
        PoolConfig(
            n_replicas=2,
            health_interval_s=0.2,
            health_timeout_s=3.0,
            unhealthy_after=2,
            restart_backoff_s=0.1,
            min_uptime_s=0.0,
            dispatch_timeout_s=30.0,
        ),
    )
    try:
        assert pool.wait_ready(timeout=180.0), "subprocess pool never ready"
        xs, ys, xq = episode(rng)
        answered = 0
        for _ in range(4):
            out = pool.classify(xs, ys, xq, timeout=60.0)
            assert np.asarray(out["logits"]).shape == (3, 5)
            answered += 1
        assert answered == 4, "zero failed requests across the worker crash"
        assert pool.metrics.replica_deaths_total.value >= 1, (
            "the armed worker must actually have died"
        )
        assert pool.metrics.retry_total.value >= 1
        # Supervision respawns the dead worker process.
        deadline = time.monotonic() + 120
        while pool.healthz()["healthy_replicas"] < 2:
            assert time.monotonic() < deadline, "worker never respawned"
            time.sleep(0.2)
        assert pool.metrics.replica_restarts_total.value >= 1
        pool.classify(xs, ys, xq, timeout=60.0)
    finally:
        pool.close()
