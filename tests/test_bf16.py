"""bf16 compute path (VERDICT r1 item 10; ISSUE 9 lever 2).

``compute_dtype="bfloat16"`` runs the backbone in bf16 (MXU-native) while
parameters and BN statistics stay fp32 (``models/maml.py:95-99``,
``ops/norm.py`` fp32 stats). The toy task must still train to high
accuracy — bf16's ~3 decimal digits are plenty for this net.

ISSUE 9 additions: ``--compute_dtype auto`` resolves to bf16 only on TPU
backends (f32 elsewhere, keeping CPU receipts bit-exact); the bf16 K=1
and K-scan train paths stay finite and within golden tolerance of the f32
program; the PR 3 divergence sentinel trips on an injected bf16 overflow
(``faultinject.overflow_at_iter``); and ``--compute_dtype float32``
restores the pre-bf16 program bit for bit — including against checkpoints
written before this PR (``cast_floats`` is the IDENTITY at f32, so the
f32 train program never changed)."""

import os

import jax
import jax.numpy as jnp
import numpy as np

import pytest

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    GradientDescentLearner,
    MAMLConfig,
    MAMLFewShotLearner,
    MatchingNetsLearner,
)
from howtotrainyourmamlpytorch_tpu.models.common import cast_floats
from howtotrainyourmamlpytorch_tpu.utils import faultinject
from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
    resolve_compute_dtype,
)


def _cfg(dtype):
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2, num_filters=8, per_step_bn_statistics=True,
            num_steps=2, num_classes=5, image_height=8, image_width=8,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        compute_dtype=dtype,
    )


def _batches(rng, n_iters, b=4):
    protos = rng.randn(5, 1, 8, 8).astype(np.float32)
    out = []
    for _ in range(n_iters):
        xs = np.stack(
            [protos + 0.3 * rng.randn(5, 1, 8, 8).astype(np.float32)
             for _ in range(b)]
        )[:, :, None]
        ys = np.tile(np.arange(5)[None, :, None], (b, 1, 1))
        out.append((xs, xs.copy(), ys, ys.copy()))
    return out


def test_bf16_trains_to_accuracy(rng):
    learner = MAMLFewShotLearner(_cfg("bfloat16"))
    state = learner.init_state(jax.random.PRNGKey(0))
    # Master weights stay fp32.
    for leaf in jax.tree.leaves(state.theta):
        assert leaf.dtype == jnp.float32
    for batch in _batches(rng, 15):
        state, losses = learner.run_train_iter(state, batch, epoch=0)
    assert np.isfinite(float(losses["loss"]))
    assert float(losses["accuracy"]) > 0.9
    # BN running stats stayed fp32 and finite.
    for leaf in jax.tree.leaves(state.bn_state):
        assert leaf.dtype == jnp.float32
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_bf16_eval_close_to_fp32(rng):
    """Same init, one eval episode: bf16 metrics track fp32 within bf16
    tolerance."""
    a = MAMLFewShotLearner(_cfg("float32"))
    b = MAMLFewShotLearner(_cfg("bfloat16"))
    sa = a.init_state(jax.random.PRNGKey(7))
    sb = b.init_state(jax.random.PRNGKey(7))
    batch = _batches(rng, 1)[0]
    _, la, _ = a.run_validation_iter(sa, batch)
    _, lb, _ = b.run_validation_iter(sb, batch)
    np.testing.assert_allclose(float(la["loss"]), float(lb["loss"]),
                               rtol=0.1, atol=0.05)


# ---------------------------------------------------------------------------
# ISSUE 9: auto default, golden tolerance, overflow sentinel, escape hatch
# ---------------------------------------------------------------------------


def test_resolve_compute_dtype_auto_is_backend_dependent():
    """``auto`` means bf16 on TPU, f32 everywhere else; explicit values
    pass through untouched (the escape hatch)."""
    expected = "bfloat16" if jax.default_backend() == "tpu" else "float32"
    assert resolve_compute_dtype("auto") == expected
    assert resolve_compute_dtype(None) == expected
    assert resolve_compute_dtype("float32") == "float32"
    assert resolve_compute_dtype("bfloat16") == "bfloat16"


def test_cast_floats_is_identity_at_f32():
    """At f32 the boundary cast is THE SAME TREE, not even a traced copy —
    the structural proof that the f32 program (and therefore every pre-PR
    checkpoint's semantics) is untouched by the bf16 lever."""
    tree = {"w": jnp.ones((2, 2)), "i": jnp.arange(3)}
    assert cast_floats(tree, jnp.float32) is tree
    cast = cast_floats(tree, jnp.bfloat16)
    assert cast["w"].dtype == jnp.bfloat16
    assert cast["i"].dtype == tree["i"].dtype  # integers ride through


def test_bf16_golden_tolerance_k1_and_kscan(rng):
    """The bf16 K=1 and K-scan train paths stay finite and track the f32
    golden run within bf16 tolerance, per iteration."""
    a = MAMLFewShotLearner(_cfg("float32"))
    b = MAMLFewShotLearner(_cfg("bfloat16"))
    sa = a.init_state(jax.random.PRNGKey(11))
    sb = b.init_state(jax.random.PRNGKey(11))
    for batch in _batches(rng, 4):  # K=1 path
        sa, la = a.run_train_iter(sa, batch, epoch=0)
        sb, lb = b.run_train_iter(sb, batch, epoch=0)
        assert np.isfinite(float(lb["loss"]))
        np.testing.assert_allclose(
            float(la["loss"]), float(lb["loss"]), rtol=0.1, atol=0.05
        )
    k_batches = _batches(rng, 3)  # K-scan dispatch path
    sa, la = a.run_train_iters(sa, k_batches, epoch=0)
    sb, lb = b.run_train_iters(sb, k_batches, epoch=0)
    assert np.all(np.isfinite(np.asarray(lb["loss"], np.float64)))
    np.testing.assert_allclose(
        np.asarray(la["loss"], np.float64),
        np.asarray(lb["loss"], np.float64),
        rtol=0.1, atol=0.05,
    )


@pytest.mark.parametrize("cls", [GradientDescentLearner, MatchingNetsLearner])
def test_bf16_other_learners_train_finite(cls, rng):
    """GD and matching nets under bf16: masters stay f32 (their boundary
    cast sits at the backbone application), training stays finite."""
    learner = cls(_cfg("bfloat16"))
    state = learner.init_state(jax.random.PRNGKey(14))
    for batch in _batches(rng, 3):
        state, losses = learner.run_train_iter(state, batch, epoch=0)
        assert np.isfinite(float(losses["loss"]))
        assert float(losses["nonfinite"]) == 0.0
    for leaf in jax.tree.leaves(state.theta):
        assert leaf.dtype == jnp.float32


def test_sentinel_trips_on_injected_bf16_overflow(rng):
    """``faultinject.overflow_at_iter`` (the nan-hook precedent extended):
    near-float-max target images overflow the first conv accumulation to
    inf under the bf16 compute path, and the PR 3 divergence sentinel
    reports the trip through the train step's ``nonfinite`` metric."""
    faultinject.reset()
    faultinject.activate(faultinject.FaultPlan(overflow_at_iter=1))
    try:
        learner = MAMLFewShotLearner(_cfg("bfloat16"))
        state = learner.init_state(jax.random.PRNGKey(12))
        batches = _batches(rng, 2)
        clean = faultinject.poison_batch(batches[0] + (0,), 0)
        assert clean is not None and not np.isinf(np.asarray(clean[1])).any()
        state, losses = learner.run_train_iter(state, clean[:4], epoch=0)
        assert float(losses["nonfinite"]) == 0.0
        poisoned = faultinject.poison_batch(batches[1] + (0,), 1)
        assert np.max(np.abs(np.asarray(poisoned[1]))) > 1e38
        state, losses = learner.run_train_iter(state, poisoned[:4], epoch=0)
        assert float(losses["nonfinite"]) == 1.0
        assert faultinject.events == ["overflow:1"]
    finally:
        faultinject.deactivate()


def test_overflow_fault_parses_from_env(monkeypatch):
    faultinject.reset()
    monkeypatch.setenv(faultinject.ENV_VAR, "overflow_at_iter=4")
    assert faultinject.current_plan().overflow_at_iter == 4
    faultinject.reset()


def test_compute_dtype_float32_restores_pre_pr_checkpoints_bit_exact(
    tmp_path, rng
):
    """A checkpoint written by the f32 program (identical to pre-PR
    archives: ``cast_floats`` is the identity at f32 and the archive
    format is untouched) restores under ``--compute_dtype float32`` with
    bit-exact logits, and under bf16 with f32 masters intact."""
    writer = MAMLFewShotLearner(_cfg("float32"))
    state = writer.init_state(jax.random.PRNGKey(13))
    batches = _batches(rng, 2)
    state, _ = writer.run_train_iter(state, batches[0], epoch=0)
    path = os.path.join(tmp_path, "train_model_1")
    writer.save_model(path, state, {"current_iter": 2})

    hatch = MAMLFewShotLearner(_cfg("float32"))
    s_hatch, exp = hatch.load_model(str(tmp_path), "train_model", 1)
    assert exp == {"current_iter": 2}
    _, _, logits_w = writer.run_validation_iter(state, batches[1])
    _, _, logits_h = hatch.run_validation_iter(s_hatch, batches[1])
    np.testing.assert_array_equal(np.asarray(logits_w), np.asarray(logits_h))

    b = MAMLFewShotLearner(_cfg("bfloat16"))
    s_b, _ = b.load_model(str(tmp_path), "train_model", 1)
    for leaf in jax.tree.leaves(s_b.theta):
        assert leaf.dtype == jnp.float32  # masters stay f32
    _, _, logits_b = b.run_validation_iter(s_b, batches[1])
    lb = np.asarray(logits_b, np.float64)
    assert np.all(np.isfinite(lb))
    # bf16 rounding compounds through the adapted inner loop, so the pin
    # is prediction-level: the served classes overwhelmingly agree.
    lw = np.asarray(logits_w, np.float64)
    agree = np.mean(lw.argmax(-1) == lb.argmax(-1))
    assert agree >= 0.8, agree
