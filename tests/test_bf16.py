"""bf16 compute path (VERDICT r1 item 10).

``compute_dtype="bfloat16"`` runs the backbone in bf16 (MXU-native) while
parameters and BN statistics stay fp32 (``models/maml.py:95-99``,
``ops/norm.py`` fp32 stats). The toy task must still train to high
accuracy — bf16's ~3 decimal digits are plenty for this net."""

import jax
import jax.numpy as jnp
import numpy as np

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)


def _cfg(dtype):
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2, num_filters=8, per_step_bn_statistics=True,
            num_steps=2, num_classes=5, image_height=8, image_width=8,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        compute_dtype=dtype,
    )


def _batches(rng, n_iters, b=4):
    protos = rng.randn(5, 1, 8, 8).astype(np.float32)
    out = []
    for _ in range(n_iters):
        xs = np.stack(
            [protos + 0.3 * rng.randn(5, 1, 8, 8).astype(np.float32)
             for _ in range(b)]
        )[:, :, None]
        ys = np.tile(np.arange(5)[None, :, None], (b, 1, 1))
        out.append((xs, xs.copy(), ys, ys.copy()))
    return out


def test_bf16_trains_to_accuracy(rng):
    learner = MAMLFewShotLearner(_cfg("bfloat16"))
    state = learner.init_state(jax.random.PRNGKey(0))
    # Master weights stay fp32.
    for leaf in jax.tree.leaves(state.theta):
        assert leaf.dtype == jnp.float32
    for batch in _batches(rng, 15):
        state, losses = learner.run_train_iter(state, batch, epoch=0)
    assert np.isfinite(float(losses["loss"]))
    assert float(losses["accuracy"]) > 0.9
    # BN running stats stayed fp32 and finite.
    for leaf in jax.tree.leaves(state.bn_state):
        assert leaf.dtype == jnp.float32
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_bf16_eval_close_to_fp32(rng):
    """Same init, one eval episode: bf16 metrics track fp32 within bf16
    tolerance."""
    a = MAMLFewShotLearner(_cfg("float32"))
    b = MAMLFewShotLearner(_cfg("bfloat16"))
    sa = a.init_state(jax.random.PRNGKey(7))
    sb = b.init_state(jax.random.PRNGKey(7))
    batch = _batches(rng, 1)[0]
    _, la, _ = a.run_validation_iter(sa, batch)
    _, lb, _ = b.run_validation_iter(sb, batch)
    np.testing.assert_allclose(float(la["loss"]), float(lb["loss"]),
                               rtol=0.1, atol=0.05)
