"""Checkpoint integrity layer: manifest verification, typed corruption
errors, structural fail-fast, write retry with backoff, and the
hardlink-alias ``latest`` publisher (ISSUE 3 tentpole, pillars 1 + 4).

These are codec-level tests on small plain pytrees — the end-to-end
recovery paths through ``ExperimentBuilder`` live in
``tests/test_faultinject.py``."""

import json
import os

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.utils import faultinject
from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
    CheckpointCorruptError,
    CheckpointError,
    SCHEMA_VERSION,
    _EXPERIMENT_KEY,
    _MANIFEST_KEY,
    load_checkpoint,
    publish_alias,
    save_checkpoint,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.deactivate()
    yield
    faultinject.reset()


def _tree(seed=0, n=3, size=7):
    rng = np.random.RandomState(seed)
    return {
        "params": [rng.rand(size, 2).astype(np.float32) for _ in range(n)],
        "count": np.int32(seed),
    }


def _save(path, seed=0, exp=None):
    save_checkpoint(str(path), _tree(seed), exp or {"current_iter": seed})
    return str(path)


# ---------------------------------------------------------------------------
# Manifest round-trip + verification
# ---------------------------------------------------------------------------


def test_manifest_embedded_and_roundtrip(tmp_path):
    path = _save(tmp_path / "ckpt", seed=3)
    with np.load(path) as archive:
        manifest = json.loads(bytes(archive[_MANIFEST_KEY]).decode())
    assert manifest["schema"] == SCHEMA_VERSION
    assert manifest["leaf_count"] == 4  # 3 params + count
    assert len(manifest["leaf_crc32"]) == 4
    restored, exp = load_checkpoint(path, _tree(0))
    assert exp == {"current_iter": 3}
    for a, b in zip(
        restored["params"] + [restored["count"]],
        _tree(3)["params"] + [_tree(3)["count"]],
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_truncated_archive_is_typed_corrupt(tmp_path):
    path = _save(tmp_path / "ckpt")
    size = os.path.getsize(path)
    for cut in (0, 10, size // 2, size - 3):
        with open(path, "r+b") as f:
            f.truncate(cut)
        with pytest.raises(CheckpointCorruptError):
            load_checkpoint(path, _tree(0))
        _save(tmp_path / "ckpt")  # restore for the next cut


def test_bitflip_in_leaf_data_is_typed_corrupt(tmp_path):
    """Flips a byte inside actual array data (located by its byte pattern —
    flips in zip/npy metadata padding are semantically inert and rightly
    ignored): either the zip member CRC or the manifest leaf CRC must
    catch it as typed corruption."""
    path = str(tmp_path / "ckpt")
    leaf = np.full((64,), 1.2345678, np.float32)
    save_checkpoint(path, {"a": leaf}, {"current_iter": 0})
    with open(path, "rb") as f:
        blob = f.read()
    offset = blob.find(leaf.tobytes())
    assert offset > 0  # npz stores uncompressed, the raw bytes must exist
    with open(path, "r+b") as f:
        f.seek(offset + 17)
        byte = f.read(1)
        f.seek(offset + 17)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path, {"a": leaf})


def test_missing_file_is_typed_corrupt(tmp_path):
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(str(tmp_path / "nope"), _tree(0))


def test_newer_schema_refused_without_fallback(tmp_path):
    path = _save(tmp_path / "ckpt")
    with np.load(path) as archive:
        arrays = {k: archive[k] for k in archive.files}
    manifest = json.loads(bytes(arrays[_MANIFEST_KEY]).decode())
    manifest["schema"] = SCHEMA_VERSION + 1
    arrays[_MANIFEST_KEY] = np.frombuffer(
        json.dumps(manifest).encode(), dtype=np.uint8
    )
    with open(path, "wb") as f:
        np.savez(f, **arrays)
    with pytest.raises(CheckpointError) as err:
        load_checkpoint(path, _tree(0))
    # NOT the corrupt subtype: resume must not quarantine a future-schema
    # file, the build is simply too old to read it.
    assert not isinstance(err.value, CheckpointCorruptError)


# ---------------------------------------------------------------------------
# Structural fail-fast (satellite: no more load-by-truncation)
# ---------------------------------------------------------------------------


def test_leaf_count_mismatch_fails_fast_both_directions(tmp_path):
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, _tree(0, n=3), {})
    with pytest.raises(ValueError, match="leaves"):
        load_checkpoint(path, _tree(0, n=2))  # template smaller: was silent!
    with pytest.raises(ValueError, match="leaves"):
        load_checkpoint(path, _tree(0, n=5))  # template larger


def test_legacy_archive_without_manifest_still_loads(tmp_path):
    """Pre-schema files (no manifest member) load with structural checks
    only — kill-and-rerun resume across this PR keeps working."""
    import jax

    path = str(tmp_path / "legacy")
    tree = _tree(4)
    leaves = jax.tree.leaves(tree)  # flatten order = sorted dict keys
    arrays = {f"leaf_{i}": np.asarray(v) for i, v in enumerate(leaves)}
    arrays[_EXPERIMENT_KEY] = np.frombuffer(
        json.dumps({"current_iter": 9}).encode(), dtype=np.uint8
    )
    with open(path, "wb") as f:
        np.savez(f, **arrays)
    restored, exp = load_checkpoint(path, _tree(0))
    assert exp["current_iter"] == 9
    for a, b in zip(jax.tree.leaves(restored), leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # ... but a legacy archive with MORE leaves than the template no longer
    # "loads" by dropping the excess.
    arrays["leaf_4"] = np.zeros(3, np.float32)
    with open(path, "wb") as f:
        np.savez(f, **arrays)
    with pytest.raises(ValueError, match="leaves"):
        load_checkpoint(path, _tree(0))


def test_tree_fingerprint_mismatch_fails_fast(tmp_path):
    """Same leaf count and shapes, different tree structure: the manifest
    fingerprint refuses the silent positional remap."""
    path = str(tmp_path / "ckpt")
    x = np.arange(4, dtype=np.float32)
    save_checkpoint(path, {"a": x, "b": x + 1}, {})
    with pytest.raises(ValueError, match="fingerprint"):
        load_checkpoint(path, {"c": [x, x]})


def test_shape_mismatch_still_valueerror(tmp_path):
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, _tree(0, size=7), {})
    with pytest.raises(ValueError, match="shape"):
        load_checkpoint(path, _tree(0, size=9))


# ---------------------------------------------------------------------------
# Write retry + backoff (pillar 1 / acceptance d)
# ---------------------------------------------------------------------------


def test_write_retry_below_budget_succeeds(tmp_path):
    faultinject.activate(faultinject.FaultPlan(fail_next_writes=2))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, _tree(5), {"current_iter": 5}, backoff_s=0.01)
    assert faultinject.events.count("write-fail:ckpt") == 2
    _, exp = load_checkpoint(path, _tree(0))
    assert exp["current_iter"] == 5


def test_write_retry_above_budget_raises_and_keeps_old_file(tmp_path):
    path = _save(tmp_path / "ckpt", seed=1)
    faultinject.activate(faultinject.FaultPlan(fail_next_writes=99))
    with pytest.raises(OSError, match="faultinject"):
        save_checkpoint(path, _tree(2), {"current_iter": 2}, backoff_s=0.01)
    faultinject.deactivate()
    assert not os.path.exists(path + ".tmp")  # tmp cleaned up
    _, exp = load_checkpoint(path, _tree(0))  # previous file intact
    assert exp["current_iter"] == 1


def test_transient_read_error_retries_then_succeeds(tmp_path, monkeypatch):
    path = _save(tmp_path / "ckpt", seed=6)
    real_load = np.load
    calls = {"n": 0}

    def flaky(file, *args, **kwargs):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError(5, "injected EIO", str(file))
        return real_load(file, *args, **kwargs)

    monkeypatch.setattr(np, "load", flaky)
    _, exp = load_checkpoint(path, _tree(0), backoff_s=0.01)
    assert exp["current_iter"] == 6
    assert calls["n"] == 3


def test_persistent_read_error_is_not_corrupt(tmp_path, monkeypatch):
    """A persistent I/O failure must surface as plain CheckpointError, NOT
    the corrupt subtype — the resume fallback would otherwise quarantine a
    perfectly healthy checkpoint over an NFS blip."""
    path = _save(tmp_path / "ckpt")

    def always_eio(file, *args, **kwargs):
        raise OSError(5, "injected EIO", str(file))

    monkeypatch.setattr(np, "load", always_eio)
    with pytest.raises(CheckpointError, match="transient") as err:
        load_checkpoint(path, _tree(0), backoff_s=0.01)
    assert not isinstance(err.value, CheckpointCorruptError)


# ---------------------------------------------------------------------------
# latest alias publisher (satellite: one serialization per epoch)
# ---------------------------------------------------------------------------


def test_publish_alias_retries_transient_failures(tmp_path):
    """The write-retry contract covers BOTH halves of the epoch publish:
    epoch file (save_checkpoint) AND latest alias (publish_alias)."""
    epoch = _save(tmp_path / "train_model_3", seed=3)
    latest = str(tmp_path / "train_model_latest")
    faultinject.activate(faultinject.FaultPlan(fail_next_writes=2))
    publish_alias(epoch, latest, backoff_s=0.01)
    assert faultinject.events.count("write-fail:train_model_latest") == 2
    _, exp = load_checkpoint(latest, _tree(0))
    assert exp["current_iter"] == 3
    faultinject.activate(faultinject.FaultPlan(fail_next_writes=99))
    with pytest.raises(OSError, match="faultinject"):
        publish_alias(epoch, latest, backoff_s=0.01)


def test_publish_alias_is_loadable_and_hardlinked(tmp_path):
    epoch_path = _save(tmp_path / "train_model_7", seed=7)
    latest = str(tmp_path / "train_model_latest")
    publish_alias(epoch_path, latest)
    _, exp = load_checkpoint(latest, _tree(0))
    assert exp["current_iter"] == 7
    # Re-publishing over an existing alias replaces it atomically.
    epoch8 = _save(tmp_path / "train_model_8", seed=8)
    publish_alias(epoch8, latest)
    _, exp = load_checkpoint(latest, _tree(0))
    assert exp["current_iter"] == 8
    # The epoch-7 file is untouched by the re-publish.
    _, exp = load_checkpoint(epoch_path, _tree(0))
    assert exp["current_iter"] == 7


# ---------------------------------------------------------------------------
# load_for_inference (ISSUE 4 satellite: serving cold-start load)
# ---------------------------------------------------------------------------
#
# Codec-level contract: the PREFIX of the flat leaf sequence restores
# against a shorter template (the learners' InferenceState trees are field
# prefixes of their train states), the FULL archive manifest is still
# verified, and the typed-error split (CheckpointCorruptError vs
# ValueError) is preserved. End-to-end learner coverage (serve-from-loaded
# bit-exactness) lives in tests/test_serve_parity.py.


def _list_tree(seed=0, n=4, size=6):
    rng = np.random.RandomState(seed)
    return [rng.rand(size, 2).astype(np.float32) for _ in range(n)]


def test_load_for_inference_restores_prefix(tmp_path):
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        load_for_inference,
    )

    path = str(tmp_path / "ckpt")
    full = _list_tree(seed=3, n=4)
    save_checkpoint(path, full, {"current_iter": 11})
    restored, exp = load_for_inference(path, _list_tree(seed=9, n=2))
    assert exp == {"current_iter": 11}
    assert len(restored) == 2
    for got, want in zip(restored, full[:2]):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_load_for_inference_verifies_manifest_beyond_the_prefix(tmp_path):
    """A bit-flip in a leaf OUTSIDE the inference prefix still refuses the
    load — integrity is all-or-nothing, a torn write anywhere means the
    file cannot be trusted."""
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        load_for_inference,
    )

    path = str(tmp_path / "ckpt")
    marker = np.full((64,), 7.6543215, np.float32)
    save_checkpoint(path, [np.ones((3,), np.float32), marker], {})
    with open(path, "rb") as f:
        blob = f.read()
    offset = blob.find(marker.tobytes())
    assert offset > 0
    with open(path, "r+b") as f:
        f.seek(offset + 9)
        byte = f.read(1)
        f.seek(offset + 9)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(CheckpointCorruptError):
        load_for_inference(path, [np.ones((3,), np.float32)])


def test_load_for_inference_typed_errors(tmp_path):
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        load_for_inference,
    )

    path = str(tmp_path / "ckpt")
    save_checkpoint(path, _list_tree(n=3), {})
    # architecture mismatch: prefix leaf shape differs -> ValueError
    with pytest.raises(ValueError, match="shape"):
        load_for_inference(path, _list_tree(n=2, size=9))
    # template larger than the archive -> ValueError, never truncation
    with pytest.raises(ValueError, match="leaves"):
        load_for_inference(path, _list_tree(n=5))
    # missing file -> typed corrupt (resume paths may fall back)
    with pytest.raises(CheckpointCorruptError):
        load_for_inference(str(tmp_path / "nope"), _list_tree(n=2))
    # truncated archive -> typed corrupt
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.raises(CheckpointCorruptError):
        load_for_inference(path, _list_tree(n=2))


# ---------------------------------------------------------------------------
# Async background writer (ISSUE 10): snapshot on the critical path,
# serialize/CRC/rename on one writer thread, drained on every exit path
# ---------------------------------------------------------------------------


def test_snapshot_plus_write_is_byte_compatible_with_sync_save(tmp_path):
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        snapshot_for_save,
        write_snapshot,
    )

    sync_path = str(tmp_path / "sync")
    split_path = str(tmp_path / "split")
    save_checkpoint(sync_path, _tree(3), {"current_iter": 3})
    write_snapshot(split_path, snapshot_for_save(_tree(3), {"current_iter": 3}))
    with open(sync_path, "rb") as a, open(split_path, "rb") as b:
        assert a.read() == b.read()
    leaves, exp = load_checkpoint(split_path, _tree(0))
    assert exp["current_iter"] == 3


def test_async_writer_publishes_in_order_with_alias_and_drains(tmp_path):
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        AsyncCheckpointWriter,
        load_checkpoint,
        snapshot_for_save,
    )

    writer = AsyncCheckpointWriter()
    try:
        for epoch in (1, 2):
            writer.submit(
                str(tmp_path / f"ckpt_{epoch}"),
                snapshot_for_save(_tree(epoch), {"current_iter": epoch}),
                alias_dst=str(tmp_path / "latest"),
            )
        assert writer.drain()
        # Both epochs valid; the alias is the LAST submitted epoch.
        for epoch in (1, 2):
            _, exp = load_checkpoint(str(tmp_path / f"ckpt_{epoch}"), _tree(0))
            assert exp["current_iter"] == epoch
        _, exp = load_checkpoint(str(tmp_path / "latest"), _tree(0))
        assert exp["current_iter"] == 2
        assert writer.pending == 0
    finally:
        writer.close()


def test_async_writer_error_surfaces_at_next_submit_boundary(tmp_path):
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        AsyncCheckpointWriter,
        snapshot_for_save,
    )

    faultinject.activate(faultinject.FaultPlan(fail_next_writes=99))
    writer = AsyncCheckpointWriter()
    writer.submit(
        str(tmp_path / "doomed"),
        snapshot_for_save(_tree(1), {"current_iter": 1}),
        backoff_s=0.01,
    )
    # The non-raising drain (the emergency-exit fence) completes and KEEPS
    # the error readable; the raising drain then surfaces the
    # retry-exhausted OSError the sync path would have raised.
    assert writer.drain(raise_errors=False) is True
    assert isinstance(writer.pending_error(), OSError)
    with pytest.raises(OSError, match="faultinject"):
        writer.drain()
    faultinject.deactivate()
    # After surfacing once the writer is usable again.
    writer.submit(
        str(tmp_path / "fine"), snapshot_for_save(_tree(2), {"current_iter": 2})
    )
    writer.drain()
    _, exp = load_checkpoint(str(tmp_path / "fine"), _tree(0))
    assert exp["current_iter"] == 2
    writer.close()
    with pytest.raises(CheckpointError, match="closed"):
        writer.submit(
            str(tmp_path / "late"),
            snapshot_for_save(_tree(3), {"current_iter": 3}),
        )


def test_async_writer_drain_timeout_bounds_the_wait(tmp_path, monkeypatch):
    import threading

    import howtotrainyourmamlpytorch_tpu.utils.checkpoint as ckpt

    release = threading.Event()
    real_write = ckpt.write_snapshot

    def slow_write(path, snapshot, **kw):
        release.wait(timeout=30.0)
        return real_write(path, snapshot, **kw)

    monkeypatch.setattr(ckpt, "write_snapshot", slow_write)
    writer = ckpt.AsyncCheckpointWriter()
    try:
        writer.submit(
            str(tmp_path / "slow"),
            ckpt.snapshot_for_save(_tree(1), {"current_iter": 1}),
        )
        assert writer.drain(timeout=0.2) is False  # bounded: still in flight
        release.set()
        assert writer.drain(timeout=30.0) is True
        _, exp = load_checkpoint(str(tmp_path / "slow"), _tree(0))
        assert exp["current_iter"] == 1
    finally:
        release.set()
        writer.close()
