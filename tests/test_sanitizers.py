"""Trace-time sanitizer suite: the recompile guard and the debug-config
flag wiring.

The guard (``utils/sanitize.compile_guard``) must (a) demonstrably TRIP on
a seeded recompile bug — a config dict threaded as a traced argument whose
structure varies per call, and a fresh jit wrapper built inside the loop —
and (b) PASS on the real MAML train steps: the K=1 path and the K>1
scan-dispatch path each compile exactly once per (shape, dtype, K) class
across a multi-iteration run. That second property is the regression guard
behind every ``*_meta_iters_per_s`` bench key in PERF_NOTES.md.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.utils.sanitize import RecompileError


def tiny_cfg(**kw):
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=4,
            num_classes=5,
            image_height=8,
            image_width=8,
            num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        use_multi_step_loss_optimization=False,
        second_order=False,
        **kw,
    )


def tiny_batch(rng, tasks=2):
    xs = rng.rand(tasks, 5, 1, 1, 8, 8).astype(np.float32)
    ys = np.tile(np.arange(5)[None, :, None], (tasks, 1, 1)).astype(np.int32)
    return xs, xs.copy(), ys, ys.copy()


# ---------------------------------------------------------------------------
# The guard trips on seeded recompile bugs
# ---------------------------------------------------------------------------


def test_guard_trips_on_nonstatic_dict_arg(compile_guard):
    """A config dict whose structure varies per call retraces the step every
    iteration — the guard must see N compiles where the contract says 1."""

    # graftlint: disable=jit-static-config -- the seeded recompile bug this
    # test exists to trip the guard on (ISSUE 2 acceptance criterion)
    @jax.jit
    def step_with_cfg(x, cfg):
        return jnp.mean(x) * cfg["scale"]

    x = jnp.ones((4, 4))
    with compile_guard() as guard:
        step_with_cfg(x, {"scale": 1.0})
        step_with_cfg(x, {"scale": 1.0, "extra": 0.0})  # new pytree structure
        step_with_cfg(x, {"scale": 1.0, "extra": 0.0, "more": 2.0})
    assert guard.count("step_with_cfg") == 3
    with pytest.raises(RecompileError):
        guard.assert_compiles("step_with_cfg", exactly=1)


def test_guard_trips_on_fresh_jit_wrapper_per_iteration(compile_guard):
    """jit-inside-the-loop compiles an identical (shape, dtype) class every
    iteration — the duplicate-signature assertion must trip."""
    x = jnp.ones((4, 4))
    with compile_guard() as guard:
        for _ in range(3):

            def fresh_step(v):
                return jnp.mean(v) * 2.0

            jax.jit(fresh_step)(x)
    assert guard.count("fresh_step") == 3
    with pytest.raises(RecompileError):
        guard.assert_unique_signatures("fresh_step")


def test_unnamed_partial_is_invisible_to_the_guard(compile_guard):
    """Why the learners jit named_partial(...) instead of bare partials:
    jit names the XLA program from __name__, and partial objects have none
    — the compile log line says '<unnamed wrapped function>', which no
    name-keyed guard can match."""

    def step(v, scale):
        return jnp.mean(v) * scale

    with compile_guard() as guard:
        jax.jit(functools.partial(step, scale=2.0))(jnp.ones((4, 4)))
    assert guard.count("step") == 0
    assert guard.count("<unnamed wrapped function>") == 1


def test_guard_passes_on_cached_jit(compile_guard):
    @jax.jit
    def well_behaved(x):
        return jnp.mean(x)

    x = jnp.ones((4, 4))
    with compile_guard() as guard:
        for _ in range(4):
            well_behaved(x)
    guard.assert_compiles("well_behaved", exactly=1)
    guard.assert_unique_signatures("well_behaved")


# ---------------------------------------------------------------------------
# The guard passes on the real train steps (K=1 and K=25 scan dispatch)
# ---------------------------------------------------------------------------


def test_k1_train_step_compiles_once(compile_guard, rng):
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    batch = tiny_batch(rng)
    with compile_guard() as guard:
        for _ in range(4):
            state, _ = learner.run_train_iter(state, batch, epoch=0)
        jax.block_until_ready(state.theta)
    guard.assert_compiles("_train_step", exactly=1)
    guard.assert_unique_signatures("_train_step")


def test_k25_multi_train_step_compiles_once(compile_guard, rng):
    """The K=25 scan-dispatch path: several dispatches at a fixed
    (shape, dtype, K) class must reuse one compiled program."""
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    batches = [tiny_batch(rng) for _ in range(25)]
    with compile_guard() as guard:
        for _ in range(3):
            state, _ = learner.run_train_iters(state, batches, epoch=0)
        jax.block_until_ready(state.theta)
    guard.assert_compiles("multi", exactly=1)
    guard.assert_unique_signatures("multi")


def test_k_change_is_a_new_compile_class_not_a_violation(compile_guard, rng):
    """Two K values are two legitimate (shape, dtype, K) classes: two
    compiles, but no duplicated signature."""
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    with compile_guard() as guard:
        state, _ = learner.run_train_iters(
            state, [tiny_batch(rng) for _ in range(5)], epoch=0
        )
        state, _ = learner.run_train_iters(
            state, [tiny_batch(rng) for _ in range(3)], epoch=0
        )
        jax.block_until_ready(state.theta)
    assert guard.count("multi") == 2
    guard.assert_unique_signatures("multi")


# ---------------------------------------------------------------------------
# Debug-config wiring (--debug_nans / --check_tracer_leaks)
# ---------------------------------------------------------------------------


def _get_args(argv):
    from howtotrainyourmamlpytorch_tpu.utils.parser_utils import get_args

    return get_args(argv)


@pytest.fixture
def restore_debug_config():
    old_nans = jax.config.jax_debug_nans
    old_leaks = jax.config.jax_check_tracer_leaks
    yield
    jax.config.update("jax_debug_nans", old_nans)
    jax.config.update("jax_check_tracer_leaks", old_leaks)


def test_debug_flags_default_off(restore_debug_config, monkeypatch):
    monkeypatch.setenv("DATASET_DIR", "/tmp")
    jax.config.update("jax_debug_nans", False)
    jax.config.update("jax_check_tracer_leaks", False)
    args, _ = _get_args([])
    assert args.debug_nans is False
    assert args.check_tracer_leaks is False
    assert jax.config.jax_debug_nans is False
    assert jax.config.jax_check_tracer_leaks is False


def test_debug_flags_opt_in_flip_jax_config(restore_debug_config, monkeypatch):
    monkeypatch.setenv("DATASET_DIR", "/tmp")
    args, _ = _get_args(["--debug_nans", "True", "--check_tracer_leaks", "True"])
    assert args.debug_nans is True
    assert jax.config.jax_debug_nans is True
    assert jax.config.jax_check_tracer_leaks is True


def test_debug_nans_actually_raises_on_nan(restore_debug_config):
    jax.config.update("jax_debug_nans", True)

    @jax.jit
    def bad(x):
        return jnp.log(x - 1.0)

    with pytest.raises(FloatingPointError):
        jax.block_until_ready(bad(jnp.zeros(())))
