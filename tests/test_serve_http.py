"""HTTP frontend smoke (tier-1, CPU-only, tiny shapes): boot the server on
an ephemeral port, round-trip one episode, scrape ``/metrics``, shut down
cleanly. Plus the route/validation surface and the ``tools/serve_maml.py``
CLI plumbing (config-JSON learner build, warmup-spec parsing)."""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.serve import (
    ServeConfig,
    ServingAPI,
    make_http_server,
)


def tiny_cfg():
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=4,
            image_height=8,
            image_width=8,
            num_classes=5,
            per_step_bn_statistics=True,
            num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
    )


@pytest.fixture
def served():
    """A running HTTP server over a tiny fresh-init learner (warmed, so
    ``/healthz`` reports ready); yields ``(base_url, api)`` and guarantees
    clean shutdown."""
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    api = ServingAPI(
        learner, state, ServeConfig(meta_batch_size=2, max_wait_ms=1.0)
    )
    api.engine.warmup([(5, 1, 2)])
    server = make_http_server(api, port=0)  # ephemeral port
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        yield f"http://127.0.0.1:{port}", api
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=10)
        api.close()
        assert not thread.is_alive(), "server thread must exit on shutdown"


def get_json(url):
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.status, json.load(resp)


def post_episode(base, payload):
    req = urllib.request.Request(
        f"{base}/v1/episode",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.load(resp)


def episode_payload(rng, way=5, shot=1, query=2):
    return {
        "support": rng.rand(way * shot, 1, 8, 8).tolist(),
        "support_labels": np.repeat(np.arange(way), shot).tolist(),
        "query": rng.rand(query, 1, 8, 8).tolist(),
    }


def test_http_roundtrip_and_metrics_scrape(served, rng):
    base, api = served
    status, health = get_json(f"{base}/healthz")
    assert status == 200
    assert health["status"] == "ok" and health["family"] == "maml"
    # /healthz no longer lies: live queue/dispatch state rides along.
    assert health["ready"] is True and health["degraded"] is False
    assert health["queue_depth"] == 0
    assert "last_dispatch_age_s" in health
    assert health["warmed_buckets"] == ["5x1x2"]

    status, body = post_episode(base, episode_payload(rng))
    assert status == 200
    logits = np.asarray(body["logits"], np.float32)
    assert logits.shape == (2, 5)
    assert body["bucket"] == "5x1x2"
    assert body["cache_hit"] is False
    assert body["predictions"] == np.argmax(logits, axis=-1).tolist()

    with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
        assert resp.status == 200
        text = resp.read().decode()
    assert "maml_serve_requests_total 1" in text
    assert 'maml_serve_adapt_latency_ms{quantile="0.5"}' in text
    assert 'maml_serve_adapt_latency_ms{quantile="0.99"}' in text
    assert "maml_serve_cache_hit_rate" in text
    assert "maml_serve_queue_depth" in text
    assert 'maml_serve_bucket_episodes_total{bucket="5x1x2"} 1' in text
    assert 'maml_serve_program_compiles{program="adapt:2x5"} 1' in text


def test_http_cache_hit_on_repeat_support(served, rng):
    base, _ = served
    payload = episode_payload(rng)
    _, first = post_episode(base, payload)
    _, second = post_episode(base, payload)
    assert first["cache_hit"] is False
    assert second["cache_hit"] is True
    assert second["logits"] == first["logits"]


def test_http_error_surface(served, rng):
    base, _ = served
    # unknown route -> 404
    with pytest.raises(urllib.error.HTTPError) as err:
        get_json(f"{base}/nope")
    assert err.value.code == 404
    # malformed episode -> 400 with the validation message
    bad = episode_payload(rng)
    bad["support_labels"] = bad["support_labels"][:-1]
    with pytest.raises(urllib.error.HTTPError) as err:
        post_episode(base, bad)
    assert err.value.code == 400
    assert "support labels" in json.load(err.value)["error"]
    # missing field -> 400, not a hang or a 500
    with pytest.raises(urllib.error.HTTPError) as err:
        post_episode(base, {"support": []})
    assert err.value.code == 400


# ---------------------------------------------------------------------------
# Resilience surface: honest /healthz, 503 + Retry-After, /admin/promote
# ---------------------------------------------------------------------------


def unwarmed_server():
    learner = MAMLFewShotLearner(tiny_cfg())
    api = ServingAPI(
        learner,
        learner.init_state(jax.random.key(0)),
        ServeConfig(meta_batch_size=2, max_wait_ms=1.0),
    )
    server = make_http_server(api, port=0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, thread, api, f"http://127.0.0.1:{server.server_address[1]}"


def test_healthz_503_until_first_warmup(rng):
    """A replica that has never produced logits must not attract traffic:
    /healthz answers 503 with ``ready: false`` until warmup (or the first
    dispatch) completes."""
    server, thread, api, base = unwarmed_server()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            get_json(f"{base}/healthz")
        assert err.value.code == 503
        body = json.load(err.value)
        assert body["ready"] is False and body["status"] == "unready"
        # First successful episode flips readiness without explicit warmup.
        post_episode(base, episode_payload(rng))
        status, health = get_json(f"{base}/healthz")
        assert status == 200 and health["ready"] is True
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=10)
        api.close()


def test_shed_returns_503_with_retry_after(rng):
    """Admission control at the HTTP front door: a hard-limit shed is a
    503 with a Retry-After header, not a queued slow death."""
    learner = MAMLFewShotLearner(tiny_cfg())
    api = ServingAPI(
        learner,
        learner.init_state(jax.random.key(0)),
        ServeConfig(
            meta_batch_size=4,
            max_wait_ms=60_000.0,  # park the first episode in the queue
            max_queue_depth=1,
            retry_after_s=2.5,
        ),
    )
    api.engine.warmup([(5, 1, 2)])
    server = make_http_server(api, port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    blocked = threading.Thread(
        target=lambda: post_episode(base, episode_payload(rng)), daemon=True
    )
    try:
        blocked.start()
        deadline = time.monotonic() + 5
        while api.batcher.queue_depth() < 1:
            assert time.monotonic() < deadline, "queue never filled"
            time.sleep(0.005)
        with pytest.raises(urllib.error.HTTPError) as err:
            post_episode(base, episode_payload(rng))
        assert err.value.code == 503
        assert err.value.headers["Retry-After"] == "2.5"
        body = json.load(err.value)
        assert body["shed"] is True and "shed" in body["error"]
        status, health = get_json(f"{base}/healthz")
        assert status == 200  # ready, but honest about the pressure
        assert health["shed_total"] >= 1
        assert "maml_serve_shed_total 1" in api.metrics_text()
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=10)
        api.close()
        blocked.join(timeout=10)


def test_admin_promote_roundtrip_and_rejection(served, rng, tmp_path):
    """POST /admin/promote: a manifest-valid checkpoint swaps (200 + new
    state version), a corrupt one is refused with 409 and the old state
    keeps serving bit-exact."""
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import save_checkpoint

    base, api = served
    payload = episode_payload(rng)
    _, before = post_episode(base, payload)
    assert before["state_version"] == 0

    learner = MAMLFewShotLearner(tiny_cfg())
    ckpt = str(tmp_path / "promote_me")
    save_checkpoint(
        ckpt, learner.init_state(jax.random.key(7)), {"current_iter": 0}
    )
    req = urllib.request.Request(
        f"{base}/admin/promote",
        data=json.dumps({"checkpoint": ckpt}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        body = json.load(resp)
    assert body["state_version"] == 1
    assert body["buckets_canaried"] >= 1
    _, after = post_episode(base, payload)
    assert after["state_version"] == 1
    assert after["logits"] != before["logits"], "new weights must answer"

    # Corrupt checkpoint: rejected at 409, old (promoted) state unharmed.
    with open(ckpt, "r+b") as f:
        f.truncate(128)
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(req, timeout=60)
    assert err.value.code == 409
    assert json.load(err.value)["reason"] == "corrupt_checkpoint"
    _, still = post_episode(base, payload)
    assert still["state_version"] == 1
    assert still["logits"] == after["logits"]


# ---------------------------------------------------------------------------
# serve_maml CLI plumbing
# ---------------------------------------------------------------------------


def test_cli_builds_learner_from_experiment_config(tmp_path, monkeypatch):
    from tools.serve_maml import build_learner

    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    cfg_json = {
        "num_stages": 2,
        "cnn_num_filters": 4,
        "num_classes_per_set": 5,
        "image_height": 8,
        "image_width": 8,
        "image_channels": 1,
        "per_step_bn_statistics": True,
        "number_of_training_steps_per_iter": 2,
        "number_of_evaluation_steps_per_iter": 2,
    }
    path = tmp_path / "serve_cfg.json"
    path.write_text(json.dumps(cfg_json))
    learner = build_learner("maml", str(path))
    assert isinstance(learner, MAMLFewShotLearner)
    assert learner.cfg.backbone.num_filters == 4
    assert learner.cfg.backbone.num_classes == 5
    assert learner.cfg.number_of_training_steps_per_iter == 2


def test_cli_warmup_spec_parsing():
    from tools.serve_maml import parse_warmup

    assert parse_warmup("5x1x15,20x1x5") == [(5, 1, 15), (20, 1, 5)]
    assert parse_warmup("") == []
    with pytest.raises(ValueError, match="WAYxSHOTxQUERY"):
        parse_warmup("5x1")


def test_cli_pool_mode_requires_warmup(capsys):
    """--replicas without --warmup would deadlock (workers never become
    ready, the pool never routes) — the CLI must refuse up front."""
    from tools.serve_maml import main

    with pytest.raises(SystemExit) as exit_info:
        main(
            [
                "--config", "whatever.json", "--init_from_scratch",
                "--replicas", "2",
            ]
        )
    assert exit_info.value.code == 2
    assert "--warmup" in capsys.readouterr().err
