"""HTTP frontend smoke (tier-1, CPU-only, tiny shapes): boot the server on
an ephemeral port, round-trip one episode, scrape ``/metrics``, shut down
cleanly. Plus the route/validation surface and the ``tools/serve_maml.py``
CLI plumbing (config-JSON learner build, warmup-spec parsing)."""

import json
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.serve import (
    ServeConfig,
    ServingAPI,
    make_http_server,
)


def tiny_cfg():
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=4,
            image_height=8,
            image_width=8,
            num_classes=5,
            per_step_bn_statistics=True,
            num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
    )


@pytest.fixture
def served():
    """A running HTTP server over a tiny fresh-init learner; yields
    ``(base_url, api)`` and guarantees clean shutdown."""
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    api = ServingAPI(
        learner, state, ServeConfig(meta_batch_size=2, max_wait_ms=1.0)
    )
    server = make_http_server(api, port=0)  # ephemeral port
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        yield f"http://127.0.0.1:{port}", api
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=10)
        api.close()
        assert not thread.is_alive(), "server thread must exit on shutdown"


def get_json(url):
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.status, json.load(resp)


def post_episode(base, payload):
    req = urllib.request.Request(
        f"{base}/v1/episode",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.load(resp)


def episode_payload(rng, way=5, shot=1, query=2):
    return {
        "support": rng.rand(way * shot, 1, 8, 8).tolist(),
        "support_labels": np.repeat(np.arange(way), shot).tolist(),
        "query": rng.rand(query, 1, 8, 8).tolist(),
    }


def test_http_roundtrip_and_metrics_scrape(served, rng):
    base, api = served
    status, health = get_json(f"{base}/healthz")
    assert status == 200
    assert health["status"] == "ok" and health["family"] == "maml"

    status, body = post_episode(base, episode_payload(rng))
    assert status == 200
    logits = np.asarray(body["logits"], np.float32)
    assert logits.shape == (2, 5)
    assert body["bucket"] == "5x1x2"
    assert body["cache_hit"] is False
    assert body["predictions"] == np.argmax(logits, axis=-1).tolist()

    with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
        assert resp.status == 200
        text = resp.read().decode()
    assert "maml_serve_requests_total 1" in text
    assert 'maml_serve_adapt_latency_ms{quantile="0.5"}' in text
    assert 'maml_serve_adapt_latency_ms{quantile="0.99"}' in text
    assert "maml_serve_cache_hit_rate" in text
    assert "maml_serve_queue_depth" in text
    assert 'maml_serve_bucket_episodes_total{bucket="5x1x2"} 1' in text
    assert 'maml_serve_program_compiles{program="adapt:2x5"} 1' in text


def test_http_cache_hit_on_repeat_support(served, rng):
    base, _ = served
    payload = episode_payload(rng)
    _, first = post_episode(base, payload)
    _, second = post_episode(base, payload)
    assert first["cache_hit"] is False
    assert second["cache_hit"] is True
    assert second["logits"] == first["logits"]


def test_http_error_surface(served, rng):
    base, _ = served
    # unknown route -> 404
    with pytest.raises(urllib.error.HTTPError) as err:
        get_json(f"{base}/nope")
    assert err.value.code == 404
    # malformed episode -> 400 with the validation message
    bad = episode_payload(rng)
    bad["support_labels"] = bad["support_labels"][:-1]
    with pytest.raises(urllib.error.HTTPError) as err:
        post_episode(base, bad)
    assert err.value.code == 400
    assert "support labels" in json.load(err.value)["error"]
    # missing field -> 400, not a hang or a 500
    with pytest.raises(urllib.error.HTTPError) as err:
        post_episode(base, {"support": []})
    assert err.value.code == 400


# ---------------------------------------------------------------------------
# serve_maml CLI plumbing
# ---------------------------------------------------------------------------


def test_cli_builds_learner_from_experiment_config(tmp_path, monkeypatch):
    from tools.serve_maml import build_learner

    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    cfg_json = {
        "num_stages": 2,
        "cnn_num_filters": 4,
        "num_classes_per_set": 5,
        "image_height": 8,
        "image_width": 8,
        "image_channels": 1,
        "per_step_bn_statistics": True,
        "number_of_training_steps_per_iter": 2,
        "number_of_evaluation_steps_per_iter": 2,
    }
    path = tmp_path / "serve_cfg.json"
    path.write_text(json.dumps(cfg_json))
    learner = build_learner("maml", str(path))
    assert isinstance(learner, MAMLFewShotLearner)
    assert learner.cfg.backbone.num_filters == 4
    assert learner.cfg.backbone.num_classes == 5
    assert learner.cfg.number_of_training_steps_per_iter == 2


def test_cli_warmup_spec_parsing():
    from tools.serve_maml import parse_warmup

    assert parse_warmup("5x1x15,20x1x5") == [(5, 1, 15), (20, 1, 5)]
    assert parse_warmup("") == []
    with pytest.raises(ValueError, match="WAYxSHOTxQUERY"):
        parse_warmup("5x1")
