"""Dispatcher supervision-policy tests (train_maml_system_dispatch.py).

The dispatcher supervises the training process like ``serve/pool.py``
supervises replicas. These tests pin the POLICY — exit-code routing,
per-class budgets, degraded-mesh resume, the re-promotion probe, one-shot
env fault plans — against a scripted stub entry (``MAML_DISPATCH_ENTRY``)
that exits with planned codes and writes planned progress, so the policy is
provable in milliseconds without compiling a single XLA program. The real
end-to-end story (an actually wedged dispatch in the real CLI, detected by
the watchdog, resumed on a smaller virtual mesh) lives in
``tests/test_chaos_train.py``.

Pinned here:

* rc 75 (preemption requeue) re-enters on the SAME mesh and draws only on
  the requeue budget; rc 76 (watchdog hang) degrades the mesh and draws
  only on the hang budget — the code split means the two failure classes
  cannot starve each other's recovery;
* degrade steps dp 8 -> 4 -> 2 -> 1 honoring global-meta-batch
  divisibility, with an audit row per transition;
* two signal deaths in a row are treated like a hang (a crashing device
  looks like a dying worker, not a preemption);
* after a clean phase on a degraded mesh, the re-promotion probe restores
  the next-larger extent;
* ``MAML_FAULTS`` is consumed by the first phase only.
"""

import json
import os
import sys
import textwrap

import pytest

import train_maml_system_dispatch as dispatch


STUB = textwrap.dedent(
    """
    import argparse, json, os, sys

    parser = argparse.ArgumentParser()
    parser.add_argument("--name_of_args_json_file")
    args, _ = parser.parse_known_args()
    with open(args.name_of_args_json_file) as f:
        cfg = json.load(f)

    plan_path = os.environ["STUB_PLAN"]
    with open(plan_path) as f:
        plan = json.load(f)
    step = plan.pop(0)
    with open(plan_path, "w") as f:
        json.dump(plan, f)

    with open(os.environ["STUB_LOG"], "a") as f:
        f.write(json.dumps({
            "dp": cfg.get("data_parallel_devices"),
            "faults": os.environ.get("MAML_FAULTS"),
        }) + "\\n")

    logs = os.path.join(cfg["experiment_name"], "logs")
    os.makedirs(logs, exist_ok=True)
    summary = os.path.join(logs, "summary_statistics.csv")
    for _ in range(step.get("epochs", 0)):
        if not os.path.exists(summary):
            with open(summary, "w") as f:
                f.write("epoch\\n")
        with open(summary, "a") as f:
            f.write("1\\n")
    if step.get("test_eval"):
        with open(os.path.join(logs, "test_summary.csv"), "w") as f:
            f.write("ok\\n")
    sys.exit(step.get("rc", 0))
    """
)


@pytest.fixture
def harness(tmp_path, monkeypatch):
    """Chdir'd scratch repo layout + scripted stub entry; returns a driver
    ``run(plan, cfg_overrides, *extra_argv)`` -> (exit code, invocations,
    audit rows)."""
    monkeypatch.chdir(tmp_path)
    stub_path = tmp_path / "stub_entry.py"
    stub_path.write_text(STUB)
    monkeypatch.setenv(dispatch.ENTRY_ENV, str(stub_path))
    plan_path = tmp_path / "plan.json"
    log_path = tmp_path / "invocations.jsonl"
    monkeypatch.setenv("STUB_PLAN", str(plan_path))
    monkeypatch.setenv("STUB_LOG", str(log_path))
    (tmp_path / "experiment_config").mkdir()

    def run(plan, cfg_overrides=None, *extra_argv):
        cfg = {
            "experiment_name": "exp",
            "total_epochs": 2,
            "num_of_gpus": 1,
            "batch_size": 4,
            "samples_per_iter": 1,
            "data_parallel_devices": 4,
        }
        cfg.update(cfg_overrides or {})
        with open(tmp_path / "experiment_config" / "chaostest.json", "w") as f:
            json.dump(cfg, f)
        plan_path.write_text(json.dumps(plan))
        if log_path.exists():
            log_path.unlink()
        monkeypatch.setattr(
            sys, "argv", ["train_maml_system_dispatch.py", "chaostest",
                          *extra_argv]
        )
        rc = dispatch.main()
        calls = [
            json.loads(line)
            for line in log_path.read_text().splitlines()
        ] if log_path.exists() else []
        audit_path = tmp_path / "exp" / "logs" / "interruptions.csv"
        audit = (
            audit_path.read_text().splitlines()[1:]
            if audit_path.exists() else []
        )
        return rc, calls, audit

    return run


def test_hang_degrades_mesh_requeue_does_not_then_repromotes(harness):
    rc, calls, audit = harness([
        {"rc": dispatch.HANG_EXIT_CODE},            # hang -> dp4 -> dp2
        {"rc": dispatch.REQUEUE_EXIT_CODE},         # preemption: SAME mesh
        {"rc": 0, "epochs": 1},                     # progress -> probe up
        {"rc": 0, "epochs": 1, "test_eval": True},  # finish on dp4
    ])
    assert rc == 0
    assert [c["dp"] for c in calls] == [4, 2, 2, 4]
    kinds = [row.split(",")[1] for row in audit]
    assert "hang-degrade:dp4->dp2" in kinds
    assert "probe-promote:dp4" in kinds


def test_budgets_are_split_and_hang_budget_bounds_the_loop(harness):
    # dp1 with global batch 4: no smaller viable mesh, so hangs requeue on
    # the same topology — and the hang BUDGET (not the requeue or phase
    # budget) bounds the loop. The preceding requeue exits must not
    # consume it.
    rc, calls, audit = harness(
        [
            {"rc": dispatch.REQUEUE_EXIT_CODE},
            {"rc": dispatch.REQUEUE_EXIT_CODE},
            {"rc": dispatch.REQUEUE_EXIT_CODE},
            {"rc": dispatch.HANG_EXIT_CODE},
            {"rc": dispatch.HANG_EXIT_CODE},
        ],
        {"data_parallel_devices": 1},
        "--max_hangs", "2",
    )
    assert rc == dispatch.HANG_EXIT_CODE
    assert len(calls) == 5  # 3 requeues rode the requeue budget, 2 hangs
    kinds = [row.split(",")[1] for row in audit]
    assert kinds.count("hang-requeue:dp1") == 2


def test_requeue_budget_bounds_a_preemption_loop(harness):
    rc, calls, _ = harness(
        [{"rc": dispatch.REQUEUE_EXIT_CODE}] * 3,
        None,
        "--max_requeues", "2",
    )
    assert rc == dispatch.REQUEUE_EXIT_CODE
    assert len(calls) == 2


def test_repeated_signal_death_degrades_like_a_hang(harness):
    rc, calls, audit = harness([
        {"rc": 137},  # SIGKILLed worker: one death could be anything
        {"rc": 137},  # two in a row: suspect the topology
        {"rc": 0, "epochs": 2, "test_eval": True},
    ])
    assert rc == 0
    assert [c["dp"] for c in calls] == [4, 4, 2]
    kinds = [row.split(",")[1] for row in audit]
    assert "repeated-signal-death-degrade:dp4->dp2" in kinds


def test_degrade_honors_global_batch_divisibility(harness):
    # Global meta-batch 6 on dp6: 3 divides, 2 divides, but the half-step
    # search goes 6 -> 3 (first divisor on the way down) — never an extent
    # the meta-batch cannot shard over.
    rc, calls, _ = harness(
        [
            {"rc": dispatch.HANG_EXIT_CODE},
            {"rc": 0, "epochs": 2, "test_eval": True},
        ],
        {"data_parallel_devices": 6, "batch_size": 6},
    )
    assert rc == 0
    assert [c["dp"] for c in calls] == [6, 3]


def test_audit_rows_enriched_with_heartbeat_progress(harness, tmp_path):
    """ISSUE 12: the dispatcher reads the trainer heartbeat
    (logs/status.json, telemetry/heartbeat.py) and stamps last-known
    progress onto its degrade/requeue audit rows — the row says WHERE the
    run was lost, not just that it was."""
    import json as json_module

    logs = tmp_path / "exp" / "logs"
    os.makedirs(logs, exist_ok=True)
    (logs / "status.json").write_text(
        json_module.dumps(
            {"schema": 1, "t": 1.0, "current_iter": 137, "epoch": 4}
        )
    )
    rc, calls, audit = harness([
        {"rc": dispatch.HANG_EXIT_CODE},            # hang -> degrade row
        {"rc": 0, "epochs": 2, "test_eval": True},
    ])
    assert rc == 0
    degrade = next(row for row in audit if "hang-degrade" in row)
    cols = degrade.split(",")
    # Header: timestamp,signal,current_iter,epoch,...
    assert cols[2] == "137" and cols[3] == "4"


def test_audit_rows_tolerate_missing_heartbeat(harness):
    """Pre-heartbeat experiments (or a crash before the first beat) keep
    the old empty-progress rows — enrichment degrades, never breaks."""
    rc, calls, audit = harness([
        {"rc": dispatch.HANG_EXIT_CODE},
        {"rc": 0, "epochs": 2, "test_eval": True},
    ])
    assert rc == 0
    degrade = next(row for row in audit if "hang-degrade" in row)
    cols = degrade.split(",")
    assert cols[2] == "" and cols[3] == ""


def test_dispatcher_exports_one_trace_id_to_children(harness, monkeypatch):
    """Every phase of a supervised run (and so every rank of a fleet
    phase) inherits ONE MAML_TRACE_ID, making the whole elastic lifecycle
    a single merged timeline; an operator-provided id wins."""
    monkeypatch.delenv(dispatch.TRACE_ID_ENV, raising=False)
    seen = []
    real_run = dispatch.subprocess.run

    def spying_run(argv, check=False, env=None):
        seen.append((env or {}).get(dispatch.TRACE_ID_ENV))
        return real_run(argv, check=check, env=env)

    monkeypatch.setattr(dispatch.subprocess, "run", spying_run)
    rc, _calls, _audit = harness([
        {"rc": 0, "epochs": 1},
        {"rc": 0, "epochs": 1, "test_eval": True},
    ])
    assert rc == 0
    assert len(seen) == 2
    assert seen[0] and seen[0] == seen[1]  # one id, every phase

    seen.clear()
    import shutil

    shutil.rmtree("exp")  # fresh experiment: the finished run short-circuits
    monkeypatch.setenv(dispatch.TRACE_ID_ENV, "operator-trace")
    rc, _calls, _audit = harness([
        {"rc": 0, "epochs": 2, "test_eval": True},
    ])
    assert rc == 0
    assert seen == ["operator-trace"]  # inherited id wins


def test_env_fault_plan_is_consumed_by_first_phase_only(harness, monkeypatch):
    monkeypatch.setenv("MAML_FAULTS", "hang_at_iter=3")
    rc, calls, _ = harness([
        {"rc": dispatch.HANG_EXIT_CODE},
        {"rc": 0, "epochs": 2, "test_eval": True},
    ])
    assert rc == 0
    assert calls[0]["faults"] == "hang_at_iter=3"
    assert calls[1]["faults"] is None  # a degraded phase replays clean


def test_degraded_dp_extent_divisibility_edges():
    """The half-step ladder skips extents the run's own constraints refuse
    — global-batch divisibility AND an active --task_chunk multiple — and
    honestly returns None when nothing smaller divides."""
    from howtotrainyourmamlpytorch_tpu.parallel import degraded_dp_extent

    # Clean powers of two: plain halving.
    assert degraded_dp_extent(8, global_batch=16) == 4
    assert degraded_dp_extent(2, global_batch=16) == 1
    # dp already 1: no smaller extent exists.
    assert degraded_dp_extent(1, global_batch=16) is None
    # Batch divisibility skips a rung: 10 % 4 != 0, so 8 → (4 refused)
    # → 2.
    assert degraded_dp_extent(8, global_batch=10) == 2
    # Odd batch: only dp 1 divides everything.
    assert degraded_dp_extent(8, global_batch=7) == 1
    # Active task_chunk must ALSO be a multiple of the candidate
    # (sharding.guard_task_chunk): chunk 2 refuses dp 4, lands on 2.
    assert degraded_dp_extent(8, global_batch=16, task_chunk=2) == 2
    # chunk 1 forces all the way down to dp 1.
    assert degraded_dp_extent(8, global_batch=16, task_chunk=1) == 1
    # task_chunk <= 0 means inactive: no constraint.
    assert degraded_dp_extent(4, global_batch=8, task_chunk=0) == 2
    assert degraded_dp_extent(4, global_batch=8, task_chunk=-1) == 2
    # Non-power-of-two dp halves via integer division: 6 → 3 → 1.
    assert degraded_dp_extent(6, global_batch=9) == 3
    # ...but 3 is skipped when the batch refuses it: 6 → (3 refused) → 1.
    assert degraded_dp_extent(6, global_batch=8) == 1
    # A chunk that divides no intermediate rung still lands on dp 1 —
    # every chunk is a multiple of 1, so a viable single-device fallback
    # always exists once the batch divides (it always does at 1).
    assert degraded_dp_extent(4, global_batch=4, task_chunk=3) == 1
