"""ResNet-12 backbone tests (BASELINE.json config #4 architecture).

The reference has no residual backbone, so there is no parity target; these
tests pin the architecture's structure (shapes, residual path, per-step BN
threading), its behavior under the MAML meta-gradient (second order through
the scan), and its integration surface (config mapping, optimizer masks,
mesh sharding rules).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
    ResNet12Backbone,
    build_backbone,
)


def resnet_cfg(**kw):
    defaults = dict(
        architecture="resnet12",
        num_filters=4,
        num_classes=3,
        image_channels=3,
        image_height=16,
        image_width=16,
        per_step_bn_statistics=True,
        num_steps=2,
    )
    defaults.update(kw)
    return BackboneConfig(**defaults)


def maml_cfg(**kw):
    defaults = dict(
        backbone=resnet_cfg(),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        total_iter_per_epoch=4,
        total_epochs=3,
    )
    defaults.update(kw)
    return MAMLConfig(**defaults)


def tiny_batch(rng, b=2, n=3, k=2, t=2, c=3, h=16, w=16):
    xs = rng.randn(b, n, k, c, h, w).astype(np.float32)
    xt = rng.randn(b, n, t, c, h, w).astype(np.float32)
    ys = np.tile(np.arange(n)[None, :, None], (b, 1, k)).astype(np.float32)
    yt = np.tile(np.arange(n)[None, :, None], (b, 1, t)).astype(np.float32)
    return xs, xt, ys, yt


def test_factory_dispatch():
    assert isinstance(build_backbone(resnet_cfg()), ResNet12Backbone)
    with pytest.raises(ValueError):
        build_backbone(resnet_cfg(architecture="nope"))
    with pytest.raises(ValueError):
        build_backbone(resnet_cfg(norm_layer="layer_norm"))


def test_forward_shapes_and_structure():
    bb = build_backbone(resnet_cfg())
    params, bn = bb.init(jax.random.key(0))
    assert bb.widths == (4, 8, 16, 32)
    assert bb.feature_dim == 32
    # 4 stages x (3 convs + shortcut), each {conv: w+b, norm: gamma+beta},
    # plus the linear head.
    assert len(jax.tree.leaves(params)) == 4 * 4 * 4 + 2
    logits, new_bn = bb.apply(params, bn, jnp.ones((5, 3, 16, 16)), 0)
    assert logits.shape == (5, 3)
    # Per-step BN arrays: (S, F) rows, step 0 written, step 1 untouched.
    st = new_bn["res0"]["conv0"]
    assert st.running_mean.shape == (2, 4)
    assert not np.allclose(st.running_mean[0], 0.0)
    assert np.allclose(st.running_mean[1], 0.0)


def test_explicit_widths():
    bb = build_backbone(resnet_cfg(resnet_widths=(4, 6, 8, 10)))
    params, bn = bb.init(jax.random.key(0))
    assert bb.widths == (4, 6, 8, 10)
    assert params["res2"]["conv0"]["conv"]["weight"].shape == (8, 6, 3, 3)
    logits, _ = bb.apply(params, bn, jnp.ones((2, 3, 16, 16)), 0)
    assert logits.shape == (2, 3)


def test_residual_path_contributes():
    """Zeroing the conv trunk must still propagate the input via the
    shortcut: logits respond to the input through the projection path."""
    bb = build_backbone(resnet_cfg(per_step_bn_statistics=False))
    params, bn = bb.init(jax.random.key(0))
    # Zero only the trunk convs; keep shortcuts and the head.
    zeroed = {k: dict(v) for k, v in params.items() if k != "linear"}
    zeroed["linear"] = params["linear"]
    for i in range(4):
        for j in range(3):
            zeroed[f"res{i}"][f"conv{j}"] = jax.tree.map(
                jnp.zeros_like, params[f"res{i}"][f"conv{j}"]
            )
    r = np.random.RandomState(3)
    x1 = jnp.asarray(r.randn(2, 3, 16, 16), jnp.float32)
    x2 = jnp.asarray(r.randn(2, 3, 16, 16), jnp.float32)
    l1, _ = bb.apply(zeroed, bn, x1, 0)
    l2, _ = bb.apply(zeroed, bn, x2, 0)
    assert not np.allclose(np.asarray(l1), np.asarray(l2))


def test_inner_loop_mask_excludes_norm():
    bb = build_backbone(resnet_cfg())
    params, _ = bb.init(jax.random.key(0))
    mask = bb.inner_loop_mask(params)
    assert mask["res0"]["conv0"]["conv"]["weight"] is True
    assert mask["res0"]["conv0"]["norm"]["gamma"] is False
    assert mask["res0"]["shortcut"]["norm"]["beta"] is False
    assert mask["linear"]["weight"] is True
    mask_bn = build_backbone(
        resnet_cfg(enable_inner_loop_optimizable_bn_params=True)
    ).inner_loop_mask(params)
    assert mask_bn["res0"]["conv0"]["norm"]["gamma"] is True


def test_second_order_maml_train_decreases_loss(rng):
    learner = MAMLFewShotLearner(maml_cfg(second_order=True))
    state = learner.init_state(jax.random.key(0))
    batch = tiny_batch(rng)
    losses = []
    for _ in range(8):
        state, metrics = learner.run_train_iter(state, batch, epoch=0)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_eval_contract_and_bn_state_untouched(rng):
    learner = MAMLFewShotLearner(maml_cfg())
    state = learner.init_state(jax.random.key(0))
    before = jax.tree.map(np.asarray, state.bn_state)
    _, losses, _ = learner.run_validation_iter(state, tiny_batch(rng))
    assert np.isfinite(float(losses["loss"]))
    after = state.bn_state
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        before, after,
    )


def test_args_mapping_selects_resnet(monkeypatch, tmp_path):
    from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
        args_to_maml_config, get_parser, Bunch,
    )

    args = Bunch(vars(get_parser().parse_args([
        "--architecture_name", "ResNet12",
        "--resnet_widths", "8", "16", "32", "64",
        "--num_classes_per_set", "5",
        "--per_step_bn_statistics", "True",
        "--number_of_training_steps_per_iter", "5",
        "--number_of_evaluation_steps_per_iter", "5",
    ])))
    args.per_step_bn_statistics = True
    cfg = args_to_maml_config(args)
    assert cfg.backbone.architecture == "resnet12"
    assert cfg.backbone.resnet_widths == (8, 16, 32, 64)
    assert isinstance(build_backbone(cfg.backbone), ResNet12Backbone)
    # Default (architecture_name unset) stays VGG.
    args2 = Bunch(vars(get_parser().parse_args([])))
    assert args_to_maml_config(args2).backbone.architecture == "vgg"


def test_mp_sharding_rules_cover_resnet_tree():
    """parallel/mesh.param_shardings must shard resnet conv filters over mp
    and BN affine rows over their feature axis without new rules."""
    from jax.sharding import PartitionSpec as P

    from howtotrainyourmamlpytorch_tpu.parallel.mesh import (
        make_mesh, param_shardings,
    )

    from jax.sharding import NamedSharding

    bb = build_backbone(resnet_cfg(num_filters=4))
    params, _ = bb.init(jax.random.key(0))
    mesh = make_mesh(jax.devices()[:4], data_parallel=2, model_parallel=2)
    shardings = param_shardings(mesh, params, shard_model=True)

    def same_layout(sharding, spec, leaf):
        # The declarative rule tables emit rank-truncated specs (P('mp')
        # leaves trailing axes replicated) — compare LAYOUTS, not tuples.
        return sharding.is_equivalent_to(
            NamedSharding(mesh, spec), leaf.ndim
        )

    w = params["res0"]["conv0"]["conv"]["weight"]
    assert same_layout(
        shardings["res0"]["conv0"]["conv"]["weight"],
        P("mp", None, None, None), w,
    )
    assert same_layout(
        shardings["res0"]["conv0"]["norm"]["gamma"],
        P(None, "mp"), params["res0"]["conv0"]["norm"]["gamma"],
    )
    assert same_layout(
        shardings["res0"]["shortcut"]["conv"]["weight"],
        P("mp", None, None, None),
        params["res0"]["shortcut"]["conv"]["weight"],
    )
    assert same_layout(
        shardings["linear"]["weight"], P(None, "mp"),
        params["linear"]["weight"],
    )


def test_dp_sharded_train_iter_runs(rng, spmd_compile_guard):
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(jax.devices()[:2], data_parallel=2, model_parallel=1)
    learner = MAMLFewShotLearner(maml_cfg(), mesh=mesh)
    state = learner.init_state(jax.random.key(0))
    state, metrics = learner.run_train_iter(state, tiny_batch(rng, b=2), epoch=0)
    assert np.isfinite(float(metrics["loss"]))


def test_config_validation_fails_fast():
    with pytest.raises(ValueError):
        build_backbone(resnet_cfg(resnet_widths=(4, 6, 8)))
    from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
        args_to_maml_config, get_parser, Bunch,
    )
    args = Bunch(vars(get_parser().parse_args(
        ["--architecture_name", "restnet12"]
    )))
    with pytest.raises(ValueError):
        args_to_maml_config(args)
    assert resnet_cfg(num_filters=4).feature_dim == 32
    assert resnet_cfg(resnet_widths=(4, 6, 8, 10)).feature_dim == 10
