"""Mesh-portable checkpoints (ISSUE 8): save on one mesh shape, resume on
another, bit-exact.

``CheckpointableLearner.save_model`` gathers sharded leaves to full host
arrays before serializing, so the archive (and its PR 3 manifest: per-leaf
CRCs, tree fingerprint) is MESH-INDEPENDENT; ``load_model`` re-shards the
restored state onto whatever mesh the RESUMING learner carries. Covered
here: save under the 8-device mesh and restore under 4/2-device meshes and
a single device (and the reverse), params bit-exact every way; the archive
a mesh run writes is byte-for-byte the same manifest a single-device run
writes for the same values; and the PR 3 corrupt/mismatch typed-error
behavior is unchanged through the mesh path.

No sharded CONV program is compiled anywhere here (``shard_state`` /
``gather_state`` are layout ops, not program compiles), so these tests run
on every backend — including jaxlibs whose GSPMD partitioner CHECK-crashes
on sharded conv compiles.
"""

import json
import os

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.parallel import make_mesh
from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
    _MANIFEST_KEY,
    CheckpointCorruptError,
)


def cfg(num_filters=4):
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=num_filters,
            num_classes=5,
            image_height=8,
            image_width=8,
            num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        second_order=False,
    )


def dp_mesh(n):
    return make_mesh(jax.devices()[:n], data_parallel=n, model_parallel=1)


def learner_on(n_devices):
    """A learner on an n-device dp mesh (None = single device)."""
    mesh = dp_mesh(n_devices) if n_devices > 1 else None
    return MAMLFewShotLearner(cfg(), mesh=mesh)


def host_leaves(learner, state):
    return [np.asarray(x) for x in jax.tree.leaves(learner.gather_state(state))]


EXP = {"current_iter": 17, "best_val_acc": 0.5}


@pytest.mark.parametrize("restore_devices", [1, 2, 4])
def test_save_on_8_restore_on_other_mesh_shapes_bit_exact(
    tmp_path, restore_devices
):
    writer = learner_on(8)
    state = writer.shard_state(writer.init_state(jax.random.PRNGKey(5)))
    path = os.path.join(tmp_path, "train_model_3")
    writer.save_model(path, state, dict(EXP))

    reader = learner_on(restore_devices)
    restored, exp = reader.load_model(str(tmp_path), "train_model", 3)
    assert exp == EXP
    for a, b in zip(host_leaves(writer, state), host_leaves(reader, restored)):
        np.testing.assert_array_equal(a, b)
    if reader.mesh is not None:
        # The restored state actually LIVES on the resuming mesh shape.
        for leaf in jax.tree.leaves(restored):
            assert isinstance(leaf.sharding, NamedSharding)
            assert leaf.sharding.mesh.shape == reader.mesh.shape


def test_save_single_device_restore_on_8_device_mesh(tmp_path):
    """The reverse direction: a pre-mesh checkpoint resumes onto a mesh."""
    writer = learner_on(1)
    state = writer.init_state(jax.random.PRNGKey(6))
    path = os.path.join(tmp_path, "train_model_0")
    writer.save_model(path, state, dict(EXP))

    reader = learner_on(8)
    restored, _ = reader.load_model(str(tmp_path), "train_model", 0)
    for a, b in zip(host_leaves(writer, state), host_leaves(reader, restored)):
        np.testing.assert_array_equal(a, b)
    for leaf in jax.tree.leaves(restored):
        assert leaf.sharding.mesh.shape == reader.mesh.shape


def test_archive_manifest_is_mesh_independent(tmp_path):
    """The same state values produce the same manifest (leaf CRCs + tree
    fingerprint) whether saved from a sharded or a single-device learner —
    the fingerprint a resume verifies can never depend on the writer's
    topology."""
    single = learner_on(1)
    state = single.init_state(jax.random.PRNGKey(9))
    sharded = learner_on(8)
    state_s = sharded.shard_state(state)

    p1 = os.path.join(tmp_path, "train_model_1")
    p8 = os.path.join(tmp_path, "train_model_8")
    single.save_model(p1, state, dict(EXP))
    sharded.save_model(p8, state_s, dict(EXP))

    def manifest(path):
        with np.load(path) as archive:
            return json.loads(bytes(archive[_MANIFEST_KEY]).decode())

    m1, m8 = manifest(p1), manifest(p8)
    assert m1["leaf_crc32"] == m8["leaf_crc32"]
    assert m1["tree_crc32"] == m8["tree_crc32"]
    assert m1["leaf_count"] == m8["leaf_count"]


def test_corrupt_archive_stays_typed_through_the_mesh_path(tmp_path):
    """PR 3 contract unchanged: truncation surfaces as the quarantinable
    ``CheckpointCorruptError`` (not a shard/layout error) when the READER
    is a mesh learner."""
    writer = learner_on(8)
    state = writer.shard_state(writer.init_state(jax.random.PRNGKey(2)))
    path = os.path.join(tmp_path, "train_model_2")
    writer.save_model(path, state, dict(EXP))

    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 2])
    with pytest.raises(CheckpointCorruptError):
        learner_on(4).load_model(str(tmp_path), "train_model", 2)


def test_architecture_mismatch_stays_valueerror_through_the_mesh_path(
    tmp_path,
):
    """PR 3's corrupt-vs-mismatch split survives re-sharding: an archive
    from a DIFFERENT architecture fails fast as ValueError before any
    device_put happens."""
    writer = learner_on(8)
    state = writer.shard_state(writer.init_state(jax.random.PRNGKey(4)))
    path = os.path.join(tmp_path, "train_model_7")
    writer.save_model(path, state, dict(EXP))

    mesh = dp_mesh(4)
    other = MAMLFewShotLearner(cfg(num_filters=8), mesh=mesh)
    with pytest.raises(ValueError) as err:
        other.load_model(str(tmp_path), "train_model", 7)
    assert not isinstance(err.value, CheckpointCorruptError)
