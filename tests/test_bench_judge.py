"""Mechanical bench keep/revert judge (tools/bench_judge.py).

Two layers of pins:

* **Unit**: synthetic trajectories exercising each verdict class — keep,
  revert, regress, pending — plus contention-sentinel handling, baseline
  selection across null-valued runs, the restricted gate-expression
  grammar, and the stale-key detectors.
* **Tier-1 regression gate** (the ISSUE 12 acceptance): the judge runs via
  the real CLI over the checked-in ``BENCH_r01..r03`` trajectory — every
  gated key classified, exit 0 (nothing regressed at HEAD) — and a
  deliberately-degraded synthetic ``r04`` flips the headline key to
  ``regress`` with a non-zero exit, so a perf claim can never rot
  silently once a worse emission lands.

Coverage pins keep the gate data honest: every ``bench.EMITTED_KEYS``
entry is either gated or explicitly ``ungated_ok``; every bench-sourced
gate key is still emitted; every gate entry naming a PERF_NOTES section
actually appears in PERF_NOTES.md (prose and gate data cannot diverge
silently).
"""

import json
import os
import subprocess
import sys

import pytest

from tools import bench_judge

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_trajectory(tmp_path, runs):
    """Writes synthetic emission files; returns their paths oldest-first.
    Each run is a parsed-payload dict (the raw bench.py emission form)."""
    paths = []
    for i, parsed in enumerate(runs):
        path = tmp_path / f"BENCH_t{i + 1:02d}.json"
        path.write_text(json.dumps({"n": i + 1, "parsed": parsed}))
        paths.append(str(path))
    return paths


def _gates(gates, ungated_ok=(), default_tolerance=0.08):
    return {
        "schema": 1,
        "default_tolerance": default_tolerance,
        "ungated_ok": list(ungated_ok),
        "gates": gates,
    }


# ---------------------------------------------------------------------------
# Verdict classes
# ---------------------------------------------------------------------------


def test_keep_revert_pending_each_classified(tmp_path):
    gates = _gates({
        "rate": {"gate": None, "direction": "higher"},
        "lever_rate": {"gate": "this >= 1.1 * rate", "direction": "higher"},
        "bad_lever_rate": {"gate": "this >= 2.0 * rate",
                           "direction": "higher"},
        "unmeasured": {"gate": "this >= 0.5", "direction": "higher"},
        "future_gate": {"gate": "this >= 1.0", "direction": "higher",
                        "gate_from_run": 9},
    })
    runs = bench_judge.load_trajectory(_write_trajectory(tmp_path, [
        {"rate": 100.0, "lever_rate": 130.0, "bad_lever_rate": 120.0,
         "future_gate": 5.0},
    ]))
    result = bench_judge.judge(gates, runs)
    v = result["verdicts"]
    assert v["rate"]["verdict"] == "keep"          # no bar, tracked only
    assert v["lever_rate"]["verdict"] == "keep"    # 130 >= 1.1 * 100
    assert v["bad_lever_rate"]["verdict"] == "revert"  # 120 < 200
    assert v["unmeasured"]["verdict"] == "pending"
    # The pending-until-TPU marker: the lever shipped after this capture.
    assert v["future_gate"]["verdict"] == "pending"
    assert "run 9" in v["future_gate"]["reason"]
    assert result["regressions"] == []
    # Every gated key got exactly one verdict — no unclassified keys.
    assert set(v) == set(gates["gates"])


def test_regress_flips_on_degraded_run_and_dominates_gate(tmp_path):
    gates = _gates({
        "rate": {"gate": "this >= 1.0", "direction": "higher",
                 "tolerance": 0.1},
    })
    runs = bench_judge.load_trajectory(_write_trajectory(tmp_path, [
        {"rate": 100.0},
        {"rate": 50.0},  # 50% drop >> 10% tolerance — but gate still holds
    ]))
    result = bench_judge.judge(gates, runs)
    assert result["verdicts"]["rate"]["verdict"] == "regress"
    assert result["verdicts"]["rate"]["prior"] == 100.0
    assert result["regressions"] == ["rate"]


def test_tolerance_absorbs_noise_and_lower_direction(tmp_path):
    gates = _gates({
        "rate": {"gate": None, "direction": "higher", "tolerance": 0.1},
        "latency_ms": {"gate": None, "direction": "lower",
                       "tolerance": 0.1},
        "overhead_pct": {"gate": None, "direction": "lower",
                         "tolerance": 0.5, "abs_slack": 1.0},
    })
    runs = bench_judge.load_trajectory(_write_trajectory(tmp_path, [
        {"rate": 100.0, "latency_ms": 10.0, "overhead_pct": -0.2},
        # rate -5% (inside 10%), latency +5% (inside), overhead crosses
        # zero but stays inside the absolute slack that exists for
        # near-zero keys (a pure relative tolerance on -0.2 would flag
        # +0.3 as a regression).
        {"rate": 95.0, "latency_ms": 10.5, "overhead_pct": 0.3},
    ]))
    result = bench_judge.judge(gates, runs)
    assert result["regressions"] == []
    runs2 = bench_judge.load_trajectory(_write_trajectory(tmp_path, [
        {"latency_ms": 10.0}, {"latency_ms": 20.0},
    ]))
    gates2 = _gates({"latency_ms": {"gate": None, "direction": "lower",
                                    "tolerance": 0.1}})
    assert bench_judge.judge(gates2, runs2)["regressions"] == ["latency_ms"]


def test_contended_emission_is_never_baseline_nor_judged(tmp_path):
    """The contention sentinel honored both ways: a contended latest run
    is skipped (the previous accepted run stays the judged one — a
    poisoned number can't manufacture a regression), and a contended
    middle run never becomes the regression baseline."""
    gates = _gates({"rate": {"gate": None, "direction": "higher",
                             "tolerance": 0.1}})
    runs = bench_judge.load_trajectory(_write_trajectory(tmp_path, [
        {"rate": 100.0},
        {"rate": 500.0, "contended": True},   # poisoned high reading
        {"rate": 101.0},
        {"rate": 10.0, "contended": True},    # poisoned low reading, latest
    ]))
    result = bench_judge.judge(gates, runs)
    assert result["accepted_run"].endswith("t03.json")
    assert set(result["skipped_contended"]) == {
        "BENCH_t02.json", "BENCH_t04.json"
    }
    # Judged 101 vs prior 100 — neither poisoned reading participated.
    assert result["verdicts"]["rate"]["verdict"] == "keep"
    assert result["verdicts"]["rate"]["prior"] == 100.0
    assert result["regressions"] == []


def test_all_contended_trajectory_refuses(tmp_path):
    gates = _gates({"rate": {"gate": None}})
    runs = bench_judge.load_trajectory(_write_trajectory(tmp_path, [
        {"rate": 1.0, "contended": True},
    ]))
    with pytest.raises(ValueError, match="contended"):
        bench_judge.judge(gates, runs)


def test_baseline_selection_skips_null_valued_runs(tmp_path):
    """The regression baseline is the newest EARLIER accepted run that
    actually measured the key — null/absent emissions (a skipped extra)
    must not erase the history."""
    gates = _gates({"rate": {"gate": None, "direction": "higher",
                             "tolerance": 0.1}})
    runs = bench_judge.load_trajectory(_write_trajectory(tmp_path, [
        {"rate": 100.0},
        {"rate": None},     # measurement skipped that round
        {"rate": 80.0},     # vs 100 — a 20% regression
    ]))
    result = bench_judge.judge(gates, runs)
    assert result["verdicts"]["rate"]["prior_run"] == "BENCH_t01.json"
    assert result["verdicts"]["rate"]["verdict"] == "regress"


def test_gate_expression_grammar_is_restricted():
    assert bench_judge.eval_gate("this >= 0.5 * rate",
                                 {"this": 60.0, "rate": 100.0}) is True
    assert bench_judge.eval_gate("this >= 0.75 and this <= 1.0",
                                 {"this": 0.8}) is True
    assert bench_judge.eval_gate("this >= 1", {"this": True}) is True
    # Unmeasured reference -> None (judges as pending, never as a pass).
    assert bench_judge.eval_gate("this >= rate", {"this": 1.0}) is None
    assert bench_judge.eval_gate("this >= rate",
                                 {"this": 1.0, "rate": None}) is None
    for bad in ("__import__('os')", "this.x > 1", "f(this)", "this >= 'a'"):
        with pytest.raises(ValueError):
            bench_judge.eval_gate(bad, {"this": 1.0})


def test_stale_key_detection(tmp_path):
    """The judge lists gate keys the emission lacks, gate keys bench no
    longer declares, and emitted keys with neither a gate nor an
    ungated_ok entry — bench key drift is a review-time finding."""
    gates = _gates(
        {
            "rate": {"gate": None},
            "ghost_key": {"gate": None, "source": "bench.py"},
        },
        ungated_ok=["meta"],
    )
    runs = bench_judge.load_trajectory(_write_trajectory(tmp_path, [
        {"rate": 1.0, "meta": "x", "surprise_key": 2.0},
    ]))
    result = bench_judge.judge(gates, runs)
    assert result["verdicts"]["ghost_key"]["verdict"] == "pending"
    assert "ghost_key" in result["stale"]["missing_from_latest"]
    # ghost_key is not in bench.EMITTED_KEYS -> a stale gate.
    assert "ghost_key" in result["stale"]["stale_gates"]
    assert "surprise_key" in result["stale"]["ungated_keys"]


def test_program_registry_names_parses_jax_free():
    """The judge AST-parses ``PROGRAM_REGISTRY_NAMES`` from
    models/common.py without importing it (no jax in this tool) — the
    program-derived gates' declaration surface, sibling of
    ``bench_emitted_keys``."""
    names = bench_judge.program_registry_names()
    assert isinstance(names, tuple)
    assert "maml/train_multi" in names
    assert "maml/train_step" in names
    assert all(isinstance(n, str) for n in names)


def test_program_sourced_gate_stale_only_when_registry_drops_it(tmp_path):
    """A gate with source ``programs:<name>`` is judged against the live
    program registry table: a ghost program name is a stale gate even
    when the KEY is still bench-emitted; a registered name is not."""
    gates = _gates({
        "comm_bytes_per_iter": {
            "gate": None, "source": "programs:maml/train_multi",
        },
        "mfu_pct": {"gate": None, "source": "programs:ghost/name"},
    })
    runs = bench_judge.load_trajectory(_write_trajectory(tmp_path, [
        {"comm_bytes_per_iter": 1428, "mfu_pct": 3.8},
    ]))
    result = bench_judge.judge(gates, runs)
    assert "mfu_pct" in result["stale"]["stale_gates"]
    assert "comm_bytes_per_iter" not in result["stale"]["stale_gates"]


def test_raw_emission_payloads_load_too(tmp_path):
    """A trajectory of raw one-line bench.py payloads (no driver wrapper)
    judges identically — the judge must accept what the tool prints."""
    path = tmp_path / "raw.json"
    path.write_text(json.dumps({"rate": 5.0}))
    runs = bench_judge.load_trajectory([str(path)])
    assert runs[0]["parsed"]["rate"] == 5.0
    assert runs[0]["n"] == 1


# ---------------------------------------------------------------------------
# Gate-data coverage: bench.EMITTED_KEYS <-> bench_gates.json <-> PERF_NOTES
# ---------------------------------------------------------------------------


def test_every_bench_key_is_gated_or_explicitly_ungated():
    emitted = bench_judge.bench_emitted_keys()
    assert emitted, "bench.py lost its EMITTED_KEYS literal"
    doc = bench_judge.load_gates(bench_judge.DEFAULT_GATES_PATH)
    known = set(doc["gates"]) | set(doc["ungated_ok"])
    uncovered = sorted(set(emitted) - known)
    assert uncovered == [], (
        f"bench keys with no gate and no ungated_ok entry: {uncovered} — "
        "add them to tools/bench_gates.json"
    )


def test_no_stale_gates_at_head():
    emitted = set(bench_judge.bench_emitted_keys() or ())
    doc = bench_judge.load_gates(bench_judge.DEFAULT_GATES_PATH)
    stale = sorted(
        key for key, spec in doc["gates"].items()
        if spec.get("source", "bench.py") == "bench.py"
        and key not in emitted
    )
    assert stale == [], (
        f"gates for keys bench.py no longer emits: {stale}"
    )


def test_checked_in_emissions_only_use_declared_keys():
    """Every key of the newest checked-in emission is declared in
    bench.EMITTED_KEYS — the declaration the judge's coverage checks hang
    off really describes what the tool prints."""
    emitted = set(bench_judge.bench_emitted_keys() or ())
    with open(os.path.join(REPO, "BENCH_r03.json")) as f:
        parsed = json.load(f)["parsed"]
    undeclared = sorted(set(parsed) - emitted)
    assert undeclared == [], undeclared


def test_perf_notes_sections_name_their_gate_keys():
    """Prose/gate coupling: a gate entry naming a PERF_NOTES section means
    that section's keep/revert table cites the key — both the section
    heading and the key string must exist in PERF_NOTES.md."""
    doc = bench_judge.load_gates(bench_judge.DEFAULT_GATES_PATH)
    with open(os.path.join(REPO, "PERF_NOTES.md")) as f:
        notes = f.read()
    for key, spec in doc["gates"].items():
        section = spec.get("perf_notes")
        if not section:
            continue
        assert section in notes, (
            f"gate {key} cites PERF_NOTES section {section!r}, not found"
        )
        assert key in notes, (
            f"gate key {key} is absent from PERF_NOTES.md — annotate the "
            f"{section!r} keep/revert table with it"
        )


def test_every_gate_expression_parses():
    doc = bench_judge.load_gates(bench_judge.DEFAULT_GATES_PATH)
    for key, spec in doc["gates"].items():
        expr = spec.get("gate")
        if expr:
            # Must parse under the restricted grammar; evaluation with
            # an empty env must be None (pending), never an exception.
            assert bench_judge.eval_gate(expr, {}) is None or isinstance(
                bench_judge.eval_gate(expr, {}), bool
            ), key


# ---------------------------------------------------------------------------
# Tier-1 regression gate through the real CLI (the ISSUE 12 acceptance)
# ---------------------------------------------------------------------------


def _run_cli(*argv, cwd=REPO):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "tools.bench_judge", *argv],
        capture_output=True, text=True, cwd=cwd, env=env, timeout=120,
    )


def test_checked_in_trajectory_judges_clean_via_cli():
    """THE tier-1 gate: the judge over BENCH_r01..r03 emits a verdict for
    every gated key, finds no regression at HEAD, and exits 0. The day a
    worse emission is checked in, this test fails — a perf claim cannot
    silently rot."""
    proc = _run_cli("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    result = json.loads(proc.stdout)
    doc = bench_judge.load_gates(bench_judge.DEFAULT_GATES_PATH)
    # Every gated key classified — no unclassified keys.
    assert set(result["verdicts"]) == set(doc["gates"])
    for key, entry in result["verdicts"].items():
        assert entry["verdict"] in bench_judge.VERDICT_ORDER, key
    assert result["regressions"] == []
    assert result["accepted_run"] == "BENCH_r03.json"
    # The seven-plus TPU-pending acceptance gates all await their capture.
    assert result["counts"]["pending"] >= 7
    # Nothing stale at HEAD: the gates file covers the declared surface.
    assert result["stale"]["stale_gates"] == []
    assert result["stale"]["ungated_keys"] == []


def test_degraded_synthetic_run_flips_regress_via_cli(tmp_path):
    """Appending a deliberately-degraded r04 (headline halved, sentinel
    clean) to the real trajectory flips the headline key to ``regress``
    and the CLI to a non-zero exit."""
    paths = []
    for name in ("BENCH_r01.json", "BENCH_r02.json", "BENCH_r03.json"):
        paths.append(os.path.join(REPO, name))
    with open(paths[-1]) as f:
        degraded = json.load(f)
    degraded["n"] = 4
    degraded["parsed"] = dict(
        degraded["parsed"],
        value=degraded["parsed"]["value"] / 2.0,
        contended=False,
    )
    r04 = tmp_path / "BENCH_r04.json"
    r04.write_text(json.dumps(degraded))
    proc = _run_cli("--json", "--trajectory", *paths, str(r04))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    result = json.loads(proc.stdout)
    assert result["verdicts"]["value"]["verdict"] == "regress"
    assert "value" in result["regressions"]
    # The un-degraded keys keep their classifications.
    assert result["verdicts"]["bf16_meta_iters_per_s"]["verdict"] == "keep"

    # The same degraded run marked contended is SKIPPED, not a regression
    # (the sentinel's whole point: a poisoned number can't fail CI).
    degraded["parsed"]["contended"] = True
    r04.write_text(json.dumps(degraded))
    proc2 = _run_cli("--json", "--trajectory", *paths, str(r04))
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr
    result2 = json.loads(proc2.stdout)
    assert result2["accepted_run"] == "BENCH_r03.json"
    assert result2["regressions"] == []


def test_cli_human_table_renders():
    proc = _run_cli()
    assert proc.returncode == 0, proc.stderr
    assert "bench judge" in proc.stdout
    assert "pending" in proc.stdout and "keep" in proc.stdout


def test_trace_id_env_name_matches_dispatcher():
    """The dispatcher duplicates TRACE_ID_ENV (stdlib-only import
    surface); the two constants must never drift."""
    import train_maml_system_dispatch as dispatch
    from howtotrainyourmamlpytorch_tpu.telemetry import events

    assert dispatch.TRACE_ID_ENV == events.TRACE_ID_ENV
