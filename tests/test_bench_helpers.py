"""Unit tests for bench.py's timing helpers (the driver-gate script).

bench's measurement functions need a TPU + datasets, but the windowing
math they share is plain Python — covered here so a refactor can't
silently change the reported statistic (the driver records bench output
as the round's official number).
"""

import bench


def test_windowed_rates_median_peak_mean():
    windows = iter([(10, 1.0), (10, 2.0), (10, 4.0)])  # 10, 5, 2.5 u/s
    median, peak, mean = bench._windowed_rates(3, lambda: next(windows))
    assert median == 5.0
    assert peak == 10.0
    # mean is duration-weighted: 30 units over 7 s
    assert abs(mean - 30 / 7.0) < 1e-12


def test_windowed_rates_even_count_is_true_median():
    # Even window counts must interpolate, not pick the upper-middle value
    # (upper-middle would re-introduce an upward bias under one-sided
    # contention dips).
    windows = iter([(10, 1.0), (10, 1.0), (10, 2.0), (10, 2.0)])
    median, _, _ = bench._windowed_rates(4, lambda: next(windows))
    assert median == 7.5  # (10 + 5) / 2


def test_quiet_sentinel_norm_env_override(monkeypatch):
    monkeypatch.setenv("BENCH_QUIET_SENTINEL_MS", "0.25")
    assert bench._quiet_sentinel_norm_ms("TPU v5 lite") == 0.25


def test_quiet_sentinel_norm_by_kind(monkeypatch):
    monkeypatch.delenv("BENCH_QUIET_SENTINEL_MS", raising=False)
    assert bench._quiet_sentinel_norm_ms("TPU v5 lite0") == 0.04
    assert bench._quiet_sentinel_norm_ms("cpu") == 0.02
    # unknown backend falls back to the v5e-class norm rather than crashing
    assert bench._quiet_sentinel_norm_ms("TPU v99") == 0.04


def test_live_trainer_pids_sees_trainer_cmdline(tmp_path):
    """A live train_*_system process must be detected (the r3 contamination
    was a trainer that was host-side when the device sentinel ran)."""
    import subprocess
    import sys as _sys

    script = tmp_path / "train_maml_system_fake.py"
    script.write_text("import time; time.sleep(30)\n")
    proc = subprocess.Popen([_sys.executable, str(script)])
    try:
        assert proc.pid in bench._live_trainer_pids()
    finally:
        proc.kill()
        proc.wait()


def test_time_boxed_window_counts_units_and_drains():
    drained = []
    ticks = iter(x * 0.25 for x in range(100))
    run = bench._time_boxed_window(
        1.0,
        step=lambda: 3,
        drain=lambda: drained.append(True),
        clock=lambda: next(ticks),
    )
    units, dt = run()
    # clock: t0=0.0; loop checks at 0.25,0.5,0.75 (3 steps run), stops at 1.0
    assert units == 9
    assert drained == [True]
    assert dt > 0


def test_measure_multichip_weak_scaling_efficiency(monkeypatch):
    """The efficiency key is WEAK-scaling: the global meta-batch grows with
    the mesh, so ideal scaling keeps the meta-iteration rate FLAT and
    efficiency = rate(N) / rate(1) — NOT divided by another factor of N
    (which would cap perfect 8-chip scaling at 0.125 and make the 0.75
    target unreachable). Workers are stubbed; this pins the aggregation."""
    rates = {1: 10.0, 2: 10.0, 4: 9.0, 8: 7.5}

    def fake_worker(args):
        if "--probe" in args:
            return {"probe": "ok"}, None
        n = int(args[0])
        return {
            "n_devices": n,
            "meta_iters_per_s": rates[n],
            "program": "second_order",
            "skipped_reason": None,
        }, None

    monkeypatch.setattr(bench, "_run_multichip_worker", fake_worker)
    monkeypatch.setattr(
        bench.jax, "devices",
        lambda: [type("D", (), {"platform": "cpu"})()],
    )
    out = bench._measure_multichip()
    assert out["multichip_meta_iters_per_s"] == 7.5
    assert out["multichip_scaling_efficiency"] == 0.75
    assert out["multichip_program"] == "second_order"
    assert [r["n_devices"] for r in out["multichip_rows"]] == [1, 2, 4, 8]
    assert out["multichip_skipped_reason"] is None


def test_measure_multichip_first_order_fallback_records_reason(monkeypatch):
    """A CHECK-crashing partitioner (probe fails) degrades EVERY row to the
    first-order program with the reason recorded — never a dead bench."""
    def fake_worker(args):
        if "--probe" in args:
            return None, "worker rc=-6 (killed by signal)"
        assert "--first-order" in args
        n = int(args[0])
        return {
            "n_devices": n,
            "meta_iters_per_s": 4.0,
            "program": "first_order",
            "skipped_reason": None,
        }, None

    monkeypatch.setattr(bench, "_run_multichip_worker", fake_worker)
    monkeypatch.setattr(
        bench.jax, "devices",
        lambda: [type("D", (), {"platform": "cpu"})()],
    )
    out = bench._measure_multichip()
    assert out["multichip_program"] == "first_order"
    assert out["multichip_scaling_efficiency"] == 1.0
    assert "first-order" in out["multichip_fallback_reason"]
