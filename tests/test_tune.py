"""Self-driving resource plane, tune half (ISSUE 20): the declarative
knob space's guard/fingerprint contracts and the autotuner's
classify -> rank -> probe -> judge loop, driven hermetically with
injected measurement/sentinel functions (no JAX probe, no wall clock).
The real probe + gates-file append is proven by the checked-in
``AUTOTUNE_*`` receipts and the ``autotune_probe_meta_iters_per_s``
gate in ``tools/bench_gates.json``."""

import json

import pytest

from howtotrainyourmamlpytorch_tpu.tune.autotuner import (
    BASELINE_KEY,
    PROBE_APPLIERS,
    PROBE_KEY,
    ProbeSpec,
    append_gate,
    autotune_run,
    classify_regime,
    rank_candidates,
)
from howtotrainyourmamlpytorch_tpu.tune.space import (
    SPACE,
    TuneContext,
    config_fingerprint,
    fingerprint_from_args,
    resolve,
)

# ---------------------------------------------------------------------------
# The knob space: guards refuse, never clamp
# ---------------------------------------------------------------------------


def test_resolve_defaults_pass_everywhere():
    resolved = resolve({}, TuneContext())
    assert set(resolved) == set(SPACE)
    assert resolved["task_chunk"] == 0
    assert resolved["mesh_shape"] == (1, 1)


def test_resolve_unknown_knob_refuses_loudly():
    with pytest.raises(ValueError, match="unknown knob"):
        resolve({"task_chnuk": 4})  # the typo must not tune nothing


def test_unregistered_candidate_value_refused():
    with pytest.raises(ValueError, match="not a registered candidate"):
        SPACE["iters_per_dispatch"].check(7, TuneContext())


def test_task_chunk_guard_divisibility():
    # 8 % 8 == 0: legal at the default batch.
    resolve({"task_chunk": 8}, TuneContext(global_batch=8))
    with pytest.raises(ValueError, match="must divide the meta-batch"):
        resolve({"task_chunk": 8}, TuneContext(global_batch=12))
    with pytest.raises(ValueError, match="multiple of the mesh's dp"):
        resolve(
            {"task_chunk": 2},
            TuneContext(n_devices=8, dp=4, global_batch=8),
        )


def test_mesh_shape_guard_devices_and_batch():
    with pytest.raises(ValueError, match="devices"):
        resolve({"mesh_shape": (4, 1)}, TuneContext(n_devices=2))
    with pytest.raises(ValueError, match="multiple of the dp extent"):
        resolve(
            {"mesh_shape": (4, 1)},
            TuneContext(n_devices=4, global_batch=6),
        )


def test_legal_candidates_exclude_default_and_guarded():
    # global_batch=6: of (0, 2, 4, 8) only 2 divides 6; 0 is the default.
    knob = SPACE["task_chunk"]
    assert knob.legal_candidates(TuneContext(global_batch=6)) == (2,)
    assert knob.legal_candidates(TuneContext(global_batch=8)) == (2, 4, 8)


# ---------------------------------------------------------------------------
# config_fingerprint: stable value hash
# ---------------------------------------------------------------------------


def test_fingerprint_stable_and_order_independent():
    resolved = resolve({})
    fp = config_fingerprint(resolved)
    assert len(fp) == 12
    assert int(fp, 16) >= 0  # hex
    shuffled = dict(reversed(list(resolved.items())))
    assert config_fingerprint(shuffled) == fp


def test_fingerprint_moves_with_values_not_types():
    base = config_fingerprint(resolve({}))
    tuned = config_fingerprint(resolve({"task_chunk": 4}))
    assert tuned != base
    # Tuples and lists hash identically: a JSON round-trip of the
    # resolved set keeps its fingerprint.
    resolved = resolve({})
    round_tripped = json.loads(json.dumps(resolved))
    assert config_fingerprint(round_tripped) == base


def test_fingerprint_from_args_coerces_cli_strings():
    class Args:
        iters_per_dispatch = "5"
        task_chunk = 0
        lane_pad_channels = "False"
        device_prefetch = -1
        data_parallel_devices = 1
        model_parallel_devices = 1

    class Processed:
        iters_per_dispatch = 5
        task_chunk = 0
        lane_pad_channels = False
        device_prefetch = -1
        data_parallel_devices = 1
        model_parallel_devices = 1

    assert fingerprint_from_args(Args) == fingerprint_from_args(Processed)


def test_fingerprint_from_args_defaults_match_resolve():
    class Bare:
        pass

    assert fingerprint_from_args(Bare) == config_fingerprint(resolve({}))


# ---------------------------------------------------------------------------
# classify_regime + rank_candidates
# ---------------------------------------------------------------------------


def test_classify_regime_unknown_host_is_dispatch():
    regime, reason = classify_regime(None, "cpu", None)
    assert regime == "dispatch"
    assert "dispatch overhead" in reason


def test_classify_regime_roofline_split():
    # TPU v4: ridge = 275e12 / 1228e9 ~ 224 FLOP/B.
    regime, _ = classify_regime(10.0, "TPU v4", 275e12)
    assert regime == "memory"
    regime, _ = classify_regime(500.0, "TPU v4", 275e12)
    assert regime == "compute"


def test_rank_candidates_regime_first_and_probeable_only():
    ranked = rank_candidates("memory", TuneContext(), max_candidates=99)
    assert ranked, "the default context must rank something"
    assert all(name in PROBE_APPLIERS for name, _ in ranked)
    # task_chunk is the memory-regime knob: its candidates lead.
    assert ranked[0][0] == "task_chunk"
    assert len(rank_candidates("memory", TuneContext(),
                               max_candidates=2)) == 2


# ---------------------------------------------------------------------------
# autotune_run: hermetic loop with injected measurement
# ---------------------------------------------------------------------------

QUIET = {"contended": False, "sentinel_ms": 1.0}
NOISY = {"contended": True, "sentinel_ms": 99.0}


def _measure_table(baseline, table, default=9.0):
    def measure(overrides, spec):  # noqa: ARG001 — ProbeSpec unused here
        if not overrides:
            return baseline
        for (knob, value), measured in table.items():
            if overrides.get(knob) == value:
                return measured
        return default

    return measure


def test_autotune_keeps_a_judged_winner():
    measure = _measure_table(10.0, {("iters_per_dispatch", 5): 13.0})
    result = autotune_run(
        run_id="t01", spec=ProbeSpec(contention_retries=0),
        measure_fn=measure, sentinel_fn=lambda: dict(QUIET),
    )
    assert result["regime"] == "dispatch"
    assert result["judge"]["verdict"] == "keep"
    winner = result["winner"]
    assert winner["knob"] == "iters_per_dispatch"
    assert winner["value"] == 5
    assert winner["lever"] == "--iters_per_dispatch=5"
    assert winner["gain"] == pytest.approx(0.3)
    assert winner["gate_entry"]["source"] == "autotune:t01"
    # The emission wrappers replay through the judge: both runs carry
    # the baseline key and a config fingerprint.
    assert [r["parsed"][BASELINE_KEY] for r in result["emissions"]] \
        == [10.0, 10.0]
    assert all(
        r["parsed"]["config_fingerprint"] for r in result["emissions"]
    )
    assert result["emissions"][1]["parsed"][PROBE_KEY] == 13.0


def test_autotune_below_min_gain_keeps_nothing():
    measure = _measure_table(10.0, {("iters_per_dispatch", 5): 10.2})
    result = autotune_run(
        run_id="t02", spec=ProbeSpec(contention_retries=0),
        measure_fn=measure, sentinel_fn=lambda: dict(QUIET),
        min_gain=0.05,
    )
    assert result["judge"]["verdict"] != "keep"
    assert result["winner"] is None


def test_autotune_contended_baseline_judges_nothing():
    calls = {"n": 0}

    def measure(overrides, spec):  # noqa: ARG001
        calls["n"] += 1
        return 10.0

    result = autotune_run(
        run_id="t03", spec=ProbeSpec(contention_retries=1),
        measure_fn=measure, sentinel_fn=lambda: dict(NOISY),
    )
    assert result["baseline"] is None
    assert result["winner"] is None
    assert "contended" in result["error"]
    # Retried exactly contention_retries+1 times, then discarded —
    # candidates were never probed on a poisoned host.
    assert calls["n"] == 2


def test_autotune_discards_contended_probes():
    sequence = iter([QUIET, QUIET,  # baseline: clean
                     NOISY, NOISY,  # candidate 1, attempt 1: flagged
                     NOISY, NOISY])  # candidate 1, attempt 2: flagged

    def sentinel():
        return dict(next(sequence, QUIET))

    measure = _measure_table(10.0, {("iters_per_dispatch", 25): 14.0})
    result = autotune_run(
        run_id="t04", spec=ProbeSpec(contention_retries=1),
        measure_fn=measure, sentinel_fn=sentinel, max_candidates=3,
    )
    assert result["probes"][0]["discarded"] is True
    # A later clean probe still wins: discard is per-probe, not fatal.
    assert result["winner"] is not None
    assert result["winner"]["value"] == 25


# ---------------------------------------------------------------------------
# append_gate: atomic, idempotent, provenance-preserving
# ---------------------------------------------------------------------------


def test_append_gate_appends_then_replaces(tmp_path):
    gates_path = tmp_path / "gates.json"
    gates_path.write_text(json.dumps({
        "schema": 1,
        "gates": {"existing": {"direction": "higher", "gate": "this > 0"}},
        "ungated_ok": ["contended"],
    }))
    entry = {"direction": "higher", "gate": "this > 1.05 * base",
             "source": "autotune:t05"}
    append_gate(str(gates_path), PROBE_KEY, entry,
                ungated_extra=(BASELINE_KEY, "contended"))
    doc = json.loads(gates_path.read_text())
    assert doc["gates"][PROBE_KEY] == entry
    assert doc["gates"]["existing"]["gate"] == "this > 0"  # untouched
    assert doc["ungated_ok"] == ["contended", BASELINE_KEY]  # deduped

    replacement = dict(entry, source="autotune:t06")
    append_gate(str(gates_path), PROBE_KEY, replacement,
                ungated_extra=(BASELINE_KEY,))
    doc = json.loads(gates_path.read_text())
    assert doc["gates"][PROBE_KEY]["source"] == "autotune:t06"
    assert doc["ungated_ok"].count(BASELINE_KEY) == 1
