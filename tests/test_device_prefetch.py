"""Device-side async prefetch stager tests (data/device_prefetch.py).

The contracts that make the stager safe to run by default:

* staged training is BIT-IDENTICAL to the host path on the K=1 and K-scan
  dispatch paths (the stager only moves prepare/transfer off the critical
  path — it must not change a single bit of any update);
* zero new compile signatures and zero host syncs with the stager active
  (compile_guard + a ``jax.device_get`` count over the staged loop);
* dispatch groups match the builder's chunking and never straddle an epoch
  boundary;
* lifecycle: producer errors propagate, ``close()`` stops the thread and
  releases every unconsumed staged device buffer, auto depth grows only
  under measured consumer starvation.
"""

import threading
import time

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.data.device_prefetch import (
    AUTO_DEPTH,
    DEFAULT_DEPTH,
    MAX_AUTO_DEPTH,
    DevicePrefetcher,
)
from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.models.common import (
    StagedBatch,
    WireCodec,
    prepare_batch,
)


def tiny_cfg(**kw):
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=4,
            num_classes=5,
            image_height=8,
            image_width=8,
            num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        use_multi_step_loss_optimization=False,
        second_order=False,
        wire_codec=WireCodec(1.0, None, None),
        **kw,
    )


def make_samples(rng, n, tasks=2):
    """n loader-layout samples (xs, xt, ys, yt, seed), each distinct."""
    samples = []
    for i in range(n):
        xs = rng.randint(0, 2, (tasks, 5, 1, 1, 8, 8)).astype(np.float32)
        xt = rng.randint(0, 2, (tasks, 5, 1, 1, 8, 8)).astype(np.float32)
        ys = np.tile(np.arange(5)[None, :, None], (tasks, 1, 1)).astype(
            np.int32
        )
        samples.append((xs, xt, ys, ys.copy(), np.full(tasks, 100 + i)))
    return samples


def stage_all(samples, codec, **kwargs):
    stager = DevicePrefetcher(
        iter(samples), lambda b: prepare_batch(b, codec=codec), **kwargs
    )
    try:
        return list(stager), stager
    finally:
        stager.close()


# ---------------------------------------------------------------------------
# Bit-exactness: staged == host path
# ---------------------------------------------------------------------------


def test_staged_k1_training_bitwise_identical():
    rng = np.random.RandomState(0)
    samples = make_samples(rng, 5)
    learner = MAMLFewShotLearner(tiny_cfg())
    s_host = learner.init_state(jax.random.PRNGKey(7))
    s_staged = learner.init_state(jax.random.PRNGKey(7))

    for sample in samples:
        s_host, _ = learner.run_train_iter(s_host, sample[:4], epoch=0)

    staged, stager = stage_all(
        samples, learner.cfg.wire_codec, depth=2, group=1
    )
    assert [b.n_iters for b in staged] == [1] * 5
    assert [b.first_iter for b in staged] == list(range(5))
    for batch in staged:
        assert isinstance(batch, StagedBatch)
        s_staged, _ = learner.run_train_iter(s_staged, batch, epoch=0)

    for a, b in zip(jax.tree.leaves(s_host), jax.tree.leaves(s_staged)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_staged_group_dispatch_bitwise_identical():
    """group=K stages whole scan dispatches (pre-stacked form); the final
    partial group matches the builder's epoch-tail flush."""
    rng = np.random.RandomState(1)
    samples = make_samples(rng, 7)
    learner = MAMLFewShotLearner(tiny_cfg())
    s_host = learner.init_state(jax.random.PRNGKey(9))
    s_staged = learner.init_state(jax.random.PRNGKey(9))

    for chunk in (samples[:3], samples[3:6], samples[6:]):
        s_host, _ = learner.run_train_iters(
            s_host, [c[:4] for c in chunk], epoch=0
        )

    staged, _ = stage_all(samples, learner.cfg.wire_codec, depth=2, group=3)
    assert [b.n_iters for b in staged] == [3, 3, 1]
    assert [b.first_iter for b in staged] == [0, 3, 6]
    for batch in staged:
        s_staged, _ = learner.run_train_iters(s_staged, batch, epoch=0)

    for a, b in zip(jax.tree.leaves(s_host), jax.tree.leaves(s_staged)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_groups_never_straddle_epoch_boundary():
    rng = np.random.RandomState(2)
    samples = make_samples(rng, 8)
    staged, _ = stage_all(
        samples, None, depth=2, group=3, start_iter=0, epoch_len=4
    )
    assert [b.n_iters for b in staged] == [3, 1, 3, 1]
    assert [b.first_iter for b in staged] == [0, 3, 4, 7]
    # A mid-epoch resume point (start_iter=3, boundaries at 4 and 8):
    # iters 3 | 4,5,6 | 7 | 8,9,10.
    staged, _ = stage_all(
        samples, None, depth=2, group=3, start_iter=3, epoch_len=4
    )
    assert [b.n_iters for b in staged] == [1, 3, 1, 3]
    assert [b.first_iter for b in staged] == [3, 4, 7, 8]


# ---------------------------------------------------------------------------
# Zero new compile signatures, zero host syncs
# ---------------------------------------------------------------------------


def test_staged_k1_mints_no_new_signatures_and_no_syncs(compile_guard):
    """One warm host-path dispatch, then a staged loop: the step program
    must compile exactly once TOTAL (staged arrays present the identical
    signature) and the staged loop must trigger zero jax.device_get."""
    rng = np.random.RandomState(3)
    samples = make_samples(rng, 6)
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.PRNGKey(11))

    with compile_guard() as guard:
        state, _ = learner.run_train_iter(state, samples[0][:4], epoch=0)
        jax.block_until_ready(state.theta)

        device_gets = {"n": 0}
        real_device_get = jax.device_get

        def counting_device_get(x):
            device_gets["n"] += 1
            return real_device_get(x)

        jax.device_get = counting_device_get
        try:
            staged, _ = stage_all(
                samples[1:], learner.cfg.wire_codec, depth=2, group=1
            )
            for batch in staged:
                state, _ = learner.run_train_iter(state, batch, epoch=0)
            jax.block_until_ready(state.theta)
        finally:
            jax.device_get = real_device_get
    guard.assert_compiles("_train_step", exactly=1)
    guard.assert_unique_signatures("_train_step")
    assert device_gets["n"] == 0


def test_staged_k_scan_mints_no_new_signatures(compile_guard):
    rng = np.random.RandomState(4)
    samples = make_samples(rng, 9)
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.PRNGKey(13))
    with compile_guard() as guard:
        state, _ = learner.run_train_iters(
            state, [s[:4] for s in samples[:3]], epoch=0
        )
        staged, _ = stage_all(
            samples[3:], learner.cfg.wire_codec, depth=2, group=3
        )
        for batch in staged:
            state, _ = learner.run_train_iters(state, batch, epoch=0)
        jax.block_until_ready(state.theta)
    guard.assert_compiles("multi", exactly=1)
    guard.assert_unique_signatures("multi")


# ---------------------------------------------------------------------------
# Lifecycle
# ---------------------------------------------------------------------------


def test_producer_error_propagates_to_consumer():
    """A producer death surfaces at the consumer's next pop as a typed
    DataPipelineError with the ORIGINAL exception — and its producer-side
    traceback — chained as __cause__ (previously an opaque re-raise that
    read as if the consumer itself failed)."""
    from howtotrainyourmamlpytorch_tpu.data.device_prefetch import (
        DataPipelineError,
    )

    def exploding():
        yield from make_samples(np.random.RandomState(5), 1)
        raise ValueError("corrupt image mid-epoch")

    stager = DevicePrefetcher(
        exploding(), lambda b: prepare_batch(b), depth=2, group=1
    )
    try:
        next(stager)
        with pytest.raises(DataPipelineError, match="corrupt image") as exc:
            for _ in stager:
                pass
        cause = exc.value.__cause__
        assert isinstance(cause, ValueError)
        # The chained traceback reaches the producer-side raise site.
        frames = []
        tb = cause.__traceback__
        while tb is not None:
            frames.append(tb.tb_frame.f_code.co_name)
            tb = tb.tb_next
        assert "exploding" in frames
    finally:
        stager.close()


def _data_fault_events(log, path):
    log.flush()
    import json

    with open(path) as f:
        return [
            e for e in (json.loads(line) for line in f if line.strip())
            if e.get("type") == "data_fault"
        ]


def test_producer_fault_quarantine_skips_then_fails_past_budget(tmp_path):
    """With fault_budget > 0, a transient producer fault (the
    producer_fail_at_iter injection — raised before the source pull, like
    a loader I/O blip) is quarantined with a data_fault telemetry event
    and the stream continues; a persistently failing stage exhausts the
    budget and fails fast with the original error chained."""
    from howtotrainyourmamlpytorch_tpu.data.device_prefetch import (
        DataPipelineError,
    )
    from howtotrainyourmamlpytorch_tpu.telemetry import events as tel_events
    from howtotrainyourmamlpytorch_tpu.utils import faultinject

    log_path = str(tmp_path / "events.jsonl")
    log = tel_events.EventLog(log_path)
    prev = tel_events.install(log)
    try:
        # Injected transient pull fault, budget 2: quarantined, every
        # batch still arrives (the pull retries on the intact source).
        faultinject.activate(faultinject.FaultPlan(producer_fail_at_iter=2))
        stager = DevicePrefetcher(
            iter(make_samples(np.random.RandomState(7), 6)),
            lambda b: prepare_batch(b), depth=2, group=1, fault_budget=2,
        )
        try:
            assert sum(1 for _ in stager) == 6
            assert stager.faults_quarantined == 1
        finally:
            stager.close()
            faultinject.deactivate()
        faults = _data_fault_events(log, log_path)
        assert faults and not faults[0]["fatal"]

        # A persistently failing stage: two quarantined skips (each
        # consuming one batch window), then fail-fast with the original
        # OSError chained.
        def bad_stage(b):
            raise OSError(5, "corrupt episode")

        stager = DevicePrefetcher(
            iter(make_samples(np.random.RandomState(8), 6)),
            bad_stage, depth=2, group=1, fault_budget=2,
        )
        try:
            with pytest.raises(
                DataPipelineError, match="corrupt episode"
            ) as exc:
                for _ in stager:
                    pass
            assert isinstance(exc.value.__cause__, OSError)
            assert stager.faults_quarantined == 2
        finally:
            stager.close()
        assert any(e["fatal"] for e in _data_fault_events(log, log_path))
    finally:
        tel_events.install(prev)


def test_close_stops_thread_and_releases_device_buffers():
    rng = np.random.RandomState(6)
    stager = DevicePrefetcher(
        iter(make_samples(rng, 6)),
        lambda b: prepare_batch(b),
        depth=3,
        group=1,
    )
    first = next(stager)
    # Let the stager fill its buffer, then abandon it mid-stream.
    deadline = time.time() + 5.0
    while time.time() < deadline:
        with stager._lock:
            if len(stager._buffer) >= 3:
                break
        time.sleep(0.01)
    with stager._lock:
        buffered = list(stager._buffer)
    assert buffered, "stager never filled its buffer"
    stager.close()
    assert stager.closed
    assert not stager._thread.is_alive()
    assert stager.released_buffers >= len(buffered)
    # The unconsumed staged device buffers were DELETED, not just dropped.
    for batch in buffered:
        for leaf in batch.arrays:
            assert leaf.is_deleted()
    # The consumed batch stays usable — close only releases unconsumed ones.
    assert not first.arrays[0].is_deleted()
    stager.close()  # idempotent


def test_close_is_safe_while_producer_blocked_on_full_buffer():
    rng = np.random.RandomState(7)
    stager = DevicePrefetcher(
        iter(make_samples(rng, 50)),
        lambda b: prepare_batch(b),
        depth=1,
        group=1,
    )
    next(stager)
    time.sleep(0.05)  # producer parks on the full buffer
    stager.close()
    assert not stager._thread.is_alive()
    assert not any(
        t.name == "device-prefetch-stager" and t.is_alive()
        for t in threading.enumerate()
    )


def test_close_returns_promptly_when_producer_blocked_upstream():
    """A producer parked inside ``next(source)`` (empty loader queue)
    cannot be interrupted; close() must not stall the preemption/rollback
    shutdown paths behind a long join waiting for it."""
    release = threading.Event()

    def stuck_source():
        release.wait(30)
        yield None

    stager = DevicePrefetcher(
        stuck_source(), lambda b: b, depth=2, group=1
    )
    try:
        time.sleep(0.05)  # let the producer park in next(source)
        t0 = time.monotonic()
        stager.close()
        assert time.monotonic() - t0 < 10.0
        assert stager.closed
    finally:
        release.set()


def test_builder_mesh_staging_follows_learner_declaration():
    """Mesh runs STAGE now (ISSUE 8 closed PR 7's gap) — but only when the
    learner declares a staged-batch sharding; a learner that declines
    (``None`` — the arg-driven mp layout) or predates the hook keeps the
    inline host loop, and ``--device_prefetch 0`` still disables staging
    everywhere."""
    from howtotrainyourmamlpytorch_tpu.experiment_builder import (
        ExperimentBuilder,
    )

    class Stub:
        pass

    builder = Stub()
    builder.device_prefetch = -1
    builder.data_fault_budget = 0
    builder._use_multi = False
    builder.iters_per_dispatch = 1
    builder.state = {"current_iter": 0}
    builder.args = Stub()
    builder.args.total_iter_per_epoch = 4
    builder.model = Stub()
    builder.model.mesh = object()  # any active mesh
    builder.model.cfg = Stub()
    builder.model.cfg.wire_codec = None

    # Learner declares a batch layout -> mesh-aware stager staging into it.
    declared = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    builder.model.staged_batch_sharding = lambda group: declared
    stager = ExperimentBuilder._make_stager(builder, iter(()))
    try:
        assert isinstance(stager, DevicePrefetcher)
        assert stager._sharding is declared
    finally:
        stager.close()

    # Learner declines (mp mesh: committed staged layout could force a
    # reshard copy onto the critical path) -> inline host loop.
    builder.model.staged_batch_sharding = lambda group: None
    assert ExperimentBuilder._make_stager(builder, iter(())) is None

    # Learner without the hook at all -> inline host loop on mesh runs.
    del builder.model.staged_batch_sharding
    assert ExperimentBuilder._make_stager(builder, iter(())) is None

    builder.device_prefetch = 0
    builder.model.mesh = None
    assert ExperimentBuilder._make_stager(builder, iter(())) is None


def test_pop_waits_split_and_auto_depth_growth():
    """A slow upstream source accrues data_wait in the stager and
    stage_wait in the consumer; repeated starvation deepens auto mode."""
    rng = np.random.RandomState(8)
    samples = make_samples(rng, 30)

    def slow_source():
        for s in samples:
            time.sleep(0.002)
            yield s

    stager = DevicePrefetcher(
        slow_source(), lambda b: prepare_batch(b), depth=AUTO_DEPTH, group=1
    )
    try:
        assert stager.depth == DEFAULT_DEPTH
        for _ in stager:
            pass
        data_wait_s, stage_wait_s = stager.pop_waits()
        assert data_wait_s > 0.0
        assert stage_wait_s > 0.0
        assert DEFAULT_DEPTH < stager.depth <= MAX_AUTO_DEPTH
        # pop_waits resets the accumulators.
        assert stager.pop_waits() == (0.0, 0.0)
    finally:
        stager.close()


def test_pinned_depth_never_grows():
    rng = np.random.RandomState(9)
    samples = make_samples(rng, 20)

    def slow_source():
        for s in samples:
            time.sleep(0.002)
            yield s

    stager = DevicePrefetcher(
        slow_source(), lambda b: prepare_batch(b), depth=2, group=1
    )
    try:
        for _ in stager:
            pass
        assert stager.depth == 2
    finally:
        stager.close()
