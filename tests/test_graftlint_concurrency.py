"""graftlint v2: the five whole-program concurrency/contract rules.

Positive + negative units per rule (the ``tools/graftlint`` contract:
every rule proves it fires AND proves it stays quiet on the idiom it must
not flag), the exit-code registry pinned against the live constants, and
the cross-validation e2e: ONE seeded lock-order inversion is caught by
BOTH the static ``lock-order-inversion`` pass and the runtime
``utils/locksan.py`` sanitizer executing the same source.
"""

import textwrap
import threading

from tools.graftlint import RULES, lint_source, lint_sources
from tools.graftlint.concurrency import EXIT_CODE_REGISTRY


def find(violations, rule):
    return [v for v in violations if v.rule == rule]


def test_new_rules_are_registered():
    assert {
        "lock-order-inversion",
        "blocking-under-lock",
        "signal-handler-unsafe",
        "chief-only-write",
        "exit-code-contract",
    } <= set(RULES)


# ---------------------------------------------------------------------------
# lock-order-inversion
# ---------------------------------------------------------------------------

#: The seeded deadlock shared by the static test below AND the runtime
#: cross-validation: `forward` nests la -> lb, `backward` nests lb -> la.
SEEDED_INVERSION_SRC = textwrap.dedent(
    """
    import threading


    class Pair:
        def __init__(self):
            self._la = threading.Lock()
            self._lb = threading.Lock()

        def forward(self):
            with self._la:
                with self._lb:
                    pass

        def backward(self):
            with self._lb:
                with self._la:
                    pass
    """
)


def test_lock_order_inversion_fires_on_opposite_nesting():
    hits = find(lint_source(SEEDED_INVERSION_SRC, "inv.py"),
                "lock-order-inversion")
    assert len(hits) == 2  # both directions of the cycle are named
    assert any("Pair._la" in v.message and "Pair._lb" in v.message
               for v in hits)


def test_lock_order_inversion_quiet_on_consistent_order():
    src = textwrap.dedent(
        """
        import threading


        class Pair:
            def __init__(self):
                self._la = threading.Lock()
                self._lb = threading.Lock()

            def forward(self):
                with self._la:
                    with self._lb:
                        pass

            def also_forward(self):
                with self._la:
                    with self._lb:
                        pass
        """
    )
    assert find(lint_source(src, "ok.py"), "lock-order-inversion") == []


def test_lock_order_inversion_interprocedural_and_cross_module():
    """One half of the inversion acquires its second lock two calls deep
    IN ANOTHER MODULE (relative-import resolution + call-graph closure)."""
    pkg_a = textwrap.dedent(
        """
        import threading

        from . import other

        _la = threading.Lock()


        def top():
            with _la:
                other.helper()


        def regrab():
            pass
        """
    )
    pkg_b = textwrap.dedent(
        """
        import threading

        from . import mod_a

        _lb = threading.Lock()


        def helper():
            leaf()


        def leaf():
            with _lb:
                mod_a.regrab()
        """
    )
    # No cycle yet: mod_a._la -> other._lb only (regrab is lock-free).
    violations = lint_sources({"pkg/mod_a.py": pkg_a, "pkg/other.py": pkg_b})
    assert find(violations, "lock-order-inversion") == []
    # Close the cycle: regrab now takes mod_a's lock while other.leaf
    # holds its own — the opposite order, two modules apart.
    pkg_a_cyclic = pkg_a.replace(
        "def regrab():\n    pass",
        "def regrab():\n    with _la:\n        pass",
    )
    violations = lint_sources(
        {"pkg/mod_a.py": pkg_a_cyclic, "pkg/other.py": pkg_b}
    )
    hits = find(violations, "lock-order-inversion")
    assert hits, "cross-module inversion not detected"
    assert any("mod_a:_la" in v.message and "other:_lb" in v.message
               for v in hits)


def test_condition_sharing_a_lock_is_one_lock_not_a_cycle():
    """``Condition(self._lock)`` aliases the lock (the DevicePrefetcher
    idiom: two conditions, one mutex) — nesting them must NOT look like
    two locks, let alone an inversion."""
    src = textwrap.dedent(
        """
        import threading


        class Stager:
            def __init__(self):
                self._lock = threading.Lock()
                self._not_empty = threading.Condition(self._lock)
                self._not_full = threading.Condition(self._lock)

            def pop(self):
                with self._not_empty:
                    self._not_full.notify()

            def push(self):
                with self._not_full:
                    self._not_empty.notify()
        """
    )
    violations = lint_source(src, "stager.py")
    assert find(violations, "lock-order-inversion") == []
    assert find(violations, "blocking-under-lock") == []


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------


def test_blocking_under_lock_direct_primitives():
    src = textwrap.dedent(
        """
        import threading
        import time


        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                with self._lock:
                    time.sleep(1.0)
        """
    )
    hits = find(lint_source(src, "w.py"), "blocking-under-lock")
    assert len(hits) == 1 and "time.sleep" in hits[0].message


def test_blocking_under_lock_reaches_through_helpers():
    src = textwrap.dedent(
        """
        import threading


        class Pool:
            def __init__(self):
                self._lock = threading.Lock()

            def promote(self, path):
                with self._lock:
                    self.record(path)

            def record(self, path):
                digest(path)


        def digest(path):
            with open(path, "rb") as f:
                return f.read()
        """
    )
    hits = find(lint_source(src, "pool.py"), "blocking-under-lock")
    assert hits, "interprocedural blocking call not reached"
    assert "file open" in hits[0].message
    assert "self.record" in hits[0].message


def test_blocking_queue_get_under_lock_flags_nonblocking_does_not():
    src = textwrap.dedent(
        """
        import queue
        import threading


        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def bad(self):
                with self._lock:
                    return self._q.get()

            def fine(self):
                with self._lock:
                    return self._q.get(block=False)
        """
    )
    hits = find(lint_source(src, "q.py"), "blocking-under-lock")
    assert len(hits) == 1
    assert hits[0].line < 15  # only the blocking get


def test_own_condition_wait_is_not_blocking_foreign_wait_is():
    src = textwrap.dedent(
        """
        import threading


        class C:
            def __init__(self):
                self._cond = threading.Condition()
                self._other = threading.Condition()

            def good(self):
                with self._cond:
                    self._cond.wait()

            def bad(self):
                with self._cond:
                    self._other.wait(0.1)
        """
    )
    hits = find(lint_source(src, "c.py"), "blocking-under-lock")
    assert len(hits) == 1 and "DIFFERENT lock" in hits[0].message


def test_dispatch_outside_lock_is_quiet():
    """The batcher idiom — pop the group under the lock, dispatch outside
    — must stay clean."""
    src = textwrap.dedent(
        """
        import threading


        class Engine:
            def dispatch(self, group):
                return group


        class Batcher:
            def __init__(self):
                self._lock = threading.Condition()
                self.engine = Engine()
                self._groups = []

            def run_once(self):
                with self._lock:
                    ready = list(self._groups)
                    self._groups.clear()
                for group in ready:
                    self.engine.dispatch(group)
        """
    )
    assert find(lint_source(src, "b.py"), "blocking-under-lock") == []


def test_dispatch_under_lock_is_flagged():
    src = textwrap.dedent(
        """
        import threading


        class Engine:
            def dispatch(self, group):
                return group


        class Batcher:
            def __init__(self):
                self._lock = threading.Condition()
                self.engine = Engine()

            def run_once(self, group):
                with self._lock:
                    return self.engine.dispatch(group)
        """
    )
    hits = find(lint_source(src, "b.py"), "blocking-under-lock")
    assert hits and "dispatch" in hits[0].message


# ---------------------------------------------------------------------------
# signal-handler-unsafe
# ---------------------------------------------------------------------------


def test_signal_handler_lock_flagged():
    src = textwrap.dedent(
        """
        import signal
        import threading


        class S:
            def __init__(self):
                self._lock = threading.Lock()
                signal.signal(signal.SIGTERM, self._onterm)

            def _onterm(self, signum, frame):
                with self._lock:
                    self.flag = True
        """
    )
    hits = find(lint_source(src, "s.py"), "signal-handler-unsafe")
    assert hits and "deadlock" in hits[0].message


def test_signal_handler_print_flagged_flag_set_quiet():
    src = textwrap.dedent(
        """
        import os
        import signal


        def install(state):
            def handler(signum, frame):
                state.flag = signum
                print("caught", signum)

            signal.signal(signal.SIGTERM, handler)


        def install_safe(state):
            def handler(signum, frame):
                state.flag = signum
                os.write(2, b"caught\\n")
                raise KeyboardInterrupt

            signal.signal(signal.SIGINT, handler)
        """
    )
    hits = find(lint_source(src, "h.py"), "signal-handler-unsafe")
    assert len(hits) == 1 and "print()" in hits[0].message


def test_signal_handler_sanctioned_idioms_quiet():
    """The tree's real handler shapes: Event.set (promotion daemon),
    defer-to-thread (serve front door), a resolvable flag-setting method
    call one level deep (telemetry SIGUSR1 lambda)."""
    src = textwrap.dedent(
        """
        import signal
        import threading


        class Profiler:
            def request(self, reason):
                self._pending = reason


        class T:
            def __init__(self, server):
                self.profiler = Profiler()
                self.stop = threading.Event()
                self.server = server
                signal.signal(
                    signal.SIGUSR1,
                    lambda s, f: self.profiler.request("signal"),
                )
                signal.signal(signal.SIGTERM, self._graceful)
                signal.signal(signal.SIGINT, self._defer)

            def _graceful(self, signum, frame):
                self.stop.set()

            def _defer(self, signum, frame):
                threading.Thread(
                    target=self.server.shutdown, daemon=True
                ).start()
        """
    )
    assert find(lint_source(src, "t.py"), "signal-handler-unsafe") == []


def test_signal_handler_blocking_call_one_level_deep_flagged():
    src = textwrap.dedent(
        """
        import signal
        import time


        def drain():
            time.sleep(5.0)


        def handler(signum, frame):
            drain()


        signal.signal(signal.SIGTERM, handler)
        """
    )
    hits = find(lint_source(src, "d.py"), "signal-handler-unsafe")
    assert hits and "unsafe work" in hits[0].message


# ---------------------------------------------------------------------------
# chief-only-write
# ---------------------------------------------------------------------------

_CHIEF_PREFIX = textwrap.dedent(
    """
    import os


    class Trainer:
        def __init__(self, args):
            self.process_index = int(args.process_index)
            self._is_chief = self.process_index == 0
    """
)


def test_chief_only_write_flags_unguarded_mutation():
    src = _CHIEF_PREFIX + textwrap.dedent(
        """
        def publish(self, src, dst):
            os.replace(src, dst)
    """
    ).replace("\n", "\n    ")
    hits = find(lint_source(src, "t.py"), "chief-only-write")
    assert hits and "os.replace" in hits[0].message


def test_chief_only_write_quiet_under_guard_and_early_return():
    src = _CHIEF_PREFIX + textwrap.dedent(
        """
        def publish(self, src, dst):
            if self._is_chief:
                os.replace(src, dst)

        def save(self, src, dst):
            self.t0 = 0.0
            if not self._is_chief:
                self.t0 = 1.0
                return
            os.replace(src, dst)

        def epoch(self, src, dst):
            self.save(src, dst)
    """
    ).replace("\n", "\n    ")
    assert find(lint_source(src, "t.py"), "chief-only-write") == []


def test_chief_only_write_out_of_scope_without_election():
    """A module that never elects a chief (single-process serving, the
    telemetry heartbeat's per-rank files) is out of scope entirely."""
    src = textwrap.dedent(
        """
        import os


        def publish(src, dst):
            os.replace(src, dst)
        """
    )
    assert find(lint_source(src, "p.py"), "chief-only-write") == []


# ---------------------------------------------------------------------------
# exit-code-contract
# ---------------------------------------------------------------------------


def test_exit_code_registry_matches_live_constants():
    """The registry and the real constants can never diverge — this is
    the declared single source the rule enforces against."""
    from howtotrainyourmamlpytorch_tpu.experiment_builder import (
        REQUEUE_EXIT_CODE,
    )
    from howtotrainyourmamlpytorch_tpu.serve.api import REPLICA_KILL_EXIT
    from howtotrainyourmamlpytorch_tpu.telemetry.device import OOM_EXIT_CODE
    from howtotrainyourmamlpytorch_tpu.utils.watchdog import HANG_EXIT_CODE

    assert REQUEUE_EXIT_CODE in EXIT_CODE_REGISTRY
    assert HANG_EXIT_CODE in EXIT_CODE_REGISTRY
    assert REPLICA_KILL_EXIT in EXIT_CODE_REGISTRY
    assert OOM_EXIT_CODE in EXIT_CODE_REGISTRY
    assert EXIT_CODE_REGISTRY[75].startswith("preemption")
    assert "hang" in EXIT_CODE_REGISTRY[76]
    assert "OOM" in EXIT_CODE_REGISTRY[77]
    assert 3 in EXIT_CODE_REGISTRY  # the miner's no-yield exit


def test_exit_code_contract_flags_undeclared_literal():
    src = "import sys\n\nsys.exit(42)\n"
    hits = find(lint_source(src, "x.py"), "exit-code-contract")
    assert hits and "42" in hits[0].message


def test_exit_code_contract_quiet_on_declared_and_symbolic():
    src = textwrap.dedent(
        """
        import os
        import sys

        HANG = 76


        def a():
            sys.exit(75)


        def b():
            os._exit(HANG)


        def c(rc):
            sys.exit(rc)
        """
    )
    assert find(lint_source(src, "x.py"), "exit-code-contract") == []


def test_exit_code_contract_bare_except():
    src = textwrap.dedent(
        """
        def swallow():
            try:
                risky()
            except:
                pass


        def reraise():
            try:
                risky()
            except:
                cleanup()
                raise
        """
    )
    hits = find(lint_source(src, "x.py"), "exit-code-contract")
    assert len(hits) == 1 and "bare" in hits[0].message
    assert hits[0].line == 5


# ---------------------------------------------------------------------------
# Cross-validation: the SAME seeded deadlock, static AND runtime
# ---------------------------------------------------------------------------


def test_seeded_inversion_caught_by_static_and_runtime(locksan):
    """The e2e contract of graftlint v2: one seeded AB/BA inversion, the
    static rule flags the source, and executing that same source under
    the locksan sanitizer records the cycle at runtime."""
    # Static half.
    static_hits = find(
        lint_source(SEEDED_INVERSION_SRC, "seeded.py"),
        "lock-order-inversion",
    )
    assert len(static_hits) == 2

    # Runtime half: execute the very same source under the sanitizer.
    with locksan() as san:
        namespace: dict = {}
        exec(compile(SEEDED_INVERSION_SRC, "seeded.py", "exec"), namespace)
        pair = namespace["Pair"]()
        t1 = threading.Thread(target=pair.forward)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=pair.backward)
        t2.start()
        t2.join()
    cycles = san.cycles()
    assert cycles, "runtime sanitizer missed the seeded inversion"
    assert any(
        all("seeded.py" in site for site in component)
        for component in cycles
    )
    try:
        san.assert_clean()
    except AssertionError as exc:
        assert "cyclic lock-acquisition order" in str(exc)
    else:
        raise AssertionError("assert_clean did not fail on the cycle")


def test_locksan_quiet_on_consistent_order(locksan):
    with locksan() as san:
        a = threading.Lock()
        b = threading.Lock()
        for _ in range(3):
            with a:
                with b:
                    pass
    assert san.cycles() == []
    san.assert_clean(hold_budget_s=5.0)
