"""Dispatch hang watchdog unit tests (utils/watchdog.py).

The e2e story — a deterministically wedged dispatch in the real CLI being
detected, diagnosed and requeued-degraded by the dispatcher — lives in
``tests/test_chaos_train.py``; here the watchdog's own contracts are pinned
with an injectable ``exit_fn`` (so a firing is observable without dying)
and real-but-short deadlines:

* the deadline model: floor + factor x p95 of observed samples, with the
  FIRST sample (the XLA compile) excluded;
* expiry -> full thread-stack dump (file + ``hang`` telemetry event with
  the distinct exit code) -> owner's unwind hook -> ``exit_fn``;
* a dispatch that completes inside its deadline never fires, and its wall
  time feeds the distribution;
* the exit-code split itself: 76 (hang: requeue, suspect the topology) is
  distinct from 75 (preemption: requeue, same mesh) — the dispatcher
  budgets them separately.
"""

import os
import threading
import time

from howtotrainyourmamlpytorch_tpu.telemetry import events as telemetry_events
from howtotrainyourmamlpytorch_tpu.utils.watchdog import (
    HANG_EXIT_CODE,
    DispatchWatchdog,
    dump_all_stacks,
)


def test_exit_code_split_is_pinned():
    from howtotrainyourmamlpytorch_tpu.experiment_builder import (
        REQUEUE_EXIT_CODE,
    )
    import train_maml_system_dispatch as dispatch

    assert HANG_EXIT_CODE == 76
    assert REQUEUE_EXIT_CODE == 75
    assert HANG_EXIT_CODE != REQUEUE_EXIT_CODE
    # The dispatcher supervises on the SAME codes the runtime exits with.
    assert dispatch.HANG_EXIT_CODE == HANG_EXIT_CODE
    assert dispatch.REQUEUE_EXIT_CODE == REQUEUE_EXIT_CODE


def test_deadline_model_floor_factor_and_compile_exclusion():
    wd = DispatchWatchdog(min_deadline_s=10.0, factor=4.0, exit_fn=lambda c: None)
    try:
        assert wd.deadline_s() == 10.0  # no samples: the floor
        wd.observe(300.0)  # the compile-bearing first sample: DROPPED
        assert wd.deadline_s() == 10.0
        for _ in range(20):
            wd.observe(1.0)
        assert wd.deadline_s() == 10.0  # 4 x p95(1.0) < floor
        for _ in range(100):
            wd.observe(5.0)
        assert wd.deadline_s() == 20.0  # 4 x p95(5.0)
    finally:
        wd.close()


def test_clean_dispatch_never_fires_and_feeds_distribution():
    fired = []
    wd = DispatchWatchdog(
        min_deadline_s=30.0, factor=50.0, exit_fn=fired.append
    )
    try:
        with wd.armed(1):
            pass  # compile-bearing first window: sample dropped
        with wd.armed(2):
            time.sleep(0.05)
        assert not fired
        assert not wd.fired
        assert wd.deadline_s() == 30.0  # 50 x ~0.05s < floor
    finally:
        wd.close()


def test_expiry_dumps_stacks_emits_hang_event_and_exits(tmp_path):
    log_path = str(tmp_path / "events.jsonl")
    log = telemetry_events.EventLog(log_path)
    prev = telemetry_events.install(log)
    exits, diags = [], []
    release = threading.Event()

    def fake_exit(code):
        exits.append(code)
        release.set()  # unwedge the "dispatch" below

    wd = DispatchWatchdog(
        min_deadline_s=0.2,
        factor=2.0,
        logs_dir=str(tmp_path),
        on_hang=diags.append,
        exit_fn=fake_exit,
    )
    try:
        with wd.armed(7):
            # The wedged dispatch: parks until the watchdog "exits".
            assert release.wait(timeout=30.0)
    finally:
        wd.close()
        telemetry_events.install(prev)

    assert exits == [HANG_EXIT_CODE]
    assert wd.fired
    # The owner's bounded unwind hook ran, with the diagnostics.
    assert len(diags) == 1 and diags[0]["iter"] == 7
    # Full thread-stack dump on disk: contains THIS (wedged) thread's
    # frames — the diagnostic that tells a stuck collective from a wedged
    # host sync.
    stack_file = tmp_path / "hang_stacks.txt"
    assert stack_file.exists()
    dump = stack_file.read_text()
    assert "test_expiry_dumps_stacks_emits_hang_event_and_exits" in dump
    assert "iteration 7" in dump
    # The hang telemetry event carries the exit code + a stack excerpt.
    log.flush()
    import json

    events = [
        json.loads(line) for line in open(log_path) if line.strip()
    ]
    hangs = [e for e in events if e["type"] == "hang"]
    assert len(hangs) == 1
    assert hangs[0]["exit_code"] == HANG_EXIT_CODE
    assert hangs[0]["iter"] == 7
    assert hangs[0]["stacks"]


def test_broken_unwind_hook_cannot_block_the_exit(tmp_path):
    exits = []
    release = threading.Event()

    def bad_hook(diag):
        raise RuntimeError("unwind hook is itself broken")

    def fake_exit(code):
        exits.append(code)
        release.set()

    wd = DispatchWatchdog(
        min_deadline_s=0.2, factor=2.0, on_hang=bad_hook, exit_fn=fake_exit
    )
    try:
        with wd.armed(1):
            assert release.wait(timeout=30.0)
    finally:
        wd.close()
    assert exits == [HANG_EXIT_CODE]


def test_close_joins_monitor_thread():
    before = {t.ident for t in threading.enumerate()}
    wd = DispatchWatchdog(min_deadline_s=60.0, exit_fn=lambda c: None)
    spawned = [
        t for t in threading.enumerate()
        if t.ident not in before and t.name == "dispatch-watchdog"
    ]
    assert len(spawned) == 1
    wd.close()
    wd.close()  # idempotent
    assert not spawned[0].is_alive()


def test_dump_all_stacks_includes_every_live_thread():
    gate = threading.Event()
    done = threading.Event()

    def parked():
        done.set()
        gate.wait(timeout=30.0)

    t = threading.Thread(target=parked, name="parked-for-dump")
    t.start()
    try:
        assert done.wait(timeout=5.0)
        dump = dump_all_stacks()
        assert "parked-for-dump" in dump
        assert "gate.wait" in dump
    finally:
        gate.set()
        t.join(timeout=5.0)
