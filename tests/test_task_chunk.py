"""Task-axis memory policy (ISSUE 9 lever 4, ``MAMLConfig.task_chunk``).

``--task_chunk N`` scans the meta-batch in chunks of N tasks through the
SAME vmapped per-task program instead of materializing every task's
inner-loop activations at once — the HBM-spill diagnosis knob for the
meta-batch-8 16x pathology (PERF_NOTES.md "North-star de-bottlenecking").
The per-task math is identical; only the outer-grad accumulation order
changes, so results must match the full vmap within reassociation
tolerance. Pinned here:

* chunked vs full-vmap SECOND-ORDER training: per-iter losses and
  post-update parameters within reassociation tolerance;
* a chunk that does not divide the task count is refused at trace time,
  and a chunk that cannot ride a dp mesh is refused at construction;
* chunking composes with the dp mesh (first-order — the GSPMD conv
  CHECK-crash is second-order-specific, ``spmd_fo_compile_guard``);
* ALL FOUR LEVERS together (lane_pad + bf16 + task_chunk + fused train
  stack) on the real K=1 and K=25 train paths: compile exactly once per
  path, zero ``jax.device_get`` in the steady state — the acceptance pin
  that none of the levers mints signatures or host syncs.
"""

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.parallel import make_mesh
from howtotrainyourmamlpytorch_tpu.parallel.sharding import guard_task_chunk


def make_cfg(**kw):
    backbone_kw = dict(
        num_stages=2,
        num_filters=6,
        per_step_bn_statistics=True,
        num_steps=2,
        num_classes=5,
        image_height=8,
        image_width=8,
    )
    backbone_kw.update(kw.pop("backbone_kw", {}))
    kw.setdefault("second_order", True)
    return MAMLConfig(
        backbone=BackboneConfig(**backbone_kw),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        use_multi_step_loss_optimization=False,
        **kw,
    )


def make_batch(rng, tasks=4):
    xs = rng.randn(tasks, 5, 1, 1, 8, 8).astype(np.float32)
    ys = np.tile(np.arange(5)[None, :, None], (tasks, 1, 1)).astype(np.int32)
    return xs, xs.copy(), ys, ys.copy()


def test_task_chunk_matches_full_vmap_second_order(rng):
    """chunk=2 over 4 tasks, second order: the scan form is the full vmap
    within reassociation tolerance (identical per-task math, different
    outer-grad accumulation order). The contract is pinned at the
    META-GRADIENT level — parameter trajectories are NOT compared, because
    Adam's eps-normalized update (``lr * m / (sqrt(v) + eps)``) amplifies
    sub-reassociation gradient noise into O(lr) parameter jitter wherever
    a gradient entry is near zero."""
    import optax

    full = MAMLFewShotLearner(make_cfg(task_chunk=0))
    chunked = MAMLFewShotLearner(make_cfg(task_chunk=2))
    sf = full.init_state(jax.random.PRNGKey(0))
    sc = chunked.init_state(jax.random.PRNGKey(0))

    def meta_grads(learner, state, batch):
        prepared = learner._prepare_batch(batch)
        importance = learner._train_importance(0)
        outer = {"theta": state.theta, "lslr": state.lslr}
        return jax.grad(
            lambda o: learner._meta_loss(
                o, state.bn_state, prepared, importance, 2, True, None, True
            )[0]
        )(outer)

    grad_batch = make_batch(rng)
    gf = meta_grads(full, sf, grad_batch)
    gc = meta_grads(chunked, sc, grad_batch)
    assert float(optax.global_norm(gf)) > 0  # non-degenerate comparison
    for (key, leaf_f), (_, leaf_c) in zip(
        jax.tree_util.tree_flatten_with_path(gf)[0],
        jax.tree_util.tree_flatten_with_path(gc)[0],
    ):
        np.testing.assert_allclose(
            np.asarray(leaf_f), np.asarray(leaf_c),
            rtol=2e-5, atol=1e-7, err_msg=str(key),
        )

    # And the real train loop: losses/metrics track per iteration.
    for _ in range(3):
        batch = make_batch(rng)
        sf, lf = full.run_train_iter(sf, batch, epoch=0)
        sc, lc = chunked.run_train_iter(sc, batch, epoch=0)
        np.testing.assert_allclose(
            float(lf["loss"]), float(lc["loss"]), rtol=1e-5, atol=1e-6
        )


def test_task_chunk_larger_than_batch_is_full_vmap(rng):
    """chunk >= task count degenerates to the plain vmap — bit-exact, not
    just tolerance-close (the scan branch is never traced)."""
    full = MAMLFewShotLearner(make_cfg(task_chunk=0))
    big = MAMLFewShotLearner(make_cfg(task_chunk=8))
    batch = make_batch(rng, tasks=4)
    sf, lf = full.run_train_iter(full.init_state(jax.random.PRNGKey(1)), batch, epoch=0)
    sb, lb = big.run_train_iter(big.init_state(jax.random.PRNGKey(1)), batch, epoch=0)
    assert float(lf["loss"]) == float(lb["loss"])
    for leaf_f, leaf_b in zip(
        jax.tree.leaves(sf.theta), jax.tree.leaves(sb.theta)
    ):
        np.testing.assert_array_equal(np.asarray(leaf_f), np.asarray(leaf_b))


def test_task_chunk_must_divide_task_count(rng):
    learner = MAMLFewShotLearner(make_cfg(task_chunk=3))
    with pytest.raises(ValueError, match="divide"):
        learner.run_train_iter(
            learner.init_state(jax.random.PRNGKey(2)), make_batch(rng, tasks=4),
            epoch=0,
        )


def test_negative_task_chunk_refused():
    with pytest.raises(ValueError, match="task_chunk"):
        make_cfg(task_chunk=-1)


def test_guard_task_chunk_requires_dp_multiple():
    mesh = make_mesh(jax.devices()[:8], data_parallel=8, model_parallel=1)
    with pytest.raises(ValueError, match="multiple"):
        guard_task_chunk(mesh, 3)
    guard_task_chunk(mesh, 8)  # fine
    guard_task_chunk(None, 3)  # off-mesh: no constraint
    guard_task_chunk(mesh, 0)  # chunking off: no constraint


def test_task_chunk_on_dp_mesh_matches_full_vmap(spmd_fo_compile_guard, rng):
    """chunk=8 over 16 tasks on the 8-device dp mesh (first order): each
    scan step is exactly the dp-sharded program of an 8-task meta-batch,
    and the run matches the unchunked mesh program within reassociation
    tolerance."""
    mesh = make_mesh(jax.devices()[:8], data_parallel=8, model_parallel=1)
    kw = dict(second_order=False)
    full = MAMLFewShotLearner(make_cfg(task_chunk=0, **kw), mesh=mesh)
    chunked = MAMLFewShotLearner(make_cfg(task_chunk=8, **kw), mesh=mesh)
    sf = full.shard_state(full.init_state(jax.random.PRNGKey(3)))
    sc = chunked.shard_state(chunked.init_state(jax.random.PRNGKey(3)))
    for _ in range(2):
        batch = make_batch(rng, tasks=16)
        sf, lf = full.run_train_iter(sf, batch, epoch=0)
        sc, lc = chunked.run_train_iter(sc, batch, epoch=0)
        # Loss-level parity only: parameter trajectories under Adam
        # amplify reassociation noise (see the second-order test above).
        np.testing.assert_allclose(
            float(lf["loss"]), float(lc["loss"]), rtol=1e-5, atol=1e-6
        )
    jax.block_until_ready((sf.theta, sc.theta))


def test_mesh_incompatible_task_chunk_refused_at_construction():
    mesh = make_mesh(jax.devices()[:8], data_parallel=8, model_parallel=1)
    with pytest.raises(ValueError, match="multiple"):
        MAMLFewShotLearner(make_cfg(task_chunk=3, second_order=False), mesh=mesh)


# ---------------------------------------------------------------------------
# All four levers together: the acceptance pin
# ---------------------------------------------------------------------------


def all_levers_cfg():
    return make_cfg(
        backbone_kw=dict(fused_norm_train=True, lane_pad_channels=True),
        compute_dtype="bfloat16",
        task_chunk=2,
    )


def test_all_levers_k1_compiles_once_zero_syncs(compile_guard, rng):
    """lane_pad + bf16 + task_chunk + fused second-order train stack on the
    real K=1 path: one compile, unique signature, zero host syncs in the
    steady state."""
    learner = MAMLFewShotLearner(all_levers_cfg())
    state = learner.init_state(jax.random.PRNGKey(4))
    batch = make_batch(rng)
    device_gets = {"n": 0}
    real_device_get = jax.device_get

    def counting_device_get(x):
        device_gets["n"] += 1
        return real_device_get(x)

    with compile_guard() as guard:
        state, losses = learner.run_train_iter(state, batch, epoch=0)
        jax.device_get = counting_device_get
        try:
            for _ in range(3):
                state, losses = learner.run_train_iter(state, batch, epoch=0)
            jax.block_until_ready(state.theta)
        finally:
            jax.device_get = real_device_get
    guard.assert_compiles("_train_step", exactly=1)
    guard.assert_unique_signatures("_train_step")
    assert device_gets["n"] == 0
    assert np.isfinite(float(losses["loss"]))
    # Masters stay f32 under the bf16 compute path.
    for leaf in jax.tree.leaves(state.theta):
        assert leaf.dtype == jax.numpy.float32


def test_all_levers_k25_scan_compiles_once_zero_syncs(compile_guard, rng):
    """Same composition on the real K=25 scan-dispatch path."""
    learner = MAMLFewShotLearner(all_levers_cfg())
    state = learner.init_state(jax.random.PRNGKey(5))
    batches = [make_batch(rng) for _ in range(25)]
    device_gets = {"n": 0}
    real_device_get = jax.device_get

    def counting_device_get(x):
        device_gets["n"] += 1
        return real_device_get(x)

    with compile_guard() as guard:
        state, losses = learner.run_train_iters(state, batches, epoch=0)
        jax.device_get = counting_device_get
        try:
            state, losses = learner.run_train_iters(state, batches, epoch=0)
            jax.block_until_ready(state.theta)
        finally:
            jax.device_get = real_device_get
    guard.assert_compiles("multi", exactly=1)
    guard.assert_unique_signatures("multi")
    assert device_gets["n"] == 0
    assert np.all(np.isfinite(np.asarray(losses["loss"])))
