"""Durable serving tier: crash-consistent artifact spill, integrity-fenced
AOT executable cache, and consistent-hash fleet routing (ISSUE 18).

The acceptance contract: a respawned replica pointed at its tier dir
rehydrates its hot set (first repeat request is a cache hit, no re-adapt)
and performs ZERO XLA compiles under ``compile_guard``; every injected
durability fault (torn spill write, bit-flipped entry, stale executable
fence) degrades to quarantine + the cold path with typed telemetry —
never a crash, never a wrong answer.
"""

import os
import time

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    GradientDescentLearner,
    MAMLConfig,
    MAMLFewShotLearner,
    MatchingNetsLearner,
)
from howtotrainyourmamlpytorch_tpu.serve import (
    PoolConfig,
    ReplicaPool,
    ServeConfig,
    ServingAPI,
)
from howtotrainyourmamlpytorch_tpu.serve.resilience import LocalReplica
from howtotrainyourmamlpytorch_tpu.serve.tier import (
    ArtifactSpill,
    ExecutableCache,
    HashRing,
    atomic_write_bytes,
    build_fence,
    serialization_available,
)
from howtotrainyourmamlpytorch_tpu.utils import faultinject

LEARNER_CLASSES = {
    "maml": MAMLFewShotLearner,
    "gradient_descent": GradientDescentLearner,
    "matching_nets": MatchingNetsLearner,
}


def tiny_cfg(**kw):
    defaults = dict(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=4,
            image_height=8,
            image_width=8,
            num_classes=5,
            per_step_bn_statistics=True,
            num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
    )
    defaults.update(kw)
    return MAMLConfig(**defaults)


def make_api(tier_dir, learner_cls=MAMLFewShotLearner, **serve_kw):
    learner = learner_cls(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    defaults = dict(meta_batch_size=2, max_wait_ms=0.0)
    defaults.update(serve_kw)
    return ServingAPI(
        learner, state, ServeConfig(tier_dir=str(tier_dir), **defaults)
    )


def episode(rng, way=5, shot=1, query=3):
    img = (1, 8, 8)
    xs = rng.rand(way * shot, *img).astype(np.float32)
    ys = np.repeat(np.arange(way), shot).astype(np.int32)
    xq = rng.rand(query, *img).astype(np.float32)
    return xs, ys, xq


def toy_artifact(rng):
    return {
        "w": rng.rand(3, 4).astype(np.float32),
        "b": [rng.rand(4).astype(np.float32), np.int32(7)],
    }


def digest_of(i: int) -> str:
    return f"{i:064x}"


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.deactivate()
    yield
    faultinject.deactivate()


# ---------------------------------------------------------------------------
# Atomic writer + artifact spill primitives
# ---------------------------------------------------------------------------


def test_atomic_write_leaves_no_temp_residue(tmp_path):
    path = tmp_path / "sub" / "artifact.bin"
    atomic_write_bytes(str(path), b"payload")
    assert path.read_bytes() == b"payload"
    assert [p.name for p in path.parent.iterdir()] == ["artifact.bin"]


def test_spill_round_trip_bit_exact(tmp_path):
    rng = np.random.RandomState(0)
    spill = ArtifactSpill(str(tmp_path))
    artifact = toy_artifact(rng)
    assert spill.put(digest_of(1), artifact, learner="maml", state_version=0)
    back = spill.get(digest_of(1), learner="maml", state_version=0)
    assert back is not None
    orig_leaves, orig_def = jax.tree_util.tree_flatten(artifact)
    back_leaves, back_def = jax.tree_util.tree_flatten(back)
    assert orig_def == back_def
    for a, b in zip(orig_leaves, back_leaves):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)
    assert spill.stats["hits"] == 1 and spill.stats["writes"] == 1


def test_spill_version_or_learner_mismatch_is_a_skip_not_a_quarantine(tmp_path):
    rng = np.random.RandomState(1)
    spill = ArtifactSpill(str(tmp_path))
    spill.put(digest_of(2), toy_artifact(rng), learner="maml", state_version=0)
    assert spill.get(digest_of(2), learner="maml", state_version=1) is None
    assert spill.get(digest_of(2), learner="gradient_descent",
                     state_version=0) is None
    assert spill.stats["mismatch_skipped"] == 2
    assert spill.stats["corrupt_quarantined"] == 0
    # The entry is intact — a matching reader still gets it.
    assert spill.get(digest_of(2), learner="maml", state_version=0) is not None


def test_spill_prune_bounds_entry_count(tmp_path):
    rng = np.random.RandomState(2)
    spill = ArtifactSpill(str(tmp_path), max_entries=2)
    for i in range(5):
        spill.put(digest_of(i), toy_artifact(rng), learner="maml",
                  state_version=0)
        time.sleep(0.01)  # distinct mtimes so prune order is deterministic
    assert len(spill.entries()) <= 2
    assert spill.stats["pruned"] >= 3
    # The newest entry survives.
    assert spill.get(digest_of(4), learner="maml", state_version=0) is not None


# ---------------------------------------------------------------------------
# Fault hooks: torn write / bit flip / stale fence -> quarantine + cold path
# ---------------------------------------------------------------------------


def test_torn_spill_write_is_quarantined_on_read(tmp_path):
    rng = np.random.RandomState(3)
    spill = ArtifactSpill(str(tmp_path))
    faultinject.activate(faultinject.FaultPlan(torn_spill_write_at=1))
    spill.put(digest_of(7), toy_artifact(rng), learner="maml", state_version=0)
    assert any(e.startswith("torn-spill:") for e in faultinject.events)
    faultinject.deactivate()
    assert spill.get(digest_of(7), learner="maml", state_version=0) is None
    assert spill.stats["corrupt_quarantined"] == 1
    assert os.path.exists(spill.path_for(digest_of(7)) + ".corrupt")
    assert not os.path.exists(spill.path_for(digest_of(7)))


def test_corrupt_cache_entry_is_quarantined_on_read(tmp_path):
    rng = np.random.RandomState(4)
    spill = ArtifactSpill(str(tmp_path))
    spill.put(digest_of(9), toy_artifact(rng), learner="maml", state_version=0)
    faultinject.activate(faultinject.FaultPlan(corrupt_cache_entry_at=1))
    assert spill.get(digest_of(9), learner="maml", state_version=0) is None
    assert any(e.startswith("corrupt-entry:") for e in faultinject.events)
    assert spill.stats["corrupt_quarantined"] == 1
    assert os.path.exists(spill.path_for(digest_of(9)) + ".corrupt")


def test_corrupt_entry_degrades_to_cold_adapt_same_answer(tmp_path, rng):
    """A bit-flipped spill entry must cost only the re-adapt: the respawned
    replica quarantines it, falls back to the cold path, and answers the
    request with the SAME logits the warm path would have produced."""
    xs, ys, xq = episode(rng)
    api1 = make_api(tmp_path)
    try:
        warm = api1.classify(xs, ys, xq)
    finally:
        api1.close()
    faultinject.activate(faultinject.FaultPlan(corrupt_cache_entry_at=1))
    api2 = make_api(tmp_path)
    try:
        cold = api2.classify(xs, ys, xq)
        stats = api2.engine.tier_stats()
    finally:
        api2.close()
    assert not cold["cache_hit"], "corrupt entry must not serve as a hit"
    np.testing.assert_array_equal(
        np.asarray(warm["logits"]), np.asarray(cold["logits"])
    )
    assert stats["spill"]["corrupt_quarantined"] == 1


@pytest.mark.skipif(
    not serialization_available(), reason="jax serialize_executable missing"
)
def test_stale_exec_fence_recompiles_instead_of_running_wrong_code(
    tmp_path, rng, compile_guard
):
    xs, ys, xq = episode(rng)
    api1 = make_api(tmp_path)
    try:
        api1.engine.warmup([(5, 1, 3)])
        want = api1.classify(xs, ys, xq)
    finally:
        api1.close()
    faultinject.activate(
        faultinject.FaultPlan(stale_exec_cache_at=1)
    )
    with compile_guard() as guard:
        api2 = make_api(tmp_path)
        try:
            api2.engine.warmup([(5, 1, 3)])
            got = api2.classify(xs, ys, xq)
            stats = api2.engine.tier_stats()
        finally:
            api2.close()
    assert "stale-exec-fence" in faultinject.events
    assert stats["exec"]["stale"] >= 1
    # The stale executable was rejected -> at least one REAL compile.
    assert len(guard.events) >= 1
    np.testing.assert_array_equal(
        np.asarray(want["logits"]), np.asarray(got["logits"])
    )


# ---------------------------------------------------------------------------
# Warm respawn: rehydrated hot set + zero XLA compiles
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("family", sorted(LEARNER_CLASSES))
def test_respawn_first_repeat_request_hits_without_readapt(tmp_path, family):
    """All three learner families: the artifact a replica spilled is
    rehydrated bit-exact by its successor — the first repeat request is a
    cache hit with identical logits, no inner loop run."""
    rng = np.random.RandomState(5)
    xs, ys, xq = episode(rng)
    api1 = make_api(tmp_path, learner_cls=LEARNER_CLASSES[family])
    try:
        first = api1.classify(xs, ys, xq)
        assert not first["cache_hit"]
    finally:
        api1.close()
    api2 = make_api(tmp_path, learner_cls=LEARNER_CLASSES[family])
    try:
        again = api2.classify(xs, ys, xq)
    finally:
        api2.close()
    assert again["cache_hit"], "rehydrated digest must hit, not re-adapt"
    np.testing.assert_array_equal(
        np.asarray(first["logits"]), np.asarray(again["logits"])
    )


@pytest.mark.skipif(
    not serialization_available(), reason="jax serialize_executable missing"
)
def test_warm_respawn_performs_zero_xla_compiles(tmp_path, rng, compile_guard):
    """THE acceptance gate: construct + warm up + serve a fresh engine on a
    primed tier dir entirely under ``compile_guard`` — zero compile events,
    and the answers are bit-exact with the cold engine's."""
    xs, ys, xq = episode(rng)
    learner = MAMLFewShotLearner(tiny_cfg())
    state = learner.init_state(jax.random.key(0))
    cfg = ServeConfig(meta_batch_size=2, max_wait_ms=0.0,
                      tier_dir=str(tmp_path))
    api1 = ServingAPI(learner, state, cfg)
    try:
        api1.engine.warmup([(5, 1, 3)])
        want = api1.classify(xs, ys, xq)
    finally:
        api1.close()
    with compile_guard() as guard:
        api2 = ServingAPI(learner, state, cfg)
        try:
            api2.engine.warmup([(5, 1, 3)])
            got = api2.classify(xs, ys, xq)
            stats = api2.engine.tier_stats()
        finally:
            api2.close()
    assert guard.events == [], (
        "warm respawn compiled: "
        + ", ".join(e.name for e in guard.events)
    )
    assert got["cache_hit"]
    assert stats["aot_programs"] >= 2  # adapt + classify came from disk
    np.testing.assert_array_equal(
        np.asarray(want["logits"]), np.asarray(got["logits"])
    )


def test_exec_cache_fence_names_the_build_provenance(tmp_path):
    fence = build_fence("serve_adapt_maml", "adapt;float32:(5, 1, 8, 8)")
    for field in ("jax", "jaxlib", "backend", "device_kind", "program",
                  "signature", "donation", "sharding"):
        assert field in fence, fence
    cache = ExecutableCache(str(tmp_path))
    assert cache.get("serve_adapt_maml", "sig") is None
    assert cache.stats["misses"] == 1


# ---------------------------------------------------------------------------
# Consistent-hash ring
# ---------------------------------------------------------------------------


def test_ring_spreads_keys_and_routes_deterministically():
    ring = HashRing()
    for node in (0, 1, 2):
        ring.add(node)
    keys = [f"episode-{i}" for i in range(300)]
    owners = {k: ring.route(k) for k in keys}
    by_node = {n: sum(1 for o in owners.values() if o == n) for n in (0, 1, 2)}
    assert all(count > 0 for count in by_node.values()), by_node
    assert {ring.route(k) for k in keys for _ in range(2)} == {0, 1, 2}
    assert all(ring.route(k) == owners[k] for k in keys)


def test_ring_removal_moves_only_the_dead_nodes_keys():
    ring = HashRing()
    for node in (0, 1, 2):
        ring.add(node)
    keys = [f"episode-{i}" for i in range(300)]
    before = {k: ring.route(k) for k in keys}
    ring.remove(1)
    assert 1 not in ring and len(ring) == 2
    moved = [k for k in keys if ring.route(k) != before[k]]
    assert set(moved) == {k for k in keys if before[k] == 1}, (
        "a retirement may only re-home the dead node's keys"
    )
    succ = ring.successor(1)
    assert succ in (0, 2)


def test_ring_empty_routes_none():
    ring = HashRing()
    assert ring.route("anything") is None
    ring.add(3)
    assert ring.route("anything") == 3
    ring.remove(3)
    assert ring.route("anything") is None


# ---------------------------------------------------------------------------
# Fleet routing + dead-replica spill adoption
# ---------------------------------------------------------------------------


def tier_pool(tier_root, n=2, **pool_kw):
    def factory(index: int) -> LocalReplica:
        api = make_api(os.path.join(str(tier_root), f"replica-{index}"))
        api.engine.warmup([(5, 1, 3)])
        return LocalReplica(api, replica_id=f"local-{index}")

    defaults = dict(
        n_replicas=n,
        health_interval_s=0.02,
        health_timeout_s=1.0,
        unhealthy_after=2,
        restart_backoff_s=0.02,
        restart_backoff_max_s=1.0,
        min_uptime_s=0.0,
        route_by_digest=True,
        tier_root=str(tier_root),
    )
    defaults.update(pool_kw)
    pool = ReplicaPool(factory, PoolConfig(**defaults))
    assert pool.wait_ready(timeout=120.0), "pool never became healthy"
    return pool


def test_pool_digest_affinity_repeat_traffic_all_hits(tmp_path, rng):
    pool = tier_pool(tmp_path)
    try:
        episodes = [episode(rng) for _ in range(6)]
        for xs, ys, xq in episodes:
            pool.classify(xs, ys, xq)
        # Same digests route to the same replicas: every repeat is a hit.
        for xs, ys, xq in episodes:
            out = pool.classify(xs, ys, xq)
            assert out["cache_hit"], "digest affinity broke: repeat missed"
        assert pool.stats()["ring_nodes"] == 2
        assert pool.stats()["replica_ready_s"] is not None
    finally:
        pool.close()


def test_killed_replica_spill_adopted_by_successor(tmp_path, rng):
    """SIGKILL-equivalent death under traffic: the request is still
    answered, the ring re-forms, the successor rehydrates the dead
    replica's spill dir, and the dead replica's episodes keep hitting."""
    pool = tier_pool(tmp_path)
    try:
        episodes = [episode(rng) for _ in range(6)]
        for xs, ys, xq in episodes:
            out = pool.classify(xs, ys, xq)
            assert "logits" in out
        faultinject.activate(
            faultinject.FaultPlan(replica_kill_at_request=1)
        )
        out = pool.classify(*episodes[0][:3])  # kills a replica; re-dispatched
        assert "logits" in out
        faultinject.deactivate()
        deadline = time.time() + 30.0
        while time.time() < deadline:
            s = pool.stats()
            if s["rehydrations_total"] >= 1 and s["ring_nodes"] == 2:
                break
            time.sleep(0.02)
        s = pool.stats()
        assert s["replica_deaths_total"] >= 1
        assert s["rehydrations_total"] >= 1, s
        assert s["ring_nodes"] == 2, s
        # Every pre-death episode still hits: the successor adopted the
        # dead replica's artifacts, nothing re-adapts.
        for xs, ys, xq in episodes:
            assert pool.classify(xs, ys, xq)["cache_hit"]
        assert pool.stats()["request_errors"] == 0
        text = pool.metrics_text()
        assert "_rehydrations_total" in text
        assert "_replica_ready_s" in text
    finally:
        pool.close()
