"""graftlint v3: the IR-level program contract analyzer (ISSUE 17).

Covers the program registry pin (the jax-free ``PROGRAM_REGISTRY_NAMES``
literal vs the built registry), the tree-wide ``--programs`` CLI gate
(every registered program clean at HEAD, the maml train forms within the
declared collective budget), seeded positive AND negative cases for each
of the five program rules — including THE acceptance regression:
re-introducing per-leaf psums turns ``collective-budget`` red while the
fused flat-bucket form passes — the scan-body-once × dispatch-multiplier
accounting pin, and GitHub-annotation formatting for every new rule.
"""

import os
import re
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.models.common import (
    PROGRAM_REGISTRY_NAMES,
    ProgramSpec,
    registered_programs,
)
from tools.graftlint.programs import (
    PROGRAM_RULES,
    CollectiveBudgetRule,
    DonationViolationRule,
    DtypeLeakRule,
    HostCallbackInStepRule,
    SpecCoverageRule,
    analyze_program,
    lint_programs,
    render_program_table,
    walk_jaxpr,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RULE_BY_ID = {rule.id: rule for rule in PROGRAM_RULES}


def _violations(rule, analysis):
    return list(rule.check_program(analysis))


# ---------------------------------------------------------------------------
# Registry pin + HEAD-clean gates
# ---------------------------------------------------------------------------


def test_registry_matches_declared_name_table():
    """The jax-free AST-parsed literal and the built registry agree
    exactly (this process has 8 devices, so every mesh variant builds) —
    the same both-directions contract EMITTED_KEYS carries for bench."""
    built = [spec.name for spec in registered_programs()]
    assert sorted(built) == sorted(PROGRAM_REGISTRY_NAMES)
    assert len(built) == len(set(built))


def test_lint_programs_clean_at_head():
    """THE tentpole acceptance: every registered program passes every
    program rule at HEAD — in particular every maml train form sits
    within the declared collective budget."""
    assert [v.format_text() for v in lint_programs()] == []


def test_maml_train_forms_within_budget_at_head():
    from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner

    budget = MAMLFewShotLearner.collective_budget
    assert budget <= 4
    train_forms = [
        spec for spec in registered_programs()
        if spec.name.startswith("maml/train")
    ]
    assert train_forms, "registry lost the maml train programs"
    for spec in train_forms:
        analysis = analyze_program(spec)
        assert analysis.error is None, (spec.name, analysis.error)
        assert analysis.collective_count <= budget, (
            spec.name, analysis.collective_count
        )


def test_programs_cli_gate_tree_wide():
    """The CI surface: ``python -m tools.graftlint --programs`` exits 0
    at HEAD and prints the program table over the full registered set."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)  # the CLI forces its own 8-device platform
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--programs"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert f"{len(PROGRAM_REGISTRY_NAMES)} program(s) clean" in proc.stderr
    for name in PROGRAM_REGISTRY_NAMES:
        assert name in proc.stdout
    # The maml train-step row reads within budget ("ok", never "over").
    assert "over budget" not in proc.stdout


# ---------------------------------------------------------------------------
# collective-budget: the fused-all-reduce regression pin
# ---------------------------------------------------------------------------


def _dp_maml_spec(collective_fusion, budget):
    """A dp=2 maml train-step ProgramSpec in the requested fusion mode —
    the seeded-violation twin of the registry's maml/train_step entry."""
    from howtotrainyourmamlpytorch_tpu.models.common import (
        _tiny_backbone_kwargs,
        _tiny_episode_batch,
    )
    from howtotrainyourmamlpytorch_tpu.models.maml import (
        BackboneConfig,
        MAMLConfig,
        MAMLFewShotLearner,
    )
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import make_mesh

    def build():
        cfg = MAMLConfig(
            backbone=BackboneConfig(**_tiny_backbone_kwargs()),
            number_of_training_steps_per_iter=2,
            number_of_evaluation_steps_per_iter=2,
            collective_fusion=collective_fusion,
        )
        mesh = make_mesh(
            jax.devices()[:2], data_parallel=2, model_parallel=1
        )
        learner = MAMLFewShotLearner(cfg, mesh=mesh)
        state = learner.init_state(jax.random.PRNGKey(0))
        batch = learner._prepare_batch(_tiny_episode_batch())
        importance = jnp.asarray(learner._train_importance(100))
        fn = learner._get_train_step(second_order=True, final_only=True)
        return fn, (state, batch, importance)

    return ProgramSpec(
        name=f"seeded/train_{collective_fusion}",
        source="howtotrainyourmamlpytorch_tpu/models/maml.py",
        build=build,
        collective_budget=budget,
        donate=True,
    )


def test_per_leaf_psum_storm_turns_collective_budget_red():
    """THE ISSUE 17 regression: flipping the dp step back to per-leaf
    psums (one per grad/BN/LSLR leaf) blows the declared budget and the
    rule names the storm; the fused flat-bucket form passes the same
    budget with exactly its per-dtype-bucket collective count."""
    rule = RULE_BY_ID["collective-budget"]
    assert isinstance(rule, CollectiveBudgetRule)

    storm = analyze_program(_dp_maml_spec("per_leaf", budget=4))
    assert storm.error is None, storm.error
    assert storm.collective_count > 4
    found = _violations(rule, storm)
    assert len(found) == 1
    assert "psum" in found[0].message
    assert "collective_budget of 4" in found[0].message

    fused = analyze_program(_dp_maml_spec("bucketed", budget=4))
    assert fused.error is None, fused.error
    assert 1 <= fused.collective_count <= 4
    assert _violations(rule, fused) == []
    # The storm moves no more payload than the fused form concentrates
    # into flat buckets — the win is op count (per-op latency), and the
    # comm-bytes column must reflect a real payload either way.
    assert fused.comm_bytes > 0


def test_scan_body_collectives_count_once_times_dispatch_multiplier():
    """The dispatch-multiplier accounting pin: a K=25 scanned multi-iter
    step walks its scan body ONCE — the collective count is the
    per-meta-iteration count (identical to K=1), and the declared K rides
    the spec, exactly like the FLOPs ledger's scan-body-once rule."""
    from howtotrainyourmamlpytorch_tpu.models.common import (
        _tiny_backbone_kwargs,
        _tiny_episode_batch,
        dispatch_multiplier,
    )
    from howtotrainyourmamlpytorch_tpu.models.maml import (
        BackboneConfig,
        MAMLConfig,
        MAMLFewShotLearner,
    )
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import make_mesh

    K = 25

    def build():
        cfg = MAMLConfig(
            backbone=BackboneConfig(**_tiny_backbone_kwargs()),
            number_of_training_steps_per_iter=2,
            number_of_evaluation_steps_per_iter=2,
        )
        mesh = make_mesh(
            jax.devices()[:2], data_parallel=2, model_parallel=1
        )
        learner = MAMLFewShotLearner(cfg, mesh=mesh)
        state = learner.init_state(jax.random.PRNGKey(0))
        single = learner._prepare_batch(_tiny_episode_batch())
        stacked = tuple(
            jnp.stack([jnp.asarray(part)] * K) for part in single
        )
        importance = jnp.asarray(learner._train_importance(100))
        fn = learner._get_multi_train_step(
            second_order=True, final_only=True
        )
        return fn, (state, stacked, importance)

    spec = ProgramSpec(
        name="seeded/train_multi_k25",
        source="howtotrainyourmamlpytorch_tpu/models/maml.py",
        build=build,
        collective_budget=4,
        k=K,
    )
    analysis = analyze_program(spec)
    assert analysis.error is None, analysis.error
    k1 = analyze_program(_dp_maml_spec("bucketed", budget=4))
    assert analysis.collective_count == k1.collective_count
    assert analysis.spec.k == K
    # The declared K the spec carries is the same multiplier the ledger
    # derives from the stacked batch form (models/common contract).
    _fn, (_state, stacked, _imp) = spec.build()
    assert dispatch_multiplier(stacked) == K


# ---------------------------------------------------------------------------
# dtype-leak
# ---------------------------------------------------------------------------


def _leak_spec(compute_dtype, cast):
    def build():
        def fn(x, w):
            if cast:
                x = x.astype(jnp.bfloat16)
                w = w.astype(jnp.bfloat16)
            return jnp.dot(x, w)

        args = (jnp.ones((4, 4), jnp.float32), jnp.ones((4, 4), jnp.float32))
        return fn, args

    return ProgramSpec(
        name="seeded/leak", source="seeded.py", build=build,
        compute_dtype=compute_dtype,
    )


def test_dtype_leak_fires_on_f32_matmul_in_declared_bf16_program():
    rule = RULE_BY_ID["dtype-leak"]
    assert isinstance(rule, DtypeLeakRule)
    leaky = analyze_program(_leak_spec("bfloat16", cast=False))
    found = _violations(rule, leaky)
    assert len(found) == 1
    assert "dot_general" in found[0].message


def test_dtype_leak_negative_cases():
    rule = RULE_BY_ID["dtype-leak"]
    # Properly cast bf16 compute: clean.
    assert _violations(rule, analyze_program(_leak_spec("bfloat16", cast=True))) == []
    # f32-declared programs never run this check (f32 matmuls are the contract).
    assert _violations(rule, analyze_program(_leak_spec("float32", cast=False))) == []
    # The REAL declared-bf16 train step is clean by construction: the PR 9
    # boundary casts and the f32-master update chain carry no contractions.
    bf16 = next(
        spec for spec in registered_programs()
        if spec.name == "maml/train_step_bf16"
    )
    assert bf16.compute_dtype == "bfloat16"
    analysis = analyze_program(bf16)
    assert analysis.f32_contractions == {}
    assert _violations(rule, analysis) == []


# ---------------------------------------------------------------------------
# donation-violation
# ---------------------------------------------------------------------------


def _donation_spec(donate_argnums):
    def build():
        def step(state, x):
            return {"w": state["w"] + x.sum(), "b": state["b"] * 2.0}

        fn = (
            jax.jit(step, donate_argnums=donate_argnums)
            if donate_argnums else jax.jit(step)
        )
        state = {"w": jnp.ones((8,)), "b": jnp.zeros((4,))}
        return fn, (state, jnp.ones((3,)))

    return ProgramSpec(
        name="seeded/donation", source="seeded.py", build=build, donate=True,
    )


def test_donation_violation_fires_when_jit_drops_donation():
    rule = RULE_BY_ID["donation-violation"]
    assert isinstance(rule, DonationViolationRule)
    undonated = analyze_program(_donation_spec(donate_argnums=None))
    found = _violations(rule, undonated)
    assert len(found) == 1
    assert "0 of 2 donated state leaves" in found[0].message


def test_donation_violation_negative_on_donating_jit_and_real_steps():
    rule = RULE_BY_ID["donation-violation"]
    donated = analyze_program(_donation_spec(donate_argnums=(0,)))
    assert donated.aliased_outputs >= donated.donated_leaves
    assert _violations(rule, donated) == []
    # Every registry program that declares donation really aliases its
    # whole state — including the sharded mp form, whose lowering defers
    # pairing to XLA via jax.buffer_donor markers.
    for spec in registered_programs():
        if not spec.donate:
            continue
        analysis = analyze_program(spec)
        assert _violations(rule, analysis) == [], spec.name


# ---------------------------------------------------------------------------
# host-callback-in-step
# ---------------------------------------------------------------------------


def _callback_spec(with_callback):
    def build():
        def fn(x):
            if with_callback:
                x = jax.pure_callback(
                    lambda v: np.asarray(v) * 2.0,
                    jax.ShapeDtypeStruct(x.shape, x.dtype),
                    x,
                )
            return x + 1.0

        return fn, (jnp.ones((4,)),)

    return ProgramSpec(
        name="seeded/callback", source="seeded.py", build=build,
    )


def test_host_callback_rule_fires_and_stays_silent():
    rule = RULE_BY_ID["host-callback-in-step"]
    assert isinstance(rule, HostCallbackInStepRule)
    hot = analyze_program(_callback_spec(True))
    found = _violations(rule, hot)
    assert len(found) == 1
    assert "pure_callback" in found[0].message
    assert _violations(rule, analyze_program(_callback_spec(False))) == []
    for spec in registered_programs():
        assert _violations(rule, analyze_program(spec)) == [], spec.name


# ---------------------------------------------------------------------------
# spec-coverage
# ---------------------------------------------------------------------------


def test_spec_coverage_clean_at_head():
    rule = RULE_BY_ID["spec-coverage"]
    assert isinstance(rule, SpecCoverageRule)
    assert list(rule.check_registry([])) == []


def test_spec_coverage_flags_dead_rule(monkeypatch):
    from howtotrainyourmamlpytorch_tpu.parallel import sharding

    rule = RULE_BY_ID["spec-coverage"]
    dead = (r"phantom_module/weight$", sharding.P("model"))
    monkeypatch.setattr(
        sharding, "MP_STATE_RULES",
        (dead,) + tuple(sharding.MP_STATE_RULES),
    )
    found = list(rule.check_registry([]))
    assert len(found) == 1
    assert "phantom_module" in found[0].message
    assert "dead rule" in found[0].message
    assert found[0].path.endswith("parallel/sharding.py")


def test_spec_coverage_flags_unmatched_leaf(monkeypatch):
    from howtotrainyourmamlpytorch_tpu.parallel import sharding

    rule = RULE_BY_ID["spec-coverage"]
    # Drop the DP catch-all: every state leaf of every family goes
    # unmatched — the shard-time ValueError as a static finding.
    monkeypatch.setattr(
        sharding, "DP_STATE_RULES", ((r"^never-matches$", sharding.P()),),
    )
    found = list(rule.check_registry([]))
    unmatched = [v for v in found if "matches no rule" in v.message]
    assert unmatched, [v.message for v in found]
    assert any("DP_STATE_RULES" in v.message for v in unmatched)


# ---------------------------------------------------------------------------
# CLI surface: annotations, select, table rendering
# ---------------------------------------------------------------------------


def test_every_program_rule_registered_with_github_annotations():
    """Each program rule rides the shared registry (``--list-rules``,
    README sync) and its violations carry well-formed GitHub annotations
    — the CI surface ``--programs --format=github`` prints."""
    from tools.graftlint import RULES

    seeded = {
        "collective-budget": analyze_program(_dp_maml_spec("per_leaf", 4)),
        "dtype-leak": analyze_program(_leak_spec("bfloat16", cast=False)),
        "donation-violation": analyze_program(_donation_spec(None)),
        "host-callback-in-step": analyze_program(_callback_spec(True)),
    }
    for rule_id, analysis in seeded.items():
        assert rule_id in RULES
        found = _violations(RULE_BY_ID[rule_id], analysis)
        assert found, rule_id
        annotation = found[0].format_github()
        assert annotation.startswith("::error file=")
        assert f"title=graftlint {rule_id}" in annotation
    assert "spec-coverage" in RULES
    table_violation = SpecCoverageRule()._table_violation("DP_STATE_RULES", "x")
    assert "title=graftlint spec-coverage" in table_violation.format_github()


def test_lint_programs_select_filters_rules():
    storm = analyze_program(_dp_maml_spec("per_leaf", budget=4))
    hits = lint_programs({"collective-budget"}, [storm])
    assert hits and all(v.rule == "collective-budget" for v in hits)
    assert lint_programs({"dtype-leak"}, [storm]) == []


def test_program_table_renders_budget_status():
    storm = analyze_program(_dp_maml_spec("per_leaf", budget=4))
    fused = analyze_program(_dp_maml_spec("bucketed", budget=4))
    table = render_program_table([storm, fused])
    assert "over budget" in table
    assert re.search(r"seeded/train_bucketed\s+\d+\s+\d+\s+4\s+1\s+ok", table)


# ---------------------------------------------------------------------------
# Walker semantics
# ---------------------------------------------------------------------------


def test_walker_descends_scan_cond_and_pjit():
    def fn(x):
        def body(carry, _):
            return jnp.sin(carry) + 1.0, None

        out, _ = jax.lax.scan(body, x, None, length=3)
        return jax.lax.cond(
            (out > 0).all(), jnp.cos, lambda v: v * 2.0, out
        )

    closed = jax.make_jaxpr(jax.jit(fn))(jnp.ones((3,)))
    names = []
    walk_jaxpr(closed.jaxpr, lambda eqn: names.append(eqn.primitive.name))
    assert names.count("sin") == 1  # scan body walked once, not x3 (length)
    assert "cos" in names  # cond branches and pjit bodies are descended
