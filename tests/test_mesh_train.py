"""Mesh-aware device prefetch + dp-sharded dispatch paths (ISSUE 8).

PR 7 disabled the stager on mesh runs (its bare single-device
``device_put`` would fight the pinned ``in_shardings``). The mesh-aware
stager closes that gap: staged arrays are ``device_put`` straight into the
learner's declared batch sharding (``staged_batch_sharding``), so
multi-chip runs keep the overlapped pipeline. Contracts, all on the
8-device virtual CPU mesh (conftest):

* staged == inline BIT-EXACT on the mesh, on the K=1 AND the K-scan
  dispatch path (staging is a layout-aware transfer, not a program change);
* the dp-sharded step programs compile exactly once with the stager active
  and the staged loop issues zero ``jax.device_get`` — the PR 2
  compile-once and PR 5 zero-new-syncs invariants hold on mesh runs;
* dp-sharded training from the same init matches single-device training at
  equal global meta-batch (meta-gradients compared under float-reassociation
  tolerances — the ``test_sharding.py`` precedent);
* the ``staged_batch_sharding`` declaration contract across all three
  learners: task axis over ``dp`` for MAML (second axis on the K-scan
  form), replicated for the sequential baselines, ``None`` (decline —
  inline host loop) without a mesh and on mp meshes, where the arg-driven
  theta layout must not be fought by a committed staged layout.

First-order programs under ``spmd_fo_compile_guard``: the GSPMD conv
CHECK-crash some jaxlibs carry (convolution_handler.cc:831) is
SECOND-ORDER-specific, so these tests keep real mesh coverage on backends
where the second-order sharded tests must skip.
"""

import jax
import pytest
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from howtotrainyourmamlpytorch_tpu.data.device_prefetch import (
    DevicePrefetcher,
)
from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    GradientDescentLearner,
    MAMLConfig,
    MAMLFewShotLearner,
    MatchingNetsLearner,
)
from howtotrainyourmamlpytorch_tpu.models.common import (
    StagedBatch,
    WireCodec,
    prepare_batch,
)
from howtotrainyourmamlpytorch_tpu.parallel import make_mesh
from howtotrainyourmamlpytorch_tpu.parallel.mesh import (
    DEFAULT_DATA_AXIS,
)


def tiny_cfg(**kw):
    kw.setdefault("second_order", False)
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=4,
            num_classes=5,
            image_height=8,
            image_width=8,
            num_steps=2,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        use_multi_step_loss_optimization=False,
        wire_codec=WireCodec(1.0, None, None),
        **kw,
    )


def dp_mesh(n=8):
    return make_mesh(jax.devices()[:n], data_parallel=n, model_parallel=1)


def make_samples(rng, n, tasks=8):
    """n loader-layout samples whose task axis divides the 8-way dp mesh."""
    samples = []
    for i in range(n):
        xs = rng.randint(0, 2, (tasks, 5, 1, 1, 8, 8)).astype(np.float32)
        xt = rng.randint(0, 2, (tasks, 5, 1, 1, 8, 8)).astype(np.float32)
        ys = np.tile(np.arange(5)[None, :, None], (tasks, 1, 1)).astype(
            np.int32
        )
        samples.append((xs, xt, ys, ys.copy(), np.full(tasks, 100 + i)))
    return samples


def stage_all(samples, learner, group):
    stager = DevicePrefetcher(
        iter(samples),
        lambda b: prepare_batch(b, codec=learner.cfg.wire_codec),
        depth=2,
        group=group,
        sharding=learner.staged_batch_sharding(group),
    )
    try:
        return list(stager)
    finally:
        stager.close()


# ---------------------------------------------------------------------------
# Bit-exactness: staged == inline on the mesh
# ---------------------------------------------------------------------------


def test_mesh_staged_k1_training_bitwise_identical(spmd_fo_compile_guard):
    rng = np.random.RandomState(0)
    samples = make_samples(rng, 5)
    mesh = dp_mesh()
    learner = MAMLFewShotLearner(tiny_cfg(), mesh=mesh)
    s_inline = learner.shard_state(learner.init_state(jax.random.PRNGKey(7)))
    s_staged = learner.shard_state(learner.init_state(jax.random.PRNGKey(7)))

    for sample in samples:
        s_inline, _ = learner.run_train_iter(s_inline, sample[:4], epoch=0)

    staged = stage_all(samples, learner, group=1)
    assert [b.n_iters for b in staged] == [1] * 5
    for batch in staged:
        assert isinstance(batch, StagedBatch)
        # The staged arrays arrived already laid out for the pinned
        # in_shardings: task axis over 'dp', on THIS mesh.
        sh = batch.arrays[0].sharding
        assert isinstance(sh, NamedSharding)
        assert sh.mesh.shape == mesh.shape
        assert sh.spec == P(DEFAULT_DATA_AXIS)
        s_staged, _ = learner.run_train_iter(s_staged, batch, epoch=0)

    for a, b in zip(jax.tree.leaves(s_inline), jax.tree.leaves(s_staged)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_mesh_staged_k_scan_bitwise_identical(spmd_fo_compile_guard):
    """group=K stages whole pre-stacked scan dispatches, laid out with the
    task axis SECOND (after the leading K axis) per the learner's K-scan
    in_shardings."""
    rng = np.random.RandomState(1)
    samples = make_samples(rng, 7)
    mesh = dp_mesh()
    learner = MAMLFewShotLearner(tiny_cfg(), mesh=mesh)
    s_inline = learner.shard_state(learner.init_state(jax.random.PRNGKey(9)))
    s_staged = learner.shard_state(learner.init_state(jax.random.PRNGKey(9)))

    for chunk in (samples[:3], samples[3:6], samples[6:]):
        s_inline, _ = learner.run_train_iters(
            s_inline, [c[:4] for c in chunk], epoch=0
        )

    staged = stage_all(samples, learner, group=3)
    assert [b.n_iters for b in staged] == [3, 3, 1]
    for batch in staged:
        sh = batch.arrays[0].sharding
        assert isinstance(sh, NamedSharding)
        assert sh.spec == P(None, DEFAULT_DATA_AXIS)
        s_staged, _ = learner.run_train_iters(s_staged, batch, epoch=0)

    for a, b in zip(jax.tree.leaves(s_inline), jax.tree.leaves(s_staged)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Compile-exactly-once + zero host syncs with the stager active on the mesh
# ---------------------------------------------------------------------------


def test_mesh_staged_k1_compiles_once_zero_syncs(
    compile_guard, spmd_fo_compile_guard
):
    """One warm inline dispatch, then a staged loop on the mesh: the
    dp-sharded step program must compile exactly once TOTAL (staged arrays
    present the identical signature AND layout) and the staged loop must
    trigger zero jax.device_get."""
    rng = np.random.RandomState(3)
    samples = make_samples(rng, 6)
    learner = MAMLFewShotLearner(tiny_cfg(), mesh=dp_mesh())
    state = learner.shard_state(learner.init_state(jax.random.PRNGKey(11)))

    with compile_guard() as guard:
        state, _ = learner.run_train_iter(state, samples[0][:4], epoch=0)
        jax.block_until_ready(state.theta)

        device_gets = {"n": 0}
        real_device_get = jax.device_get

        def counting_device_get(x):
            device_gets["n"] += 1
            return real_device_get(x)

        jax.device_get = counting_device_get
        try:
            staged = stage_all(samples[1:], learner, group=1)
            for batch in staged:
                state, _ = learner.run_train_iter(state, batch, epoch=0)
            jax.block_until_ready(state.theta)
        finally:
            jax.device_get = real_device_get
    guard.assert_compiles("_train_step", exactly=1)
    guard.assert_unique_signatures("_train_step")
    assert device_gets["n"] == 0


def test_mesh_staged_k_scan_compiles_once(compile_guard, spmd_fo_compile_guard):
    rng = np.random.RandomState(4)
    samples = make_samples(rng, 9)
    learner = MAMLFewShotLearner(tiny_cfg(), mesh=dp_mesh())
    state = learner.shard_state(learner.init_state(jax.random.PRNGKey(13)))
    with compile_guard() as guard:
        state, _ = learner.run_train_iters(
            state, [s[:4] for s in samples[:3]], epoch=0
        )
        staged = stage_all(samples[3:], learner, group=3)
        for batch in staged:
            state, _ = learner.run_train_iters(state, batch, epoch=0)
        jax.block_until_ready(state.theta)
    guard.assert_compiles("multi", exactly=1)
    guard.assert_unique_signatures("multi")


# ---------------------------------------------------------------------------
# dp-sharded vs single-device parity at equal global meta-batch
# ---------------------------------------------------------------------------


def test_dp_first_order_meta_grads_match_single_device(spmd_fo_compile_guard):
    """The first-order dp path (the program class that survives GSPMD-broken
    partitioners, and bench.py's fallback measurement program) produces the
    single-device meta-gradient at equal global meta-batch — sharding is a
    layout change, compared under reassociation tolerances (see the
    ``test_sharding._meta_grads`` note on why grads, not post-Adam params)."""
    rng = np.random.RandomState(5)
    batch = make_samples(rng, 1)[0][:4]
    cfg = tiny_cfg()
    ref = MAMLFewShotLearner(cfg)
    state = ref.init_state(jax.random.PRNGKey(3))
    prepared = ref._prepare_batch(batch)
    importance = jnp.asarray(ref._train_importance(100))

    def meta_grads(learner, st, prep, imp):
        def f(outer, bn, b, i):
            loss, _ = learner._meta_loss(
                outer, bn, b, i, 2, False, None, True
            )
            return loss

        outer = {"theta": st.theta, "lslr": st.lslr}
        return jax.jit(jax.grad(f))(outer, st.bn_state, prep, imp)

    ref_grads = meta_grads(ref, state, prepared, importance)

    mesh = dp_mesh()
    learner = MAMLFewShotLearner(cfg, mesh=mesh)
    state_s = learner.shard_state(state)
    prepared_s = tuple(
        jax.device_put(
            jnp.asarray(p), NamedSharding(mesh, P(DEFAULT_DATA_AXIS))
        )
        for p in prepared
    )
    dp_grads = meta_grads(learner, state_s, prepared_s, importance)

    for a, b in zip(jax.tree.leaves(ref_grads), jax.tree.leaves(dp_grads)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=2e-5
        )


# ---------------------------------------------------------------------------
# staged_batch_sharding declaration contract
# ---------------------------------------------------------------------------


def test_staged_batch_sharding_contract():
    """Pure declarations — no sharded conv program is compiled, so this
    runs on every backend (no spmd guard)."""
    cfg = tiny_cfg()
    mesh = dp_mesh()

    # No mesh: decline (the stager's plain single-device put is correct).
    assert MAMLFewShotLearner(cfg).staged_batch_sharding(1) is None

    # dp mesh: task axis over 'dp'; second axis on the K-scan form.
    maml = MAMLFewShotLearner(cfg, mesh=mesh)
    sh1 = maml.staged_batch_sharding(1)
    assert isinstance(sh1, NamedSharding)
    assert sh1.spec == P(DEFAULT_DATA_AXIS)
    shk = maml.staged_batch_sharding(3)
    assert shk.spec == P(None, DEFAULT_DATA_AXIS)

    # mp mesh: the arg-driven theta layout drives the program — decline,
    # the builder keeps the inline host loop there.
    mp_mesh = make_mesh(jax.devices()[:4], data_parallel=2, model_parallel=2)
    assert MAMLFewShotLearner(cfg, mesh=mp_mesh).staged_batch_sharding(1) is None

    # Sequential baselines: whole batch replicated on mesh runs, declined
    # without a mesh.
    for cls in (GradientDescentLearner, MatchingNetsLearner):
        assert cls(cfg).staged_batch_sharding(1) is None
        sh = cls(cfg, mesh=mesh).staged_batch_sharding(1)
        assert isinstance(sh, NamedSharding)
        assert sh.spec == P()


def test_default_mesh_from_args_refuses_oversized_mp_cleanly():
    """``--model_parallel_devices`` larger than the host must raise the
    explanatory ValueError, not a ZeroDivisionError from a 0-dp extent
    (dp default 0 = fill: len(devices) // mp == 0 there)."""
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import (
        default_mesh_from_args,
    )

    class Args:
        data_parallel_devices = 0
        model_parallel_devices = 16  # > the 8 virtual devices
        batch_size = 8

    with pytest.raises(ValueError, match="exceeds"):
        default_mesh_from_args(Args())


def test_sequential_learners_state_stays_replicated_on_mp_meshes():
    """gd/matching pin fully replicated in/out shardings on their step
    programs, so their state must NOT be laid out by MP_STATE_RULES on an
    mp mesh — that would force a reshard copy back to replicated on the
    first dispatch (and defeat donation). Only MAML declares
    ``supports_model_sharding``."""
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import replicated

    cfg = tiny_cfg()
    mp_mesh = make_mesh(jax.devices()[:4], data_parallel=2, model_parallel=2)
    rep = replicated(mp_mesh)
    for cls in (GradientDescentLearner, MatchingNetsLearner):
        learner = cls(cfg, mesh=mp_mesh)
        assert not learner.supports_model_sharding
        state = learner.init_state(jax.random.PRNGKey(0))
        shardings = learner.state_shardings(state)
        assert all(
            sh == rep for sh in jax.tree.leaves(shardings)
        ), "sequential learner state must ride replicated on mp meshes"
    maml = MAMLFewShotLearner(cfg, mesh=mp_mesh)
    assert maml.supports_model_sharding
    mp_specs = [
        sh.spec for sh in jax.tree.leaves(maml.state_shardings(maml.init_state(
            jax.random.PRNGKey(0))))
    ]
    assert any(any(ax is not None for ax in sp) for sp in mp_specs)
