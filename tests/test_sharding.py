"""Mesh-sharding correctness on the 8-device virtual CPU mesh (conftest).

SPMD must be a pure layout change: the same program with sharded arrays has
to produce the unsharded results. Covers the ``dp`` (task) axis end-to-end
through the learner and the ``mp`` (tensor) axis of
``parallel/mesh.param_shardings`` — conv output-channel sharding + the
row-parallel linear head (psum over partial products inserted by XLA) —
which the reference cannot do at all (its only strategy is
``nn.DataParallel`` scatter/gather, ``few_shot_learning_system.py:73-81``).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    param_shardings,
    replicated,
)


def _cfg(num_filters=8, second_order=True):
    return MAMLConfig(
        backbone=BackboneConfig(
            num_stages=2,
            num_filters=num_filters,
            per_step_bn_statistics=True,
            num_steps=2,
            num_classes=5,
            image_height=8,
            image_width=8,
        ),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        second_order=second_order,
    )


def _batch(rng, n_tasks=8):
    xs = rng.rand(n_tasks, 5, 1, 1, 8, 8).astype(np.float32)
    ys = np.tile(np.arange(5)[None, :, None], (n_tasks, 1, 1))
    return (xs, xs.copy(), ys, ys.copy())


def _meta_grads(learner, state, prepared, importance):
    """The outer meta-gradient — compared directly because comparing
    post-Adam parameters amplifies reduction-order noise on near-cancelling
    leaves into sign flips (Adam's first step is ~lr * sign(g))."""

    def f(outer, bn, batch, imp):
        loss, _ = learner._meta_loss(outer, bn, batch, imp, 2, True, None, True)
        return loss

    outer = {"theta": state.theta, "lslr": state.lslr}
    loss, grads = jax.jit(jax.value_and_grad(f))(
        outer, state.bn_state, prepared, importance
    )
    return loss, grads


def test_dp_meta_grads_match_unsharded(rng, spmd_compile_guard):
    batch = _batch(rng)
    learner = MAMLFewShotLearner(_cfg())
    state = learner.init_state(jax.random.PRNGKey(3))
    prepared = learner._prepare_batch(batch)
    importance = jnp.asarray(learner._train_importance(100))
    ref_loss, ref_grads = _meta_grads(learner, state, prepared, importance)

    mesh = make_mesh(jax.devices()[:8], data_parallel=8, model_parallel=1)
    state_s = state._replace(
        theta=jax.device_put(
            state.theta, jax.tree.map(lambda _: replicated(mesh), state.theta)
        ),
    )
    prepared_s = tuple(
        jax.device_put(jnp.asarray(p), batch_sharding(mesh)) for p in prepared
    )
    dp_loss, dp_grads = _meta_grads(learner, state_s, prepared_s, importance)

    np.testing.assert_allclose(float(ref_loss), float(dp_loss),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(ref_grads), jax.tree.leaves(dp_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=2e-5)


def test_dp_train_iter_runs_sharded(rng, spmd_compile_guard):
    """The learner's own mesh path (in_shardings pinned) trains to finite
    loss with the task axis over 8 devices."""
    batch = _batch(rng)
    mesh = make_mesh(jax.devices()[:8], data_parallel=8, model_parallel=1)
    learner = MAMLFewShotLearner(_cfg(), mesh=mesh)
    state = learner.init_state(jax.random.PRNGKey(3))
    state, metrics = learner.run_train_iter(state, batch, epoch=0)
    assert np.isfinite(float(metrics["loss"]))


def test_mp_backbone_forward_matches_replicated(rng):
    """Model-sharded forward (conv out-channels + row-parallel linear over
    ``mp``) equals the replicated forward."""
    learner = MAMLFewShotLearner(_cfg())
    state = learner.init_state(jax.random.PRNGKey(7))
    x = jnp.asarray(rng.rand(16, 1, 8, 8), jnp.float32)

    @jax.jit
    def fwd(theta, bn_state, x):
        logits, _ = learner.backbone.apply(theta, bn_state, x, 0)
        return logits

    ref_logits = fwd(state.theta, state.bn_state, x)

    mesh = make_mesh(jax.devices()[:4], data_parallel=2, model_parallel=2)
    theta_sh = param_shardings(mesh, state.theta, shard_model=True)
    # The guard must have actually sharded something, or this test is vacuous.
    specs = [s.spec for s in jax.tree.leaves(theta_sh)]
    assert any(any(ax is not None for ax in sp) for sp in specs)
    theta = jax.device_put(state.theta, theta_sh)
    bn_state = jax.device_put(
        state.bn_state, jax.tree.map(lambda _: replicated(mesh), state.bn_state)
    )
    x_sh = jax.device_put(x, batch_sharding(mesh))
    logits = fwd(theta, bn_state, x_sh)

    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=1e-5, atol=1e-6)


def test_mp_train_step_matches_replicated(rng, spmd_compile_guard):
    """A full second-order MAML train step with theta laid out over the
    ``mp`` axis (dp x mp = 2 x 2) produces the replicated step's results.
    Uses the inner-gradient anchor (mp_grad_anchor) the learner installs
    for mp meshes."""
    batch = _batch(rng, n_tasks=4)
    ref = MAMLFewShotLearner(_cfg())
    state0 = ref.init_state(jax.random.PRNGKey(11))
    importance = jnp.asarray(ref._train_importance(100))
    prepared = ref._prepare_batch(batch)

    ref_step = jax.jit(
        functools.partial(ref._train_step, second_order=True, final_only=True)
    )
    ref_state, ref_metrics = ref_step(state0, prepared, importance)

    mesh = make_mesh(jax.devices()[:4], data_parallel=2, model_parallel=2)
    mp = MAMLFewShotLearner(_cfg(), mesh=mesh)
    assert mp._inner_grad_anchor is not None
    state_mp = mp.init_state(jax.random.PRNGKey(11))  # same init as ref
    theta = jax.device_put(
        state_mp.theta, param_shardings(mesh, state_mp.theta, shard_model=True)
    )
    rep = lambda tree: jax.device_put(
        tree, jax.tree.map(lambda _: replicated(mesh), tree)
    )
    state_mp = state_mp._replace(
        theta=theta,
        lslr=rep(state_mp.lslr),
        bn_state=rep(state_mp.bn_state),
        opt_state=rep(state_mp.opt_state),
    )
    prepared_s = tuple(
        jax.device_put(jnp.asarray(p), NamedSharding(mesh, P("dp")))
        for p in prepared
    )
    mp_step = jax.jit(
        functools.partial(mp._train_step, second_order=True, final_only=True)
    )
    new_state, metrics = mp_step(state_mp, prepared_s, rep(importance))

    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-5, atol=1e-6
    )
    for leaf in jax.tree.leaves(new_state.theta):
        assert np.all(np.isfinite(np.asarray(leaf)))

    # Meta-gradients compared directly (see _meta_grads note): the layout
    # change must not alter the outer gradient beyond fp reassociation.
    _, ref_grads = _meta_grads(ref, state0, prepared, importance)
    _, mp_grads = _meta_grads(mp, state_mp, prepared_s, rep(importance))
    for a, b in zip(jax.tree.leaves(ref_grads), jax.tree.leaves(mp_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=2e-5)


# ---------------------------------------------------------------------------
# Declarative rule tables (parallel/sharding) — pure spec functions, no
# sharded program compiles: these run on every backend, no spmd guard.
# ---------------------------------------------------------------------------


def test_match_partition_rules_first_match_wins():
    """Rule ORDER is the policy: ``(^|/)lslr/`` precedes ``conv/weight$``,
    so an LSLR table whose path also ends in conv/weight stays replicated
    while theta's conv weight (and its Adam moment mirrors, matched
    anywhere in the path) shard over mp."""
    from howtotrainyourmamlpytorch_tpu.parallel.sharding import (
        MP_STATE_RULES,
        match_partition_rules,
    )

    tree = {
        "lslr": {"conv0": {"conv": {"weight": np.zeros(3)}}},
        "theta": {"conv0": {"conv": {"weight": np.zeros((8, 4, 3, 3))}}},
        "opt_state": {"mu": {"theta": {"conv0": {"conv": {
            "weight": np.zeros((8, 4, 3, 3))}}}}},
    }
    specs = match_partition_rules(MP_STATE_RULES, tree)
    assert specs["lslr"]["conv0"]["conv"]["weight"] == P()
    assert specs["theta"]["conv0"]["conv"]["weight"] == P("mp")
    assert (
        specs["opt_state"]["mu"]["theta"]["conv0"]["conv"]["weight"]
        == P("mp")
    )


def test_match_partition_rules_unmatched_leaf_is_an_error():
    """Silent replicate-by-omission would defeat the table being the
    single source of truth — a leaf no rule matches must raise."""
    import pytest

    from howtotrainyourmamlpytorch_tpu.parallel.sharding import (
        match_partition_rules,
    )

    with pytest.raises(ValueError, match="no partition rule matched"):
        match_partition_rules(
            ((r"conv/weight$", P("mp")),), {"bias": np.zeros(4)}
        )


def test_match_partition_rules_scalars_never_partitioned():
    from howtotrainyourmamlpytorch_tpu.parallel.sharding import (
        match_partition_rules,
    )

    specs = match_partition_rules(
        ((r".*", P("dp")),),
        {"count": np.zeros(()), "one": np.zeros(1), "vec": np.zeros(8)},
    )
    assert specs["count"] == P()
    assert specs["one"] == P()  # single element: nothing to split
    assert specs["vec"] == P("dp")


def test_guard_divisible_replicates_per_axis():
    """A 5-way head on an 8-way mp axis replicates THAT axis only — other
    sharded axes of the same leaf survive."""
    from howtotrainyourmamlpytorch_tpu.parallel.sharding import (
        guard_divisible,
    )

    mesh = make_mesh(jax.devices()[:8], data_parallel=2, model_parallel=4)
    leaf = np.zeros((5, 16))
    assert guard_divisible(mesh, P("mp", None), leaf) == P(None, None)
    assert guard_divisible(mesh, P(None, "mp"), leaf) == P(None, "mp")
    assert guard_divisible(mesh, P("dp", "mp"), np.zeros((4, 16))) == P(
        "dp", "mp"
    )


def test_state_rules_cover_every_learner_state_leaf():
    """Both rule tables produce a spec for EVERY leaf of every learner's
    full train state (params, LSLR, BN stats, optimizer moments, counters)
    — a new state field that slips past the tables raises at declaration
    time, not as a silent layout surprise mid-run."""
    from howtotrainyourmamlpytorch_tpu.models import (
        GradientDescentLearner,
        MatchingNetsLearner,
    )
    from howtotrainyourmamlpytorch_tpu.parallel.sharding import (
        DP_STATE_RULES,
        MP_STATE_RULES,
        match_partition_rules,
    )

    for cls in (MAMLFewShotLearner, GradientDescentLearner,
                MatchingNetsLearner):
        learner = cls(_cfg())
        state = learner.init_state(jax.random.PRNGKey(0))
        for rules in (DP_STATE_RULES, MP_STATE_RULES):
            specs = match_partition_rules(rules, state)
            assert len(jax.tree.leaves(state)) == len(
                jax.tree.leaves(
                    specs, is_leaf=lambda x: isinstance(x, P)
                )
            )
        mp_specs = match_partition_rules(MP_STATE_RULES, state)
        flat = jax.tree.leaves(
            mp_specs, is_leaf=lambda x: isinstance(x, P)
        )
        # The MP table actually shards something on every learner family.
        assert any(any(ax is not None for ax in sp) for sp in flat)


def test_shard_and_gather_round_trip_on_mesh():
    """shard_fns lay host leaves out on the mesh; gather_fns bring them
    back to full host numpy bit-exactly — the checkpoint save/restore
    core, exercised without any conv program compile."""
    from howtotrainyourmamlpytorch_tpu.parallel.sharding import (
        DP_STATE_RULES,
        gather_tree,
        make_shard_and_gather_fns,
        match_partition_rules,
        shard_tree,
    )

    mesh = make_mesh(jax.devices()[:8], data_parallel=8, model_parallel=1)
    learner = MAMLFewShotLearner(_cfg())
    state = learner.init_state(jax.random.PRNGKey(21))
    specs = match_partition_rules(DP_STATE_RULES, state)
    shard_fns, gather_fns = make_shard_and_gather_fns(mesh, specs)
    sharded = shard_tree(state, shard_fns)
    for leaf in jax.tree.leaves(sharded):
        assert isinstance(leaf.sharding, NamedSharding)
        assert leaf.sharding.mesh.shape == mesh.shape
    back = gather_tree(sharded, gather_fns)
    batched = gather_tree(sharded)  # the one-batched-device_get form
    for a, b, c in zip(
        jax.tree.leaves(state), jax.tree.leaves(back), jax.tree.leaves(batched)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


def test_batch_sharding_spec_forms():
    from howtotrainyourmamlpytorch_tpu.parallel.sharding import (
        batch_sharding_spec,
    )

    mesh = make_mesh(jax.devices()[:8], data_parallel=8, model_parallel=1)
    assert batch_sharding_spec(mesh).spec == P("dp")
    assert (
        batch_sharding_spec(mesh, leading_scan_axis=True).spec
        == P(None, "dp")
    )
