"""Pod-scale multi-host training tests (ISSUE 11).

Three layers, each as cheap as its claim allows:

* HOST-MATH units — per-host data-plane seed partitioning (the global
  meta-batch assembled from sharded loaders is BIT-IDENTICAL to the
  single-process loader at any shard count), ``host_batch_bounds`` /
  ``degraded_process_count`` topology math, and the bring-up flag
  pre-parser — no jax, milliseconds.
* FAIL-FAST bring-up — a wrong ``--coordinator_address`` raises the typed
  ``DistributedInitError`` with a "coordinator unreachable" message within
  its timeout instead of blocking forever (subprocess: ``jax.distributed``
  state is process-global).
* TWO-PROCESS e2e (``multihost_cpu_guard``) — the real dispatcher CLI runs
  a 2-process CPU fleet over a loopback coordinator to completion, and the
  result is pinned BIT-EXACT against a single-process run on the same
  dp=2 mesh at the same global meta-batch (subsuming batch bit-exactness:
  params see every episode through the same reduction tree), with
  host-attributed telemetry, per-rank compile-once, chief-only checkpoint/
  CSV writes, and the archive loadable on a single host (mesh-portable
  resume). Fleet SUPERVISION policy (host-loss -> coordinated shutdown ->
  degraded resume -> re-promotion) is pinned against a scripted stub entry
  like tests/test_dispatch_supervise.py; the full kill-a-host story
  through the real CLI lives in tools/chaos_train.py --schedule killhost
  (tests/test_chaos_train.py, slow-marked).
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import train_maml_system_dispatch as dispatch
from howtotrainyourmamlpytorch_tpu.parallel.distributed import (
    distributed_config_from_argv,
)
from howtotrainyourmamlpytorch_tpu.parallel.mesh import (
    degraded_process_count,
    host_batch_bounds,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Per-host data plane: seed-partitioned loader shards
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_workdir(tmp_path_factory):
    from tools.chaos_train import make_tiny_dataset

    workdir = tmp_path_factory.mktemp("multihost_data")
    make_tiny_dataset(str(workdir / "omniglot_mini"), seed=11)
    return workdir


def _loader_args(workdir, shard_index=0, shard_count=1, current_iter=0):
    from tools.chaos_train import tiny_config
    from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
        Bunch,
        extract_args_from_json,
    )

    cfg_path = tiny_config(str(workdir), "loader_shard", devices=1)
    os.environ["DATASET_DIR"] = str(workdir)
    base = extract_args_from_json(cfg_path, {})
    base["dataset_path"] = os.path.join(str(workdir), base["dataset_path"])
    base["data_shard_index"] = shard_index
    base["data_shard_count"] = shard_count
    return Bunch(base), current_iter


def _first_batches(workdir, shard_index, shard_count, n=2, current_iter=0):
    from howtotrainyourmamlpytorch_tpu.data import MetaLearningSystemDataLoader

    args, start = _loader_args(workdir, shard_index, shard_count, current_iter)
    loader = MetaLearningSystemDataLoader(args=args, current_iter=start)
    gen = loader.get_train_batches(total_batches=8, augment_images=True)
    return [next(gen) for _ in range(n)]


def test_sharded_loaders_assemble_the_single_process_batch(tiny_workdir):
    """Bit-identical global meta-batch at any host count: the two shards'
    slices, concatenated, equal the single-process loader's batches — the
    per-host data plane's determinism contract (seeds are GLOBAL episode
    index keyed, so who synthesizes an episode cannot change it)."""
    full = _first_batches(tiny_workdir, 0, 1)
    lo = _first_batches(tiny_workdir, 0, 2)
    hi = _first_batches(tiny_workdir, 1, 2)
    for b_full, b_lo, b_hi in zip(full, lo, hi):
        assert len(b_full) == len(b_lo) == len(b_hi)
        for col_full, col_lo, col_hi in zip(b_full, b_lo, b_hi):
            assert np.array_equal(
                np.concatenate([col_lo, col_hi]), col_full
            )


def test_sharded_loader_resume_keeps_global_seed_window(tiny_workdir):
    """``continue_from_iter`` advances the GLOBAL seed window: a sharded
    loader resumed at iteration N serves the same episodes as a fresh
    single-process loader's batch N slices."""
    full = _first_batches(tiny_workdir, 0, 1, n=3)
    resumed = _first_batches(tiny_workdir, 1, 2, n=1, current_iter=2)
    target = full[2]
    shard = resumed[0]
    for col_t, col_s in zip(target[:4], shard[:4]):
        half = col_t.shape[0] // 2
        assert np.array_equal(col_s, col_t[half:])


def test_loader_refuses_indivisible_and_out_of_range_shards(tiny_workdir):
    from howtotrainyourmamlpytorch_tpu.data import MetaLearningSystemDataLoader

    args, _ = _loader_args(tiny_workdir, shard_index=2, shard_count=2)
    with pytest.raises(ValueError, match="out of range"):
        MetaLearningSystemDataLoader(args=args)
    args, _ = _loader_args(tiny_workdir, shard_index=0, shard_count=3)
    loader = MetaLearningSystemDataLoader(args=args)  # batch 2 % 3 != 0
    with pytest.raises(ValueError, match="not divisible"):
        _ = loader.shard_size


# ---------------------------------------------------------------------------
# Topology math + bring-up pre-parser (no jax)
# ---------------------------------------------------------------------------


def test_host_batch_bounds_partition_the_batch():
    assert host_batch_bounds(8, 0, 2) == (0, 4)
    assert host_batch_bounds(8, 1, 2) == (4, 8)
    with pytest.raises(ValueError, match="not divisible"):
        host_batch_bounds(5, 0, 2)


def test_degraded_process_count_honors_all_constraints():
    # 4 hosts x 2 devices, batch 8: 2 hosts (dp 4) is viable.
    assert degraded_process_count(
        4, global_batch=8, local_devices=2
    ) == 2
    # task_chunk must ride the degraded dp extent too: chunk 6 refuses the
    # 2-host dp-4 step but rides the 1-host dp-2 one.
    assert degraded_process_count(
        4, global_batch=8, local_devices=2, task_chunk=4
    ) == 2
    assert degraded_process_count(
        4, global_batch=8, local_devices=2, task_chunk=6
    ) == 1
    # Nothing divides: no viable smaller fleet.
    assert degraded_process_count(
        2, global_batch=3, local_devices=2
    ) is None
    # Single host: nothing smaller.
    assert degraded_process_count(1, global_batch=8) is None


def test_distributed_config_pre_parser_reads_flags_and_config(tmp_path):
    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps({
        "coordinator_address": "10.0.0.1:1234",
        "num_processes": 4,
        "distributed_init_timeout_s": 30,
    }))
    # Config keys picked up through --name_of_args_json_file...
    out = distributed_config_from_argv(
        ["--name_of_args_json_file", str(cfg)]
    )
    assert out["coordinator_address"] == "10.0.0.1:1234"
    assert out["num_processes"] == 4
    assert out["distributed_init_timeout_s"] == 30
    # ...and explicit flags BEAT config keys (the dispatcher retargets a
    # fleet without rewriting the experiment config).
    out = distributed_config_from_argv([
        "--name_of_args_json_file", str(cfg),
        "--coordinator_address", "127.0.0.1:9",
        "--num_processes", "2",
        "--process_id", "1",
    ])
    assert out["coordinator_address"] == "127.0.0.1:9"
    assert out["num_processes"] == "2"
    assert out["process_id"] == "1"
    # No signal at all -> empty (the opt-in contract).
    assert distributed_config_from_argv([]) == {}


def test_initialize_distributed_fails_fast_on_unreachable_coordinator(
    tmp_path,
):
    """A wrong coordinator address must raise the typed error with a clear
    message within the init timeout — not block forever inside
    ``jax.distributed.initialize`` (the pre-watchdog bring-up gap)."""
    script = tmp_path / "failfast.py"
    script.write_text(textwrap.dedent(
        """
        from howtotrainyourmamlpytorch_tpu.utils.platform import (
            force_virtual_cpu_env,
        )

        force_virtual_cpu_env(1)

        from howtotrainyourmamlpytorch_tpu.parallel import (
            DistributedInitError,
            initialize_distributed,
        )

        try:
            initialize_distributed(
                coordinator_address="127.0.0.1:9",  # discard port: refused
                num_processes=2,
                process_id=1,
                distributed_init_timeout_s=3.0,
            )
        except DistributedInitError as exc:
            assert "coordinator unreachable" in str(exc), exc
            print("FAILFAST_OK")
        """
    ))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True,
        env=env, cwd=REPO, timeout=120,
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "FAILFAST_OK" in proc.stdout
    # Bounded by the 3 s timeout + interpreter startup, nowhere near the
    # runtime's own 5-minute default.
    assert elapsed < 60, elapsed


# ---------------------------------------------------------------------------
# Host identity in observability (cheap in-process units)
# ---------------------------------------------------------------------------


def test_telemetry_stamps_host_identity(tmp_path):
    from howtotrainyourmamlpytorch_tpu.telemetry import (
        TrainTelemetry,
        read_events,
    )

    telemetry = TrainTelemetry(
        str(tmp_path), enabled=True, process_index=1, process_count=2
    )
    with telemetry.activate():
        telemetry.record_dispatch(1, n_iters=1)
        telemetry.record_dispatch(2, n_iters=1)
        telemetry.event("preemption", signal=15, iter=2)
        stats = telemetry.epoch_stats("train", epoch=0)
    assert stats["process_index"] == 1
    assert stats["process_count"] == 2
    events = read_events(os.path.join(str(tmp_path), "telemetry.jsonl"))
    step = next(e for e in events if e["type"] == "step")
    assert step["process_index"] == 1 and step["process_count"] == 2
    preemption = next(e for e in events if e["type"] == "preemption")
    assert preemption["process_index"] == 1


def test_watchdog_hang_event_carries_identity(tmp_path):
    from howtotrainyourmamlpytorch_tpu.telemetry import events as tel_events
    from howtotrainyourmamlpytorch_tpu.telemetry.events import EventLog
    from howtotrainyourmamlpytorch_tpu.utils.watchdog import DispatchWatchdog

    log = EventLog(str(tmp_path / "t.jsonl"))
    previous = tel_events.install(log)
    fired = []
    try:
        wd = DispatchWatchdog(
            min_deadline_s=0.2,
            factor=1.0,
            logs_dir=str(tmp_path),
            exit_fn=fired.append,
            identity={"process_index": 1, "process_count": 2},
        )
        try:
            with wd.armed(7):
                deadline = time.monotonic() + 10.0
                while not fired and time.monotonic() < deadline:
                    time.sleep(0.02)
        finally:
            wd.close()
    finally:
        tel_events.install(previous)
    assert fired
    log.flush()
    events = [
        json.loads(line)
        for line in (tmp_path / "t.jsonl").read_text().splitlines()
        if line.strip()
    ]
    hang = next(e for e in events if e.get("type") == "hang")
    assert hang["process_index"] == 1 and hang["process_count"] == 2


def test_telemetry_report_header_names_ranks(tmp_path):
    from tools.telemetry_report import render_text, summarize

    events = [
        {"t": 1.0, "type": "step", "iter": 1, "k": 1, "step_s": 0.1,
         "data_wait_s": 0.0, "stage_wait_s": 0.0, "device_s": 0.1,
         "n_devices": 2, "mesh_shape": "dp2xmp1",
         "process_index": 0, "process_count": 2},
        {"t": 1.1, "type": "step", "iter": 1, "k": 1, "step_s": 0.1,
         "data_wait_s": 0.0, "stage_wait_s": 0.0, "device_s": 0.1,
         "n_devices": 2, "mesh_shape": "dp2xmp1",
         "process_index": 1, "process_count": 2},
    ]
    summary = summarize(events)
    assert summary["process_count"] == 2
    assert summary["process_indices"] == [0, 1]
    assert "rank(s) 0+1 of 2 process(es)" in render_text(summary)


# ---------------------------------------------------------------------------
# Fleet supervision policy (scripted stub entry — no jax)
# ---------------------------------------------------------------------------


FLEET_STUB = textwrap.dedent(
    """
    import argparse, json, os, sys, time

    parser = argparse.ArgumentParser()
    parser.add_argument("--name_of_args_json_file")
    parser.add_argument("--coordinator_address", default=None)
    parser.add_argument("--num_processes", default=None)
    parser.add_argument("--process_id", default=None)
    args, _ = parser.parse_known_args()
    with open(args.name_of_args_json_file) as f:
        cfg = json.load(f)

    key = (
        "rank%s" % args.process_id if args.process_id is not None
        else "single"
    )
    plan_path = os.path.join(os.environ["STUB_PLAN_DIR"], key + ".json")
    with open(plan_path) as f:
        plan = json.load(f)
    step = plan.pop(0)
    with open(plan_path, "w") as f:
        json.dump(plan, f)

    with open(os.environ["STUB_LOG"] + "." + key, "a") as f:
        f.write(json.dumps({
            "key": key,
            "dp": cfg.get("data_parallel_devices"),
            "coordinator": args.coordinator_address,
            "num_processes": args.num_processes,
            "faults": os.environ.get("MAML_FAULTS"),
        }) + "\\n")

    logs = os.path.join(cfg["experiment_name"], "logs")
    os.makedirs(logs, exist_ok=True)
    summary = os.path.join(logs, "summary_statistics.csv")
    for _ in range(step.get("epochs", 0)):
        if not os.path.exists(summary):
            with open(summary, "w") as f:
                f.write("epoch\\n")
        with open(summary, "a") as f:
            f.write("1\\n")
    time.sleep(step.get("sleep", 0))
    if step.get("test_eval"):
        with open(os.path.join(logs, "test_summary.csv"), "w") as f:
            f.write("ok\\n")
    sys.exit(step.get("rc", 0))
    """
)


@pytest.fixture
def fleet_harness(tmp_path, monkeypatch):
    """Scripted-fleet driver: per-rank plans (rank0/rank1/single), returns
    ``run(plans, cfg_overrides, *extra) -> (rc, calls_by_key, audit)``."""
    monkeypatch.chdir(tmp_path)
    stub_path = tmp_path / "stub_entry.py"
    stub_path.write_text(FLEET_STUB)
    monkeypatch.setenv(dispatch.ENTRY_ENV, str(stub_path))
    plan_dir = tmp_path / "plans"
    plan_dir.mkdir()
    log_path = tmp_path / "invocations"
    monkeypatch.setenv("STUB_PLAN_DIR", str(plan_dir))
    monkeypatch.setenv("STUB_LOG", str(log_path))

    def run(plans, cfg_overrides=None, *extra_argv):
        cfg = {
            "experiment_name": "exp",
            "total_epochs": 2,
            "num_of_gpus": 1,
            "batch_size": 4,
            "samples_per_iter": 1,
            "data_parallel_devices": 2,
        }
        cfg.update(cfg_overrides or {})
        cfg_path = tmp_path / "fleet_cfg.json"
        cfg_path.write_text(json.dumps(cfg))
        for key, plan in plans.items():
            (plan_dir / f"{key}.json").write_text(json.dumps(plan))
        monkeypatch.setattr(
            sys, "argv",
            ["train_maml_system_dispatch.py", str(cfg_path), *extra_argv],
        )
        rc = dispatch.main()
        calls = {}
        for key in plans:
            path = tmp_path / f"invocations.{key}"
            if path.exists():
                calls[key] = [
                    json.loads(line)
                    for line in path.read_text().splitlines()
                ]
        audit_path = tmp_path / "exp" / "logs" / "interruptions.csv"
        audit = (
            audit_path.read_text().splitlines()[1:]
            if audit_path.exists() else []
        )
        return rc, calls, audit

    return run


def test_host_loss_coordinated_shutdown_and_degraded_resume(fleet_harness):
    """Rank 1 dies by signal mid-phase; rank 0 would run on forever. The
    supervisor must shut the survivor down after the grace, attribute the
    loss to rank 1 (exit ORDER, not exit codes), append the
    host-attributed audit row, and resume DEGRADED on a single process —
    which then finishes the run."""
    rc, calls, audit = fleet_harness(
        {
            "rank0": [{"rc": 0, "sleep": 60}],   # survivor: would run on
            "rank1": [{"rc": 137, "sleep": 1}],  # the lost host
            "single": [{"rc": 0, "epochs": 2, "test_eval": True}],
        },
        None,
        "--num_processes", "2", "--fleet_grace_s", "2",
    )
    assert rc == 0
    # Fleet phase: both ranks saw coordinator flags and the full dp.
    assert calls["rank0"][0]["coordinator"].startswith("127.0.0.1:")
    assert calls["rank0"][0]["num_processes"] == "2"
    assert calls["rank0"][0]["dp"] == 2
    # Degraded phase: single process, no distributed flags, dp shrunk.
    assert calls["single"][0]["coordinator"] is None
    assert calls["single"][0]["dp"] == 1
    kinds = [row.split(",")[1] for row in audit]
    assert "host-loss:rank1-degrade:procs2->procs1" in kinds
    # The audit row attributes the dead rank in the process_index column.
    loss_row = next(r for r in audit if "host-loss:rank1" in r)
    assert loss_row.split(",")[4] == "1"


def test_fleet_preemption_requeues_same_fleet_and_repromotion_probes(
    fleet_harness,
):
    """Every rank exiting 75 is a fleet-wide preemption: requeue the SAME
    fleet size on the requeue budget. After a host-loss degrade, a clean
    progressing phase triggers the re-promotion probe back to the full
    fleet."""
    rc, calls, audit = fleet_harness(
        {
            "rank0": [
                {"rc": dispatch.REQUEUE_EXIT_CODE},   # fleet preemption
                {"rc": 137, "sleep": 1},              # then host loss
                {"rc": 0, "epochs": 1, "test_eval": True},  # re-promoted
            ],
            "rank1": [
                {"rc": dispatch.REQUEUE_EXIT_CODE},
                {"rc": 0, "sleep": 60},
                {"rc": 0, "epochs": 0, "test_eval": True},
            ],
            "single": [{"rc": 0, "epochs": 1}],  # degraded, progresses
        },
        None,
        "--num_processes", "2", "--fleet_grace_s", "2",
    )
    assert rc == 0
    # Three fleet phases (preempted, host-loss, re-promoted) + 1 degraded.
    assert len(calls["rank0"]) == 3
    assert len(calls["single"]) == 1
    kinds = [row.split(",")[1] for row in audit]
    assert "host-loss:rank0-degrade:procs2->procs1" in kinds
    assert "probe-promote:procs2" in kinds


def test_fault_rank_targets_the_env_plan(fleet_harness, monkeypatch):
    monkeypatch.setenv("MAML_FAULTS", "sigkill_at_iter=3")
    rc, calls, _ = fleet_harness(
        {
            "rank0": [{"rc": 0, "epochs": 2, "test_eval": True}],
            "rank1": [{"rc": 0, "test_eval": False}],
        },
        None,
        "--num_processes", "2", "--fault_rank", "1",
    )
    assert rc == 0
    assert calls["rank0"][0]["faults"] is None
    assert calls["rank1"][0]["faults"] == "sigkill_at_iter=3"


# ---------------------------------------------------------------------------
# Two-process e2e through the real CLI (probe-guarded)
# ---------------------------------------------------------------------------


def _fleet_env(workdir, devices_per_proc=1):
    env = dict(os.environ)
    env["DATASET_DIR"] = str(workdir)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_proc}"
    )
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("MAML_FAULTS", None)
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.pop("JAX_NUM_PROCESSES", None)
    return env


@pytest.fixture(scope="module")
def fleet_run(multihost_cpu_guard, tiny_workdir):
    """ONE 2-process fleet run through the real dispatcher CLI plus ONE
    single-process run on the same dp=2 mesh — shared by the e2e
    assertions below (two subprocess training runs are the expensive
    part; every claim reads their artifacts)."""
    from tools.chaos_train import tiny_config

    workdir = str(tiny_workdir)
    fleet_cfg = tiny_config(workdir, "fleet_exp", devices=2)
    fleet_cfg_path = fleet_cfg
    proc = subprocess.run(
        [sys.executable, "-u", "train_maml_system_dispatch.py", fleet_cfg,
         "--num_processes", "2", "--fleet_grace_s", "25"],
        cwd=REPO, env=_fleet_env(workdir, devices_per_proc=1),
        capture_output=True, text=True, timeout=360,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]

    twin_cfg_path = os.path.join(workdir, "twin_exp.json")
    with open(tiny_config(workdir, "twin_tmp", devices=2)) as f:
        twin_cfg = json.load(f)
    twin_cfg["experiment_name"] = os.path.join(workdir, "twin_exp")
    with open(twin_cfg_path, "w") as f:
        json.dump(twin_cfg, f)
    twin = subprocess.run(
        [sys.executable, "-u", "train_maml_system_dispatch.py",
         twin_cfg_path],
        cwd=REPO, env=_fleet_env(workdir, devices_per_proc=2),
        capture_output=True, text=True, timeout=360,
    )
    assert twin.returncode == 0, twin.stdout[-2000:] + twin.stderr[-2000:]
    return {
        "fleet_dir": os.path.join(workdir, "fleet_exp"),
        "twin_dir": os.path.join(workdir, "twin_exp"),
        "cfg_path": fleet_cfg_path,
    }


def _leaves(path):
    with np.load(path) as archive:
        return {k: archive[k] for k in archive.files if k.startswith("leaf_")}


def test_two_process_run_is_bitexact_vs_single_process(fleet_run):
    """The strongest per-host data-plane pin: the FINAL TRAINED PARAMS of
    the 2-process fleet equal the single-process dp=2 run bit for bit —
    every episode of every global batch was identical AND flowed through
    the same sharded reduction tree, whoever synthesized it."""
    fleet = _leaves(
        os.path.join(fleet_run["fleet_dir"], "saved_models",
                     "train_model_latest")
    )
    twin = _leaves(
        os.path.join(fleet_run["twin_dir"], "saved_models",
                     "train_model_latest")
    )
    assert set(fleet) == set(twin)
    for key in fleet:
        assert np.array_equal(fleet[key], twin[key]), key


def test_fleet_telemetry_attributes_both_ranks(fleet_run):
    from howtotrainyourmamlpytorch_tpu.telemetry import read_events

    events = read_events(
        os.path.join(fleet_run["fleet_dir"], "logs", "telemetry.jsonl")
    )
    steps = [e for e in events if e.get("type") == "step"]
    ranks = {int(e["process_index"]) for e in steps}
    assert ranks == {0, 1}
    assert all(int(e["process_count"]) == 2 for e in steps)
    # Compile-once per rank under the compile bridge: the tiny config's
    # MSL horizon (2 of 3 epochs) builds exactly TWO static train-step
    # variants (final_only False then True) — each rank must compile
    # exactly those two, run 6 iterations, and never mint another (a
    # per-iteration recompile would show ~6 per rank).
    for rank in (0, 1):
        train_compiles = [
            e for e in events
            if e.get("type") == "compile"
            and e.get("name") == "_train_step"
            and int(e.get("process_index", -1)) == rank
        ]
        assert len(train_compiles) == 2, (rank, len(train_compiles))


def test_fleet_trace_ids_consistent_and_report_merges_ranks(fleet_run):
    """ISSUE 12 acceptance on a REAL 2-rank run through the dispatcher
    CLI: every rank's events carry the ONE dispatcher-exported trace_id,
    step events carry rank-aligned dispatch_ids, and
    ``tools/telemetry_report.py --fleet`` renders the shared JSONL as one
    merged timeline with per-rank lanes and per-dispatch slowest-rank
    attribution."""
    from howtotrainyourmamlpytorch_tpu.telemetry import read_events

    jsonl = os.path.join(fleet_run["fleet_dir"], "logs", "telemetry.jsonl")
    events = read_events(jsonl)
    trace_ids = {
        e["trace_id"] for e in events
        if e.get("type") != "schema" and "trace_id" in e
    }
    assert len(trace_ids) == 1, trace_ids  # one trace across both ranks
    steps = [e for e in events if e.get("type") == "step"]
    by_rank = {
        rank: sorted(
            e["dispatch_id"] for e in steps
            if int(e["process_index"]) == rank
        )
        for rank in (0, 1)
    }
    # Lockstep fleet: both ranks dispatched the same iteration windows —
    # equal dispatch_id sets are what make cross-rank attribution a join.
    assert by_rank[0] == by_rank[1] and by_rank[0]

    from tools.telemetry_report import fleet_summarize, render_fleet_text

    summary = fleet_summarize([fleet_run["fleet_dir"]])
    assert summary["ranks"] == [0, 1]
    assert summary["trace_consistent"]
    assert summary["dispatch_skew"]["dispatches"] == len(by_rank[0])
    assert set(summary["slowest_rank_dispatches"]) <= {"0", "1"}
    text = render_fleet_text(summary)
    assert "per-rank step lanes" in text
    assert "slowest-rank attribution" in text


def test_fleet_ranks_write_per_rank_heartbeats(fleet_run):
    """Both ranks of the shared logs dir heartbeat without racing one
    rename target: rank 0 owns status.json (what the dispatcher reads),
    rank 1 writes status.r1.json — each with its own identity and
    progress."""
    from howtotrainyourmamlpytorch_tpu.telemetry import read_heartbeat

    logs = os.path.join(fleet_run["fleet_dir"], "logs")
    chief = read_heartbeat(os.path.join(logs, "status.json"))
    peer = read_heartbeat(os.path.join(logs, "status.r1.json"))
    assert chief is not None and peer is not None
    assert chief["process_index"] == 0 and peer["process_index"] == 1
    assert chief["trace_id"] == peer["trace_id"]
    assert chief["current_iter"] == peer["current_iter"] == 6
    assert chief["epoch"] is not None


def test_fleet_chief_is_the_single_writer(fleet_run):
    """Rank 0 owns checkpoints and the summary CSV; the telemetry stream
    carries both ranks (attribution), the CSV carries one epoch row per
    epoch (no duplicated writers)."""
    logs = os.path.join(fleet_run["fleet_dir"], "logs")
    with open(os.path.join(logs, "summary_statistics.csv")) as f:
        rows = [line for line in f if line.strip()]
    assert len(rows) == 1 + 3  # header + one row per epoch, not 2x
    with open(os.path.join(logs, "test_summary.csv")) as f:
        assert len(f.read().splitlines()) == 2


def test_fleet_checkpoint_resumes_on_one_host(fleet_run):
    """Mesh-portable restore: the archive the 2-host fleet wrote loads
    into a single-host (no-mesh) learner bit-exactly — host-count changes
    are a resume, not a migration."""
    import jax

    from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner
    from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
        Bunch,
        args_to_maml_config,
        extract_args_from_json,
    )

    cfg = args_to_maml_config(
        Bunch(extract_args_from_json(fleet_run["cfg_path"], {}))
    )
    learner = MAMLFewShotLearner(cfg)  # no mesh: a lone surviving host
    state, exp_state = learner.load_model(
        model_save_dir=os.path.join(fleet_run["fleet_dir"], "saved_models"),
        model_name="train_model",
        model_idx="latest",
    )
    assert int(exp_state["current_iter"]) == 6
    archive = _leaves(
        os.path.join(fleet_run["fleet_dir"], "saved_models",
                     "train_model_latest")
    )
    restored = jax.tree.leaves(
        jax.tree.map(lambda x: np.asarray(x), state)
    )
    assert len(restored) == len(archive)


def test_fleet_interruptions_csv_has_identity_columns(fleet_run):
    """A clean fleet run writes no interruption rows, but the header
    contract (identity columns) is pinned by the killhost chaos harness;
    here pin the builder's row shape directly."""
    interruptions = os.path.join(
        fleet_run["fleet_dir"], "logs", "interruptions.csv"
    )
    if os.path.exists(interruptions):
        with open(interruptions) as f:
            header = f.readline().strip().split(",")
        assert header[-2:] == ["process_index", "process_count"]


# ---------------------------------------------------------------------------
# Fused vs per-leaf collective parity on a REAL 2-process fleet (ISSUE 17)
# ---------------------------------------------------------------------------

_FUSION_PARITY_SRC = """
import sys
from howtotrainyourmamlpytorch_tpu.utils.platform import force_virtual_cpu_env

force_virtual_cpu_env(1)

from howtotrainyourmamlpytorch_tpu.parallel import initialize_distributed

addr, pid, mode, out = (
    sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
)
initialize_distributed(
    coordinator_address=addr, num_processes=2, process_id=pid,
    distributed_init_timeout_s=90,
)

import jax
import numpy as np

from howtotrainyourmamlpytorch_tpu.models import (
    BackboneConfig, MAMLConfig, MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.models.common import (
    StagedBatch, prepare_batch,
)
from howtotrainyourmamlpytorch_tpu.parallel import make_mesh

cfg = MAMLConfig(
    backbone=BackboneConfig(
        num_stages=2, num_filters=4, per_step_bn_statistics=True,
        num_steps=2, num_classes=5, image_height=8, image_width=8,
    ),
    number_of_training_steps_per_iter=2,
    number_of_evaluation_steps_per_iter=2,
    second_order=False,
    collective_fusion=mode,
)
mesh = make_mesh(jax.devices(), data_parallel=2, model_parallel=1)
learner = MAMLFewShotLearner(cfg, mesh=mesh)
state = learner.shard_state(learner.init_state(jax.random.PRNGKey(0)))
rng = np.random.RandomState(0)
xs = rng.rand(2, 5, 1, 1, 8, 8).astype(np.float32)
ys = np.tile(np.arange(5)[None, :, None], (2, 1, 1))
sh = learner.staged_batch_sharding(1)
local = prepare_batch(
    tuple(a[pid:pid + 1] for a in (xs, xs.copy(), ys, ys.copy()))
)
batch = StagedBatch(
    arrays=tuple(
        jax.make_array_from_process_local_data(sh, a) for a in local
    ),
    n_iters=1, first_iter=0,
)
state, losses = learner.run_train_iter(state, batch, epoch=0)
print("loss", repr(float(jax.device_get(losses["loss"]))))
if pid == 0:
    leaves = jax.tree.leaves(state)
    np.savez(out, **{
        "leaf_%04d" % i: np.asarray(jax.device_get(leaf))
        for i, leaf in enumerate(leaves)
    })
print("FUSION_PARITY_OK", pid)
"""


def test_fleet_fused_vs_per_leaf_collectives_parity(
    multihost_cpu_guard, tmp_path
):
    """The fused flat-bucket all-reduce on a REAL 2-process fleet: the
    final trained state after one meta-iteration is bit-identical between
    `collective_fusion="bucketed"` (one psum per dtype bucket) and the
    per-leaf reference form it replaced — same reduction, 22x fewer
    collectives, and the gloo transport agrees with single-process CPU."""
    import socket

    script = tmp_path / "fusion_parity.py"
    script.write_text(_FUSION_PARITY_SRC)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # each rank forces its own 1-device platform
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.pop("JAX_NUM_PROCESSES", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    results = {}
    for mode in ("bucketed", "per_leaf"):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        out = tmp_path / f"state_{mode}.npz"
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), f"127.0.0.1:{port}",
                 str(pid), mode, str(out)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                env=env, cwd=REPO, text=True,
            )
            for pid in (0, 1)
        ]
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for pid, (p, text) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, (mode, pid, text[-2000:])
            assert f"FUSION_PARITY_OK {pid}" in text, (mode, pid, text)
        losses = {
            line.split(" ", 1)[1]
            for text in outs for line in text.splitlines()
            if line.startswith("loss ")
        }
        assert len(losses) == 1, (mode, losses)  # ranks agree exactly
        with np.load(out) as archive:
            results[mode] = (
                {k: archive[k] for k in archive.files}, losses.pop()
            )

    fused_leaves, fused_loss = results["bucketed"]
    ref_leaves, ref_loss = results["per_leaf"]
    assert fused_loss == ref_loss
    assert set(fused_leaves) == set(ref_leaves)
    for key in sorted(fused_leaves):
        assert np.array_equal(fused_leaves[key], ref_leaves[key]), key
