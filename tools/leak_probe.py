"""Host-memory leak probe for the experiment runtime (VERDICT r2 weak #2).

Reproduces the long-run training path on CPU with 20-way-shaped episode
batches (batch 8, 20 classes, 5 shots — the flagship 20w-5s host-side data
load) but a tiny first-order model, and logs per-epoch:

  * RSS (VmRSS from /proc/self/status)
  * number of live JAX arrays (jax.live_arrays()) — leaked device buffers
  * total Python objects (gc.get_objects()) — leaked host structures

Usage:  python tools/leak_probe.py [--epochs 15] [--iters 50]
                                   [--platform cpu|default]

NOTE: ``JAX_PLATFORMS=cpu`` is NOT honored in this image — the axon
sitecustomize registers the tunnel backend and pins the platform config, so
the env var silently leaves you on the TPU tunnel. ``--platform cpu``
(default) goes through ``utils.platform.force_virtual_cpu``, which works;
``--platform default`` keeps the tunnel device to measure ITS leak.
Prints one line per epoch and a final verdict: the regression criterion is
RSS slope over the last half of the run (first epochs are excluded — jit
compilation and cache warmup legitimately allocate).
"""

from __future__ import annotations

import argparse
import gc
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"
    ),
)


def rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024.0
    return -1.0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=15)
    parser.add_argument("--iters", type=int, default=50)
    parser.add_argument("--ways", type=int, default=20)
    parser.add_argument("--shots", type=int, default=5)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--backend", default="thread")
    parser.add_argument("--platform", default="cpu",
                        choices=["cpu", "default"])
    args_cli = parser.parse_args()

    if args_cli.platform == "cpu":
        from howtotrainyourmamlpytorch_tpu.utils.platform import (
            force_virtual_cpu,
        )

        force_virtual_cpu(1)

    import jax
    import numpy as np

    from test_data import make_args, make_dataset_dir  # noqa: E402
    from howtotrainyourmamlpytorch_tpu.experiment_builder import (
        ExperimentBuilder,
    )
    from howtotrainyourmamlpytorch_tpu.data import MetaLearningSystemDataLoader
    from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner
    from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
        args_to_maml_config,
    )

    import pathlib

    tmp = tempfile.mkdtemp(prefix="leak_probe_")
    tmp_path = pathlib.Path(tmp)
    # Enough classes for a 20-way split (80 classes -> 40 train / 20 / 20)
    # and shots+targets images per class.
    make_dataset_dir(
        tmp_path / "omniglot_mini",
        n_alphabets=10,
        n_chars=8,
        n_imgs=2 * args_cli.shots + 1,
    )
    os.environ["DATASET_DIR"] = str(tmp_path)

    args = make_args(
        tmp_path,
        experiment_name=os.path.join(tmp, "exp"),
        seed=11,
        continue_from_epoch="from_scratch",
        max_models_to_save=5,
        total_epochs=args_cli.epochs,
        total_iter_per_epoch=args_cli.iters,
        total_epochs_before_pause=args_cli.epochs + 1,
        num_evaluation_tasks=2 * args_cli.batch,
        evaluate_on_test_set_only=False,
        batch_size=args_cli.batch,
        num_classes_per_set=args_cli.ways,
        num_samples_per_class=args_cli.shots,
        num_target_samples=args_cli.shots,
        num_dataprovider_workers=2,
        dataprovider_backend=args_cli.backend,
        # tiny first-order model: the leak is host-side, keep compute cheap
        num_stages=2,
        cnn_num_filters=4,
        conv_padding=True,
        max_pooling=True,
        norm_layer="batch_norm",
        per_step_bn_statistics=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        second_order=False,
        first_order_to_second_order_epoch=-1,
        use_multi_step_loss_optimization=True,
        multi_step_loss_num_epochs=3,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        enable_inner_loop_optimizable_bn_params=False,
        learnable_bn_gamma=True,
        learnable_bn_beta=True,
        meta_learning_rate=0.001,
        min_learning_rate=1e-5,
        task_learning_rate=0.1,
        init_inner_loop_learning_rate=0.1,
    )

    model = MAMLFewShotLearner(args_to_maml_config(args))
    builder = ExperimentBuilder(
        args=args, data=MetaLearningSystemDataLoader, model=model, device=None
    )

    samples: list[tuple[int, float, int, int]] = []

    orig_save = builder.save_models

    def probed_save(model, epoch, state):  # noqa: ANN001
        orig_save(model=model, epoch=epoch, state=state)
        gc.collect()
        n_live = len(jax.live_arrays())
        n_obj = len(gc.get_objects())
        mb = rss_mb()
        samples.append((int(epoch), mb, n_live, n_obj))
        print(
            f"[leak_probe] epoch {int(epoch):3d}  rss {mb:9.1f} MB  "
            f"jax_arrays {n_live:6d}  py_objects {n_obj:8d}",
            flush=True,
        )

    builder.save_models = probed_save
    builder.run_experiment()

    # Verdict: slope over the last half (warmup excluded).
    half = samples[len(samples) // 2 :]
    if len(half) < 2:
        print("[leak_probe] not enough samples")
        return 2
    epochs = np.array([s[0] for s in half], dtype=np.float64)
    rss = np.array([s[1] for s in half], dtype=np.float64)
    arrays = np.array([s[2] for s in half], dtype=np.float64)
    slope = np.polyfit(epochs, rss, 1)[0]
    arr_slope = np.polyfit(epochs, arrays, 1)[0]
    print(
        f"[leak_probe] steady-state RSS slope: {slope:+.2f} MB/epoch; "
        f"jax-array slope: {arr_slope:+.1f}/epoch "
        f"({samples[0][1]:.0f} -> {samples[-1][1]:.0f} MB over "
        f"{len(samples)} epochs)"
    )
    leak = slope > 5.0 or arr_slope > 10.0
    print("[leak_probe] LEAK" if leak else "[leak_probe] FLAT")
    return 1 if leak else 0


if __name__ == "__main__":
    sys.exit(main())
