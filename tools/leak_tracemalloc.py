"""tracemalloc-based drill-down for the per-iteration host leak.

Runs the same harness as leak_probe.py but snapshots tracemalloc between
epochs and prints the top allocation-site diffs. numpy>=1.13 registers array
buffers with tracemalloc, so leaked batch arrays show their allocation site.

Usage: JAX_PLATFORMS=cpu python tools/leak_tracemalloc.py
"""

from __future__ import annotations

import gc
import os
import sys
import tempfile
import tracemalloc

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"
    ),
)


def main() -> None:
    import pathlib

    from test_data import make_args, make_dataset_dir  # noqa: E402
    from howtotrainyourmamlpytorch_tpu.experiment_builder import (
        ExperimentBuilder,
    )
    from howtotrainyourmamlpytorch_tpu.data import MetaLearningSystemDataLoader
    from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner
    from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
        args_to_maml_config,
    )

    tmp = tempfile.mkdtemp(prefix="leak_tm_")
    tmp_path = pathlib.Path(tmp)
    make_dataset_dir(tmp_path / "omniglot_mini", n_alphabets=10, n_chars=8,
                     n_imgs=11)
    os.environ["DATASET_DIR"] = str(tmp_path)

    args = make_args(
        tmp_path,
        experiment_name=os.path.join(tmp, "exp"),
        seed=11, continue_from_epoch="from_scratch", max_models_to_save=5,
        total_epochs=4, total_iter_per_epoch=15,
        total_epochs_before_pause=99, num_evaluation_tasks=8,
        evaluate_on_test_set_only=False, batch_size=8,
        num_classes_per_set=20, num_samples_per_class=5,
        num_target_samples=5, num_dataprovider_workers=2,
        num_stages=2, cnn_num_filters=4, conv_padding=True, max_pooling=True,
        norm_layer="batch_norm", per_step_bn_statistics=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        second_order=False, first_order_to_second_order_epoch=-1,
        use_multi_step_loss_optimization=True, multi_step_loss_num_epochs=3,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        enable_inner_loop_optimizable_bn_params=False,
        learnable_bn_gamma=True, learnable_bn_beta=True,
        meta_learning_rate=0.001, min_learning_rate=1e-5,
        task_learning_rate=0.1, init_inner_loop_learning_rate=0.1,
    )

    model = MAMLFewShotLearner(args_to_maml_config(args))
    builder = ExperimentBuilder(
        args=args, data=MetaLearningSystemDataLoader, model=model, device=None
    )

    tracemalloc.start(10)
    snaps = []
    orig_save = builder.save_models

    def probed_save(model, epoch, state):  # noqa: ANN001
        orig_save(model=model, epoch=epoch, state=state)
        gc.collect()
        snaps.append(tracemalloc.take_snapshot())
        if len(snaps) >= 2:
            diff = snaps[-1].compare_to(snaps[-2], "traceback")
            print(f"===== epoch {int(epoch)} top growth =====", flush=True)
            for stat in diff[:6]:
                if stat.size_diff <= 0:
                    continue
                print(f"  +{stat.size_diff/1e6:8.2f} MB  count+{stat.count_diff}")
                for line in stat.traceback.format()[-6:]:
                    print("   ", line)

    builder.save_models = probed_save
    builder.run_experiment()


if __name__ == "__main__":
    main()
