"""Isolates the per-iteration RSS leak: JAX dispatch vs data pipeline.

Scenarios (pick with argv[1], default 'all'):
  jit_keep    - jit step with fresh 5MB numpy input each iter, KEEP output
                scalars in a list, clear every 50 iters (mimics the builder)
  jit_nokeep  - same but outputs read immediately (float()) and dropped
  data_only   - synthesize + collate episodes, never touch JAX
  jit_const   - jit step with the SAME input array each iter (no transfers)

Each runs 300 iterations printing RSS every 50.
Usage: JAX_PLATFORMS=cpu python tools/leak_isolate.py [scenario]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024.0
    return -1.0


def run_jit(keep: bool, fresh_input: bool) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def step(w, batch):
        loss = jnp.mean((batch @ w) ** 2)
        acc = jnp.mean(batch)
        return w - 1e-4 * loss, {"loss": loss, "accuracy": acc}

    w = jnp.zeros((784, 16))
    rng = np.random.RandomState(0)
    base = rng.rand(1600, 784).astype(np.float32)  # ~5 MB
    kept: list = []
    for i in range(300):
        batch = (base + np.float32(i)) if fresh_input else base
        w, metrics = step(w, batch)
        if keep:
            kept.append(metrics)
            if len(kept) >= 50:
                kept.clear()
        else:
            float(metrics["loss"])
        if (i + 1) % 50 == 0:
            jax.block_until_ready(w)
            print(f"  iter {i+1:4d}  rss {rss_mb():9.1f} MB", flush=True)


def run_data_only() -> None:
    import pathlib
    import tempfile

    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tests",
        ),
    )
    from test_data import make_args, make_dataset_dir

    from howtotrainyourmamlpytorch_tpu.data import MetaLearningSystemDataLoader

    tmp = tempfile.mkdtemp(prefix="leak_iso_")
    tmp_path = pathlib.Path(tmp)
    make_dataset_dir(tmp_path / "omniglot_mini", n_alphabets=10, n_chars=8,
                     n_imgs=11)
    os.environ["DATASET_DIR"] = str(tmp_path)
    args = make_args(
        tmp_path, batch_size=8, num_classes_per_set=20,
        num_samples_per_class=5, num_target_samples=5,
        num_dataprovider_workers=2,
    )
    loader = MetaLearningSystemDataLoader(args=args, current_iter=0)
    n = 0
    for _ in range(6):
        for batch in loader.get_train_batches(total_batches=50,
                                              augment_images=True):
            n += 1
            if n % 50 == 0:
                print(f"  iter {n:4d}  rss {rss_mb():9.1f} MB", flush=True)


def main() -> None:
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    scenarios = {
        "jit_keep": lambda: run_jit(keep=True, fresh_input=True),
        "jit_nokeep": lambda: run_jit(keep=False, fresh_input=True),
        "jit_const": lambda: run_jit(keep=True, fresh_input=False),
        "data_only": run_data_only,
    }
    for name, fn in scenarios.items():
        if which not in ("all", name):
            continue
        print(f"== {name} ==", flush=True)
        fn()


if __name__ == "__main__":
    main()
