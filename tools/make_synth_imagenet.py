"""Generates a SYNTHETIC pre-split mini-ImageNet-shaped dataset tree.

The real mini-ImageNet images are absent from this environment (only the
index JSONs exist; the reference's README.md:34-40 assumes a download that
cannot happen here). This tool writes a tree with the exact layout, split,
and scale the real dataset has — ``<root>/{train,val,test}/<class>/<i>.png``
with 64/16/20 classes x 600 images of 84x84 RGB — so the full L4-L5 path
(pre-split loader, RGB /255 + ImageNet-normalization pipeline, episode
synthesis, training, checkpoints, ensemble eval) can be exercised at
north-star shapes end to end (VERDICT r3 next #5).

Images are class-correlated noise (a per-class prototype plus per-image
jitter), so episodes are learnable and training visibly reduces loss;
ACCURACY NUMBERS FROM THIS DATA ARE MEANINGLESS for comparison with the
paper — the run record is the deliverable, not the accuracy.

Usage: python tools/make_synth_imagenet.py [--root datasets/synth_mini_imagenet]
       [--imgs-per-class 600]
"""

from __future__ import annotations

import argparse
import os

import numpy as np
from PIL import Image

# The real dataset's split (train_val_test_split [0.64, 0.16, 0.2] of 100).
SPLIT = {"train": 64, "val": 16, "test": 20}
SIZE = 84


def make_tree(root: str, imgs_per_class: int = 600, seed: int = 7) -> int:
    rng = np.random.RandomState(seed)
    total = 0
    for set_name, n_classes in SPLIT.items():
        for c in range(n_classes):
            d = os.path.join(root, set_name, f"synth_{set_name}{c:04d}")
            os.makedirs(d, exist_ok=True)
            # Low-frequency per-class prototype (upsampled coarse noise) so
            # classes are separable but images within a class vary.
            coarse = rng.randint(0, 256, (7, 7, 3))
            proto = np.repeat(np.repeat(coarse, 12, axis=0), 12, axis=1)
            for i in range(imgs_per_class):
                img = np.clip(
                    proto + rng.randint(-40, 41, proto.shape), 0, 255
                ).astype(np.uint8)
                Image.fromarray(img, mode="RGB").save(
                    os.path.join(d, f"{i}.png")
                )
                total += 1
    return total


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--root", default="datasets/synth_mini_imagenet")
    parser.add_argument("--imgs-per-class", type=int, default=600)
    parser.add_argument("--seed", type=int, default=7)
    args = parser.parse_args()
    n = make_tree(args.root, args.imgs_per_class, args.seed)
    print(f"wrote {n} images under {args.root}")


if __name__ == "__main__":
    main()
