"""Hard-episode miner: serving telemetry -> training replay manifest.

The feedback half of the train→serve loop: the serving engine stamps
per-episode prediction confidence (softmax top1-top2 margin and
predictive entropy) plus the client's opaque tag onto every
``serve_dispatch`` telemetry event — host-side, zero extra device syncs.
Clients that drew their episode from the dataset distribution tag it
``"seed:<int>"`` (the dataset synthesizes episodes as pure functions of
that seed), which is exactly enough identity to REPLAY the episode into
the training stream: this tool selects the lowest-margin tagged episodes
and writes a replay manifest the loader mixes in
(``--replay_manifest``/``--replay_every`` — every Nth training episode
slot draws a mined seed instead of the next fresh one, deterministically,
so resume/bit-exactness contracts are untouched).

Usage::

    python tools/episode_miner.py --telemetry <exp>/logs/telemetry.jsonl \
        --out replay_manifest.json [--max-margin 0.5] [--top 64] \
        [--min-count 1] [--json]

Then train with::

    python train_maml_system.py --name_of_args_json_file cfg.json \
        --replay_manifest replay_manifest.json --replay_every 8
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MANIFEST_SCHEMA = 1

#: Tag prefix that makes an episode replayable: the integer after it is
#: the dataset synthesis seed.
SEED_TAG_PREFIX = "seed:"


def family_bucket_stats(events) -> dict[tuple[str, str], dict]:
    """Folds ``serve_dispatch`` events into per-(learner family, bucket)
    traffic stats — the zoo-era view of a telemetry stream that may mix
    MAML/ANIL/protonets replicas and coarsened geometry traffic::

        {(family, bucket): {"dispatches": n, "episodes": n,
                            "coarsened": n, "min_margin": x}}

    ``bucket`` is the COARSENED ``"WxSxQ"`` string the dispatch actually
    rode (serve/geometry.py), ``coarsened`` counts episodes whose real
    geometry differed from it, and ``min_margin`` is the hardest episode
    seen. Events from pre-zoo engines (no ``family`` field) fold under
    ``"maml"``."""
    out: dict[tuple[str, str], dict] = {}
    for event in events:
        if event.get("type") != "serve_dispatch":
            continue
        family = str(event.get("family") or "maml")
        bucket = str(event.get("bucket") or "?")
        row = out.setdefault(
            (family, bucket),
            {"dispatches": 0, "episodes": 0, "coarsened": 0,
             "min_margin": None},
        )
        row["dispatches"] += 1
        row["episodes"] += int(event.get("episodes") or 0)
        row["coarsened"] += int(event.get("coarsened") or 0)
        margins = [
            float(m) for m in (event.get("margins") or [])
            if isinstance(m, (int, float)) and math.isfinite(m)
        ]
        if margins:
            low = min(margins)
            if row["min_margin"] is None or low < row["min_margin"]:
                row["min_margin"] = low
    return out


def mine_events(events) -> dict[int, dict]:
    """Folds ``serve_dispatch`` events into per-seed confidence stats:
    ``{seed: {"margin": min_margin, "entropy": max_entropy, "count": n}}``.
    Episodes without a parseable ``seed:<int>`` tag are skipped (no
    replayable identity); non-finite margins (a NaN-logits episode) are
    treated as margin 0.0 — maximally hard."""
    out: dict[int, dict] = {}
    for event in events:
        if event.get("type") != "serve_dispatch":
            continue
        tags = event.get("tags") or []
        margins = event.get("margins") or []
        entropies = event.get("entropies") or []
        for i, tag in enumerate(tags):
            if not isinstance(tag, str) or not tag.startswith(SEED_TAG_PREFIX):
                continue
            try:
                seed = int(tag[len(SEED_TAG_PREFIX):])
            except ValueError:
                continue
            margin = margins[i] if i < len(margins) else None
            entropy = entropies[i] if i < len(entropies) else None
            margin = (
                float(margin)
                if isinstance(margin, (int, float)) and math.isfinite(margin)
                else 0.0
            )
            entropy = (
                float(entropy)
                if isinstance(entropy, (int, float)) and math.isfinite(entropy)
                else None
            )
            row = out.setdefault(
                seed, {"margin": margin, "entropy": entropy, "count": 0}
            )
            row["count"] += 1
            row["margin"] = min(row["margin"], margin)
            if entropy is not None:
                row["entropy"] = max(row["entropy"] or 0.0, entropy)
    return out


def select_hard_episodes(
    stats: dict[int, dict],
    *,
    max_margin: float = 0.5,
    top: int = 64,
    min_count: int = 1,
) -> list[dict]:
    """Lowest-margin episodes first, filtered to ``margin <= max_margin``
    and at least ``min_count`` sightings, capped at ``top``."""
    rows = [
        {"seed": seed, **row}
        for seed, row in stats.items()
        if row["margin"] <= max_margin and row["count"] >= min_count
    ]
    rows.sort(key=lambda r: (r["margin"], r["seed"]))
    return rows[: max(int(top), 0)]


def write_manifest(
    path: str, episodes: list[dict], source: str, learner: str | None = None
) -> dict:
    """``learner`` (optional, schema-compatible) records which learner
    family's serving traffic mined these seeds — provenance for a human
    triaging a mixed-fleet replay set. The training loader reads only
    ``schema`` and ``episodes[].seed`` and ignores it by construction
    (data/loader.py ``load_replay_manifest``)."""
    manifest = {
        "schema": MANIFEST_SCHEMA,
        "source": source,
        "episodes": episodes,
    }
    if learner is not None:
        manifest["learner"] = learner
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, path)
    return manifest


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--telemetry", required=True,
                        help="telemetry JSONL with serve_dispatch events")
    parser.add_argument("--out", required=True,
                        help="replay manifest JSON to write")
    parser.add_argument("--max-margin", type=float, default=0.5,
                        help="only episodes at or below this softmax "
                        "top1-top2 margin are mined")
    parser.add_argument("--top", type=int, default=64,
                        help="manifest size cap (lowest margins first)")
    parser.add_argument("--min-count", type=int, default=1,
                        help="minimum sightings before an episode is mined")
    parser.add_argument("--json", action="store_true",
                        help="print the manifest summary as one JSON line")
    opts = parser.parse_args(argv)

    from howtotrainyourmamlpytorch_tpu.telemetry.events import read_events

    events = read_events(opts.telemetry)
    stats = mine_events(events)
    episodes = select_hard_episodes(
        stats, max_margin=opts.max_margin, top=opts.top,
        min_count=opts.min_count,
    )
    by_family = family_bucket_stats(events)
    families = sorted({family for family, _bucket in by_family})
    summary = {
        "tagged_episodes": len(stats),
        "mined": len(episodes),
        "out": opts.out if episodes else None,
        "min_margin": episodes[0]["margin"] if episodes else None,
        "families": {
            f"{family}/{bucket}": row
            for (family, bucket), row in sorted(by_family.items())
        },
    }
    if not episodes:
        # Nothing cleared the gates: write NO manifest and exit non-zero
        # — the loader refuses empty manifests, so a scripted
        # mine-then-train pipeline must branch here, not start a training
        # run that dies at loader construction.
        if opts.json:
            print(json.dumps(summary))
        else:
            print(
                f"no episodes at or below margin {opts.max_margin} "
                f"(of {len(stats)} tagged) — no manifest written",
                file=sys.stderr,
            )
        return 3
    write_manifest(
        opts.out, episodes, source=os.path.abspath(opts.telemetry),
        # Single-family telemetry stamps its provenance; a mixed-fleet
        # stream has no one owner, so the optional field is omitted.
        learner=families[0] if len(families) == 1 else None,
    )
    if opts.json:
        print(json.dumps(summary))
    else:
        print(
            f"mined {summary['mined']} hard episode(s) of "
            f"{summary['tagged_episodes']} tagged -> {opts.out}"
        )
        for (family, bucket), row in sorted(by_family.items()):
            coarse = (
                f", {row['coarsened']} coarsened" if row["coarsened"] else ""
            )
            print(
                f"  {family} @ {bucket}: {row['episodes']} episode(s) over "
                f"{row['dispatches']} dispatch(es){coarse}"
            )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
