"""Developer tooling: benchmarks, profilers, leak probes, and graftlint."""
