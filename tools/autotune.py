"""Autotune CLI: zero-human-choice knob tuning with judged receipts.

One invocation classifies the machine's roofline regime (ProgramLedger
arithmetic intensity vs ``PEAK_FLOPS_BY_KIND`` over the HBM ridge),
ranks candidate single-knob moves from the declared space
(``tune/space.py``), drives short A/B probes under bench's contention-
sentinel protocol, hands the best candidate to ``tools/bench_judge``
mechanically, and — on a ``keep`` verdict — appends the winning gate to
``tools/bench_gates.json`` with provenance ``source: autotune:<run_id>``
plus the probe emissions as ``AUTOTUNE_<run_id>_r0{1,2}.json``.

Usage::

    python tools/autotune.py                      # probe, judge, append
    python tools/autotune.py --dry-run            # probe + judge only
    python tools/autotune.py --json               # machine-readable
    python tools/autotune.py --run-id r01 --min-gain 0.05 \
        [--max-candidates 6] [--out .] [--gates tools/bench_gates.json]

Exit codes: 0 = a winner was judged ``keep`` (and appended unless
``--dry-run``); 2 = no candidate beat the gate (every verdict revert) or
every probe was sentinel-contended — nothing was appended.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _machine_facts():
    """Device count/kind + the roofline inputs, measured on THIS machine.
    Cost analysis is backend-optional (CPU returns None) — the regime
    then honestly classifies as dispatch-bound."""
    import jax

    from howtotrainyourmamlpytorch_tpu.telemetry.device import (
        ProgramLedger,
        record_train_program,
        resolve_peak_flops,
    )
    from howtotrainyourmamlpytorch_tpu.tune.autotuner import (
        ProbeSpec,
        _probe_batch,
        _probe_config,
    )

    devices = jax.devices()
    kind = devices[0].device_kind
    peak = resolve_peak_flops(kind)
    intensity = None
    try:
        import numpy as np

        from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner

        spec = ProbeSpec()
        cfg = _probe_config({}, spec)
        learner = MAMLFewShotLearner(cfg)
        state = learner.init_state(jax.random.PRNGKey(0))
        batches = [_probe_batch(spec, np.random.RandomState(1))]
        ledger = ProgramLedger(emit_events=False)
        entry = record_train_program(ledger, learner, state, batches, 0)
        if entry is not None and entry.flops:
            intensity = entry.arithmetic_intensity
    except Exception as exc:  # noqa: BLE001 — classification is best-effort
        print(f"# roofline probe unavailable: {exc}", file=sys.stderr)
    return len(devices), kind, peak, intensity


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--run-id", default=None,
                        help="provenance id (default: next free autotune "
                        "rNN from existing AUTOTUNE_* files in --out)")
    parser.add_argument("--out", default=".",
                        help="where AUTOTUNE_<run_id>_r0*.json land")
    parser.add_argument("--gates", default=os.path.join(
                            os.path.dirname(os.path.abspath(__file__)),
                            "bench_gates.json"),
                        help="gates file the winning verdict appends to")
    parser.add_argument("--min-gain", type=float, default=0.05,
                        help="the judged bar: candidate must beat baseline "
                        "by this fraction (gate expression)")
    parser.add_argument("--max-candidates", type=int, default=6)
    parser.add_argument("--global-batch", type=int, default=8,
                        help="meta-batch size the divisibility guards "
                        "check candidates against")
    parser.add_argument("--window-iters", type=int, default=50,
                        help="meta-iterations per probe timing window")
    parser.add_argument("--dry-run", action="store_true",
                        help="probe + judge, but never touch the gates "
                        "file or write emissions")
    parser.add_argument("--json", action="store_true")
    opts = parser.parse_args(argv)

    from howtotrainyourmamlpytorch_tpu.tune.autotuner import (
        BASELINE_KEY,
        PROBE_KEY,
        ProbeSpec,
        append_gate,
        autotune_run,
    )
    from howtotrainyourmamlpytorch_tpu.tune.space import TuneContext

    n_devices, kind, peak, intensity = _machine_facts()
    run_id = opts.run_id or _next_run_id(opts.out)
    result = autotune_run(
        run_id=run_id,
        ctx=TuneContext(
            n_devices=n_devices, dp=1, mp=1, global_batch=opts.global_batch
        ),
        spec=ProbeSpec(
            batch_size=opts.global_batch, window_iters=opts.window_iters
        ),
        min_gain=opts.min_gain,
        max_candidates=opts.max_candidates,
        device_kind=kind,
        peak_flops=peak,
        arithmetic_intensity=intensity,
    )

    appended = False
    if result.get("winner") and not opts.dry_run:
        for run in result["emissions"]:
            path = os.path.join(opts.out, run["name"])
            with open(path, "w") as f:
                json.dump({"n": run["n"], "parsed": run["parsed"]}, f,
                          indent=2)
                f.write("\n")
        append_gate(
            opts.gates,
            PROBE_KEY,
            result["winner"]["gate_entry"],
            ungated_extra=(
                BASELINE_KEY, "autotune_knob", "autotune_value",
            ),
        )
        appended = True
    result["gates_appended"] = appended

    if opts.json:
        print(json.dumps(result, indent=2))
    else:
        print(_render(result))
    return 0 if result.get("winner") else 2


def _next_run_id(out_dir: str) -> str:
    import glob
    import re

    taken = set()
    for path in glob.glob(os.path.join(out_dir, "AUTOTUNE_r*_r0*.json")):
        match = re.search(r"AUTOTUNE_(r\d+)_", os.path.basename(path))
        if match:
            taken.add(match.group(1))
    n = 1
    while f"r{n:02d}" in taken:
        n += 1
    return f"r{n:02d}"


def _render(result: dict) -> str:
    lines = [
        f"autotune {result['run_id']} — regime {result['regime']} "
        f"({result['regime_reason']})"
    ]
    base = result.get("baseline")
    lines.append(
        f"  baseline: "
        + (f"{base:.2f} meta-iters/s" if base else "DISCARDED (contended)")
    )
    for probe in result.get("probes", []):
        measured = probe["measured"]
        lines.append(
            f"  probe {probe['knob']}={probe['value']}: "
            + (f"{measured:.2f} meta-iters/s"
               if measured is not None else "DISCARDED (contended)")
        )
    judge = result.get("judge")
    if judge:
        lines.append(
            f"  judge: {judge['verdict']} — {judge['reason']} "
            f"(gate {judge['gate']})"
        )
    winner = result.get("winner")
    if winner:
        lines.append(
            f"  WINNER {winner['lever']}: {winner['baseline']:.2f} -> "
            f"{winner['measured']:.2f} meta-iters/s "
            f"(+{winner['gain'] * 100:.0f}%), fingerprint "
            f"{winner['config_fingerprint']}"
            + ("; gate appended" if result.get("gates_appended")
               else "; dry run — gate NOT appended")
        )
    elif "error" in result:
        lines.append(f"  {result['error']}")
    else:
        lines.append("  no candidate beat the gate — nothing appended")
    return "\n".join(lines)


if __name__ == "__main__":
    raise SystemExit(main())
